"""Benchmark suite: TPU engine vs honest baselines (BASELINE.md).

Workloads (all on the real chip, identical data/queries verified against
the CPU oracle engine):

  aggregate   TPC-H-Q6-flavored aggregate range scan (the headline)
  ycsb_e      YCSB-E-shaped row scans: concurrent LIMIT-100 pages served
              as serialized CQL wire bytes (native page server)
  point_read  YCSB-C / CassandraKeyValue-shaped exact-key GETs
  ycsb_a/f    mixed read/update and read-modify-write over a live
              memtable (bloom-pruned point path)
  redis       pipelined GET/SET through the RESP proxy over MiniCluster
  tpch_q1/q6  grouped / expression aggregates over lineitem
  write       batched write throughput into the engine (apply+flush)
  compact     multi-run merge + history GC throughput

Baselines, stated explicitly (BASELINE.md):
  - The reference's own published node-level numbers: YCSB-E 14,007
    scan-ops/s on 3x n1-standard-16 => ~292 scan-ops/s/vCPU, i.e. about
    29K scanned rows/s/vCPU and ~470K scanned rows/s per 16-vCPU NODE.
    ``vs_baseline`` for scan metrics = this chip vs that calibrated
    C++-class NODE (not the in-repo Python oracle).
  - ``vs_cpu_engine`` = same workload on the in-repo CPU oracle engine —
    an implementation-for-implementation ratio on identical code paths.
  - TPC-H has no in-reference numbers (YSQL was beta): Q1/Q6 report
    vs_cpu_engine only and carry vs_baseline = null.

Prints one JSON line per sub-metric (prefixed "#" as comments) and ends
with ONE final JSON line for the headline:
  {"metric", "value", "unit", "vs_baseline", "details": {...}}
"""

from __future__ import annotations

import json
import random
import sys
import time

# Optional flags (scanned out before the positional NUM_KEYS):
#   --compile_witness         count XLA trace/compile events per
#                             @compile_contract jit entry (utils/jitting)
#   --compile-witness-out P   dump the compile witness to P for
#                             yb-lint --witness-check
#   --only a,b / --skip a,b   run only / all-but the named sections
#                             (section names printed in the final JSON's
#                             "sections" map). The cluster sections run
#                             isolated in child interpreters on a full
#                             run, so --only is also how the parent asks
#                             a child for exactly one section.
_ARGV = sys.argv[1:]
COMPILE_WITNESS = "--compile_witness" in _ARGV


def _flag_value(flag):
    return _ARGV[_ARGV.index(flag) + 1] if flag in _ARGV else None


CWITNESS_OUT = _flag_value("--compile-witness-out")
_ONLY_RAW = _flag_value("--only")
_SKIP_RAW = _flag_value("--skip")
ONLY = set(_ONLY_RAW.split(",")) if _ONLY_RAW else None
SKIP = set(_SKIP_RAW.split(",")) if _SKIP_RAW else set()
_FLAG_VALS = {v for v in (CWITNESS_OUT, _ONLY_RAW, _SKIP_RAW)
              if v is not None}
_POS = [a for a in _ARGV if not a.startswith("--") and a not in _FLAG_VALS]
NUM_KEYS = int(_POS[0]) if _POS else 200_000
TIMED_ITERS = 5

# BASELINE.md calibration: ~29K scanned rows/s/vCPU on the reference's
# C++ DocDB; a 16-vCPU node => ~470K rows/s. YCSB-E node share:
# 14,007 scan-ops/s across 3 nodes => ~4,669 scan-ops/s per node.
CPP_NODE_SCAN_ROWS_S = 29_000 * 16
CPP_NODE_YCSBE_OPS_S = 14_007 / 3
# CassandraBatchKeyValue 258K ops/s across 3 nodes => ~86K rows/s/node.
CPP_NODE_BATCH_WRITE_ROWS_S = 258_000 / 3


def _median(f, iters=TIMED_ITERS):
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def bench_aggregate(schema, rows, max_ht, make_engine, S, n_concurrent=32,
                    depth=6, n_batches=12):
    """Aggregate scans two ways: single-scan latency (one fetch cycle on
    the tunnel link dominates it) and SERVER THROUGHPUT — concurrent
    aggregate scans pipelined through the async batch API, the shape a
    tserver actually runs, where the link round trip amortizes across
    whole batches and the device's scan rate is what's measured. The
    headline is the throughput number; latency rides in the details."""
    import collections

    tpu = make_engine("tpu", schema, {"rows_per_block": 2048})
    t0 = time.perf_counter()
    tpu.apply(rows)
    tpu.flush()
    load_s = time.perf_counter() - t0

    def spec(lo=-500_000):
        return S.ScanSpec(
            read_ht=max_ht + 1,
            predicates=[S.Predicate("d", ">=", lo)],
            aggregates=[S.AggSpec("count", None), S.AggSpec("sum", "a"),
                        S.AggSpec("min", "a"), S.AggSpec("max", "a"),
                        S.AggSpec("sum", "d")])

    warm = tpu.scan(spec())
    lat = _median(lambda: tpu.scan(spec()))
    versions = tpu.runs[0].crun.num_versions

    cpu = make_engine("cpu", schema)
    cpu.apply(rows)
    cpu.flush()
    # Same-workload CPU throughput: the oracle gains nothing from
    # concurrency (single-thread compute), so its rate on 2 of the
    # concurrent specs extrapolates linearly to the whole workload.
    t0 = time.perf_counter()
    cres, _c2 = cpu.scan_batch([spec(), spec(-500_007)])
    cpu_dt = (time.perf_counter() - t0) / 2
    cpu_rows_s = versions / cpu_dt
    for g, w in zip(warm.rows[0], cres.rows[0]):
        if isinstance(w, float):
            assert g is not None and abs(g - w) <= 1e-3 + 1e-5 * abs(w)
        else:
            assert g == w, (g, w)

    # Throughput: n_batches batches of n_concurrent DISTINCT aggregate
    # scans (varying literals), depth-pipelined; every scan walks the
    # whole table.
    batches = [[spec(-500_000 - 7 * (b * n_concurrent + i))
                for i in range(n_concurrent)] for b in range(n_batches)]

    def pipeline(bs):
        q = collections.deque()
        for batch in bs:
            q.append(tpu.scan_batch_async(batch))
            if len(q) > depth:
                q.popleft().finish()
        while q:
            q.popleft().finish()

    pipeline(batches[: depth + 2])  # warm compiles
    # Steady state starts here: every program the measured region needs
    # exists, so any further compile is a recompile charged to a request
    # (yb_jit_compiles{entry} + the compile witness when enabled).
    from yugabyte_db_tpu.utils import jitting, metrics

    warm_compiles = dict(metrics.jit_compiles())
    jitting.mark_steady_state()
    t0 = time.perf_counter()
    pipeline(batches)
    tdt = time.perf_counter() - t0
    tpu_rows_s = versions * n_concurrent * n_batches / tdt
    steady_recompiles = {
        k: v - warm_compiles.get(k, 0)
        for k, v in metrics.jit_compiles().items()
        if v != warm_compiles.get(k, 0)}

    return tpu, cpu, versions, {
        "metric": "aggregate_range_scan_rows_per_sec",
        "value": round(tpu_rows_s, 1),
        "unit": (f"rows/s ({n_concurrent} concurrent aggregate scans, "
                 f"depth-{depth} pipeline)"),
        "vs_baseline": round(tpu_rows_s / CPP_NODE_SCAN_ROWS_S, 2),
        "vs_cpu_engine": round(tpu_rows_s / cpu_rows_s, 2),
        "single_scan_latency_ms": round(lat * 1000, 1),
        "single_scan_rows_per_sec": round(versions / lat, 1),
        "load_s": round(load_s, 1),
        # {} proves the measured region recompiled nothing.
        "steady_state_recompiles": steady_recompiles,
    }


def bench_ycsb_e(schema, tpu, cpu, max_ht, S, n_pages=256, n_batches=40):
    """Steady-state server throughput: batches of concurrent LIMIT-100
    predicate pages served as SERIALIZED CQL WIRE BYTES — the shape the
    reference actually measures (YCSB-E ops return rows_data the CQL
    service forwards; src/yb/common/ql_rowblock.h:66). scan_batch_wire
    emits every page's result-frame cells straight from the run's plane
    buffers in C (native serve_page_wire_batch): no Python value object
    is ever constructed on the hot path. Byte-parity with the CPU
    oracle's scan + Python serialization is asserted on a full batch.
    The row-tuple API path (scan_batch, the r4 metric) rides along as a
    detail for round-over-round continuity."""
    import collections

    from yugabyte_db_tpu.models.partition import compute_hash_code

    rng = random.Random(11)

    def make_batch(k):
        out = []
        for _ in range(k):
            i = rng.randrange(NUM_KEYS)
            lo = schema.encode_primary_key(
                {"k": f"user{i:06d}", "r": 0},
                compute_hash_code(schema, {"k": f"user{i:06d}"}))
            out.append(S.ScanSpec(
                lower=lo, read_ht=max_ht + 1,
                predicates=[S.Predicate("d", ">=", -500_000)],
                projection=["k", "r", "a", "d"], limit=100))
        return out

    batches = [make_batch(n_pages) for _ in range(n_batches)]

    # Correctness: wire bytes identical to the CPU oracle's serialized
    # pages (independent implementations: C plane emitter vs Python
    # scan + models.wirefmt), and identical row tuples engine-vs-engine.
    aw = cpu.scan_batch_wire(batches[0], "cql")
    bw = tpu.scan_batch_wire(batches[0], "cql")
    assert [(p.data, p.nrows, p.resume) for p in aw] == \
        [(p.data, p.nrows, p.resume) for p in bw]
    a = cpu.scan_batch(batches[1])
    b = tpu.scan_batch(batches[1])
    assert [r.rows for r in a] == [r.rows for r in b]

    tpu.scan_batch_wire(batches[0], "cql")  # warm blob/mask caches
    t0 = time.perf_counter()
    nrows = nbytes = 0
    for batch in batches:
        for pg in tpu.scan_batch_wire(batch, "cql"):
            nrows += pg.nrows
            nbytes += len(pg.data)
    tdt = time.perf_counter() - t0
    ops_s = n_pages * n_batches / tdt

    # CPU oracle on identical work (2 batches, extrapolated linearly).
    t0 = time.perf_counter()
    cpu.scan_batch_wire(batches[0], "cql")
    cpu.scan_batch_wire(batches[1], "cql")
    cdt = (time.perf_counter() - t0) / 2 * n_batches

    # r4-continuity detail: the row-tuple scan path, depth-pipelined.
    def pipeline(bs, depth=6):
        q = collections.deque()
        n = 0
        for batch in bs:
            q.append(tpu.scan_batch_async(batch))
            if len(q) > depth:
                n += sum(len(r.rows) for r in q.popleft().finish())
        while q:
            n += sum(len(r.rows) for r in q.popleft().finish())
        return n

    pipeline(batches[:8])  # warm
    t0 = time.perf_counter()
    pipeline(batches[:12])
    tup_dt = time.perf_counter() - t0
    tup_ops_s = n_pages * 12 / tup_dt

    page_lat = _median(
        lambda: tpu.scan_batch_wire([batches[2][0]], "cql"), iters=7)
    return {
        "metric": "ycsb_e_scan_ops_per_sec",
        "value": round(ops_s, 1),
        "unit": (f"scan-ops/s (LIMIT-100 pages as serialized CQL wire "
                 f"bytes, batches of {n_pages})"),
        "vs_baseline": round(ops_s / CPP_NODE_YCSBE_OPS_S, 2),
        "vs_cpu_engine": round(cdt / tdt, 2),
        "result_rows_per_sec": round(nrows / tdt, 1),
        "wire_mb_per_sec": round(nbytes / tdt / 1e6, 1),
        "rowtuple_ops_per_sec": round(tup_ops_s, 1),
        "rowtuple_vs_baseline": round(tup_ops_s / CPP_NODE_YCSBE_OPS_S, 2),
        "single_page_latency_ms": round(page_lat * 1000, 3),
    }


def bench_point_reads(schema, tpu, cpu, max_ht, S, n_ops=256,
                      n_batches=40):
    """YCSB-C / CassandraKeyValue-shaped point reads: batched exact-key
    GETs ([key, key+0xff), LIMIT 1) served as wire bytes. Baseline:
    CassandraKeyValue reads 220K ops/s across 3 nodes => ~73.3K
    ops/s/node (docs/yb-perf-v1.0.7.md:7)."""
    from yugabyte_db_tpu.models.partition import compute_hash_code

    rng = random.Random(13)

    def make_batch(k):
        out = []
        for _ in range(k):
            i = rng.randrange(NUM_KEYS)
            key = schema.encode_primary_key(
                {"k": f"user{i:06d}", "r": i % 7},
                compute_hash_code(schema, {"k": f"user{i:06d}"}))
            out.append(S.ScanSpec(
                lower=key, upper=key + b"\xff", read_ht=max_ht + 1,
                projection=["k", "r", "a", "d"], limit=1))
        return out

    batches = [make_batch(n_ops) for _ in range(n_batches)]
    aw = cpu.scan_batch_wire(batches[0], "cql")
    bw = tpu.scan_batch_wire(batches[0], "cql")
    assert [(p.data, p.nrows) for p in aw] == \
        [(p.data, p.nrows) for p in bw]

    t0 = time.perf_counter()
    hits = 0
    for batch in batches:
        for pg in tpu.scan_batch_wire(batch, "cql"):
            hits += pg.nrows
    tdt = time.perf_counter() - t0
    ops_s = n_ops * n_batches / tdt

    t0 = time.perf_counter()
    cpu.scan_batch_wire(batches[0], "cql")
    cpu.scan_batch_wire(batches[1], "cql")
    cdt = (time.perf_counter() - t0) / 2 * n_batches
    return {
        "metric": "point_read_ops_per_sec",
        "value": round(ops_s, 1),
        "unit": (f"GET ops/s (exact-key LIMIT-1 wire pages, "
                 f"batches of {n_ops})"),
        "vs_baseline": round(ops_s / (220_000 / 3), 2),
        "vs_cpu_engine": round(cdt / tdt, 2),
        "hit_rate": round(hits / (n_ops * n_batches), 3),
    }


def bench_ycsb_mix(make_engine, S, n_keys=None):
    """YCSB-A (50/50 read-update) and YCSB-F (read-modify-write) on a
    dedicated engine pair: updates land in the live memtable, reads take
    the bloom-pruned point path over memtable + runs — the real mixed
    steady state (the reference's YCSB numbers,
    docs/yb-perf-v1.0.7.md:585-601; per-node = /3)."""
    from __graft_entry__ import _make_rows, _make_schema
    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.storage.row_version import RowVersion

    n_keys = n_keys or max(NUM_KEYS // 2, 10_000)
    schema = _make_schema()
    rows, ht = _make_rows(schema, n_keys, seed=5)
    tpu = make_engine("tpu", schema, {"rows_per_block": 2048})
    cpu = make_engine("cpu", schema)
    for e in (tpu, cpu):
        e.apply(rows)
        e.flush()
    cid = {c.name: c.col_id for c in schema.value_columns}
    rng = random.Random(23)

    # Keys pre-encoded outside the timed loops: the reference's YCSB
    # measures SERVER throughput — key construction happens on client
    # machines (docs/yb-perf-v1.0.7.md workload setup) and is not part
    # of the reported ops/s.
    keys = [schema.encode_primary_key(
        {"k": f"user{i:06d}", "r": i % 7},
        compute_hash_code(schema, {"k": f"user{i:06d}"}))
        for i in range(n_keys)]

    def key_of(i):
        return keys[i]

    def get_spec(i, rht):
        return S.ScanSpec(lower=keys[i], upper=keys[i] + b"\xff",
                          read_ht=rht, projection=["k", "r", "a", "d"],
                          limit=1)

    out = []
    # A: 50/50 in batches of 64 reads + 64 updates.
    n_rounds = 60
    ops = 0
    # Warm + parity on one round against the oracle.
    specs = [get_spec(rng.randrange(n_keys), ht + 1) for _ in range(64)]
    assert [p.data for p in tpu.scan_batch_wire(specs, "cql")] == \
        [p.data for p in cpu.scan_batch_wire(specs, "cql")]
    t0 = time.perf_counter()
    for _ in range(n_rounds):
        upd = []
        for _ in range(64):
            i = rng.randrange(n_keys)
            ht += 1
            upd.append(RowVersion(key_of(i), ht=ht, columns={
                cid["d"]: rng.randrange(-10**6, 10**6)}))
        tpu.apply(upd)
        specs = [get_spec(rng.randrange(n_keys), ht + 1)
                 for _ in range(64)]
        for pg in tpu.scan_batch_wire(specs, "cql"):
            pass
        ops += 128
    a_dt = time.perf_counter() - t0
    out.append({
        "metric": "ycsb_a_ops_per_sec",
        "value": round(ops / a_dt, 1),
        "unit": "ops/s (50/50 point-read/update, live memtable)",
        "vs_baseline": round(ops / a_dt / (107_120 / 3), 2),
    })
    # F: read-modify-write (read the row, rewrite column d).
    ops = 0
    t0 = time.perf_counter()
    for _ in range(n_rounds):
        idxs = [rng.randrange(n_keys) for _ in range(64)]
        specs = [get_spec(i, ht + 1) for i in idxs]
        pages = tpu.scan_batch_wire(specs, "cql")
        upd = []
        for i, pg in zip(idxs, pages):
            ht += 1
            upd.append(RowVersion(key_of(i), ht=ht, columns={
                cid["d"]: pg.nrows + 1}))
        tpu.apply(upd)
        ops += 64
    f_dt = time.perf_counter() - t0
    # Spot-check: the mixed state still matches the oracle that applied
    # nothing — only on keys never updated is that meaningful, so replay
    # the tpu updates into the oracle lazily via dump comparison cost is
    # excessive; instead verify a fresh parity batch through the point
    # path (memtable + run merge) against the SAME engine's row API.
    specs = [get_spec(rng.randrange(n_keys), ht + 1) for _ in range(32)]
    pages = tpu.scan_batch_wire(specs, "cql")
    rows_api = tpu.scan_batch(specs)
    from yugabyte_db_tpu.models.wirefmt import serialize_rows
    for pg, rr, sp in zip(pages, rows_api, specs):
        dts = [schema.column(n).dtype for n in rr.columns]
        assert pg.data == serialize_rows("cql", dts, rr.rows)
    out.append({
        "metric": "ycsb_f_ops_per_sec",
        "value": round(ops / f_dt, 1),
        "unit": "RMW ops/s (point read + rewrite, live memtable)",
        "vs_baseline": round(ops / f_dt / (72_185 / 3), 2),
    })
    return out


def bench_index(n_rows=4000, n_reads=4000):
    """Secondary-index write maintenance + index-driven reads over the
    RF=3 MiniCluster through the real CQL wire server, driven by the
    vendored driver with prepared statements (the
    CassandraSecondaryIndex workload shape). Baselines per node:
    5.9K idx writes /3, 200K idx reads /3
    (docs/yb-perf-v1.0.7.md:9-10)."""
    import tempfile

    from yugabyte_db_tpu.drivers import CqlConnection
    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
    from yugabyte_db_tpu.yql.cql.server import CQLServer

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            server = CQLServer(ClientCluster(mc.client()))
            host, port = server.listen("127.0.0.1", 0)
            conn = CqlConnection(host, port)
            conn.execute("CREATE KEYSPACE bench")
            conn.execute("USE bench")
            conn.execute("CREATE TABLE users (id bigint PRIMARY KEY, "
                         "email text, v bigint)")
            conn.execute("CREATE INDEX users_email ON users (email)")
            emails = [f"u{i}@x.io" for i in range(n_rows)]
            # Stream-multiplexed pipelining on one connection — the
            # in-flight request window every stock driver keeps.
            ins = conn.prepare(
                "INSERT INTO users (id, email, v) VALUES (?, ?, ?)")
            sel = conn.prepare("SELECT id, v FROM users WHERE email = ?")
            rng = random.Random(7)
            picks = [rng.randrange(n_rows) for _ in range(n_reads)]
            t0 = time.perf_counter()
            conn.execute_prepared_many(
                ins, [[i, emails[i], i * 3] for i in range(n_rows)])
            w_dt = time.perf_counter() - t0
            r = conn.execute_prepared(sel, [emails[picks[0]]])
            assert r.rows == [(picks[0], picks[0] * 3)], r.rows
            t0 = time.perf_counter()
            res = conn.execute_prepared_many(
                sel, [[emails[i]] for i in picks])
            r_dt = time.perf_counter() - t0
            assert all(r.rows for r in res)
            conn.close()
            server.shutdown()
        finally:
            mc.shutdown()
    return [{
        "metric": "index_write_ops_per_sec",
        "value": round(n_rows / w_dt, 1),
        "unit": "indexed-INSERT ops/s (CQL wire, prepared, RF=3)",
        "vs_baseline": round(n_rows / w_dt / (5_900 / 3), 2),
    }, {
        "metric": "index_read_ops_per_sec",
        "value": round(n_reads / r_dt, 1),
        "unit": "index-driven SELECT ops/s (CQL wire, prepared, RF=3)",
        "vs_baseline": round(n_reads / r_dt / (200_000 / 3), 2),
    }]


def bench_redis(n_keys=20_000, pipeline=256):
    """Redis proxy over the RF=3 MiniCluster through a real RESP socket,
    pipelined (the RedisPipelinedKeyValue shape): SET load then GET
    sweep. Baselines per node: pipelined reads 538K/3 => ~179K ops/s,
    writes 536K/3 => ~179K (docs/yb-perf-v1.0.7.md:18-19)."""
    import socket
    import tempfile

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.redis import RedisServer

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            server = RedisServer(mc.client("redis-bench"))
            host, port = server.listen("127.0.0.1", 0)
            sock = socket.create_connection((host, port))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            f = sock.makefile("rwb")

            def run(cmds):
                n = 0
                for c0 in range(0, len(cmds), pipeline):
                    chunk = cmds[c0:c0 + pipeline]
                    f.write(b"".join(chunk))
                    f.flush()
                    for _ in chunk:
                        line = f.readline()
                        if line[:1] == b"$":
                            ln = int(line[1:])
                            if ln >= 0:
                                f.read(ln + 2)
                        n += 1
                return n

            def resp(*args):
                parts = [b"*%d\r\n" % len(args)]
                for a in args:
                    b = a if isinstance(a, bytes) else str(a).encode()
                    parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
                return b"".join(parts)

            sets = [resp("SET", f"bk{i:07d}", f"val{i}")
                    for i in range(n_keys)]
            t0 = time.perf_counter()
            run(sets)
            set_dt = time.perf_counter() - t0
            rng = random.Random(3)
            gets = [resp("GET", f"bk{rng.randrange(n_keys):07d}")
                    for _ in range(n_keys)]
            t0 = time.perf_counter()
            run(gets)
            get_dt = time.perf_counter() - t0
            sock.close()
            server.shutdown()
        finally:
            mc.shutdown()
    return [{
        "metric": "redis_pipelined_get_ops_per_sec",
        "value": round(n_keys / get_dt, 1),
        "unit": f"GET ops/s (RESP socket, pipeline {pipeline}, RF=3)",
        "vs_baseline": round(n_keys / get_dt / (538_000 / 3), 2),
    }, {
        "metric": "redis_pipelined_set_ops_per_sec",
        "value": round(n_keys / set_dt, 1),
        "unit": f"SET ops/s (RESP socket, pipeline {pipeline}, RF=3)",
        "vs_baseline": round(n_keys / set_dt / (536_000 / 3), 2),
    }]


def bench_serving_path(n_keys=20_000, pipeline=256, cql_rows=2_000,
                       cql_ops=10_000, window=128):
    """The native request-batch serving path (docs/serving-path.md)
    against its own Python per-op fallback, same sockets, same data:
    pipelined RESP GETs and pipelined prepared CQL point SELECTs, each
    timed with the native batch executors enabled and then force-
    disabled. NEW metric keys — the pre-existing redis_pipelined_* keys
    keep measuring whatever path the server picks by default."""
    import socket
    import tempfile

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.cql import wire_protocol as W
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
    from yugabyte_db_tpu.yql.cql.processor import QLProcessor
    from yugabyte_db_tpu.yql.cql.server import CQLServer
    from yugabyte_db_tpu.yql.redis import RedisServer
    from yugabyte_db_tpu.yql.redis.server import RedisServiceImpl

    out = []
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            # -- redis: pipelined GET sweep, native vs forced-Python ----
            server = RedisServer(mc.client("redis-bench-native"))
            host, port = server.listen("127.0.0.1", 0)
            sock = socket.create_connection((host, port))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            f = sock.makefile("rwb")

            def run(cmds):
                for c0 in range(0, len(cmds), pipeline):
                    chunk = cmds[c0:c0 + pipeline]
                    f.write(b"".join(chunk))
                    f.flush()
                    for _ in chunk:
                        line = f.readline()
                        if line[:1] == b"$":
                            ln = int(line[1:])
                            if ln >= 0:
                                f.read(ln + 2)

            def resp(*args):
                parts = [b"*%d\r\n" % len(args)]
                for a in args:
                    b = a if isinstance(a, bytes) else str(a).encode()
                    parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
                return b"".join(parts)

            run([resp("SET", f"nk{i:07d}", f"val{i}")
                 for i in range(n_keys)])
            rng = random.Random(7)
            gets = [resp("GET", f"nk{rng.randrange(n_keys):07d}")
                    for _ in range(n_keys)]
            run(gets[:pipeline])  # warm both paths' caches
            t0 = time.perf_counter()
            run(gets)
            native_dt = time.perf_counter() - t0
            native_get = RedisServiceImpl._native_get_values
            RedisServiceImpl._native_get_values = \
                lambda self, rkeys: None
            try:
                t0 = time.perf_counter()
                run(gets)
                py_dt = time.perf_counter() - t0
            finally:
                RedisServiceImpl._native_get_values = native_get
            sock.close()
            server.shutdown()
            out.append({
                "metric": "redis_native_batch_get_ops_per_sec",
                "value": round(n_keys / native_dt, 1),
                "unit": f"GET ops/s (native batch path, pipeline "
                        f"{pipeline}, RF=3)",
                "vs_baseline": round(n_keys / native_dt / (538_000 / 3),
                                     2),
                "python_per_op_ops_per_sec": round(n_keys / py_dt, 1),
                "speedup_vs_python": round(py_dt / native_dt, 2),
            })

            # -- CQL: pipelined prepared point SELECTs ------------------
            cql = CQLServer(ClientCluster(mc.client()))
            host, port = cql.listen("127.0.0.1", 0)
            sock = socket.create_connection((host, port))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

            def send(stream, opcode, body):
                sock.sendall(W.HEADER.pack(W.VERSION_REQ, 0, stream,
                                           opcode, len(body)) + body)

            def recvn(n):
                buf = b""
                while len(buf) < n:
                    chunk = sock.recv(n - len(buf))
                    assert chunk
                    buf += chunk
                return buf

            def recv_frame():
                hdr = recvn(W.HEADER.size)
                _v, _fl, _s, op, ln = W.HEADER.unpack(hdr)
                return op, recvn(ln)

            def query(q):
                w = W.Writer().long_string(q).short(1).byte(0)
                send(1, W.OP_QUERY, w.getvalue())
                op, body = recv_frame()
                assert op == W.OP_RESULT, body

            w = W.Writer()
            w.short(1)
            w.string("CQL_VERSION").string("3.4.4")
            send(0, W.OP_STARTUP, w.getvalue())
            assert recv_frame()[0] == W.OP_READY
            query("CREATE KEYSPACE IF NOT EXISTS bench_sp")
            query("USE bench_sp")
            query("CREATE TABLE t (k bigint PRIMARY KEY, v text)")
            for i in range(cql_rows):
                query(f"INSERT INTO t (k, v) VALUES ({i}, 'val{i}')")
            send(1, W.OP_PREPARE,
                 W.Writer().long_string(
                     "SELECT k, v FROM t WHERE k = ?").getvalue())
            op, body = recv_frame()
            assert op == W.OP_RESULT, body
            r = W.Reader(body)
            assert r.int32() == W.RESULT_PREPARED
            stmt_id = r.short_bytes()

            def exec_frames(keys):
                frames = []
                for s, k in enumerate(keys):
                    w = W.Writer().short_bytes(stmt_id)
                    w.short(1).byte(0x01).short(1)
                    w.bytes_(k.to_bytes(8, "big", signed=True))
                    b = w.getvalue()
                    frames.append(W.HEADER.pack(
                        W.VERSION_REQ, 0, s + 2, W.OP_EXECUTE, len(b))
                        + b)
                return b"".join(frames)

            keys = [rng.randrange(cql_rows) for _ in range(cql_ops)]
            bufs = [exec_frames(keys[c0:c0 + window])
                    for c0 in range(0, len(keys), window)]

            def sweep():
                for buf, c0 in zip(bufs, range(0, len(keys), window)):
                    sock.sendall(buf)
                    for _ in range(len(keys[c0:c0 + window])):
                        recv_frame()

            sweep()  # warm
            t0 = time.perf_counter()
            sweep()
            native_dt = time.perf_counter() - t0
            batch = QLProcessor.execute_wire_point_batch
            QLProcessor.execute_wire_point_batch = \
                lambda self, items: [None] * len(items)
            try:
                t0 = time.perf_counter()
                sweep()
                py_dt = time.perf_counter() - t0
            finally:
                QLProcessor.execute_wire_point_batch = batch
            sock.close()
            cql.shutdown()
            out.append({
                "metric": "ycql_native_point_select_ops_per_sec",
                "value": round(cql_ops / native_dt, 1),
                "unit": f"prepared point SELECT ops/s (native batch "
                        f"path, window {window}, RF=3)",
                "vs_baseline": None,
                "python_per_op_ops_per_sec": round(cql_ops / py_dt, 1),
                "speedup_vs_python": round(py_dt / native_dt, 2),
            })
        finally:
            mc.shutdown()
    return out


def bench_multisource(schema, tpu, cpu, max_ht, S, waves=4):
    """Post-write scans: after heavy update traffic the engine holds a
    live memtable + overlapping runs (the VERDICT-flagged shape real
    workloads spend most time in). Applies 4 waves of updates to 2% of
    keys (flushing between the first 3 — leaving 4 runs + a non-empty
    memtable), verifies results against the CPU oracle, and measures the
    steady-state aggregate scan against the single-run number measured
    beforehand. The delta overlay (storage.tpu_engine._overlay) is what
    keeps this a pure device scan; its one-time build cost is reported
    separately."""
    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.storage.row_version import RowVersion

    def spec(rht, lo=-500_000):
        return S.ScanSpec(
            read_ht=rht, predicates=[S.Predicate("d", ">=", lo)],
            aggregates=[S.AggSpec("count", None), S.AggSpec("sum", "a"),
                        S.AggSpec("min", "a"), S.AggSpec("max", "a")])

    tpu.scan(spec(max_ht + 1))
    t_single = _median(lambda: tpu.scan(spec(max_ht + 1)))

    rng = random.Random(5)
    cid = {c.name: c.col_id for c in schema.value_columns}
    ht = max_ht
    for wave in range(waves):
        batch = []
        for _ in range(NUM_KEYS // 50):
            i = rng.randrange(NUM_KEYS)
            ht += 1
            key = schema.encode_primary_key(
                {"k": f"user{i:06d}", "r": i % 7},
                compute_hash_code(schema, {"k": f"user{i:06d}"}))
            batch.append(RowVersion(
                key, ht=ht,
                columns={cid["d"]: rng.randrange(-10**6, 10**6)}))
        tpu.apply(batch)
        cpu.apply(batch)
        if wave < waves - 1:
            tpu.flush()
            cpu.flush()

    a = cpu.scan(spec(ht + 1))
    t0 = time.perf_counter()
    b = tpu.scan(spec(ht + 1))  # first scan pays the full overlay build
    t_first_build = time.perf_counter() - t0
    assert a.rows == b.rows, (a.rows, b.rows)
    t_multi = _median(lambda: tpu.scan(spec(ht + 1)))

    # Steady state: one more memtable-only write wave, then the overlay
    # advances INCREMENTALLY by the memtable delta (versions_since) —
    # this is the recurring per-wave cost, the number that was 899ms
    # when every wave re-collected the whole dirty set.
    batch = []
    for _ in range(NUM_KEYS // 50):
        i = rng.randrange(NUM_KEYS)
        ht += 1
        key = schema.encode_primary_key(
            {"k": f"user{i:06d}", "r": i % 7},
            compute_hash_code(schema, {"k": f"user{i:06d}"}))
        batch.append(RowVersion(
            key, ht=ht, columns={cid["d"]: rng.randrange(-10**6, 10**6)}))
    tpu.apply(batch)
    cpu.apply(batch)
    t0 = time.perf_counter()
    tpu._overlay(tpu.memtable)  # the delta apply, isolated from the scan
    t_delta = time.perf_counter() - t0
    a = cpu.scan(spec(ht + 1))
    b = tpu.scan(spec(ht + 1))
    assert a.rows == b.rows, (a.rows, b.rows)

    versions = sum(t.crun.num_versions for t in tpu.runs) + \
        tpu.memtable.num_versions
    return {
        "metric": "postwrite_scan_rows_per_sec",
        "value": round(versions / t_multi, 1),
        "unit": (f"rows/s (memtable + {len(tpu.runs)} overlapping runs, "
                 "single aggregate scan)"),
        "vs_baseline": round(
            (versions / t_multi) / CPP_NODE_SCAN_ROWS_S, 2),
        "vs_single_run": round(t_single / t_multi, 2),
        "latency_ms": round(t_multi * 1000, 1),
        "overlay_build_ms": round(t_delta * 1000, 1),
        "overlay_first_build_ms": round(t_first_build * 1000, 1),
    }


def bench_oversubscribed(schema, rows, max_ht, make_engine, S, parts=4,
                         rounds=3):
    """Working set ≈ 4× the HBM budget: four single-run engines share
    the process-wide residency cache with ``--tpu_hbm_budget_bytes``
    shrunk to about one run's planes, so each round-robin scan
    demand-re-uploads what the previous scans evicted (the RocksDB
    block-cache oversubscription shape). End-to-end and honest: the
    measured time includes every re-upload."""
    from yugabyte_db_tpu.storage.residency import hbm_cache
    from yugabyte_db_tpu.utils.flags import FLAGS

    def spec():
        return S.ScanSpec(
            read_ht=max_ht + 1,
            aggregates=[S.AggSpec("count", None), S.AggSpec("sum", "a"),
                        S.AggSpec("min", "a"), S.AggSpec("max", "a")])

    chunk = len(rows) // parts
    engines = []
    versions = 0
    for p in range(parts):
        e = make_engine("tpu", schema, {"rows_per_block": 2048})
        e.apply(rows[p * chunk:(p + 1) * chunk])
        e.flush()
        engines.append(e)
        versions += sum(t.crun.num_versions for t in e.runs)
    total_planes = sum(t._nbytes_hint() for e in engines for t in e.runs)
    cache = hbm_cache()
    old_budget = FLAGS.get("tpu_hbm_budget_bytes")
    FLAGS.set("tpu_hbm_budget_bytes", max(total_planes // parts, 1))
    try:
        for e in engines:  # compile warmup (first upload included below)
            e.scan(spec())
        m0 = cache.stats()["misses"]
        u0 = cache.stats()["demand_upload_bytes"]
        t0 = time.perf_counter()
        for _ in range(rounds):
            for e in engines:
                e.scan(spec())
        dt = time.perf_counter() - t0
        st = cache.stats()
        churn = st["misses"] - m0
        upload_mb = (st["demand_upload_bytes"] - u0) / 1e6
        # Compressed-plane accounting: how much smaller each demand
        # re-upload is than the plain format would have been
        # (--tpu_plane_encoding). Ratio < 1.0 is budget headroom.
        enc_b = sum(e.plane_stats()["encoded_bytes"] for e in engines)
        log_b = sum(e.plane_stats()["logical_bytes"] for e in engines)
        enc_ratio = round(enc_b / log_b, 3) if log_b else 1.0
    finally:
        FLAGS.set("tpu_hbm_budget_bytes", old_budget)
        for e in engines:
            e.close()
    return {
        "metric": "oversubscribed_scan_rows_per_sec",
        "value": round(versions * rounds / dt, 1),
        "unit": (f"rows/s ({parts} single-run engines round-robin, "
                 f"budget = working set / {parts})"),
        "vs_baseline": round(
            (versions * rounds / dt) / CPP_NODE_SCAN_ROWS_S, 2),
        "demand_reuploads": churn,
        "demand_upload_mb": round(upload_mb, 1),
        "plane_encoded_ratio": enc_ratio,
        "latency_ms": round(dt * 1000 / (parts * rounds), 1),
    }


def bench_oversubscribed_friendly(make_engine, S, parts=4, rounds=3,
                                  n=None):
    """The oversubscription shape on dictionary/RLE-friendly columns
    (low-cardinality strings, long int runs, small per-block deltas) —
    the workloads compressed planes exist for. Measures the SAME budget
    twice: --tpu_plane_encoding=auto (compressed re-uploads) then =off
    (plain re-uploads), and reports the re-upload byte reduction."""
    import random as _r

    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.models.schema import (
        ColumnKind, ColumnSchema, Schema,
    )
    from yugabyte_db_tpu.storage.residency import hbm_cache
    from yugabyte_db_tpu.storage.row_version import RowVersion
    from yugabyte_db_tpu.utils.flags import FLAGS

    n = n or max(NUM_KEYS // 2, 20_000)
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("city", DataType.STRING),
        ColumnSchema("grp", DataType.INT32),
        ColumnSchema("seq", DataType.INT32),
    ], table_id="bench_enc")
    cid = {c.name: c.col_id for c in schema.columns}
    cities = [f"city{j:03d}" for j in range(64)]
    rng = _r.Random(13)
    rows = []
    ht = 100
    for i in range(n):
        key = schema.encode_primary_key(
            {"k": f"user{i:06d}", "r": i % 7},
            compute_hash_code(schema, {"k": f"user{i:06d}"}))
        ht += 1
        rows.append(RowVersion(key, ht=ht, liveness=True, columns={
            cid["city"]: rng.choice(cities),
            cid["grp"]: (i // 4096) * 1_000_000,
            cid["seq"]: i % 10_000,
        }))

    def spec():
        return S.ScanSpec(
            read_ht=ht + 1,
            predicates=[S.Predicate("city", "<", "city032")],
            aggregates=[S.AggSpec("count", None), S.AggSpec("sum", "grp"),
                        S.AggSpec("max", "seq")])

    cache = hbm_cache()
    old_budget = FLAGS.get("tpu_hbm_budget_bytes")
    old_enc = FLAGS.get("tpu_plane_encoding")
    chunk = len(rows) // parts
    engines = []
    versions = 0
    try:
        for p in range(parts):
            e = make_engine("tpu", schema, {"rows_per_block": 2048})
            e.apply(rows[p * chunk:(p + 1) * chunk])
            e.flush()
            engines.append(e)
            versions += sum(t.crun.num_versions for t in e.runs)
        total_planes = sum(t._nbytes_hint()
                           for e in engines for t in e.runs)
        FLAGS.set("tpu_hbm_budget_bytes", max(total_planes // parts, 1))

        def measure():
            for e in engines:  # warmup (compiles + first uploads)
                e.scan(spec())
            u0 = cache.stats()["demand_upload_bytes"]
            t0 = time.perf_counter()
            for _ in range(rounds):
                for e in engines:
                    e.scan(spec())
            dt = time.perf_counter() - t0
            return cache.stats()["demand_upload_bytes"] - u0, dt

        FLAGS.set("tpu_plane_encoding", "auto")
        for e in engines:
            for t in e.runs:
                t._dev_nbytes_hint = None
                t.invalidate_device()
        up_enc, dt_enc = measure()
        FLAGS.set("tpu_plane_encoding", "off")
        for e in engines:
            for t in e.runs:
                t._dev_nbytes_hint = None
                t.invalidate_device()
        up_plain, dt_plain = measure()
    finally:
        FLAGS.set("tpu_hbm_budget_bytes", old_budget)
        FLAGS.set("tpu_plane_encoding", old_enc)
        for e in engines:
            e.close()
    return {
        "metric": "oversubscribed_friendly_scan_rows_per_sec",
        "value": round(versions * rounds / dt_enc, 1),
        "unit": (f"rows/s ({parts} engines round-robin, dict/RLE-friendly "
                 f"columns, budget = working set / {parts}, encoded)"),
        "vs_baseline": round(
            (versions * rounds / dt_enc) / CPP_NODE_SCAN_ROWS_S, 2),
        "vs_plain_planes": round(dt_plain / dt_enc, 2),
        "demand_upload_mb": round(up_enc / 1e6, 1),
        "demand_upload_mb_plain": round(up_plain / 1e6, 1),
        "reupload_reduction_x": round(up_plain / up_enc, 2)
        if up_enc else None,
    }


def bench_tpch(make_engine):
    from yugabyte_db_tpu.yql.pgsql import tpch

    n = max(NUM_KEYS, 100_000)
    schema = tpch.lineitem_schema()
    tpu = make_engine("tpu", schema)
    cpu = make_engine("cpu", schema)
    ht = tpch.load_engine(tpu, schema, n)
    tpch.load_engine(cpu, schema, n)
    import collections

    out = []
    for name, build in (("tpch_q1", tpch.q1_spec), ("tpch_q6", tpch.q6_spec)):
        spec = build(ht + 1)
        a = cpu.scan(spec)
        b = tpu.scan(spec)
        assert a.rows == b.rows, name
        tdt = _median(lambda: tpu.scan(spec))
        t0 = time.perf_counter()
        cpu.scan(spec)
        cdt = time.perf_counter() - t0
        # Server throughput: concurrent copies of the query pipelined
        # through the async batch API (single-scan latency is one
        # synchronous fetch on the link and rides in the details).
        # vs_cpu_engine compares THROUGHPUT on the same 80-query
        # workload: the single-thread oracle gains nothing from
        # concurrency, so its serial per-query time extrapolates
        # linearly (same convention as bench_aggregate).
        batches = [[build(ht + 1) for _ in range(8)] for _ in range(10)]
        q = collections.deque()
        for bt in batches[:4]:
            q.append(tpu.scan_batch_async(bt))
        while q:
            q.popleft().finish()
        t0 = time.perf_counter()
        for bt in batches:
            q.append(tpu.scan_batch_async(bt))
            if len(q) > 4:
                q.popleft().finish()
        while q:
            q.popleft().finish()
        pdt = time.perf_counter() - t0
        # Two metrics, honestly named: the pipelined number measures a
        # different quantity (8 concurrent queries, depth-4 pipeline)
        # than the single-query scan rate, so it must not ship under
        # the plain rows_per_sec name history already tracks.
        out.append({
            "metric": f"{name}_pipelined_rows_per_sec",
            "value": round(n * 80 / pdt, 1),
            "unit": "rows/s (8 concurrent queries, depth-4 pipeline)",
            "vs_baseline": None,  # no TPC-H numbers exist in-reference
            "vs_cpu_engine": round(cdt * 80 / pdt, 2),
            "single_query_latency_ms": round(tdt * 1000, 1),
        })
        out.append({
            "metric": f"{name}_rows_per_sec",
            "value": round(n / tdt, 1),
            "unit": "rows/s (single query, synchronous)",
            "vs_baseline": None,
            "vs_cpu_engine": round(cdt / tdt, 2),
            "single_query_latency_ms": round(tdt * 1000, 1),
        })
    return out


def bench_kernel_scan(n_rows=16 * 1024 * 1024, R=2048, iters=12):
    """Device-resident scan-kernel throughput at HBM scale: 10M+ rows
    pre-staged as columnar planes in HBM, jit-warm, one full-run
    aggregate dispatch per iteration. Reports rows/s AND achieved GB/s
    (bytes = the planes the kernel actually reads per pass) for the
    flat path and the segmented MVCC-resolve path. The per-dispatch
    link overhead is removed by differencing a 1-dispatch and an
    N-dispatch timing (both end in one blocking fetch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from yugabyte_db_tpu.ops import agg_fold
    from yugabyte_db_tpu.ops import scan as dscan
    from yugabyte_db_tpu.utils import planes as P

    B = n_rows // R
    rng = np.random.default_rng(7)

    # Synthetic planes, directly in device layout (building 16M rows
    # through the memtable would measure Python, not the kernel).
    idx = np.arange(n_rows, dtype=np.int64)
    # MVCC shape: 2 versions per key group, newest first.
    ht_vals = (n_rows - (idx // 2) * 2) - (idx % 2)
    ht_hi, ht_lo = P.ht_to_planes(ht_vals)
    maxhi, maxlo = P.scalar_ht_planes((1 << 62))
    a_vals = rng.integers(-10**12, 10**12, n_rows, dtype=np.int64)
    a_hi, a_lo = P.i64_to_ordered_planes(a_vals)
    d_vals = rng.integers(-10**6, 10**6, n_rows, dtype=np.int32)

    def shape(x, extra=()):
        return np.ascontiguousarray(x.reshape((B, R) + tuple(extra)))

    dev = jax.devices()[0]

    def up(x):
        return jax.device_put(x, dev)

    arrays = {
        "valid": up(np.ones((B, R), dtype=bool)),
        "tomb": up(np.zeros((B, R), dtype=bool)),
        "live": up(np.ones((B, R), dtype=bool)),
        "group_start": up(shape((idx % 2 == 0))),
        "ht_hi": up(shape(ht_hi)),
        "ht_lo": up(shape(ht_lo)),
        "exp_hi": up(np.full((B, R), maxhi, dtype=np.int32)),
        "exp_lo": up(np.full((B, R), maxlo, dtype=np.int32)),
        "cols": {
            1: {"set": up(np.ones((B, R), dtype=bool)),
                "isnull": up(np.zeros((B, R), dtype=bool)),
                "cmp": up(shape(np.stack([a_hi, a_lo], axis=-1), (2,)))},
            2: {"set": up(np.ones((B, R), dtype=bool)),
                "isnull": up(np.zeros((B, R), dtype=bool)),
                "cmp": up(shape(d_vals, (1,)))},
        },
    }

    K = agg_fold.safe_window_blocks(R, agg_fold.FULL_WINDOW_BLOCKS)
    cols = (dscan.ColSig(1, "i64"), dscan.ColSig(2, "i32"))
    preds = (dscan.PredSig(2, "i32", ">="),)
    aggs = (dscan.AggSig("count", None, None),
            dscan.AggSig("sum", 1, "i64"),
            dscan.AggSig("max", 1, "i64"))
    r_hi, r_lo = P.scalar_ht_planes(1 << 61)
    e_hi, e_lo = P.scalar_ht_planes(1 << 61)
    pred_lits = (jnp.int32(-500_000),)
    W = B // K

    # Expected values (host numpy) for a correctness pin.
    flat_mask = d_vals >= -500_000
    mvcc_mask = flat_mask & ((idx % 2) == 0)  # newest version per group

    from yugabyte_db_tpu.ops import flat_fold, lookback_fold

    out = []
    for label, flat, mask in (("flat", True, flat_mask),
                              ("mvcc", False, mvcc_mask)):
        sig = dscan.ScanSig(B=B, R=R, K=K, cols=cols, preds=preds,
                            aggs=aggs, apply_preds=True, flat=flat,
                            lookback=0 if flat else 2)
        # The engine's fused full-array programs (flat_fold for flat
        # runs; bounded-lookback resolve for multi-version runs — the
        # route _plan_device_aggregate takes for this run shape).
        fn = (flat_fold.compiled_flat_aggregate(sig) if flat
              else lookback_fold.compiled_lookback_aggregate(sig))
        args = (arrays, jnp.int32(0), jnp.int32(n_rows),
                jnp.int32(r_hi), jnp.int32(r_lo),
                jnp.int32(e_hi), jnp.int32(e_lo), pred_lits)
        ivec, fvec = fn(*args)
        jax.block_until_ready(ivec)
        acc, _scanned = agg_fold.unpack(aggs, ivec, fvec)
        got_count = agg_fold.finalize(aggs[0], acc[0], "count")
        got_sum = agg_fold.finalize(aggs[1], acc[1], "sum")
        assert got_count == int(mask.sum()), (label, got_count)
        assert got_sum == int(a_vals[mask].sum()), label

        def run_n(n):
            t0 = time.perf_counter()
            res = None
            for _ in range(n):
                res = fn(*args)
            jax.block_until_ready(res)
            return time.perf_counter() - t0

        run_n(2)  # warm
        t1 = min(run_n(1) for _ in range(3))
        tm = min(run_n(iters) for _ in range(3))
        t_pass = max((tm - t1) / (iters - 1), 1e-9)

        bytes_per_pass = sum(
            x.nbytes for x in (
                arrays["valid"], arrays["tomb"], arrays["live"],
                arrays["ht_hi"], arrays["ht_lo"], arrays["exp_hi"],
                arrays["exp_lo"],
                arrays["cols"][1]["set"], arrays["cols"][1]["isnull"],
                arrays["cols"][1]["cmp"],
                arrays["cols"][2]["set"], arrays["cols"][2]["isnull"],
                arrays["cols"][2]["cmp"]))
        if not flat:
            # Free the ~600MB of staged planes before later benches: the
            # residue skews their upload-bound phases (measured on the
            # engine write bench).
            for leaf in jax.tree.leaves(arrays):
                leaf.delete()
        if not flat:
            bytes_per_pass += arrays["group_start"].nbytes
        out.append({
            "metric": f"kernel_{label}_scan_rows_per_sec",
            "value": round(n_rows / t_pass, 1),
            "unit": (f"rows/s ({n_rows/1e6:.0f}M-row HBM-resident run, "
                     "single full-run aggregate dispatch)"),
            "vs_baseline": round(
                (n_rows / t_pass) / CPP_NODE_SCAN_ROWS_S, 2),
            "hbm_gb_per_sec": round(bytes_per_pass / t_pass / 1e9, 1),
            "pass_ms": round(t_pass * 1000, 2),
        })
    return out


def bench_write(schema, rows, make_engine):
    eng = make_engine("tpu", schema, {"rows_per_block": 2048})

    def run():
        for i in range(0, len(rows), 4096):
            eng.apply(rows[i:i + 4096])
        eng.flush()

    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    rows_s = len(rows) / dt
    return {
        "metric": "batched_write_rows_per_sec",
        "value": round(rows_s, 1),
        "unit": "rows/s (engine apply+flush)",
        "vs_baseline": round(rows_s / CPP_NODE_BATCH_WRITE_ROWS_S, 2),
    }


def bench_cluster_write(n_rows=60_000, writers=4, batch=256):
    """Cluster write path end-to-end: MiniCluster RF=3, concurrent batched
    sessions -> tserver write RPC -> WAL append -> Raft replication to 2
    followers -> majority ack -> engine apply. The reference's comparable
    number is CassandraBatchKeyValue: 258K ops/s across 3 nodes => ~86K
    rows/s per node (this is ONE in-process 3-tserver cluster on one
    machine, fsync off — the reference bench also rode the SSD page
    cache). A real multi-process topology exists (tools.yb_ctl spawns
    1 master + 3 tserver processes; the same sessions drive it over
    TCP) but measures LOWER than in-process — the per-RPC socket/codec
    cost outweighs the extra interpreters — so the in-process number is
    the honest best configuration and stays comparable across rounds."""
    import tempfile
    import threading

    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("kv", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.STRING),
            ], num_tablets=6)
            table = client.open_table("kv")
            warm = YBSession(mc.client("warm"))
            for i in range(2000):
                warm.insert(table, {"k": f"w{i:08d}", "v": f"val{i}"})
                if warm.pending_ops >= batch:
                    warm.flush()
            warm.flush()

            per = n_rows // writers
            errors = []
            t0 = time.perf_counter()

            def worker(w):
                try:
                    s = YBSession(mc.client(f"w{w}"))
                    for i in range(w * per, (w + 1) * per):
                        s.insert(table, {"k": f"key{i:08d}", "v": f"val{i}"})
                        if s.pending_ops >= batch:
                            s.flush()
                    s.flush()
                except Exception as e:  # surfaced after join
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(writers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errors:
                raise errors[0]
            rows_s = per * writers / dt
        finally:
            mc.shutdown()
    return {
        "metric": "cluster_write_rows_per_sec",
        "value": round(rows_s, 1),
        "unit": (f"rows/s (RF=3 Raft+WAL, {writers} writers, "
                 f"batch {batch})"),
        "vs_baseline": round(rows_s / CPP_NODE_BATCH_WRITE_ROWS_S, 2),
    }


def bench_ycsb_a_cluster(n_keys=20_000, n_ops=24_000, workers=4,
                         batch=64, theta=0.99):
    """YCSB-A at cluster scope: 50/50 zipfian point-read/update through
    the full RF=3 write path (session batcher -> tserver RPC -> WAL ->
    Raft group commit -> commit-ack) — the mixed workload the write-path
    overhaul targets, where writes previously throttled the whole mix.
    Baseline: YCSB-A 107,120 ops/s across 3 nodes => ~35.7K per node
    (docs/yb-perf-v1.0.7.md:585-601)."""
    import bisect
    import tempfile
    import threading

    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema

    # Zipfian(theta) CDF over the keyspace — YCSB's request distribution.
    weights = [1.0 / (i + 1) ** theta for i in range(n_keys)]
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w
        cdf.append(acc)

    def zipf(rng):
        return bisect.bisect_left(cdf, rng.random() * acc)

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("ycsba", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.STRING),
            ], num_tablets=6)
            table = client.open_table("ycsba")
            load = YBSession(mc.client("load"))
            for i in range(n_keys):
                load.insert(table, {"k": f"user{i:08d}", "v": f"val{i}"})
                if load.pending_ops >= 256:
                    load.flush()
            load.flush()

            per = n_ops // workers
            errors = []

            def worker(w):
                try:
                    rng = random.Random(100 + w)
                    s = YBSession(mc.client(f"mix{w}"))
                    done = 0
                    while done < per:
                        half = min(batch, per - done) // 2 or 1
                        for _ in range(half):
                            i = zipf(rng)
                            s.insert(table, {"k": f"user{i:08d}",
                                             "v": f"v{rng.random():.6f}"})
                        s.flush()
                        got = s.get_many(table, [
                            {"k": f"user{zipf(rng):08d}"}
                            for _ in range(half)])
                        assert all(r is not None for r in got)
                        done += 2 * half
                except Exception as e:  # surfaced after join
                    errors.append(e)

            t0 = time.perf_counter()
            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errors:
                raise errors[0]
        finally:
            mc.shutdown()
    return {
        "metric": "ycsb_a_mixed_ops_per_sec",
        "value": round(n_ops / dt, 1),
        "unit": (f"ops/s (50/50 zipfian read/write, RF=3 cluster, "
                 f"{workers} sessions, batch {batch})"),
        "vs_baseline": round(n_ops / dt / (107_120 / 3), 2),
    }


def bench_traffic(seed=1234):
    """Sustained-traffic replay: the seeded mixed-protocol sweep
    (YCSB-A/B/E + TPC-H Q1/Q6 + Redis, zipfian hot keys) against a live
    RF=3 cluster WHILE both seed tablets split, a follower rolls, and
    the leader balancer moves leaders — the elasticity scenario, not a
    steady-state ceiling. Emits the sweep's TRAFFIC_METRICS line
    (per-protocol p50/p99 + ops/s, splits fired, leader moves) and
    returns it as the section sub-metric."""
    import tempfile

    from yugabyte_db_tpu.integration.traffic_sweep import run_sweep

    with tempfile.TemporaryDirectory() as root:
        out = run_sweep(root, seed)
    print("TRAFFIC_METRICS " + json.dumps(out, sort_keys=True))
    return {
        "metric": "traffic",
        "value": out["ops_per_sec"],
        "unit": ("ops/s (mixed YCSB/TPC-H/Redis under splits + "
                 "rolling restart + leader rebalance, RF=3)"),
        "splits_fired": out["splits_fired"],
        "leader_moves": out["leader_moves"],
        "protocols": out["protocols"],
    }


def bench_device_flush(schema, rows, make_engine, n=65_536):
    """Flush cost after the device-side overhaul: one memtable of n rows
    built into a sorted columnar run. The device path stages the op log,
    computes the sort permutation host-side, and materializes the padded
    planes in one jitted scatter (ops/flush.py) — seeding HBM residency
    with no separate upload; the host path is the pre-overhaul numpy /
    native build, timed on identical contents."""
    from yugabyte_db_tpu.utils.flags import FLAGS
    from yugabyte_db_tpu.utils.metrics import flush_path_count

    work = rows[:n]
    old = FLAGS.get("tpu_device_flush")

    def timed_flush(device):
        FLAGS.set("tpu_device_flush", device)
        eng = make_engine("tpu", schema, {"rows_per_block": 2048})
        eng.apply(work)
        t0 = time.perf_counter()
        eng.flush()
        dt = time.perf_counter() - t0
        eng.close()
        return dt

    try:
        timed_flush(True)  # warm the scatter compile for this bucket
        d0 = flush_path_count("device")
        dev_dt = min(timed_flush(True) for _ in range(3))
        assert flush_path_count("device") == d0 + 3, \
            "device flush fell back to host"
        host_dt = min(timed_flush(False) for _ in range(2))
    finally:
        FLAGS.set("tpu_device_flush", old)
    return {
        "metric": "postflush_device_flush_ms",
        "value": round(dev_dt * 1000, 1),
        "unit": f"ms (device-path memtable flush, {len(work)} rows)",
        "vs_baseline": None,  # no comparable in-reference microbenchmark
        "host_flush_ms": round(host_dt * 1000, 1),
        "speedup_vs_host": round(host_dt / dev_dt, 2),
        "rows_per_sec": round(len(work) / dev_dt, 1),
    }


def bench_compact(schema, rows, max_ht, make_engine):
    """4-run merge with REAL history GC: base load + 3 update/delete
    waves over the same keyspace (multi-version groups, tombstones),
    compacted at the max cutoff — the shape update traffic actually
    leaves behind (a disjoint-run merge would never exercise the
    retention filter). Output content is pinned to the CPU oracle."""
    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.storage.row_version import RowVersion

    cid = {c.name: c.col_id for c in schema.value_columns}
    per_wave = max(1, int(NUM_KEYS * 0.35))

    def load(name):
        e = make_engine(name, schema, {"rows_per_block": 2048})
        e.apply(rows)
        e.flush()
        rng = random.Random(9)
        ht = max_ht
        for _wave in range(3):
            batch = []
            for _ in range(per_wave):
                i = rng.randrange(NUM_KEYS)
                ht += 1
                key = schema.encode_primary_key(
                    {"k": f"user{i:06d}", "r": i % 7},
                    compute_hash_code(schema, {"k": f"user{i:06d}"}))
                if rng.random() < 0.1:
                    batch.append(RowVersion(key, ht=ht, tombstone=True))
                else:
                    batch.append(RowVersion(
                        key, ht=ht,
                        columns={cid["d"]: rng.randrange(-10**6, 10**6)}))
            e.apply(batch)
            e.flush()
        return e, ht

    n_versions = len(rows) + 3 * per_wave
    tpu, cut = load("tpu")
    tpu.compact(cut)  # includes one-time compile/warm costs
    tpu2, cut = load("tpu")
    t0 = time.perf_counter()
    tpu2.compact(cut)
    tdt = time.perf_counter() - t0
    cpu, cut2 = load("cpu")
    t0 = time.perf_counter()
    cpu.compact(cut2)
    cdt = time.perf_counter() - t0
    ca, cb = cpu.dump_entries(), tpu2.dump_entries()
    assert [k for k, _ in ca] == [k for k, _ in cb]
    for (k1, v1), (_k2, v2) in zip(ca, cb):
        assert [(r.ht, r.tombstone, r.columns) for r in v1] == \
            [(r.ht, r.tombstone, r.columns) for r in v2], k1
    return {
        "metric": "compaction_versions_per_sec",
        "value": round(n_versions / tdt, 1),
        "unit": "versions/s (4-run merge + full history GC)",
        "vs_baseline": None,  # no comparable in-reference microbenchmark
        "vs_cpu_engine": round(cdt / tdt, 2),
    }


def _section_subprocess(name, timeout_s=1800):
    """Run one bench section isolated in a child interpreter (via
    ``--only name``): a native crash — the known in-process MiniCluster
    segfault under bench_cluster_write — costs that section its rc, not
    the whole headline run. Returns (sub-metric dicts, rc)."""
    import subprocess

    cmd = [sys.executable, __file__, "--only", name, str(NUM_KEYS)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
        rc, out = proc.returncode, proc.stdout
    except subprocess.TimeoutExpired as e:
        rc, out = -1, (e.stdout or "")
    subs = []
    for line in out.splitlines():
        if not line.startswith("# "):
            continue
        try:
            d = json.loads(line[2:])
        except ValueError:
            continue
        if isinstance(d, dict) and "metric" in d and \
                d["metric"] != "jit_compiles_per_entry":
            subs.append(d)
    if not subs:
        subs = [{"metric": name, "error": f"section subprocess rc={rc}"}]
    return subs, rc


# Sections that consume the shared engine pair bench_aggregate builds.
_DEP_AGG = ("aggregate", "ycsb_e", "point_read", "multisource")
# Sections that consume the shared (schema, rows) dataset.
_NEED_ROWS = _DEP_AGG + ("oversubscribed", "write", "device_flush",
                         "compact")


def main():
    import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401 registers 'tpu'
    from yugabyte_db_tpu import storage as S
    from yugabyte_db_tpu.storage import make_engine

    if COMPILE_WITNESS or CWITNESS_OUT:
        from yugabyte_db_tpu.utils import jitting
        jitting.enable_compile_witness()

    def want(name):
        return (ONLY is None or name in ONLY) and name not in SKIP

    sections = {}  # name -> rc (0 ok; >0 exception; <0 signal/timeout)
    subs = []

    def run(name, fn):
        if not want(name):
            return
        try:
            out = fn()
            sections[name] = 0
        except Exception as e:  # noqa: BLE001 — a section must not kill the run
            sections[name] = 1
            out = {"metric": name, "error": repr(e)}
        subs.extend(out if isinstance(out, (list, tuple)) else [out])

    # Cluster sections first (host-CPU-bound: they measure low after the
    # TPU workloads' background threads/memory are resident). On a full
    # run each one is isolated in a child interpreter; with --only we ARE
    # the child (or the user asked for exactly this section): in-process.
    for cname, cfn in (("cluster_write", bench_cluster_write),
                       ("ycsb_a_cluster", bench_ycsb_a_cluster),
                       ("traffic", bench_traffic)):
        if not want(cname):
            continue
        if ONLY is None:
            csubs, rc = _section_subprocess(cname)
            sections[cname] = rc
            subs.extend(csubs)
        else:
            run(cname, cfn)

    schema = rows = max_ht = None
    if any(want(n) for n in _NEED_ROWS):
        from __graft_entry__ import _make_rows, _make_schema

        schema = _make_schema()
        rows, max_ht = _make_rows(schema, NUM_KEYS)

    tpu = cpu = headline = None
    if any(want(n) for n in _DEP_AGG):
        try:
            tpu, cpu, versions, headline = bench_aggregate(
                schema, rows, max_ht, make_engine, S)
            sections["aggregate"] = 0
        except Exception as e:  # noqa: BLE001 — dependents degrade, run continues
            sections["aggregate"] = 1
            subs.append({"metric": "aggregate", "error": repr(e)})
    if tpu is not None:
        run("ycsb_e", lambda: bench_ycsb_e(schema, tpu, cpu, max_ht, S))
        run("point_read",
            lambda: bench_point_reads(schema, tpu, cpu, max_ht, S))
    run("ycsb_mix", lambda: bench_ycsb_mix(make_engine, S))
    run("index", bench_index)
    run("redis", bench_redis)
    run("serving_path", bench_serving_path)
    if tpu is not None:
        run("multisource",
            lambda: bench_multisource(schema, tpu, cpu, max_ht, S))
    run("oversubscribed",
        lambda: bench_oversubscribed(schema, rows, max_ht, make_engine, S))
    run("oversubscribed_friendly",
        lambda: bench_oversubscribed_friendly(make_engine, S))
    run("kernel_scan", bench_kernel_scan)
    run("tpch", lambda: bench_tpch(make_engine))
    run("write", lambda: bench_write(schema, rows, make_engine))
    run("device_flush",
        lambda: bench_device_flush(schema, rows, make_engine))
    run("compact", lambda: bench_compact(schema, rows, max_ht, make_engine))

    details = {}
    for sub in subs:
        print("# " + json.dumps(sub))
        details[sub["metric"]] = {k: v for k, v in sub.items()
                                  if k != "metric"}

    from yugabyte_db_tpu.utils import metrics
    compiles = metrics.jit_compiles()
    print("# " + json.dumps({"metric": "jit_compiles_per_entry",
                             "value": sum(compiles.values()),
                             "unit": "XLA compiles (whole suite)",
                             "per_entry": compiles}))
    if CWITNESS_OUT:
        from yugabyte_db_tpu.utils import jitting
        jitting.dump_compile_witness(CWITNESS_OUT)

    if headline is not None and want("aggregate"):
        headline["details"] = details
        headline["sections"] = sections
        headline["baseline_note"] = (
            "vs_baseline compares one chip against a calibrated C++-class "
            "16-vCPU reference NODE (~29K scanned rows/s/vCPU, BASELINE.md); "
            "vs_cpu_engine compares against the in-repo CPU oracle engine")
        print(json.dumps(headline))
    else:
        # Partial run (--only/--skip without the headline section):
        # still end with ONE machine-readable JSON line.
        print(json.dumps({"metric": "bench_sections",
                          "sections": sections, "details": details}))


if __name__ == "__main__":
    main()
