"""Headline benchmark: range-scan + aggregate rows/sec through the TPU
storage engine vs the CPU engine baseline (BASELINE.json configs 1-3).

Workload shape: TPC-H-Q6-flavored aggregate range scan (count/sum/min/max
with a numeric predicate) over a YCSB-style KV table — the path where the
reference walks DocRowwiseIterator/MergingIterator row by row
(src/yb/docdb/doc_rowwise_iterator.cc:545) and this framework runs the
MVCC-merge + filter + aggregate as one device program over columnar blocks.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
value = MVCC row versions scanned per second on the device engine and
vs_baseline = speedup over the CPU oracle engine on identical data+query.
"""

from __future__ import annotations

import json
import sys
import time

NUM_KEYS = int(sys.argv[1]) if len(sys.argv) > 1 else 120_000
TIMED_ITERS = 8


def main():
    from __graft_entry__ import _make_rows, _make_schema
    from yugabyte_db_tpu.storage import AggSpec, Predicate, ScanSpec, make_engine
    import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401 (registers 'tpu')

    schema = _make_schema()
    rows, max_ht = _make_rows(schema, NUM_KEYS)

    tpu = make_engine("tpu", schema, {"rows_per_block": 2048})
    tpu.apply(rows)
    tpu.flush()

    spec = ScanSpec(read_ht=max_ht + 1,
                    predicates=[Predicate("d", ">=", -500_000)],
                    aggregates=[AggSpec("count", None), AggSpec("sum", "a"),
                                AggSpec("min", "a"), AggSpec("max", "a"),
                                AggSpec("sum", "d")])

    warm = tpu.scan(spec)           # compile + upload warmup
    t0 = time.perf_counter()
    for _ in range(TIMED_ITERS):
        res = tpu.scan(spec)
    tpu_dt = (time.perf_counter() - t0) / TIMED_ITERS
    assert res.rows == warm.rows
    versions = tpu.runs[0].crun.num_versions
    tpu_rows_s = versions / tpu_dt

    cpu = make_engine("cpu", schema)
    cpu.apply(rows)
    cpu.flush()
    t0 = time.perf_counter()
    cres = cpu.scan(spec)
    cpu_dt = time.perf_counter() - t0
    cpu_rows_s = versions / cpu_dt

    for g, w in zip(res.rows[0], cres.rows[0]):
        if isinstance(w, float):
            assert g is not None and abs(g - w) <= 1e-3 + 1e-5 * abs(w), (g, w)
        else:
            assert g == w, (g, w)

    print(json.dumps({
        "metric": "aggregate_range_scan_rows_per_sec",
        "value": round(tpu_rows_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rows_s / cpu_rows_s, 2),
    }))


if __name__ == "__main__":
    main()
