// Native implementation of the framework's tagged binary codec.
//
// Same wire grammar as yugabyte_db_tpu/utils/codec.py (the canonical
// spec): tag byte then payload; varints are LEB128; ints are zigzag.
// The reference serializes its WAL/RPC records through C++ protobuf
// (src/yb/consensus/consensus.proto, log.proto) — this module puts the
// equivalent hot path (every RPC payload and WAL record body) in native
// code, with the Python implementation as the compatibility fallback
// for arbitrary-precision integers (OverflowError here -> Python path).
//
// The codec core lives in tagcodec.h, shared with writeplane.cc.
//
// Exposed as the CPython extension module `yb_codec`:
//   yb_codec.encode(obj) -> bytes
//   yb_codec.decode(bytes_like) -> obj

#include "tagcodec.h"

namespace {

using ybtag::Buf;
using ybtag::Reader;

PyObject* py_encode(PyObject*, PyObject* arg) {
  Buf b;
  if (!ybtag::encode_obj(&b, arg, 0)) return nullptr;
  return PyBytes_FromStringAndSize(b.data, (Py_ssize_t)b.len);
}

PyObject* py_decode(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{static_cast<const unsigned char*>(view.buf),
           (size_t)view.len};
  PyObject* v = ybtag::decode_obj(&r, 0);
  if (v != nullptr && r.pos != r.len) {
    PyErr_Format(PyExc_ValueError, "codec: %zu trailing bytes",
                 r.len - r.pos);
    Py_CLEAR(v);
  }
  PyBuffer_Release(&view);
  return v;
}

PyMethodDef kMethods[] = {
    {"encode", py_encode, METH_O, "encode(obj) -> bytes"},
    {"decode", py_decode, METH_O, "decode(bytes_like) -> obj"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "yb_codec",
    "native tagged binary codec (see yugabyte_db_tpu/utils/codec.py)",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_yb_codec() { return PyModule_Create(&kModule); }
