// Native write plane: row-block codec, client-side batch encoding,
// leader-side hybrid-time stamping, and the C++ memtable.
//
// The reference's entire write pipeline is C++ — RPC framing
// (src/yb/rpc/reactor.cc), WAL group-commit append (src/yb/consensus/
// log.cc Log::Appender/TaskStream), leader-side batch assembly
// (src/yb/tablet/preparer.cc), and the rocksdb memtable
// (src/yb/rocksdb/memtable). This module is the equivalent hot path for
// the TPU-native framework: a write batch is encoded ONCE on the client
// into a contiguous "row block" (doc-key encoding + partition hashing +
// per-tablet split all native), flows opaque through RPC, the WAL body,
// and Raft replication, is stamped with the commit hybrid time by a
// single native pass on the leader, and lands in a C++ memtable on every
// replica — no per-row Python objects anywhere on the path.
//
// Row block layout (little-endian):
//   u32 nrows, then per row:
//     u16 key_len, key bytes        (byte-comparable DocKey)
//     u64 ht                        (commit hybrid time; 0 until stamped)
//     u64 expire_ht                 (TTL expiry; MAX_HT = none)
//     i64 ttl_us                    (-1 = none; resolved at stamping)
//     u32 write_id                  (intra-batch MVCC order)
//     u8  flags                     (1 = tombstone, 2 = liveness)
//     u16 ncols, then per column: u32 col_id, tagged value (tagcodec.h)
//
// The pure-Python spec lives in yugabyte_db_tpu/storage/rowblock.py;
// yugabyte_db_tpu/storage/memtable.py documents the memtable interface.
//
// Exposed as the CPython extension module `yb_wp`.

#include "keycodec.h"
#include "tagcodec.h"

#include <algorithm>
#include <map>
#include <unordered_map>
#include <string>
#include <vector>

namespace {

using ybtag::Buf;
using ybtag::Reader;
using namespace ybkey;  // key tags, dtype codes, crc32, LE scalar helpers

constexpr uint64_t kMaxHT = (1ULL << 63) - 1;

// -- record writer -----------------------------------------------------------

struct RecHeader {
  uint64_t ht;
  uint64_t expire_ht;
  int64_t ttl_us;      // -1 = none
  uint32_t write_id;
  uint8_t flags;       // 1 = tombstone, 2 = liveness
};

// After key: ht(8) expire(8) ttl(8) write_id(4) flags(1) ncols(2)
constexpr size_t kFixedAfterKey = 8 + 8 + 8 + 4 + 1 + 2;

bool write_rec_fixed(Buf* b, const RecHeader& h, uint16_t ncols) {
  return put_u64(b, h.ht) && put_u64(b, h.expire_ht) &&
         put_i64(b, h.ttl_us) && put_u32(b, h.write_id) &&
         ybtag::buf_putc(b, h.flags) && put_u16(b, ncols);
}

// Parse one record starting at r->pos. On success leaves r->pos at the
// next record and fills out the component offsets/lengths.
struct RecView {
  const unsigned char* key;
  size_t key_len;
  size_t fixed_off;     // offset of ht field within the block
  RecHeader h;
  uint16_t ncols;
  const unsigned char* cols;
  size_t cols_len;
};

bool parse_rec(Reader* r, RecView* out) {
  if (!ybtag::need(r, 2)) return false;
  uint16_t klen = get_u16(r->data + r->pos);
  r->pos += 2;
  if (!ybtag::need(r, klen + kFixedAfterKey)) return false;
  out->key = r->data + r->pos;
  out->key_len = klen;
  r->pos += klen;
  out->fixed_off = r->pos;
  const unsigned char* p = r->data + r->pos;
  out->h.ht = get_u64(p);
  out->h.expire_ht = get_u64(p + 8);
  out->h.ttl_us = get_i64(p + 16);
  out->h.write_id = get_u32(p + 24);
  out->h.flags = p[28];
  out->ncols = get_u16(p + 29);
  r->pos += kFixedAfterKey;
  size_t cols_start = r->pos;
  out->cols = r->data + cols_start;
  for (uint16_t i = 0; i < out->ncols; i++) {
    if (!ybtag::need(r, 4)) return false;
    r->pos += 4;
    if (!ybtag::skip_obj(r, 0)) return false;
  }
  out->cols_len = r->pos - cols_start;
  return true;
}

bool read_nrows(Reader* r, uint32_t* nrows) {
  if (!ybtag::need(r, 4)) return false;
  *nrows = get_u32(r->data + r->pos);
  r->pos += 4;
  return true;
}

// Decode a record's column section into a fresh dict {col_id: value}.
PyObject* cols_to_dict(const unsigned char* cols, size_t cols_len,
                       uint16_t ncols) {
  PyObject* d = PyDict_New();
  if (d == nullptr) return nullptr;
  Reader r{cols, cols_len};
  for (uint16_t i = 0; i < ncols; i++) {
    if (!ybtag::need(&r, 4)) { Py_DECREF(d); return nullptr; }
    uint32_t col_id = get_u32(r.data + r.pos);
    r.pos += 4;
    PyObject* key = PyLong_FromUnsignedLong(col_id);
    if (key == nullptr) { Py_DECREF(d); return nullptr; }
    PyObject* val = ybtag::decode_obj(&r, 0);
    if (val == nullptr) { Py_DECREF(key); Py_DECREF(d); return nullptr; }
    int rc = PyDict_SetItem(d, key, val);
    Py_DECREF(key);
    Py_DECREF(val);
    if (rc < 0) { Py_DECREF(d); return nullptr; }
  }
  return d;
}

// Build the Python row tuple (key, ht, tombstone, liveness, columns,
// expire_ht, ttl_us, write_id) — RowVersion's positional field order.
PyObject* rec_to_tuple(const RecView& v) {
  PyObject* cols = cols_to_dict(v.cols, v.cols_len, v.ncols);
  if (cols == nullptr) return nullptr;
  PyObject* ttl = (v.h.ttl_us < 0) ? Py_NewRef(Py_None)
                                   : PyLong_FromLongLong(v.h.ttl_us);
  if (ttl == nullptr) { Py_DECREF(cols); return nullptr; }
  PyObject* tup = Py_BuildValue(
      "(y#LOONLNk)",
      (const char*)v.key, (Py_ssize_t)v.key_len,
      (long long)v.h.ht,
      (v.h.flags & 1) ? Py_True : Py_False,
      (v.h.flags & 2) ? Py_True : Py_False,
      cols,
      (long long)v.h.expire_ht,
      ttl,
      (unsigned long)v.h.write_id);
  // Py_BuildValue 'N' steals cols/ttl refs on success; on failure it
  // decrefs already-converted items itself.
  return tup;
}

// -- encode_ops: the client-side batch encoder -------------------------------

struct ColSpec {
  PyObject* name;   // borrowed from the desc tuple (held by caller)
  int dtype;
};

bool parse_colspecs(PyObject* seq, std::vector<ColSpec>* out) {
  PyObject* fast = PySequence_Fast(seq, "encode_ops: column spec list");
  if (fast == nullptr) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, i);
    PyObject* name;
    int dtype;
    if (!PyArg_ParseTuple(item, "Oi", &name, &dtype)) {
      Py_DECREF(fast);
      return false;
    }
    out->push_back({name, dtype});
  }
  Py_DECREF(fast);
  return true;
}

// encode_ops(desc, ops, starts) -> list of (nrows, bytes) | None per
// partition.
//   desc = (hash_cols, range_cols, value_cols, valmap)
//     hash_cols / range_cols: sequence of (name, dtype_code)
//     value_cols: sequence of (name, col_id) in schema order
//     valmap: dict name -> col_id (update-set lookups)
//   ops: sequence of (kind, key_src, cols_src, expire_ht, ttl_us)
//     kind 0 = insert (columns taken from key_src by value_cols order),
//     kind 1 = update (columns from cols_src via valmap),
//     kind 2 = delete (tombstone)
//   starts: sequence of partition start hash codes (sorted, first == 0)
PyObject* py_encode_ops(PyObject*, PyObject* args) {
  PyObject *desc, *ops, *starts_obj;
  if (!PyArg_ParseTuple(args, "OOO", &desc, &ops, &starts_obj)) return nullptr;

  PyObject *hash_cols_obj, *range_cols_obj, *value_cols_obj, *valmap;
  if (!PyArg_ParseTuple(desc, "OOOO", &hash_cols_obj, &range_cols_obj,
                        &value_cols_obj, &valmap)) {
    return nullptr;
  }
  std::vector<ColSpec> hash_cols, range_cols;
  if (!parse_colspecs(hash_cols_obj, &hash_cols) ||
      !parse_colspecs(range_cols_obj, &range_cols)) {
    return nullptr;
  }
  // value columns: (name, col_id)
  std::vector<std::pair<PyObject*, uint32_t>> value_cols;
  {
    PyObject* fast = PySequence_Fast(value_cols_obj,
                                     "encode_ops: value column list");
    if (fast == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* item = PySequence_Fast_GET_ITEM(fast, i);
      PyObject* name;
      unsigned long col_id;
      if (!PyArg_ParseTuple(item, "Ok", &name, &col_id)) {
        Py_DECREF(fast);
        return nullptr;
      }
      value_cols.push_back({name, (uint32_t)col_id});
    }
    Py_DECREF(fast);
  }
  std::vector<uint32_t> starts;
  {
    PyObject* fast = PySequence_Fast(starts_obj, "encode_ops: starts");
    if (fast == nullptr) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
      long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
      if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
      starts.push_back((uint32_t)v);
    }
    Py_DECREF(fast);
  }
  if (starts.empty() || starts[0] != 0) {
    // starts[0] == 0 guarantees the upper_bound partition lookup below
    // can never underflow (every hash has a covering partition).
    PyErr_SetString(PyExc_ValueError,
                    "encode_ops: partition starts must begin at 0");
    return nullptr;
  }

  size_t nparts = starts.size();
  std::vector<Buf> bufs(nparts);
  std::vector<uint32_t> counts(nparts, 0);

  PyObject* ops_fast = PySequence_Fast(ops, "encode_ops: ops");
  if (ops_fast == nullptr) return nullptr;
  Py_ssize_t nops = PySequence_Fast_GET_SIZE(ops_fast);
  Buf key;      // reused per row
  Buf hashbuf;  // reused per row (hash-column bytes for crc)
  for (Py_ssize_t i = 0; i < nops; i++) {
    PyObject* op = PySequence_Fast_GET_ITEM(ops_fast, i);
    int kind;
    PyObject *key_src, *cols_src, *ttl_obj;
    long long expire_ht;
    if (!PyArg_ParseTuple(op, "iOOLO", &kind, &key_src, &cols_src,
                          &expire_ht, &ttl_obj)) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    // -- doc key + partition hash
    key.len = 0;
    size_t part = 0;
    if (!hash_cols.empty()) {
      hashbuf.len = 0;
      for (const ColSpec& c : hash_cols) {
        PyObject* v = PyDict_GetItemWithError(key_src, c.name);
        if (v == nullptr) {
          if (!PyErr_Occurred()) PyErr_SetObject(PyExc_KeyError, c.name);
          Py_DECREF(ops_fast);
          return nullptr;
        }
        if (!encode_key_component(&hashbuf, v, c.dtype)) {
          Py_DECREF(ops_fast);
          return nullptr;
        }
      }
      uint32_t crc = crc32((const unsigned char*)hashbuf.data, hashbuf.len);
      uint16_t h = (uint16_t)(((crc >> 16) ^ (crc & 0xFFFF)) & 0xFFFF);
      // partition index: last start <= h
      part = std::upper_bound(starts.begin(), starts.end(), (uint32_t)h) -
             starts.begin() - 1;
      bool ok = ybtag::buf_putc(&key, K_HASH) &&
                ybtag::buf_putc(&key, (unsigned char)(h >> 8)) &&
                ybtag::buf_putc(&key, (unsigned char)(h & 0xFF)) &&
                ybtag::buf_put(&key, hashbuf.data, hashbuf.len) &&
                ybtag::buf_putc(&key, K_GROUP_END);
      if (!ok) { Py_DECREF(ops_fast); return nullptr; }
    }
    for (const ColSpec& c : range_cols) {
      PyObject* v = PyDict_GetItemWithError(key_src, c.name);
      if (v == nullptr) {
        if (!PyErr_Occurred()) PyErr_SetObject(PyExc_KeyError, c.name);
        Py_DECREF(ops_fast);
        return nullptr;
      }
      if (!encode_key_component(&key, v, c.dtype)) {
        Py_DECREF(ops_fast);
        return nullptr;
      }
    }
    if (!ybtag::buf_putc(&key, K_GROUP_END)) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    // -- record
    if (key.len > 0xFFFF) {
      PyErr_SetString(PyExc_ValueError, "encoded key exceeds 64KiB");
      Py_DECREF(ops_fast);
      return nullptr;
    }
    Buf* out = &bufs[part];
    if (counts[part] == 0 && !put_u32(out, 0)) {  // nrows placeholder
      Py_DECREF(ops_fast);
      return nullptr;
    }
    RecHeader h{};
    h.ht = 0;
    h.expire_ht = (uint64_t)expire_ht;
    h.ttl_us = (ttl_obj == Py_None) ? -1 : PyLong_AsLongLong(ttl_obj);
    if (h.ttl_us == -1 && ttl_obj != Py_None && PyErr_Occurred()) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    h.write_id = 0;
    h.flags = (kind == 2) ? 1 : (kind == 0 ? 2 : 0);
    if (!put_u16(out, (uint16_t)key.len) ||
        !ybtag::buf_put(out, key.data, key.len)) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    size_t fixed_at = out->len;
    if (!write_rec_fixed(out, h, 0)) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    uint16_t ncols = 0;
    bool ok = true;
    if (kind == 0) {
      for (const auto& vc : value_cols) {
        PyObject* v = PyDict_GetItemWithError(key_src, vc.first);
        if (v == nullptr) {
          if (PyErr_Occurred()) { ok = false; break; }
          continue;  // column not provided
        }
        ok = put_u32(out, vc.second) && ybtag::encode_obj(out, v, 0);
        if (!ok) break;
        ncols++;
      }
    } else if (kind == 1) {
      PyObject *name, *v;
      Py_ssize_t pos = 0;
      while (ok && PyDict_Next(cols_src, &pos, &name, &v)) {
        PyObject* cid = PyDict_GetItemWithError(valmap, name);
        if (cid == nullptr) {
          if (!PyErr_Occurred()) PyErr_SetObject(PyExc_KeyError, name);
          ok = false;
          break;
        }
        unsigned long col_id = PyLong_AsUnsignedLong(cid);
        if (col_id == (unsigned long)-1 && PyErr_Occurred()) {
          ok = false;
          break;
        }
        ok = put_u32(out, (uint32_t)col_id) && ybtag::encode_obj(out, v, 0);
        if (ok) ncols++;
      }
    }
    if (!ok) {
      Py_DECREF(ops_fast);
      return nullptr;
    }
    // patch ncols
    uint16_t nc = ncols;
    memcpy(out->data + fixed_at + 29, &nc, 2);
    counts[part]++;
  }
  Py_DECREF(ops_fast);

  PyObject* result = PyList_New((Py_ssize_t)nparts);
  if (result == nullptr) return nullptr;
  for (size_t p = 0; p < nparts; p++) {
    if (counts[p] == 0) {
      PyList_SET_ITEM(result, (Py_ssize_t)p, Py_NewRef(Py_None));
      continue;
    }
    memcpy(bufs[p].data, &counts[p], 4);  // patch nrows
    PyObject* block = PyBytes_FromStringAndSize(bufs[p].data,
                                               (Py_ssize_t)bufs[p].len);
    if (block == nullptr) { Py_DECREF(result); return nullptr; }
    PyObject* pair = Py_BuildValue("(kN)", (unsigned long)counts[p], block);
    if (pair == nullptr) { Py_DECREF(result); return nullptr; }
    PyList_SET_ITEM(result, (Py_ssize_t)p, pair);
  }
  return result;
}

// -- encode_rows: RowVersion list -> block (legacy-path bridge) --------------

PyObject* py_encode_rows(PyObject*, PyObject* arg) {
  PyObject* fast = PySequence_Fast(arg, "encode_rows: row list");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  Buf out;
  if (!put_u32(&out, (uint32_t)n)) { Py_DECREF(fast); return nullptr; }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* row = PySequence_Fast_GET_ITEM(fast, i);
    PyObject* key = PyObject_GetAttrString(row, "key");
    PyObject* ht = key ? PyObject_GetAttrString(row, "ht") : nullptr;
    PyObject* tomb = ht ? PyObject_GetAttrString(row, "tombstone") : nullptr;
    PyObject* live = tomb ? PyObject_GetAttrString(row, "liveness") : nullptr;
    PyObject* cols = live ? PyObject_GetAttrString(row, "columns") : nullptr;
    PyObject* exp = cols ? PyObject_GetAttrString(row, "expire_ht") : nullptr;
    PyObject* ttl = exp ? PyObject_GetAttrString(row, "ttl_us") : nullptr;
    PyObject* wid = ttl ? PyObject_GetAttrString(row, "write_id") : nullptr;
    PyObject* incs = wid ? PyObject_GetAttrString(row, "increments") : nullptr;
    bool ok = incs != nullptr;
    if (ok && PyObject_IsTrue(incs)) {
      PyErr_SetString(PyExc_ValueError,
                      "encode_rows: unresolved counter increments");
      ok = false;
    }
    char* kp = nullptr;
    Py_ssize_t klen = 0;
    ok = ok && PyBytes_AsStringAndSize(key, &kp, &klen) == 0;
    RecHeader h{};
    if (ok) {
      h.ht = (uint64_t)PyLong_AsLongLong(ht);
      h.expire_ht = (uint64_t)PyLong_AsLongLong(exp);
      h.ttl_us = (ttl == Py_None) ? -1 : PyLong_AsLongLong(ttl);
      h.write_id = (uint32_t)PyLong_AsLong(wid);
      int t = PyObject_IsTrue(tomb);
      int l = PyObject_IsTrue(live);
      if (t < 0 || l < 0 || PyErr_Occurred()) ok = false;
      h.flags = (uint8_t)((t ? 1 : 0) | (l ? 2 : 0));
    }
    if (ok && !PyDict_Check(cols)) {
      PyErr_SetString(PyExc_TypeError, "encode_rows: columns must be a dict");
      ok = false;
    }
    if (ok && klen > 0xFFFF) {
      PyErr_SetString(PyExc_ValueError, "encoded key exceeds 64KiB");
      ok = false;
    }
    if (ok) {
      Py_ssize_t ncols = PyDict_Size(cols);
      ok = ncols <= 0xFFFF &&
           put_u16(&out, (uint16_t)klen) &&
           ybtag::buf_put(&out, kp, (size_t)klen) &&
           write_rec_fixed(&out, h, (uint16_t)ncols);
      PyObject *ck, *cv;
      Py_ssize_t pos = 0;
      while (ok && PyDict_Next(cols, &pos, &ck, &cv)) {
        unsigned long col_id = PyLong_AsUnsignedLong(ck);
        if (col_id == (unsigned long)-1 && PyErr_Occurred()) {
          ok = false;
          break;
        }
        ok = put_u32(&out, (uint32_t)col_id) && ybtag::encode_obj(&out, cv, 0);
      }
    }
    Py_XDECREF(key); Py_XDECREF(ht); Py_XDECREF(tomb); Py_XDECREF(live);
    Py_XDECREF(cols); Py_XDECREF(exp); Py_XDECREF(ttl); Py_XDECREF(wid);
    Py_XDECREF(incs);
    if (!ok) {
      Py_DECREF(fast);
      if (!PyErr_Occurred()) {
        PyErr_SetString(PyExc_ValueError, "encode_rows: bad row");
      }
      return nullptr;
    }
  }
  Py_DECREF(fast);
  return PyBytes_FromStringAndSize(out.data, (Py_ssize_t)out.len);
}

// -- stamp_block -------------------------------------------------------------

// stamp_block(block, ht, logical_shift) -> bytes
// Leader-side commit stamping in one native pass: every row gets the
// batch hybrid time, its position as write_id, and TTLs resolved to
// absolute expiry (expire_ht = ht + (ttl_us << logical_shift)).
PyObject* py_stamp_block(PyObject*, PyObject* args) {
  Py_buffer view;
  long long ht;
  int shift;
  if (!PyArg_ParseTuple(args, "y*Li", &view, &ht, &shift)) return nullptr;
  PyObject* out = PyBytes_FromStringAndSize((const char*)view.buf, view.len);
  PyBuffer_Release(&view);
  if (out == nullptr) return nullptr;
  unsigned char* data = (unsigned char*)PyBytes_AS_STRING(out);
  size_t len = (size_t)PyBytes_GET_SIZE(out);
  Reader r{data, len};
  uint32_t nrows;
  if (!read_nrows(&r, &nrows)) { Py_DECREF(out); return nullptr; }
  for (uint32_t i = 0; i < nrows; i++) {
    RecView v;
    if (!parse_rec(&r, &v)) { Py_DECREF(out); return nullptr; }
    unsigned char* p = data + v.fixed_off;
    uint64_t hts = (uint64_t)ht;
    memcpy(p, &hts, 8);
    if (v.h.ttl_us >= 0) {
      uint64_t exp = (uint64_t)ht + ((uint64_t)v.h.ttl_us << shift);
      memcpy(p + 8, &exp, 8);
      int64_t none = -1;
      memcpy(p + 16, &none, 8);  // ttl resolved; stamped rows carry none
    }
    memcpy(p + 24, &i, 4);
  }
  if (r.pos != len) {
    PyErr_SetString(PyExc_ValueError, "stamp_block: trailing bytes");
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

// -- block accessors ---------------------------------------------------------

PyObject* py_block_count(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{(const unsigned char*)view.buf, (size_t)view.len};
  uint32_t nrows;
  bool ok = read_nrows(&r, &nrows);
  PyBuffer_Release(&view);
  if (!ok) return nullptr;
  return PyLong_FromUnsignedLong(nrows);
}

PyObject* py_block_keys(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{(const unsigned char*)view.buf, (size_t)view.len};
  uint32_t nrows;
  if (!read_nrows(&r, &nrows)) { PyBuffer_Release(&view); return nullptr; }
  PyObject* out = PyList_New((Py_ssize_t)nrows);
  if (out == nullptr) { PyBuffer_Release(&view); return nullptr; }
  for (uint32_t i = 0; i < nrows; i++) {
    RecView v;
    if (!parse_rec(&r, &v)) {
      Py_DECREF(out);
      PyBuffer_Release(&view);
      return nullptr;
    }
    PyObject* key = PyBytes_FromStringAndSize((const char*)v.key,
                                              (Py_ssize_t)v.key_len);
    if (key == nullptr) {
      Py_DECREF(out);
      PyBuffer_Release(&view);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, key);
  }
  PyBuffer_Release(&view);
  return out;
}

PyObject* py_block_rows(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{(const unsigned char*)view.buf, (size_t)view.len};
  uint32_t nrows;
  if (!read_nrows(&r, &nrows)) { PyBuffer_Release(&view); return nullptr; }
  PyObject* out = PyList_New((Py_ssize_t)nrows);
  if (out == nullptr) { PyBuffer_Release(&view); return nullptr; }
  for (uint32_t i = 0; i < nrows; i++) {
    RecView v;
    PyObject* tup = parse_rec(&r, &v) ? rec_to_tuple(v) : nullptr;
    if (tup == nullptr) {
      Py_DECREF(out);
      PyBuffer_Release(&view);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, tup);
  }
  PyBuffer_Release(&view);
  return out;
}

// block_ht_range(block) -> (min_ht, max_ht) or None for an empty block.
PyObject* py_block_ht_range(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{(const unsigned char*)view.buf, (size_t)view.len};
  uint32_t nrows;
  if (!read_nrows(&r, &nrows)) { PyBuffer_Release(&view); return nullptr; }
  uint64_t lo = ~0ULL, hi = 0;
  for (uint32_t i = 0; i < nrows; i++) {
    RecView v;
    if (!parse_rec(&r, &v)) { PyBuffer_Release(&view); return nullptr; }
    lo = std::min(lo, v.h.ht);
    hi = std::max(hi, v.h.ht);
  }
  PyBuffer_Release(&view);
  if (nrows == 0) Py_RETURN_NONE;
  return Py_BuildValue("(LL)", (long long)lo, (long long)hi);
}

// -- page server -------------------------------------------------------------
//
// The YCSB-E hot path — LIMIT-k pages from a run's host mirror,
// entirely in C: binary search over the run's key blob for the range
// bounds, binary search of the precomputed match index, then direct
// row-tuple emission from the plane buffers (decoding the ordered int32
// planes back to int64/float64 inline). serve_page handles one page
// (with an optional upper bound); serve_page_batch serves a whole
// same-structure page GROUP per call so buffer acquisition and colspec
// parsing amortize. Both share one emit core. The Python path
// (storage/host_page.py) is the spec and the fallback.

struct BufView {
  Py_buffer view{};
  bool held = false;
  ~BufView() { if (held) PyBuffer_Release(&view); }
  bool get(PyObject* obj, const char* what) {
    if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0) {
      PyErr_Format(PyExc_TypeError, "serve_page: %s must support the "
                   "buffer protocol", what);
      return false;
    }
    held = true;
    return true;
  }
  const int64_t* i64() const { return (const int64_t*)view.buf; }
  const int32_t* i32() const { return (const int32_t*)view.buf; }
  const unsigned char* u8() const {
    return (const unsigned char*)view.buf;
  }
  size_t n(size_t itemsize) const { return (size_t)view.len / itemsize; }
};

// memcmp-order compare of blob key i vs (p, n).
static int key_cmp(const char* blob, const int64_t* offs, size_t i,
                   const char* p, size_t n) {
  size_t a0 = (size_t)offs[i], a1 = (size_t)offs[i + 1];
  size_t alen = a1 - a0;
  int c = memcmp(blob + a0, p, alen < n ? alen : n);
  if (c != 0) return c;
  return alen < n ? -1 : (alen > n ? 1 : 0);
}

// First 4 bytes of key i as a big-endian u32 (0-padded) — the
// interpolation coordinate. DocKeys start with the hash tag + 16-bit
// hash code, so this is near-uniform over a tablet's key space.
static inline uint32_t key_prefix4(const char* blob, const int64_t* offs,
                                   size_t i) {
  size_t a0 = (size_t)offs[i], a1 = (size_t)offs[i + 1];
  uint32_t v = 0;
  for (size_t j = 0; j < 4; j++)
    v = (v << 8) | (a0 + j < a1 ? (unsigned char)blob[a0 + j] : 0);
  return v;
}

// first index i in [0, nv) with key[i] >= (p, n). Interpolation probes
// (cold binary search over a multi-MB blob is ~half the per-page fixed
// cost) alternating with binary halving so skewed key spaces keep the
// O(log n) bound.
static size_t key_lower_bound(const char* blob, const int64_t* offs,
                              size_t nv, const char* p, size_t n) {
  size_t lo = 0, hi = nv;
  uint32_t tp = 0;
  for (size_t j = 0; j < 4; j++)
    tp = (tp << 8) | (j < n ? (unsigned char)p[j] : 0);
  bool interp = true;
  while (lo < hi) {
    size_t mid;
    if (interp && hi - lo > 16) {
      uint32_t lp = key_prefix4(blob, offs, lo);
      uint32_t hp = key_prefix4(blob, offs, hi - 1);
      if (hp > lp && tp > lp && tp < hp) {
        mid = lo + (size_t)((uint64_t)(tp - lp) * (hi - 1 - lo) /
                            (hp - lp));
      } else {
        mid = (lo + hi) / 2;
      }
    } else {
      mid = (lo + hi) / 2;
    }
    interp = !interp;
    if (key_cmp(blob, offs, mid, p, n) < 0) lo = mid + 1;
    else hi = mid;
  }
  return lo;
}

static size_t i64_lower_bound(const int64_t* a, size_t n, int64_t v) {
  size_t lo = 0, hi = n;
  bool interp = true;  // values are near-uniform row indices
  while (lo < hi) {
    size_t mid;
    if (interp && hi - lo > 16 && a[hi - 1] > a[lo] && v > a[lo] &&
        v < a[hi - 1]) {
      mid = lo + (size_t)((uint64_t)(v - a[lo]) * (hi - 1 - lo) /
                          (uint64_t)(a[hi - 1] - a[lo]));
    } else {
      mid = (lo + hi) / 2;
    }
    interp = !interp;
    if (a[mid] < v) lo = mid + 1;
    else hi = mid;
  }
  return lo;
}

static inline uint64_t planes_u64(int32_t hi, int32_t lo) {
  uint32_t uh = (uint32_t)hi ^ 0x80000000u;
  uint32_t ul = (uint32_t)lo ^ 0x80000000u;
  return ((uint64_t)uh << 32) | ul;
}

// Parsed per-column emit specs (see host_page._native_colspecs):
//   ("obj", list)            list[g] (value as-is; key columns)
//   ("objnn", list, nn_u8)   nn[g] ? list[g] : None (str/f32 payloads)
//   ("i32"|"bool", cmp_i32, nn_u8)
//   ("i64"|"f64", cmp2_i32 (two interleaved planes), nn_u8)
struct ColEmit {
  enum Kind { C_OBJ, C_OBJNN, C_I32, C_BOOL, C_I64, C_F64 };
  std::vector<Kind> kinds;
  std::vector<PyObject*> objs;
  std::vector<BufView> cmps;
  std::vector<BufView> nns;

  bool parse(PyObject* colspecs) {
    if (!PyTuple_Check(colspecs)) {
      PyErr_SetString(PyExc_TypeError,
                      "serve_page: colspecs must be a tuple");
      return false;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(colspecs);
    kinds.resize(n);
    objs.assign(n, nullptr);
    cmps = std::vector<BufView>(n);
    nns = std::vector<BufView>(n);
    for (Py_ssize_t c = 0; c < n; c++) {
      PyObject* spec = PyTuple_GET_ITEM(colspecs, c);
      const char* tag = PyUnicode_AsUTF8(PyTuple_GET_ITEM(spec, 0));
      if (tag == nullptr) return false;
      if (strcmp(tag, "obj") == 0) {
        kinds[c] = C_OBJ;
        objs[c] = PyTuple_GET_ITEM(spec, 1);
      } else if (strcmp(tag, "objnn") == 0) {
        kinds[c] = C_OBJNN;
        objs[c] = PyTuple_GET_ITEM(spec, 1);
        if (!nns[c].get(PyTuple_GET_ITEM(spec, 2), "nn")) return false;
      } else {
        kinds[c] = strcmp(tag, "i32") == 0 ? C_I32
                   : strcmp(tag, "bool") == 0 ? C_BOOL
                   : strcmp(tag, "i64") == 0 ? C_I64 : C_F64;
        if (!cmps[c].get(PyTuple_GET_ITEM(spec, 1), "cmp")) return false;
        if (!nns[c].get(PyTuple_GET_ITEM(spec, 2), "nn")) return false;
      }
    }
    return true;
  }

  // One row tuple for global row g, or nullptr on error.
  PyObject* row(int64_t g) const {
    Py_ssize_t n = (Py_ssize_t)kinds.size();
    PyObject* tup = PyTuple_New(n);
    if (tup == nullptr) return nullptr;
    for (Py_ssize_t c = 0; c < n; c++) {
      PyObject* v = nullptr;
      switch (kinds[c]) {
        case C_OBJ:
          v = PyList_GET_ITEM(objs[c], (Py_ssize_t)g);
          Py_INCREF(v);
          break;
        case C_OBJNN:
          if (nns[c].u8()[g]) {
            v = PyList_GET_ITEM(objs[c], (Py_ssize_t)g);
            Py_INCREF(v);
          } else {
            v = Py_NewRef(Py_None);
          }
          break;
        case C_I32:
          v = nns[c].u8()[g] ? PyLong_FromLong(cmps[c].i32()[g])
                             : Py_NewRef(Py_None);
          break;
        case C_BOOL:
          v = nns[c].u8()[g]
                  ? PyBool_FromLong(cmps[c].i32()[g] != 0)
                  : Py_NewRef(Py_None);
          break;
        case C_I64: {
          if (!nns[c].u8()[g]) { v = Py_NewRef(Py_None); break; }
          uint64_t u = planes_u64(cmps[c].i32()[2 * g],
                                  cmps[c].i32()[2 * g + 1]);
          v = PyLong_FromLongLong((long long)(u ^ (1ULL << 63)));
          break;
        }
        case C_F64: {
          if (!nns[c].u8()[g]) { v = Py_NewRef(Py_None); break; }
          uint64_t flipped = planes_u64(cmps[c].i32()[2 * g],
                                        cmps[c].i32()[2 * g + 1]);
          uint64_t bits = (flipped >> 63) ? (flipped & ~(1ULL << 63))
                                          : ~flipped;
          double d;
          memcpy(&d, &bits, 8);
          v = PyFloat_FromDouble(d);
          break;
        }
      }
      if (v == nullptr) { Py_DECREF(tup); return nullptr; }
      PyTuple_SET_ITEM(tup, c, v);
    }
    return tup;
  }
};

// Serve one page -> (rows, scanned, resume|None) tuple, or nullptr.
static PyObject* emit_page(const char* blob, const BufView& offs,
                           const BufView& valid, const BufView& match,
                           const BufView& exists, const ColEmit& cols,
                           const char* lower, size_t lower_n,
                           const char* upper, size_t upper_n,
                           Py_ssize_t limit) {
  size_t nv = valid.n(8);
  size_t nm = match.n(8);
  size_t ne = exists.n(8);

  size_t lo_i = key_lower_bound(blob, offs.i64(), nv, lower, lower_n);
  int64_t row_lo = lo_i < nv ? valid.i64()[lo_i] : INT64_MAX;
  int64_t row_hi = INT64_MAX;
  if (upper_n > 0) {
    size_t hi_i = key_lower_bound(blob, offs.i64(), nv, upper, upper_n);
    row_hi = hi_i < nv ? valid.i64()[hi_i] : INT64_MAX;
  }
  size_t i0 = i64_lower_bound(match.i64(), nm, row_lo);
  size_t i1 = row_hi == INT64_MAX
                  ? nm
                  : i64_lower_bound(match.i64(), nm, row_hi);
  if (i1 < i0) i1 = i0;
  size_t take = i1 - i0;
  if (limit >= 0 && (size_t)limit < take) take = (size_t)limit;
  bool hit_limit = limit >= 0 && take >= (size_t)limit && take > 0;

  PyObject* rows = PyList_New((Py_ssize_t)take);
  if (rows == nullptr) return nullptr;
  for (size_t j = 0; j < take; j++) {
    PyObject* tup = cols.row(match.i64()[i0 + j]);
    if (tup == nullptr) { Py_DECREF(rows); return nullptr; }
    PyList_SET_ITEM(rows, (Py_ssize_t)j, tup);
  }

  // scanned: existing rows examined through the last consumed row.
  int64_t hi_row = take > 0 ? match.i64()[i0 + take - 1] + 1 : row_hi;
  size_t e1 = hi_row == INT64_MAX
                  ? ne
                  : i64_lower_bound(exists.i64(), ne, hi_row);
  size_t e0 = i64_lower_bound(exists.i64(), ne, row_lo);

  PyObject* resume;
  if (hit_limit) {
    int64_t g_last = match.i64()[i0 + take - 1];
    size_t pos = i64_lower_bound(valid.i64(), nv, g_last);
    size_t k0 = (size_t)offs.i64()[pos], k1 = (size_t)offs.i64()[pos + 1];
    resume = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)(k1 - k0 + 1));
    if (resume == nullptr) { Py_DECREF(rows); return nullptr; }
    char* rp = PyBytes_AS_STRING(resume);
    memcpy(rp, blob + k0, k1 - k0);
    rp[k1 - k0] = '\0';
  } else {
    resume = Py_NewRef(Py_None);
  }
  PyObject* out = PyTuple_New(3);
  if (out == nullptr) {
    Py_DECREF(rows);
    Py_DECREF(resume);
    return nullptr;
  }
  PyTuple_SET_ITEM(out, 0, rows);
  PyObject* sc = PyLong_FromLongLong((long long)(e1 - e0));
  if (sc == nullptr) { Py_DECREF(out); Py_DECREF(resume); return nullptr; }
  PyTuple_SET_ITEM(out, 1, sc);
  PyTuple_SET_ITEM(out, 2, resume);
  return out;
}

// serve_page(blob, offsets, valid_rows, match_idx, exists_idx, colspecs,
//            lower, upper, limit) -> (rows, scanned, resume|None)
//   upper b"" = unbounded; limit -1 = none.
PyObject* py_serve_page(PyObject*, PyObject* args) {
  const char *blob, *lower, *upper;
  Py_ssize_t blob_n, lower_n, upper_n, limit;
  PyObject *offs_o, *valid_o, *match_o, *exists_o, *colspecs;
  if (!PyArg_ParseTuple(args, "y#OOOOOy#y#n", &blob, &blob_n, &offs_o,
                        &valid_o, &match_o, &exists_o, &colspecs,
                        &lower, &lower_n, &upper, &upper_n, &limit)) {
    return nullptr;
  }
  BufView offs, valid, match, exists;
  if (!offs.get(offs_o, "offsets") || !valid.get(valid_o, "valid_rows") ||
      !match.get(match_o, "match_idx") ||
      !exists.get(exists_o, "exists_idx")) {
    return nullptr;
  }
  ColEmit cols;
  if (!cols.parse(colspecs)) return nullptr;
  return emit_page(blob, offs, valid, match, exists, cols, lower,
                   (size_t)lower_n, upper, (size_t)upper_n, limit);
}

// serve_page_batch(blob, offsets, valid_rows, match_idx, exists_idx,
//                  colspecs, lowers: list[bytes], limit) ->
//   [(rows, scanned, resume|None)]
PyObject* py_serve_page_batch(PyObject*, PyObject* args) {
  const char* blob;
  Py_ssize_t blob_n, limit;
  PyObject *offs_o, *valid_o, *match_o, *exists_o, *colspecs, *lowers;
  if (!PyArg_ParseTuple(args, "y#OOOOOOn", &blob, &blob_n, &offs_o,
                        &valid_o, &match_o, &exists_o, &colspecs,
                        &lowers, &limit)) {
    return nullptr;
  }
  if (!PyList_Check(lowers)) {
    PyErr_SetString(PyExc_TypeError,
                    "serve_page_batch: lowers must be a list");
    return nullptr;
  }
  BufView offs, valid, match, exists;
  if (!offs.get(offs_o, "offsets") || !valid.get(valid_o, "valid_rows") ||
      !match.get(match_o, "match_idx") ||
      !exists.get(exists_o, "exists_idx")) {
    return nullptr;
  }
  ColEmit cols;
  if (!cols.parse(colspecs)) return nullptr;

  Py_ssize_t npages = PyList_GET_SIZE(lowers);
  PyObject* results = PyList_New(npages);
  if (results == nullptr) return nullptr;
  for (Py_ssize_t pi = 0; pi < npages; pi++) {
    char* lower;
    Py_ssize_t lower_n;
    if (PyBytes_AsStringAndSize(PyList_GET_ITEM(lowers, pi), &lower,
                                &lower_n) < 0) {
      Py_DECREF(results);
      return nullptr;
    }
    PyObject* entry = emit_page(blob, offs, valid, match, exists, cols,
                                lower, (size_t)lower_n, "", 0, limit);
    if (entry == nullptr) { Py_DECREF(results); return nullptr; }
    PyList_SET_ITEM(results, pi, entry);
  }
  return results;
}

// -- wire page server --------------------------------------------------------
//
// Result pages serialized straight to protocol bytes from the plane
// buffers — the hot path never constructs a Python value object per
// cell. The reference serializes each row block once into rows_data
// (src/yb/common/ql_rowblock.h:66 Serialize) and the CQL/PG layers
// forward the bytes; this is the same contract restaged over the
// columnar host mirror.
//
// Wire colspecs (host_page._native_wirespecs):
//   ("wblob", offsets_i64, blob_bytes[, nn_u8])  pre-encoded payloads;
//       cell = [len][blob slice]; with nn, nn[g]==0 emits NULL
//   ("wi64", cmp2_i32, nn_u8)   ordered planes -> int64
//   ("wi32", cmp_i32, nn_u8[, width])  int32; fmt 0 emits the low
//       `width` bytes BE (4 default; 2 smallint, 1 tinyint)
//   ("wf64", cmp2_i32, nn_u8)   ordered planes -> double bits
//   ("wbool", cmp_i32, nn_u8)   bool
// fmt 0 (CQL): cell = int32 BE length + binary payload (i64 -> 8B BE,
//   i32 -> 4B BE, f64 -> IEEE bits BE, bool -> 1 byte), NULL = len -1 —
//   byte-identical to yql.cql.wire_protocol.encode_value.
// fmt 1 (PG text): each row is a complete DataRow message ('D' +
//   int32 msglen + int16 ncols + cells); ints render as ascii, bool as
//   t/f — byte-identical to yql.pgsql.wire.data_row (floats/strings
//   ride pre-encoded wblob payloads so repr parity is exact).

struct WireEmit {
  enum Kind { W_BLOB, W_BLOBNN, W_I64, W_I32, W_F64, W_BOOL };
  std::vector<Kind> kinds;
  std::vector<int> widths;     // W_I32: cell byte width (fmt 0)
  std::vector<BufView> offs;   // W_BLOB*: payload offsets
  std::vector<BufView> blobs;  // W_BLOB*: payload bytes
  std::vector<BufView> cmps;
  std::vector<BufView> nns;

  bool parse(PyObject* wirespecs) {
    if (!PyTuple_Check(wirespecs)) {
      PyErr_SetString(PyExc_TypeError,
                      "serve_page_wire: wirespecs must be a tuple");
      return false;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(wirespecs);
    kinds.resize(n);
    widths.assign(n, 4);
    offs = std::vector<BufView>(n);
    blobs = std::vector<BufView>(n);
    cmps = std::vector<BufView>(n);
    nns = std::vector<BufView>(n);
    for (Py_ssize_t c = 0; c < n; c++) {
      PyObject* spec = PyTuple_GET_ITEM(wirespecs, c);
      const char* tag = PyUnicode_AsUTF8(PyTuple_GET_ITEM(spec, 0));
      if (tag == nullptr) return false;
      if (strcmp(tag, "wblob") == 0) {
        bool has_nn = PyTuple_GET_SIZE(spec) > 3 &&
                      PyTuple_GET_ITEM(spec, 3) != Py_None;
        kinds[c] = has_nn ? W_BLOBNN : W_BLOB;
        if (!offs[c].get(PyTuple_GET_ITEM(spec, 1), "offsets") ||
            !blobs[c].get(PyTuple_GET_ITEM(spec, 2), "blob")) {
          return false;
        }
        if (has_nn && !nns[c].get(PyTuple_GET_ITEM(spec, 3), "nn")) {
          return false;
        }
      } else {
        kinds[c] = strcmp(tag, "wi64") == 0 ? W_I64
                   : strcmp(tag, "wi32") == 0 ? W_I32
                   : strcmp(tag, "wf64") == 0 ? W_F64 : W_BOOL;
        if (!cmps[c].get(PyTuple_GET_ITEM(spec, 1), "cmp")) return false;
        if (!nns[c].get(PyTuple_GET_ITEM(spec, 2), "nn")) return false;
        if (kinds[c] == W_I32 && PyTuple_GET_SIZE(spec) > 3) {
          widths[c] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 3));
          if (widths[c] != 1 && widths[c] != 2 && widths[c] != 4) {
            PyErr_SetString(PyExc_ValueError,
                            "serve_page_wire: wi32 width must be 1/2/4");
            return false;
          }
        }
      }
    }
    return true;
  }

  static void put_i32be(std::string* out, int32_t v) {
    unsigned char b[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                          (unsigned char)(v >> 8), (unsigned char)v};
    out->append((const char*)b, 4);
  }
  static inline void stamp_i32be(char* p, int32_t v) {
    p[0] = (char)(v >> 24);
    p[1] = (char)(v >> 16);
    p[2] = (char)(v >> 8);
    p[3] = (char)v;
  }
  static inline void stamp_u64be(char* p, uint64_t v) {
    for (int i = 0; i < 8; i++) p[i] = (char)(v >> (56 - 8 * i));
  }

  // Append one cell (fmt 0 binary / fmt 1 text); PG msglen patching is
  // the caller's job. Each cell lands in ONE append (two for blob
  // payloads) — per-byte push_back was the measured per-row hot spot.
  void cell(std::string* out, Py_ssize_t c, int64_t g, int fmt) const {
    char tmp[28];
    switch (kinds[c]) {
      case W_BLOB:
      case W_BLOBNN: {
        if (kinds[c] == W_BLOBNN && !nns[c].u8()[g]) {
          put_i32be(out, -1);
          return;
        }
        int64_t o0 = offs[c].i64()[g], o1 = offs[c].i64()[g + 1];
        if (o0 < 0) { put_i32be(out, -1); return; }  // NULL sentinel
        put_i32be(out, (int32_t)(o1 - o0));
        out->append((const char*)blobs[c].u8() + o0, (size_t)(o1 - o0));
        return;
      }
      case W_I64: {
        if (!nns[c].u8()[g]) { put_i32be(out, -1); return; }
        uint64_t u = planes_u64(cmps[c].i32()[2 * g],
                                cmps[c].i32()[2 * g + 1]);
        int64_t v = (int64_t)(u ^ (1ULL << 63));
        if (fmt == 0) {
          stamp_i32be(tmp, 8);
          stamp_u64be(tmp + 4, (uint64_t)v);
          out->append(tmp, 12);
        } else {
          int n = snprintf(tmp + 4, sizeof(tmp) - 4, "%lld", (long long)v);
          stamp_i32be(tmp, n);
          out->append(tmp, (size_t)n + 4);
        }
        return;
      }
      case W_I32: {
        if (!nns[c].u8()[g]) { put_i32be(out, -1); return; }
        int32_t v = cmps[c].i32()[g];
        if (fmt == 0) {
          int w = widths[c];
          stamp_i32be(tmp, w);
          for (int i = 0; i < w; i++)
            tmp[4 + i] = (char)((uint32_t)v >> (8 * (w - 1 - i)));
          out->append(tmp, (size_t)w + 4);
        } else {
          int n = snprintf(tmp + 4, sizeof(tmp) - 4, "%d", v);
          stamp_i32be(tmp, n);
          out->append(tmp, (size_t)n + 4);
        }
        return;
      }
      case W_F64: {
        if (!nns[c].u8()[g]) { put_i32be(out, -1); return; }
        uint64_t flipped = planes_u64(cmps[c].i32()[2 * g],
                                      cmps[c].i32()[2 * g + 1]);
        uint64_t bits = (flipped >> 63) ? (flipped & ~(1ULL << 63))
                                        : ~flipped;
        stamp_i32be(tmp, 8);
        stamp_u64be(tmp + 4, bits);  // fmt 1 floats ride wblob
        out->append(tmp, 12);
        return;
      }
      case W_BOOL: {
        if (!nns[c].u8()[g]) { put_i32be(out, -1); return; }
        bool v = cmps[c].i32()[g] != 0;
        stamp_i32be(tmp, 1);
        tmp[4] = fmt == 0 ? (v ? '\x01' : '\x00') : (v ? 't' : 'f');
        out->append(tmp, 5);
        return;
      }
    }
  }

  // Hint the lines a future row will touch (the emit loop runs ~8 rows
  // ahead): page rows are near-consecutive but cold on first touch.
  void prefetch(int64_t g) const {
    for (size_t c = 0; c < kinds.size(); c++) {
      switch (kinds[c]) {
        case W_BLOB:
        case W_BLOBNN:
          __builtin_prefetch(&offs[c].i64()[g]);
          if (kinds[c] == W_BLOBNN) __builtin_prefetch(&nns[c].u8()[g]);
          break;
        case W_I64:
        case W_F64:
          __builtin_prefetch(&cmps[c].i32()[2 * g]);
          __builtin_prefetch(&nns[c].u8()[g]);
          break;
        default:
          __builtin_prefetch(&cmps[c].i32()[g]);
          __builtin_prefetch(&nns[c].u8()[g]);
      }
    }
  }
};

// One wire page -> (data, nrows, scanned, resume|None), or nullptr.
static PyObject* emit_wire_page(const char* blob, const BufView& offs,
                                const BufView& valid, const BufView& match,
                                const BufView& exists, const WireEmit& cols,
                                const char* lower, size_t lower_n,
                                const char* upper, size_t upper_n,
                                Py_ssize_t limit, int fmt,
                                std::string* scratch) {
  size_t nv = valid.n(8);
  size_t nm = match.n(8);
  size_t ne = exists.n(8);

  size_t lo_i = key_lower_bound(blob, offs.i64(), nv, lower, lower_n);
  int64_t row_lo = lo_i < nv ? valid.i64()[lo_i] : INT64_MAX;
  int64_t row_hi = INT64_MAX;
  if (upper_n > 0) {
    size_t hi_i = key_lower_bound(blob, offs.i64(), nv, upper, upper_n);
    row_hi = hi_i < nv ? valid.i64()[hi_i] : INT64_MAX;
  }
  size_t i0 = i64_lower_bound(match.i64(), nm, row_lo);
  size_t i1 = row_hi == INT64_MAX
                  ? nm
                  : i64_lower_bound(match.i64(), nm, row_hi);
  if (i1 < i0) i1 = i0;
  size_t take = i1 - i0;
  if (limit >= 0 && (size_t)limit < take) take = (size_t)limit;
  bool hit_limit = limit >= 0 && take >= (size_t)limit && take > 0;

  std::string& out = *scratch;
  out.clear();
  size_t ncols = cols.kinds.size();
  if (out.capacity() < take * (ncols * 16 + 16))
    out.reserve(take * (ncols * 16 + 16));
  for (size_t j = 0; j < take; j++) {
    int64_t g = match.i64()[i0 + j];
    if (j + 8 < take) cols.prefetch(match.i64()[i0 + j + 8]);
    if (fmt == 1) {
      out.push_back('D');
      size_t len_at = out.size();
      WireEmit::put_i32be(&out, 0);  // patched below
      out.push_back((char)(ncols >> 8));
      out.push_back((char)(ncols & 0xff));
      for (size_t c = 0; c < ncols; c++) cols.cell(&out, (Py_ssize_t)c, g, 1);
      int32_t msglen = (int32_t)(out.size() - len_at);
      out[len_at] = (char)(msglen >> 24);
      out[len_at + 1] = (char)(msglen >> 16);
      out[len_at + 2] = (char)(msglen >> 8);
      out[len_at + 3] = (char)msglen;
    } else {
      for (size_t c = 0; c < ncols; c++) cols.cell(&out, (Py_ssize_t)c, g, 0);
    }
  }

  int64_t hi_row = take > 0 ? match.i64()[i0 + take - 1] + 1 : row_hi;
  size_t e1 = hi_row == INT64_MAX
                  ? ne
                  : i64_lower_bound(exists.i64(), ne, hi_row);
  size_t e0 = i64_lower_bound(exists.i64(), ne, row_lo);

  PyObject* data = PyBytes_FromStringAndSize(out.data(),
                                             (Py_ssize_t)out.size());
  if (data == nullptr) return nullptr;
  PyObject* resume;
  if (hit_limit) {
    int64_t g_last = match.i64()[i0 + take - 1];
    size_t pos = i64_lower_bound(valid.i64(), nv, g_last);
    size_t k0 = (size_t)offs.i64()[pos], k1 = (size_t)offs.i64()[pos + 1];
    resume = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)(k1 - k0 + 1));
    if (resume == nullptr) { Py_DECREF(data); return nullptr; }
    char* rp = PyBytes_AS_STRING(resume);
    memcpy(rp, blob + k0, k1 - k0);
    rp[k1 - k0] = '\0';
  } else {
    resume = Py_NewRef(Py_None);
  }
  return Py_BuildValue("(NnnN)", data, (Py_ssize_t)take,
                       (Py_ssize_t)(e1 - e0), resume);
}

// serve_page_wire_batch(blob, offsets, valid_rows, match_idx, exists_idx,
//                       wirespecs, lowers: list[bytes], uppers: list[bytes]
//                       | None, limit, fmt) ->
//   [(data, nrows, scanned, resume|None)]
PyObject* py_serve_page_wire_batch(PyObject*, PyObject* args) {
  const char* blob;
  Py_ssize_t blob_n, limit, fmt;
  PyObject *offs_o, *valid_o, *match_o, *exists_o, *wirespecs, *lowers,
      *uppers;
  if (!PyArg_ParseTuple(args, "y#OOOOOOOnn", &blob, &blob_n, &offs_o,
                        &valid_o, &match_o, &exists_o, &wirespecs,
                        &lowers, &uppers, &limit, &fmt)) {
    return nullptr;
  }
  if (!PyList_Check(lowers)) {
    PyErr_SetString(PyExc_TypeError,
                    "serve_page_wire_batch: lowers must be a list");
    return nullptr;
  }
  bool has_uppers = uppers != Py_None;
  if (has_uppers && (!PyList_Check(uppers) ||
                     PyList_GET_SIZE(uppers) != PyList_GET_SIZE(lowers))) {
    PyErr_SetString(PyExc_TypeError,
                    "serve_page_wire_batch: uppers must match lowers");
    return nullptr;
  }
  BufView offs, valid, match, exists;
  if (!offs.get(offs_o, "offsets") || !valid.get(valid_o, "valid_rows") ||
      !match.get(match_o, "match_idx") ||
      !exists.get(exists_o, "exists_idx")) {
    return nullptr;
  }
  WireEmit cols;
  if (!cols.parse(wirespecs)) return nullptr;

  Py_ssize_t npages = PyList_GET_SIZE(lowers);
  PyObject* results = PyList_New(npages);
  if (results == nullptr) return nullptr;
  std::string scratch;
  for (Py_ssize_t pi = 0; pi < npages; pi++) {
    char* lower;
    Py_ssize_t lower_n;
    if (PyBytes_AsStringAndSize(PyList_GET_ITEM(lowers, pi), &lower,
                                &lower_n) < 0) {
      Py_DECREF(results);
      return nullptr;
    }
    char* upper = nullptr;
    Py_ssize_t upper_n = 0;
    if (has_uppers &&
        PyBytes_AsStringAndSize(PyList_GET_ITEM(uppers, pi), &upper,
                                &upper_n) < 0) {
      Py_DECREF(results);
      return nullptr;
    }
    PyObject* entry = emit_wire_page(
        blob, offs, valid, match, exists, cols, lower, (size_t)lower_n,
        upper ? upper : "", (size_t)upper_n, limit, (int)fmt, &scratch);
    if (entry == nullptr) { Py_DECREF(results); return nullptr; }
    PyList_SET_ITEM(results, pi, entry);
  }
  return results;
}

// -- Memtable ----------------------------------------------------------------

struct Ver {
  uint64_t ht;
  uint64_t expire_ht;
  int64_t ttl_us;
  uint32_t write_id;
  uint8_t flags;
  uint16_t ncols;
  std::string cols;
};

// Hash-map store + lazily-sorted key index: writes are O(1) (the hot
// path), the sort is amortized across scans/flushes — the same shape as
// the rocksdb memtable's skiplist trade-off, tuned for write-heavy
// batches. Key-string pointers are stable across inserts (node-based
// unordered_map), so the index holds pointers.
struct MtData {
  std::unordered_map<std::string, std::vector<Ver>> map;
  std::vector<const std::string*> index;  // sorted when index_valid
  bool index_valid = false;

  void ensure_index() {
    if (index_valid) return;
    index.clear();
    index.reserve(map.size());
    for (const auto& kv : map) index.push_back(&kv.first);
    std::sort(index.begin(), index.end(),
              [](const std::string* a, const std::string* b) {
                return *a < *b;
              });
    index_valid = true;
  }
};

struct MemtableObject {
  PyObject_HEAD
  MtData* data;
  size_t num_versions;
  size_t approx_bytes;
  uint64_t min_ht, max_ht;
  bool has_ht;
};

PyObject* mt_new(PyTypeObject* type, PyObject*, PyObject*) {
  MemtableObject* self = (MemtableObject*)type->tp_alloc(type, 0);
  if (self == nullptr) return nullptr;
  self->data = new (std::nothrow) MtData();
  if (self->data == nullptr) {
    Py_DECREF(self);
    return PyErr_NoMemory();
  }
  self->num_versions = 0;
  self->approx_bytes = 0;
  self->min_ht = 0;
  self->max_ht = 0;
  self->has_ht = false;
  return (PyObject*)self;
}

void mt_dealloc(MemtableObject* self) {
  delete self->data;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyObject* mt_apply_block(MemtableObject* self, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  Reader r{(const unsigned char*)view.buf, (size_t)view.len};
  uint32_t nrows;
  if (!read_nrows(&r, &nrows)) { PyBuffer_Release(&view); return nullptr; }
  for (uint32_t i = 0; i < nrows; i++) {
    RecView v;
    if (!parse_rec(&r, &v)) { PyBuffer_Release(&view); return nullptr; }
    std::string key((const char*)v.key, v.key_len);
    Ver ver{v.h.ht, v.h.expire_ht, v.h.ttl_us, v.h.write_id, v.h.flags,
            v.ncols, std::string((const char*)v.cols, v.cols_len)};
    auto emplaced = self->data->map.try_emplace(std::move(key));
    if (emplaced.second) self->data->index_valid = false;
    emplaced.first->second.push_back(std::move(ver));
    self->num_versions++;
    self->approx_bytes += v.key_len + 64 + 16 * (size_t)v.ncols;
    if (!self->has_ht) {
      self->min_ht = self->max_ht = v.h.ht;
      self->has_ht = true;
    } else {
      self->min_ht = std::min(self->min_ht, v.h.ht);
      self->max_ht = std::max(self->max_ht, v.h.ht);
    }
  }
  PyBuffer_Release(&view);
  if (r.pos != r.len) {
    PyErr_SetString(PyExc_ValueError, "apply_block: trailing bytes");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject* ver_to_tuple(const std::string& key, const Ver& v) {
  RecView rv;
  rv.key = (const unsigned char*)key.data();
  rv.key_len = key.size();
  rv.h = RecHeader{v.ht, v.expire_ht, v.ttl_us, v.write_id, v.flags};
  rv.ncols = v.ncols;
  rv.cols = (const unsigned char*)v.cols.data();
  rv.cols_len = v.cols.size();
  return rec_to_tuple(rv);
}

PyObject* mt_versions(MemtableObject* self, PyObject* arg) {
  char* kp;
  Py_ssize_t klen;
  if (PyBytes_AsStringAndSize(arg, &kp, &klen) < 0) return nullptr;
  std::string key(kp, (size_t)klen);
  auto it = self->data->map.find(key);
  if (it == self->data->map.end()) return PyList_New(0);
  PyObject* out = PyList_New((Py_ssize_t)it->second.size());
  if (out == nullptr) return nullptr;
  for (size_t i = 0; i < it->second.size(); i++) {
    PyObject* tup = ver_to_tuple(it->first, it->second[i]);
    if (tup == nullptr) { Py_DECREF(out); return nullptr; }
    PyList_SET_ITEM(out, (Py_ssize_t)i, tup);
  }
  return out;
}

PyObject* mt_scan_keys(MemtableObject* self, PyObject* args) {
  Py_buffer lo, hi;
  if (!PyArg_ParseTuple(args, "y*y*", &lo, &hi)) return nullptr;
  std::string lower((const char*)lo.buf, (size_t)lo.len);
  std::string upper((const char*)hi.buf, (size_t)hi.len);
  PyBuffer_Release(&lo);
  PyBuffer_Release(&hi);
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  self->data->ensure_index();
  auto& idx = self->data->index;
  auto it = std::lower_bound(idx.begin(), idx.end(), lower,
                             [](const std::string* a, const std::string& b) {
                               return *a < b;
                             });
  for (; it != idx.end(); ++it) {
    if (!upper.empty() && **it >= upper) break;
    PyObject* key = PyBytes_FromStringAndSize((*it)->data(),
                                              (Py_ssize_t)(*it)->size());
    if (key == nullptr || PyList_Append(out, key) < 0) {
      Py_XDECREF(key);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(key);
  }
  return out;
}

// has_keys(lower, upper) -> bool: emptiness probe without materializing.
PyObject* mt_has_keys(MemtableObject* self, PyObject* args) {
  Py_buffer lo, hi;
  if (!PyArg_ParseTuple(args, "y*y*", &lo, &hi)) return nullptr;
  std::string lower((const char*)lo.buf, (size_t)lo.len);
  std::string upper((const char*)hi.buf, (size_t)hi.len);
  PyBuffer_Release(&lo);
  PyBuffer_Release(&hi);
  self->data->ensure_index();
  auto& idx = self->data->index;
  auto it = std::lower_bound(idx.begin(), idx.end(), lower,
                             [](const std::string* a, const std::string& b) {
                               return *a < b;
                             });
  bool hit = it != idx.end() && (upper.empty() || **it < upper);
  return PyBool_FromLong(hit);
}

// drain_sorted() -> [(key, [row tuples ht-desc])] in key order.
PyObject* mt_drain_sorted(MemtableObject* self, PyObject*) {
  PyObject* out = PyList_New((Py_ssize_t)self->data->map.size());
  if (out == nullptr) return nullptr;
  self->data->ensure_index();
  Py_ssize_t idx = 0;
  for (const std::string* kp : self->data->index) {
    const std::string& key = *kp;
    std::vector<Ver>& vers = self->data->map[key];
    if (vers.size() > 1) {
      std::stable_sort(vers.begin(), vers.end(),
                       [](const Ver& a, const Ver& b) {
                         if (a.ht != b.ht) return a.ht > b.ht;
                         return a.write_id > b.write_id;
                       });
    }
    PyObject* vlist = PyList_New((Py_ssize_t)vers.size());
    if (vlist == nullptr) { Py_DECREF(out); return nullptr; }
    for (size_t i = 0; i < vers.size(); i++) {
      PyObject* tup = ver_to_tuple(key, vers[i]);
      if (tup == nullptr) {
        Py_DECREF(vlist);
        Py_DECREF(out);
        return nullptr;
      }
      PyList_SET_ITEM(vlist, (Py_ssize_t)i, tup);
    }
    PyObject* kb = PyBytes_FromStringAndSize(key.data(),
                                             (Py_ssize_t)key.size());
    if (kb == nullptr) { Py_DECREF(vlist); Py_DECREF(out); return nullptr; }
    PyObject* pair = PyTuple_New(2);
    if (pair == nullptr) {
      Py_DECREF(kb);
      Py_DECREF(vlist);
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(pair, 0, kb);
    PyTuple_SET_ITEM(pair, 1, vlist);
    PyList_SET_ITEM(out, idx++, pair);
  }
  return out;
}

// drain_run(R, key_words, coldesc) — the native flush: walk the sorted
// memtable ONCE and emit everything ColumnarRun needs as flat packed
// buffers (block-packing included) so Python's only remaining work is
// vectorized plane math + scatters. No per-row Python on the flush hot
// path (reference analog: rocksdb flush building the SSTable straight
// from the memtable iterator, src/yb/rocksdb/db/flush_job.cc).
//
// coldesc: [(col_id, kind)]; kind 0 = int-like (emit int64),
// 1 = double, 2 = float32-source (emit double), 3 = varlen (emit 8-byte
// BE prefix + the value objects; container values land in "pyfix" for
// host-side prefix computation). Unsupported value shapes raise
// ValueError — the caller falls back to the Python build.
//
// Returns a dict of bytes buffers (frombuffer-ready), object lists, and
// per-column sub-dicts; see storage/columnar.py build_from_memtable.
PyObject* mt_drain_run(MemtableObject* self, PyObject* args) {
  Py_ssize_t R, key_words;
  PyObject* coldesc;
  if (!PyArg_ParseTuple(args, "nnO", &R, &key_words, &coldesc)) {
    return nullptr;
  }
  if (!PyList_Check(coldesc)) {
    PyErr_SetString(PyExc_TypeError, "drain_run: coldesc must be a list");
    return nullptr;
  }
  struct ColBuf {
    uint32_t col_id;
    int kind;
    std::vector<int32_t> rows, null_rows;
    std::vector<int64_t> ivals;
    std::vector<double> dvals;
    std::vector<uint64_t> prefix;
    PyObject* pyvals = nullptr;   // varlen payload objects
    PyObject* pyfix = nullptr;    // varlen rows needing host prefixes
    size_t maxlen = 0;
  };
  std::vector<ColBuf> cols(PyList_GET_SIZE(coldesc));
  std::unordered_map<uint32_t, size_t> colpos;
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(coldesc); i++) {
    PyObject* item = PyList_GET_ITEM(coldesc, i);
    cols[i].col_id = (uint32_t)PyLong_AsUnsignedLong(
        PyTuple_GET_ITEM(item, 0));
    cols[i].kind = (int)PyLong_AsLong(PyTuple_GET_ITEM(item, 1));
    if (cols[i].kind == 3) {
      cols[i].pyvals = PyList_New(0);
      cols[i].pyfix = PyList_New(0);
      if (cols[i].pyvals == nullptr || cols[i].pyfix == nullptr) {
        for (auto& c : cols) { Py_XDECREF(c.pyvals); Py_XDECREF(c.pyfix); }
        return nullptr;
      }
    }
    colpos[cols[i].col_id] = (size_t)i;
  }
  static PyObject* rv_cls = nullptr;
  if (rv_cls == nullptr) {
    PyObject* mod =
        PyImport_ImportModule("yugabyte_db_tpu.storage.row_version");
    if (mod != nullptr) {
      rv_cls = PyObject_GetAttrString(mod, "RowVersion");
      Py_DECREF(mod);
    }
    if (rv_cls == nullptr) {
      for (auto& c : cols) { Py_XDECREF(c.pyvals); Py_XDECREF(c.pyfix); }
      return nullptr;
    }
  }

  MtData* d = self->data;
  d->ensure_index();
  size_t ngroups = d->index.size();
  size_t n = self->num_versions;

  auto fail = [&](PyObject* a, PyObject* b, PyObject* c) -> PyObject* {
    Py_XDECREF(a);
    Py_XDECREF(b);
    Py_XDECREF(c);
    for (auto& cb : cols) { Py_XDECREF(cb.pyvals); Py_XDECREF(cb.pyfix); }
    return nullptr;
  };

  PyObject* keys = PyList_New((Py_ssize_t)ngroups);
  PyObject* versions = PyList_New((Py_ssize_t)n);
  if (keys == nullptr || versions == nullptr) {
    return fail(keys, versions, nullptr);
  }
  std::vector<uint64_t> ht(n), exp(n);
  std::vector<uint8_t> tomb(n), live(n);
  std::vector<int32_t> gsizes(ngroups);
  std::string keyblob;
  keyblob.resize(n * (size_t)key_words * 4, '\0');
  std::vector<int32_t> ranges;  // (g0, gn, rows) per block
  size_t max_key_len = 0, max_group = 0;
  int64_t g0 = 0, gn = 0, fill = 0;

  size_t row = 0;
  Py_ssize_t gi = 0;
  for (const std::string* kp : d->index) {
    const std::string& key = *kp;
    std::vector<Ver>& vers = d->map[key];
    size_t nv = vers.size();
    if ((Py_ssize_t)nv > R) {
      PyErr_Format(PyExc_ValueError,
                   "key has %zu versions > rows_per_block=%zd; "
                   "GC history (compact with a cutoff) to shrink it",
                   nv, R);
      return fail(keys, versions, nullptr);
    }
    if (fill + (int64_t)nv > R && fill > 0) {
      ranges.push_back((int32_t)g0);
      ranges.push_back((int32_t)gn);
      ranges.push_back((int32_t)fill);
      g0 = gi;
      gn = 0;
      fill = 0;
    }
    gn += 1;
    fill += (int64_t)nv;
    if (nv > max_group) max_group = nv;
    if (key.size() > max_key_len) max_key_len = key.size();
    gsizes[(size_t)gi] = (int32_t)nv;
    if (nv > 1) {
      std::stable_sort(vers.begin(), vers.end(),
                       [](const Ver& a, const Ver& b) {
                         if (a.ht != b.ht) return a.ht > b.ht;
                         return a.write_id > b.write_id;
                       });
    }
    PyObject* kb = PyBytes_FromStringAndSize(key.data(),
                                             (Py_ssize_t)key.size());
    if (kb == nullptr) return fail(keys, versions, nullptr);
    PyList_SET_ITEM(keys, gi, kb);  // list owns the ref
    gi++;
    for (const Ver& v : vers) {
      ht[row] = v.ht;
      exp[row] = v.expire_ht;
      tomb[row] = (v.flags & 1) ? 1 : 0;
      live[row] = (v.flags & 2) ? 1 : 0;
      size_t w = key.size() < (size_t)key_words * 4
                     ? key.size() : (size_t)key_words * 4;
      memcpy(&keyblob[row * (size_t)key_words * 4], key.data(), w);
      // Columns: one parse builds the RowVersion dict AND the plane
      // records.
      PyObject* dict = PyDict_New();
      if (dict == nullptr) return fail(keys, versions, nullptr);
      ybtag::Reader r{(const unsigned char*)v.cols.data(), v.cols.size(),
                      0};
      bool ok = true;
      for (uint16_t ci = 0; ci < v.ncols && ok; ci++) {
        if (r.len - r.pos < 4) { ok = false; break; }
        uint32_t col_id = get_u32(r.data + r.pos);
        r.pos += 4;
        PyObject* val = ybtag::decode_obj(&r, 0);
        if (val == nullptr) { ok = false; break; }
        PyObject* idk = PyLong_FromUnsignedLong(col_id);
        if (idk == nullptr || PyDict_SetItem(dict, idk, val) < 0) {
          Py_XDECREF(idk);
          Py_DECREF(val);
          ok = false;
          break;
        }
        Py_DECREF(idk);
        auto cp = colpos.find(col_id);
        if (cp != colpos.end()) {
          ColBuf& cb = cols[cp->second];
          if (val == Py_None) {
            cb.rows.push_back((int32_t)row);
            cb.null_rows.push_back((int32_t)row);
          } else if (cb.kind == 0) {
            long long x;
            if (val == Py_True) {
              x = 1;
            } else if (val == Py_False) {
              x = 0;
            } else {
              x = PyLong_AsLongLong(val);
              if (x == -1 && PyErr_Occurred()) ok = false;
            }
            if (ok) {
              cb.rows.push_back((int32_t)row);
              cb.ivals.push_back((int64_t)x);
            }
          } else if (cb.kind == 1 || cb.kind == 2) {
            double x = PyFloat_AsDouble(val);
            if (x == -1.0 && PyErr_Occurred()) {
              ok = false;
            } else {
              cb.rows.push_back((int32_t)row);
              cb.dvals.push_back(x);
            }
          } else {  // varlen
            const char* p = nullptr;
            Py_ssize_t plen = 0;
            if (PyUnicode_Check(val)) {
              p = PyUnicode_AsUTF8AndSize(val, &plen);
              if (p == nullptr) {
                PyErr_Clear();  // surrogates etc.: host fallback row
              }
            } else if (PyBytes_Check(val)) {
              p = PyBytes_AS_STRING(val);
              plen = PyBytes_GET_SIZE(val);
            }
            cb.rows.push_back((int32_t)row);
            if (PyList_Append(cb.pyvals, val) < 0) ok = false;
            if (ok && p != nullptr) {
              uint64_t pre = 0;
              for (int bi = 0; bi < 8; bi++) {
                pre = (pre << 8) |
                      (bi < plen ? (unsigned char)p[bi] : 0);
              }
              cb.prefix.push_back(pre);
              if ((size_t)plen > cb.maxlen) cb.maxlen = (size_t)plen;
            } else if (ok) {
              cb.prefix.push_back(0);
              PyObject* ri = PyLong_FromSize_t(row);
              if (ri == nullptr ||
                  PyList_Append(cb.pyfix, ri) < 0) {
                Py_XDECREF(ri);
                ok = false;
              } else {
                Py_DECREF(ri);
              }
            }
          }
        }
        Py_DECREF(val);
      }
      if (!ok) {
        Py_DECREF(dict);
        return fail(keys, versions, nullptr);
      }
      PyObject* ttl = (v.ttl_us < 0) ? Py_NewRef(Py_None)
                                     : PyLong_FromLongLong(v.ttl_us);
      PyObject* rv = ttl == nullptr ? nullptr : PyObject_CallFunction(
          rv_cls, "OLOOOLOk", PyList_GET_ITEM(keys, gi - 1),
          (long long)v.ht, (v.flags & 1) ? Py_True : Py_False,
          (v.flags & 2) ? Py_True : Py_False, dict,
          (long long)v.expire_ht, ttl, (unsigned long)v.write_id);
      Py_XDECREF(ttl);
      Py_DECREF(dict);
      if (rv == nullptr) {
        return fail(keys, versions, nullptr);
      }
      PyList_SET_ITEM(versions, (Py_ssize_t)row, rv);
      row++;
    }
  }
  if ((fill > 0 || ranges.empty()) && gn > 0) {
    ranges.push_back((int32_t)g0);
    ranges.push_back((int32_t)gn);
    ranges.push_back((int32_t)fill);
  }

  auto vec_bytes = [](const void* p, size_t nbytes) {
    return PyBytes_FromStringAndSize((const char*)p, (Py_ssize_t)nbytes);
  };
  PyObject* colout = PyDict_New();
  if (colout == nullptr) return fail(keys, versions, nullptr);
  for (ColBuf& cb : cols) {
    PyObject* entry = Py_BuildValue(
        "{s:i,s:N,s:N,s:N,s:N,s:N,s:N,s:n}",
        "kind", cb.kind,
        "rows", vec_bytes(cb.rows.data(), cb.rows.size() * 4),
        "nulls", vec_bytes(cb.null_rows.data(), cb.null_rows.size() * 4),
        "ivals", vec_bytes(cb.ivals.data(), cb.ivals.size() * 8),
        "dvals", vec_bytes(cb.dvals.data(), cb.dvals.size() * 8),
        "prefix", vec_bytes(cb.prefix.data(), cb.prefix.size() * 8),
        "pyvals", cb.pyvals ? cb.pyvals : Py_NewRef(Py_None),
        "maxlen", (Py_ssize_t)cb.maxlen);
    cb.pyvals = nullptr;  // Py_BuildValue 'N' owns it (even on failure)
    PyObject* idk = entry ? PyLong_FromUnsignedLong(cb.col_id) : nullptr;
    if (entry == nullptr || idk == nullptr ||
        PyDict_SetItem(colout, idk, entry) < 0 ||
        (cb.pyfix != nullptr &&
         PyDict_SetItemString(entry, "pyfix", cb.pyfix) < 0)) {
      Py_XDECREF(entry);
      Py_XDECREF(idk);
      Py_DECREF(colout);
      return fail(keys, versions, nullptr);
    }
    Py_XDECREF(cb.pyfix);
    cb.pyfix = nullptr;
    Py_DECREF(entry);
    Py_DECREF(idk);
  }
  return Py_BuildValue(
      "{s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:N,s:n,s:n,s:n}",
      "ranges", vec_bytes(ranges.data(), ranges.size() * 4),
      "group_sizes", vec_bytes(gsizes.data(), gsizes.size() * 4),
      "keys", keys,
      "versions", versions,
      "ht", vec_bytes(ht.data(), ht.size() * 8),
      "exp", vec_bytes(exp.data(), exp.size() * 8),
      "tomb", vec_bytes(tomb.data(), tomb.size()),
      "live", vec_bytes(live.data(), live.size()),
      "keywords", vec_bytes(keyblob.data(), keyblob.size()),
      "cols", colout,
      "max_key_len", (Py_ssize_t)max_key_len,
      "max_group", (Py_ssize_t)max_group,
      "n", (Py_ssize_t)n);
}

// point_lookup(keys, read_ht, col_id) -> list (one entry per key)
//
// The request-batch read executor: replicate storage/merge.py
// merge_versions for ONE projected column over a batch of encoded
// DocKeys, returning the winning value's raw tagged payload so the
// serving layer can emit reply bytes without building a Python value
// per row. Entries:
//   bytes — payload of the winning T_STR/T_BYTES value (exactly
//           str.encode('utf-8','surrogateescape') for strings, so RESP
//           bulk replies slice it verbatim)
//   None  — key absent, row shadowed/tombstoned, column unset, explicit
//           NULL, or TTL-expired (expiry reads NULL but still shadows)
//   False — winning value is not a string/bytes: not definitive here,
//           the caller must fall back to the Python path for this key.
PyObject* mt_point_lookup(MemtableObject* self, PyObject* args) {
  PyObject* keys;
  long long read_ht_s;
  unsigned long col_id;
  if (!PyArg_ParseTuple(args, "OLk", &keys, &read_ht_s, &col_id)) {
    return nullptr;
  }
  uint64_t read_ht = (uint64_t)read_ht_s;
  PyObject* fast = PySequence_Fast(keys, "point_lookup: keys");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject* out = PyList_New(n);
  if (out == nullptr) { Py_DECREF(fast); return nullptr; }
  std::vector<const Ver*> vis;  // reused per key
  std::string key;
  for (Py_ssize_t i = 0; i < n; i++) {
    char* kp;
    Py_ssize_t klen;
    if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fast, i), &kp,
                                &klen) < 0) {
      Py_DECREF(out);
      Py_DECREF(fast);
      return nullptr;
    }
    key.assign(kp, (size_t)klen);
    auto it = self->data->map.find(key);
    PyObject* entry = nullptr;
    if (it == self->data->map.end()) {
      entry = Py_NewRef(Py_None);
    } else {
      const std::vector<Ver>& vers = it->second;
      uint64_t tomb_ht = 0;
      for (const Ver& v : vers) {
        if (v.ht <= read_ht && (v.flags & 1) && v.ht > tomb_ht) {
          tomb_ht = v.ht;
        }
      }
      vis.clear();
      for (const Ver& v : vers) {
        if (v.ht > read_ht || v.ht <= tomb_ht || (v.flags & 1)) continue;
        vis.push_back(&v);
      }
      std::stable_sort(vis.begin(), vis.end(),
                       [](const Ver* a, const Ver* b) {
                         if (a->ht != b->ht) return a->ht > b->ht;
                         return a->write_id > b->write_id;
                       });
      for (const Ver* v : vis) {
        Reader r{(const unsigned char*)v->cols.data(), v->cols.size()};
        bool found = false, bad = false;
        for (uint16_t ci = 0; ci < v->ncols; ci++) {
          if (r.len - r.pos < 4) { bad = true; break; }
          uint32_t cid = get_u32(r.data + r.pos);
          r.pos += 4;
          if (cid != (uint32_t)col_id) {
            if (!ybtag::skip_obj(&r, 0)) { bad = true; PyErr_Clear(); }
            if (bad) break;
            continue;
          }
          found = true;
          bool expired = v->expire_ht != kMaxHT && read_ht >= v->expire_ht;
          if (expired || r.pos >= r.len) {
            entry = expired ? Py_NewRef(Py_None) : nullptr;
            if (entry == nullptr) bad = true;
            break;
          }
          unsigned char tag = r.data[r.pos++];
          if (tag == ybtag::T_NONE) {
            entry = Py_NewRef(Py_None);
          } else if (tag == ybtag::T_STR || tag == ybtag::T_BYTES) {
            uint64_t plen;
            if (!ybtag::read_varint(&r, &plen) ||
                r.len - r.pos < plen) {
              PyErr_Clear();
              bad = true;
            } else {
              entry = PyBytes_FromStringAndSize(
                  (const char*)(r.data + r.pos), (Py_ssize_t)plen);
              if (entry == nullptr) {
                Py_DECREF(out);
                Py_DECREF(fast);
                return nullptr;
              }
            }
          } else {
            entry = Py_NewRef(Py_False);  // non-string value: fall back
          }
          break;
        }
        if (bad) { entry = Py_NewRef(Py_False); }
        if (found || bad) break;  // newest setter wins (even as NULL)
      }
      if (entry == nullptr) entry = Py_NewRef(Py_None);  // no setter
    }
    PyList_SET_ITEM(out, i, entry);
  }
  Py_DECREF(fast);
  return out;
}

PyObject* mt_stats(MemtableObject* self, PyObject*) {
  return Py_BuildValue(
      "{s:n,s:n,s:N,s:N}",
      "num_versions", (Py_ssize_t)self->num_versions,
      "approx_bytes", (Py_ssize_t)self->approx_bytes,
      "min_ht", self->has_ht
          ? PyLong_FromUnsignedLongLong(self->min_ht) : Py_NewRef(Py_None),
      "max_ht", self->has_ht
          ? PyLong_FromUnsignedLongLong(self->max_ht) : Py_NewRef(Py_None));
}

PyObject* mt_num_versions(MemtableObject* self, void*) {
  return PyLong_FromSize_t(self->num_versions);
}
PyObject* mt_approx_bytes(MemtableObject* self, void*) {
  return PyLong_FromSize_t(self->approx_bytes);
}
PyObject* mt_min_ht(MemtableObject* self, void*) {
  if (!self->has_ht) Py_RETURN_NONE;
  return PyLong_FromUnsignedLongLong(self->min_ht);
}
PyObject* mt_max_ht(MemtableObject* self, void*) {
  if (!self->has_ht) Py_RETURN_NONE;
  return PyLong_FromUnsignedLongLong(self->max_ht);
}

Py_ssize_t mt_len(PyObject* self) {
  return (Py_ssize_t)((MemtableObject*)self)->num_versions;
}

PyMethodDef kMemtableMethods[] = {
    {"apply_block", (PyCFunction)mt_apply_block, METH_O,
     "apply_block(block): insert every row of an encoded row block"},
    {"versions", (PyCFunction)mt_versions, METH_O,
     "versions(key) -> list of row tuples (insertion order)"},
    {"scan_keys", (PyCFunction)mt_scan_keys, METH_VARARGS,
     "scan_keys(lower, upper) -> ordered keys in [lower, upper)"},
    {"has_keys", (PyCFunction)mt_has_keys, METH_VARARGS,
     "has_keys(lower, upper) -> any key in [lower, upper)"},
    {"drain_sorted", (PyCFunction)mt_drain_sorted, METH_NOARGS,
     "drain_sorted() -> [(key, [row tuples ht-desc])] in key order"},
    {"point_lookup", (PyCFunction)mt_point_lookup, METH_VARARGS,
     "point_lookup(keys, read_ht, col_id) -> [payload bytes | None | "
     "False] (False = not definitive, fall back to the Python path)"},
    {"drain_run", (PyCFunction)mt_drain_run, METH_VARARGS,
     "drain_run(R, key_words, coldesc) -> flat packed run buffers "
     "(the native flush path; see storage/columnar.py)"},
    {"stats", (PyCFunction)mt_stats, METH_NOARGS, "summary dict"},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef kMemtableGetSet[] = {
    {"num_versions", (getter)mt_num_versions, nullptr, nullptr, nullptr},
    {"approx_bytes", (getter)mt_approx_bytes, nullptr, nullptr, nullptr},
    {"min_ht", (getter)mt_min_ht, nullptr, nullptr, nullptr},
    {"max_ht", (getter)mt_max_ht, nullptr, nullptr, nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PySequenceMethods kMemtableSeq = {
    mt_len,  // sq_length
};

PyTypeObject MemtableType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "yb_wp.Memtable",              // tp_name
    sizeof(MemtableObject),        // tp_basicsize
};

// -- module ------------------------------------------------------------------

PyMethodDef kMethods[] = {
    {"encode_ops", py_encode_ops, METH_VARARGS,
     "encode_ops(desc, ops, starts) -> per-partition (nrows, block)"},
    {"encode_rows", py_encode_rows, METH_O,
     "encode_rows(row_versions) -> block bytes"},
    {"serve_page_batch", py_serve_page_batch, METH_VARARGS,
     "serve_page_batch(blob, offsets, valid_rows, match_idx, exists_idx, "
     "colspecs, lowers, limit) -> [(rows, scanned, resume|None)]"},
    {"serve_page", py_serve_page, METH_VARARGS,
     "serve_page(blob, offsets, valid_rows, match_idx, exists_idx, "
     "colspecs, lower, upper, limit) -> (rows, scanned, resume|None)"},
    {"serve_page_wire_batch", py_serve_page_wire_batch, METH_VARARGS,
     "serve_page_wire_batch(blob, offsets, valid_rows, match_idx, "
     "exists_idx, wirespecs, lowers, uppers|None, limit, fmt) -> "
     "[(data, nrows, scanned, resume|None)] (fmt 0=CQL cells, 1=PG "
     "DataRow messages)"},
    {"stamp_block", py_stamp_block, METH_VARARGS,
     "stamp_block(block, ht, logical_shift) -> stamped block"},
    {"block_count", py_block_count, METH_O, "row count of a block"},
    {"block_keys", py_block_keys, METH_O, "keys of a block"},
    {"block_rows", py_block_rows, METH_O,
     "block -> list of RowVersion field tuples"},
    {"block_ht_range", py_block_ht_range, METH_O,
     "block -> (min_ht, max_ht) | None"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "yb_wp",
    "native write plane: row blocks, batch encode, stamping, memtable",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_yb_wp() {
  MemtableType.tp_flags = Py_TPFLAGS_DEFAULT;
  MemtableType.tp_doc = "C++ memtable: encoded-key -> MVCC version list";
  MemtableType.tp_new = mt_new;
  MemtableType.tp_dealloc = (destructor)mt_dealloc;
  MemtableType.tp_methods = kMemtableMethods;
  MemtableType.tp_getset = kMemtableGetSet;
  MemtableType.tp_as_sequence = &kMemtableSeq;
  if (PyType_Ready(&MemtableType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&kModule);
  if (m == nullptr) return nullptr;
  Py_INCREF(&MemtableType);
  if (PyModule_AddObject(m, "Memtable", (PyObject*)&MemtableType) < 0) {
    Py_DECREF(&MemtableType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
