// Native request-batch serving path: RESP batch parsing and batched
// point-key routing for the per-op CQL/Redis hot loop.
//
// The reference batch-executes redis ops inside its C++ reactor
// (src/yb/yql/redis/redisserver/redis_service.cc BatchContext +
// src/yb/rpc/reactor.cc): one drained socket buffer becomes one batch
// of parsed commands, routed to tablets by partition hash, served, and
// answered without per-op allocation. This module is that shape for the
// TPU-native framework's Python frontends: Python keeps sockets, auth,
// consensus, and transactions; the per-op inner loop — frame parse,
// DocKey encode, partition route — runs here over whole batches, and
// point reads are served by yb_wp.Memtable.point_lookup against the
// native memtable. Anything unusual falls back to the Python path with
// byte-identical results (yql/redis/resp.py and models/encoding.py are
// the specs).
//
// Exposed as the CPython extension module `yb_rb`.

#include "keycodec.h"
#include "tagcodec.h"

#include <algorithm>
#include <string>
#include <vector>

namespace {

using ybtag::Buf;
using namespace ybkey;

// -- parse_resp --------------------------------------------------------------
//
// parse_resp(data) -> (commands, consumed) | None
//
// Strict RESP2 array-of-bulk-strings parser (the form every pipelined
// client emits). Consumes complete commands; incomplete trailing data is
// left unconsumed (commands parsed so far are returned). Returns None —
// having consumed NOTHING — on anything the strict grammar doesn't
// cover (inline commands, malformed lengths): the caller re-parses the
// whole buffer with yql.redis.resp.parse_commands so error behavior and
// consumption stay byte-identical to the Python path.

// index of "\r\n" at/after `from`, or -1.
static Py_ssize_t find_crlf(const unsigned char* p, Py_ssize_t n,
                            Py_ssize_t from) {
  for (Py_ssize_t i = from; i + 1 < n; i++) {
    if (p[i] == '\r' && p[i + 1] == '\n') return i;
  }
  return -1;
}

// Parse "-?[0-9]+" in [a, b). Returns false on any other shape.
static bool parse_strict_int(const unsigned char* p, Py_ssize_t a,
                             Py_ssize_t b, long long* out) {
  if (a >= b) return false;
  bool neg = false;
  if (p[a] == '-') { neg = true; a++; }
  if (a >= b || b - a > 18) return false;  // 18 digits caps < 2^63
  long long v = 0;
  for (Py_ssize_t i = a; i < b; i++) {
    if (p[i] < '0' || p[i] > '9') return false;
    v = v * 10 + (p[i] - '0');
  }
  *out = neg ? -v : v;
  return true;
}

PyObject* py_parse_resp(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  const unsigned char* p = (const unsigned char*)view.buf;
  Py_ssize_t n = view.len;

  PyObject* cmds = PyList_New(0);
  if (cmds == nullptr) { PyBuffer_Release(&view); return nullptr; }
  Py_ssize_t consumed = 0;
  bool fallback = false;

  while (consumed < n) {
    if (p[consumed] != '*') { fallback = true; break; }  // inline form
    Py_ssize_t end = find_crlf(p, n, consumed);
    if (end < 0) break;  // incomplete header
    long long nargs;
    if (!parse_strict_int(p, consumed + 1, end, &nargs)) {
      fallback = true;  // parse_commands raises ProtocolError here
      break;
    }
    Py_ssize_t pos = end + 2;
    PyObject* args = PyList_New(0);
    if (args == nullptr) { Py_DECREF(cmds); PyBuffer_Release(&view);
                           return nullptr; }
    bool complete = true;
    for (long long a = 0; a < nargs; a++) {
      if (pos >= n) { complete = false; break; }
      if (p[pos] != '$') { fallback = true; break; }
      Py_ssize_t lend = find_crlf(p, n, pos);
      if (lend < 0) { complete = false; break; }
      long long ln;
      if (!parse_strict_int(p, pos + 1, lend, &ln) || ln < 0) {
        fallback = true;  // bad / negative bulk length
        break;
      }
      Py_ssize_t start = lend + 2;
      if (n < start + ln + 2) { complete = false; break; }
      PyObject* item = PyBytes_FromStringAndSize((const char*)p + start,
                                                 (Py_ssize_t)ln);
      if (item == nullptr || PyList_Append(args, item) < 0) {
        Py_XDECREF(item);
        Py_DECREF(args);
        Py_DECREF(cmds);
        PyBuffer_Release(&view);
        return nullptr;
      }
      Py_DECREF(item);
      pos = start + ln + 2;
    }
    if (fallback || !complete) { Py_DECREF(args); break; }
    consumed = pos;
    if (PyList_GET_SIZE(args) > 0) {
      if (PyList_Append(cmds, args) < 0) {
        Py_DECREF(args);
        Py_DECREF(cmds);
        PyBuffer_Release(&view);
        return nullptr;
      }
    }
    Py_DECREF(args);
  }
  PyBuffer_Release(&view);
  if (fallback) {
    Py_DECREF(cmds);
    Py_RETURN_NONE;
  }
  return Py_BuildValue("(Nn)", cmds, consumed);
}

// -- encode_point_keys -------------------------------------------------------
//
// encode_point_keys(hash_dtypes, range_dtypes, rows, starts, full)
//   -> [(partition_index, key_bytes)]
//
// Batch DocKey encoder + partition router for point ops: each row is a
// flat sequence of key column values (hash components then range
// components); dtypes are models/datatypes.py key-kind codes. full=1
// appends the trailing group terminator (schema.encode_primary_key
// parity — redis point rows); full=0 stops after the range components
// (models/encoding.py encode_doc_key_prefix parity — CQL point-SELECT
// bounds, paired with prefix_successor upper bounds).

static bool parse_dtypes(PyObject* seq, std::vector<int>* out,
                         const char* what) {
  PyObject* fast = PySequence_Fast(seq, what);
  if (fast == nullptr) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  for (Py_ssize_t i = 0; i < n; i++) {
    long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
    if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return false; }
    out->push_back((int)v);
  }
  Py_DECREF(fast);
  return true;
}

PyObject* py_encode_point_keys(PyObject*, PyObject* args) {
  PyObject *hash_o, *range_o, *rows, *starts_obj;
  int full;
  if (!PyArg_ParseTuple(args, "OOOOi", &hash_o, &range_o, &rows,
                        &starts_obj, &full)) {
    return nullptr;
  }
  std::vector<int> hash_dt, range_dt;
  if (!parse_dtypes(hash_o, &hash_dt, "encode_point_keys: hash dtypes") ||
      !parse_dtypes(range_o, &range_dt, "encode_point_keys: range dtypes")) {
    return nullptr;
  }
  if (hash_dt.empty()) {
    PyErr_SetString(PyExc_ValueError,
                    "encode_point_keys: need at least one hash column");
    return nullptr;
  }
  std::vector<uint32_t> starts;
  {
    PyObject* fast = PySequence_Fast(starts_obj,
                                     "encode_point_keys: starts");
    if (fast == nullptr) return nullptr;
    Py_ssize_t sn = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < sn; i++) {
      long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
      if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
      starts.push_back((uint32_t)v);
    }
    Py_DECREF(fast);
  }
  if (starts.empty() || starts[0] != 0) {
    PyErr_SetString(PyExc_ValueError,
                    "encode_point_keys: partition starts must begin at 0");
    return nullptr;
  }

  PyObject* rows_fast = PySequence_Fast(rows, "encode_point_keys: rows");
  if (rows_fast == nullptr) return nullptr;
  Py_ssize_t nrows = PySequence_Fast_GET_SIZE(rows_fast);
  PyObject* out = PyList_New(nrows);
  if (out == nullptr) { Py_DECREF(rows_fast); return nullptr; }

  Buf key, hashbuf;  // reused per row
  size_t ncomp = hash_dt.size() + range_dt.size();
  for (Py_ssize_t i = 0; i < nrows; i++) {
    PyObject* row_fast = PySequence_Fast(
        PySequence_Fast_GET_ITEM(rows_fast, i), "encode_point_keys: row");
    if (row_fast == nullptr) goto fail;
    if ((size_t)PySequence_Fast_GET_SIZE(row_fast) != ncomp) {
      PyErr_SetString(PyExc_ValueError,
                      "encode_point_keys: row arity mismatch");
      Py_DECREF(row_fast);
      goto fail;
    }
    {
      key.len = 0;
      hashbuf.len = 0;
      bool ok = true;
      for (size_t c = 0; ok && c < hash_dt.size(); c++) {
        ok = encode_key_component(
            &hashbuf, PySequence_Fast_GET_ITEM(row_fast, (Py_ssize_t)c),
            hash_dt[c]);
      }
      uint16_t h = 0;
      size_t part = 0;
      if (ok) {
        h = hash_code_of(hashbuf);
        part = std::upper_bound(starts.begin(), starts.end(),
                                (uint32_t)h) - starts.begin() - 1;
        ok = ybtag::buf_putc(&key, K_HASH) &&
             ybtag::buf_putc(&key, (unsigned char)(h >> 8)) &&
             ybtag::buf_putc(&key, (unsigned char)(h & 0xFF)) &&
             ybtag::buf_put(&key, hashbuf.data, hashbuf.len) &&
             ybtag::buf_putc(&key, K_GROUP_END);
      }
      for (size_t c = 0; ok && c < range_dt.size(); c++) {
        ok = encode_key_component(
            &key,
            PySequence_Fast_GET_ITEM(row_fast,
                                     (Py_ssize_t)(hash_dt.size() + c)),
            range_dt[c]);
      }
      if (ok && full) ok = ybtag::buf_putc(&key, K_GROUP_END);
      Py_DECREF(row_fast);
      if (!ok) goto fail;
      PyObject* entry = Py_BuildValue(
          "(ny#)", (Py_ssize_t)part, key.data, (Py_ssize_t)key.len);
      if (entry == nullptr) goto fail;
      PyList_SET_ITEM(out, i, entry);
    }
  }
  Py_DECREF(rows_fast);
  return out;

fail:
  Py_DECREF(rows_fast);
  Py_DECREF(out);
  return nullptr;
}

// -- module ------------------------------------------------------------------

PyMethodDef kMethods[] = {
    {"parse_resp", py_parse_resp, METH_O,
     "parse_resp(data) -> (commands, consumed) | None "
     "(None = fall back to yql.redis.resp.parse_commands)"},
    {"encode_point_keys", py_encode_point_keys, METH_VARARGS,
     "encode_point_keys(hash_dtypes, range_dtypes, rows, starts, full) "
     "-> [(partition_index, key_bytes)]"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "yb_rb",
    "native request-batch serving: RESP batch parse + point-key routing",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_yb_rb() {
  return PyModule_Create(&kModule);
}
