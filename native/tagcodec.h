// Shared tagged-binary codec core for the native extensions.
//
// Same wire grammar as yugabyte_db_tpu/utils/codec.py (the canonical
// spec): tag byte then payload; varints are LEB128; ints are zigzag.
// Used by codec.cc (the yb_codec module) and writeplane.cc (row blocks
// store column values with this grammar so any codec-encodable value
// round-trips through the native write path).

#ifndef YB_NATIVE_TAGCODEC_H
#define YB_NATIVE_TAGCODEC_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>

namespace ybtag {

enum Tag : unsigned char {
  T_NONE = 0, T_TRUE, T_FALSE, T_INT, T_F64, T_STR, T_BYTES, T_LIST,
  T_MAP,
  // Rich QL scalars (DECIMAL/VARINT-beyond-64-bit/UUID/TIMEUUID/INET/
  // DATE/TIME): varint length + the byte-comparable key-component
  // encoding (models/encoding.py). Native code skips them structurally;
  // decoding materializes through the Python helper (these ride the
  // host-payload path, never the native hot loops).
  T_EXT
};

constexpr int kMaxDepth = 200;

// -- growable output buffer --------------------------------------------------

struct Buf {
  char* data = nullptr;
  size_t len = 0, cap = 0;
  ~Buf() { PyMem_Free(data); }
};

inline bool buf_reserve(Buf* b, size_t extra) {
  if (b->len + extra <= b->cap) return true;
  size_t cap = b->cap ? b->cap : 256;
  while (cap < b->len + extra) cap *= 2;
  char* p = static_cast<char*>(PyMem_Realloc(b->data, cap));
  if (p == nullptr) { PyErr_NoMemory(); return false; }
  b->data = p;
  b->cap = cap;
  return true;
}

inline bool buf_put(Buf* b, const void* p, size_t n) {
  if (!buf_reserve(b, n)) return false;
  memcpy(b->data + b->len, p, n);
  b->len += n;
  return true;
}

inline bool buf_putc(Buf* b, unsigned char c) { return buf_put(b, &c, 1); }

inline bool write_varint(Buf* b, uint64_t v) {
  unsigned char tmp[10];
  int n = 0;
  for (;;) {
    unsigned char byte = v & 0x7F;
    v >>= 7;
    if (v) {
      tmp[n++] = byte | 0x80;
    } else {
      tmp[n++] = byte;
      return buf_put(b, tmp, n);
    }
  }
}

// -- encode ------------------------------------------------------------------

inline bool encode_obj(Buf* b, PyObject* v, int depth) {
  if (depth > kMaxDepth) {
    PyErr_SetString(PyExc_ValueError, "codec: nesting too deep");
    return false;
  }
  if (v == Py_None) return buf_putc(b, T_NONE);
  if (PyBool_Check(v)) return buf_putc(b, v == Py_True ? T_TRUE : T_FALSE);
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow != 0) {
      // > 64-bit int: the Python implementation handles it (wrapper
      // catches OverflowError and falls back).
      PyErr_SetString(PyExc_OverflowError, "int beyond int64");
      return false;
    }
    if (x == -1 && PyErr_Occurred()) return false;
    uint64_t z = (x >= 0)
        ? (static_cast<uint64_t>(x) << 1)
        : ((static_cast<uint64_t>(-(x + 1)) << 1) | 1);
    return buf_putc(b, T_INT) && write_varint(b, z);
  }
  if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    // wire format is little-endian f64; all supported targets are LE
    return buf_putc(b, T_F64) && buf_put(b, &d, 8);
  }
  if (PyUnicode_Check(v)) {
    PyObject* raw = PyUnicode_AsEncodedString(v, "utf-8", "surrogateescape");
    if (raw == nullptr) return false;
    char* p;
    Py_ssize_t n;
    if (PyBytes_AsStringAndSize(raw, &p, &n) < 0) {
      Py_DECREF(raw);
      return false;
    }
    bool ok = buf_putc(b, T_STR) && write_varint(b, (uint64_t)n) &&
              buf_put(b, p, (size_t)n);
    Py_DECREF(raw);
    return ok;
  }
  if (PyBytes_Check(v)) {
    char* p;
    Py_ssize_t n;
    if (PyBytes_AsStringAndSize(v, &p, &n) < 0) return false;
    return buf_putc(b, T_BYTES) && write_varint(b, (uint64_t)n) &&
           buf_put(b, p, (size_t)n);
  }
  if (PyByteArray_Check(v) || PyMemoryView_Check(v)) {
    PyObject* raw = PyBytes_FromObject(v);
    if (raw == nullptr) return false;
    bool ok = encode_obj(b, raw, depth);
    Py_DECREF(raw);
    return ok;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    PyObject* fast = PySequence_Fast(v, "codec: sequence");
    if (fast == nullptr) return false;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    bool ok = buf_putc(b, T_LIST) && write_varint(b, (uint64_t)n);
    for (Py_ssize_t i = 0; ok && i < n; i++) {
      ok = encode_obj(b, PySequence_Fast_GET_ITEM(fast, i), depth + 1);
    }
    Py_DECREF(fast);
    return ok;
  }
  if (PyDict_Check(v)) {
    if (!buf_putc(b, T_MAP) ||
        !write_varint(b, (uint64_t)PyDict_Size(v))) {
      return false;
    }
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (!encode_obj(b, key, depth + 1) ||
          !encode_obj(b, val, depth + 1)) {
        return false;
      }
    }
    return true;
  }
  // Rich QL scalars (Decimal, UUID/TimeUuid, Inet, date, time): emit
  // T_EXT with the byte-comparable component encoding produced by the
  // Python helper (these never ride the native hot loops).
  {
    static PyObject* fn = nullptr;
    if (fn == nullptr) {
      PyObject* mod =
          PyImport_ImportModule("yugabyte_db_tpu.models.encoding");
      if (mod != nullptr) {
        fn = PyObject_GetAttrString(mod, "encode_component_value");
        Py_DECREF(mod);
      }
      PyErr_Clear();
    }
    if (fn != nullptr) {
      PyObject* raw = PyObject_CallOneArg(fn, v);
      if (raw == nullptr) {
        PyErr_Clear();
      } else if (PyBytes_Check(raw)) {
        char* p;
        Py_ssize_t n;
        if (PyBytes_AsStringAndSize(raw, &p, &n) < 0) {
          Py_DECREF(raw);
          return false;
        }
        bool ok = buf_putc(b, T_EXT) && write_varint(b, (uint64_t)n) &&
                  buf_put(b, p, (size_t)n);
        Py_DECREF(raw);
        return ok;
      } else {
        Py_DECREF(raw);
      }
    }
  }
  PyErr_Format(PyExc_TypeError, "codec cannot encode %s",
               Py_TYPE(v)->tp_name);
  return false;
}

// -- decode ------------------------------------------------------------------

struct Reader {
  const unsigned char* data;
  size_t len, pos = 0;
};

inline bool read_varint(Reader* r, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  for (;;) {
    if (r->pos >= r->len) {
      PyErr_SetString(PyExc_ValueError, "codec: truncated varint");
      return false;
    }
    unsigned char byte = r->data[r->pos++];
    if (shift >= 64 || (shift == 63 && (byte & 0x7E))) {
      // arbitrary-precision int: fall back to the Python decoder
      PyErr_SetString(PyExc_OverflowError, "varint beyond uint64");
      return false;
    }
    result |= (uint64_t)(byte & 0x7F) << shift;
    if (!(byte & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
  }
}

inline bool need(Reader* r, size_t n) {
  if (r->len - r->pos < n) {
    PyErr_SetString(PyExc_ValueError, "codec: truncated payload");
    return false;
  }
  return true;
}

inline PyObject* decode_obj(Reader* r, int depth) {
  if (depth > kMaxDepth) {
    PyErr_SetString(PyExc_ValueError, "codec: nesting too deep");
    return nullptr;
  }
  if (!need(r, 1)) return nullptr;
  unsigned char tag = r->data[r->pos++];
  switch (tag) {
    case T_NONE: Py_RETURN_NONE;
    case T_TRUE: Py_RETURN_TRUE;
    case T_FALSE: Py_RETURN_FALSE;
    case T_INT: {
      uint64_t z;
      if (!read_varint(r, &z)) return nullptr;
      long long x = (z & 1)
          ? -(long long)(z >> 1) - 1
          : (long long)(z >> 1);
      return PyLong_FromLongLong(x);
    }
    case T_F64: {
      if (!need(r, 8)) return nullptr;
      double d;
      memcpy(&d, r->data + r->pos, 8);
      r->pos += 8;
      return PyFloat_FromDouble(d);
    }
    case T_STR: {
      uint64_t n;
      if (!read_varint(r, &n) || !need(r, n)) return nullptr;
      PyObject* s = PyUnicode_DecodeUTF8(
          (const char*)(r->data + r->pos), (Py_ssize_t)n, "surrogateescape");
      r->pos += n;
      return s;
    }
    case T_BYTES: {
      uint64_t n;
      if (!read_varint(r, &n) || !need(r, n)) return nullptr;
      PyObject* s = PyBytes_FromStringAndSize(
          (const char*)(r->data + r->pos), (Py_ssize_t)n);
      r->pos += n;
      return s;
    }
    case T_LIST: {
      uint64_t n;
      if (!read_varint(r, &n)) return nullptr;
      if (n > r->len - r->pos) {  // each item needs >= 1 byte
        PyErr_SetString(PyExc_ValueError, "codec: bad list length");
        return nullptr;
      }
      PyObject* list = PyList_New((Py_ssize_t)n);
      if (list == nullptr) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject* item = decode_obj(r, depth + 1);
        if (item == nullptr) {
          Py_DECREF(list);
          return nullptr;
        }
        PyList_SET_ITEM(list, (Py_ssize_t)i, item);
      }
      return list;
    }
    case T_EXT: {
      uint64_t n;
      if (!read_varint(r, &n) || !need(r, n)) return nullptr;
      PyObject* raw = PyBytes_FromStringAndSize(
          (const char*)(r->data + r->pos), (Py_ssize_t)n);
      r->pos += n;
      if (raw == nullptr) return nullptr;
      static PyObject* fn = nullptr;
      if (fn == nullptr) {
        PyObject* mod =
            PyImport_ImportModule("yugabyte_db_tpu.models.encoding");
        if (mod != nullptr) {
          fn = PyObject_GetAttrString(mod, "decode_component_value");
          Py_DECREF(mod);
        }
        if (fn == nullptr) {
          Py_DECREF(raw);
          return nullptr;
        }
      }
      PyObject* out = PyObject_CallOneArg(fn, raw);
      Py_DECREF(raw);
      return out;
    }
    case T_MAP: {
      uint64_t n;
      if (!read_varint(r, &n)) return nullptr;
      if (n > r->len - r->pos) {
        PyErr_SetString(PyExc_ValueError, "codec: bad map length");
        return nullptr;
      }
      PyObject* d = PyDict_New();
      if (d == nullptr) return nullptr;
      for (uint64_t i = 0; i < n; i++) {
        PyObject* key = decode_obj(r, depth + 1);
        if (key == nullptr) {
          Py_DECREF(d);
          return nullptr;
        }
        PyObject* val = decode_obj(r, depth + 1);
        if (val == nullptr) {
          Py_DECREF(key);
          Py_DECREF(d);
          return nullptr;
        }
        int rc = PyDict_SetItem(d, key, val);
        Py_DECREF(key);
        Py_DECREF(val);
        if (rc < 0) {
          Py_DECREF(d);
          return nullptr;
        }
      }
      return d;
    }
    default:
      PyErr_Format(PyExc_ValueError, "codec: bad tag 0x%02x at %zu",
                   tag, r->pos - 1);
      return nullptr;
  }
}

// Skip one encoded value without materializing it. Returns false (with a
// Python error set) on truncation/corruption.
inline bool skip_obj(Reader* r, int depth) {
  if (depth > kMaxDepth) {
    PyErr_SetString(PyExc_ValueError, "codec: nesting too deep");
    return false;
  }
  if (!need(r, 1)) return false;
  unsigned char tag = r->data[r->pos++];
  uint64_t n;
  switch (tag) {
    case T_NONE: case T_TRUE: case T_FALSE:
      return true;
    case T_INT:
      return read_varint(r, &n);
    case T_F64:
      if (!need(r, 8)) return false;
      r->pos += 8;
      return true;
    case T_STR: case T_BYTES: case T_EXT:
      if (!read_varint(r, &n) || !need(r, n)) return false;
      r->pos += n;
      return true;
    case T_LIST:
      if (!read_varint(r, &n)) return false;
      for (uint64_t i = 0; i < n; i++) {
        if (!skip_obj(r, depth + 1)) return false;
      }
      return true;
    case T_MAP:
      if (!read_varint(r, &n)) return false;
      for (uint64_t i = 0; i < 2 * n; i++) {
        if (!skip_obj(r, depth + 1)) return false;
      }
      return true;
    default:
      PyErr_Format(PyExc_ValueError, "codec: bad tag 0x%02x at %zu",
                   tag, r->pos - 1);
      return false;
  }
}

}  // namespace ybtag

#endif  // YB_NATIVE_TAGCODEC_H
