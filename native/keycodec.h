// Shared DocKey-component encoding + partition hashing for the native
// extensions. Factored out of writeplane.cc so the request-batch serving
// module (servebatch.cc) routes point ops with exactly the bytes the
// write plane produces — one implementation, two hot paths.
//
// Parity contracts (hold byte-for-byte, enforced by the engine-diff
// tests):
//   encode_key_component  <->  models/encoding.py encode_key_component
//   crc32 + fold          <->  models/partition.py compute_hash_code
//   upper_bound(starts)   <->  models/partition.py partition_index
//
// Reference analog: src/yb/docdb/doc_key.cc (DocKey::EncodeFrom) and
// src/yb/common/partition.cc (PartitionSchema::EncodeKey) — the
// reference likewise shares one key codec between its write path and its
// redis/cql serving paths.

#ifndef YB_NATIVE_KEYCODEC_H
#define YB_NATIVE_KEYCODEC_H

#include "tagcodec.h"

namespace ybkey {

using ybtag::Buf;

// Key-encoding tags (yugabyte_db_tpu/models/encoding.py).
enum KeyTag : unsigned char {
  K_GROUP_END = 0x01,
  K_NULL = 0x04,
  K_HASH = 0x08,
  K_FALSE = 0x10,
  K_TRUE = 0x11,
  K_INT = 0x20,
  K_DOUBLE = 0x28,
  K_STRING = 0x30,
  K_BINARY = 0x32,
};

// dtype codes passed from Python (models/datatypes.py key kinds).
enum DtypeCode { DT_BOOL = 0, DT_INT = 1, DT_DOUBLE = 2, DT_STR = 3,
                 DT_BIN = 4 };

// -- little-endian scalar writes/reads ---------------------------------------

inline bool put_u16(Buf* b, uint16_t v) { return ybtag::buf_put(b, &v, 2); }
inline bool put_u32(Buf* b, uint32_t v) { return ybtag::buf_put(b, &v, 4); }
inline bool put_u64(Buf* b, uint64_t v) { return ybtag::buf_put(b, &v, 8); }
inline bool put_i64(Buf* b, int64_t v) { return ybtag::buf_put(b, &v, 8); }

inline uint16_t get_u16(const unsigned char* p) {
  uint16_t v; memcpy(&v, p, 2); return v;
}
inline uint32_t get_u32(const unsigned char* p) {
  uint32_t v; memcpy(&v, p, 4); return v;
}
inline uint64_t get_u64(const unsigned char* p) {
  uint64_t v; memcpy(&v, p, 8); return v;
}
inline int64_t get_i64(const unsigned char* p) {
  int64_t v; memcpy(&v, p, 8); return v;
}

// -- crc32 (zlib-compatible) -------------------------------------------------

inline const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      table[i] = c;
    }
    init = true;
  }
  return table;
}

inline uint32_t crc32(const unsigned char* p, size_t n) {
  const uint32_t* t = crc_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) {
    c = t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// 16-bit partition hash over the concatenated encoded hash components
// (models/partition.py compute_hash_code).
inline uint16_t hash_code_of(const Buf& hashbuf) {
  uint32_t crc = crc32((const unsigned char*)hashbuf.data, hashbuf.len);
  return (uint16_t)(((crc >> 16) ^ (crc & 0xFFFF)) & 0xFFFF);
}

// -- key-component encoding (parity with models/encoding.py) -----------------

inline bool key_put_int(Buf* b, long long x) {
  // Sign-flip maps signed order onto unsigned byte order; big-endian.
  uint64_t biased = static_cast<uint64_t>(x) + (1ULL << 63);
  unsigned char be[8];
  for (int i = 7; i >= 0; i--) { be[i] = biased & 0xFF; biased >>= 8; }
  return ybtag::buf_putc(b, K_INT) && ybtag::buf_put(b, be, 8);
}

inline bool key_put_double(Buf* b, double d) {
  if (d == 0.0) d = 0.0;  // canonicalize -0.0
  uint64_t bits;
  memcpy(&bits, &d, 8);
  if (bits & (1ULL << 63)) {
    bits = ~bits;                 // negative: flip all bits
  } else {
    bits |= 1ULL << 63;           // positive: flip sign bit
  }
  unsigned char be[8];
  for (int i = 7; i >= 0; i--) { be[i] = bits & 0xFF; bits >>= 8; }
  return ybtag::buf_putc(b, K_DOUBLE) && ybtag::buf_put(b, be, 8);
}

inline bool key_put_escaped(Buf* b, const unsigned char* p, size_t n) {
  // 0x00 -> 0x00 0x01, terminated 0x00 0x00 (ZeroEncodeAndAppendStrToKey).
  for (size_t i = 0; i < n; i++) {
    if (!ybtag::buf_putc(b, p[i])) return false;
    if (p[i] == 0 && !ybtag::buf_putc(b, 0x01)) return false;
  }
  return ybtag::buf_putc(b, 0x00) && ybtag::buf_putc(b, 0x00);
}

// Encode one key column value as [tag][payload]. Returns false with a
// Python error set on unsupported value.
inline bool encode_key_component(Buf* b, PyObject* v, int dtype) {
  if (v == Py_None) return ybtag::buf_putc(b, K_NULL);
  switch (dtype) {
    case DT_BOOL: {
      int truth = PyObject_IsTrue(v);
      if (truth < 0) return false;
      return ybtag::buf_putc(b, truth ? K_TRUE : K_FALSE);
    }
    case DT_INT: {
      long long x;
      if (PyLong_Check(v)) {
        int overflow = 0;
        x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow != 0) {
          PyErr_SetString(PyExc_ValueError,
                          "integer key value out of int64 range");
          return false;
        }
        if (x == -1 && PyErr_Occurred()) return false;
      } else {
        PyObject* as_int = PyNumber_Long(v);
        if (as_int == nullptr) return false;
        x = PyLong_AsLongLong(as_int);
        Py_DECREF(as_int);
        if (x == -1 && PyErr_Occurred()) return false;
      }
      return key_put_int(b, x);
    }
    case DT_DOUBLE: {
      double d = PyFloat_AsDouble(v);
      if (d == -1.0 && PyErr_Occurred()) return false;
      return key_put_double(b, d);
    }
    case DT_STR: {
      if (!PyUnicode_Check(v)) {
        PyErr_Format(PyExc_TypeError, "string key value must be str, not %s",
                     Py_TYPE(v)->tp_name);
        return false;
      }
      PyObject* raw = PyUnicode_AsEncodedString(v, "utf-8", "surrogateescape");
      if (raw == nullptr) return false;
      char* p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(raw, &p, &n) < 0) {
        Py_DECREF(raw);
        return false;
      }
      bool ok = ybtag::buf_putc(b, K_STRING) &&
                key_put_escaped(b, (const unsigned char*)p, (size_t)n);
      Py_DECREF(raw);
      return ok;
    }
    case DT_BIN: {
      PyObject* raw = PyBytes_FromObject(v);
      if (raw == nullptr) return false;
      char* p;
      Py_ssize_t n;
      if (PyBytes_AsStringAndSize(raw, &p, &n) < 0) {
        Py_DECREF(raw);
        return false;
      }
      bool ok = ybtag::buf_putc(b, K_BINARY) &&
                key_put_escaped(b, (const unsigned char*)p, (size_t)n);
      Py_DECREF(raw);
      return ok;
    }
    default:
      PyErr_Format(PyExc_ValueError, "bad key dtype code %d", dtype);
      return false;
  }
}

}  // namespace ybkey

#endif  // YB_NATIVE_KEYCODEC_H
