"""Distributed transaction tests: atomic multi-tablet commit, snapshot
isolation, conflict resolution, abort/expiry cleanup, restart recovery.

Reference test analogs: src/yb/client/ql-transaction-test.cc and
snapshot-txn-test.cc (MiniCluster transactional DML + concurrency).
"""

import random
import threading
import time

import pytest

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.txn import (TransactionConflict, TransactionManager,
                                 YBTransaction)

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("v", DataType.INT64),
]


def wait_for(pred, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = pred()
        if r:
            return r
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    yield c
    c.shutdown()


def _scan_kv(client, table, read_ht=None):
    spec = ScanSpec(projection=["k", "v"])
    if read_ht is not None:
        spec.read_ht = read_ht
    res = YBSession(client).scan(table, spec)
    return dict(res.rows)


def test_commit_atomic_across_tablets(cluster):
    client = cluster.client()
    table = client.create_table("bank", COLUMNS, num_tablets=4)
    mgr = TransactionManager(client)
    txn = mgr.begin()
    for i in range(20):
        txn.insert(table, {"k": f"acct{i}", "v": 100})
    commit_ht = txn.commit()
    # At the commit time: every row visible (all-or-nothing).
    rows = _scan_kv(client, table, read_ht=commit_ht)
    assert rows == {f"acct{i}": 100 for i in range(20)}
    # Just before the commit time: none visible.
    assert _scan_kv(client, table, read_ht=commit_ht - 1) == {}


def test_abort_leaves_nothing(cluster):
    client = cluster.client()
    table = client.create_table("ab", COLUMNS, num_tablets=2)
    mgr = TransactionManager(client)
    txn = mgr.begin()
    txn.insert(table, {"k": "x", "v": 1})
    txn.insert(table, {"k": "y", "v": 2})
    txn.flush()
    txn.abort()
    # Intents are cleaned up on every participant.
    def intents_gone():
        for ts in cluster.tservers.values():
            for peer in ts.tablet_manager.peers():
                if peer.tablet.participant.has_intents(txn.txn_id):
                    return False
        return True
    wait_for(intents_gone, msg="intent cleanup after abort")
    assert _scan_kv(client, table) == {}


def test_read_your_writes(cluster):
    client = cluster.client()
    table = client.create_table("ryw", COLUMNS, num_tablets=2)
    s = YBSession(client)
    s.insert(table, {"k": "a", "v": 1})
    s.flush()
    mgr = TransactionManager(client)
    txn = mgr.begin()
    assert txn.get(table, {"k": "a"}) == ("a", 1)
    txn.update(table, {"k": "a"}, {"v": 5})
    assert txn.get(table, {"k": "a"}) == ("a", 5)   # buffered
    txn.flush()
    assert txn.get(table, {"k": "a"}) == ("a", 5)   # flushed intent
    txn.insert(table, {"k": "b", "v": 7})
    assert txn.get(table, {"k": "b"}) == ("b", 7)
    txn.delete_row(table, {"k": "a"})
    assert txn.get(table, {"k": "a"}) is None
    txn.abort()
    # Nothing leaked to committed state.
    assert _scan_kv(client, table) == {"a": 1}


def test_snapshot_isolation_first_committer_wins(cluster):
    client = cluster.client()
    table = client.create_table("si", COLUMNS, num_tablets=2)
    s = YBSession(client)
    s.insert(table, {"k": "c", "v": 1})
    s.flush()
    mgr = TransactionManager(client)
    txn = mgr.begin()  # snapshot taken now
    # A plain write lands after the txn's read point...
    s.update(table, {"k": "c"}, {"v": 2})
    s.flush()
    # ...so the txn's write to the same key must lose.
    txn.update(table, {"k": "c"}, {"v": 3})
    with pytest.raises(TransactionConflict):
        txn.flush()
    assert _scan_kv(client, table) == {"c": 2}


def test_pending_conflict_priority_duel(cluster):
    client = cluster.client()
    table = client.create_table("duel", COLUMNS, num_tablets=2)
    mgr = TransactionManager(client)
    t1 = mgr.begin()
    t2 = mgr.begin()
    t1.priority = 10
    t2.priority = 20
    t1.insert(table, {"k": "contested", "v": 1})
    t1.flush()
    # Higher priority wounds the pending lower-priority holder.
    t2.insert(table, {"k": "contested", "v": 2})
    t2.flush()
    assert t2.commit() > 0
    # t1 was wounded: its commit must fail.
    with pytest.raises(Exception):
        t1.commit()
    wait_for(lambda: _scan_kv(client, table) == {"contested": 2},
             msg="winner's write visible")


def test_lower_priority_writer_loses(cluster):
    client = cluster.client()
    table = client.create_table("duel2", COLUMNS, num_tablets=2)
    mgr = TransactionManager(client)
    t1 = mgr.begin()
    t2 = mgr.begin()
    t1.priority = 20
    t2.priority = 10
    t1.insert(table, {"k": "c2", "v": 1})
    t1.flush()
    t2.insert(table, {"k": "c2", "v": 2})
    with pytest.raises(TransactionConflict):
        t2.flush()
    assert t1.commit() > 0


def test_expired_txn_auto_aborts(cluster):
    client = cluster.client()
    table = client.create_table("exp", COLUMNS, num_tablets=2)
    mgr = TransactionManager(client)
    txn = mgr.begin()
    txn.insert(table, {"k": "zzz", "v": 9})
    txn.flush()
    # Shrink the expiry on every status-tablet coordinator.
    for ts in cluster.tservers.values():
        for peer in ts.tablet_manager.peers():
            if peer.tablet.coordinator is not None:
                peer.tablet.coordinator.expiry_s = 0.5
    # With no heartbeats the coordinator aborts it; a conflicting plain
    # write then cleans the intents and proceeds.
    s = YBSession(client)
    def plain_write_succeeds():
        try:
            s.insert(table, {"k": "zzz", "v": 10})
            s.flush()
            return True
        except Exception:
            return False
    wait_for(plain_write_succeeds, msg="expiry + wound of silent txn")
    assert _scan_kv(client, table)["zzz"] == 10


def test_concurrent_transfers_conserve_total(cluster):
    """Randomized concurrency: N threads transfer between accounts with
    retries; snapshot isolation must conserve the total balance."""
    client = cluster.client()
    table = client.create_table("xfer", COLUMNS, num_tablets=4)
    s = YBSession(client)
    NACCT = 8
    for i in range(NACCT):
        s.insert(table, {"k": f"a{i}", "v": 1000})
    s.flush()
    mgr = TransactionManager(client)
    stop = threading.Event()
    errors = []

    def worker(seed):
        rng = random.Random(seed)
        mine = attempts = 0
        while not stop.is_set() and mine < 8 and attempts < 80:
            attempts += 1
            i, j = rng.sample(range(NACCT), 2)
            amt = rng.randrange(1, 50)
            txn = mgr.begin()
            try:
                vi = txn.get(table, {"k": f"a{i}"})[1]
                vj = txn.get(table, {"k": f"a{j}"})[1]
                txn.update(table, {"k": f"a{i}"}, {"v": vi - amt})
                txn.update(table, {"k": f"a{j}"}, {"v": vj + amt})
                txn.commit()
                mine += 1
            except Exception as e:  # noqa: BLE001
                txn.abort()
                if not isinstance(e, TransactionConflict) and \
                        "conflict" not in str(e).lower() and \
                        "abort" not in str(e).lower():
                    errors.append(e)
                    return

    threads = [threading.Thread(target=worker, args=(s_,))
               for s_ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "worker hung"
    assert not errors, errors[:3]

    def total_conserved():
        rows = _scan_kv(client, table)
        return len(rows) == NACCT and sum(rows.values()) == NACCT * 1000
    wait_for(total_conserved, msg="balance conservation")


def test_intents_survive_restart(tmp_path):
    c = MiniCluster(str(tmp_path) + "/x", num_masters=1, num_tservers=3)
    c.start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("dur", COLUMNS, num_tablets=2)
        mgr = TransactionManager(client)
        txn = mgr.begin()
        txn.insert(table, {"k": "p", "v": 1})
        txn.flush()
        committed = mgr.begin()
        committed.insert(table, {"k": "q", "v": 2})
        commit_ht = committed.commit()
        wait_for(lambda: _scan_kv(client, table, read_ht=commit_ht)
                 == {"q": 2}, msg="commit applied")
        # Flush every tablet so intents + txn state hit the sidecars.
        for ts in c.tservers.values():
            for peer in ts.tablet_manager.peers():
                peer.flush()
    finally:
        c.shutdown()
    c2 = MiniCluster(str(tmp_path) + "/x", num_masters=1, num_tservers=3)
    c2.start()
    try:
        c2.wait_tservers_registered()
        client2 = c2.client()
        table2 = client2.open_table("dur")

        def state_recovered():
            rows = _scan_kv(client2, table2)
            return rows.get("q") == 2 and "p" not in rows
        wait_for(state_recovered, msg="committed data after restart")
        # The orphaned pending txn's intents were recovered too, and the
        # coordinator (also recovered) eventually expires it.
        for ts in c2.tservers.values():
            for peer in ts.tablet_manager.peers():
                if peer.tablet.coordinator is not None:
                    peer.tablet.coordinator.expiry_s = 0.5
        s2 = YBSession(client2)

        def overwrite_succeeds():
            try:
                s2.insert(table2, {"k": "p", "v": 3})
                s2.flush()
                return True
            except Exception:
                return False
        wait_for(overwrite_succeeds, msg="recovered intent expiry")
    finally:
        c2.shutdown()
