"""YSQL layer: SQL parser, PgProcessor, pggate API, PG wire server.

Reference analogs: YSQL DML/DDL semantics (PostgreSQL-side behavior
over pggate), pg_libpq-test.cc-style socket tests against the FE/BE
protocol, and the TPC-H Q1/Q6 path (pgsql_operation.cc:345,473).
"""

import socket
import struct

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.storage.expr import BinOp, Col, Const
from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound)
from yugabyte_db_tpu.yql.cql.processor import LocalCluster
from yugabyte_db_tpu.yql.pgsql import (PgApi, PgProcessor, PgServer,
                                       parse_statement, tpch)
from yugabyte_db_tpu.yql.pgsql import ast


# -- parser ------------------------------------------------------------------

def test_parse_create_table():
    stmt = parse_statement(
        "CREATE TABLE t (a INT, b BIGINT, c TEXT, d DOUBLE PRECISION, "
        "e BOOLEAN, PRIMARY KEY ((a), b)) SPLIT INTO 7 TABLETS")
    assert stmt.hash_keys == ["a"] and stmt.range_keys == ["b"]
    assert stmt.num_tablets == 7
    types = {c.name: c.dtype for c in stmt.columns}
    assert types == {"a": DataType.INT32, "b": DataType.INT64,
                     "c": DataType.STRING, "d": DataType.DOUBLE,
                     "e": DataType.BOOL}


def test_parse_inline_pk_and_varchar():
    stmt = parse_statement(
        "CREATE TABLE u (id TEXT PRIMARY KEY, n VARCHAR(32))")
    assert stmt.hash_keys == ["id"] and stmt.range_keys == []
    assert stmt.columns[1].dtype == DataType.STRING


def test_parse_insert_multi_row():
    stmt = parse_statement(
        "INSERT INTO t (a, b) VALUES (1, 'x'), (2, 'y'), (3, NULL)")
    assert stmt.columns == ["a", "b"]
    assert stmt.rows == [[1, "x"], [2, "y"], [3, None]]


def test_parse_select_exprs_and_clauses():
    stmt = parse_statement(
        "SELECT a, sum(p * (100 - d)) AS rev, count(*) FROM t "
        "WHERE s BETWEEN 5 AND 9 AND q IN (1, 2, 3) AND a <> 0 "
        "GROUP BY a ORDER BY a DESC LIMIT 10")
    assert stmt.group_by == ["a"]
    assert stmt.order_by[0].column == "a" and stmt.order_by[0].desc
    assert stmt.limit == 10
    rels = {(r.column, r.op): r.value for r in stmt.where}
    assert rels[("s", ">=")] == 5 and rels[("s", "<=")] == 9
    assert rels[("q", "IN")] == (1, 2, 3)
    assert rels[("a", "!=")] == 0
    rev = stmt.items[1]
    assert rev.alias == "rev"
    assert isinstance(rev.expr, ast.Agg) and rev.expr.fn == "sum"
    assert rev.expr.arg == BinOp("*", Col("p"),
                                 BinOp("-", Const(100), Col("d")))


def test_parse_bind_markers():
    stmt = parse_statement("SELECT a FROM t WHERE a = $1 AND b > $2")
    assert stmt.where[0].value == ast.BindMarker(0)
    assert stmt.where[1].value == ast.BindMarker(1)


def test_parse_errors():
    for bad in ("SELECT FROM t", "CREATE TABLE t (a INT)",
                "INSERT INTO t (a) VALUES (1, 2)", "FROBNICATE x"):
        with pytest.raises(InvalidArgument):
            parse_statement(bad)


# -- executor ----------------------------------------------------------------

@pytest.fixture()
def pg():
    cluster = LocalCluster(num_tablets=4)
    yield PgProcessor(cluster)
    cluster.close()


def _setup_kv(pg):
    pg.execute("CREATE TABLE kv (k TEXT, r BIGINT, v TEXT, n BIGINT, "
               "PRIMARY KEY ((k), r))")
    pg.execute("INSERT INTO kv (k, r, v, n) VALUES "
               "('a', 1, 'va', 10), ('a', 2, 'vb', 20), "
               "('b', 1, 'vc', 30), ('c', 1, NULL, 40)")


def test_pg_crud(pg):
    _setup_kv(pg)
    res = pg.execute("SELECT k, r, v, n FROM kv ORDER BY k, r")
    assert res.rows == [("a", 1, "va", 10), ("a", 2, "vb", 20),
                        ("b", 1, "vc", 30), ("c", 1, None, 40)]
    # PG rejects duplicate PKs (CQL would upsert)
    with pytest.raises(AlreadyPresent):
        pg.execute("INSERT INTO kv (k, r, v) VALUES ('a', 1, 'dup')")
    # UPDATE with arithmetic over the old row value, arbitrary WHERE
    res = pg.execute("UPDATE kv SET n = n + 100 WHERE n >= 20")
    assert res.command == "UPDATE 3"
    res = pg.execute("SELECT n FROM kv ORDER BY n")
    assert [r[0] for r in res.rows] == [10, 120, 130, 140]
    # DELETE by non-key predicate
    res = pg.execute("DELETE FROM kv WHERE n > 125")
    assert res.command == "DELETE 2"
    res = pg.execute("SELECT k, r FROM kv ORDER BY k, r")
    assert res.rows == [("a", 1), ("a", 2)]


def test_pg_null_bound_pk_rejected(pg):
    _setup_kv(pg)
    # a NULL arriving via $N must hit the not-null PK check too
    with pytest.raises(InvalidArgument):
        pg.execute("INSERT INTO kv (k, r, v) VALUES ($1, $2, $3)",
                   params=[None, 1, "x"])


def test_pg_comments_and_multi_statement():
    from yugabyte_db_tpu.yql.pgsql import parse_script

    stmts = parse_script("SELECT a FROM t; -- done")
    assert len(stmts) == 1
    stmts = parse_script("-- leading comment\nSELECT a FROM t;\n"
                         "SELECT b FROM t -- trailing")
    assert len(stmts) == 2


def test_pg_point_and_binds(pg):
    _setup_kv(pg)
    res = pg.execute("SELECT v FROM kv WHERE k = $1 AND r = $2",
                     params=["a", 2])
    assert res.rows == [("vb",)]
    res = pg.execute("SELECT k, r FROM kv WHERE n IN (10, 30) "
                     "ORDER BY k")
    assert res.rows == [("a", 1), ("b", 1)]


def test_pg_aggregates_group_order(pg):
    _setup_kv(pg)
    res = pg.execute(
        "SELECT k, count(*) AS c, sum(n) AS s, avg(n) AS a FROM kv "
        "GROUP BY k ORDER BY k")
    assert res.columns == ["k", "c", "s", "a"]
    assert res.rows == [("a", 2, 30, 15.0), ("b", 1, 30, 30.0),
                        ("c", 1, 40, 40.0)]
    res = pg.execute("SELECT count(*), min(n), max(n) FROM kv")
    assert res.rows == [(4, 10, 40)]
    # expression aggregate across tablets
    res = pg.execute("SELECT sum(n * 2) FROM kv")
    assert res.rows == [(200,)]


def test_pg_limit_and_star(pg):
    _setup_kv(pg)
    res = pg.execute("SELECT * FROM kv ORDER BY n DESC LIMIT 2")
    assert [r[3] for r in res.rows] == [40, 30]


def test_pg_secondary_index(pg):
    _setup_kv(pg)
    pg.execute("CREATE INDEX kv_by_v ON kv (v)")
    handle = pg.cluster.table("kv")
    assert any(i["name"] == "kv_by_v" for i in handle.indexes)
    # backfill covered the pre-existing rows; maintenance covers new ones
    pg.execute("INSERT INTO kv (k, r, v, n) VALUES ('d', 9, 'vb', 50)")
    res = pg.execute("SELECT k, r FROM kv WHERE v = 'vb' ORDER BY k")
    assert res.rows == [("a", 2), ("d", 9)]
    # the read is actually index-driven: it touches only the index
    # prefix + two base point reads (vs a 4-tablet full scan)
    res = pg.execute("SELECT n FROM kv WHERE v = 'va'")
    assert res.rows == [(10,)]
    pg.execute("DROP INDEX kv_by_v")
    with pytest.raises(NotFound):
        pg.execute("DROP INDEX kv_by_v")


def test_pg_ddl_errors(pg):
    _setup_kv(pg)
    with pytest.raises(AlreadyPresent):
        pg.execute("CREATE TABLE kv (x INT PRIMARY KEY)")
    pg.execute("CREATE TABLE IF NOT EXISTS kv (x INT PRIMARY KEY)")
    pg.execute("DROP TABLE IF EXISTS nope")
    with pytest.raises(NotFound):
        pg.execute("DROP TABLE nope")


# -- pggate API --------------------------------------------------------------

def test_pggate_prepared_statements():
    cluster = LocalCluster(num_tablets=2)
    try:
        api = PgApi(cluster)
        s = api.new_session()
        s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b TEXT)")
        ins = s.prepare("INSERT INTO t (a, b) VALUES ($1, $2)")
        for i in range(10):
            ins.execute([i, f"s{i}"])
        assert s.prepare("INSERT INTO t (a, b) VALUES ($1, $2)") is ins
        sel = s.prepare("SELECT b FROM t WHERE a = $1")
        assert sel.execute([7]).rows == [("s7",)]
    finally:
        cluster.close()


# -- TPC-H through SQL -------------------------------------------------------

def test_tpch_q1_q6_through_pg_sql():
    cluster = LocalCluster(num_tablets=4)
    try:
        pg = PgProcessor(cluster)
        cols = ", ".join(
            f"{c.name} {'BIGINT' if c.dtype == DataType.INT64 else 'INT'}"
            if c.dtype != DataType.STRING else f"{c.name} TEXT"
            for c in tpch.LINEITEM_COLUMNS)
        pg.execute(f"CREATE TABLE lineitem ({cols}, "
                   "PRIMARY KEY ((l_orderkey), l_linenumber))")
        rows = list(tpch.generate_lineitem(1200))
        batch = []
        for r in rows:
            batch.append("(" + ", ".join(
                f"'{v}'" if isinstance(v, str) else str(v)
                for v in r.values()) + ")")
        names = ", ".join(rows[0])
        pg.execute(f"INSERT INTO lineitem ({names}) VALUES "
                   + ", ".join(batch))
        res = pg.execute(tpch.q1_sql())
        cutoff = 10471
        want = {}
        for r in rows:
            if r["l_shipdate"] > cutoff:
                continue
            k = (r["l_returnflag"], r["l_linestatus"])
            acc = want.setdefault(k, [0, 0, 0])
            acc[0] += r["l_quantity"]
            acc[1] += (r["l_extendedprice"] * (100 - r["l_discount"])
                       * (100 + r["l_tax"]))
            acc[2] += 1
        assert [r[:2] for r in res.rows] == sorted(want)
        for row in res.rows:
            acc = want[(row[0], row[1])]
            assert row[2] == acc[0]              # sum_qty
            assert row[5] == acc[1]              # sum_charge
            assert row[8] == acc[2]              # count_order
            assert row[6] == pytest.approx(acc[0] / acc[2])  # avg_qty
        res6 = pg.execute(tpch.q6_sql())
        want6 = sum(r["l_extendedprice"] * r["l_discount"] for r in rows
                    if 9131 <= r["l_shipdate"] < 9131 + 365
                    and 5 <= r["l_discount"] <= 7
                    and r["l_quantity"] < 24)
        assert res6.rows[0][0] == want6
    finally:
        cluster.close()


# -- wire protocol -----------------------------------------------------------

class MiniPgClient:
    """Just enough libpq to drive the simple-query protocol."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.buf = b""

    def close(self):
        self.sock.close()

    def startup(self, ssl_probe=False):
        if ssl_probe:
            self.sock.sendall(struct.pack(">II", 8, 80877103))
            resp = self.sock.recv(1)
            assert resp == b"N", resp
        params = (b"user\x00tester\x00database\x00db\x00\x00")
        payload = struct.pack(">I", 196608) + params
        self.sock.sendall(struct.pack(">I", len(payload) + 4) + payload)
        msgs = self.read_until_ready()
        assert msgs[0][0] == b"R"  # AuthenticationOk
        assert any(t == b"S" for t, _ in msgs)

    def query(self, sql: str):
        payload = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack(">I", len(payload) + 4)
                          + payload)
        return self.read_until_ready()

    def read_msg(self):
        while len(self.buf) < 5:
            d = self.sock.recv(65536)
            assert d, "connection closed"
            self.buf += d
        tag = self.buf[:1]
        (length,) = struct.unpack_from(">I", self.buf, 1)
        while len(self.buf) < 1 + length:
            d = self.sock.recv(65536)
            assert d, "connection closed"
            self.buf += d
        payload = self.buf[5:1 + length]
        self.buf = self.buf[1 + length:]
        return tag, payload

    def read_until_ready(self):
        msgs = []
        while True:
            tag, payload = self.read_msg()
            msgs.append((tag, payload))
            if tag == b"Z":
                return msgs

    @staticmethod
    def rows_of(msgs):
        rows = []
        for tag, payload in msgs:
            if tag != b"D":
                continue
            (n,) = struct.unpack_from(">H", payload, 0)
            off = 2
            row = []
            for _ in range(n):
                (ln,) = struct.unpack_from(">i", payload, off)
                off += 4
                if ln < 0:
                    row.append(None)
                else:
                    row.append(payload[off:off + ln].decode())
                    off += ln
            rows.append(tuple(row))
        return rows


def test_pg_wire_end_to_end():
    cluster = LocalCluster(num_tablets=2)
    server = PgServer(cluster)
    try:
        host, port = server.listen("127.0.0.1", 0)
        c = MiniPgClient(host, port)
        c.startup(ssl_probe=True)
        msgs = c.query("CREATE TABLE w (a BIGINT PRIMARY KEY, b TEXT)")
        assert any(t == b"C" for t, _ in msgs)
        c.query("INSERT INTO w (a, b) VALUES (1, 'one'), (2, 'two')")
        msgs = c.query("SELECT a, b FROM w ORDER BY a")
        assert MiniPgClient.rows_of(msgs) == [("1", "one"), ("2", "two")]
        # multi-statement simple query
        msgs = c.query("INSERT INTO w (a, b) VALUES (3, NULL); "
                       "SELECT count(*) FROM w")
        assert MiniPgClient.rows_of(msgs) == [("3",)]
        # NULL comes back with length -1
        msgs = c.query("SELECT b FROM w WHERE a = 3")
        assert MiniPgClient.rows_of(msgs) == [(None,)]
        # errors produce ErrorResponse then ReadyForQuery
        msgs = c.query("SELECT nope FROM missing")
        assert msgs[0][0] == b"E" and msgs[-1][0] == b"Z"
        msgs = c.query("NOT SQL AT ALL")
        assert msgs[0][0] == b"E"
        # duplicate key -> 23505
        msgs = c.query("INSERT INTO w (a, b) VALUES (1, 'dup')")
        assert msgs[0][0] == b"E" and b"23505" in msgs[0][1]
        c.close()
    finally:
        server.shutdown()
        cluster.close()


def test_pg_wire_over_mini_cluster():
    """The full distributed shape: PG wire server -> pggate-style
    processor -> ClientCluster -> master/tserver RPCs."""
    import tempfile

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        server = None
        try:
            mc.wait_tservers_registered()
            server = PgServer(ClientCluster(mc.client("pg-proxy")))
            host, port = server.listen("127.0.0.1", 0)
            c = MiniPgClient(host, port)
            c.startup()
            c.query("CREATE TABLE d (k TEXT PRIMARY KEY, n BIGINT)")
            c.query("INSERT INTO d (k, n) VALUES ('x', 1), ('y', 2), "
                    "('z', 3)")
            msgs = c.query("SELECT k FROM d WHERE n >= 2 ORDER BY k")
            assert MiniPgClient.rows_of(msgs) == [("y",), ("z",)]
            msgs = c.query("SELECT sum(n) FROM d")
            assert MiniPgClient.rows_of(msgs) == [("6",)]
            c.close()
        finally:
            if server is not None:
                server.shutdown()
            mc.shutdown()


def test_sql_transactions_end_to_end():
    """BEGIN/COMMIT/ROLLBACK through the SQL layer over the distributed
    transaction subsystem: snapshot isolation, read-your-writes point
    reads, first-committer-wins conflicts as SerializationFailure."""
    import tempfile

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
    from yugabyte_db_tpu.yql.pgsql.executor import SerializationFailure

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            s1 = PgProcessor(ClientCluster(mc.client("s1")))
            s2 = PgProcessor(ClientCluster(mc.client("s2")))
            s1.execute("CREATE TABLE acct (id TEXT PRIMARY KEY, "
                       "bal BIGINT)")
            s1.execute("INSERT INTO acct (id, bal) VALUES ('a', 100), "
                       "('b', 50)")

            # atomic transfer, invisible to s2 until commit
            s1.execute("BEGIN")
            assert s1.in_txn
            s1.execute("UPDATE acct SET bal = bal - 30 WHERE id = 'a'")
            s1.execute("UPDATE acct SET bal = bal + 30 WHERE id = 'b'")
            # read-your-writes inside the txn
            r = s1.execute("SELECT bal FROM acct WHERE id = 'a'")
            assert r.rows == [(70,)]
            # s2 still sees the pre-txn state
            r = s2.execute("SELECT bal FROM acct WHERE id = 'a'")
            assert r.rows == [(100,)]
            s1.execute("COMMIT")
            assert not s1.in_txn
            r = s2.execute("SELECT bal FROM acct WHERE id = 'b'")
            assert r.rows == [(80,)]

            # rollback discards everything
            s1.execute("BEGIN")
            s1.execute("UPDATE acct SET bal = 0 WHERE id = 'a'")
            s1.execute("ROLLBACK")
            r = s2.execute("SELECT bal FROM acct WHERE id = 'a'")
            assert r.rows == [(70,)]

            # INSERT inside a txn + duplicate detection
            s1.execute("BEGIN")
            s1.execute("INSERT INTO acct (id, bal) VALUES ('c', 1)")
            with pytest.raises(AlreadyPresent):
                s1.execute("INSERT INTO acct (id, bal) VALUES ('c', 2)")
            s1.execute("ROLLBACK")

            # write-write conflict: first committer wins — exactly one
            # side fails, with a transaction-conflict error
            from yugabyte_db_tpu.client.client import TabletOpFailed
            from yugabyte_db_tpu.txn.errors import (TransactionAborted,
                                                    TransactionConflict)

            conflict_errs = (SerializationFailure, TransactionConflict,
                             TransactionAborted, TabletOpFailed)
            s1.execute("BEGIN")
            s2.execute("BEGIN")
            s1.execute("UPDATE acct SET bal = 1 WHERE id = 'a'")
            outcomes = []
            for s, sql in ((s2, "UPDATE acct SET bal = 2 WHERE id = 'a'"),
                           (s1, "COMMIT"), (s2, "COMMIT")):
                try:
                    s.execute(sql)
                    outcomes.append("ok")
                except conflict_errs:
                    outcomes.append("conflict")
                except InvalidArgument:
                    outcomes.append("aborted-block")
            assert "conflict" in outcomes, outcomes
            for s in (s1, s2):
                if s.in_txn:
                    s.execute("ROLLBACK")
        finally:
            mc.shutdown()


def test_pg_wire_transactions():
    """The FE/BE protocol carries transaction state: ReadyForQuery says
    'T' inside a transaction, 'I' when idle."""
    import tempfile

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        server = None
        try:
            mc.wait_tservers_registered()
            server, (host, port) = mc.start_pg_server()
            c = MiniPgClient(host, port)
            c.startup()
            c.query("CREATE TABLE t (k TEXT PRIMARY KEY, v BIGINT)")
            msgs = c.query("BEGIN")
            assert msgs[-1] == (b"Z", b"T")
            c.query("INSERT INTO t (k, v) VALUES ('x', 1)")
            msgs = c.query("SELECT v FROM t WHERE k = 'x'")
            assert MiniPgClient.rows_of(msgs) == [("1",)]
            msgs = c.query("COMMIT")
            assert msgs[-1] == (b"Z", b"I")
            msgs = c.query("SELECT count(*) FROM t")
            assert MiniPgClient.rows_of(msgs) == [("1",)]
            c.close()
        finally:
            if server is not None:
                server.shutdown()
            mc.shutdown()
