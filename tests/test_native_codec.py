"""Native codec parity: the C++ extension must be byte-identical to the
pure-Python codec in both directions, including the arbitrary-precision
fallback seam.

Reference analog: the C++ protobuf serialization of WAL/RPC records
(src/yb/consensus/log.proto) that this codec replaces.
"""

import random

import pytest

from yugabyte_db_tpu.native import yb_codec
from yugabyte_db_tpu.utils import codec

needs_native = pytest.mark.skipif(yb_codec is None,
                                  reason="native codec not built")


def _norm(v):
    """What decode is specified to return for an encoded v."""
    if isinstance(v, tuple):
        return [_norm(x) for x in v]
    if isinstance(v, (bytearray, memoryview)):
        return bytes(v)
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, dict):
        return {_norm(k): _norm(x) for k, x in v.items()}
    return v


def _random_value(rng, depth=0):
    kinds = ["none", "bool", "int", "big", "float", "str", "bytes"]
    if depth < 3:
        kinds += ["list", "dict"] * 2
    k = rng.choice(kinds)
    if k == "none":
        return None
    if k == "bool":
        return rng.random() < 0.5
    if k == "int":
        return rng.randint(-2**63, 2**63 - 1)
    if k == "big":
        return rng.randint(2**63, 2**80) * rng.choice([1, -1])
    if k == "float":
        return rng.uniform(-1e18, 1e18)
    if k == "str":
        return "".join(chr(rng.randint(1, 0x2FF))
                       for _ in range(rng.randint(0, 12)))
    if k == "bytes":
        return bytes(rng.randint(0, 255) for _ in range(rng.randint(0, 12)))
    if k == "list":
        return [_random_value(rng, depth + 1)
                for _ in range(rng.randint(0, 6))]
    return {str(i): _random_value(rng, depth + 1)
            for i in range(rng.randint(0, 5))}


@needs_native
def test_fuzz_parity_both_directions():
    rng = random.Random(20260730)
    for _ in range(300):
        v = _random_value(rng)
        py_bytes = codec._py_encode(v)
        assert codec.decode(py_bytes) == _norm(v)
        try:
            nat_bytes = yb_codec.encode(v)
        except OverflowError:
            continue  # big-int case: native defers to Python
        assert nat_bytes == py_bytes
        assert yb_codec.decode(nat_bytes) == _norm(v)
        assert codec._py_decode(nat_bytes) == _norm(v)


@needs_native
def test_bigint_fallback_is_transparent():
    v = {"hi": [2**100, -2**77, 5]}
    buf = codec.encode(v)  # dispatch must fall back, not raise
    assert codec.decode(buf) == v
    with pytest.raises(OverflowError):
        yb_codec.encode(v)
    with pytest.raises(OverflowError):
        yb_codec.decode(buf)


@needs_native
def test_native_error_contract():
    with pytest.raises(TypeError):
        yb_codec.encode(object())
    with pytest.raises(ValueError):
        yb_codec.decode(b"\x42")          # bad tag
    with pytest.raises(ValueError):
        yb_codec.decode(b"\x05\x0aab")    # truncated string
    with pytest.raises(ValueError):
        yb_codec.decode(b"\x00\x00")      # trailing bytes
    with pytest.raises(ValueError):
        yb_codec.decode(b"\x07\xff\xff\xff\x7f")  # absurd list length


@needs_native
def test_surrogateescape_strings_roundtrip():
    v = b"\xff\x00\x80raw".decode("utf-8", "surrogateescape")
    assert yb_codec.decode(yb_codec.encode(v)) == v
    assert yb_codec.encode(v) == codec._py_encode(v)


def test_python_fallback_disabled_native(monkeypatch):
    monkeypatch.setattr(codec, "_native", None)
    v = {"k": [1, "x", b"y", None, True, 2.5]}
    assert codec.decode(codec.encode(v)) == v
