"""Extended QL type surface: DECIMAL, VARINT, UUID, TIMEUUID, INET,
DATE, TIME, TUPLE, FROZEN.

Mirrors the reference's type semantics (common.proto:65-99 type list;
util/decimal.h comparable ordering; util/uuid.cc timeuuid time-ordering)
as a matrix: byte-comparable key encoding round-trips and sorts
correctly, engine-diff parity on both engines, frontend literals, codec
round-trips, and CQL wire cell formats.
"""

import datetime
import decimal
import random
import uuid as uuid_mod

import pytest

from yugabyte_db_tpu.models.datatypes import DataType, Inet, TimeUuid
from yugabyte_db_tpu.models import encoding as E
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import Predicate, RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.utils import codec
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


D = decimal.Decimal

# Ordered samples per type (strictly ascending in the type's logical
# order) — the encoding matrix asserts memcmp order == this order.
ORDERED = {
    DataType.DECIMAL: [
        D("-1E+10"), D("-200.5"), D("-200.4999"), D("-1"), D("-0.001"),
        D(0), D("0.0001"), D("0.00010000001"), D("1"), D("1.5"),
        D("1.52"), D("2"), D("10"), D("100.001"), D("1E+20"),
    ],
    DataType.VARINT: [
        -(1 << 100), -(1 << 64), -256, -255, -2, -1, 0, 1, 2, 255, 256,
        (1 << 63), (1 << 100),
    ],
    DataType.UUID: sorted(
        [uuid_mod.uuid4() for _ in range(6)]
        + [uuid_mod.UUID(int=0), uuid_mod.UUID(int=(1 << 128) - 1)]),
    DataType.INET: [
        Inet("0.0.0.0"), Inet("10.0.0.1"), Inet("10.0.0.2"),
        Inet("255.255.255.255"), Inet("::1"),
        Inet("2001:db8::1"), Inet("ffff::ffff"),
    ],
    DataType.DATE: [
        datetime.date(1, 1, 1), datetime.date(1969, 12, 31),
        datetime.date(1970, 1, 1), datetime.date(2024, 2, 29),
        datetime.date(9999, 12, 31),
    ],
    DataType.TIME: [
        datetime.time(0, 0, 0), datetime.time(0, 0, 0, 1),
        datetime.time(11, 59, 59, 999999), datetime.time(12, 0, 0),
        datetime.time(23, 59, 59, 999999),
    ],
    DataType.TUPLE: [
        (1, "a"), (1, "b"), (2, "a"), (2, "a", 0), (3,),
    ],
    DataType.FROZEN: [
        [1], [1, 2], [1, 3], [2], [2, 0],
    ],
}


def test_timeuuid_orders_by_time():
    us = []
    for t in (1, 2, 3, 10**9):
        u = uuid_mod.uuid1(node=random.getrandbits(47), clock_seq=0)
        # Rebuild with a forced timestamp so time order is controlled.
        fields = list(u.fields)
        time_hi = (t >> 48) & 0x0FFF
        time_mid = (t >> 32) & 0xFFFF
        time_low = t & 0xFFFFFFFF
        u2 = uuid_mod.UUID(
            fields=(time_low, time_mid, time_hi | 0x1000,
                    fields[3], fields[4], fields[5]))
        us.append(TimeUuid(u2))
    assert [u.u.time for u in us] == sorted(u.u.time for u in us)
    encs = [E.encode_key_component(u, DataType.TIMEUUID) for u in us]
    assert encs == sorted(encs)
    assert us == sorted(us, key=lambda x: x.sort_key())


@pytest.mark.parametrize("dt", list(ORDERED))
def test_key_encoding_order_and_roundtrip(dt):
    vals = ORDERED[dt]
    encs = [E.encode_key_component(v, dt) for v in vals]
    assert encs == sorted(encs), f"{dt.name} encodings out of order"
    assert len(set(encs)) == len(encs)
    for v, enc in zip(vals, encs):
        got, pos = E.decode_key_component(enc, 0)
        assert pos == len(enc)
        if dt == DataType.TUPLE:
            assert tuple(got) == v
        elif dt == DataType.DECIMAL:
            assert got == v.normalize()
        else:
            assert got == v


def test_decimal_trailing_zeros_equal():
    a = E.encode_key_component(D("1.500"), DataType.DECIMAL)
    b = E.encode_key_component(D("1.5"), DataType.DECIMAL)
    assert a == b
    z1 = E.encode_key_component(D("0"), DataType.DECIMAL)
    z2 = E.encode_key_component(D("0.000"), DataType.DECIMAL)
    assert z1 == z2


def test_null_sorts_first_everywhere():
    for dt, vals in ORDERED.items():
        null = E.encode_key_component(None, dt)
        assert all(null < E.encode_key_component(v, dt) for v in vals)


def test_codec_roundtrip_rich_scalars():
    vals = [D("-12.345"), 1 << 90, uuid_mod.uuid4(),
            TimeUuid(uuid_mod.uuid1()), Inet("10.1.2.3"),
            Inet("2001:db8::2"), datetime.date(2024, 7, 31),
            datetime.time(13, 14, 15, 161718)]
    for v in vals:
        got = codec.decode(codec.encode(v))
        assert got == v, v
    # Nested inside the structures RPC payloads use.
    payload = {"rows": [[1, D("2.5"), None], ["x", vals[2]]],
               "u": vals[3]}
    got = codec.decode(codec.encode(payload))
    assert got["rows"][0][1] == D("2.5")
    assert got["u"] == vals[3]


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("dec", DataType.DECIMAL),
        ColumnSchema("vi", DataType.VARINT),
        ColumnSchema("u", DataType.UUID),
        ColumnSchema("tu", DataType.TIMEUUID),
        ColumnSchema("ip", DataType.INET),
        ColumnSchema("dt", DataType.DATE),
        ColumnSchema("tm", DataType.TIME),
        ColumnSchema("tp", DataType.TUPLE),
        ColumnSchema("fz", DataType.FROZEN),
    ], table_id="typed")


def test_engine_diff_typed_values():
    """Both engines store/scan the extended types identically, including
    host-side predicates over them."""
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 16})
    rng = random.Random(3)
    cid = {c.name: c.col_id for c in schema.value_columns}
    ht = 5
    rows = []
    for i in range(120):
        ht += 1
        key = schema.encode_primary_key(
            {"k": f"t{i:04d}"},
            compute_hash_code(schema, {"k": f"t{i:04d}"}))
        cols = {
            cid["dec"]: D(rng.randrange(-10**6, 10**6)) / 100,
            cid["vi"]: rng.randrange(-(1 << 80), 1 << 80),
            cid["u"]: uuid_mod.UUID(int=rng.getrandbits(128)),
            cid["tu"]: TimeUuid(uuid_mod.uuid1(
                node=rng.getrandbits(47))),
            cid["ip"]: Inet(f"10.0.{i % 256}.{(i * 7) % 256}"),
            cid["dt"]: datetime.date(2000 + i % 30, 1 + i % 12,
                                     1 + i % 28),
            cid["tm"]: datetime.time(i % 24, i % 60, i % 60),
            cid["tp"]: [i, f"s{i}"],
            cid["fz"]: [i % 5, i % 3],
        }
        if i % 10 == 0:
            del cols[cid["u"]]  # NULLs
        rows.append(RowVersion(key, ht=ht, liveness=True, columns=cols))
    for e in (cpu, tpu):
        e.apply(rows)
        e.flush()
    spec = ScanSpec(read_ht=ht + 1)
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows
    assert len(a.rows) == 120
    # Host predicates on rich types.
    for pred in (Predicate("dec", ">=", D("0")),
                 Predicate("vi", "<", 0),
                 Predicate("ip", ">=", Inet("10.0.60.0")),
                 Predicate("dt", ">=", datetime.date(2015, 1, 1)),
                 Predicate("tm", "<", datetime.time(12, 0))):
        sa = cpu.scan(ScanSpec(read_ht=ht + 1, predicates=[pred]))
        sb = tpu.scan(ScanSpec(read_ht=ht + 1, predicates=[pred]))
        assert sa.rows == sb.rows, pred
        assert 0 < len(sa.rows) < 120, pred
    # Wire pages fall back to Python serialization and still parity.
    w_a = cpu.scan_batch_wire([ScanSpec(read_ht=ht + 1, limit=30)])
    w_b = tpu.scan_batch_wire([ScanSpec(read_ht=ht + 1, limit=30)])
    assert w_a[0].data == w_b[0].data


def test_typed_key_columns_sort_in_engine():
    """DECIMAL range key: engine scan order follows decimal.h ordering
    (exponent-dominant, trailing-zero-insensitive)."""
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.DECIMAL, ColumnKind.RANGE),
        ColumnSchema("v", DataType.INT32),
    ], table_id="deckey")
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 8})
    vals = ORDERED[DataType.DECIMAL]
    shuffled = list(vals)
    random.Random(1).shuffle(shuffled)
    rows = []
    for i, d in enumerate(shuffled):
        key = schema.encode_primary_key(
            {"k": "x", "r": d}, compute_hash_code(schema, {"k": "x"}))
        rows.append(RowVersion(key, ht=10 + i, liveness=True,
                               columns={schema.column("v").col_id: i}))
    for e in (cpu, tpu):
        e.apply(rows)
        e.flush()
    a = cpu.scan(ScanSpec(read_ht=100, projection=["r"]))
    b = tpu.scan(ScanSpec(read_ht=100, projection=["r"]))
    assert a.rows == b.rows
    assert [r[0] for r in a.rows] == [v.normalize() for v in vals]


def test_cql_frontend_typed_table(tmp_path):
    """CQL DDL/DML with the extended types: string literals coerce,
    values round-trip through the processor, and wire cells encode the
    protocol formats."""
    from yugabyte_db_tpu.yql.cql import QLProcessor
    from yugabyte_db_tpu.yql.cql.processor import LocalCluster
    from yugabyte_db_tpu.models.wirefmt import cql_cell

    cluster = LocalCluster(str(tmp_path), num_tablets=2, engine="tpu",
                           engine_options={"rows_per_block": 16})
    try:
        ql = QLProcessor(cluster)
        ql.execute(
            "CREATE TABLE typed (k text PRIMARY KEY, d decimal, "
            "vi varint, u uuid, tu timeuuid, ip inet, dt date, "
            "tm time, tp tuple<int, text>, fs frozen<set<int>>)")
        u = uuid_mod.uuid4()
        tu = uuid_mod.uuid1()
        ql.execute(
            "INSERT INTO typed (k, d, vi, u, tu, ip, dt, tm, tp, fs) "
            "VALUES ('a', ?, ?, ?, ?, ?, ?, ?, ?, ?)",
            params=["12.340", "123456789012345678901234567890",
                    str(u), str(tu), "10.20.30.40", "2024-07-31",
                    "13:14:15", [7, "x"], {3, 1, 2}])
        r = ql.execute("SELECT d, vi, u, tu, ip, dt, tm, tp, fs "
                       "FROM typed WHERE k = 'a'")
        d, vi, uu, tuu, ip, dt, tm, tp, fs = r.rows[0]
        assert d == D("12.34") or d == D("12.340")
        assert vi == 123456789012345678901234567890
        assert uu == u and tuu == TimeUuid(tu)
        assert ip == Inet("10.20.30.40")
        assert dt == datetime.date(2024, 7, 31)
        assert tm == datetime.time(13, 14, 15)
        assert list(tp) == [7, "x"]
        assert fs == [1, 2, 3]
        # Wire cell formats (protocol §6).
        days = (dt - datetime.date(1970, 1, 1)).days
        assert cql_cell(DataType.DATE, dt) == (
            (days + (1 << 31)).to_bytes(4, "big"))
        assert cql_cell(DataType.TIME, tm) == (
            ((13 * 3600 + 14 * 60 + 15) * 10**9).to_bytes(8, "big"))
        assert cql_cell(DataType.UUID, uu) == u.bytes
        assert cql_cell(DataType.INET, ip) == bytes([10, 20, 30, 40])
        cd = cql_cell(DataType.DECIMAL, D("12.34"))
        assert cd[:4] == (2).to_bytes(4, "big")  # scale 2
        assert int.from_bytes(cd[4:], "big", signed=True) == 1234
        assert cql_cell(DataType.VARINT, -256) == b"\xff\x00"
    finally:
        cluster.close()


def test_pg_frontend_typed_table(tmp_path):
    from yugabyte_db_tpu.yql.pgsql import PgProcessor
    from yugabyte_db_tpu.yql.cql.processor import LocalCluster

    cluster = LocalCluster(str(tmp_path), num_tablets=2, engine="cpu")
    try:
        pg = PgProcessor(cluster)
        pg.execute("CREATE TABLE m (id bigint PRIMARY KEY, "
                   "amt numeric(10,2), u uuid, ip inet, d date, t time)")
        pg.execute("INSERT INTO m (id, amt, u, ip, d, t) VALUES "
                   "(1, '99.95', 'c0fe0000-0000-1000-8000-00805f9b34fb',"
                   " '192.168.0.1', '2023-12-25', '08:30:00')")
        r = pg.execute("SELECT amt, u, ip, d, t FROM m WHERE id = 1")
        amt, u, ip, d, t = r.rows[0]
        assert amt == D("99.95")
        assert str(u) == "c0fe0000-0000-1000-8000-00805f9b34fb"
        assert ip == Inet("192.168.0.1")
        assert d == datetime.date(2023, 12, 25)
        assert t == datetime.time(8, 30)
        # PG text rendering through the wire serializer.
        from yugabyte_db_tpu.models.wirefmt import pg_text

        assert pg_text(amt) == b"99.95"
        assert pg_text(ip) == b"192.168.0.1"
        assert pg_text(d) == b"2023-12-25"
    finally:
        cluster.close()
