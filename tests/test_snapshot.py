"""Tablet snapshots: create / restore / delete, replicated + replayed.

Reference analogs: Tablet::CreateCheckpoint (tablet.h:348) over hard-link
checkpoints (rocksdb checkpoint.cc:53) and the TabletSnapshotOp
CREATE/RESTORE/DELETE RPCs (tserver/backup.proto).
"""

import tempfile

import pytest

from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.tools.admin_client import AdminClient


def _rows(client, table, read_names=("k", "v")):
    s = YBSession(client)
    res = s.scan(table, ScanSpec(projection=list(read_names)))
    return sorted(res.rows)


def test_snapshot_create_restore_delete_cluster():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("kv", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.INT64),
            ], num_tablets=4)
            table = client.open_table("kv")
            s = YBSession(client)
            for i in range(30):
                s.insert(table, {"k": f"a{i:03d}", "v": i})
            s.flush()

            admin = AdminClient(mc.transport.bind("admin"),
                                mc.master_uuids)
            n = admin.snapshot_table("kv", "snap1", "create_snapshot")
            assert n == 4
            snaps = admin.list_snapshots("kv")
            assert all(s == ["snap1"] for s in snaps.values())

            # diverge: overwrite some rows, add others, delete one
            for i in range(10):
                s.insert(table, {"k": f"a{i:03d}", "v": -1})
            for i in range(30, 40):
                s.insert(table, {"k": f"a{i:03d}", "v": i})
            s.delete(table, {"k": "a020"})
            s.flush()
            before = _rows(client, table)
            assert len(before) == 39 and ("a000", -1) in before

            admin.snapshot_table("kv", "snap1", "restore_snapshot")
            after = _rows(client, table)
            assert after == [(f"a{i:03d}", i) for i in range(30)]

            admin.snapshot_table("kv", "snap1", "delete_snapshot")
            assert all(s == [] for s in
                       admin.list_snapshots("kv").values())
            # restoring a deleted snapshot fails cleanly
            from yugabyte_db_tpu.tools.admin_client import AdminError
            with pytest.raises(AdminError):
                admin.snapshot_table("kv", "snap1", "restore_snapshot")
        finally:
            mc.shutdown()


def test_snapshot_survives_restart():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("kv", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.INT64),
            ], num_tablets=2)
            table = client.open_table("kv")
            s = YBSession(client)
            for i in range(10):
                s.insert(table, {"k": f"k{i}", "v": i})
            s.flush()
            admin = AdminClient(mc.transport.bind("admin2"),
                                mc.master_uuids)
            admin.snapshot_table("kv", "s1", "create_snapshot")
            for i in range(10):
                s.insert(table, {"k": f"k{i}", "v": i * 100})
            s.flush()

            victim = next(iter(mc.tservers))
            mc.stop_tserver(victim)
            mc.restart_tserver(victim)
            mc.wait_tservers_registered()

            # snapshot still listed after restart + WAL replay
            snaps = admin.list_snapshots("kv")
            assert all("s1" in v for v in snaps.values())
            admin.snapshot_table("kv", "s1", "restore_snapshot")
            assert _rows(client, table) == [(f"k{i}", i)
                                            for i in range(10)]
        finally:
            mc.shutdown()


def test_snapshot_local_tablet_both_engines():
    import os

    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.models.schema import Schema
    from yugabyte_db_tpu.storage.row_version import RowVersion
    from yugabyte_db_tpu.tablet.tablet import Tablet, TabletMetadata

    for engine in ("cpu", "tpu"):
        if engine == "tpu":
            import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401
        with tempfile.TemporaryDirectory() as root:
            schema = Schema([
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.INT64),
            ], table_id="t")
            cid = schema.column("v").col_id
            meta = TabletMetadata("t-0001", "t", schema, 0, 65536,
                                  engine=engine)
            t = Tablet.create(meta, root, fsync=False)

            def key(i):
                return schema.encode_primary_key(
                    {"k": f"x{i}"},
                    compute_hash_code(schema, {"k": f"x{i}"}))

            t.write([RowVersion(key(i), ht=0, liveness=True,
                                columns={cid: i}) for i in range(8)])
            t.snapshot_op("create_snapshot", "base")
            t.write([RowVersion(key(i), ht=0, liveness=True,
                                columns={cid: -i}) for i in range(8)])
            res = t.scan(ScanSpec(read_ht=t.read_time().value,
                                  projection=["k", "v"]))
            assert all(v <= 0 for _k, v in res.rows)
            t.snapshot_op("restore_snapshot", "base")
            res = t.scan(ScanSpec(read_ht=t.read_time().value,
                                  projection=["k", "v"]))
            assert sorted(v for _k, v in res.rows) == list(range(8))
            assert t.list_snapshots() == ["base"]
            t.snapshot_op("delete_snapshot", "base")
            assert t.list_snapshots() == []
            assert os.path.isdir(t.dir)
            t.close()


def test_master_coordinated_cluster_snapshot():
    """The master drives create/restore/delete across every tablet and
    tracks snapshot state in the replicated sys catalog (reference:
    CreateSnapshot/RestoreSnapshot master RPCs over backup.proto ops);
    the registry survives a full cluster kill + restart, and restore
    after the restart still rolls data back."""
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("kv", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.INT64),
            ], num_tablets=4)
            table = client.open_table("kv")
            s = YBSession(client)
            for i in range(40):
                s.insert(table, {"k": f"a{i:03d}", "v": i})
            s.flush()
            baseline = _rows(client, table)

            admin = AdminClient(mc.transport.bind("admin2"),
                                mc.master_uuids)
            resp = admin.cluster_snapshot("create", "kv", "cs1")
            assert resp["tablets"] == 4
            reg = admin.cluster_snapshot("list")["snapshots"]
            assert reg["cs1"]["state"] == "COMPLETE"
            assert reg["cs1"]["table"] == "kv"

            # unknown snapshot / double create fail cleanly
            with pytest.raises(Exception):
                admin.cluster_snapshot("restore", snapshot_id="nope")
            with pytest.raises(Exception):
                admin.cluster_snapshot("create", "kv", "cs1")

            # diverge
            for i in range(20):
                s.insert(table, {"k": f"a{i:03d}", "v": i + 1000})
            for i in range(40, 55):
                s.insert(table, {"k": f"a{i:03d}", "v": i})
            s.flush()
            assert _rows(client, table) != baseline

            # kill the whole cluster; registry must survive the restart
            mc.shutdown()
            mc = MiniCluster(root, num_tservers=3).start()
            mc.wait_tservers_registered()
            client = mc.client("after-restart")
            table = client.open_table("kv")
            admin = AdminClient(mc.transport.bind("admin3"),
                                mc.master_uuids)
            reg = admin.cluster_snapshot("list")["snapshots"]
            assert reg["cs1"]["state"] == "COMPLETE"

            admin.cluster_snapshot("restore", snapshot_id="cs1")
            assert _rows(client, table) == baseline

            admin.cluster_snapshot("delete", snapshot_id="cs1")
            assert admin.cluster_snapshot("list")["snapshots"] == {}
            with pytest.raises(Exception):
                admin.cluster_snapshot("restore", snapshot_id="cs1")
        finally:
            mc.shutdown()
