"""Topology-aware placement + read replicas.

Reference analog: PlacementInfoPB/CloudInfoPB placement
(src/yb/master/master.proto:172-197) honored by CatalogManager replica
selection and the ClusterLoadBalancer, plus follower/read-replica reads.
"""

import tempfile
import time

import pytest

from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec

COLUMNS = [ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
           ColumnSchema("v", DataType.INT64)]

ZONES = {f"ts-{i}": {"cloud": "c1", "region": "r1", "zone": f"z{i % 3}"}
         for i in range(6)}


def _zone_spread(mc, master, table_name):
    """Per tablet: the set of zones its replicas occupy."""
    t = master.catalog.table_by_name(table_name)
    spreads = []
    for info in master.catalog.tablets_of(t.table_id):
        zones = {master.ts_manager.cloud_info_of(r).get("zone")
                 for r in info.replicas}
        spreads.append((info.tablet_id, info.replicas, zones))
    return spreads


def test_rf3_spreads_across_three_zones():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=6,
                         ts_cloud_info=ZONES).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("zt", COLUMNS, num_tablets=6)
            master = mc.leader_master()
            for tablet_id, replicas, zones in _zone_spread(mc, master,
                                                           "zt"):
                assert len(zones) == 3, (tablet_id, replicas, zones)
        finally:
            mc.shutdown()


def test_zone_kill_rereplicates_to_survivors():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=6, ts_cloud_info=ZONES,
                         ts_unresponsive_timeout_s=1.0).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("zk", COLUMNS, num_tablets=4)
            table = client.open_table("zk")
            s = YBSession(client)
            for i in range(200):
                s.insert(table, {"k": f"r{i:04d}", "v": i})
            s.flush()
            # Kill zone z0 entirely (ts-0 and ts-3).
            mc.stop_tserver("ts-0")
            mc.stop_tserver("ts-3")
            master = mc.leader_master()
            dead = {"ts-0", "ts-3"}
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                spreads = _zone_spread(mc, master, "zk")
                if all(not (set(reps) & dead) for _t, reps, _z in spreads):
                    break
                time.sleep(0.3)
            spreads = _zone_spread(mc, master, "zk")
            for tablet_id, replicas, zones in spreads:
                assert not (set(replicas) & dead), (tablet_id, replicas)
                # Two zones survive: best possible spread is 2 zones.
                assert len(zones) == 2, (tablet_id, replicas, zones)
            # Ack'd data survives the zone loss.
            res = YBSession(client).scan(
                table, ScanSpec(projection=["k", "v"]))
            assert len(res.rows) == 200
        finally:
            mc.shutdown()


def test_stale_read_prefers_same_zone_replica():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=6,
                         ts_cloud_info=ZONES).start()
        try:
            mc.wait_tservers_registered()
            admin = mc.client()
            admin.create_table("sr", COLUMNS, num_tablets=2)
            table = admin.open_table("sr")
            s = YBSession(admin)
            for i in range(50):
                s.insert(table, {"k": f"s{i:03d}", "v": i})
            s.flush()
            client = mc.client("zoned", cloud_info=ZONES["ts-1"])
            sess = YBSession(client)
            # Spy on transport targets to verify same-zone routing.
            targets = []
            inner_send = client.transport.send

            def spy(dst, method, payload, timeout=5.0):
                if method == "ts.scan":
                    targets.append(dst)
                return inner_send(dst, method, payload, timeout)

            client.transport.send = spy
            # Stale reads serve a replica's APPLIED state: allow the
            # follower a moment to catch up (bounded staleness).
            deadline = time.monotonic() + 10.0
            while True:
                targets.clear()
                res = sess.scan(table, ScanSpec(projection=["k", "v"]),
                                stale_ok=True)
                if len(res.rows) == 50 or time.monotonic() > deadline:
                    break
                time.sleep(0.2)
            assert len(res.rows) == 50
            same_zone = {u for u, ci in ZONES.items()
                         if ci == ZONES["ts-1"]}
            locs = client.meta_cache.locations("sr")
            for dst, loc in zip(targets, locs.tablets):
                expected = {r for r in loc.replicas if r in same_zone}
                if expected:  # a same-zone replica exists: must be used
                    assert dst in expected, (dst, loc.replicas)
            # Strong read still routes to the leader and agrees.
            res2 = sess.scan(table, ScanSpec(projection=["k", "v"]))
            assert sorted(res2.rows) == sorted(res.rows)
        finally:
            mc.shutdown()


def test_unlabeled_cluster_still_places():
    """Zone-awareness must not regress unlabeled clusters (everyone in
    the one empty zone: pure least-loaded spread)."""
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("ul", COLUMNS, num_tablets=4)
            master = mc.leader_master()
            for _t, replicas, _z in _zone_spread(mc, master, "ul"):
                assert len(set(replicas)) == 3
        finally:
            mc.shutdown()


def test_stale_aggregate_honors_zone_routing():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=6,
                         ts_cloud_info=ZONES).start()
        try:
            mc.wait_tservers_registered()
            admin = mc.client()
            admin.create_table("sa", COLUMNS, num_tablets=2)
            table = admin.open_table("sa")
            s = YBSession(admin)
            for i in range(60):
                s.insert(table, {"k": f"a{i:03d}", "v": i})
            s.flush()
            from yugabyte_db_tpu.storage.scan_spec import AggSpec
            client = mc.client("zoned", cloud_info=ZONES["ts-2"])
            sess = YBSession(client)
            spec = ScanSpec(aggregates=[AggSpec("count", None),
                                        AggSpec("sum", "v")])
            deadline = time.monotonic() + 10.0
            while True:
                res = sess.scan(table, spec, stale_ok=True)
                if res.rows[0] == (60, sum(range(60))) or \
                        time.monotonic() > deadline:
                    break
                time.sleep(0.2)
            assert res.rows[0] == (60, sum(range(60)))
        finally:
            mc.shutdown()
