"""Order-preservation property tests for the DocKey encoding.

Reference test analog: src/yb/docdb/doc_key-test.cc and
primitive_value-test.cc (encode/decode round-trip + ordering).
"""

import random

import numpy as np
import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import (
    decode_doc_key,
    decode_key_component,
    encode_doc_key,
    encode_doc_key_prefix,
    encode_key_component,
    prefix_successor,
)
from yugabyte_db_tpu.utils.planes import key_prefix_planes


def _rand_value(dtype, rnd):
    if dtype == DataType.INT64:
        return rnd.randrange(-(1 << 62), 1 << 62)
    if dtype == DataType.INT32:
        return rnd.randrange(-(1 << 31), 1 << 31)
    if dtype == DataType.DOUBLE:
        return rnd.choice([
            rnd.uniform(-1e18, 1e18), 0.0, -0.0, 1.5, -1.5,
            float("inf"), float("-inf"),
        ])
    if dtype == DataType.BOOL:
        return rnd.choice([True, False])
    if dtype == DataType.STRING:
        n = rnd.randrange(0, 20)
        return "".join(rnd.choice("ab\x01cde\x7fxyz0") for _ in range(n))
    if dtype == DataType.BINARY:
        n = rnd.randrange(0, 20)
        return bytes(rnd.randrange(0, 256) for _ in range(n))
    raise AssertionError(dtype)


@pytest.mark.parametrize("dtype", [
    DataType.INT64, DataType.INT32, DataType.DOUBLE, DataType.BOOL,
    DataType.STRING, DataType.BINARY,
])
def test_component_roundtrip_and_order(dtype):
    rnd = random.Random(42 + dtype)
    values = [_rand_value(dtype, rnd) for _ in range(300)]
    encoded = [encode_key_component(v, dtype) for v in values]
    # Round trip.
    for v, e in zip(values, encoded):
        decoded, pos = decode_key_component(e, 0)
        assert pos == len(e)
        if dtype == DataType.DOUBLE:
            assert decoded == v or (np.isnan(decoded) and np.isnan(v))
        else:
            assert decoded == v
    # Order preservation: byte order == logical order.
    pairs = sorted(zip(values, encoded), key=lambda p: p[0])
    for (v1, e1), (v2, e2) in zip(pairs, pairs[1:]):
        if v1 == v2:
            assert e1 == e2, f"{v1!r} == {v2!r} but encodings differ"
        else:
            assert e1 < e2, f"{v1!r} < {v2!r} but {e1!r} >= {e2!r}"


def test_null_sorts_first():
    for dtype in (DataType.INT64, DataType.STRING, DataType.DOUBLE, DataType.BOOL):
        null_e = encode_key_component(None, dtype)
        small = {DataType.INT64: -(1 << 62), DataType.STRING: "",
                 DataType.DOUBLE: float("-inf"), DataType.BOOL: False}[dtype]
        assert null_e < encode_key_component(small, dtype)


def test_doc_key_roundtrip():
    key = encode_doc_key(
        0xBEEF,
        [("user7", DataType.STRING), (42, DataType.INT64)],
        [("2020-01-01", DataType.STRING), (7, DataType.INT64)],
    )
    h, hashed, ranges = decode_doc_key(key)
    assert h == 0xBEEF
    assert hashed == ["user7", 42]
    assert ranges == ["2020-01-01", 7]


def test_doc_key_composite_ordering():
    """Multi-component keys sort component-wise; shorter prefixes sort first."""
    def k(h, hs, rs):
        return encode_doc_key(h, [(v, DataType.STRING) for v in hs],
                              [(v, DataType.INT64) for v in rs])

    assert k(1, ["a"], [1]) < k(2, ["a"], [0])          # hash code dominates
    assert k(1, ["a"], [1]) < k(1, ["b"], [0])          # then hashed cols
    assert k(1, ["a"], [1]) < k(1, ["a"], [2])          # then range cols
    assert k(1, ["a"], []) < k(1, ["a"], [-(1 << 62)])  # prefix-group sorts first

    # A key prefix is a byte-prefix of every key extending it.
    prefix = encode_doc_key_prefix(1, [("a", DataType.STRING)], [])
    full = k(1, ["a"], [123, 456][:1])
    assert full.startswith(prefix)


def test_prefix_successor():
    assert prefix_successor(b"ab") == b"ac"
    assert prefix_successor(b"a\xff") == b"b"
    assert prefix_successor(b"\xff\xff") == b""
    p = encode_doc_key_prefix(3, [("x", DataType.STRING)], [])
    s = prefix_successor(p)
    assert p < s


def test_key_prefix_planes_order_matches_bytes():
    """int32-plane signed-lex order == byte order on the prefix width."""
    rnd = random.Random(7)
    keys = []
    for _ in range(500):
        h = rnd.randrange(0, 1 << 16)
        u = _rand_value(DataType.STRING, rnd)
        r = _rand_value(DataType.INT64, rnd)
        keys.append(encode_doc_key(h, [(u, DataType.STRING)], [(r, DataType.INT64)]))
    planes = key_prefix_planes(keys, num_words=8)  # 32-byte prefix

    def plane_tuple(i):
        return tuple(int(w) for w in planes[i])

    order_bytes = sorted(range(len(keys)), key=lambda i: keys[i][:32])
    order_planes = sorted(range(len(keys)), key=plane_tuple)
    # Same order up to ties in the 32-byte prefix.
    for a, b in zip(order_bytes, order_planes):
        assert keys[a][:32] == keys[b][:32]
