"""PgDocOp-style prefetching (reference: pg_doc_op.h:111): multi-tablet
SELECTs keep several tablet reads in flight; results stay identical and
arrive in tablet order."""

import threading
import time

import pytest

from yugabyte_db_tpu.yql.pgsql import PgProcessor
from yugabyte_db_tpu.yql.cql.processor import LocalCluster


@pytest.fixture
def pg(tmp_path):
    cluster = LocalCluster(str(tmp_path), num_tablets=4, engine="cpu")
    proc = PgProcessor(cluster)
    yield proc
    cluster.close()


def seed(pg, n=400):
    pg.execute("CREATE TABLE big (id bigint PRIMARY KEY, g text, "
               "v bigint)")
    for i in range(n):
        pg.execute(f"INSERT INTO big (id, g, v) VALUES "
                   f"({i}, 'g{i % 3}', {i * 7})")


def test_prefetch_overlaps_tablet_scans(pg):
    seed(pg)
    handle = pg.cluster.table("big")
    inflight = [0]
    peak = [0]
    lock = threading.Lock()

    orig = {}
    for t in handle.tablets:
        orig[id(t)] = t.scan

        def make(t):
            inner = t.scan

            def slow_scan(spec):
                with lock:
                    inflight[0] += 1
                    peak[0] = max(peak[0], inflight[0])
                try:
                    time.sleep(0.05)
                    return inner(spec)
                finally:
                    with lock:
                        inflight[0] -= 1
            return slow_scan
        t.scan = make(t)

    r = pg.execute("SELECT count(*), sum(v) FROM big")
    assert r.rows == [(400, sum(i * 7 for i in range(400)))]
    assert peak[0] > 1, "tablet scans did not overlap"

    peak[0] = 0
    r = pg.execute("SELECT id FROM big WHERE v >= 0 ORDER BY id "
                   "LIMIT 5")
    assert [x[0] for x in r.rows] == [0, 1, 2, 3, 4]
    assert peak[0] > 1


def test_prefetch_results_match_sequential(pg):
    seed(pg, n=200)
    r = pg.execute("SELECT g, count(*), sum(v), min(v), max(v) FROM big "
                   "GROUP BY g ORDER BY g")
    assert len(r.rows) == 3
    assert sum(row[1] for row in r.rows) == 200
    r2 = pg.execute("SELECT id, v FROM big WHERE id < 50 ORDER BY id")
    assert len(r2.rows) == 50
