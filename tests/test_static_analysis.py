"""yb-lint: the tier-1 gate plus per-rule unit coverage.

The gate runs the full analysis over the committed tree and fails on
any violation that is neither suppressed inline nor grandfathered in
``yugabyte_db_tpu/analysis/baseline.json`` — new code must come in
lint-clean. The unit tests feed each rule a known-bad fragment and
assert it fires (and that ``# yb-lint: disable=`` is honored).
"""

import json
import os
import subprocess
import sys
import textwrap

from yugabyte_db_tpu.analysis import (
    all_rules,
    load_baseline,
    run_analysis,
)
from yugabyte_db_tpu.analysis.core import apply_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "yugabyte_db_tpu")


def lint(tmp_path, files):
    """Write {rel: code} fixtures and lint the fixture package."""
    for rel, code in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    return run_analysis([str(tmp_path / "yugabyte_db_tpu")],
                        repo_root=str(tmp_path))


def fired(result, rule):
    return [v for v in result.violations if v.rule == rule]


# -- the tier-1 gate ---------------------------------------------------------

def test_tree_is_lint_clean():
    """Zero non-baselined violations over the whole package. On failure:
    fix the code, suppress with a justified `# yb-lint: disable=`, or
    (for deliberate grandfathering only) regenerate the baseline."""
    result = run_analysis([PKG], repo_root=REPO_ROOT,
                          baseline=load_baseline())
    assert result.ok, "new yb-lint violations:\n" + "\n".join(
        v.render() for v in result.violations)
    assert result.files_checked > 100


def test_cli_json_clean_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=json", PKG],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["ok"] and data["violations"] == []


def test_cli_nonzero_on_violations(tmp_path):
    """The acceptance fixtures: a layering violation, a host sync in an
    ops kernel, an unlocked write to a guarded attribute, and a bare
    except-pass — each reported with file, line, and rule id, and the
    CLI exits non-zero."""
    fixtures = {
        "yugabyte_db_tpu/storage/bad_layer.py": """\
            from yugabyte_db_tpu.yql.pgsql import executor
        """,
        "yugabyte_db_tpu/ops/bad_kernel.py": """\
            import jax

            @jax.jit
            def kernel(x):
                return x.item()
        """,
        "yugabyte_db_tpu/tablet/bad_locks.py": """\
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def incr(self):
                    with self._lock:
                        self._n += 1

                def reset(self):
                    self._n = 0
        """,
        "yugabyte_db_tpu/util/bad_errors.py": """\
            def f():
                try:
                    g()
                except Exception:
                    pass
        """,
    }
    for rel, code in fixtures.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=json", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    by_rule = {v["rule"]: v for v in data["violations"]}
    expect = {
        "layering/upward-import": "bad_layer.py",
        "jax/host-sync-item": "bad_kernel.py",
        "locks/unguarded-write": "bad_locks.py",
        "errors/swallowed-exception": "bad_errors.py",
    }
    for rule, fname in expect.items():
        assert rule in by_rule, (rule, data["violations"])
        v = by_rule[rule]
        assert v["file"].endswith(fname)
        assert isinstance(v["line"], int) and v["line"] > 0


def test_list_rules_names_all_families():
    names = set(all_rules())
    for family in ("layering/", "jax/", "locks/", "errors/"):
        assert any(n.startswith(family) for n in names), names


# -- layering ----------------------------------------------------------------

def test_layering_upward_import_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        from yugabyte_db_tpu.consensus.raft import RaftConsensus
    """})
    (v,) = fired(res, "layering/upward-import")
    assert v.line == 1 and "rpc -> consensus" in v.message


def test_layering_forbidden_edge_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/yql/bad.py": """\
        import yugabyte_db_tpu.ops.scan
    """})
    assert fired(res, "layering/forbidden-import")


def test_layering_relative_and_lazy_imports_resolve(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/deep/bad.py": """\
        def f():
            from ...yql import pgsql  # lazy does not launder the edge
            return pgsql
    """})
    assert fired(res, "layering/upward-import")


def test_layering_downward_and_type_checking_ok(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/yql/good.py": """\
        from typing import TYPE_CHECKING

        from yugabyte_db_tpu.storage import engine

        if TYPE_CHECKING:
            from yugabyte_db_tpu.ops import scan  # type-only: no edge
    """})
    assert not fired(res, "layering/upward-import")
    assert not fired(res, "layering/forbidden-import")


def test_layering_suppression_respected(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        from yugabyte_db_tpu.consensus import raft  # yb-lint: disable=layering/upward-import
    """})
    assert not res.violations and res.suppressed == 1


# -- jax hygiene -------------------------------------------------------------

def test_jax_item_in_jitted_function(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        @jax.jit
        def k(x):
            return x.item()
    """})
    (v,) = fired(res, "jax/host-sync-item")
    assert v.line == 5


def test_jax_item_via_named_tracing_call(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        def body(x):
            return x.sum().item()

        run = jax.jit(body)
    """})
    assert fired(res, "jax/host-sync-item")


def test_jax_cast_on_tracer(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        @jax.jit
        def k(x):
            return float(x)
    """})
    assert fired(res, "jax/host-sync-cast")


def test_jax_shape_math_is_not_a_sync(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/good.py": """\
        import jax

        @jax.jit
        def k(x):
            return int(x.shape[0]) + float(len(x.shape))
    """})
    assert not fired(res, "jax/host-sync-cast")


def test_jax_host_transfer_in_trace(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax
        import numpy as np

        @jax.jit
        def k(x):
            return np.asarray(x)
    """})
    assert fired(res, "jax/host-transfer")


def test_jax_module_scope_jnp(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax.numpy as jnp

        ZERO = jnp.int32(0)
    """})
    assert fired(res, "jax/module-scope-jnp")


def test_jax_block_until_ready_outside_bench(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        def fetch(x):
            return x.block_until_ready()
    """})
    assert fired(res, "jax/block-until-ready")


def test_jax_mutable_static_arg_default(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        def k(x, opts=[1, 2]):
            return x

        run = jax.jit(k, static_argnums=(1,))
    """})
    assert fired(res, "jax/unhashable-static-arg")


def test_jax_suppression_respected(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/ok.py": """\
        import jax

        @jax.jit
        def k(x):
            # yb-lint: disable=jax/host-sync-item
            return x.item()
    """})
    assert not fired(res, "jax/host-sync-item") and res.suppressed == 1


# -- lock discipline ---------------------------------------------------------

LOCKED_CLASS = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def incr(self):
            with self._lock:
                self._n += 1

        def reset(self):
            self._n = 0{suffix}
"""


def test_unguarded_write_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py":
                          LOCKED_CLASS.format(suffix="")})
    (v,) = fired(res, "locks/unguarded-write")
    assert "C.reset writes self._n" in v.message and v.line == 13


def test_unguarded_write_suppression(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/tablet/ok.py": LOCKED_CLASS.format(
            suffix="  # yb-lint: disable=locks/unguarded-write")})
    assert not fired(res, "locks/unguarded-write")
    assert res.suppressed == 1


def test_locked_suffix_convention_counts_as_held(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def incr(self):
                with self._lock:
                    self._reset_locked()

            def _reset_locked(self):
                self._n = 0
    """})
    assert not fired(res, "locks/unguarded-write")


def test_condition_aliases_its_lock(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._n = 0

            def incr(self):
                with self._lock:
                    self._n += 1

            def wake(self):
                with self._cv:
                    self._n = 0
    """})
    assert not fired(res, "locks/unguarded-write")


def test_abba_lock_order(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """})
    (v,) = fired(res, "locks/inconsistent-order")
    assert "ABBA" in v.message


# -- error discipline --------------------------------------------------------

def test_swallowed_exception_fires_and_suppresses(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/bad.py": """\
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except Exception:  # yb-lint: disable=errors
                pass
    """})
    (v,) = fired(res, "errors/swallowed-exception")
    assert v.line == 4
    assert res.suppressed == 1


def test_narrow_except_pass_is_fine(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/ok.py": """\
        def f():
            try:
                g()
            except (OSError, ValueError):
                pass
    """})
    assert not fired(res, "errors/swallowed-exception")


def test_handler_bare_return_and_fall_off_end(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        class Svc:
            def _h_ping(self, body):
                if body:
                    return {"ok": True}
                return

            def _h_pong(self, body):
                if body:
                    return {"ok": True}

            def _h_good(self, body):
                return {"ok": True}
    """})
    vs = fired(res, "errors/handler-returns-none")
    assert {v.fingerprint for v in vs} == {"Svc._h_ping", "Svc._h_pong"}


def test_unguarded_daemon_thread(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/server/bad.py": """\
        import threading

        class S:
            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()
                threading.Thread(target=self._safe, daemon=True).start()

            def _loop(self):
                while True:
                    step()

            def _safe(self):
                try:
                    while True:
                        step()
                except Exception:
                    log()
    """})
    (v,) = fired(res, "errors/unguarded-daemon-thread")
    assert "_loop" in v.message


# -- suppression + baseline machinery ----------------------------------------

def test_standalone_suppression_covers_next_line(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/ok.py": """\
        # yb-lint: disable=all
        from yugabyte_db_tpu.consensus import raft
    """})
    assert not res.violations and res.suppressed >= 1


def test_baseline_budget_absorbs_only_grandfathered_count(tmp_path):
    files = {"yugabyte_db_tpu/util/two.py": """\
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except Exception:
                pass
    """}
    res = lint(tmp_path, files)
    raw = fired(res, "errors/swallowed-exception")
    assert len(raw) == 2
    # Both share one baseline key (same file/rule/fingerprint). A budget
    # of 1 absorbs only the first in line order: the file grew a fresh
    # violation past its grandfathered count.
    assert raw[0].baseline_key() == raw[1].baseline_key()
    budget = {raw[0].baseline_key(): 1}
    fresh, absorbed = apply_baseline(raw, budget)
    assert absorbed == 1
    assert [v.line for v in fresh] == [max(v.line for v in raw)]
