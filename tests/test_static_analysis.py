"""yb-lint: the tier-1 gate plus per-rule unit coverage.

The gate runs the full analysis over the committed tree and fails on
any violation that is neither suppressed inline nor grandfathered in
``yugabyte_db_tpu/analysis/baseline.json`` — new code must come in
lint-clean. The unit tests feed each rule a known-bad fragment and
assert it fires (and that ``# yb-lint: disable=`` is honored).
"""

import json
import os
import subprocess
import sys
import textwrap

from yugabyte_db_tpu.analysis import (
    all_project_rules,
    all_rules,
    load_baseline,
    run_analysis,
)
from yugabyte_db_tpu.analysis.core import apply_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO_ROOT, "yugabyte_db_tpu")


def lint(tmp_path, files):
    """Write {rel: code} fixtures and lint the fixture package."""
    for rel, code in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    return run_analysis([str(tmp_path / "yugabyte_db_tpu")],
                        repo_root=str(tmp_path))


def fired(result, rule):
    return [v for v in result.violations if v.rule == rule]


# -- the tier-1 gate ---------------------------------------------------------

def test_tree_is_lint_clean():
    """Zero non-baselined violations over the whole package. On failure:
    fix the code, suppress with a justified `# yb-lint: disable=`, or
    (for deliberate grandfathering only) regenerate the baseline."""
    result = run_analysis([PKG], repo_root=REPO_ROOT,
                          baseline=load_baseline())
    assert result.ok, "new yb-lint violations:\n" + "\n".join(
        v.render() for v in result.violations)
    assert result.files_checked > 100


def test_cli_json_clean_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=json", PKG],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["ok"] and data["violations"] == []


def test_cli_nonzero_on_violations(tmp_path):
    """The acceptance fixtures: a layering violation, a host sync in an
    ops kernel, an unlocked write to a guarded attribute, and a bare
    except-pass — each reported with file, line, and rule id, and the
    CLI exits non-zero."""
    fixtures = {
        "yugabyte_db_tpu/storage/bad_layer.py": """\
            from yugabyte_db_tpu.yql.pgsql import executor
        """,
        "yugabyte_db_tpu/ops/bad_kernel.py": """\
            import jax

            @jax.jit
            def kernel(x):
                return x.item()
        """,
        "yugabyte_db_tpu/tablet/bad_locks.py": """\
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def incr(self):
                    with self._lock:
                        self._n += 1

                def reset(self):
                    self._n = 0
        """,
        "yugabyte_db_tpu/util/bad_errors.py": """\
            def f():
                try:
                    g()
                except Exception:
                    pass
        """,
    }
    for rel, code in fixtures.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(code))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=json", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    by_rule = {v["rule"]: v for v in data["violations"]}
    expect = {
        "layering/upward-import": "bad_layer.py",
        "jax/host-sync-item": "bad_kernel.py",
        "locks/unguarded-write": "bad_locks.py",
        "errors/swallowed-exception": "bad_errors.py",
    }
    for rule, fname in expect.items():
        assert rule in by_rule, (rule, data["violations"])
        v = by_rule[rule]
        assert v["file"].endswith(fname)
        assert isinstance(v["line"], int) and v["line"] > 0


def test_list_rules_names_all_families():
    names = set(all_rules())
    for family in ("layering/", "jax/", "locks/", "errors/"):
        assert any(n.startswith(family) for n in names), names
    inames = set(all_project_rules())
    for family in ("ilocks/", "ierrors/", "irpc/", "ijax/", "iraces/",
                   "ijit/", "ires/", "iholds/"):
        assert any(n.startswith(family) for n in inames), inames


def test_baseline_is_empty():
    """Policy: the grandfather list is burned down to nothing — CI fails
    on ANY new entry. Suppress inline (with justification) or fix; do
    not regenerate the baseline with content."""
    assert load_baseline() == {}


# -- layering ----------------------------------------------------------------

def test_layering_upward_import_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        from yugabyte_db_tpu.consensus.raft import RaftConsensus
    """})
    (v,) = fired(res, "layering/upward-import")
    assert v.line == 1 and "rpc -> consensus" in v.message


def test_layering_forbidden_edge_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/yql/bad.py": """\
        import yugabyte_db_tpu.ops.scan
    """})
    assert fired(res, "layering/forbidden-import")


def test_layering_relative_and_lazy_imports_resolve(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/deep/bad.py": """\
        def f():
            from ...yql import pgsql  # lazy does not launder the edge
            return pgsql
    """})
    assert fired(res, "layering/upward-import")


def test_layering_downward_and_type_checking_ok(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/yql/good.py": """\
        from typing import TYPE_CHECKING

        from yugabyte_db_tpu.storage import engine

        if TYPE_CHECKING:
            from yugabyte_db_tpu.ops import scan  # type-only: no edge
    """})
    assert not fired(res, "layering/upward-import")
    assert not fired(res, "layering/forbidden-import")


def test_layering_suppression_respected(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        from yugabyte_db_tpu.consensus import raft  # yb-lint: disable=layering/upward-import
    """})
    assert not res.violations and res.suppressed == 1


# -- jax hygiene -------------------------------------------------------------

def test_jax_item_in_jitted_function(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        @jax.jit
        def k(x):
            return x.item()
    """})
    (v,) = fired(res, "jax/host-sync-item")
    assert v.line == 5


def test_jax_item_via_named_tracing_call(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        def body(x):
            return x.sum().item()

        run = jax.jit(body)
    """})
    assert fired(res, "jax/host-sync-item")


def test_jax_cast_on_tracer(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        @jax.jit
        def k(x):
            return float(x)
    """})
    assert fired(res, "jax/host-sync-cast")


def test_jax_shape_math_is_not_a_sync(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/good.py": """\
        import jax

        @jax.jit
        def k(x):
            return int(x.shape[0]) + float(len(x.shape))
    """})
    assert not fired(res, "jax/host-sync-cast")


def test_jax_host_transfer_in_trace(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax
        import numpy as np

        @jax.jit
        def k(x):
            return np.asarray(x)
    """})
    assert fired(res, "jax/host-transfer")


def test_jax_module_scope_jnp(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax.numpy as jnp

        ZERO = jnp.int32(0)
    """})
    assert fired(res, "jax/module-scope-jnp")


def test_jax_block_until_ready_outside_bench(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        def fetch(x):
            return x.block_until_ready()
    """})
    assert fired(res, "jax/block-until-ready")


def test_jax_mutable_static_arg_default(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        def k(x, opts=[1, 2]):
            return x

        run = jax.jit(k, static_argnums=(1,))
    """})
    assert fired(res, "jax/unhashable-static-arg")


def test_jax_suppression_respected(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/ok.py": """\
        import jax

        @jax.jit
        def k(x):
            # yb-lint: disable=jax/host-sync-item
            return x.item()
    """})
    assert not fired(res, "jax/host-sync-item") and res.suppressed == 1


# -- lock discipline ---------------------------------------------------------

LOCKED_CLASS = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def incr(self):
            with self._lock:
                self._n += 1

        def reset(self):
            self._n = 0{suffix}
"""


def test_unguarded_write_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py":
                          LOCKED_CLASS.format(suffix="")})
    (v,) = fired(res, "locks/unguarded-write")
    assert "C.reset writes self._n" in v.message and v.line == 13


def test_unguarded_write_suppression(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/tablet/ok.py": LOCKED_CLASS.format(
            suffix="  # yb-lint: disable=locks/unguarded-write")})
    assert not fired(res, "locks/unguarded-write")
    assert res.suppressed == 1


def test_locked_suffix_convention_counts_as_held(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def incr(self):
                with self._lock:
                    self._reset_locked()

            def _reset_locked(self):
                self._n = 0
    """})
    assert not fired(res, "locks/unguarded-write")


def test_condition_aliases_its_lock(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._n = 0

            def incr(self):
                with self._lock:
                    self._n += 1

            def wake(self):
                with self._cv:
                    self._n = 0
    """})
    assert not fired(res, "locks/unguarded-write")


def test_abba_lock_order(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """})
    (v,) = fired(res, "locks/inconsistent-order")
    assert "ABBA" in v.message


# -- error discipline --------------------------------------------------------

def test_swallowed_exception_fires_and_suppresses(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/bad.py": """\
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except Exception:  # yb-lint: disable=errors
                pass
    """})
    (v,) = fired(res, "errors/swallowed-exception")
    assert v.line == 4
    assert res.suppressed == 1


def test_narrow_except_pass_is_fine(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/ok.py": """\
        def f():
            try:
                g()
            except (OSError, ValueError):
                pass
    """})
    assert not fired(res, "errors/swallowed-exception")


def test_handler_bare_return_and_fall_off_end(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py": """\
        class Svc:
            def _h_ping(self, body):
                if body:
                    return {"ok": True}
                return

            def _h_pong(self, body):
                if body:
                    return {"ok": True}

            def _h_good(self, body):
                return {"ok": True}
    """})
    vs = fired(res, "errors/handler-returns-none")
    assert {v.fingerprint for v in vs} == {"Svc._h_ping", "Svc._h_pong"}


def test_unguarded_daemon_thread(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/server/bad.py": """\
        import threading

        class S:
            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()
                threading.Thread(target=self._safe, daemon=True).start()

            def _loop(self):
                while True:
                    step()

            def _safe(self):
                try:
                    while True:
                        step()
                except Exception:
                    log()
    """})
    (v,) = fired(res, "errors/unguarded-daemon-thread")
    assert "_loop" in v.message


# -- interprocedural: ilocks -------------------------------------------------

def test_ilocks_cross_function_abba_fires(tmp_path):
    """Thread 1 runs one() (A, then B via the helper), thread 2 runs
    two() (B then A) — neither method nests inconsistently on its own,
    so only the call-graph pass can see the deadlock."""
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._take_b()

            def _take_b(self):
                with self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """})
    (v,) = fired(res, "ilocks/abba-cycle")
    assert "ABBA" in v.message and "C._a" in v.message
    assert not fired(res, "locks/inconsistent-order")  # intra can't see it


def test_ilocks_consistent_order_through_calls_is_clean(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._take_b()

            def _take_b(self):
                with self._b:
                    pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """})
    assert not fired(res, "ilocks/abba-cycle")


def test_ilocks_recursive_acquire_through_call(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    self._n += 1
    """})
    (v,) = fired(res, "ilocks/recursive-lock")
    assert "C.outer" in v.message and "self-deadlock" in v.message


def test_ilocks_rlock_reentry_is_legal(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()
                self._n = 0

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    self._n += 1
    """})
    assert not fired(res, "ilocks/recursive-lock")


# -- interprocedural: ierrors ------------------------------------------------

IERRORS_CLASS = """\
    class Sender:
        def __init__(self, transport):
            self.transport = transport

        def send_op(self, peer):
            return self.transport.send(peer, "m", {{}}, timeout=1.0)

        def caller(self, peer):
            {body}
"""


def test_ierrors_dropped_chain_fires(tmp_path):
    """send_op returns the raw RPC response (the error channel); the
    caller discards it, so a not_leader/not_found answer vanishes."""
    res = lint(tmp_path, {"yugabyte_db_tpu/client/bad.py":
                          IERRORS_CLASS.format(body="self.send_op(peer)")})
    (v,) = fired(res, "ierrors/dropped-error-result")
    assert "Sender.caller" in v.message and "send_op" in v.message


def test_ierrors_checked_result_is_clean(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/client/ok.py":
                          IERRORS_CLASS.format(body="""\
resp = self.send_op(peer)
            if resp.get("code") != "ok":
                raise RuntimeError(resp)""")})
    assert not fired(res, "ierrors/dropped-error-result")


def test_ierrors_direct_transport_discard_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/client/bad.py": """\
        class Fan:
            def __init__(self, transport):
                self.transport = transport

            def blast(self, peer):
                self.transport.send(peer, "m", {}, timeout=1.0)
    """})
    (v,) = fired(res, "ierrors/dropped-error-result")
    assert "transport.send" in v.message


def test_ierrors_code_checking_wrapper_is_not_error_channel(tmp_path):
    """A tablet_rpc-style wrapper that inspects the code and raises
    converts the error channel to exceptions — discarding ITS result
    is safe."""
    res = lint(tmp_path, {"yugabyte_db_tpu/client/ok.py": """\
        class Sender:
            def __init__(self, transport):
                self.transport = transport

            def checked_rpc(self, peer):
                resp = self.transport.send(peer, "m", {}, timeout=1.0)
                if resp.get("code") != "ok":
                    raise RuntimeError(resp["code"])
                return resp

            def caller(self, peer):
                self.checked_rpc(peer)
    """})
    assert not fired(res, "ierrors/dropped-error-result")


# -- interprocedural: irpc ---------------------------------------------------

IRPC_SVC = """\
    class Svc:
        def __init__(self, transport):
            self.transport = transport

        def _h_ping(self, body):
            self._fan_out()
            return {{"code": "ok"}}

        def _fan_out(self):
            resp = self.transport.send("peer", "m", {{}}{timeout})
            if resp.get("code") != "ok":
                raise RuntimeError(resp)
"""


def test_irpc_handler_reaches_deadline_less_send(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad.py":
                          IRPC_SVC.format(timeout="")})
    (v,) = fired(res, "irpc/handler-no-deadline")
    assert "Svc._h_ping" in v.message and "_fan_out" in v.message


def test_irpc_deadline_propagated_is_clean(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/ok.py":
                          IRPC_SVC.format(timeout=", timeout=2.0")})
    assert not fired(res, "irpc/handler-no-deadline")


def test_irpc_bare_retry_loop_reaching_rpc_fires(tmp_path):
    """An except-continue while loop with no deadline/attempt bound,
    reaching a blocking send through a helper — the interprocedural
    part: the loop body itself never names the transport."""
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/bad_loop.py": """\
        class Pinger:
            def __init__(self, transport):
                self.transport = transport

            def ping_until_up(self, peer):
                while True:
                    try:
                        resp = self._send_one(peer)
                    except ConnectionError:
                        continue
                    if resp.get("code") == "ok":
                        return resp

            def _send_one(self, peer):
                return self.transport.send(peer, "ping", {}, timeout=1.0)
    """})
    (v,) = fired(res, "irpc/bare-retry-loop")
    assert "transport.send" in v.message
    assert "ping_until_up" in v.message


def test_irpc_budgeted_retry_loops_are_clean(tmp_path):
    """The two sanctioned shapes: a RetryPolicy.attempts() for-loop and
    a while loop explicitly bounded by a Deadline."""
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/ok_loop.py": """\
        class Pinger:
            def __init__(self, transport, policy):
                self.transport = transport
                self.policy = policy

            def ping_with_policy(self, peer):
                for attempt in self.policy.attempts():
                    try:
                        return self.transport.send(
                            peer, "ping", {}, timeout=attempt.timeout(1.0))
                    except ConnectionError as e:
                        attempt.note(e)
                        continue

            def ping_with_deadline(self, peer, deadline):
                while not deadline.expired():
                    try:
                        return self.transport.send(
                            peer, "ping", {}, timeout=deadline.timeout(1.0))
                    except ConnectionError:
                        continue
    """})
    assert not fired(res, "irpc/bare-retry-loop")


def test_irpc_bare_loop_without_rpc_is_clean(tmp_path):
    """A budget-less retry loop around pure computation is somebody
    else's problem — the rule only fires when a blocking RPC is in
    reach."""
    res = lint(tmp_path, {"yugabyte_db_tpu/utils/spin.py": """\
        def stir(items):
            out = []
            while items:
                try:
                    out.append(items.pop())
                except IndexError:
                    continue
            return out
    """})
    assert not fired(res, "irpc/bare-retry-loop")


# -- interprocedural: ijax ---------------------------------------------------

def test_ijax_jit_reachable_item_helper_fires(tmp_path):
    """The helper is textually innocent — no decorator, plain body — but
    it is called from inside a jit trace, where .item() fails."""
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        def helper(x):
            return x.item()

        @jax.jit
        def kernel(x):
            return helper(x)
    """})
    (v,) = fired(res, "ijax/reachable-host-sync")
    assert "helper" in v.message and "kernel" in v.message
    assert not fired(res, "jax/host-sync-item")  # intra rule can't see it


def test_ijax_clean_helper_passes(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/ok.py": """\
        import jax

        def helper(x):
            return x * 2

        @jax.jit
        def kernel(x):
            return helper(x)
    """})
    assert not fired(res, "ijax/reachable-host-sync")


def test_ijax_traced_callee_is_the_intra_rules_problem(tmp_path):
    """A jitted callee starts its own trace; host syncs inside it are
    the intra rule's finding, not a second interprocedural report."""
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/bad.py": """\
        import jax

        @jax.jit
        def inner(x):
            return x.item()

        @jax.jit
        def outer(x):
            return inner(x)
    """})
    assert fired(res, "jax/host-sync-item")
    assert not fired(res, "ijax/reachable-host-sync")


# -- interprocedural: ijax/unmanaged-device-put ------------------------------

def test_ijax_unmanaged_device_put_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        import jax

        def upload(planes):
            return jax.device_put(planes)
    """})
    (v,) = fired(res, "ijax/unmanaged-device-put")
    assert "device_put" in v.message and "residency" in v.message


def test_ijax_unmanaged_device_put_in_lambda_fires(tmp_path):
    """The sharded-mesh shape: the upload hides inside a tree.map
    lambda, invisible to a scanner that skips lambda bodies."""
    res = lint(tmp_path, {"yugabyte_db_tpu/parallel/bad.py": """\
        import jax

        def stack(tree, sharding):
            return jax.tree.map(
                lambda a: jax.device_put(a, sharding), tree)
    """})
    (v,) = fired(res, "ijax/unmanaged-device-put")
    assert "stack" in v.message


def test_ijax_unmanaged_asarray_of_planes_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        import jax.numpy as jnp

        def reupload(run):
            return jnp.asarray(run.cmp_planes)
    """})
    (v,) = fired(res, "ijax/unmanaged-device-put")
    assert "cmp_planes" in v.message


def test_ijax_asarray_of_scalars_is_clean(tmp_path):
    """Index vectors and literals are staging, not plane residency."""
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        import jax.numpy as jnp

        def stage(idx, lit):
            return jnp.asarray(idx), jnp.asarray(lit)
    """})
    assert not fired(res, "ijax/unmanaged-device-put")


def test_ijax_unmanaged_allowlists_residency_modules(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/storage/residency.py": """\
            import jax

            def admit(planes):
                return jax.device_put(planes)
        """,
        "yugabyte_db_tpu/ops/device_run.py": """\
            import jax

            def up(arr, device):
                return jax.device_put(arr, device)
        """})
    assert not fired(res, "ijax/unmanaged-device-put")


def test_ijax_unmanaged_suppression_honored(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/parallel/ok.py": """\
        import jax

        def stack(tree, sharding):
            return jax.tree.map(
                lambda a: jax.device_put(a, sharding),  # yb-lint: disable=ijax/unmanaged-device-put
                tree)
    """})
    assert not fired(res, "ijax/unmanaged-device-put")


# -- SARIF -------------------------------------------------------------------

def test_sarif_output_on_violations(tmp_path):
    p = tmp_path / "yugabyte_db_tpu" / "util" / "bad.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent("""\
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=sarif", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2
    sarif = json.loads(proc.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "yb-lint"
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    (res,) = [r for r in run["results"]
              if r["ruleId"] == "errors/swallowed-exception"]
    assert res["ruleId"] in ids
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("util/bad.py")
    assert loc["region"]["startLine"] == 4
    assert "ybLintBaselineKey/v1" in res["partialFingerprints"]


def test_sarif_clean_tree_has_no_results():
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=sarif", PKG],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)
    assert sarif["runs"][0]["results"] == []


# -- --changed-only ----------------------------------------------------------

def test_changed_only_filters_to_dirty_files(tmp_path):
    """A violation in a committed file is mute under --changed-only; the
    same violation in a dirty file is reported. The whole tree is still
    analyzed (files_checked covers both)."""
    pkg = tmp_path / "yugabyte_db_tpu"
    (pkg / "util").mkdir(parents=True)
    bad = textwrap.dedent("""\
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    (pkg / "util" / "old.py").write_text(bad)
    git_env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
               "JAX_PLATFORMS": "cpu"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=tmp_path, check=True, env=git_env,
                       capture_output=True)
    (pkg / "util" / "new.py").write_text(bad)

    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis", "--no-baseline",
         "--changed-only", "--format=json", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, env=git_env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    files = {v["file"] for v in data["violations"]}
    assert files == {"yugabyte_db_tpu/util/new.py"}
    assert data["files_checked"] == 2

    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis", "--no-baseline",
         "--format=json", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, env=git_env)
    data = json.loads(proc.stdout)
    assert {v["file"] for v in data["violations"]} == {
        "yugabyte_db_tpu/util/new.py", "yugabyte_db_tpu/util/old.py"}


# -- suppression + baseline machinery ----------------------------------------

def test_standalone_suppression_covers_next_line(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/rpc/ok.py": """\
        # yb-lint: disable=all
        from yugabyte_db_tpu.consensus import raft
    """})
    assert not res.violations and res.suppressed >= 1


def test_baseline_budget_absorbs_only_grandfathered_count(tmp_path):
    files = {"yugabyte_db_tpu/util/two.py": """\
        def f():
            try:
                g()
            except Exception:
                pass
            try:
                g()
            except Exception:
                pass
    """}
    res = lint(tmp_path, files)
    raw = fired(res, "errors/swallowed-exception")
    assert len(raw) == 2
    # Both share one baseline key (same file/rule/fingerprint). A budget
    # of 1 absorbs only the first in line order: the file grew a fresh
    # violation past its grandfathered count.
    assert raw[0].baseline_key() == raw[1].baseline_key()
    budget = {raw[0].baseline_key(): 1}
    fresh, absorbed = apply_baseline(raw, budget)
    assert absorbed == 1
    assert [v.line for v in fresh] == [max(v.line for v in raw)]


# -- iraces/ lock-set race detection -----------------------------------------

RACY_COUNTER = """\
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def start(self):
            threading.Thread(target=self._loop).start()

        def _loop(self):
            with self._lock:
                self._n = self._n + 1

        def bump(self):
            self._n += 1
"""


def test_iraces_unguarded_shared_write_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/c.py": RACY_COUNTER})
    (v,) = fired(res, "iraces/unguarded-shared-write")
    assert v.line == 16 and "_n" in v.message
    assert "Counter" in v.message


def test_iraces_unguarded_shared_write_clean_when_locked(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/c.py": """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._n = self._n + 1

            def bump(self):
                with self._lock:
                    self._n += 1
    """})
    assert not fired(res, "iraces/unguarded-shared-write")


def test_iraces_fires_on_guarded_by_declaration_alone(tmp_path):
    """@guarded_by marks the class shared by assertion: no thread root
    needed for the write to be a finding."""
    res = lint(tmp_path, {"yugabyte_db_tpu/util/d.py": """\
        import threading

        from yugabyte_db_tpu.utils.locking import guarded_by

        @guarded_by("_lock", "_state")
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = "idle"

            def set(self, s):
                self._state = s
    """})
    (v,) = fired(res, "iraces/unguarded-shared-write")
    assert "guarded_by" in v.message


def test_iraces_inconsistent_lock_set_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/s.py": """\
        import threading

        class Split:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._v = 0

            def start(self):
                threading.Thread(target=self.write_a).start()

            def write_a(self):
                with self._a:
                    self._v = 1

            def write_b(self):
                with self._b:
                    self._v = 2
    """})
    (v,) = fired(res, "iraces/inconsistent-lock-set")
    assert "_v" in v.message and "no common lock" in v.message


def test_iraces_inconsistent_lock_set_clean_with_common_lock(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/s.py": """\
        import threading

        class Split:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._v = 0

            def start(self):
                threading.Thread(target=self.write_a).start()

            def write_a(self):
                with self._a:
                    self._v = 1

            def write_b(self):
                with self._a:
                    self._v = 2
    """})
    assert not fired(res, "iraces/inconsistent-lock-set")


def test_iraces_guarded_read_unguarded_write_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/g.py": """\
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0

            def start(self):
                threading.Thread(target=self.read).start()

            def read(self):
                with self._lock:
                    return self._v

            def bump(self):
                self._v = self._v + 1
    """})
    (v,) = fired(res, "iraces/guarded-read-unguarded-write")
    assert "readers hold" in v.message


def test_iraces_guarded_read_unguarded_write_clean(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/g.py": """\
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0

            def start(self):
                threading.Thread(target=self.read).start()

            def read(self):
                with self._lock:
                    return self._v

            def bump(self):
                with self._lock:
                    self._v = self._v + 1
    """})
    assert not fired(res, "iraces/guarded-read-unguarded-write")


def test_iraces_callback_lambda_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/r.py": """\
        import threading
        import weakref

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def add(self, owner, k):
                with self._lock:
                    self._items.update({k: owner})
                weakref.ref(owner, lambda r: self._items.pop(k, None))
    """})
    (v,) = fired(res, "iraces/callback-into-locked-state")
    assert "weakref callback" in v.message and "_items" in v.message


def test_iraces_callback_rlock_reentry_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/util/r.py": """\
        import threading
        import weakref

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items.update({k: v})

            def register(self, owner, k):
                weakref.ref(owner, self._on_death)

            def _on_death(self, ref):
                with self._lock:
                    self._items.pop(ref, None)
    """})
    assert any("re-entrant" in v.message
               for v in fired(res, "iraces/callback-into-locked-state"))


def test_iraces_callback_clean_with_deferred_queue(tmp_path):
    """The fix shape: the death callback appends to an undeclared
    atomic deque; guarded state is drained under the lock elsewhere."""
    res = lint(tmp_path, {"yugabyte_db_tpu/util/r.py": """\
        import collections
        import threading
        import weakref

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}
                self._dead = collections.deque()

            def add(self, owner, k):
                with self._lock:
                    self._items.update({k: owner})
                weakref.ref(owner, lambda r: self._dead.append(k))
    """})
    assert not fired(res, "iraces/callback-into-locked-state")


def test_iraces_suppression_honored(tmp_path):
    code = RACY_COUNTER.replace(
        "            self._n += 1",
        "            # yb-lint: disable=iraces/unguarded-shared-write\n"
        "            self._n += 1")
    res = lint(tmp_path, {"yugabyte_db_tpu/util/c.py": code})
    assert not fired(res, "iraces/unguarded-shared-write")
    assert res.suppressed >= 1


def test_iraces_in_sarif_with_fingerprint(tmp_path):
    p = tmp_path / "yugabyte_db_tpu" / "util" / "c.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(RACY_COUNTER))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=sarif", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2
    sarif = json.loads(proc.stdout)
    run = sarif["runs"][0]
    assert any(r["id"].startswith("iraces/")
               for r in run["tool"]["driver"]["rules"])
    (res,) = [r for r in run["results"]
              if r["ruleId"] == "iraces/unguarded-shared-write"]
    assert "ybLintBaselineKey/v1" in res["partialFingerprints"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("util/c.py")


def test_iraces_changed_only_scopes_to_dirty_files(tmp_path):
    """Race findings anchor on the write site's file, so --changed-only
    mutes a committed racy class and reports the same shape in a dirty
    file — while lock-set inference still runs whole-program."""
    pkg = tmp_path / "yugabyte_db_tpu"
    (pkg / "util").mkdir(parents=True)
    (pkg / "util" / "old.py").write_text(textwrap.dedent(RACY_COUNTER))
    git_env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
               "JAX_PLATFORMS": "cpu"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=tmp_path, check=True, env=git_env,
                       capture_output=True)
    (pkg / "util" / "new.py").write_text(
        textwrap.dedent(RACY_COUNTER).replace("Counter", "Tally"))

    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis", "--no-baseline",
         "--changed-only", "--format=json", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, env=git_env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    race = [v for v in data["violations"]
            if v["rule"] == "iraces/unguarded-shared-write"]
    assert {v["file"] for v in race} == {"yugabyte_db_tpu/util/new.py"}


# -- interprocedural: ijit ---------------------------------------------------

IJIT_KERN = """\
    import functools

    import jax

    from yugabyte_db_tpu.utils.jitting import compile_contract


    @functools.lru_cache(maxsize=8)
    @compile_contract("toy_entry", max_compiles=8)
    def compiled_toy(sig):
        def run(x):
            return x * 2
        return jax.jit(run)
"""

IJIT_SERVE = """\
    import jax
    import numpy as np

    from yugabyte_db_tpu.ops.kern import compiled_toy


    def point_serve(req, arr):
        fn = compiled_toy({body})
        return fn(arr)
"""


def test_ijit_unstable_static_arg_fires(tmp_path):
    """A per-request value (request attribute) in a factory position:
    every distinct value compiles a new program."""
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py":
            IJIT_SERVE.format(body="req.limit")})
    (v,) = fired(res, "ijit/unstable-static-arg")
    assert "toy_entry" in v.message and "sig" in v.message
    assert v.fingerprint == "ijit:toy_entry:point_serve:sig"


def test_ijit_shape_from_data_fires(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py":
            IJIT_SERVE.format(body="arr.shape[0]")})
    (v,) = fired(res, "ijit/shape-from-data")
    assert "bucketing" in v.message
    assert not fired(res, "ijit/unstable-static-arg")


def test_ijit_bucketed_shape_is_clean(tmp_path):
    """Routing the data-derived size through a bucketing helper bounds
    the compile count: sanctioned."""
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            from yugabyte_db_tpu.ops.agg_fold import safe_window_blocks
            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(req, arr):
                fn = compiled_toy(safe_window_blocks(arr.shape[0]))
                return fn(arr)
        """})
    assert not fired(res, "ijit/shape-from-data")
    assert not fired(res, "ijit/unstable-static-arg")


def test_ijit_raw_dict_width_fires(tmp_path):
    """A dictionary width taken straight off the data (the unique-value
    count of a column) in a factory position: every distinct cardinality
    compiles a new program."""
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            import numpy as np

            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(req, arr):
                fn = compiled_toy(len(np.unique(arr)))
                return fn(arr)
        """})
    (v,) = fired(res, "ijit/shape-from-data")
    assert "bucketing" in v.message


def test_ijit_pow2_bucketed_dict_width_is_clean(tmp_path):
    """The plane encoder's dictionary-width ladder (pow2_bucket) bounds
    the compile-key space, so a bucketed cardinality is sanctioned —
    the same standing as safe_window_blocks for window counts."""
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            import numpy as np

            from yugabyte_db_tpu.ops.encodings import pow2_bucket
            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(req, arr):
                fn = compiled_toy(pow2_bucket(len(np.unique(arr)) + 1))
                return fn(arr)
        """})
    assert not fired(res, "ijit/shape-from-data")
    assert not fired(res, "ijit/unstable-static-arg")


def test_ijit_cold_path_is_silent(tmp_path):
    """The identical call in a function no serve path reaches: compile
    cost off the hot path is startup cost, not a finding."""
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py":
            IJIT_SERVE.format(body="req.limit").replace(
                "point_serve", "warmup_helper")})
    assert not fired(res, "ijit/unstable-static-arg")


def test_ijit_self_capture_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/kern.py": """\
        import jax


        class Folder:
            @jax.jit
            def kernel(self, x):
                return x + self.offset
    """})
    (v,) = fired(res, "ijit/mutable-closure-capture")
    assert "self.offset" in v.message


def test_ijit_global_capture_fires(tmp_path):
    """A module global rebound via ``global`` elsewhere is mutable
    state baked in at trace time; a never-rebound module constant is
    not a capture."""
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/kern.py": """\
        import jax

        _MODE = 0
        _SCALE = 4


        def set_mode(m):
            global _MODE
            _MODE = m


        @jax.jit
        def kernel(x):
            return x * _SCALE + _MODE
    """})
    (v,) = fired(res, "ijit/mutable-closure-capture")
    assert "_MODE" in v.message and "_SCALE" not in v.message


def test_ijit_factory_param_inner_is_clean(tmp_path):
    """The factory pattern itself: the inner function reading enclosing
    factory params is the sanctioned shape, not a capture."""
    res = lint(tmp_path, {"yugabyte_db_tpu/ops/kern.py": """\
        import functools

        import jax


        @functools.lru_cache(maxsize=8)
        def compiled_scale(n):
            def run(x):
                return x * n
            return jax.jit(run)
    """})
    assert not fired(res, "ijit/mutable-closure-capture")


def test_ijit_hot_path_transfer_fires(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            import numpy as np

            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(sig, arr):
                fn = compiled_toy(sig)
                res = fn(arr)
                return np.asarray(res)
        """})
    (v,) = fired(res, "ijit/hot-path-transfer")
    assert "device_get" in v.message and "point_serve" in v.message


def test_ijit_explicit_device_get_is_clean(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            import jax
            import numpy as np

            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(sig, arr):
                fn = compiled_toy(sig)
                res = fn(arr)
                res = jax.device_get(res)
                return np.asarray(res)
        """})
    assert not fired(res, "ijit/hot-path-transfer")


def test_ijit_suppression_honored(tmp_path):
    res = lint(tmp_path, {
        "yugabyte_db_tpu/ops/kern.py": IJIT_KERN,
        "yugabyte_db_tpu/storage/serve.py": """\
            import numpy as np

            from yugabyte_db_tpu.ops.kern import compiled_toy


            def point_serve(sig, arr):
                fn = compiled_toy(sig)
                res = fn(arr)
                # Deliberate single-scalar fetch; measured not hot.
                return np.asarray(res)  # yb-lint: disable=ijit/hot-path-transfer
        """})
    assert not fired(res, "ijit/hot-path-transfer")
    assert res.suppressed >= 1


def test_ijit_in_sarif_with_fingerprint(tmp_path):
    p = tmp_path / "yugabyte_db_tpu" / "ops" / "kern.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(IJIT_KERN))
    s = tmp_path / "yugabyte_db_tpu" / "storage" / "serve.py"
    s.parent.mkdir(parents=True, exist_ok=True)
    s.write_text(textwrap.dedent(IJIT_SERVE.format(body="req.limit")))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=sarif", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2
    sarif = json.loads(proc.stdout)
    run = sarif["runs"][0]
    assert any(r["id"].startswith("ijit/")
               for r in run["tool"]["driver"]["rules"])
    (res,) = [r for r in run["results"]
              if r["ruleId"] == "ijit/unstable-static-arg"]
    assert "ybLintBaselineKey/v1" in res["partialFingerprints"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("storage/serve.py")


def test_ijit_changed_only_scopes_to_dirty_files(tmp_path):
    """ijit findings anchor on the serve-path call site, so
    --changed-only mutes a committed caller and reports the same shape
    in a dirty one — jit-entry fact extraction still runs
    whole-program (the entry module itself stays committed)."""
    pkg = tmp_path / "yugabyte_db_tpu"
    (pkg / "ops").mkdir(parents=True)
    (pkg / "storage").mkdir(parents=True)
    (pkg / "ops" / "kern.py").write_text(textwrap.dedent(IJIT_KERN))
    (pkg / "storage" / "old.py").write_text(
        textwrap.dedent(IJIT_SERVE.format(body="req.limit")))
    git_env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
               "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
               "JAX_PLATFORMS": "cpu"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=tmp_path, check=True, env=git_env,
                       capture_output=True)
    # Same hot-root name in a second module: the serve-path set is
    # matched by name, so the dirty file carries the same shape.
    (pkg / "storage" / "new.py").write_text(
        textwrap.dedent(IJIT_SERVE.format(body="req.limit")))

    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis", "--no-baseline",
         "--changed-only", "--format=json", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, env=git_env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    hits = [v for v in data["violations"]
            if v["rule"] == "ijit/unstable-static-arg"]
    assert {v["file"] for v in hits} == {"yugabyte_db_tpu/storage/new.py"}


# -- interprocedural: ires resource lifecycle --------------------------------

def test_ires_leak_on_raise_fires(tmp_path):
    """A raise-capable call sits between pin and unpin with no
    finally/broad handler: any exception leaks the pin."""
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        class Scanner:
            def scan(self, run):
                run.pin()
                rows = decode(42)
                run.unpin()
                return rows
    """})
    (v,) = fired(res, "ires/leak-on-raise")
    assert v.line == 4 and "decode" in v.message and "pin" in v.message


def test_ires_leak_on_raise_clean_with_finally(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Scanner:
            def scan(self, run):
                run.pin()
                try:
                    rows = decode(42)
                finally:
                    run.unpin()
                return rows
    """})
    assert not fired(res, "ires/leak-on-raise")


def test_ires_leak_on_early_return_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        class Scanner:
            def scan(self, run, fast):
                run.pin()
                if fast:
                    return 0
                run.unpin()
                return 1
    """})
    (v,) = fired(res, "ires/leak-on-early-return")
    assert v.line == 5 and "skips the release" in v.message


def test_ires_early_return_after_release_is_clean(tmp_path):
    """Returns AFTER the release don't skip anything — only a return
    between the acquire and the (non-finally) release fires."""
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Scanner:
            def scan(self, run, fast):
                run.pin()
                rows = decode(42)
                run.unpin()
                if fast:
                    return 0
                return rows
    """})
    assert not fired(res, "ires/leak-on-early-return")
    assert not fired(res, "ires/double-release")


def test_ires_double_release_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        class Scanner:
            def stop(self, run):
                run.pin()
                run.unpin()
                run.unpin()
    """})
    (v,) = fired(res, "ires/double-release")
    assert v.line == 5 and "double-release" in v.message


def test_ires_double_release_clean_with_reacquire(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Scanner:
            def stop(self, run):
                run.pin()
                run.unpin()
                run.pin()
                run.unpin()
    """})
    assert not fired(res, "ires/double-release")


def test_ires_unbalanced_tracker_fires(tmp_path):
    """A tracker debit on a frame-local tracker with a raise-capable
    call before the credit: the charge leaks and skews the budget."""
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/bad.py": """\
        class Upload:
            def charge(self, n):
                tracker = device_tracker()
                tracker.consume(n)
                planes = build(n)
                tracker.release(n)
                return planes
    """})
    (v,) = fired(res, "ires/unbalanced-tracker")
    assert "tracker" in v.message


def test_ires_unbalanced_tracker_clean_with_finally(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Upload:
            def charge(self, n):
                tracker = device_tracker()
                tracker.consume(n)
                try:
                    planes = build(n)
                finally:
                    tracker.release(n)
                return planes
    """})
    assert not fired(res, "ires/unbalanced-tracker")


def test_ires_ownership_escape_is_clean(tmp_path):
    """Passing the resource to a call (or storing it into self/a
    container) transfers ownership out of the frame — no leak."""
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Scanner:
            def hand_off(self, run, batch):
                run.pin()
                batch.adopt(run)
                return batch

            def keep(self, run):
                run.pin()
                self._held = run
    """})
    assert not fired(res, "ires/leak-on-early-return")
    assert not fired(res, "ires/leak-on-raise")


def test_ires_suppression_honored(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/storage/ok.py": """\
        class Scanner:
            def stop(self, run):
                run.pin()
                run.unpin()
                # yb-lint: disable=ires/double-release
                run.unpin()
    """})
    assert not fired(res, "ires/double-release")
    assert res.suppressed >= 1


# -- interprocedural: iholds lock-across-blocking ----------------------------

def test_iholds_fsync_under_lock_fires(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import os
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def save(self):
                with self._lock:
                    os.fsync(self._f)
    """})
    (v,) = fired(res, "iholds/lock-across-blocking")
    assert v.line == 11 and "os.fsync" in v.message
    assert "_lock" in v.message


def test_iholds_fsync_outside_lock_is_clean(tmp_path):
    """The group-commit shape: snapshot under the lock, block outside."""
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import os
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def save(self):
                with self._lock:
                    f = self._f
                os.fsync(f)
    """})
    assert not fired(res, "iholds/lock-across-blocking")


def test_iholds_one_hop_through_helper_fires(tmp_path):
    """The caller holds the lock across a helper whose transitive
    summary blocks — only the call-graph pass can see it."""
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/bad.py": """\
        import os
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def save(self):
                with self._lock:
                    self._sync_file()

            def flush_unlocked(self):
                self._sync_file()

            def _sync_file(self):
                os.fsync(self._f)
    """})
    # _sync_file is NOT locked on every entry (flush_unlocked), so the
    # hold is save()'s fault and is reported at save's call site.
    vs = fired(res, "iholds/lock-across-blocking")
    assert any("_sync_file" in v.message and "save" in v.fingerprint
               for v in vs)


def test_iholds_cond_wait_on_own_lock_is_exempt(tmp_path):
    """Waiting on a condition releases its aliased lock — the legal
    release-and-wait pattern is not a hold."""
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._items = []

            def take(self):
                with self._cv:
                    while not self._items:
                        self._cv.wait()
                    return self._items.pop()
    """})
    assert not fired(res, "iholds/lock-across-blocking")


def test_iholds_suppression_honored(tmp_path):
    res = lint(tmp_path, {"yugabyte_db_tpu/tablet/ok.py": """\
        import os
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def save(self):
                with self._lock:
                    # Justified: segment roll-over must be durable
                    # before the lock drops.
                    # yb-lint: disable=iholds/lock-across-blocking
                    os.fsync(self._f)
    """})
    assert not fired(res, "iholds/lock-across-blocking")
    assert res.suppressed >= 1


IRES_BAD_DOUBLE = """\
    class Scanner:
        def stop(self, run):
            run.pin()
            run.unpin()
            run.unpin()
"""


def test_ires_iholds_in_sarif_with_fingerprint(tmp_path):
    p = tmp_path / "yugabyte_db_tpu" / "storage" / "bad.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(IRES_BAD_DOUBLE))
    q = tmp_path / "yugabyte_db_tpu" / "tablet" / "bad.py"
    q.parent.mkdir(parents=True, exist_ok=True)
    q.write_text(textwrap.dedent("""\
        import os
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._f = None

            def save(self):
                with self._lock:
                    os.fsync(self._f)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis",
         "--format=sarif", str(tmp_path / "yugabyte_db_tpu")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2
    sarif = json.loads(proc.stdout)
    run = sarif["runs"][0]
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert any(i.startswith("ires/") for i in ids)
    assert any(i.startswith("iholds/") for i in ids)
    (dr,) = [r for r in run["results"]
             if r["ruleId"] == "ires/double-release"]
    # Fingerprints are line-free (rule:qualname:obj) so SARIF baselining
    # survives unrelated edits shifting the site.
    fp = dr["partialFingerprints"]["ybLintBaselineKey/v1"]
    assert "Scanner.stop" in fp and not any(ch.isdigit() for ch in
                                            fp.rsplit(":", 1)[-1])
    (hv,) = [r for r in run["results"]
             if r["ruleId"] == "iholds/lock-across-blocking"]
    assert "ybLintBaselineKey/v1" in hv["partialFingerprints"]
    loc = hv["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("tablet/bad.py")


def test_ires_changed_only_scopes_to_dirty_files(tmp_path):
    pkg = tmp_path / "yugabyte_db_tpu"
    (pkg / "storage").mkdir(parents=True)
    (pkg / "storage" / "old.py").write_text(
        textwrap.dedent(IRES_BAD_DOUBLE))
    git_env = {**os.environ, "GIT_AUTHOR_NAME": "t",
               "GIT_AUTHOR_EMAIL": "t@t", "GIT_COMMITTER_NAME": "t",
               "GIT_COMMITTER_EMAIL": "t@t", "JAX_PLATFORMS": "cpu"}
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=tmp_path, check=True, env=git_env,
                       capture_output=True)
    (pkg / "storage" / "new.py").write_text(
        textwrap.dedent(IRES_BAD_DOUBLE).replace("Scanner", "Reaper"))

    proc = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.analysis", "--no-baseline",
         "--changed-only", "--format=json", str(pkg)],
        cwd=REPO_ROOT, capture_output=True, text=True, env=git_env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    hits = [v for v in data["violations"]
            if v["rule"] == "ires/double-release"]
    assert {v["file"] for v in hits} == {"yugabyte_db_tpu/storage/new.py"}
