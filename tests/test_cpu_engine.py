"""CPU storage engine tests: MVCC semantics, flush/compaction, paging, aggregates.

Reference test analog: src/yb/docdb/docdb-test.cc and the randomized
oracle tests (randomized_docdb-test.cc with InMemDocDbState).
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (
    AggSpec, CpuStorageEngine, Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.row_version import MAX_HT


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


@pytest.fixture
def eng():
    return make_engine("cpu", make_schema())


def col_ids(schema):
    return {c.name: c.col_id for c in schema.value_columns}


def test_insert_and_scan(eng):
    ids = col_ids(eng.schema)
    for i in range(10):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100 + i, liveness=True,
                              columns={ids["a"]: i * 10, ids["b"]: f"v{i}"})])
    res = eng.scan(ScanSpec(read_ht=MAX_HT))
    assert res.columns == ["k", "r", "a", "b"]
    assert res.rows == [("p", i, i * 10, f"v{i}") for i in range(10)]


def test_mvcc_snapshot_reads(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True, columns={ids["a"]: 1})])
    eng.apply([RowVersion(key, ht=20, columns={ids["a"]: 2})])
    eng.apply([RowVersion(key, ht=30, tombstone=True)])
    eng.apply([RowVersion(key, ht=40, liveness=True, columns={ids["a"]: 4})])

    def a_at(read_ht):
        rows = eng.scan(ScanSpec(read_ht=read_ht, projection=["a"])).rows
        return rows[0][0] if rows else None

    assert a_at(5) is None          # before any write
    assert a_at(10) == 1
    assert a_at(25) == 2            # partial update merged over insert
    assert a_at(30) is None         # deleted
    assert a_at(35) is None
    assert a_at(40) == 4            # reinserted; old columns must not leak
    rows = eng.scan(ScanSpec(read_ht=45)).rows
    assert rows == [("p", 1, 4, None)]  # b must NOT resurrect from ht=10


def test_partial_update_merges_columns(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True,
                          columns={ids["a"]: 1, ids["b"]: "x"})])
    eng.apply([RowVersion(key, ht=20, columns={ids["b"]: "y"})])
    rows = eng.scan(ScanSpec(read_ht=MAX_HT)).rows
    assert rows == [("p", 1, 1, "y")]


def test_update_without_insert_then_null_out(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    # UPDATE without prior INSERT: row visible while a column is non-null.
    eng.apply([RowVersion(key, ht=10, columns={ids["a"]: 7})])
    assert eng.scan(ScanSpec(read_ht=15)).rows == [("p", 1, 7, None)]
    # Nulling the only column makes the row vanish (no liveness).
    eng.apply([RowVersion(key, ht=20, columns={ids["a"]: None})])
    assert eng.scan(ScanSpec(read_ht=25)).rows == []


def test_ttl_expiry_shadows_older(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True, columns={ids["a"]: 1})])
    eng.apply([RowVersion(key, ht=20, columns={ids["a"]: 2}, expire_ht=30)])
    assert eng.scan(ScanSpec(read_ht=25)).rows == [("p", 1, 2, None)]
    # At 30 the ht=20 value expired: reads as null, does NOT resurrect a=1.
    assert eng.scan(ScanSpec(read_ht=30)).rows == [("p", 1, None, None)]


def test_ttl_row_expiry(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True, columns={ids["a"]: 1},
                          expire_ht=50)])
    assert eng.scan(ScanSpec(read_ht=49)).rows == [("p", 1, 1, None)]
    assert eng.scan(ScanSpec(read_ht=50)).rows == []  # whole row gone


def test_range_bounds_and_predicates(eng):
    ids = col_ids(eng.schema)
    for i in range(20):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100, liveness=True,
                              columns={ids["a"]: i % 5})])
    lo = enc(eng.schema, "p", 5)
    hi = enc(eng.schema, "p", 15)
    res = eng.scan(ScanSpec(lower=lo, upper=hi, read_ht=MAX_HT, projection=["r"]))
    assert [r[0] for r in res.rows] == list(range(5, 15))
    res = eng.scan(ScanSpec(read_ht=MAX_HT, projection=["r"],
                            predicates=[Predicate("a", ">=", 3)]))
    assert [r[0] for r in res.rows] == [i for i in range(20) if i % 5 >= 3]


def test_paging(eng):
    ids = col_ids(eng.schema)
    for i in range(25):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100, liveness=True,
                              columns={ids["a"]: i})])
    got, spec = [], ScanSpec(read_ht=MAX_HT, projection=["r"], limit=10)
    pages = 0
    while True:
        res = eng.scan(spec)
        got.extend(r[0] for r in res.rows)
        pages += 1
        if res.resume_key is None:
            break
        spec = ScanSpec(lower=res.resume_key, read_ht=MAX_HT,
                        projection=["r"], limit=10)
    assert got == list(range(25))
    assert pages == 3


def test_flush_compact_preserve_results(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True, columns={ids["a"]: 1})])
    eng.flush()
    eng.apply([RowVersion(key, ht=20, columns={ids["b"]: "y"})])
    eng.flush()
    eng.apply([RowVersion(key, ht=30, columns={ids["a"]: 3})])
    # merge across two runs + memtable
    assert eng.scan(ScanSpec(read_ht=MAX_HT)).rows == [("p", 1, 3, "y")]
    eng.flush()
    eng.compact()
    assert eng.stats()["num_runs"] == 1
    assert eng.scan(ScanSpec(read_ht=MAX_HT)).rows == [("p", 1, 3, "y")]
    assert eng.scan(ScanSpec(read_ht=15)).rows == [("p", 1, 1, None)]


def test_compaction_history_gc(eng):
    ids = col_ids(eng.schema)
    key = enc(eng.schema, "p", 1)
    eng.apply([RowVersion(key, ht=10, liveness=True, columns={ids["a"]: 1})])
    eng.apply([RowVersion(key, ht=20, columns={ids["a"]: 2})])
    eng.apply([RowVersion(key, ht=30, columns={ids["a"]: 3})])
    key2 = enc(eng.schema, "q", 1)
    eng.apply([RowVersion(key2, ht=10, liveness=True, columns={ids["a"]: 9})])
    eng.apply([RowVersion(key2, ht=25, tombstone=True)])
    eng.flush()
    eng.compact(history_cutoff_ht=28)
    # a=1 at ht 10 shadowed by a=2 at 20 for reads >= 28 BUT liveness@10 must
    # survive; tombstoned key2 disappears entirely.
    stats = eng.stats()
    assert stats["num_runs"] == 1
    assert eng.scan(ScanSpec(read_ht=MAX_HT)).rows == [("p", 1, 3, None)]
    assert eng.scan(ScanSpec(read_ht=28)).rows == [("p", 1, 2, None)]
    # key2 fully GC'd.
    assert all(k != key2 for k in eng.runs[0].keys)


def test_aggregates(eng):
    ids = col_ids(eng.schema)
    for i in range(10):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100, liveness=True,
                              columns={ids["a"]: i, ids["b"]: "x" if i % 2 else None})])
    res = eng.scan(ScanSpec(read_ht=MAX_HT, aggregates=[
        AggSpec("count", None), AggSpec("count", "b"), AggSpec("sum", "a"),
        AggSpec("min", "a"), AggSpec("max", "a"), AggSpec("avg", "a"),
    ]))
    assert res.columns == ["count(*)", "count(b)", "sum(a)", "min(a)", "max(a)", "avg(a)"]
    assert res.rows == [(10, 5, 45, 0, 9, 4.5)]


def test_aggregate_group_by(eng):
    ids = col_ids(eng.schema)
    for i in range(12):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100, liveness=True,
                              columns={ids["a"]: i % 3, ids["b"]: f"g{i % 2}"})])
    res = eng.scan(ScanSpec(read_ht=MAX_HT, group_by=["b"],
                            aggregates=[AggSpec("count", None), AggSpec("sum", "a")]))
    assert res.columns == ["b", "count(*)", "sum(a)"]
    assert res.rows == [("g0", 6, 6), ("g1", 6, 6)]


def test_auto_flush_and_compact_trigger():
    eng = make_engine("cpu", make_schema(),
                      {"memtable_flush_versions": 10, "compaction_trigger": 3})
    ids = col_ids(eng.schema)
    for i in range(100):
        eng.apply([RowVersion(enc(eng.schema, "p", i), ht=100 + i, liveness=True,
                              columns={ids["a"]: i})])
    stats = eng.stats()
    assert stats["num_runs"] < 3
    assert stats["run_versions"] + stats["memtable_versions"] == 100
    res = eng.scan(ScanSpec(read_ht=MAX_HT, projection=["r"]))
    assert [r[0] for r in res.rows] == list(range(100))


class BruteForceModel:
    """Model-checking oracle: replays the exact history per read.

    The pattern of the reference's InMemDocDbState: an independent, simpler
    implementation of the same semantics (src/yb/docdb/in_mem_docdb.cc).
    """

    def __init__(self, schema):
        self.schema = schema
        self.history: list[RowVersion] = []

    def apply(self, rows):
        self.history.extend(rows)

    def row_at(self, key, read_ht):
        tomb = 0
        for v in self.history:
            if v.key == key and v.ht <= read_ht and v.tombstone:
                tomb = max(tomb, v.ht)
        cols, hts, live = {}, {}, 0
        for v in sorted([v for v in self.history if v.key == key],
                        key=lambda r: -r.ht):
            if v.ht > read_ht or v.ht <= tomb or v.tombstone:
                continue
            expired = v.expire_ht != MAX_HT and read_ht >= v.expire_ht
            if v.liveness and not expired:
                live = max(live, v.ht)
            for c, val in v.columns.items():
                if c not in cols:
                    cols[c] = None if expired else val
                    hts[c] = v.ht
        exists = live > 0 or any(val is not None for val in cols.values())
        return cols if exists else None


def test_randomized_vs_oracle():
    rnd = random.Random(99)
    schema = make_schema()
    eng = make_engine("cpu", schema,
                      {"memtable_flush_versions": 37, "compaction_trigger": 3})
    model = BruteForceModel(schema)
    ids = col_ids(schema)
    keys = [enc(schema, rnd.choice("abc"), i) for i in range(30)]
    ht = 0
    checkpoints = []
    for step in range(600):
        ht += rnd.randrange(1, 5)
        key = rnd.choice(keys)
        roll = rnd.random()
        if roll < 0.15:
            rv = RowVersion(key, ht=ht, tombstone=True)
        elif roll < 0.5:
            cols = {ids["a"]: rnd.randrange(100)}
            if rnd.random() < 0.5:
                cols[ids["b"]] = rnd.choice(["x", "y", None])
            rv = RowVersion(key, ht=ht, liveness=True, columns=cols,
                            expire_ht=ht + rnd.randrange(1, 50) if rnd.random() < 0.2 else MAX_HT)
        else:
            cols = {rnd.choice([ids["a"], ids["b"]]): rnd.choice([1, 2, None, "z"])}
            rv = RowVersion(key, ht=ht, columns=cols)
        eng.apply([rv])
        model.apply([rv])
        if step % 97 == 0:
            checkpoints.append(ht)
    for read_ht in checkpoints + [ht, MAX_HT]:
        res = eng.scan(ScanSpec(read_ht=read_ht))
        got = {tuple(r[:2]): r[2:] for r in res.rows}
        expect = {}
        for key in set(keys):
            row = model.row_at(key, read_ht)
            if row is not None:
                from yugabyte_db_tpu.models.encoding import decode_doc_key
                _, hashed, ranges = decode_doc_key(key)
                expect[tuple(hashed + ranges)] = (
                    row.get(ids["a"]), row.get(ids["b"]))
        assert got == expect, f"mismatch at read_ht={read_ht}"
