"""Roles / permissions / authentication across frontends.

Reference analogs: master CreateRole/GrantRevokeRole/
GrantRevokePermission RPCs (master.proto:1383-1388), CQL enforcement +
auth vtables (yql_auth_roles_vtable.cc), PG password auth. Every
unauthorized-op test asserts fail-closed behavior.
"""

import pytest

from yugabyte_db_tpu.auth import RoleStore, hash_password
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound)
from yugabyte_db_tpu.yql.cql.processor import (LocalCluster, QLProcessor,
                                               Unauthorized)


@pytest.fixture
def auth_on():
    FLAGS.set("use_cassandra_authentication", True)
    yield
    FLAGS.set("use_cassandra_authentication", False)


# -- RoleStore unit ----------------------------------------------------------

def test_role_store_basics():
    st = RoleStore()
    st.apply({"op": "auth_create_role", "name": "admin",
              "superuser": True, "can_login": True,
              "salted_hash": hash_password("pw")})
    st.apply({"op": "auth_create_role", "name": "reader",
              "can_login": True, "salted_hash": hash_password("r")})
    with pytest.raises(AlreadyPresent):
        st.apply({"op": "auth_create_role", "name": "admin"})
    assert st.check_login("admin", "pw")
    assert not st.check_login("admin", "wrong")
    assert not st.check_login("ghost", "pw")
    # superuser passes everything; reader nothing yet
    assert st.authorize("admin", "MODIFY", "data/ks/t")
    assert not st.authorize("reader", "SELECT", "data/ks/t")
    st.apply({"op": "auth_grant_perm", "role": "reader",
              "resource": "data/ks", "perm": "SELECT"})
    # keyspace grant covers tables beneath it
    assert st.authorize("reader", "SELECT", "data/ks/t")
    assert not st.authorize("reader", "MODIFY", "data/ks/t")
    st.apply({"op": "auth_revoke_perm", "role": "reader",
              "resource": "data/ks", "perm": "SELECT"})
    assert not st.authorize("reader", "SELECT", "data/ks/t")


def test_role_store_membership_transitive():
    st = RoleStore()
    for n in ("a", "b", "c"):
        st.apply({"op": "auth_create_role", "name": n})
    st.apply({"op": "auth_grant_perm", "role": "a",
              "resource": "data", "perm": "SELECT"})
    st.apply({"op": "auth_grant_role", "role": "a", "member": "b"})
    st.apply({"op": "auth_grant_role", "role": "b", "member": "c"})
    assert st.authorize("c", "SELECT", "data/x/y")   # c -> b -> a
    with pytest.raises(InvalidArgument):             # circular grant
        st.apply({"op": "auth_grant_role", "role": "c", "member": "a"})
    st.apply({"op": "auth_revoke_role", "role": "a", "member": "b"})
    assert not st.authorize("c", "SELECT", "data/x/y")


def test_role_store_drop_cleans_up():
    st = RoleStore()
    st.apply({"op": "auth_create_role", "name": "a"})
    st.apply({"op": "auth_create_role", "name": "b"})
    st.apply({"op": "auth_grant_role", "role": "a", "member": "b"})
    st.apply({"op": "auth_grant_perm", "role": "a",
              "resource": "data", "perm": "ALL"})
    st.apply({"op": "auth_drop_role", "name": "a"})
    assert "a" not in st.roles
    assert not st.roles["b"].member_of
    assert not st.perms
    with pytest.raises(NotFound):
        st.apply({"op": "auth_drop_role", "name": "a"})


def test_role_store_serialization_round_trip():
    st = RoleStore()
    st.apply({"op": "auth_create_role", "name": "r", "can_login": True,
              "salted_hash": hash_password("x")})
    st.apply({"op": "auth_grant_perm", "role": "r",
              "resource": "data/ks", "perm": "MODIFY"})
    st2 = RoleStore.from_dict(st.to_dict())
    assert st2.check_login("r", "x")
    assert st2.authorize("r", "MODIFY", "data/ks/t")


# -- CQL statements + enforcement (in-process cluster) -----------------------

def test_cql_role_ddl_and_lists():
    p = QLProcessor(LocalCluster(num_tablets=2))
    p.execute("CREATE ROLE admin WITH PASSWORD = 'pw' AND LOGIN = true "
              "AND SUPERUSER = true")
    p.execute("CREATE ROLE reader WITH PASSWORD = 'r' AND LOGIN = true")
    p.execute("GRANT SELECT ON ALL KEYSPACES TO reader")
    roles = p.execute("LIST ROLES")
    assert [r[0] for r in roles.rows] == ["admin", "reader"]
    perms = p.execute("LIST ALL PERMISSIONS")
    assert ("reader", "data", "SELECT") in perms.rows
    p.execute("REVOKE SELECT ON ALL KEYSPACES FROM reader")
    assert not p.execute("LIST ALL PERMISSIONS").rows
    p.execute("ALTER ROLE reader WITH SUPERUSER = true")
    roles = p.execute("LIST ROLES").dicts()
    assert roles[1]["is_superuser"] is True
    p.execute("DROP ROLE reader")
    assert len(p.execute("LIST ROLES").rows) == 1
    # idempotent forms
    p.execute("CREATE ROLE IF NOT EXISTS admin")
    p.execute("DROP ROLE IF EXISTS ghost")


def test_cql_enforcement_fails_closed(auth_on):
    cluster = LocalCluster(num_tablets=2)
    root = QLProcessor(cluster, login_role="root")
    # Bootstrap superuser applied directly to the store (the reference
    # seeds the cassandra superuser at initdb time).
    cluster.auth_op({"op": "auth_create_role", "name": "root",
                     "superuser": True, "can_login": True,
                     "salted_hash": hash_password("rootpw")})
    root.execute("CREATE TABLE t (k INT PRIMARY KEY, v TEXT)")
    root.execute("INSERT INTO t (k, v) VALUES (1, 'a')")
    root.execute("CREATE ROLE reader WITH PASSWORD = 'r' AND LOGIN = true")

    unauth = QLProcessor(cluster)       # no login at all
    with pytest.raises(Unauthorized):
        unauth.execute("SELECT * FROM t")

    reader = QLProcessor(cluster, login_role="reader")
    for stmt in ("SELECT * FROM t",
                 "INSERT INTO t (k, v) VALUES (2, 'b')",
                 "CREATE TABLE t2 (k INT PRIMARY KEY)",
                 "DROP TABLE t",
                 "ALTER TABLE t ADD x INT",
                 "CREATE ROLE sneaky",
                 "GRANT SELECT ON ALL KEYSPACES TO reader"):
        with pytest.raises(Unauthorized):
            reader.execute(stmt)

    root.execute("GRANT SELECT ON TABLE t TO reader")
    assert reader.execute("SELECT * FROM t").rows == [(1, "a")]
    with pytest.raises(Unauthorized):   # SELECT != MODIFY
        reader.execute("INSERT INTO t (k, v) VALUES (3, 'c')")
    root.execute("GRANT MODIFY ON KEYSPACE default TO reader")
    reader.execute("INSERT INTO t (k, v) VALUES (3, 'c')")
    root.execute("REVOKE SELECT ON TABLE t FROM reader")
    with pytest.raises(Unauthorized):
        reader.execute("SELECT * FROM t")


def test_cql_wire_auth_handshake(tmp_path, auth_on):
    from tests.test_cql_wire import WireClient
    from yugabyte_db_tpu.yql.cql import wire_protocol as W
    from yugabyte_db_tpu.yql.cql.server import CQLServer

    cluster = LocalCluster(num_tablets=2)
    cluster.auth_op({"op": "auth_create_role", "name": "cassandra",
                     "superuser": True, "can_login": True,
                     "salted_hash": hash_password("cassandra")})
    server = CQLServer(cluster)
    host, port = server.listen("127.0.0.1", 0)
    try:
        cli = WireClient(host, port)
        w = W.Writer()
        w.short(1)
        w.string("CQL_VERSION").string("3.4.4")
        cli._send(W.OP_STARTUP, w.getvalue())
        _s, opcode, body = cli._recv_frame()
        assert opcode == W.OP_AUTHENTICATE
        assert b"PasswordAuthenticator" in body
        # wrong password -> credentials error
        bad = W.Writer().bytes_(b"\x00cassandra\x00wrong").getvalue()
        cli._send(W.OP_AUTH_RESPONSE, bad)
        _s, opcode, body = cli._recv_frame()
        assert opcode == W.OP_ERROR
        # right password -> AUTH_SUCCESS, then statements flow
        good = W.Writer().bytes_(b"\x00cassandra\x00cassandra").getvalue()
        cli._send(W.OP_AUTH_RESPONSE, good)
        _s, opcode, _b = cli._recv_frame()
        assert opcode == W.OP_AUTH_SUCCESS
        kind, _, _ = cli.query(
            "CREATE TABLE ta (k INT, PRIMARY KEY (k))")
        assert kind == W.RESULT_SCHEMA_CHANGE
        cli.close()
        # a fresh connection that skips auth is rejected on QUERY
        cli2 = WireClient(host, port)
        cli2._send(W.OP_STARTUP, w.getvalue())
        _s, opcode, _b = cli2._recv_frame()
        assert opcode == W.OP_AUTHENTICATE
        with pytest.raises(Exception):
            cli2.query("SELECT * FROM ta")
        cli2.close()
    finally:
        server.shutdown()


def test_pg_wire_password_auth(tmp_path):
    import socket
    import struct

    from yugabyte_db_tpu.yql.pgsql.wire import PgServer

    FLAGS.set("ysql_require_auth", True)
    cluster = LocalCluster(num_tablets=2)
    cluster.auth_op({"op": "auth_create_role", "name": "postgres",
                     "can_login": True,
                     "salted_hash": hash_password("pg")})
    server = PgServer(cluster)
    host, port = server.listen("127.0.0.1", 0)

    def startup(sock, user):
        body = struct.pack(">I", 196608) + \
            b"user\x00" + user.encode() + b"\x00\x00"
        sock.sendall(struct.pack(">I", len(body) + 4) + body)

    def read_msg(sock, buf):
        while len(buf) < 5:
            buf += sock.recv(65536)
        tag = buf[:1]
        (ln,) = struct.unpack_from(">I", buf, 1)
        while len(buf) < 1 + ln:
            buf += sock.recv(65536)
        return tag, bytes(buf[5:1 + ln]), buf[1 + ln:]

    try:
        # wrong password fails closed
        s = socket.create_connection((host, port), timeout=10)
        startup(s, "postgres")
        tag, payload, rest = read_msg(s, b"")
        assert tag == b"R" and struct.unpack(">I", payload)[0] == 3
        pw = b"wrong\x00"
        s.sendall(b"p" + struct.pack(">I", len(pw) + 4) + pw)
        tag, payload, rest = read_msg(s, rest)
        assert tag == b"E" and b"authentication failed" in payload
        s.close()
        # right password authenticates and serves queries
        s = socket.create_connection((host, port), timeout=10)
        startup(s, "postgres")
        tag, payload, rest = read_msg(s, b"")
        assert tag == b"R"
        pw = b"pg\x00"
        s.sendall(b"p" + struct.pack(">I", len(pw) + 4) + pw)
        tag, payload, rest = read_msg(s, rest)
        assert tag == b"R" and struct.unpack(">I", payload)[0] == 0
        s.close()
    finally:
        FLAGS.set("ysql_require_auth", False)
        server.shutdown()


# -- distributed: role ops replicate through the master catalog --------------

def test_roles_replicate_through_master(tmp_path):
    from yugabyte_db_tpu.integration import MiniCluster
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster

    mc = MiniCluster(str(tmp_path), num_masters=3, num_tservers=3).start()
    try:
        mc.wait_tservers_registered()
        cc = ClientCluster(mc.client())
        p = QLProcessor(cc)
        p.execute("CREATE ROLE dadmin WITH PASSWORD = 'd' AND "
                  "LOGIN = true AND SUPERUSER = true")
        p.execute("GRANT SELECT ON ALL KEYSPACES TO dadmin")
        with pytest.raises(Exception):
            p.execute("CREATE ROLE dadmin")  # duplicate rejected
        # a second client session observes the replicated store
        cc2 = ClientCluster(mc.client("c2"))
        st = cc2.auth_store()
        assert st.check_login("dadmin", "d")
        assert st.authorize("dadmin", "SELECT", "data/ks/t")
        assert ("dadmin", "data", "SELECT") in st.list_perms()
    finally:
        mc.shutdown()
