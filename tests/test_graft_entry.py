"""Driver-gate regression tests.

The driver invokes ``__graft_entry__.dryrun_multichip(n)`` in a fresh process
whose ambient environment pins JAX_PLATFORMS to the axon real-TPU tunnel.
Rounds 1 and 2 both failed this gate (mesh reshape crash; then eager arrays
landing on the TPU backend → libtpu AOT mismatch).  These tests run the entry
points in subprocesses that reproduce the driver's environment shapes, so the
gate can never silently regress again.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.mesh


def _run_dryrun(n_devices, env_overrides, timeout=300, bench=False):
    env = dict(os.environ)
    # Start from the ambient (axon-pinned) environment, not the conftest's
    # cpu-pinned one: the driver does not inherit our test env.
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "axon"
    for k, v in env_overrides.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    code = (f"import __graft_entry__ as g; "
            f"g.dryrun_multichip({n_devices}, bench={bench}); "
            f"print('DRYRUN_OK')")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)
    return proc


@pytest.mark.parametrize("n", [8])
def test_dryrun_multichip_under_axon_env(n):
    """The exact round-2 failure mode: ambient env pins the TPU tunnel."""
    proc = _run_dryrun(n, {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_multichip_under_driver_cpu_env():
    """The documented driver recipe: host-platform device count + cpu."""
    proc = _run_dryrun(8, {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_multichip_odd_device_count():
    proc = _run_dryrun(4, {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


@pytest.mark.slow
def test_multichip_bench_metrics():
    """The MULTICHIP metrics sweep the driver records: real numbers at
    1/2/4/8 simulated devices plus the scaling-efficiency ratio."""
    import json

    proc = _run_dryrun(8, {}, timeout=480, bench=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("MULTICHIP_METRICS "))
    out = json.loads(line[len("MULTICHIP_METRICS "):])
    assert out["device_counts"] == [1, 2, 4, 8]
    for name in ("aggregate_range_scan_rows_per_sec",
                 "mesh_row_scan_rows_per_sec",
                 "tpch_q1_rows_per_sec", "tpch_q6_rows_per_sec"):
        by_dev = out["metrics"][name]["by_devices"]
        assert set(by_dev) == {"1", "2", "4", "8"}
        assert all(v > 0 for v in by_dev.values()), name
    # Throughput retention under 8-way partitioning (virtual devices
    # share one CPU, so this measures partition + collective overhead).
    assert out["scaling_efficiency"] >= 0.7, out["scaling_efficiency"]


def test_entry_compiles_in_process():
    """entry() must stay jittable (the driver compile-checks single-chip)."""
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
