"""Driver-gate regression tests.

The driver invokes ``__graft_entry__.dryrun_multichip(n)`` in a fresh process
whose ambient environment pins JAX_PLATFORMS to the axon real-TPU tunnel.
Rounds 1 and 2 both failed this gate (mesh reshape crash; then eager arrays
landing on the TPU backend → libtpu AOT mismatch).  These tests run the entry
points in subprocesses that reproduce the driver's environment shapes, so the
gate can never silently regress again.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dryrun(n_devices, env_overrides, timeout=300):
    env = dict(os.environ)
    # Start from the ambient (axon-pinned) environment, not the conftest's
    # cpu-pinned one: the driver does not inherit our test env.
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "axon"
    for k, v in env_overrides.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    code = (f"import __graft_entry__ as g; "
            f"g.dryrun_multichip({n_devices}); print('DRYRUN_OK')")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)
    return proc


@pytest.mark.parametrize("n", [8])
def test_dryrun_multichip_under_axon_env(n):
    """The exact round-2 failure mode: ambient env pins the TPU tunnel."""
    proc = _run_dryrun(n, {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


def test_dryrun_multichip_under_driver_cpu_env():
    """The documented driver recipe: host-platform device count + cpu."""
    proc = _run_dryrun(8, {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


def test_dryrun_multichip_odd_device_count():
    proc = _run_dryrun(4, {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DRYRUN_OK" in proc.stdout


def test_entry_compiles_in_process():
    """entry() must stay jittable (the driver compile-checks single-chip)."""
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
