"""yb-ctl multi-process cluster + bulk load + web dashboards.

Reference analogs: bin/yb-ctl (local cluster orchestrator spawning real
yb-master/yb-tserver processes — the ExternalMiniCluster deployment
shape), yb-bulk_load.cc, and the www/ dashboards served by every
daemon's webserver.
"""

import csv
import json
import os
import tempfile
import urllib.request

import pytest

from yugabyte_db_tpu.tools.yb_ctl import ClusterCtl


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read()


@pytest.fixture(scope="module")
def cluster():
    with tempfile.TemporaryDirectory() as root:
        ctl = ClusterCtl(os.path.join(root, "c"))
        ctl.create(num_masters=1, num_tservers=3)
        ctl.wait_tservers_registered()
        try:
            yield ctl
        finally:
            ctl.destroy()


def test_cluster_up_and_status(cluster):
    rows = cluster.status()
    assert len(rows) == 4
    assert all(r["alive"] and r["healthy"] for r in rows), rows


def test_bulk_load_and_query_over_tcp(cluster):
    from yugabyte_db_tpu.client.client import YBClient
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
    from yugabyte_db_tpu.storage.scan_spec import ScanSpec
    from yugabyte_db_tpu.tools.bulk_load import load_csv

    client = YBClient.connect(cluster.master_addresses())
    client.create_table("bulk", [
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("n", DataType.INT64),
        ColumnSchema("note", DataType.STRING),
    ], num_tablets=4)

    with tempfile.NamedTemporaryFile("w", suffix=".csv", newline="",
                                     delete=False) as f:
        w = csv.writer(f)
        w.writerow(["k", "n", "note"])
        for i in range(500):
            w.writerow([f"row{i:04d}", i, f"note-{i}" if i % 3 else ""])
        path = f.name
    try:
        n = load_csv(client, "bulk", path, batch=128)
        assert n == 500
        s = YBSession(client)
        table = client.open_table("bulk")
        res = s.scan(table, ScanSpec(projection=["k", "n", "note"]))
        assert len(res.rows) == 500
        got = {r[0]: (r[1], r[2]) for r in res.rows}
        assert got["row0003"] == (3, None)  # empty CSV cell -> NULL
        assert got["row0004"] == (4, "note-4")
    finally:
        os.unlink(path)


def test_dashboards_and_memz(cluster):
    state = cluster.load()
    master = next(d for d in state["daemons"] if d["role"] == "master")
    ts = next(d for d in state["daemons"] if d["role"] == "tserver")
    base = f"http://127.0.0.1:{master['web_port']}"
    home = _get(base + "/").decode()
    assert "m-0" in home and "/dashboards/tables" in home
    tables = _get(base + "/dashboards/tables").decode()
    assert "<table>" in tables and "bulk" in tables
    tablets = _get(base + "/dashboards/tablet-servers").decode()
    assert "ts-0" in tablets
    memz = json.loads(_get(base + "/memz"))
    assert memz["max_rss_kb"] > 0
    ts_tablets = _get(
        f"http://127.0.0.1:{ts['web_port']}/dashboards/tablets").decode()
    assert "leader" in ts_tablets or "follower" in ts_tablets
    # per-device residency: the /memz hbm_cache.by_device split as a
    # table (rows appear once device runs are resident; the endpoint
    # itself must always serve)
    hbm = _get(
        f"http://127.0.0.1:{ts['web_port']}/dashboards/hbm-devices").decode()
    assert "HBM devices" in hbm
    hbm_json = json.loads(_get(
        f"http://127.0.0.1:{ts['web_port']}/hbm-devices"))
    assert isinstance(hbm_json, list)
    ts_memz = json.loads(_get(f"http://127.0.0.1:{ts['web_port']}/memz"))
    assert "by_device" in ts_memz["hbm_cache"]
    # prometheus endpoint still serves on every daemon
    prom = _get(base + "/metrics").decode()
    assert "rpc_requests_total" in prom


def test_stop_start_preserves_data(cluster):
    from yugabyte_db_tpu.client.client import YBClient
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.storage.scan_spec import ScanSpec

    cluster.stop()
    assert all(not r["alive"] for r in cluster.status())
    cluster.start()
    cluster.wait_tservers_registered()
    client = YBClient.connect(cluster.master_addresses())
    table = client.open_table("bulk")
    res = YBSession(client).scan(table, ScanSpec(projection=["k"]))
    assert len(res.rows) == 500


def test_load_tester_workloads(cluster):
    from yugabyte_db_tpu.tools.load_test import run_keyvalue, run_scan

    out = run_keyvalue(cluster.master_addresses(), num_ops=600,
                       threads=3, read_ratio=0.3, batch=32,
                       value_size=16)
    assert out["write"]["ops"] > 0 and out["write"]["errors"] == 0
    assert out["write"]["ops_per_sec"] > 0
    out = run_scan(cluster.master_addresses(), num_ops=30, threads=3,
                   limit=50)
    assert out["scan"]["ops"] == 30 and out["scan"]["errors"] == 0
    assert out["scan"]["p99_us"] > 0


def test_yb_admin_split_tablet_and_rebalance(cluster, capsys):
    from yugabyte_db_tpu.client.client import YBClient
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
    from yugabyte_db_tpu.storage.scan_spec import ScanSpec
    from yugabyte_db_tpu.tools import yb_admin
    from yugabyte_db_tpu.tools.admin_client import AdminClient

    client = YBClient.connect(cluster.master_addresses())
    table = client.create_table("adm", [
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64)], num_tablets=2)
    s = YBSession(client)
    for i in range(120):
        s.insert(table, {"k": f"adm{i:04d}", "v": i})
    s.flush()

    admin = AdminClient.connect(cluster.master_addresses())
    parent = admin.table_locations("adm")[0]["tablet_id"]
    resp = admin.split_tablet("adm", parent)
    children = resp["children"]
    assert len(children) == 2
    after = [t["tablet_id"] for t in admin.table_locations("adm")]
    assert parent not in after and set(children) <= set(after)
    # Data survives the split over the TCP path.
    res = YBSession(client).scan(table, ScanSpec(projection=["k", "v"]))
    assert dict(res.rows) == {f"adm{i:04d}": i for i in range(120)}

    # CLI wiring: rebalance prints either a move or "balanced".
    rc = yb_admin.main(["--master", cluster.master_addresses(),
                        "rebalance"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "moved leader" in out or "balanced" in out
    assert "leaders" in out  # the per-tserver count table

    # Master dashboard: split lineage rendered parent -> children.
    state = cluster.load()
    master = next(d for d in state["daemons"] if d["role"] == "master")
    page = _get(f"http://127.0.0.1:{master['web_port']}"
                "/dashboards/tablet-splits").decode()
    assert parent in page and children[0] in page
    splits = json.loads(_get(
        f"http://127.0.0.1:{master['web_port']}/tablet-splits"))
    rec = next(r for r in splits if r["parent"] == parent)
    assert rec["state"] == "COMMITTED"
