"""PG extended query protocol (Parse/Bind/Describe/Execute/Sync) over a
raw socket — the exact message flow libpq's PQexecParams/psycopg2 uses.
"""

import socket
import struct

import pytest

from yugabyte_db_tpu.yql.cql.processor import LocalCluster
from yugabyte_db_tpu.yql.pgsql.wire import PgServer

_U32 = struct.Struct(">I")


class ExtClient:
    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.buf = b""
        body = _U32.pack(196608) + b"user\x00pg\x00\x00"
        self.sock.sendall(_U32.pack(len(body) + 4) + body)
        # consume until ReadyForQuery
        while True:
            tag, _payload = self.read_msg()
            if tag == b"Z":
                break

    def close(self):
        self.sock.close()

    def send(self, tag: bytes, payload: bytes = b""):
        self.sock.sendall(tag + _U32.pack(len(payload) + 4) + payload)

    def read_msg(self):
        while len(self.buf) < 5:
            chunk = self.sock.recv(65536)
            assert chunk, "closed"
            self.buf += chunk
        tag = self.buf[:1]
        (ln,) = _U32.unpack_from(self.buf, 1)
        while len(self.buf) < 1 + ln:
            chunk = self.sock.recv(65536)
            assert chunk, "closed"
            self.buf += chunk
        payload = self.buf[5:1 + ln]
        self.buf = self.buf[1 + ln:]
        return tag, payload

    # -- extended-protocol helpers ------------------------------------------
    def parse(self, name: str, query: str):
        self.send(b"P", name.encode() + b"\x00" + query.encode()
                  + b"\x00" + struct.pack(">H", 0))

    def bind(self, portal: str, stmt: str, params: list):
        out = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
        out += struct.pack(">H", 0)        # all-text param formats
        out += struct.pack(">H", len(params))
        for p in params:
            if p is None:
                out += struct.pack(">i", -1)
            else:
                b = str(p).encode()
                out += struct.pack(">i", len(b)) + b
        out += struct.pack(">H", 0)        # result formats: default text
        self.send(b"B", out)

    def describe_portal(self, portal: str):
        self.send(b"D", b"P" + portal.encode() + b"\x00")

    def execute(self, portal: str, max_rows: int = 0):
        self.send(b"E", portal.encode() + b"\x00"
                  + struct.pack(">i", max_rows))

    def sync(self):
        self.send(b"S")

    def drain_until_ready(self):
        msgs = []
        while True:
            tag, payload = self.read_msg()
            msgs.append((tag, payload))
            if tag == b"Z":
                return msgs

    def run(self, query: str, params: list = ()):  # full PQexecParams flow
        self.parse("", query)
        self.bind("", "", list(params))
        self.describe_portal("")
        self.execute("")
        self.sync()
        return self.drain_until_ready()


def _rows(msgs):
    out = []
    for tag, payload in msgs:
        if tag != b"D":
            continue
        (n,) = struct.unpack_from(">H", payload, 0)
        pos, row = 2, []
        for _ in range(n):
            (ln,) = struct.unpack_from(">i", payload, pos)
            pos += 4
            if ln < 0:
                row.append(None)
            else:
                row.append(payload[pos:pos + ln].decode())
                pos += ln
        out.append(tuple(row))
    return out


def _tags(msgs):
    return [t for t, _p in msgs]


@pytest.fixture
def cli():
    server = PgServer(LocalCluster(num_tablets=2))
    host, port = server.listen("127.0.0.1", 0)
    c = ExtClient(host, port)
    yield c
    c.close()
    server.shutdown()


def test_extended_ddl_dml_select(cli):
    msgs = cli.run("CREATE TABLE t (k INT PRIMARY KEY, v TEXT, d FLOAT8)")
    assert b"1" in _tags(msgs) and b"2" in _tags(msgs)
    assert b"C" in _tags(msgs) and b"Z" in _tags(msgs)

    # parameterized inserts: text params coerced to column types
    for i in range(5):
        msgs = cli.run("INSERT INTO t (k, v, d) VALUES ($1, $2, $3)",
                       [i, f"row{i}", i * 1.5])
        assert b"E" not in _tags(msgs), msgs
    msgs = cli.run("SELECT k, v, d FROM t WHERE k >= $1 ORDER BY k", [3])
    tags = _tags(msgs)
    # Describe produced a RowDescription before the data rows.
    assert tags.index(b"T") < tags.index(b"D")
    assert _rows(msgs) == [("3", "row3", "4.5"), ("4", "row4", "6.0")]


def test_extended_named_statement_reuse(cli):
    cli.run("CREATE TABLE n (k INT PRIMARY KEY, v BIGINT)")
    cli.parse("ins", "INSERT INTO n (k, v) VALUES ($1, $2)")
    for i in range(3):
        cli.bind("", "ins", [i, i * 100])
        cli.execute("")
    cli.sync()
    msgs = cli.drain_until_ready()
    assert _tags(msgs).count(b"C") == 3   # three CommandCompletes
    msgs = cli.run("SELECT count(*) FROM n")
    assert _rows(msgs) == [("3",)]


def test_extended_error_skips_until_sync(cli):
    cli.run("CREATE TABLE e (k INT PRIMARY KEY)")
    cli.parse("", "INSERT INTO e (k) VALUES ($1)")
    cli.bind("", "", ["notanint"])
    cli.describe_portal("")
    cli.execute("")      # must be skipped after the bind error surfaces
    cli.sync()
    msgs = cli.drain_until_ready()
    tags = _tags(msgs)
    assert b"E" in tags                  # one ErrorResponse
    assert tags[-1] == b"Z"              # and recovery at Sync
    # the connection works again afterwards
    msgs = cli.run("INSERT INTO e (k) VALUES ($1)", [7])
    assert b"E" not in _tags(msgs)
    assert _rows(cli.run("SELECT k FROM e")) == [("7",)]


def test_extended_unknown_statement_errors(cli):
    cli.bind("", "missing", [])
    cli.sync()
    msgs = cli.drain_until_ready()
    assert _tags(msgs)[0] == b"E"


def test_extended_null_param(cli):
    cli.run("CREATE TABLE np (k INT PRIMARY KEY, v TEXT)")
    cli.run("INSERT INTO np (k, v) VALUES ($1, $2)", [1, None])
    assert _rows(cli.run("SELECT v FROM np WHERE k = $1", [1])) == [(None,)]
