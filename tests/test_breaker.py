"""Circuit-breaker fault domain: state machine + engine degrade/recover.

Unit half: the CLOSED -> OPEN -> HALF_OPEN transitions on an injectable
clock (no sleeping through cooldowns). Integration half: a device
dispatch fault trips the TPU engine into host-serve mode — scans stay
byte-identical to the CPU oracle, ``yb_engine_degraded`` goes 1 -> 0
across the half-open probe, and neither residency pins nor the device
MemTracker leak across the degrade/recover cycle.
"""

import random
import time

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.storage.breaker import (CLOSED, HALF_OPEN, OPEN,
                                             CircuitBreaker, degraded,
                                             health_report)
from yugabyte_db_tpu.storage.residency import hbm_cache
from yugabyte_db_tpu.utils.fault_injection import arm_fault_once
from yugabyte_db_tpu.utils.metrics import process_registry
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def degraded_gauge() -> int:
    """Read yb_engine_degraded off the process registry the way a
    scraper would (the callback gauge lives on the entity the breaker
    module wired; the text endpoint is the public surface)."""
    total = 0
    for line in process_registry().prometheus_text().splitlines():
        if line.startswith("yb_engine_degraded"):
            total += int(float(line.rsplit(" ", 1)[1]))
    return total


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def make_breaker(threshold=3, cooldown=1.0):
    clock = FakeClock()
    b = CircuitBreaker("test", failure_threshold=threshold,
                       cooldown_s=cooldown, clock=clock)
    return b, clock


# ---------------------------------------------------------------- unit


def test_breaker_stays_closed_below_threshold():
    b, _ = make_breaker(threshold=3)
    for _ in range(2):
        assert b.allow()
        b.record_failure(RuntimeError("x"))
    assert b.state == CLOSED
    assert b.allow()
    assert not b.is_degraded


def test_breaker_success_resets_failure_streak():
    b, _ = make_breaker(threshold=3)
    b.record_failure(RuntimeError("x"))
    b.record_failure(RuntimeError("x"))
    b.record_success()
    b.record_failure(RuntimeError("x"))
    b.record_failure(RuntimeError("x"))
    assert b.state == CLOSED  # streak broke; never reached 3 consecutive


def test_breaker_trips_open_and_blocks_until_cooldown():
    b, clock = make_breaker(threshold=2, cooldown=5.0)
    b.record_failure(RuntimeError("a"))
    b.record_failure(RuntimeError("b"))
    assert b.state == OPEN
    assert b.trips == 1
    assert not b.allow()
    clock.advance(4.9)
    assert not b.allow()
    clock.advance(0.2)
    assert b.allow()  # cooldown elapsed: half-open, probe admitted
    assert b.state == HALF_OPEN


def test_breaker_half_open_admits_exactly_one_probe():
    b, clock = make_breaker(threshold=1, cooldown=1.0)
    b.record_failure(RuntimeError("x"))
    clock.advance(1.5)
    assert b.allow()       # the probe
    assert not b.allow()   # everyone else stays on the fallback
    assert not b.allow()
    b.record_success()
    assert b.state == CLOSED
    assert b.allow()


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    b, clock = make_breaker(threshold=1, cooldown=2.0)
    b.record_failure(RuntimeError("x"))
    clock.advance(2.5)
    assert b.allow()
    b.record_failure(RuntimeError("probe died"))
    assert b.state == OPEN
    assert b.trips == 2
    assert not b.allow()          # fresh cooldown from the probe failure
    clock.advance(1.9)
    assert not b.allow()
    clock.advance(0.2)
    assert b.allow()


def test_breaker_trip_opens_immediately_and_reset_closes():
    b, _ = make_breaker(threshold=5)
    exc = RuntimeError("native module gone")
    b.trip(exc)
    assert b.state == OPEN
    assert b.last_error is exc
    assert b in degraded()
    report = health_report()
    assert report["status"] == "degraded"
    assert any(d["breaker"] == "test" for d in report["degraded"])
    b.reset()
    assert b.state == CLOSED
    assert b not in degraded()


def test_degraded_gauge_counts_open_breakers():
    base = degraded_gauge()
    b, clock = make_breaker(threshold=1, cooldown=1.0)
    b.record_failure(RuntimeError("x"))
    assert degraded_gauge() == base + 1
    clock.advance(1.5)
    assert b.allow()
    # HALF_OPEN still counts as degraded — only a successful probe clears.
    assert degraded_gauge() == base + 1
    b.record_success()
    assert degraded_gauge() == base


# ----------------------------------------------------- engine integration


def _make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
    ], table_id="t")


def _load(schema, engines, n=120, seed=11):
    rnd = random.Random(seed)
    cids = {c.name: c.col_id for c in schema.value_columns}
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 3)
        key = schema.encode_primary_key(
            {"k": rnd.choice(["p", "q"]), "r": i % 53},
            compute_hash_code(schema, {"k": rnd.choice(["p", "q"])}))
        row = RowVersion(key, ht=ht, liveness=True, columns={
            cids["a"]: rnd.randrange(-100, 100),
            cids["b"]: f"v{i}"})
        for eng in engines:
            eng.apply([row])
    return ht


def _assert_identical(cpu, tpu, spec):
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.columns == b.columns
    assert a.rows == b.rows
    assert a.resume_key == b.resume_key
    return b


def test_engine_degrade_and_recover_byte_identical():
    """The acceptance scenario: device-dispatch fault -> breaker opens,
    scans re-serve from host byte-identically, yb_engine_degraded goes
    1 -> 0 after the half-open probe, and no residency pin or device
    MemTracker bytes leak."""
    schema = _make_schema()
    opts = {"breaker_failure_threshold": 1, "breaker_cooldown_s": 0.05}
    cpu = make_engine("cpu", schema, dict(opts))
    tpu = make_engine("tpu", schema, dict(opts, rows_per_block=32))
    max_ht = _load(schema, [cpu, tpu])
    cpu.flush()
    tpu.flush()
    spec = ScanSpec(read_ht=max_ht + 1, limit=1000)

    def quiesce():
        tpu._drop_overlay_cache()
        hbm_cache().evict_unpinned()

    _assert_identical(cpu, tpu, spec)  # healthy baseline
    quiesce()
    pins0 = hbm_cache().pinned_bytes()
    dev0 = tpu.device_tracker.consumption
    base = degraded_gauge()

    # One armed dispatch fault trips the threshold-1 breaker; the faulted
    # batch itself must already be re-served from the host, byte-identical.
    arm_fault_once("fault.tpu_dispatch")
    _assert_identical(cpu, tpu, spec)
    assert tpu.breaker.state == OPEN
    assert degraded_gauge() == base + 1
    assert tpu.breaker in degraded()

    # While quarantined (cooldown not yet elapsed) every scan serves from
    # the host path — still byte-identical, still degraded.
    _assert_identical(cpu, tpu, spec)
    assert tpu.breaker.state == OPEN

    # Cooldown elapses; the next scan is the half-open probe. It succeeds
    # (the fault was one-shot) and the breaker closes: recovered.
    time.sleep(0.06)
    _assert_identical(cpu, tpu, spec)
    assert tpu.breaker.state == CLOSED
    assert degraded_gauge() == base

    # No leaks across the whole degrade/recover cycle.
    quiesce()
    assert hbm_cache().pinned_bytes() == pins0
    assert tpu.device_tracker.consumption == dev0

    cpu.close()
    tpu.close()


def test_engine_open_breaker_serves_writes_made_during_degrade():
    """Writes applied while the device path is quarantined are visible
    through the host-serve path and after recovery (the host structures
    are authoritative; the device is only an accelerator)."""
    schema = _make_schema()
    opts = {"breaker_failure_threshold": 1, "breaker_cooldown_s": 0.05}
    cpu = make_engine("cpu", schema, dict(opts))
    tpu = make_engine("tpu", schema, dict(opts, rows_per_block=32))
    max_ht = _load(schema, [cpu, tpu], n=40)
    cpu.flush()
    tpu.flush()

    arm_fault_once("fault.tpu_dispatch")
    tpu.scan(ScanSpec(read_ht=max_ht + 1, limit=10))
    assert tpu.breaker.state == OPEN

    # New write lands in the memtable while degraded.
    cids = {c.name: c.col_id for c in schema.value_columns}
    key = schema.encode_primary_key(
        {"k": "zz", "r": 1}, compute_hash_code(schema, {"k": "zz"}))
    row = RowVersion(key, ht=max_ht + 2, liveness=True,
                     columns={cids["a"]: 777, cids["b"]: "late"})
    cpu.apply([row])
    tpu.apply([row])
    spec = ScanSpec(read_ht=max_ht + 3, limit=1000)

    _assert_identical(cpu, tpu, spec)     # host-serve sees the new row
    time.sleep(0.06)
    _assert_identical(cpu, tpu, spec)     # probe succeeds, device path back
    assert tpu.breaker.state == CLOSED

    cpu.close()
    tpu.close()
