"""Log-cache eviction, remote bootstrap, and exactly-once retries.

Reference test analogs: remote_bootstrap-itest.cc (kill a replica, write
past log GC, watch it re-seed), and the RetryableRequests dedup tests
(retryable_requests.h:34).
"""

import time

import pytest

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.fault_injection import arm_fault_once
from yugabyte_db_tpu.utils.metrics import faults_fired

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("v", DataType.INT64),
]


def wait_for(pred, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = pred()
        if r:
            return r
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def test_exactly_once_duplicate_write(tmp_path):
    """The same (client_id, request_id) applied twice writes ONCE and the
    duplicate returns the original hybrid time."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("once", COLUMNS, num_tablets=1)
        loc = client.meta_cache.locations("once").tablets[0]
        rows = [{"k": "dup", "v": 1}]
        enc = wire.encode_rows([
            __import__("yugabyte_db_tpu.storage.row_version",
                       fromlist=["RowVersion"]).RowVersion(
                table.encode_key({"k": "dup"}), ht=0, liveness=True,
                columns={table.col_id["v"]: 1})])
        payload = {"rows": enc, "client_id": client.client_id,
                   "request_id": 7}
        r1 = client.tablet_rpc("once", loc, "ts.write", dict(payload))
        r2 = client.tablet_rpc("once", loc, "ts.write", dict(payload))
        assert r1["ht"] == r2["ht"], "duplicate must return original ht"
        # exactly one version of the row exists on the leader
        ts = next(ts for ts in c.tservers.values()
                  if any(p.tablet_id == loc.tablet_id and p.is_leader()
                         for p in ts.tablet_manager.peers()))
        peer = ts.tablet_manager.get(loc.tablet_id)
        versions = peer.tablet.engine.memtable.versions(
            table.encode_key({"k": "dup"}))
        assert len(list(versions)) == 1
        # dedup state survives flush + restart replay
        peer.flush()
        assert peer.tablet.retryable.seen(client.client_id, 7) == r1["ht"]
    finally:
        c.shutdown()


def test_log_cache_eviction_bounded(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("ev", COLUMNS, num_tablets=1)
        s = YBSession(client)
        for i in range(300):
            s.insert(table, {"k": f"x{i}", "v": i})
            if i % 50 == 49:
                s.flush()
        s.flush()
        loc = client.meta_cache.locations("ev").tablets[0]
        for ts in c.tservers.values():
            try:
                peer = ts.tablet_manager.get(loc.tablet_id)
            except Exception:
                continue
            before = len(peer.raft._entries)
            peer.flush()
            after = len(peer.raft._entries)
            assert after <= before
            assert after < 250, f"cache not bounded: {after}"
        # reads still correct after eviction
        res = s.scan(table, ScanSpec(projection=["k", "v"]))
        assert len(res.rows) == 300
    finally:
        c.shutdown()


def test_remote_bootstrap_after_log_gc(tmp_path):
    """Kill a replica, write + flush past log GC on the survivors,
    restart it: it must catch up via remote bootstrap (install), not log
    replay, and serve identical data."""
    c = MiniCluster(str(tmp_path) + "/rb", num_masters=1,
                    num_tservers=3)
    c.start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("rb", COLUMNS, num_tablets=1)
        s = YBSession(client)
        for i in range(50):
            s.insert(table, {"k": f"a{i}", "v": i})
        s.flush()
        loc = client.meta_cache.locations("rb", refresh=True).tablets[0]
        leader = next(
            ts.uuid for ts in c.tservers.values()
            if any(p.tablet_id == loc.tablet_id and p.is_leader()
                   for p in ts.tablet_manager.peers()))
        victim = next(r for r in loc.replicas if r != leader)
        c.stop_tserver(victim)

        # Many separate write BATCHES (one raft entry each) so the
        # victim's position falls far below the eviction floor — normal
        # cached catch-up must be impossible, only bootstrap can work.
        def write_batch(start):
            for i in range(start, start + 5):
                s.insert(table, {"k": f"b{i}", "v": i})
            s.flush()
        wait_for(lambda: _try(write_batch, 0), msg="writes after kill")
        for r in range(1, 30):
            write_batch(r * 5)
        for ts in c.tservers.values():
            for p in ts.tablet_manager.peers():
                if p.tablet_id == loc.tablet_id:
                    p.flush()
                    assert min(p.raft._entries, default=10**9) > 3

        c.start_tserver(victim)

        def caught_up():
            try:
                ts = c.tservers[victim]
                peer = ts.tablet_manager.get(loc.tablet_id)
            except Exception:
                return False
            if ts.tablet_manager.bootstrap_installs < 1:
                return False
            st = peer.raft.stats()
            leaders = [p for t2 in c.tservers.values()
                       for p in t2.tablet_manager.peers()
                       if p.tablet_id == loc.tablet_id and p.is_leader()]
            if not leaders:
                return False
            return st["applied_index"] >= \
                leaders[0].raft.stats()["commit_index"] - 1
        wait_for(caught_up, timeout=60.0, msg="remote bootstrap catch-up")

        # The re-seeded replica holds the full data set.
        ts = c.tservers[victim]
        peer = ts.tablet_manager.get(loc.tablet_id)
        res = peer.tablet.engine.scan(ScanSpec(projection=["k"]))
        assert len(res.rows) == 200  # 50 a-keys + 150 b-keys
    finally:
        c.shutdown()


def _try(fn, *args):
    try:
        fn(*args)
        return True
    except Exception:
        return False


def test_crash_recovery_replays_wal_and_dedups_retries(tmp_path):
    """WAL sync fault mid-workload, then crash-restart the leader: the
    tablet must come back via bootstrap WAL replay with every acked row,
    and a re-sent (client_id, request_id) write must dedup to the
    ORIGINAL hybrid time — RetryableRequests state is rebuilt by replay,
    so a client retrying across the crash still gets exactly-once."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("cr", COLUMNS, num_tablets=1)
        s = YBSession(client)
        for i in range(30):
            s.insert(table, {"k": f"a{i}", "v": i})
        s.flush()

        # Mid-workload: the next WAL sync fails. On the leader raft
        # swallows it (the majority acks via follower appends), so the
        # writes below must still be acked and durable cluster-wide.
        fired0 = faults_fired("fault.wal_sync_failed")
        arm_fault_once("fault.wal_sync_failed")
        for i in range(30, 60):
            s.insert(table, {"k": f"a{i}", "v": i})
        s.flush()
        assert faults_fired("fault.wal_sync_failed") == fired0 + 1

        # One write with an explicit request id (the retry-dedup probe).
        loc = client.meta_cache.locations("cr", refresh=True).tablets[0]
        enc = wire.encode_rows([RowVersion(
            table.encode_key({"k": "dup"}), ht=0, liveness=True,
            columns={table.col_id["v"]: 999})])
        payload = {"rows": enc, "client_id": client.client_id,
                   "request_id": 4242}
        r1 = client.tablet_rpc("cr", loc, "ts.write", dict(payload))
        assert r1["code"] == "ok"

        # Crash-restart the leader.
        leader = next(
            ts.uuid for ts in c.tservers.values()
            if any(p.tablet_id == loc.tablet_id and p.is_leader()
                   for p in ts.tablet_manager.peers()))
        c.stop_tserver(leader)
        c.start_tserver(leader)

        # The restarted replica replays its WAL: all 61 acked rows back.
        def replayed():
            try:
                peer = c.tservers[leader].tablet_manager.get(loc.tablet_id)
                res = peer.tablet.engine.scan(ScanSpec(projection=["k"]))
            except Exception:
                return False
            return len(res.rows) == 61
        wait_for(replayed, timeout=60.0, msg="bootstrap WAL replay")

        # The client's RETRY of the same request (same client_id +
        # request_id, re-sent because the crash made the first ack
        # uncertain from its point of view) must be absorbed by dedup.
        loc = client.meta_cache.locations("cr", refresh=True).tablets[0]
        r2 = client.tablet_rpc("cr", loc, "ts.write", dict(payload))
        assert r2["code"] == "ok"
        assert r2["ht"] == r1["ht"], \
            "replayed RetryableRequests must return the original ht"

        # And the cluster still serves every acked row exactly once.
        res = s.scan(table, ScanSpec(projection=["k", "v"]))
        assert len(res.rows) == 61
        assert sum(1 for row in res.rows if row[0] == "dup") == 1
    finally:
        c.shutdown()
