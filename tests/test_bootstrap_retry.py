"""Log-cache eviction, remote bootstrap, and exactly-once retries.

Reference test analogs: remote_bootstrap-itest.cc (kill a replica, write
past log GC, watch it re-seed), and the RetryableRequests dedup tests
(retryable_requests.h:34).
"""

import time

import pytest

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.scan_spec import ScanSpec

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("v", DataType.INT64),
]


def wait_for(pred, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = pred()
        if r:
            return r
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def test_exactly_once_duplicate_write(tmp_path):
    """The same (client_id, request_id) applied twice writes ONCE and the
    duplicate returns the original hybrid time."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("once", COLUMNS, num_tablets=1)
        loc = client.meta_cache.locations("once").tablets[0]
        rows = [{"k": "dup", "v": 1}]
        enc = wire.encode_rows([
            __import__("yugabyte_db_tpu.storage.row_version",
                       fromlist=["RowVersion"]).RowVersion(
                table.encode_key({"k": "dup"}), ht=0, liveness=True,
                columns={table.col_id["v"]: 1})])
        payload = {"rows": enc, "client_id": client.client_id,
                   "request_id": 7}
        r1 = client.tablet_rpc("once", loc, "ts.write", dict(payload))
        r2 = client.tablet_rpc("once", loc, "ts.write", dict(payload))
        assert r1["ht"] == r2["ht"], "duplicate must return original ht"
        # exactly one version of the row exists on the leader
        ts = next(ts for ts in c.tservers.values()
                  if any(p.tablet_id == loc.tablet_id and p.is_leader()
                         for p in ts.tablet_manager.peers()))
        peer = ts.tablet_manager.get(loc.tablet_id)
        versions = peer.tablet.engine.memtable.versions(
            table.encode_key({"k": "dup"}))
        assert len(list(versions)) == 1
        # dedup state survives flush + restart replay
        peer.flush()
        assert peer.tablet.retryable.seen(client.client_id, 7) == r1["ht"]
    finally:
        c.shutdown()


def test_log_cache_eviction_bounded(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("ev", COLUMNS, num_tablets=1)
        s = YBSession(client)
        for i in range(300):
            s.insert(table, {"k": f"x{i}", "v": i})
            if i % 50 == 49:
                s.flush()
        s.flush()
        loc = client.meta_cache.locations("ev").tablets[0]
        for ts in c.tservers.values():
            try:
                peer = ts.tablet_manager.get(loc.tablet_id)
            except Exception:
                continue
            before = len(peer.raft._entries)
            peer.flush()
            after = len(peer.raft._entries)
            assert after <= before
            assert after < 250, f"cache not bounded: {after}"
        # reads still correct after eviction
        res = s.scan(table, ScanSpec(projection=["k", "v"]))
        assert len(res.rows) == 300
    finally:
        c.shutdown()


def test_remote_bootstrap_after_log_gc(tmp_path):
    """Kill a replica, write + flush past log GC on the survivors,
    restart it: it must catch up via remote bootstrap (install), not log
    replay, and serve identical data."""
    c = MiniCluster(str(tmp_path) + "/rb", num_masters=1,
                    num_tservers=3)
    c.start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("rb", COLUMNS, num_tablets=1)
        s = YBSession(client)
        for i in range(50):
            s.insert(table, {"k": f"a{i}", "v": i})
        s.flush()
        loc = client.meta_cache.locations("rb", refresh=True).tablets[0]
        leader = next(
            ts.uuid for ts in c.tservers.values()
            if any(p.tablet_id == loc.tablet_id and p.is_leader()
                   for p in ts.tablet_manager.peers()))
        victim = next(r for r in loc.replicas if r != leader)
        c.stop_tserver(victim)

        # Many separate write BATCHES (one raft entry each) so the
        # victim's position falls far below the eviction floor — normal
        # cached catch-up must be impossible, only bootstrap can work.
        def write_batch(start):
            for i in range(start, start + 5):
                s.insert(table, {"k": f"b{i}", "v": i})
            s.flush()
        wait_for(lambda: _try(write_batch, 0), msg="writes after kill")
        for r in range(1, 30):
            write_batch(r * 5)
        for ts in c.tservers.values():
            for p in ts.tablet_manager.peers():
                if p.tablet_id == loc.tablet_id:
                    p.flush()
                    assert min(p.raft._entries, default=10**9) > 3

        c.start_tserver(victim)

        def caught_up():
            try:
                ts = c.tservers[victim]
                peer = ts.tablet_manager.get(loc.tablet_id)
            except Exception:
                return False
            if ts.tablet_manager.bootstrap_installs < 1:
                return False
            st = peer.raft.stats()
            leaders = [p for t2 in c.tservers.values()
                       for p in t2.tablet_manager.peers()
                       if p.tablet_id == loc.tablet_id and p.is_leader()]
            if not leaders:
                return False
            return st["applied_index"] >= \
                leaders[0].raft.stats()["commit_index"] - 1
        wait_for(caught_up, timeout=60.0, msg="remote bootstrap catch-up")

        # The re-seeded replica holds the full data set.
        ts = c.tservers[victim]
        peer = ts.tablet_manager.get(loc.tablet_id)
        res = peer.tablet.engine.scan(ScanSpec(projection=["k"]))
        assert len(res.rows) == 200  # 50 a-keys + 150 b-keys
    finally:
        c.shutdown()


def _try(fn, *args):
    try:
        fn(*args)
        return True
    except Exception:
        return False
