"""Randomized concurrency stress over the threaded Raft control plane:
election storms, partitions, config changes, WAL-sync faults, and
kill/restart races under concurrent write load, with apply-order and
replica-agreement invariants asserted after every storm.

Reference analog: raft_consensus-itest.cc under stress + the apply-order
assertions of operation_order_verifier.cc (the tsan-build discipline,
exercised here as randomized interleavings rather than a sanitizer).
"""

import random
import threading
import time

import pytest

from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.fault_injection import arm_fault_once, clear_faults

# Excluded from tier-1 (-m 'not slow'): multi-minute rig, full runs keep it.
pytestmark = pytest.mark.slow

COLUMNS = [ColumnSchema("k", DataType.INT64, ColumnKind.HASH),
           ColumnSchema("v", DataType.INT64)]


def _assert_replicas_agree(mc, table_name, acked, unknown, timeout_s=45.0):
    """Every replica of every tablet converges to identical applied
    content; the union holds every acked write exactly once."""
    deadline = time.monotonic() + timeout_s
    last_err = None
    while time.monotonic() < deadline:
        try:
            by_tablet: dict = {}
            for ts in mc.tservers.values():
                for peer in ts.tablet_manager.peers():
                    if peer.tablet.meta.table_name != table_name:
                        continue
                    # Applied content signature: merged rows per key at
                    # the replica's applied state.
                    sig = {}
                    eng = peer.tablet.engine
                    for key, vers in eng.dump_entries():
                        sig[key] = tuple(
                            (r.ht, r.tombstone,
                             tuple(sorted(r.columns.items())))
                            for r in vers)
                    for key in eng.memtable.scan_keys(b"", b""):
                        sig[key] = tuple(
                            (r.ht, r.tombstone,
                             tuple(sorted(r.columns.items())))
                            for r in sorted(
                                eng.memtable.versions(key),
                                key=lambda r: (-r.ht, -r.write_id)))
                    by_tablet.setdefault(peer.tablet_id, []).append(
                        (ts.uuid, peer.raft.stats()["applied_index"], sig))
            seen_keys: set = set()
            for tablet_id, replicas in by_tablet.items():
                assert len(replicas) == 3, (tablet_id, len(replicas))
                # Replicas at the same applied index must hold identical
                # content (apply order is the log order everywhere).
                top = max(a for _u, a, _s in replicas)
                tops = [(u, s) for u, a, s in replicas if a == top]
                first = tops[0][1]
                for u, s in tops[1:]:
                    assert s == first, (tablet_id, u, "content diverged")
                seen_keys.update(first.keys())
            return seen_keys
        except AssertionError as e:
            last_err = e
            time.sleep(0.5)
    raise last_err


def test_raft_storms_keep_replicas_identical(tmp_path):
    rnd = random.Random(99)
    mc = MiniCluster(str(tmp_path), num_tservers=3).start()
    try:
        mc.wait_tservers_registered()
        client = mc.client()
        client.create_table("st", COLUMNS, num_tablets=3)
        table = client.open_table("st")
        acked: set[int] = set()
        unknown: set[int] = set()
        stop = threading.Event()
        next_key = [0]
        lock = threading.Lock()

        def writer():
            while not stop.is_set():
                with lock:
                    base = next_key[0]
                    next_key[0] += 20
                s = YBSession(mc.client(f"w{base}"))
                batch = list(range(base, base + 20))
                for i in batch:
                    s.insert(table, {"k": i, "v": i * 3})
                try:
                    s.flush(timeout_s=6.0)
                    acked.update(batch)
                except Exception:  # noqa: BLE001
                    unknown.update(batch)

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for t in threads:
            t.start()
        transport = mc.transport
        uuids = list(mc.tservers)
        try:
            for storm in range(12):
                action = rnd.randrange(4)
                if action == 0:      # partition a random pair, then heal
                    a, b = rnd.sample(uuids, 2)
                    transport.partition(a, b)
                    time.sleep(rnd.uniform(0.1, 0.5))
                    transport.heal(a, b)
                elif action == 1:    # isolate one node briefly
                    u = rnd.choice(uuids)
                    transport.isolate(u)
                    time.sleep(rnd.uniform(0.2, 0.6))
                    transport.heal(u)
                elif action == 2:    # forced election on a random tablet
                    ts = mc.tservers[rnd.choice(uuids)]
                    for peer in ts.tablet_manager.peers():
                        try:
                            transport.send(peer.node_uuid,
                                           "raft.run_election",
                                           {"tablet_id": peer.tablet_id})
                        except Exception:  # noqa: BLE001
                            pass
                else:                # one-shot WAL sync fault
                    arm_fault_once("fault.wal_sync_failed")
                    time.sleep(0.2)
                time.sleep(rnd.uniform(0.05, 0.2))
        finally:
            clear_faults()
            transport.heal()
            stop.set()
            for t in threads:
                t.join(timeout=30.0)

        keys_present = _assert_replicas_agree(mc, "st", acked, unknown)
        present_ids = set()
        res = YBSession(client).scan(table,
                                     ScanSpec(projection=["k", "v"]),
                                     timeout_s=30.0)
        for k, v in res.rows:
            present_ids.add(k)
            assert v == k * 3, (k, v)
        missing = acked - present_ids
        assert not missing, f"lost acked writes: {sorted(missing)[:10]}"
        invented = present_ids - acked - unknown
        assert not invented, sorted(invented)[:10]
        assert len(acked) > 100
        _ = keys_present
        # Standing stall check (kernel_stack_watchdog.h analog): the
        # storm must not have wedged an apply (threshold 5s); fsync
        # stalls are tolerated on slow CI disks but reported.
        from yugabyte_db_tpu.utils.watchdog import watchdog

        holes = watchdog().stalls("raft.apply_hole")
        assert not [h for h in holes if h["seconds"] > 30], holes
    finally:
        mc.shutdown()


def test_config_change_races_with_writes_and_kills(tmp_path):
    """One-at-a-time membership changes racing writes + a restart: the
    final config converges, nothing applies out of order, and acked
    writes survive (reference: raft_consensus-itest's config stress)."""
    rnd = random.Random(3)
    mc = MiniCluster(str(tmp_path), num_tservers=4).start()
    try:
        mc.wait_tservers_registered()
        client = mc.client()
        client.create_table("cc", COLUMNS, num_tablets=1,
                            replication_factor=3)
        table = client.open_table("cc")
        s = YBSession(client)
        acked = set()
        for i in range(60):
            s.insert(table, {"k": i, "v": i * 3})
        s.flush()
        acked.update(range(60))

        # Find the tablet's peer set and rotate membership through ts-3.
        loc = client.meta_cache.locations("cc").tablets[0]
        start_replicas = list(loc.replicas)
        spare = next(u for u in mc.tservers if u not in start_replicas)
        leader_uuid = None
        for ts in mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                if peer.tablet_id == loc.tablet_id and peer.is_leader():
                    leader_uuid = ts.uuid
        assert leader_uuid is not None

        def do_config_cycle():
            ts = mc.tservers.get(leader_uuid)
            peer = ts.tablet_manager.get(loc.tablet_id)
            victim = rnd.choice(
                [r for r in start_replicas if r != leader_uuid])
            try:
                peer.raft.change_config(
                    [r for r in start_replicas if r != victim] + [spare],
                    timeout=10.0)
                peer.raft.change_config(start_replicas, timeout=10.0)
            except Exception:  # noqa: BLE001 — racing storms may abort
                pass

        cfg_thread = threading.Thread(target=do_config_cycle)
        cfg_thread.start()
        for i in range(60, 160):
            s.insert(table, {"k": i, "v": i * 3})
            if s.pending_ops >= 20:
                try:
                    s.flush(timeout_s=8.0)
                    acked.update(range(i - s.pending_ops, i + 1))
                except Exception:  # noqa: BLE001
                    pass
        try:
            s.flush(timeout_s=8.0)
        except Exception:  # noqa: BLE001
            pass
        cfg_thread.join(timeout=30.0)

        res = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                res = YBSession(client).scan(
                    table, ScanSpec(projection=["k", "v"]), timeout_s=20.0)
                if acked <= {r[0] for r in res.rows}:
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        present = {r[0] for r in res.rows}
        assert acked <= present, sorted(acked - present)[:10]
        for k, v in res.rows:
            assert v == k * 3
    finally:
        mc.shutdown()
