"""Delta-overlay multi-source scans (storage.tpu_engine._overlay):
post-write aggregate scans (live memtable + overlapping runs) must route
through the device overlay plan and match the CPU oracle exactly —
overwrites, deletes, NULL writes, many read points, predicates, bounds.
"""

import random

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (AggSpec, Predicate, RowVersion,
                                     ScanSpec, make_engine)
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401

AGGS = [AggSpec("count", None), AggSpec("count", "d"), AggSpec("sum", "a"),
        AggSpec("sum", "d"), AggSpec("min", "a"), AggSpec("max", "a"),
        AggSpec("min", "d"), AggSpec("max", "d"), AggSpec("avg", "a")]


def _schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("d", DataType.INT32),
    ], table_id="ov")


def _setup(seed=7, nbase=1500, nkeys=250, waves=3, per_wave=120):
    schema = _schema()
    cid = {c.name: c.col_id for c in schema.value_columns}

    def enc(k, r):
        return schema.encode_primary_key(
            {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))

    rnd = random.Random(seed)
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    ht = 0
    batch = []
    for i in range(nbase):
        ht += 1
        batch.append(RowVersion(
            enc(f"k{i % nkeys:04d}", i % 6), ht=ht, liveness=True,
            columns={cid["a"]: rnd.randrange(-10**12, 10**12),
                     cid["d"]: rnd.randrange(-10**6, 10**6)}))
    for e in (cpu, tpu):
        e.apply(batch)
        e.flush()
    for wave in range(waves):
        batch = []
        for _ in range(per_wave):
            ht += 1
            k = enc(f"k{rnd.randrange(nkeys):04d}", rnd.randrange(6))
            roll = rnd.random()
            if roll < 0.15:
                batch.append(RowVersion(k, ht=ht, tombstone=True))
            elif roll < 0.3:
                batch.append(RowVersion(k, ht=ht, columns={cid["d"]: None}))
            else:
                batch.append(RowVersion(
                    k, ht=ht,
                    columns={cid["d"]: rnd.randrange(-10**6, 10**6)}))
        for e in (cpu, tpu):
            e.apply(batch)
        if wave < waves - 1:
            for e in (cpu, tpu):
                e.flush()
    return schema, cpu, tpu, ht, enc


def _assert_same(cpu, tpu, **kw):
    a = cpu.scan(ScanSpec(**kw))
    b = tpu.scan(ScanSpec(**kw))
    assert a.columns == b.columns
    for va, vb, nm in zip(a.rows[0], b.rows[0], a.columns):
        if isinstance(va, float):
            assert vb is not None and \
                abs(va - vb) <= 1e-5 + 1e-5 * abs(va), nm
        else:
            assert va == vb, (nm, va, vb)


def test_overlay_route_and_oracle_parity():
    schema, cpu, tpu, ht, enc = _setup()
    assert len(tpu.runs) == 3 and not tpu.memtable.is_empty
    kind = tpu._plan_scan(ScanSpec(read_ht=MAX_HT,
                                   aggregates=[AggSpec("count", None)]))[0]
    assert kind == "issued"  # overlay device plan, not the host merge
    assert tpu._overlay_cache is not None and \
        tpu._overlay_cache[3] is not None
    for rp in (1, ht // 3, ht // 2, ht, MAX_HT):
        _assert_same(cpu, tpu, read_ht=rp, aggregates=list(AGGS))


def test_overlay_predicates_bounds_and_staleness():
    schema, cpu, tpu, ht, enc = _setup(seed=13)
    lo, hi = enc("k0050", 0), enc("k0200", 0)
    for kw in (
        dict(read_ht=MAX_HT, aggregates=list(AGGS),
             predicates=[Predicate("d", ">=", 0)]),
        dict(read_ht=ht, aggregates=list(AGGS),
             predicates=[Predicate("a", "<", 0), Predicate("d", "!=", 3)]),
        dict(read_ht=ht // 2, aggregates=list(AGGS), lower=lo, upper=hi),
    ):
        _assert_same(cpu, tpu, **kw)
    # The cache must not serve stale state after NEW writes.
    cid = {c.name: c.col_id for c in schema.value_columns}
    k = enc("k0001", 0)
    for e in (cpu, tpu):
        e.apply([RowVersion(k, ht=ht + 1, columns={cid["d"]: 424242})])
    _assert_same(cpu, tpu, read_ht=ht + 2, aggregates=list(AGGS))
    # ...and after a flush that changes the run set.
    for e in (cpu, tpu):
        e.flush()
    _assert_same(cpu, tpu, read_ht=ht + 2, aggregates=list(AGGS))


def test_overlay_large_dirty_set_falls_back():
    """A dirty set rivaling the primary must skip the overlay (a
    compaction is the right tool there) and still answer correctly."""
    schema, cpu, tpu, ht, enc = _setup(seed=19, nbase=300, nkeys=60,
                                       waves=2, per_wave=400)
    _assert_same(cpu, tpu, read_ht=MAX_HT, aggregates=list(AGGS))
