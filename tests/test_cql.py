"""YCQL frontend tests: parse -> bind -> execute against LocalCluster.

Reference analog: the CQL query tests driven through QLTestBase
(src/yb/yql/cql/ql/test/ql-query-test.cc, ql-create-table-test.cc) — full
statements through the processor against in-process tablets, both storage
engines.
"""

import pytest

from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound, StatusError)
from yugabyte_db_tpu.yql.cql import QLProcessor, parse_statement
from yugabyte_db_tpu.yql.cql.processor import LocalCluster


@pytest.fixture(params=["cpu", "tpu"])
def ql(request, tmp_path):
    cluster = LocalCluster(str(tmp_path), num_tablets=3,
                           engine=request.param,
                           engine_options={"rows_per_block": 16})
    proc = QLProcessor(cluster)
    yield proc
    cluster.close()


def seed_kv(ql, n=30):
    ql.execute("CREATE TABLE kv (k text, r int, v int, s text, "
               "PRIMARY KEY ((k), r))")
    for i in range(n):
        ql.execute(f"INSERT INTO kv (k, r, v, s) VALUES "
                   f"('key{i % 5}', {i}, {i * 10}, 'val{i}')")


# -- parsing ----------------------------------------------------------------

def test_parse_create_table():
    s = parse_statement(
        "CREATE TABLE IF NOT EXISTS ks.t (a text, b bigint, c double, "
        "PRIMARY KEY ((a), b)) WITH tablets = 7")
    assert s.name == "ks.t" and s.if_not_exists
    assert s.hash_keys == ["a"] and s.range_keys == ["b"]
    assert s.properties == {"tablets": 7}


def test_parse_literals():
    s = parse_statement(
        "INSERT INTO t (a, b, c, d, e, f) VALUES "
        "('it''s', -3, 2.5, true, null, 0x0aFF)")
    assert s.values == ["it's", -3, 2.5, True, None, bytes([0x0A, 0xFF])]


def test_parse_select_shapes():
    s = parse_statement("SELECT count(*), sum(v) AS total FROM t "
                        "WHERE k = 'a' AND r >= 3 LIMIT 10 ALLOW FILTERING")
    assert s.items[0].agg_fn == "count" and s.items[1].alias == "total"
    assert [r.op for r in s.where] == ["=", ">="]
    assert s.limit == 10 and s.allow_filtering


def test_parse_errors():
    for bad in ["SELEC * FROM t", "INSERT INTO t (a) VALUES (1, 2)",
                "CREATE TABLE t (a int)", "SELECT * FROM t WHERE a ~ 3"]:
        with pytest.raises(StatusError):
            parse_statement(bad)


# -- DDL --------------------------------------------------------------------

def test_create_use_drop(ql):
    ql.execute("CREATE KEYSPACE app")
    ql.execute("USE app")
    ql.execute("CREATE TABLE t (a int PRIMARY KEY, b text)")
    assert "app.t" in ql.cluster.tables
    with pytest.raises(AlreadyPresent):
        ql.execute("CREATE TABLE t (a int PRIMARY KEY)")
    ql.execute("CREATE TABLE IF NOT EXISTS t (a int PRIMARY KEY)")
    ql.execute("DROP TABLE t")
    with pytest.raises(NotFound):
        ql.execute("SELECT * FROM t")
    ql.execute("DROP TABLE IF EXISTS t")


def test_float_key_rejected(ql):
    with pytest.raises(InvalidArgument):
        ql.execute("CREATE TABLE t (a double PRIMARY KEY, b int)")


# -- DML + SELECT -----------------------------------------------------------

def test_insert_select_point(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT v, s FROM kv WHERE k = 'key1' AND r = 6")
    assert rs.columns == ["v", "s"] and rs.rows == [(60, "val6")]


def test_partition_scan_ordered_by_range(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT r FROM kv WHERE k = 'key2'")
    assert [r[0] for r in rs.rows] == [2, 7, 12, 17, 22, 27]


def test_range_bounds(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT r FROM kv WHERE k = 'key2' AND r > 7 AND r <= 22")
    assert [r[0] for r in rs.rows] == [12, 17, 22]


def test_full_scan_with_filter(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT v FROM kv WHERE v >= 250 ALLOW FILTERING")
    assert sorted(r[0] for r in rs.rows) == [250, 260, 270, 280, 290]


def test_limit(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT * FROM kv LIMIT 7")
    assert len(rs.rows) == 7


def test_update_upsert_and_overwrite(ql):
    seed_kv(ql, n=5)
    ql.execute("UPDATE kv SET v = 111, s = 'new' WHERE k = 'key1' AND r = 1")
    rs = ql.execute("SELECT v, s FROM kv WHERE k = 'key1' AND r = 1")
    assert rs.rows == [(111, "new")]
    # upsert semantics: UPDATE on a new key creates the column data
    ql.execute("UPDATE kv SET v = 5 WHERE k = 'fresh' AND r = 0")
    rs = ql.execute("SELECT v, s FROM kv WHERE k = 'fresh' AND r = 0")
    assert rs.rows == [(5, None)]


def test_delete_row_and_column(ql):
    seed_kv(ql, n=5)
    ql.execute("DELETE FROM kv WHERE k = 'key3' AND r = 3")
    assert ql.execute("SELECT * FROM kv WHERE k = 'key3' AND r = 3").rows == []
    ql.execute("DELETE s FROM kv WHERE k = 'key2' AND r = 2")
    rs = ql.execute("SELECT v, s FROM kv WHERE k = 'key2' AND r = 2")
    assert rs.rows == [(20, None)]


def test_dml_requires_full_key(ql):
    seed_kv(ql, n=5)
    with pytest.raises(InvalidArgument):
        ql.execute("UPDATE kv SET v = 1 WHERE k = 'key1'")
    with pytest.raises(InvalidArgument):
        ql.execute("DELETE FROM kv WHERE r = 3")


def test_aggregates_multi_tablet(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT count(*), sum(v), min(v), max(v), avg(v) FROM kv")
    n = 30
    vals = [i * 10 for i in range(n)]
    assert rs.rows == [(n, sum(vals), 0, 290, sum(vals) / n)]


def test_aggregate_with_predicate(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT count(*), sum(v) FROM kv WHERE v < 100 "
                    "ALLOW FILTERING")
    assert rs.rows == [(10, sum(i * 10 for i in range(10)))]


def test_aggregate_single_partition(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT count(*), max(r) FROM kv WHERE k = 'key0'")
    assert rs.rows == [(6, 25)]


def test_in_predicate(ql):
    seed_kv(ql)
    rs = ql.execute("SELECT r FROM kv WHERE k = 'key0' AND r IN (0, 5, 10) "
                    "ALLOW FILTERING")
    assert sorted(r[0] for r in rs.rows) == [0, 5, 10]


def test_ttl_expiry(ql):
    ql.execute("CREATE TABLE e (a int PRIMARY KEY, b int)")
    ql.execute("INSERT INTO e (a, b) VALUES (1, 10) USING TTL 3600")
    ql.execute("INSERT INTO e (a, b) VALUES (2, 20)")
    assert len(ql.execute("SELECT * FROM e").rows) == 2
    # Jump the shared clock past the TTL: row 1 disappears.
    from yugabyte_db_tpu.utils.hybrid_time import HybridTime
    clk = ql.cluster.clock
    clk.update(HybridTime.from_micros(
        clk.now().physical_micros + 2 * 3600 * 1_000_000))
    rs = ql.execute("SELECT a FROM e")
    assert [r[0] for r in rs.rows] == [2]


def test_mixed_agg_plain_rejected(ql):
    seed_kv(ql, n=3)
    with pytest.raises(InvalidArgument):
        ql.execute("SELECT k, count(*) FROM kv")


def test_insert_if_not_exists(ql):
    ql.execute("CREATE TABLE u (a int PRIMARY KEY, b int)")
    ql.execute("INSERT INTO u (a, b) VALUES (1, 10)")
    rs = ql.execute("INSERT INTO u (a, b) VALUES (1, 99) IF NOT EXISTS")
    assert rs.columns == ["[applied]"] and rs.rows == [(False,)]
    assert ql.execute("SELECT b FROM u WHERE a = 1").rows == [(10,)]
    rs = ql.execute("INSERT INTO u (a, b) VALUES (2, 20) IF NOT EXISTS")
    assert rs.rows == [(True,)]
    assert ql.execute("SELECT b FROM u WHERE a = 2").rows == [(20,)]


def test_eq_on_trailing_range_column_filters(ql):
    ql.execute("CREATE TABLE m (h int, r1 int, r2 int, v int, "
               "PRIMARY KEY ((h), r1, r2))")
    for r1 in range(3):
        for r2 in range(3):
            ql.execute(f"INSERT INTO m (h, r1, r2, v) VALUES "
                       f"(1, {r1}, {r2}, {r1 * 10 + r2})")
    rs = ql.execute("SELECT v FROM m WHERE h = 1 AND r2 = 2 ALLOW FILTERING")
    assert sorted(r[0] for r in rs.rows) == [2, 12, 22]


def test_create_keyspace_with_replication(ql):
    ql.execute("CREATE KEYSPACE rf3 WITH replication = "
               "{'class': 'SimpleStrategy', 'replication_factor': 3}")
    ql.execute("USE rf3")
    ql.execute("CREATE TABLE t (a int PRIMARY KEY)")
    assert "rf3.t" in ql.cluster.tables


def test_delete_unknown_column_rejected(ql):
    ql.execute("CREATE TABLE d (a int PRIMARY KEY, b int)")
    with pytest.raises(InvalidArgument):
        ql.execute("DELETE nosuch FROM d WHERE a = 1")
