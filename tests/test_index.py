"""Secondary index tests: CREATE INDEX, write-path maintenance, backfill,
index-driven SELECT, drop — over both cluster seams.

Reference test analog: java/yb-cql TestIndex + the index write path of
src/yb/tablet/tablet.cc:1015 (UpdateQLIndexes).
"""

import time

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.processor import LocalCluster, QLProcessor


def wait_for(pred, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = pred()
        if r:
            return r
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def local_ql():
    cluster = LocalCluster(num_tablets=4)
    ql = QLProcessor(cluster)
    yield ql
    cluster.close()


@pytest.fixture
def dist_ql(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    ql = QLProcessor(ClientCluster(c.client()))
    yield ql
    c.shutdown()


def _setup(ql, n=30):
    ql.execute("CREATE TABLE emp (id INT, dept TEXT, salary BIGINT, "
               "PRIMARY KEY (id))")
    for i in range(n):
        ql.execute(f"INSERT INTO emp (id, dept, salary) "
                   f"VALUES ({i}, 'dept{i % 5}', {i * 100})")


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_index_lookup_after_create(fixture, request):
    ql = request.getfixturevalue(fixture)
    _setup(ql)
    # Backfill: index created AFTER the rows exist.
    ql.execute("CREATE INDEX emp_dept ON emp (dept)")

    def rows_via_index():
        res = ql.execute("SELECT id, dept FROM emp WHERE dept = 'dept2'")
        return sorted(r[0] for r in res.rows)
    wait_for(lambda: rows_via_index() == [2, 7, 12, 17, 22, 27],
             msg="index backfill visible")
    # New writes maintained.
    ql.execute("INSERT INTO emp (id, dept, salary) "
               "VALUES (100, 'dept2', 1)")
    wait_for(lambda: 100 in rows_via_index(), msg="index maintenance")
    # Updates move entries between index keys.
    ql.execute("UPDATE emp SET dept = 'dept9' WHERE id = 2")
    wait_for(lambda: 2 not in rows_via_index(), msg="old entry removed")
    res = ql.execute("SELECT id FROM emp WHERE dept = 'dept9'")
    assert [r[0] for r in res.rows] == [2]
    # Deletes drop entries.
    ql.execute("DELETE FROM emp WHERE id = 7")
    wait_for(lambda: 7 not in rows_via_index(), msg="delete maintenance")


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_index_respects_other_predicates_and_limit(fixture, request):
    ql = request.getfixturevalue(fixture)
    _setup(ql)
    ql.execute("CREATE INDEX emp_dept2 ON emp (dept)")

    def q():
        return ql.execute("SELECT id FROM emp WHERE dept = 'dept1' "
                          "AND salary >= 1000")
    wait_for(lambda: sorted(r[0] for r in q().rows) == [11, 16, 21, 26],
             msg="index + extra predicate")
    res = ql.execute("SELECT id FROM emp WHERE dept = 'dept1' LIMIT 2")
    assert len(res.rows) == 2


def test_drop_index(local_ql):
    ql = local_ql
    _setup(ql, n=10)
    ql.execute("CREATE INDEX di ON emp (dept)")
    assert ql.execute("SELECT id FROM emp WHERE dept = 'dept3'").rows
    ql.execute("DROP INDEX di")
    # Still answerable (full scan path), index table gone.
    res = ql.execute("SELECT id FROM emp WHERE dept = 'dept3'")
    assert sorted(r[0] for r in res.rows) == [3, 8]
    assert not any("__idx__" in t or t == "default.di"
                   for t in ql.cluster.tables)


def test_null_indexed_values_skipped(local_ql):
    ql = local_ql
    ql.execute("CREATE TABLE n (k INT, v TEXT, PRIMARY KEY (k))")
    ql.execute("CREATE INDEX nv ON n (v)")
    ql.execute("INSERT INTO n (k, v) VALUES (1, 'x')")
    ql.execute("INSERT INTO n (k) VALUES (2)")  # v NULL: no entry
    res = ql.execute("SELECT k FROM n WHERE v = 'x'")
    assert [r[0] for r in res.rows] == [1]
    ih = ql.cluster.table("default.n_v_idx"
                          if "default.n_v_idx" in ql.cluster.tables
                          else "default.nv")
    total = sum(len(t.scan(
        __import__("yugabyte_db_tpu.storage.scan_spec",
                   fromlist=["ScanSpec"]).ScanSpec()).rows)
        for t in ih.tablets)
    assert total == 1


def test_index_set_reconciled_after_lost_push(tmp_path):
    """A replica that missed ts.set_indexes (or restarted with stale
    metadata) gets the catalog's index set re-pushed via heartbeat
    reconciliation."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        ql = QLProcessor(ClientCluster(c.client()))
        ql.execute("CREATE TABLE rec (k INT, v TEXT, PRIMARY KEY (k))")
        ql.execute("CREATE INDEX rec_v ON rec (v)")
        # Simulate a lost push: wipe the index set everywhere.
        for ts in c.tservers.values():
            for peer in ts.tablet_manager.peers():
                if peer.tablet.meta.table_name == "default.rec":
                    peer.tablet.meta.indexes = []

        def restored():
            return all(
                peer.tablet.meta.indexes
                for ts in c.tservers.values()
                for peer in ts.tablet_manager.peers()
                if peer.tablet.meta.table_name == "default.rec")
        wait_for(restored, msg="heartbeat index reconciliation")
        ql.execute("INSERT INTO rec (k, v) VALUES (1, 'hello')")
        wait_for(lambda: [r[0] for r in ql.execute(
            "SELECT k FROM rec WHERE v = 'hello'").rows] == [1],
            msg="maintenance after reconciliation")
    finally:
        c.shutdown()


def _setup_multi(ql, n=40):
    ql.execute("CREATE TABLE mc (id INT, dept TEXT, grade INT, "
               "salary BIGINT, name TEXT, PRIMARY KEY (id))")
    for i in range(n):
        ql.execute(
            f"INSERT INTO mc (id, dept, grade, salary, name) VALUES "
            f"({i}, 'd{i % 3}', {i % 4}, {i * 100}, 'emp{i}')")


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_multi_column_index_lookup(fixture, request):
    ql = request.getfixturevalue(fixture)
    _setup_multi(ql)
    ql.execute("CREATE INDEX mc_dg ON mc (dept, grade)")

    def rows():
        return ql.execute(
            "SELECT id, salary FROM mc WHERE dept = 'd1' AND grade = 2"
        ).rows

    expect = sorted((i, i * 100) for i in range(40)
                    if i % 3 == 1 and i % 4 == 2)
    wait_for(lambda: sorted(rows()) == expect, msg="multi-col lookup")
    # Updates move entries between compound keys.
    ql.execute("UPDATE mc SET grade = 2 WHERE id = 1")  # d1, was grade 1
    wait_for(lambda: (1, 100) in rows(), msg="index follows update")
    ql.execute("DELETE FROM mc WHERE id = 13")  # was d1/grade 1? 13%3=1,13%4=1
    ql.execute("UPDATE mc SET dept = 'd9' WHERE id = 6")
    wait_for(lambda: all(r[0] != 6 for r in rows()),
             msg="index drops moved row")


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_covered_index_serves_without_base_reads(fixture, request):
    ql = request.getfixturevalue(fixture)
    _setup_multi(ql)
    ql.execute("CREATE INDEX mc_dept ON mc (dept) INCLUDE (salary)")

    def q():
        return ql.execute(
            "SELECT id, salary FROM mc WHERE dept = 'd0'").rows

    expect = sorted((i, i * 100) for i in range(40) if i % 3 == 0)
    wait_for(lambda: sorted(q()) == expect, msg="covered lookup")
    # The covered read must not touch the base table: poke a hole by
    # scanning with base tablets instrumented (local cluster only).
    if fixture == "local_ql":
        handle = ql.cluster.table(ql._qualify("mc"))
        calls = []
        for t in handle.tablets:
            orig = t.scan
            t.scan = (lambda spec, _o=orig: (calls.append(1), _o(spec))[1])
        rows = q()
        assert sorted(rows) == expect
        assert not calls, "covered query read the base table"
    # Covered values follow updates.
    ql.execute("UPDATE mc SET salary = 999999 WHERE id = 0")
    wait_for(lambda: (0, 999999) in q(), msg="covered value updated")
