"""SyncPoint interleaving control, fault injection, MemTrackers.

Reference analogs: src/yb/util/sync_point.h:61 (LoadDependency),
fault_injection.h:49 + FLAGS_respond_write_failed_probability
(tablet_service.cc:784), and the MemTracker hierarchy + shared
memstore budget (mem_tracker.h, docdb_rocksdb_util.cc:437).
"""

import tempfile
import threading

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import Predicate, ScanSpec, make_engine
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.utils.fault_injection import (arm_fault_once,
                                                   clear_faults)
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.memtracker import MemTracker, root_tracker
from yugabyte_db_tpu.utils.sync_point import SYNC_POINT, sync_point


def _schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
    ], table_id="t")


def _key(schema, i):
    return schema.encode_primary_key(
        {"k": f"k{i:04d}"}, compute_hash_code(schema, {"k": f"k{i:04d}"}))


# -- SyncPoint ---------------------------------------------------------------

def test_sync_point_orders_threads():
    order = []
    SYNC_POINT.load_dependency([("a:done", "b:start")])
    SYNC_POINT.enable()
    try:
        def thread_b():
            sync_point("b:start")   # blocks until a:done processed
            order.append("b")

        t = threading.Thread(target=thread_b)
        t.start()
        import time

        time.sleep(0.05)            # give b a chance to run early (it must not)
        order.append("a")
        sync_point("a:done")
        t.join(timeout=5)
        assert order == ["a", "b"]
    finally:
        SYNC_POINT.disable_and_clear()


def test_sync_point_timeout_and_disable():
    SYNC_POINT.load_dependency([("never", "waits")])
    SYNC_POINT.enable()
    try:
        with pytest.raises(TimeoutError):
            sync_point("waits")
    finally:
        SYNC_POINT.disable_and_clear()
    sync_point("waits")  # disabled: free


def test_sync_point_flush_scan_interleaving():
    """Deterministically force a flush into the window between a scan's
    memtable snapshot and its execution — the exact race the plan-time
    snapshot defends against; results must include every pre-scan row."""
    import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401

    schema = _schema()
    cid = schema.column("v").col_id
    eng = make_engine("tpu", schema, {"rows_per_block": 16})
    eng.apply([RowVersion(_key(schema, i), ht=10 + i, liveness=True,
                          columns={cid: i}) for i in range(20)])
    eng.flush()
    # memtable rows that a racing flush would move into a run mid-scan
    eng.apply([RowVersion(_key(schema, i), ht=100 + i, liveness=True,
                          columns={cid: 1000 + i}) for i in range(20, 30)])

    SYNC_POINT.load_dependency([
        ("tpu_engine:plan:mem_snapshotted", "tpu_engine:flush:start")])
    SYNC_POINT.enable()
    results = {}
    try:
        def flusher():
            eng.flush()   # blocks until the scan snapshotted its sources
            results["flushed"] = True

        ft = threading.Thread(target=flusher)
        ft.start()
        res = eng.scan(ScanSpec(read_ht=10_000, projection=["k", "v"]))
        ft.join(timeout=10)
        results["rows"] = res.rows
    finally:
        SYNC_POINT.disable_and_clear()
    assert results.get("flushed")
    got = dict(results["rows"])
    assert len(got) == 30
    assert got["k0025"] == 1025


# -- fault injection ---------------------------------------------------------

def test_write_respond_failed_is_exactly_once():
    """The injected 'applied but responded failure' fault: the client
    retries with the same request id and the dedup registry returns the
    original result — the row exists exactly once."""
    from yugabyte_db_tpu.client.client import YBClient
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            client.create_table("kv", [
                ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
                ColumnSchema("v", DataType.INT64)], num_tablets=1)
            table = client.open_table("kv")
            s = YBSession(client)
            s.insert(table, {"k": "a", "v": 1})
            s.flush()

            arm_fault_once("fault.ts_write_respond_failed")
            s.insert(table, {"k": "b", "v": 2})
            s.flush()  # first response injected-fails; retry dedups

            res = s.scan(table, ScanSpec(projection=["k", "v"]))
            assert sorted(res.rows) == [("a", 1), ("b", 2)]
            # exactly-once: one version of 'b' in the whole tablet
            versions = 0
            for ts in mc.tservers.values():
                for peer in ts.tablet_manager.peers():
                    if not peer.is_leader():
                        continue
                    eng = peer.tablet.engine
                    for key, vers in eng.dump_entries():
                        versions += len(vers)
                    versions += sum(
                        len(eng.memtable.versions(k))
                        for k in eng.memtable.scan_keys(b"", b""))
            assert versions == 2  # 'a' and 'b', one version each
        finally:
            clear_faults()
            mc.shutdown()


def test_wal_sync_fault_fails_write_then_recovers():
    from yugabyte_db_tpu.tablet.tablet import Tablet, TabletMetadata
    from yugabyte_db_tpu.utils.fault_injection import FaultInjected

    schema = _schema()
    cid = schema.column("v").col_id
    with tempfile.TemporaryDirectory() as root:
        meta = TabletMetadata("t-0001", "t", schema, 0, 65536)
        t = Tablet.create(meta, root, fsync=False)
        arm_fault_once("fault.wal_sync_failed")
        with pytest.raises(FaultInjected):
            t.write([RowVersion(_key(schema, 1), ht=0, liveness=True,
                                columns={cid: 1})])
        # the fault was one-shot: the next write lands
        t.write([RowVersion(_key(schema, 2), ht=0, liveness=True,
                            columns={cid: 2})])
        res = t.scan(ScanSpec(read_ht=t.read_time().value,
                              projection=["k"]))
        assert [r[0] for r in res.rows] == ["k0002"]
        t.close()


# -- MemTracker --------------------------------------------------------------

def test_memtracker_hierarchy():
    root = MemTracker("r")
    a = root.child("a")
    b = root.child("b", limit=100)
    a.consume(50)
    b.consume(150)
    assert root.consumption == 200 and root.peak == 200
    assert b.over_limit()
    b.release(100)
    assert root.consumption == 100 and b.consumption == 50
    assert root.peak == 200
    a.detach()
    assert root.consumption == 50
    assert root.child("b") is b  # child() returns the existing node


def test_global_memstore_budget_triggers_flush():
    import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401

    schema = _schema()
    cid = schema.column("v").col_id
    memstore = root_tracker().child("memstore")
    # The budget flush only fires for the LARGEST memstore consumer, so
    # sibling trackers left behind by earlier tests (unclosed engines,
    # cluster teardowns still draining) can starve this engine's flush.
    # Park the strays out of the comparison before measuring.
    for stray in list(memstore._children.values()):
        stray.detach()
    baseline = memstore.consumption
    old = FLAGS.get("global_memstore_limit_bytes")
    FLAGS.set("global_memstore_limit_bytes", baseline + 2000, force=True)
    try:
        eng = make_engine("cpu", schema)
        # each row ~80+ bytes: crossing the budget must auto-flush
        for i in range(200):
            eng.apply([RowVersion(_key(schema, i), ht=10 + i,
                                  liveness=True, columns={cid: i})])
        assert len(eng.runs) >= 1          # budget forced a flush
        assert eng.memtable.approx_bytes < 2000
        res = eng.scan(ScanSpec(read_ht=10_000))
        assert len(res.rows) == 200        # nothing lost across flushes
        eng.close()
        # Engine-scoped: close() released every byte THIS engine held
        # (the parent count can move under a detached straggler).
        assert eng.mem_tracker.consumption == 0
    finally:
        FLAGS.set("global_memstore_limit_bytes", old, force=True)
