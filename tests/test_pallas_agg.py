"""Pallas flat-aggregate kernel vs the XLA/CPU oracle.

Runs in pallas interpret mode (CPU backend); the same program compiles
natively on TPU (probed by bench/engine integration behind the
``tpu_engine_use_pallas`` flag).
"""

import random

import numpy as np
import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.ops import pallas_agg
from yugabyte_db_tpu.ops.device_run import DeviceRun
from yugabyte_db_tpu.ops.scan import AggSig, PredSig
from yugabyte_db_tpu.storage import AggSpec, Predicate, ScanSpec, make_engine
from yugabyte_db_tpu.storage.row_version import RowVersion


def _schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("d", DataType.INT32),
    ], table_id="pal")


def _build(num_keys=700, seed=5, rows_per_block=128):
    schema = _schema()
    cid = {c.name: c.col_id for c in schema.columns}
    rng = random.Random(seed)
    rows = []
    ht = 10
    for i in range(num_keys):
        key = schema.encode_primary_key(
            {"k": f"u{i:05d}"}, compute_hash_code(schema, {"k": f"u{i:05d}"}))
        ht += rng.randrange(1, 3)
        if rng.random() < 0.06:
            rows.append(RowVersion(key, ht=ht, tombstone=True))
            continue
        cols = {}
        if rng.random() < 0.9:
            cols[cid["a"]] = rng.randrange(-10**14, 10**14)
        if rng.random() < 0.85:
            cols[cid["d"]] = rng.randrange(-10**6, 10**6)
        elif rng.random() < 0.5:
            cols[cid["d"]] = None
        rows.append(RowVersion(key, ht=ht, liveness=True, columns=cols))
    eng = make_engine("cpu", schema)
    eng.apply(rows)
    eng.flush()
    # a flat columnar run + device planes for the kernel
    from yugabyte_db_tpu.storage.columnar import ColumnarRun
    from yugabyte_db_tpu.storage.memtable import MemTable

    mem = MemTable()
    mem.apply(rows)
    crun = ColumnarRun.build(schema, mem.drain_sorted(), rows_per_block)
    assert crun.max_group_versions == 1  # flat
    dev = DeviceRun(crun, pallas_agg.BLOCKS_PER_STEP)
    return schema, cid, eng, crun, dev, ht


@pytest.mark.parametrize("pred_lo", [None, -400_000])
def test_pallas_matches_oracle(pred_lo):
    schema, cid, eng, crun, dev, max_ht = _build()
    read_ht = max_ht + 1

    preds = [] if pred_lo is None else [Predicate("d", ">=", pred_lo)]
    spec = ScanSpec(read_ht=read_ht, predicates=list(preds), aggregates=[
        AggSpec("count", None), AggSpec("count", "d"),
        AggSpec("sum", "a"), AggSpec("sum", "d"),
        AggSpec("min", "a"), AggSpec("max", "a"),
        AggSpec("min", "d"), AggSpec("max", "d")])
    want = eng.scan(spec).rows[0]

    aggs = (AggSig("count", None, None), AggSig("count", cid["d"], "i32"),
            AggSig("sum", cid["a"], "i64"), AggSig("sum", cid["d"], "i32"),
            AggSig("min", cid["a"], "i64"), AggSig("max", cid["a"], "i64"),
            AggSig("min", cid["d"], "i32"), AggSig("max", cid["d"], "i32"))
    psigs = tuple(PredSig(cid["d"], "i32", ">=") for _ in preds)
    assert pallas_agg.eligible(True, aggs, psigs)
    col_order = ((cid["a"], True), (cid["d"], False))

    from yugabyte_db_tpu.utils import planes as P

    r_hi, r_lo = P.scalar_ht_planes(read_ht)
    e_hi, e_lo = P.scalar_ht_planes(read_ht - 1)
    iparams = [0, crun.total_rows(), r_hi, r_lo, e_hi, e_lo]
    for p in preds:
        iparams.append(int(p.value))
    fn = pallas_agg.compiled_flat_aggregate(
        dev.B, crun.R, aggs, psigs, col_order, interpret=True)
    tensors = pallas_agg.gather_tensors(dev.arrays, col_order)
    partials = np.asarray(fn(tensors, np.array(iparams, np.int32)))
    count, scanned, vals = pallas_agg.combine_partials(partials, aggs)
    assert tuple(vals) == tuple(want)


def test_pallas_row_bounds():
    schema, cid, eng, crun, dev, max_ht = _build(num_keys=300)
    read_ht = max_ht + 1
    # bound the scan to the middle of the run and compare to the engine
    lo_key = crun.key_at(crun.total_rows() // 4)
    hi_key = crun.key_at(crun.total_rows() // 2)
    spec = ScanSpec(lower=lo_key, upper=hi_key, read_ht=read_ht,
                    aggregates=[AggSpec("count", None),
                                AggSpec("sum", "d")])
    want = eng.scan(spec).rows[0]

    aggs = (AggSig("count", None, None), AggSig("sum", cid["d"], "i32"))
    col_order = ((cid["a"], True), (cid["d"], False))
    from yugabyte_db_tpu.utils import planes as P

    r_hi, r_lo = P.scalar_ht_planes(read_ht)
    e_hi, e_lo = P.scalar_ht_planes(read_ht - 1)
    iparams = np.array([crun.lower_row(lo_key), crun.upper_row(hi_key),
                        r_hi, r_lo, e_hi, e_lo], np.int32)
    fn = pallas_agg.compiled_flat_aggregate(
        dev.B, crun.R, aggs, (), col_order, interpret=True)
    tensors = pallas_agg.gather_tensors(dev.arrays, col_order)
    partials = np.asarray(fn(tensors, iparams))
    _c, _s, vals = pallas_agg.combine_partials(partials, aggs)
    assert tuple(vals) == tuple(want)
