"""CQL collections (list/set/map) and JSONB.

Reference analogs: DocDB subdocument collections
(src/yb/docdb/primitive_value.h collection ValueTypes, per-element
writes in cql_operation.cc) — here stored as normalized host containers
with read-modify-write edits — and the jsonb type + operators
(src/yb/common/jsonb.cc).
"""

import pytest

from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.cql.processor import LocalCluster, QLProcessor


@pytest.fixture()
def ql():
    cluster = LocalCluster(num_tablets=2)
    yield QLProcessor(cluster)
    cluster.close()


def test_list_operations(ql):
    ql.execute("CREATE TABLE t (k TEXT, l LIST<INT>, PRIMARY KEY ((k)))")
    ql.execute("INSERT INTO t (k, l) VALUES ('a', [1, 2, 3])")
    assert ql.execute("SELECT l FROM t").rows == [([1, 2, 3],)]
    ql.execute("UPDATE t SET l = l + [4, 5] WHERE k = 'a'")
    assert ql.execute("SELECT l FROM t").rows == [([1, 2, 3, 4, 5],)]
    ql.execute("UPDATE t SET l = [0] + l WHERE k = 'a'")
    assert ql.execute("SELECT l FROM t").rows == [([0, 1, 2, 3, 4, 5],)]
    ql.execute("UPDATE t SET l = l - [2, 4] WHERE k = 'a'")
    assert ql.execute("SELECT l FROM t").rows == [([0, 1, 3, 5],)]
    ql.execute("UPDATE t SET l[1] = 99 WHERE k = 'a'")
    assert ql.execute("SELECT l FROM t").rows == [([0, 99, 3, 5],)]
    with pytest.raises(InvalidArgument):
        ql.execute("UPDATE t SET l[50] = 1 WHERE k = 'a'")


def test_set_operations(ql):
    ql.execute("CREATE TABLE t (k TEXT, s SET<TEXT>, PRIMARY KEY ((k)))")
    ql.execute("INSERT INTO t (k, s) VALUES ('a', {'x', 'y', 'x'})")
    assert ql.execute("SELECT s FROM t").rows == [(["x", "y"],)]
    ql.execute("UPDATE t SET s = s + {'a', 'y'} WHERE k = 'a'")
    assert ql.execute("SELECT s FROM t").rows == [(["a", "x", "y"],)]
    ql.execute("UPDATE t SET s = s - {'x'} WHERE k = 'a'")
    assert ql.execute("SELECT s FROM t").rows == [(["a", "y"],)]


def test_map_operations(ql):
    ql.execute("CREATE TABLE t (k TEXT, m MAP<TEXT, INT>, "
               "PRIMARY KEY ((k)))")
    ql.execute("INSERT INTO t (k, m) VALUES ('a', {'b': 2, 'a': 1})")
    assert ql.execute("SELECT m FROM t").rows == [({"a": 1, "b": 2},)]
    ql.execute("UPDATE t SET m['c'] = 3 WHERE k = 'a'")
    ql.execute("UPDATE t SET m = m + {'d': 4, 'a': 10} WHERE k = 'a'")
    assert ql.execute("SELECT m FROM t").rows == [
        ({"a": 10, "b": 2, "c": 3, "d": 4},)]
    ql.execute("UPDATE t SET m = m - {'b', 'd'} WHERE k = 'a'")
    assert ql.execute("SELECT m FROM t").rows == [({"a": 10, "c": 3},)]
    # element set on a NULL map creates it
    ql.execute("INSERT INTO t (k) VALUES ('fresh')")
    ql.execute("UPDATE t SET m['first'] = 1 WHERE k = 'fresh'")
    res = ql.execute("SELECT m FROM t WHERE k = 'fresh'")
    assert res.rows == [({"first": 1},)]


def test_collections_survive_flush_both_engines():
    for engine in ("cpu", "tpu"):
        cluster = LocalCluster(num_tablets=1, engine=engine,
                               engine_options={"rows_per_block": 8})
        try:
            ql = QLProcessor(cluster)
            ql.execute("CREATE TABLE t (k TEXT, l LIST<INT>, "
                       "m MAP<TEXT, INT>, PRIMARY KEY ((k)))")
            for i in range(20):
                ql.execute(f"INSERT INTO t (k, l, m) VALUES "
                           f"('r{i:02d}', [{i}, {i + 1}], "
                           f"{{'v': {i}}})")
            for t in cluster.table("default.t").tablets:
                t.flush()
            res = ql.execute("SELECT k, l, m FROM t WHERE k = 'r07'")
            assert res.rows == [("r07", [7, 8], {"v": 7})]
            res = ql.execute("SELECT count(*) FROM t")
            assert res.rows[0][0] == 20
        finally:
            cluster.close()


def test_jsonb_pgsql():
    from yugabyte_db_tpu.yql.pgsql import PgProcessor

    cluster = LocalCluster(num_tablets=2)
    try:
        pg = PgProcessor(cluster)
        pg.execute("CREATE TABLE docs (id BIGINT PRIMARY KEY, j JSONB)")
        pg.execute("""INSERT INTO docs (id, j) VALUES
            (1, '{"name": "ada", "tags": ["x", "y"], "n": {"d": 7}}'),
            (2, '{"name": "bob", "n": {"d": 9}}')""")
        res = pg.execute("SELECT j FROM docs WHERE id = 1")
        assert res.rows[0][0]["name"] == "ada"
        # -> returns json, ->> returns text; paths chain
        res = pg.execute("SELECT id, j -> 'name' FROM docs ORDER BY id")
        assert res.rows == [(1, "ada"), (2, "bob")]
        res = pg.execute(
            "SELECT j -> 'n' ->> 'd' FROM docs ORDER BY id")
        assert res.rows == [("7",), ("9",)]
        res = pg.execute("SELECT j -> 'tags' -> 0 FROM docs WHERE id = 1")
        assert res.rows == [("x",)]
        res = pg.execute("SELECT j ->> 'n' FROM docs WHERE id = 2")
        assert res.rows == [('{"d":9}',)]
        # missing keys are NULL
        res = pg.execute("SELECT j -> 'nope' FROM docs WHERE id = 1")
        assert res.rows == [(None,)]
        with pytest.raises(InvalidArgument):
            pg.execute("INSERT INTO docs (id, j) VALUES (3, 'not json')")
    finally:
        cluster.close()


def test_jsonb_cql_storage(ql):
    ql.execute("CREATE TABLE j (k TEXT, doc JSONB, PRIMARY KEY ((k)))")
    ql.execute('INSERT INTO j (k, doc) VALUES '
               '(\'a\', \'{"z": 1, "a": [true, null]}\')')
    res = ql.execute("SELECT doc FROM j")
    assert res.rows == [({"a": [True, None], "z": 1},)]


def test_counter_increments(ql):
    ql.execute("CREATE TABLE c (k TEXT, hits COUNTER, "
               "PRIMARY KEY ((k)))")
    ql.execute("UPDATE c SET hits = hits + 1 WHERE k = 'page'")
    ql.execute("UPDATE c SET hits = hits + 5 WHERE k = 'page'")
    ql.execute("UPDATE c SET hits = hits - 2 WHERE k = 'page'")
    res = ql.execute("SELECT hits FROM c WHERE k = 'page'")
    assert res.rows == [(4,)]


def test_counter_concurrent_increments_distributed():
    """Counter deltas resolve atomically at the tablet leader: N
    concurrent incrementing sessions must never lose an increment."""
    import tempfile
    import threading

    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            setup = QLProcessor(ClientCluster(mc.client("cql-setup")))
            setup.execute("CREATE TABLE hits (k TEXT, n COUNTER, "
                          "PRIMARY KEY ((k)))")
            errs = []

            def worker(w):
                try:
                    ql = QLProcessor(ClientCluster(mc.client(f"c{w}")))
                    for _ in range(25):
                        ql.execute("UPDATE hits SET n = n + 1 "
                                   "WHERE k = 'page'")
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(w,))
                  for w in range(4)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs, errs[:1]
            res = setup.execute("SELECT n FROM hits WHERE k = 'page'")
            assert res.rows == [(100,)]
            # fused-sign subtraction parses too: 'n = n -10'
            setup.execute("UPDATE hits SET n = n -10 WHERE k = 'page'")
            res = setup.execute("SELECT n FROM hits WHERE k = 'page'")
            assert res.rows == [(90,)]
        finally:
            mc.shutdown()
