"""Socket-level CQL native-protocol tests: frame bytes in, rows out.

Reference test analog: the driver-level CQL tests
(java/yb-cql TestSelect etc.) — here a minimal v4 wire client drives the
CQLServer over a real TCP socket against a MiniCluster-backed
ClientCluster, exercising STARTUP, QUERY, PREPARE/EXECUTE with bound
values, result paging, and the ERROR path.
"""

import socket
import struct

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.cql import wire_protocol as W
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.server import CQLServer


class WireClient:
    """A tiny CQL v4 client speaking raw frames."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.stream = 0

    def close(self):
        self.sock.close()

    def _send(self, opcode, body: bytes, stream=None):
        s = self.stream if stream is None else stream
        self.sock.sendall(
            W.HEADER.pack(W.VERSION_REQ, 0, s, opcode, len(body)) + body)

    def _recv_frame(self):
        hdr = self._recvn(W.HEADER.size)
        version, flags, stream, opcode, length = W.HEADER.unpack(hdr)
        body = self._recvn(length)
        return stream, opcode, body

    def _recvn(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            assert chunk, "connection closed"
            buf += chunk
        return buf

    def startup(self):
        w = W.Writer()
        w.short(1)
        w.string("CQL_VERSION").string("3.4.4")
        self._send(W.OP_STARTUP, w.getvalue())
        _s, opcode, _b = self._recv_frame()
        assert opcode == W.OP_READY

    def query(self, cql, page_size=None, paging_state=None, values=None):
        self.stream = (self.stream + 1) % 32000
        w = W.Writer().long_string(cql)
        self._query_params(w, values, page_size, paging_state)
        self._send(W.OP_QUERY, w.getvalue())
        return self._result()

    def prepare(self, cql):
        self.stream = (self.stream + 1) % 32000
        self._send(W.OP_PREPARE, W.Writer().long_string(cql).getvalue())
        stream, opcode, body = self._recv_frame()
        assert opcode == W.OP_RESULT, body
        r = W.Reader(body)
        kind = r.int32()
        assert kind == W.RESULT_PREPARED
        stmt_id = r.short_bytes()
        flags = r.int32()
        ncols = r.int32()
        r.int32()  # pk count
        if flags & 0x0001:
            r.string(); r.string()
        bind_types = []
        for _ in range(ncols):
            r.string()
            bind_types.append(r.short())
        return stmt_id, bind_types

    def execute(self, stmt_id, raw_values, page_size=None):
        self.stream = (self.stream + 1) % 32000
        w = W.Writer().short_bytes(stmt_id)
        self._query_params(w, raw_values, page_size, None)
        self._send(W.OP_EXECUTE, w.getvalue())
        return self._result()

    def _query_params(self, w, values, page_size, paging_state):
        flags = 0
        if values:
            flags |= 0x01
        if page_size is not None:
            flags |= 0x04
        if paging_state is not None:
            flags |= 0x08
        w.short(1).byte(flags)  # consistency ONE
        if values:
            w.short(len(values))
            for v in values:
                w.bytes_(v)
        if page_size is not None:
            w.int32(page_size)
        if paging_state is not None:
            w.bytes_(paging_state)

    def _result(self):
        stream, opcode, body = self._recv_frame()
        if opcode == W.OP_ERROR:
            r = W.Reader(body)
            code = r.int32()
            raise CqlError(code, r.string())
        assert opcode == W.OP_RESULT
        r = W.Reader(body)
        kind = r.int32()
        if kind in (W.RESULT_VOID, W.RESULT_SET_KEYSPACE,
                    W.RESULT_SCHEMA_CHANGE):
            return kind, None, None
        assert kind == W.RESULT_ROWS
        flags = r.int32()
        ncols = r.int32()
        paging = r.bytes_() if flags & 0x0002 else None
        if flags & 0x0001:
            r.string(); r.string()
        cols = []
        for _ in range(ncols):
            name = r.string()
            cols.append((name, r.short()))
        nrows = r.int32()
        rows = []
        for _ in range(nrows):
            rows.append(tuple(r.bytes_() for _ in range(ncols)))
        return cols, rows, paging


class CqlError(Exception):
    def __init__(self, code, message):
        super().__init__(message)
        self.code = code


def _i32(v):  # CQL INT serialization
    return struct.pack(">i", v)


def _i64(v):
    return struct.pack(">q", v)


def _f64(v):
    return struct.pack(">d", v)


@pytest.fixture
def cql_cluster(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = CQLServer(ClientCluster(c.client()))
    host, port = server.listen("127.0.0.1", 0)
    cli = WireClient(host, port)
    cli.startup()
    yield cli
    cli.close()
    server.shutdown()
    c.shutdown()


def test_ddl_dml_select_over_socket(cql_cluster):
    cli = cql_cluster
    kind, _, _ = cli.query(
        "CREATE TABLE users (id INT, name TEXT, score DOUBLE, "
        "PRIMARY KEY (id))")
    assert kind == W.RESULT_SCHEMA_CHANGE
    for i in range(10):
        kind, _, _ = cli.query(
            f"INSERT INTO users (id, name, score) "
            f"VALUES ({i}, 'user{i}', {i}.5)")
        assert kind == W.RESULT_VOID
    cols, rows, paging = cli.query(
        "SELECT id, name, score FROM users WHERE id = 7")
    assert [c[0] for c in cols] == ["id", "name", "score"]
    assert [c[1] for c in cols] == [W.T_INT, W.T_VARCHAR, W.T_DOUBLE]
    assert len(rows) == 1
    assert struct.unpack(">i", rows[0][0])[0] == 7
    assert rows[0][1] == b"user7"
    assert struct.unpack(">d", rows[0][2])[0] == 7.5


def test_paging_over_socket(cql_cluster):
    cli = cql_cluster
    cli.query("CREATE TABLE pages (k INT, v TEXT, PRIMARY KEY (k))")
    for i in range(25):
        cli.query(f"INSERT INTO pages (k, v) VALUES ({i}, 'v{i}')")
    got = []
    paging = None
    pages = 0
    while True:
        cols, rows, paging = cli.query(
            "SELECT k, v FROM pages", page_size=7, paging_state=paging)
        got.extend(struct.unpack(">i", r[0])[0] for r in rows)
        pages += 1
        assert len(rows) <= 7
        if paging is None:
            break
        assert pages < 20
    assert sorted(got) == list(range(25))
    assert pages >= 4


def test_prepare_execute_over_socket(cql_cluster):
    cli = cql_cluster
    cli.query("CREATE TABLE pe (id INT, n BIGINT, s TEXT, "
              "PRIMARY KEY (id))")
    stmt_id, bind_types = cli.prepare(
        "INSERT INTO pe (id, n, s) VALUES (?, ?, ?)")
    assert bind_types == [W.T_INT, W.T_BIGINT, W.T_VARCHAR]
    for i in range(5):
        kind, _, _ = cli.execute(
            stmt_id, [_i32(i), _i64(i * 1000), f"s{i}".encode()])
        assert kind == W.RESULT_VOID
    sel_id, sel_binds = cli.prepare("SELECT n, s FROM pe WHERE id = ?")
    assert sel_binds == [W.T_INT]
    cols, rows, _ = cli.execute(sel_id, [_i32(3)])
    assert len(rows) == 1
    assert struct.unpack(">q", rows[0][0])[0] == 3000
    assert rows[0][1] == b"s3"


def test_error_frame_over_socket(cql_cluster):
    cli = cql_cluster
    with pytest.raises(CqlError) as ei:
        cli.query("SELECT * FROM missing_table")
    assert ei.value.code in (W.ERR_INVALID, W.ERR_SERVER)
    with pytest.raises(CqlError):
        cli.query("THIS IS NOT CQL")
    # connection still usable after errors
    kind, _, _ = cli.query(
        "CREATE TABLE after_err (k INT, PRIMARY KEY (k))")
    assert kind == W.RESULT_SCHEMA_CHANGE


def test_aggregates_over_socket(cql_cluster):
    cli = cql_cluster
    cli.query("CREATE TABLE agg (k INT, v BIGINT, PRIMARY KEY (k))")
    for i in range(20):
        cli.query(f"INSERT INTO agg (k, v) VALUES ({i}, {i * 10})")
    cols, rows, _ = cli.query("SELECT count(*), sum(v), avg(v) FROM agg")
    assert len(rows) == 1
    assert struct.unpack(">q", rows[0][0])[0] == 20


def test_limit_bind_marker_and_paging_snapshot(cql_cluster):
    cli = cql_cluster
    cli.query("CREATE TABLE lim (k INT, v INT, PRIMARY KEY (k))")
    for i in range(12):
        cli.query(f"INSERT INTO lim (k, v) VALUES ({i}, {i})")
    stmt_id, binds = cli.prepare("SELECT k FROM lim LIMIT ?")
    assert binds == [W.T_INT]
    _cols, rows, _ = cli.execute(stmt_id, [_i32(5)])
    assert len(rows) == 5
    # Paged scans pin one snapshot: a row inserted mid-scan must not
    # appear in later pages.
    got = []
    paging = None
    first = True
    while True:
        _c, rows, paging = cli.query("SELECT k FROM lim",
                                     page_size=4, paging_state=paging)
        got.extend(struct.unpack(">i", r[0])[0] for r in rows)
        if first:
            first = False
            cli.query("INSERT INTO lim (k, v) VALUES (1000, 1000)")
        if paging is None:
            break
    assert sorted(got) == list(range(12))
