"""Runtime compile witness: @compile_contract instrumentation + the
``--witness-check`` cross-validation against yb-lint's static ijit
facts.

Tier 1 counts real XLA compiles through a contracted factory, exercises
the witness dump/check exit codes, and runs one deterministic
fault-sweep round under ``compile_witness_out`` (must exit 0: runtime
compile behaviour never contradicts a static @compile_contract fact).
"""

import functools
import json
import tempfile

import jax
import jax.numpy as jnp
import pytest

from yugabyte_db_tpu.utils import jitting, metrics
from yugabyte_db_tpu.utils.jitting import compile_contract


@pytest.fixture(autouse=True)
def _witness_reset():
    jitting.witness().clear()
    yield
    jitting.disable_compile_witness()
    jitting.witness().clear()


def _obs(entry):
    for row in jitting.witness().observations():
        if row["entry"] == entry:
            return row
    return None


# -- decorator semantics -----------------------------------------------------

def test_declaration_is_registered():
    compile_contract("test_decl_entry", max_compiles=7)(lambda: None)
    assert jitting.declared_contracts()["test_decl_entry"] == 7


def test_non_literal_declaration_rejected():
    with pytest.raises(TypeError):
        compile_contract("", max_compiles=4)
    with pytest.raises(TypeError):
        compile_contract("x", max_compiles=0)
    with pytest.raises(TypeError):
        compile_contract(3, max_compiles=4)
    with pytest.raises(TypeError):
        compile_contract("x", max_compiles="4")


def test_factory_wraps_only_jitted_results():
    @compile_contract("test_passthrough", max_compiles=4)
    def factory(jitted):
        return jax.jit(lambda x: x) if jitted else (lambda x: x)

    assert isinstance(factory(True), jitting.ContractedJit)
    assert not isinstance(factory(False), jitting.ContractedJit)
    assert factory.__compile_contract__ == ("test_passthrough", 4)


def test_wrapper_delegates_attributes():
    @compile_contract("test_deleg", max_compiles=4)
    @jax.jit
    def double(x):
        return x + x

    assert isinstance(double, jitting.ContractedJit)
    assert callable(double.lower)          # jit API still reachable
    assert double._cache_size() == 0


# -- compile counting --------------------------------------------------------

def test_factory_counts_one_compile_per_signature():
    @functools.lru_cache(maxsize=None)
    @compile_contract("test_toy_factory", max_compiles=4)
    def toy(n):
        return jax.jit(lambda x: x * n)

    jitting.enable_compile_witness()
    before = metrics.jit_compiles("test_toy_factory")
    toy(2)(jnp.arange(3))      # compile 1
    toy(2)(jnp.arange(3))      # cache hit: no compile
    toy(2)(jnp.arange(5))      # new shape: compile 2
    toy(3)(jnp.arange(3))      # new factory signature: compile 3
    assert metrics.jit_compiles("test_toy_factory") - before == 3
    row = _obs("test_toy_factory")
    assert row["compiles"] == 3 and row["steady"] == 0
    assert row["budget"] == 4
    assert any("test_compile_witness" in s for s in row["sites"])


def test_direct_jit_counts_compiles():
    @compile_contract("test_toy_direct", max_compiles=2)
    @jax.jit
    def double(x):
        return x + x

    jitting.enable_compile_witness()
    before = metrics.jit_compiles("test_toy_direct")
    double(jnp.arange(4))
    double(jnp.arange(4))
    assert metrics.jit_compiles("test_toy_direct") - before == 1


def test_metric_counts_with_witness_disabled():
    @functools.lru_cache(maxsize=None)
    @compile_contract("test_toy_nowit", max_compiles=4)
    def toy(n):
        return jax.jit(lambda x: x + n)

    before = metrics.jit_compiles("test_toy_nowit")
    toy(5)(jnp.arange(2))
    assert metrics.jit_compiles("test_toy_nowit") - before == 1
    assert _obs("test_toy_nowit") is None  # witness off: no observation


def test_steady_state_compiles_tracked_separately():
    @functools.lru_cache(maxsize=None)
    @compile_contract("test_toy_steady", max_compiles=8)
    def toy(n):
        return jax.jit(lambda x: x - n)

    jitting.enable_compile_witness()
    toy(1)(jnp.arange(3))              # warmup compile
    jitting.mark_steady_state()
    toy(1)(jnp.arange(3))              # cache hit: nothing recorded
    toy(1)(jnp.arange(9))              # steady-state recompile
    row = _obs("test_toy_steady")
    assert row["compiles"] == 2 and row["steady"] == 1


# -- dump / load -------------------------------------------------------------

def test_dump_load_round_trip(tmp_path):
    @functools.lru_cache(maxsize=None)
    @compile_contract("test_toy_dump", max_compiles=4)
    def toy(n):
        return jax.jit(lambda x: x * x * n)

    jitting.enable_compile_witness()
    toy(2)(jnp.arange(3))
    path = str(tmp_path / "cwit.json")
    assert jitting.dump_compile_witness(path) == path
    data = jitting.load_compile_witness_dump(path)
    assert data["kind"] == "yb-compile-witness"
    rows = {o["entry"]: o for o in data["observations"]}
    assert rows["test_toy_dump"]["compiles"] == 1
    assert rows["test_toy_dump"]["budget"] == 4


def test_load_rejects_wrong_kind(tmp_path):
    p = tmp_path / "wrong.json"
    p.write_text(json.dumps({"kind": "yb-lock-witness", "observations": []}))
    with pytest.raises(ValueError):
        jitting.load_compile_witness_dump(str(p))


# -- witness-check exit codes ------------------------------------------------

def _witness_check(dump_path):
    from yugabyte_db_tpu.analysis.__main__ import main

    return main(["--witness-check", dump_path])


def _forged_dump(tmp_path, observations):
    p = tmp_path / "forged.json"
    p.write_text(json.dumps({"version": 1, "kind": "yb-compile-witness",
                             "observations": observations}))
    return str(p)


def test_witness_check_clean_dump_exits_zero(tmp_path, capsys):
    """Real compiles of a tree-contracted entry (ops.compact gc_mask)
    within budget: no contradiction."""
    from yugabyte_db_tpu.ops.compact import compiled_gc_mask

    jitting.enable_compile_witness()
    N = 12
    s = {"new_group": jnp.array([True] + [False] * (N - 1)),
         "tomb": jnp.zeros(N, jnp.bool_),
         "live": jnp.ones(N, jnp.bool_),
         "ht_hi": jnp.arange(N, 0, -1, dtype=jnp.int32),
         "ht_lo": jnp.zeros(N, jnp.int32),
         "exp_hi": jnp.full(N, 2**30, jnp.int32),
         "exp_lo": jnp.zeros(N, jnp.int32),
         "set_": jnp.ones((1, N), jnp.bool_)}
    planes = (jnp.int32(6), jnp.int32(0), jnp.int32(6), jnp.int32(0))
    compiled_gc_mask(1, N)(s, planes)
    assert _obs("gc_mask") is not None
    path = str(tmp_path / "cwit.json")
    jitting.dump_compile_witness(path)
    assert _witness_check(path) == 0
    assert "OK" in capsys.readouterr().out


def test_witness_check_budget_overrun_exits_two(tmp_path, capsys):
    path = _forged_dump(tmp_path, [
        {"entry": "seg_aggregate", "compiles": 999, "steady": 0,
         "budget": 128, "sites": ["forged.py:1"]}])
    assert _witness_check(path) == 2
    out = capsys.readouterr().out
    assert "seg_aggregate" in out and "max_compiles=128" in out


def test_witness_check_uncontracted_entry_exits_two(tmp_path, capsys):
    path = _forged_dump(tmp_path, [
        {"entry": "no_such_entry", "compiles": 1, "steady": 0,
         "budget": None, "sites": []}])
    assert _witness_check(path) == 2
    assert "no @compile_contract" in capsys.readouterr().out


def test_witness_check_steady_recompile_on_stable_exits_two(tmp_path, capsys):
    """seg_aggregate is statically proven stable (zero ijit findings),
    so a steady-state recompile contradicts the static pass."""
    path = _forged_dump(tmp_path, [
        {"entry": "seg_aggregate", "compiles": 2, "steady": 1,
         "budget": 128, "sites": []}])
    assert _witness_check(path) == 2
    assert "steady-state" in capsys.readouterr().out


def test_witness_check_rejects_non_dump(tmp_path):
    p = tmp_path / "not_a_dump.json"
    p.write_text("{}")
    assert _witness_check(str(p)) == 1


# -- the tier-1 integration round --------------------------------------------

def test_sweep_compile_witness_clean(tmp_path):
    """One deterministic fault-sweep round under the compile witness:
    every compile observed at runtime stays within its declared budget
    and no statically-stable entry recompiles (``--witness-check``
    exits 0)."""
    from yugabyte_db_tpu.integration.fault_sweep import FaultSweep

    path = str(tmp_path / "sweep_cwit.json")
    with tempfile.TemporaryDirectory() as root:
        summary = FaultSweep(root, seed=1234, ops_per_round=8,
                             schedule=("wal_sync", "hbm_eviction"),
                             compile_witness_out=path).run()
    assert summary["rounds"] == 2
    data = jitting.load_compile_witness_dump(path)
    assert data["observations"], "sweep compiled nothing?"
    assert _witness_check(path) == 0
