"""Tablet splitting: the master-driven seal -> fork -> seed -> commit
protocol, per-tablet meta-cache invalidation, the ``tablet_split`` wire
code, and the auto-split threshold pass.

Reference analogs: tablet-split-itest.cc (split under load, client
re-routing), meta_cache.cc (one RemoteTablet marked stale on
TABLET_SPLIT), and the size/ops trigger scan of
master/tablet_split_manager.cc.
"""

import os
import tempfile
import time

import pytest

from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import tablet_splits_total


@pytest.fixture(scope="module")
def cluster():
    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(os.path.join(root, "c"), num_tservers=3).start()
        mc.wait_tservers_registered()
        try:
            yield mc
        finally:
            mc.shutdown()


@pytest.fixture(scope="module")
def table(cluster):
    client = cluster.client()
    t = client.create_table("split_t", [
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64)], num_tablets=2)
    s = YBSession(client)
    for i in range(200):
        s.insert(t, {"k": f"key-{i:04d}", "v": i})
    s.flush()
    return client, t


def test_meta_cache_invalidates_one_tablet_not_siblings(table):
    """Satellite regression: splitting one tablet must not evict the
    SIBLING tablets' cached locations or learned leader hints."""
    client, t = table
    locs = client.meta_cache.locations("split_t", refresh=True)
    assert len(locs.tablets) == 2
    victim, sibling = locs.tablets
    # Learn a leader hint on the sibling, then punch the victim out.
    client.meta_cache.mark_leader("split_t", sibling.tablet_id, "ts-1")
    client.meta_cache.invalidate_tablet("split_t", victim.tablet_id)
    cached = client.meta_cache._tables["split_t"].tablets
    assert [x.tablet_id for x in cached] == [sibling.tablet_id]
    assert cached[0] is sibling          # same object: nothing rebuilt
    assert cached[0].leader == "ts-1"    # hint survived the punch-out
    assert not client.meta_cache.covers("split_t", victim.partition_start)
    assert client.meta_cache.covers("split_t", sibling.partition_start)
    # A lookup into the punched range self-heals with ONE refresh.
    back = client.meta_cache.lookup_by_hash("split_t",
                                            victim.partition_start)
    assert back.tablet_id == victim.tablet_id
    # Unknown tablet ids are a no-op (idempotent double invalidation).
    client.meta_cache.invalidate_tablet("split_t", "no-such-tablet")
    assert len(client.meta_cache._tables["split_t"].tablets) == 2


def test_manual_split_preserves_data_and_lineage(cluster, table):
    client, t = table
    base_splits = tablet_splits_total()
    locs = client.meta_cache.locations("split_t", refresh=True)
    parent = locs.tablets[0].tablet_id
    resp = client.master_rpc(
        "master.split_tablet",
        {"table": "split_t", "tablet_id": parent, "timeout": 30.0},
        timeout_s=40.0)
    assert resp["code"] == "ok", resp
    children = resp["children"]
    assert len(children) == 2
    assert tablet_splits_total() == base_splits + 1

    # The parent's range was divided at an interior hash: children abut.
    locs = client.meta_cache.locations("split_t", refresh=True)
    ids = [x.tablet_id for x in locs.tablets]
    assert parent not in ids and set(children) <= set(ids)
    assert len(locs.tablets) == 3
    for a, b in zip(locs.tablets, locs.tablets[1:]):
        assert a.partition_end == b.partition_start

    # Every pre-split row is still readable; writes route to children.
    s = YBSession(client)
    res = s.scan(t, ScanSpec(projection=["k", "v"]))
    assert dict(res.rows) == {f"key-{i:04d}": i for i in range(200)}
    s.insert(t, {"k": "post-split", "v": 777})
    s.flush()
    assert s.get(t, {"k": "post-split"})[1] == 777

    # Replicated lineage: parent -> children, COMMITTED.
    m = cluster.masters["m-0"]
    lineage = {r["parent"]: r for r in m.catalog.split_lineage()}
    assert lineage[parent]["state"] == "COMMITTED"
    assert sorted(lineage[parent]["children"]) == sorted(children)


def test_stale_cache_replans_through_departed_parent(cluster, table):
    """A client that cached locations BEFORE the split (its cache still
    names the deleted parent) must transparently re-plan, not fail."""
    client, _t = table
    fresh = cluster.client()
    t2 = fresh.open_table("split_t")
    fresh.meta_cache.locations("split_t")  # prime the cache
    locs = client.meta_cache.locations("split_t", refresh=True)
    parent = locs.tablets[-1].tablet_id  # the un-split seed tablet
    resp = client.master_rpc(
        "master.split_tablet", {"tablet_id": parent, "timeout": 30.0},
        timeout_s=40.0)
    assert resp["code"] == "ok", resp
    # The stale client reads and writes through its dead cache entry.
    s = YBSession(fresh)
    res = s.scan(t2, ScanSpec(projection=["k", "v"]))
    assert len(res.rows) == 201  # 200 seed rows + post-split
    s.insert(t2, {"k": "stale-route", "v": 888})
    s.flush()
    assert s.get(t2, {"k": "stale-route"})[1] == 888


def test_sealed_tablet_answers_tablet_split_wire_code(cluster):
    """The seal gate's wire contract: a sealed parent rejects reads AND
    writes with ``code=tablet_split`` naming the tablet (what drives
    per-tablet invalidation client-side)."""
    from yugabyte_db_tpu.storage import wire

    client = cluster.client()
    t = client.create_table("seal_t", [
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64)], num_tablets=1)
    s = YBSession(client)
    s.insert(t, {"k": "a", "v": 1})
    s.flush()
    # The flush just learned the leader (not_leader hint-following);
    # a master refresh could race the heartbeat and report None.
    loc = client.meta_cache.locations("seal_t").tablets[0]
    assert loc.leader is not None
    sealed = client.transport.send(
        loc.leader, "ts.split_seal",
        {"tablet_id": loc.tablet_id, "timeout": 5.0}, timeout=10.0)
    assert sealed["code"] == "ok", sealed
    w = client.transport.send(loc.leader, "ts.write", {
        "tablet_id": loc.tablet_id,
        "rows": wire.encode_rows([]), "timeout": 2.0}, timeout=5.0)
    assert w["code"] == "tablet_split"
    assert w["tablet_id"] == loc.tablet_id
    r = client.transport.send(loc.leader, "ts.scan", {
        "tablet_id": loc.tablet_id,
        "spec": wire.encode_spec(ScanSpec()), "timeout": 2.0},
        timeout=5.0)
    assert r["code"] == "tablet_split"
    client.delete_table("seal_t")


def test_auto_split_pass_triggers_on_size_threshold(cluster, table):
    """With ``--tablet_split_size_bytes`` live, the master's background
    pass splits an over-threshold tablet on its own (one per pass)."""
    client, _t = table
    m = cluster.masters["m-0"]
    before = len(m.catalog.split_lineage())
    FLAGS.set("tablet_split_size_bytes", 1, force=True)
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            done = [r for r in m.catalog.split_lineage()
                    if r["state"] == "COMMITTED"]
            if len(done) > before:
                break
            time.sleep(0.1)
        else:
            pytest.fail("auto-split pass never committed a split")
    finally:
        FLAGS.set("tablet_split_size_bytes", 0, force=True)
    # Data still intact after the background split.
    res = YBSession(client).scan(
        client.open_table("split_t"), ScanSpec(projection=["k"]))
    assert len(res.rows) == 202
