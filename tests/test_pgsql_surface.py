"""PG surface round 4: RIGHT/FULL JOIN, views, sequences, SAVEPOINT.

Reference parity targets: the full PG 11.2 surface (src/postgres/);
these close the VERDICT-flagged gaps incrementally.
"""

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.utils.status import InvalidArgument, NotFound
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.processor import LocalCluster
from yugabyte_db_tpu.yql.pgsql.executor import PgProcessor


@pytest.fixture
def pg():
    cluster = LocalCluster(num_tablets=2)
    yield PgProcessor(cluster)
    cluster.close()


@pytest.fixture
def dist_pg(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    yield PgProcessor(ClientCluster(c.client()))
    c.shutdown()


def _load(pg):
    pg.execute("CREATE TABLE emp (id INT PRIMARY KEY, dept TEXT, "
               "salary BIGINT)")
    pg.execute("CREATE TABLE dept (name TEXT PRIMARY KEY, region TEXT)")
    for i in range(9):
        pg.execute(f"INSERT INTO emp (id, dept, salary) VALUES "
                   f"({i}, 'd{i % 3}', {i * 100})")
    pg.execute("INSERT INTO dept (name, region) VALUES ('d0', 'east')")
    pg.execute("INSERT INTO dept (name, region) VALUES ('d1', 'west')")
    pg.execute("INSERT INTO dept (name, region) VALUES ('dx', 'void')")


def test_right_join_preserves_unmatched_right(pg):
    _load(pg)
    rows = pg.execute(
        "SELECT emp.id, dept.name FROM emp RIGHT JOIN dept "
        "ON emp.dept = dept.name").rows
    ids_by_dept = {}
    for i, name in rows:
        ids_by_dept.setdefault(name, []).append(i)
    assert sorted(ids_by_dept["d0"]) == [0, 3, 6]
    assert ids_by_dept["dx"] == [None]
    assert "d2" not in ids_by_dept  # left-only depts drop on RIGHT join


def test_full_join_preserves_both_sides(pg):
    _load(pg)
    rows = pg.execute(
        "SELECT emp.id, emp.dept, dept.name FROM emp FULL JOIN dept "
        "ON emp.dept = dept.name").rows
    # 9 matched-or-left rows + 1 right-only (dx)
    assert len(rows) == 10
    assert (None, None, "dx") in rows
    d2 = [r for r in rows if r[1] == "d2"]
    assert d2 and all(r[2] is None for r in d2)  # left preserved


def test_full_join_where_applies_after_join(pg):
    _load(pg)
    rows = pg.execute(
        "SELECT emp.id, dept.name FROM emp FULL JOIN dept "
        "ON emp.dept = dept.name WHERE dept.region = 'void'").rows
    assert rows == [(None, "dx")]


@pytest.mark.parametrize("fixture", ["pg", "dist_pg"])
def test_views_round_trip(fixture, request):
    pg = request.getfixturevalue(fixture)
    _load(pg)
    pg.execute("CREATE VIEW rich AS SELECT id, salary FROM emp "
               "WHERE salary >= 400")
    rows = pg.execute("SELECT id FROM rich WHERE salary < 700 "
                      "ORDER BY id").rows
    assert rows == [(4,), (5,), (6,)]
    assert len(pg.execute("SELECT * FROM rich").rows) == 5
    with pytest.raises(InvalidArgument):
        pg.execute("CREATE VIEW rich AS SELECT id FROM emp")
    pg.execute("CREATE OR REPLACE VIEW rich AS SELECT id FROM emp "
               "WHERE salary >= 800")
    assert pg.execute("SELECT * FROM rich").rows == [(8,)]
    pg.execute("DROP VIEW rich")
    with pytest.raises((InvalidArgument, NotFound)):
        pg.execute("SELECT * FROM rich")


@pytest.mark.parametrize("fixture", ["pg", "dist_pg"])
def test_sequences(fixture, request):
    pg = request.getfixturevalue(fixture)
    pg.execute("CREATE SEQUENCE ids")
    assert pg.execute("SELECT nextval('ids')").rows == [(1,)]
    assert pg.execute("SELECT nextval('ids')").rows == [(2,)]
    assert pg.execute("SELECT currval('ids')").rows == [(2,)]
    pg.execute("CREATE TABLE st (id INT PRIMARY KEY, v INT)")
    pg.execute("INSERT INTO st (id, v) VALUES (nextval('ids'), 7)")
    assert pg.execute("SELECT id, v FROM st").rows == [(3, 7)]
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT nextval('nope')")
    pg.execute("DROP SEQUENCE ids")
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT nextval('ids')")


def test_savepoints(dist_pg):
    pg = dist_pg
    pg.execute("CREATE TABLE acc (id INT PRIMARY KEY, bal INT)")
    pg.execute("BEGIN")
    pg.execute("INSERT INTO acc (id, bal) VALUES (1, 100)")
    pg.execute("SAVEPOINT s1")
    pg.execute("INSERT INTO acc (id, bal) VALUES (2, 200)")
    pg.execute("SAVEPOINT s2")
    pg.execute("INSERT INTO acc (id, bal) VALUES (3, 300)")
    pg.execute("ROLLBACK TO SAVEPOINT s2")   # drops id=3
    pg.execute("INSERT INTO acc (id, bal) VALUES (4, 400)")
    pg.execute("ROLLBACK TO s1")             # drops 2 and 4
    pg.execute("INSERT INTO acc (id, bal) VALUES (5, 500)")
    pg.execute("RELEASE SAVEPOINT s1")
    pg.execute("COMMIT")
    rows = sorted(pg.execute("SELECT id, bal FROM acc").rows)
    assert rows == [(1, 100), (5, 500)]
    # rollback-to a released/unknown savepoint fails the block
    pg.execute("BEGIN")
    with pytest.raises(Exception):
        pg.execute("ROLLBACK TO SAVEPOINT nope")
    pg.execute("ROLLBACK")
