"""ops.flat_fold (fused full-array flat aggregate) vs the windowed fold
and the CPU oracle — exact equivalence on randomized data.

The flat path must agree bit-for-bit on integer aggregates (exact limb
sums incl. negative values, NULLs, TTL expiry, tombstones, predicates,
range bounds) and to float tolerance on float sums.
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (AggSpec, Predicate, RowVersion,
                                     ScanSpec, make_engine)
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
        ColumnSchema("f", DataType.FLOAT),
    ], table_id="ff")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def load_flat(schema, engines, n=600, seed=13):
    rnd = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.value_columns}
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 3)
        key = enc(schema, f"k{i:05d}", i % 9)
        if rnd.random() < 0.05:
            rv = RowVersion(key, ht=ht, tombstone=True)
        else:
            rv = RowVersion(
                key, ht=ht, liveness=True,
                columns={cid["a"]: rnd.randrange(-10**13, 10**13),
                         cid["c"]: rnd.uniform(-1e8, 1e8),
                         cid["d"]: rnd.choice(
                             [None, rnd.randrange(-10**6, 10**6)]),
                         cid["f"]: rnd.uniform(-100, 100)},
                expire_ht=(ht + rnd.randrange(10, 400)
                           if rnd.random() < 0.1 else MAX_HT))
        for e in engines:
            e.apply([rv])
    for e in engines:
        e.flush()
    return ht


AGGS = [AggSpec("count", None), AggSpec("count", "d"), AggSpec("sum", "a"),
        AggSpec("sum", "d"), AggSpec("min", "a"), AggSpec("max", "a"),
        AggSpec("min", "d"), AggSpec("max", "d"), AggSpec("min", "c"),
        AggSpec("max", "c"), AggSpec("avg", "a")]


def assert_same_agg(cpu, tpu, **kw):
    a = cpu.scan(ScanSpec(**kw))
    b = tpu.scan(ScanSpec(**kw))
    assert a.columns == b.columns
    for va, vb, name in zip(a.rows[0], b.rows[0], a.columns):
        if isinstance(va, float):
            assert vb == pytest.approx(va, rel=1e-5, abs=1e-5), name
        else:
            assert va == vb, name


def test_flat_fold_route_taken():
    from yugabyte_db_tpu.ops import flat_fold

    schema = make_schema()
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    load_flat(schema, [tpu])
    spec = ScanSpec(read_ht=MAX_HT, aggregates=list(AGGS))
    plan = tpu._plan_scan(spec)
    assert plan[0] == "agg_deferred"  # device aggregate (batched sink)
    route = tpu._device_agg_prep(tpu.runs[0], spec, [])[1]
    assert route == "flat"
    assert tpu.runs[0].crun.max_group_versions <= 1
    # eligibility holds for this shape
    assert flat_fold.MAX_B >= tpu.runs[0].dev.B


def test_flat_fold_matches_oracle_exactly():
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    ht = load_flat(schema, [cpu, tpu])
    for rp in (1, ht // 3, ht, MAX_HT):
        assert_same_agg(cpu, tpu, read_ht=rp, aggregates=list(AGGS))


def test_flat_fold_with_predicates_and_bounds():
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    ht = load_flat(schema, [cpu, tpu])
    lo = enc(schema, "k00100", 0)
    hi = enc(schema, "k00400", 0)
    cases = [
        dict(read_ht=MAX_HT, aggregates=list(AGGS),
             predicates=[Predicate("d", ">=", 0)]),
        dict(read_ht=MAX_HT, aggregates=list(AGGS),
             predicates=[Predicate("a", "<", 0),
                         Predicate("d", "!=", 7)]),
        dict(read_ht=ht, aggregates=list(AGGS), lower=lo, upper=hi),
        dict(read_ht=MAX_HT, aggregates=[AggSpec("count", None)],
             predicates=[Predicate("c", ">=", 0.0)]),
        dict(read_ht=MAX_HT, aggregates=list(AGGS),
             predicates=[Predicate("d", ">", 10**7)]),  # empty match
    ]
    for kw in cases:
        assert_same_agg(cpu, tpu, **kw)


def test_flat_fold_float_sum_tolerance():
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    load_flat(schema, [cpu, tpu], n=900, seed=21)
    a = cpu.scan(ScanSpec(read_ht=MAX_HT,
                          aggregates=[AggSpec("sum", "c"),
                                      AggSpec("sum", "f"),
                                      AggSpec("avg", "c")]))
    b = tpu.scan(ScanSpec(read_ht=MAX_HT,
                          aggregates=[AggSpec("sum", "c"),
                                      AggSpec("sum", "f"),
                                      AggSpec("avg", "c")]))
    for va, vb in zip(a.rows[0], b.rows[0]):
        assert vb == pytest.approx(va, rel=1e-4)


def test_flat_fold_extreme_int_sums():
    """Limb exactness at the extremes: int64 values near +/-2^62 and a
    sum crossing zero."""
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    cid = {c.name: c.col_id for c in schema.value_columns}
    vals = [(1 << 62) - 1, -(1 << 62), 12345, -12345, 1, -1,
            (1 << 61), -(1 << 61) + 7]
    for i, v in enumerate(vals):
        rv = RowVersion(enc(schema, f"x{i}", 0), ht=10 + i, liveness=True,
                        columns={cid["a"]: v})
        cpu.apply([rv])
        tpu.apply([rv])
    cpu.flush()
    tpu.flush()
    assert_same_agg(cpu, tpu, read_ht=MAX_HT,
                    aggregates=[AggSpec("sum", "a"), AggSpec("min", "a"),
                                AggSpec("max", "a"),
                                AggSpec("count", "a")])
