"""Redis command families added for reference parity: sorted sets,
lists, time series, ranges, rename, TTL variants, multi-database,
AUTH/CONFIG, FLUSHDB/FLUSHALL, and pubsub/MONITOR server-push frames
(reference registry: redis_commands.cc:69-154).
"""

import socket
import time

import pytest

from tests.test_redis import RedisError, RespClient
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.redis import RedisServer


@pytest.fixture(scope="module")
def rig(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("redisfam")
    c = MiniCluster(str(tmp), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = RedisServer(c.client("redis-proxy"))
    host, port = server.listen("127.0.0.1", 0)
    yield host, port
    server.shutdown()
    c.shutdown()


@pytest.fixture
def r(rig):
    cli = RespClient(*rig)
    cli.cmd("FLUSHALL")
    cli.cmd("CONFIG", "SET", "requirepass", "")  # note: "" means unset-ish
    yield cli
    cli.close()


def test_sorted_sets(r):
    assert r.cmd("ZADD", "z", "3", "c", "1", "a", "2", "b") == 3
    assert r.cmd("ZADD", "z", "5", "a") == 0         # update, not add
    assert r.cmd("ZCARD", "z") == 3
    assert r.cmd("ZSCORE", "z", "a") == "5"
    assert r.cmd("ZSCORE", "z", "nope") is None
    assert r.cmd("ZRANGE", "z", "0", "-1") == ["b", "c", "a"]
    assert r.cmd("ZRANGE", "z", "0", "1", "WITHSCORES") == \
        ["b", "2", "c", "3"]
    assert r.cmd("ZREVRANGE", "z", "0", "0") == ["a"]
    assert r.cmd("ZRANGEBYSCORE", "z", "2", "3") == ["b", "c"]
    assert r.cmd("ZRANGEBYSCORE", "z", "(2", "+inf") == ["c", "a"]
    assert r.cmd("ZRANGEBYSCORE", "z", "-inf", "+inf") == ["b", "c", "a"]
    assert r.cmd("ZREM", "z", "b", "nope") == 1
    assert r.cmd("ZCARD", "z") == 2


def test_lists(r):
    assert r.cmd("RPUSH", "l", "b", "c") == 2
    assert r.cmd("LPUSH", "l", "a") == 3
    assert r.cmd("LLEN", "l") == 3
    assert r.cmd("LPOP", "l") == "a"
    assert r.cmd("RPOP", "l") == "c"
    assert r.cmd("LPOP", "l") == "b"
    assert r.cmd("LPOP", "l") is None
    assert r.cmd("LLEN", "l") == 0


def test_time_series(r):
    assert r.cmd("TSADD", "ts", "100", "v100", "50", "v50",
                 "-20", "vneg") == "OK"
    assert r.cmd("TSGET", "ts", "50") == "v50"
    assert r.cmd("TSGET", "ts", "51") is None
    assert r.cmd("TSCARD", "ts") == 3
    assert r.cmd("TSRANGEBYTIME", "ts", "-inf", "+inf") == \
        ["-20", "vneg", "50", "v50", "100", "v100"]
    assert r.cmd("TSRANGEBYTIME", "ts", "0", "99") == ["50", "v50"]
    assert r.cmd("TSREVRANGEBYTIME", "ts", "-inf", "+inf") == \
        ["100", "v100", "50", "v50", "-20", "vneg"]
    assert r.cmd("TSLASTN", "ts", "2") == ["50", "v50", "100", "v100"]
    assert r.cmd("TSREM", "ts", "50") == 1
    assert r.cmd("TSCARD", "ts") == 2


def test_string_ranges(r):
    r.cmd("SET", "s", "Hello World")
    assert r.cmd("GETRANGE", "s", "0", "4") == "Hello"
    assert r.cmd("GETRANGE", "s", "-5", "-1") == "World"
    assert r.cmd("SETRANGE", "s", "6", "Redis") == 11
    assert r.cmd("GET", "s") == "Hello Redis"
    assert r.cmd("SETRANGE", "empty", "3", "x") == 4
    assert r.cmd("GET", "empty") == "\x00\x00\x00x"


def test_hash_extensions(r):
    r.cmd("HSET", "h", "f", "10")
    assert r.cmd("HINCRBY", "h", "f", "5") == 15
    assert r.cmd("HINCRBY", "h", "new", "-3") == -3
    assert r.cmd("HSTRLEN", "h", "f") == 2
    assert r.cmd("HSTRLEN", "h", "missing") == 0


def test_rename(r):
    r.cmd("HSET", "src", "a", "1", "b", "2")
    r.cmd("SET", "dst", "old")
    assert r.cmd("RENAME", "src", "dst") == "OK"
    assert r.cmd("HGET", "dst", "a") == "1"
    assert r.cmd("GET", "dst") is None          # old dst content replaced
    assert r.cmd("EXISTS", "src") == 0
    with pytest.raises(RedisError):
        r.cmd("RENAME", "nope", "x")


def test_ttl_variants(r):
    r.cmd("SET", "t1", "v")
    assert r.cmd("PEXPIRE", "t1", "600000") == 1
    assert r.cmd("PERSIST", "t1") == 1
    assert r.cmd("TTL", "t1") == -1
    assert r.cmd("PTTL", "missing") == -2
    assert r.cmd("EXPIREAT", "t1", str(int(time.time()) + 600)) == 1
    assert r.cmd("GET", "t1") == "v"
    # expireat in the past deletes
    assert r.cmd("EXPIREAT", "t1", "1") == 1
    assert r.cmd("GET", "t1") is None
    assert r.cmd("PSETEX", "t2", "600000", "v2") == "OK"
    assert r.cmd("GET", "t2") == "v2"


def test_databases(r):
    r.cmd("SET", "k", "db0")
    assert r.cmd("CREATEDB", "two") == "OK"
    assert "two" in r.cmd("LISTDB")
    assert r.cmd("SELECT", "two") == "OK"
    assert r.cmd("GET", "k") is None            # isolated namespace
    r.cmd("SET", "k", "db2")
    assert r.cmd("GET", "k") == "db2"
    assert r.cmd("KEYS", "*") == ["k"]
    assert r.cmd("SELECT", "0") == "OK"
    assert r.cmd("GET", "k") == "db0"
    with pytest.raises(RedisError):
        r.cmd("SELECT", "nonexistent")
    assert r.cmd("DELETEDB", "two") == "OK"
    with pytest.raises(RedisError):
        r.cmd("SELECT", "two")


def test_flushdb_scoped(r):
    r.cmd("SET", "a", "1")
    r.cmd("CREATEDB", "other")
    r.cmd("SELECT", "other")
    r.cmd("SET", "b", "2")
    assert r.cmd("FLUSHDB") == "OK"
    assert r.cmd("KEYS", "*") == []
    r.cmd("SELECT", "0")
    assert r.cmd("GET", "a") == "1"             # other db untouched
    assert r.cmd("FLUSHALL") == "OK"
    assert r.cmd("KEYS", "*") == []
    r.cmd("DELETEDB", "other")


def test_pubsub_push(rig):
    sub = RespClient(*rig)
    pub = RespClient(*rig)
    try:
        assert sub.cmd("SUBSCRIBE", "news") == ["subscribe", "news", 1]
        # Let the subscription register before publishing.
        assert pub.cmd("PUBSUB", "CHANNELS") == ["news"]
        assert pub.cmd("PUBLISH", "news", "hello") == 1
        assert sub._read_reply() == ["message", "news", "hello"]
        assert pub.cmd("PUBLISH", "nosubs", "x") == 0
        assert sub.cmd("UNSUBSCRIBE", "news") == ["unsubscribe", "news", 0]
        assert pub.cmd("PUBSUB", "NUMPAT") == 0
    finally:
        sub.close()
        pub.close()


def test_pattern_subscribe(rig):
    sub = RespClient(*rig)
    pub = RespClient(*rig)
    try:
        assert sub.cmd("PSUBSCRIBE", "news.*") == \
            ["psubscribe", "news.*", 1]
        assert pub.cmd("PUBLISH", "news.tech", "t") == 1
        assert sub._read_reply() == ["pmessage", "news.*", "news.tech", "t"]
    finally:
        sub.close()
        pub.close()


def test_monitor_push(rig):
    mon = RespClient(*rig)
    cli = RespClient(*rig)
    try:
        assert mon.cmd("MONITOR") == "OK"
        cli.cmd("SET", "mk", "v")
        line = mon._read_reply()
        assert '"SET"' in line and '"mk"' in line
    finally:
        mon.close()
        cli.close()


def test_auth(rig):
    admin = RespClient(*rig)
    other = RespClient(*rig)
    try:
        assert admin.cmd("CONFIG", "SET", "requirepass", "s3cret") == "OK"
        with pytest.raises(RedisError, match="NOAUTH"):
            other.cmd("GET", "k")
        with pytest.raises(RedisError, match="invalid password"):
            other.cmd("AUTH", "wrong")
        assert other.cmd("AUTH", "s3cret") == "OK"
        other.cmd("SET", "k", "v")            # authorized now
        assert other.cmd("GET", "k") == "v"
        # admin set the password but never authed: locked out too.
        with pytest.raises(RedisError, match="NOAUTH"):
            admin.cmd("CONFIG", "GET", "requirepass")
        assert other.cmd("CONFIG", "GET", "requirepass") == \
            ["requirepass", "s3cret"]
    finally:
        # Unset so later tests in this module aren't locked out.
        try:
            other.cmd("CONFIG", "SET", "requirepass", "")
        finally:
            admin.close()
            other.close()


def test_misc_server_commands(r):
    assert r.cmd("ROLE") == ["master"]
    assert r.cmd("QUIT") == "OK"
    assert "cluster_enabled:0" in r.cmd("CLUSTER", "INFO")
    assert r.cmd("PUBSUB", "NUMSUB", "nochannel") == ["nochannel", 0]


def test_command_count_target():
    """The reference registers ~85 commands (redis_commands.cc:69-154);
    parity requires >= 70 here."""
    from yugabyte_db_tpu.yql.redis.server import RedisServiceImpl

    cmds = [m for m in dir(RedisServiceImpl) if m.startswith("cmd_")]
    assert len(cmds) >= 70, len(cmds)
