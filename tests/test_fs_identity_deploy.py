"""Task-11 gap closers: data-dir identity (FsManager), block-level run
dump (sst_dump analog), and the docker deploy orchestrator's command
construction.
"""

import os
import subprocess
import sys

import pytest

from yugabyte_db_tpu import fs as yfs
from yugabyte_db_tpu.tools import yb_docker_ctl as dctl


def test_instance_metadata_format_and_reopen(tmp_path):
    d = str(tmp_path / "ts-data")
    meta = yfs.format_or_open(d, "ts-1")
    assert meta["server_uuid"] == "ts-1" and meta["instance_uuid"]
    again = yfs.format_or_open(d, "ts-1")
    assert again["instance_uuid"] == meta["instance_uuid"]


def test_instance_metadata_rejects_swapped_dir(tmp_path):
    d = str(tmp_path / "ts-data")
    yfs.format_or_open(d, "ts-1")
    with pytest.raises(yfs.FsMismatch):
        yfs.format_or_open(d, "ts-2")


def test_daemons_refuse_foreign_data_dir(tmp_path):
    from yugabyte_db_tpu.consensus.transport import LocalTransport
    from yugabyte_db_tpu.tserver.tablet_server import TabletServer

    root = str(tmp_path / "node")
    t = LocalTransport()
    ts = TabletServer("ts-a", root, t, ["m-0"], fsync=False)
    with pytest.raises(yfs.FsMismatch):
        TabletServer("ts-b", root, LocalTransport(), ["m-0"], fsync=False)
    assert ts.instance["server_uuid"] == "ts-a"


def test_fs_tool_blocks_and_instance(tmp_path):
    from yugabyte_db_tpu.storage.row_version import RowVersion
    from yugabyte_db_tpu.storage.run_io import save_run

    entries = []
    for i in range(10):
        key = b"\x01" + bytes([i]) + b"\x02k%d" % i
        entries.append((key, [RowVersion(key, ht=100 + i, liveness=True,
                                         columns={3: i * 7})]))
    run_path = str(tmp_path / "run-0000000000.dat")
    save_run(run_path, entries)
    out = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.tools.fs_tool",
         "blocks", run_path, "--rows-per-block", "4"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert "10 keys, 10 versions, 3 block(s)" in out.stdout
    assert "block 0:" in out.stdout and "keycrc=" in out.stdout

    yfs.format_or_open(str(tmp_path), "node-X")
    out = subprocess.run(
        [sys.executable, "-m", "yugabyte_db_tpu.tools.fs_tool",
         "instance", str(tmp_path)],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0 and '"server_uuid": "node-X"' in out.stdout


def test_docker_ctl_command_construction():
    cmds = dctl.create_commands(1, 3, "yugabyte-tpu:latest")
    assert cmds[0] == ["docker", "network", "create", "yb-tpu-net"]
    run_cmds = cmds[1:]
    assert len(run_cmds) == 4  # 1 master + 3 tservers
    master = run_cmds[0]
    assert "--role" in master and master[master.index("--role") + 1] == \
        "master"
    # every daemon shares the master topology string
    topo = master[master.index("--topology") + 1]
    assert topo == "yb-master-0=yb-master-0:7100"
    for c in run_cmds[1:]:
        assert c[c.index("--topology") + 1] == topo
        assert c[c.index("--role") + 1] == "tserver"
    # dry run prints, never invokes docker
    assert dctl._run(cmds, dry_run=True) == 0


def test_docker_ctl_cli_dry_run(capsys):
    rc = dctl.main(["create", "--masters", "1", "--tservers", "2",
                    "--dry-run"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("docker run") == 3
    assert "yb-tserver-1" in out
    rc = dctl.main(["destroy", "--dry-run"])
    assert rc == 0


def test_k8s_manifest_parses_and_binds_roles():
    """The shipped manifest must stay structurally valid (no yaml module
    dependency: structural checks on the text)."""
    text = open("/root/repo/deploy/kubernetes/"
                "yugabyte-tpu-statefulset.yaml").read()
    assert text.count("kind: StatefulSet") == 2
    assert text.count("kind: Service") == 2
    assert "--role=master" in text and "--role=tserver" in text
    assert "google.com/tpu" in text            # tserver pins the TPU
    assert "JAX_PLATFORMS" in text             # master stays on cpu
    assert "volumeClaimTemplates" in text
