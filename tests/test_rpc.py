"""RPC framework tests: framing, multiplexing, errors, deadlines, foreign
protocol contexts, and a raft group over real loopback sockets.

Reference test analog: src/yb/rpc/rpc-test.cc, rpc_stub-test.cc, and
raft_consensus-itest.cc running over real server sockets.
"""

import threading
import time

import pytest

from yugabyte_db_tpu.consensus import RaftOptions
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.rpc import (ConnectionContext, Messenger, Proxy,
                                 RpcCallError, SocketTransport)
from yugabyte_db_tpu.storage import RowVersion, ScanSpec
from yugabyte_db_tpu.tablet import TabletMetadata
from yugabyte_db_tpu.tablet.tablet_peer import TabletPeer


@pytest.fixture
def messenger():
    m = Messenger("test")
    yield m
    m.shutdown()


def echo_handler(method, body):
    if method == "echo":
        return body
    if method == "slow":
        time.sleep(body["sleep_s"])
        return "done"
    if method == "boom":
        raise ValueError("intentional failure")
    raise KeyError(method)


def test_echo_roundtrip(messenger):
    host, port = messenger.listen("127.0.0.1", 0, echo_handler)
    proxy = Proxy(host, port)
    assert proxy.call("echo", {"x": [1, 2.5, "s", b"b", None, True]}) == \
        {"x": [1, 2.5, "s", b"b", None, True]}
    proxy.close()


def test_concurrent_calls_multiplex(messenger):
    host, port = messenger.listen("127.0.0.1", 0, echo_handler)
    proxy = Proxy(host, port)
    results = {}
    errors = []

    def worker(i):
        try:
            results[i] = proxy.call("echo", {"i": i})
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(50)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert all(results[i] == {"i": i} for i in range(50))
    proxy.close()


def test_remote_error_propagates(messenger):
    host, port = messenger.listen("127.0.0.1", 0, echo_handler)
    proxy = Proxy(host, port)
    with pytest.raises(RpcCallError, match="intentional failure"):
        proxy.call("boom", None)
    # connection still usable after a handler error
    assert proxy.call("echo", 42) == 42
    proxy.close()


def test_call_deadline(messenger):
    host, port = messenger.listen("127.0.0.1", 0, echo_handler)
    proxy = Proxy(host, port)
    with pytest.raises(TimeoutError):
        proxy.call("slow", {"sleep_s": 2.0}, timeout=0.2)
    proxy.close()


def test_large_payload(messenger):
    host, port = messenger.listen("127.0.0.1", 0, echo_handler)
    proxy = Proxy(host, port)
    blob = b"\xab" * (4 * 1024 * 1024)
    assert proxy.call("echo", blob) == blob
    proxy.close()


def test_connect_refused():
    with pytest.raises(OSError):
        Proxy("127.0.0.1", 1, connect_timeout=0.5)


class LineContext(ConnectionContext):
    """A trivial newline-delimited text protocol, standing in for RESP/CQL
    to prove foreign protocols ride the same reactor."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data):
        self._buf.extend(data)
        calls = []
        while b"\n" in self._buf:
            line, _, rest = bytes(self._buf).partition(b"\n")
            self._buf = bytearray(rest)
            calls.append((None, "line", line.decode()))
        return calls

    def serialize(self, response):
        _, _, body = response
        return (body + "\n").encode()


def test_foreign_protocol_context(messenger):
    def upper(method, line):
        return line.upper()

    host, port = messenger.listen("127.0.0.1", 0, upper,
                                  context_factory=LineContext)
    import socket
    s = socket.create_connection((host, port))
    s.sendall(b"hello\nworld\n")
    got = b""
    while got.count(b"\n") < 2:
        got += s.recv(1024)
    assert got == b"HELLO\nWORLD\n"
    s.close()


# -- raft over sockets -------------------------------------------------------

def test_raft_group_over_sockets(tmp_path):
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
    ], table_id="t")
    cid = {c.name: c.col_id for c in schema.columns}
    opts = RaftOptions(election_timeout_s=0.25, heartbeat_interval_s=0.05,
                       lease_s=0.6, rpc_timeout_s=1.0)
    nodes = ["s-0", "s-1", "s-2"]
    transport = SocketTransport()
    messengers, peers = {}, {}
    try:
        for uuid in nodes:
            m = Messenger(uuid)
            meta = TabletMetadata("tablet-1", "t", schema, 0, 65536)
            peer = TabletPeer(uuid, meta, str(tmp_path / uuid), transport,
                              nodes, fsync=False, raft_opts=opts)
            host, port = m.listen(
                "127.0.0.1", 0,
                lambda method, body, _p=peer: _p.raft.handle(method, body))
            transport.set_address(uuid, host, port)
            messengers[uuid], peers[uuid] = m, peer
        for p in peers.values():
            p.start()

        deadline = time.monotonic() + 10
        leader = None
        while time.monotonic() < deadline and leader is None:
            leader = next((p for p in peers.values()
                           if p.raft.is_leader() and p.raft.has_lease()), None)
            time.sleep(0.02)
        assert leader is not None, "no leader over sockets"

        key = schema.encode_primary_key(
            {"k": "sock"}, compute_hash_code(schema, {"k": "sock"}))
        for i in range(10):
            leader.write([RowVersion(key, ht=0, liveness=True,
                                     columns={cid["v"]: i})])
        # all replicas converge
        deadline = time.monotonic() + 5
        target = leader.raft.stats()["applied_index"]
        while time.monotonic() < deadline:
            if all(p.raft.stats()["applied_index"] >= target
                   for p in peers.values()):
                break
            time.sleep(0.02)
        for p in peers.values():
            res = p.scan(ScanSpec(read_ht=p.tablet.clock.now().value),
                         allow_stale=True)
            assert res.rows == [("sock", 9)], (p.node_uuid, res.rows)
    finally:
        for p in peers.values():
            p.shutdown()
        transport.close()
        for m in messengers.values():
            m.shutdown()
