"""Runtime lock witness: @guarded_by instrumentation + the
``--witness-check`` cross-validation against yb-lint's static facts.

Tier 1 runs the witness over one deterministic fault-sweep round plus
direct breaker/residency exercise and feeds the dump to
``python -m yugabyte_db_tpu.analysis --witness-check`` (must exit 0:
runtime behaviour never contradicts a static "guarded" fact).  Full
randomized witness rounds stay under ``-m slow``.
"""

import tempfile
import threading

import pytest

from yugabyte_db_tpu.utils import locking
from yugabyte_db_tpu.utils.locking import guarded_by


@pytest.fixture(autouse=True)
def _witness_reset():
    locking.witness().clear()
    yield
    locking.disable_lock_witness()
    locking.witness().clear()


def _obs(cls_name, field):
    for row in locking.witness().observations():
        if row["class"] == cls_name and row["field"] == field:
            return row
    return None


@guarded_by("_lock", "_n", "_state")
class _Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._state = "closed"

    def bump_locked_path(self):
        with self._lock:
            self._n += 1

    def bump_racy_path(self):
        self._n += 1


# -- decorator semantics -----------------------------------------------------

def test_declaration_is_recorded_on_class():
    assert _Guarded.__guarded_by__ == {"_n": "_lock", "_state": "_lock"}
    assert _Guarded.__guard_locks__ == frozenset({"_lock"})


def test_declarations_stack():
    @guarded_by("_a", "_x")
    @guarded_by("_b", "_y")
    class Two:
        pass

    assert Two.__guarded_by__ == {"_x": "_a", "_y": "_b"}
    assert Two.__guard_locks__ == frozenset({"_a", "_b"})


def test_non_literal_declaration_rejected():
    with pytest.raises(TypeError):
        guarded_by("_lock")  # no fields
    with pytest.raises(TypeError):
        guarded_by(3, "_x")


def test_disabled_witness_records_nothing():
    g = _Guarded()
    g.bump_racy_path()
    assert locking.witness().observations() == []


# -- held/unheld observation -------------------------------------------------

def test_witness_sees_held_and_unheld_writes():
    locking.enable_lock_witness()
    g = _Guarded()  # constructed under the witness: lock gets wrapped
    g.bump_locked_path()
    g.bump_locked_path()
    g.bump_racy_path()
    row = _obs("_Guarded", "_n")
    assert row["held"] == 2 and row["unheld"] == 1
    assert row["lock"] == "_lock"
    assert any("test_lock_witness" in s for s in row["unheld_sites"])


def test_init_writes_are_not_observations():
    locking.enable_lock_witness()
    _Guarded()  # only construction writes
    assert _obs("_Guarded", "_n") is None


def test_rlock_ownership_probed_without_wrapping():
    """Instances that predate enable_lock_witness still witness
    correctly when the guard is an RLock (native _is_owned probe);
    plain-Lock instances are skipped, never misreported."""

    @guarded_by("_lock", "_v")
    class R:
        def __init__(self):
            self._lock = threading.RLock()
            self._v = 0

        def set_locked(self, v):
            with self._lock:
                self._v = v

        def set_racy(self, v):
            self._v = v

    r = R()  # BEFORE enable: no wrapper
    locking.enable_lock_witness()
    r.set_locked(1)
    r.set_racy(2)
    row = _obs("R", "_v")
    assert row["held"] == 1 and row["unheld"] == 1

    g = _Guarded()  # plain Lock, but constructed after enable: wrapped
    g.bump_locked_path()
    assert _obs("_Guarded", "_n")["held"] == 1


def test_plain_lock_created_before_enable_is_undecidable():
    g = _Guarded()
    locking.enable_lock_witness()
    g.bump_racy_path()
    # Ownership of an unwrapped plain Lock is undecidable for "this
    # thread"; the witness must skip, not fabricate a contradiction.
    assert _obs("_Guarded", "_n") is None


def test_cross_thread_writes_attributed_per_thread():
    locking.enable_lock_witness()
    g = _Guarded()
    threads = [threading.Thread(target=g.bump_locked_path)
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    row = _obs("_Guarded", "_n")
    assert row["held"] == 8 and row["unheld"] == 0


# -- dump / witness-check ----------------------------------------------------

def _witness_check(dump_path):
    from yugabyte_db_tpu.analysis.__main__ import main

    return main(["--witness-check", dump_path])


def test_witness_check_clean_dump_exits_zero(tmp_path, capsys):
    locking.enable_lock_witness()
    g = _Guarded()
    g.bump_locked_path()
    path = str(tmp_path / "wit.json")
    locking.dump_lock_witness(path)
    assert _witness_check(path) == 0
    assert "OK" in capsys.readouterr().out


def test_witness_check_contradiction_exits_two(tmp_path, capsys):
    """An unheld write to a field the TREE declares @guarded_by must
    fail the check.  CircuitBreaker._state is declared in
    storage/breaker.py, so a forged unheld observation contradicts."""
    locking.enable_lock_witness()
    from yugabyte_db_tpu.storage.breaker import CircuitBreaker

    b = CircuitBreaker("witness-test")
    b.record_failure(RuntimeError("x"))          # held writes
    b._state = "open"                            # deliberate unheld write
    path = str(tmp_path / "wit.json")
    locking.dump_lock_witness(path)
    assert _witness_check(path) == 2
    out = capsys.readouterr().out
    assert "CircuitBreaker._state" in out and "contradiction" in out


def test_witness_check_rejects_non_dump(tmp_path):
    p = tmp_path / "not_a_dump.json"
    p.write_text("{}")
    assert _witness_check(str(p)) == 1


# -- the tier-1 integration round --------------------------------------------

def test_sweep_and_core_classes_witness_clean(tmp_path):
    """One deterministic fault-sweep round plus direct breaker/residency
    exercise under the witness: every observed write to a declared field
    holds its declared lock (``--witness-check`` exits 0)."""
    from yugabyte_db_tpu.integration.fault_sweep import FaultSweep
    from yugabyte_db_tpu.storage.breaker import CircuitBreaker
    from yugabyte_db_tpu.storage.residency import HbmCache

    path = str(tmp_path / "sweep_witness.json")
    with tempfile.TemporaryDirectory() as root:
        summary = FaultSweep(root, seed=1234, ops_per_round=8,
                             schedule=("wal_sync", "hbm_eviction"),
                             witness_out=path).run()
    assert summary["rounds"] == 2

    # Direct breaker/residency exercise folded into the same dump.
    locking.enable_lock_witness()
    b = CircuitBreaker("wit", failure_threshold=1, cooldown_s=0.0)
    b.record_failure(RuntimeError("boom"))       # trips open
    assert b.allow()                             # half-open probe
    b.record_success()                           # closes
    cache = HbmCache()

    class Owner:
        pass

    o = Owner()
    key = cache.register(o, label="wit")
    cache.acquire(key, lambda: (object(), 128), priority="high")
    cache.invalidate(key)
    locking.dump_lock_witness(path)

    res = _witness_check(path)
    assert res == 0
    row = _obs("CircuitBreaker", "_state")
    assert row is not None and row["unheld"] == 0
    # The sweep's writes ran through the group-commit pipeline: its
    # bookkeeping watermark must only ever move under the raft lock.
    row = _obs("RaftConsensus", "_gc_handled_index")
    assert row is not None and row["unheld"] == 0


@pytest.mark.slow
def test_randomized_sweep_witness_clean(tmp_path):
    from yugabyte_db_tpu.integration.fault_sweep import run_sweep

    path = str(tmp_path / "rand_witness.json")
    with tempfile.TemporaryDirectory() as root:
        run_sweep(root, seed=1977, rounds=8, ops_per_round=24,
                  witness_out=path)
    assert _witness_check(path) == 0
