"""Unit tests for the unified retry/deadline policy (utils/retry.py).

These pin down the contract every RPC loop in the tree now leans on:
deadline debiting, jittered-exponential backoff shape, retriable
classification across the failure representations that actually occur
(Status, Code, wire-code string, response dict, exception), and the
attempts()/call() loop drivers.
"""

import random

import pytest

from yugabyte_db_tpu.utils.retry import (RETRIABLE_WIRE_CODES, Deadline,
                                         DeadlineExpired, RetryPolicy)
from yugabyte_db_tpu.utils.status import Code, Status, StatusError


def no_sleep_policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("rng", random.Random(7))
    return RetryPolicy(**kw)


# ------------------------------------------------------------- Deadline


def test_deadline_after_and_remaining():
    d = Deadline.after(10.0)
    assert 9.0 < d.remaining() <= 10.0
    assert not d.expired()
    d.check("op")  # no raise


def test_deadline_expired_raises_timed_out():
    d = Deadline.after(-1.0)
    assert d.expired()
    with pytest.raises(DeadlineExpired) as ei:
        d.check("scan")
    assert ei.value.status.code == Code.TIMED_OUT
    assert "scan" in str(ei.value)


def test_deadline_timeout_caps_at_remaining():
    d = Deadline.after(0.5)
    assert d.timeout(2.0) <= 0.5
    assert d.timeout(0.1) == pytest.approx(0.1, abs=0.01)
    expired = Deadline.after(-5.0)
    assert expired.timeout(2.0) == 0.0  # floored, never negative


def test_infinite_deadline_never_expires():
    d = Deadline.infinite()
    assert not d.expired()
    assert d.timeout(3.0) == 3.0
    assert d.remaining() == float("inf")


# ------------------------------------------------------- classification


def test_retriable_accepts_every_failure_shape():
    p = no_sleep_policy(max_attempts=3)
    assert p.retriable(Code.TIMED_OUT)
    assert p.retriable(Status(Code.SERVICE_UNAVAILABLE, "x"))
    assert p.retriable("timed_out")
    assert p.retriable({"code": "not_leader"})
    assert p.retriable(StatusError(Status(Code.NETWORK_ERROR, "x")))
    assert p.retriable(TimeoutError("slow"))
    assert p.retriable(ConnectionError("refused"))


def test_terminal_failures_are_not_retriable():
    p = no_sleep_policy(max_attempts=3)
    assert not p.retriable(None)
    assert not p.retriable(Code.INVALID_ARGUMENT)
    assert not p.retriable(Code.EXPIRED)  # the budget itself — never retried
    assert not p.retriable("conflict")
    assert not p.retriable({"code": "ok"})
    assert not p.retriable(ValueError("bug"))


def test_wire_codes_mirror_the_rpc_payload_convention():
    assert "timed_out" in RETRIABLE_WIRE_CODES
    assert "not_leader" in RETRIABLE_WIRE_CODES
    assert "conflict" not in RETRIABLE_WIRE_CODES


# ------------------------------------------------------------- backoff


def test_backoff_grows_exponentially_within_jitter_bounds():
    p = no_sleep_policy(max_attempts=10, initial_backoff_s=0.1,
                        max_backoff_s=10.0, multiplier=2.0, jitter=0.25)
    for n, base in [(1, 0.1), (2, 0.2), (3, 0.4), (4, 0.8)]:
        for _ in range(20):
            s = p.backoff_s(n)
            assert base * 0.75 <= s <= base * 1.25


def test_backoff_is_capped_at_max():
    p = no_sleep_policy(max_attempts=10, initial_backoff_s=0.1,
                        max_backoff_s=0.5, multiplier=2.0, jitter=0.0)
    assert p.backoff_s(10) == pytest.approx(0.5)


def test_unbounded_policy_is_rejected_at_construction():
    with pytest.raises(ValueError):
        RetryPolicy()


# ------------------------------------------------------------ attempts


def test_attempts_stop_at_max_attempts():
    p = no_sleep_policy(max_attempts=4)
    numbers = [a.number for a in p.attempts()]
    assert numbers == [1, 2, 3, 4]


def test_attempts_sleep_between_iterations_but_not_after_last():
    slept = []
    p = RetryPolicy(max_attempts=3, sleep=slept.append,
                    rng=random.Random(7))
    list(p.attempts())
    assert len(slept) == 2  # n attempts -> n-1 backoffs


def test_attempts_stop_when_deadline_expires():
    p = no_sleep_policy(max_attempts=100)
    d = Deadline.after(-1.0)
    # First attempt is always yielded (the caller gets one shot), then
    # the exhausted deadline stops the loop.
    assert [a.number for a in p.attempts(deadline=d)] == [1]


def test_attempts_never_sleep_past_the_deadline():
    slept = []
    p = RetryPolicy(max_attempts=50, initial_backoff_s=5.0,
                    sleep=slept.append, rng=random.Random(7))
    d = Deadline.after(0.2)
    list(p.attempts(deadline=d))
    assert all(s <= 0.2 for s in slept)


def test_attempt_note_carries_the_last_failure():
    p = no_sleep_policy(max_attempts=2)
    seen = None
    for attempt in p.attempts():
        attempt.note({"code": "timed_out"})
        seen = attempt.last
    assert seen == {"code": "timed_out"}


def test_attempts_timeout_s_overrides_policy_budget():
    p = no_sleep_policy(timeout_s=100.0, initial_backoff_s=0.001)
    count = 0
    for attempt in p.attempts(timeout_s=-1.0):
        count += 1
    assert count == 1  # the explicit (already expired) budget wins


# ---------------------------------------------------------------- call


def test_call_returns_first_success():
    p = no_sleep_policy(max_attempts=5)
    calls = []

    def fn(attempt):
        calls.append(attempt.number)
        if attempt.number < 3:
            raise TimeoutError("not yet")
        return "ok"

    assert p.call(fn) == "ok"
    assert calls == [1, 2, 3]


def test_call_propagates_terminal_errors_immediately():
    p = no_sleep_policy(max_attempts=5)
    calls = []

    def fn(attempt):
        calls.append(attempt.number)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        p.call(fn)
    assert calls == [1]


def test_call_reraises_last_retriable_failure_on_exhaustion():
    p = no_sleep_policy(max_attempts=2)

    def fn(attempt):
        raise ConnectionError(f"attempt {attempt.number}")

    with pytest.raises(ConnectionError, match="attempt 2"):
        p.call(fn)


def test_call_raises_deadline_expired_when_nothing_ran():
    p = no_sleep_policy(max_attempts=5)
    d = Deadline.after(-1.0)

    # One attempt is always yielded; make it fail retriably so the loop
    # consults the (expired) deadline and gives up.
    def fn(attempt):
        raise TimeoutError("x")

    with pytest.raises(TimeoutError):
        p.call(fn, deadline=d)
