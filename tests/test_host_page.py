"""Host page-cache path (storage.host_page) vs the CPU oracle.

Flat single-run LIMIT scans route through HostPage (no device round
trip); these tests pin that route's results to the oracle across MVCC
read points, tombstones, TTL expiry, NULLs, predicates, projections and
paging — and that non-eligible shapes still fall back to the device /
host paths with identical results.
"""

import random

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (
    Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("s", DataType.STRING),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
        ColumnSchema("bl", DataType.BOOL),
    ], table_id="hp")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def load_flat(schema, engines, n=400, seed=3, prefix="u"):
    """Each key written exactly once -> flat run after one flush."""
    rnd = random.Random(seed)
    cids = {c.name: c.col_id for c in schema.value_columns}
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 4)
        key = enc(schema, f"{prefix}{i:05d}", i % 11)
        roll = rnd.random()
        if roll < 0.06:
            rv = RowVersion(key, ht=ht, tombstone=True)
        else:
            rv = RowVersion(
                key, ht=ht, liveness=True,
                columns={cids["a"]: rnd.randrange(-10**10, 10**10),
                         cids["s"]: rnd.choice(["ab", "xyz", None, "qq"]),
                         cids["c"]: rnd.uniform(-100, 100),
                         cids["d"]: rnd.randrange(-500, 500),
                         cids["bl"]: rnd.choice([True, False, None])},
                expire_ht=(ht + rnd.randrange(5, 300)
                           if rnd.random() < 0.12 else MAX_HT))
        for e in engines:
            e.apply([rv])
    for e in engines:
        e.flush()
    return ht


def assert_same(cpu, tpu, **kw):
    a = cpu.scan(ScanSpec(**kw))
    b = tpu.scan(ScanSpec(**kw))
    assert a.columns == b.columns
    assert a.rows == b.rows, kw
    assert (a.resume_key is None) == (b.resume_key is None)
    return a, b


def setup(n=400, seed=3):
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    ht = load_flat(schema, [cpu, tpu], n=n, seed=seed)
    return schema, cpu, tpu, ht


def page_plan_taken(tpu, spec):
    return tpu._plan_scan(spec)[0] == "page"


def test_page_route_selected_and_identical():
    schema, cpu, tpu, ht = setup()
    spec = ScanSpec(read_ht=ht + 1, limit=50,
                    projection=["k", "r", "a", "d"])
    assert page_plan_taken(tpu, spec)
    assert_same(cpu, tpu, read_ht=ht + 1, limit=50,
                projection=["k", "r", "a", "d"])


def test_page_all_types_projection():
    schema, cpu, tpu, ht = setup()
    assert_same(cpu, tpu, read_ht=ht + 1, limit=40,
                projection=["k", "r", "a", "s", "c", "d", "bl"])


def test_page_read_points_time_travel():
    schema, cpu, tpu, ht = setup()
    for rp in (1, ht // 3, ht // 2, ht, MAX_HT):
        assert_same(cpu, tpu, read_ht=rp, limit=30)


def test_page_predicates():
    schema, cpu, tpu, ht = setup()
    cases = [
        [Predicate("d", ">=", 0)],
        [Predicate("d", "<", -100), Predicate("a", ">", 0)],
        [Predicate("a", "<=", 10**9)],
        [Predicate("c", ">=", 0.0)],
        [Predicate("a", "!=", 5)],
        [Predicate("d", "=", 7)],
    ]
    for preds in cases:
        spec = ScanSpec(read_ht=ht + 1, limit=25, predicates=preds,
                        projection=["k", "a", "d"])
        assert page_plan_taken(tpu, spec), preds
        assert_same(cpu, tpu, read_ht=ht + 1, limit=25, predicates=preds,
                    projection=["k", "a", "d"])


def test_page_string_pred_not_page_routed():
    """str predicates are superset-only: must NOT take the page route,
    results still identical via the device+verify path."""
    schema, cpu, tpu, ht = setup()
    spec = ScanSpec(read_ht=ht + 1, limit=25,
                    predicates=[Predicate("s", "=", "ab")])
    assert not page_plan_taken(tpu, spec)
    assert_same(cpu, tpu, read_ht=ht + 1, limit=25,
                predicates=[Predicate("s", "=", "ab")])


def test_page_paging_loop_covers_everything():
    schema, cpu, tpu, ht = setup()
    spec_a = ScanSpec(read_ht=ht + 1, limit=17)
    spec_b = ScanSpec(read_ht=ht + 1, limit=17)
    pages = 0
    total = 0
    while True:
        ra, rb = cpu.scan(spec_a), tpu.scan(spec_b)
        assert ra.rows == rb.rows
        assert (ra.resume_key is None) == (rb.resume_key is None)
        total += len(rb.rows)
        pages += 1
        if ra.resume_key is None:
            break
        spec_a = ScanSpec(lower=ra.resume_key, read_ht=ht + 1, limit=17)
        spec_b = ScanSpec(lower=rb.resume_key, read_ht=ht + 1, limit=17)
    assert pages > 5
    full = cpu.scan(ScanSpec(read_ht=ht + 1))
    assert total == len(full.rows)


def test_page_range_bounds():
    schema, cpu, tpu, ht = setup()
    keys = sorted(enc(schema, f"u{i:05d}", i % 11) for i in range(0, 400, 7))
    lo, hi = keys[10], keys[40]
    assert_same(cpu, tpu, lower=lo, upper=hi, read_ht=ht + 1, limit=20)
    assert_same(cpu, tpu, lower=hi, upper=lo, read_ht=ht + 1, limit=20)
    assert_same(cpu, tpu, lower=keys[-1], upper=b"", read_ht=ht + 1, limit=20)


def test_page_batch_mixed_with_device_work():
    """scan_batch mixing page scans + aggregates + multi-run fallbacks."""
    from yugabyte_db_tpu.storage import AggSpec

    schema, cpu, tpu, ht = setup()
    specs = [
        ScanSpec(read_ht=ht + 1, limit=10, projection=["k", "a"]),
        ScanSpec(read_ht=ht + 1,
                 aggregates=[AggSpec("count", None), AggSpec("sum", "a")]),
        ScanSpec(read_ht=ht + 1, limit=5, predicates=[Predicate("d", ">", 0)],
                 projection=["k", "d"]),
    ]
    ra = cpu.scan_batch(list(specs))
    rb = tpu.scan_batch(list(specs))
    for a, b in zip(ra, rb):
        assert a.rows == b.rows


def test_page_not_taken_multi_run_or_memtable():
    schema, cpu, tpu, ht = setup()
    spec = ScanSpec(read_ht=MAX_HT, limit=10)
    assert page_plan_taken(tpu, spec)
    # Live memtable overlay: no longer single-source.
    cids = {c.name: c.col_id for c in schema.value_columns}
    rv = RowVersion(enc(schema, "u00000", 0), ht=ht + 5, liveness=True,
                    columns={cids["a"]: 1})
    cpu.apply([rv])
    tpu.apply([rv])
    assert not page_plan_taken(tpu, spec)
    assert_same(cpu, tpu, read_ht=MAX_HT, limit=10)


def test_page_not_taken_multiversion_run():
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    cids = {c.name: c.col_id for c in schema.value_columns}
    key = enc(schema, "mv", 0)
    for e in (cpu, tpu):
        e.apply([RowVersion(key, ht=10, liveness=True,
                            columns={cids["a"]: 1}),
                 RowVersion(key, ht=20, columns={cids["a"]: 2})])
        e.flush()
    spec = ScanSpec(read_ht=MAX_HT, limit=10)
    assert not page_plan_taken(tpu, spec)
    assert_same(cpu, tpu, read_ht=MAX_HT, limit=10)
    assert_same(cpu, tpu, read_ht=15, limit=10)


def test_page_after_compaction_flat_again():
    """Two flat runs (disjoint keys) merge into one flat run under
    compaction: the page route re-engages and stays correct."""
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    ht = load_flat(schema, [cpu, tpu], n=150, seed=5, prefix="u")
    ht2 = load_flat(schema, [cpu, tpu], n=150, seed=6, prefix="w")
    cpu.compact(history_cutoff_ht=max(ht, ht2))
    tpu.compact(history_cutoff_ht=max(ht, ht2))
    spec = ScanSpec(read_ht=MAX_HT, limit=20)
    assert page_plan_taken(tpu, spec)
    assert_same(cpu, tpu, read_ht=MAX_HT, limit=20,
                projection=["k", "a", "s", "d"])
