"""Engine-diff regression tests for review-caught edge cases:
float32 predicate rounding, string/key-column aggregates, same-ht
tombstone shadowing."""

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import AggSpec, Predicate, RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def schema_f():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("f", DataType.FLOAT),
        ColumnSchema("s", DataType.STRING),
    ])


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def pair():
    s = schema_f()
    return s, make_engine("cpu", s), make_engine("tpu", s, {"rows_per_block": 64})


def same(cpu, tpu, **kw):
    a, b = cpu.scan(ScanSpec(**kw)), tpu.scan(ScanSpec(**kw))
    assert a.rows == b.rows, (a.rows, b.rows)
    return a


def test_float32_predicate_rounding_ties():
    s, cpu, tpu = pair()
    ids = {c.name: c.col_id for c in s.value_columns}
    vals = [0.3 + 1e-9, 0.3, 0.3 - 1e-9, 0.2999, 1.5]
    for i, v in enumerate(vals):
        rv = RowVersion(enc(s, "p", i), ht=10 + i, liveness=True,
                        columns={ids["f"]: v})
        cpu.apply([rv]); tpu.apply([rv])
    cpu.flush(); tpu.flush()
    for op in ("=", "!=", "<", "<=", ">", ">="):
        same(cpu, tpu, read_ht=MAX_HT, predicates=[Predicate("f", op, 0.3)])


def test_string_minmax_falls_back_to_host():
    s, cpu, tpu = pair()
    ids = {c.name: c.col_id for c in s.value_columns}
    for i, v in enumerate(["banana", "apple", "cherry", "commonprefix-zz",
                           "commonprefix-aa"]):
        rv = RowVersion(enc(s, "p", i), ht=10 + i, liveness=True,
                        columns={ids["s"]: v})
        cpu.apply([rv]); tpu.apply([rv])
    cpu.flush(); tpu.flush()
    r = same(cpu, tpu, read_ht=MAX_HT,
             aggregates=[AggSpec("min", "s"), AggSpec("max", "s")])
    assert r.rows == [("apple", "commonprefix-zz")]


def test_key_column_aggregates():
    s, cpu, tpu = pair()
    ids = {c.name: c.col_id for c in s.value_columns}
    for i in range(7):
        rv = RowVersion(enc(s, "p", i), ht=10 + i, liveness=True,
                        columns={ids["f"]: float(i)})
        cpu.apply([rv]); tpu.apply([rv])
    cpu.flush(); tpu.flush()
    r = same(cpu, tpu, read_ht=MAX_HT,
             aggregates=[AggSpec("min", "r"), AggSpec("max", "r"),
                         AggSpec("count", "r"), AggSpec("sum", "r")])
    assert r.rows == [(0, 6, 7, 21)]


def test_same_ht_tombstone_shadows_value():
    """DELETE + re-write in one batch share a hybrid time: the tombstone
    shadows the value (merge.py <= semantics) on BOTH paths, including the
    device aggregate path which has no host verification."""
    s, cpu, tpu = pair()
    ids = {c.name: c.col_id for c in s.value_columns}
    key = enc(s, "p", 1)
    batch = [RowVersion(key, ht=50, tombstone=True),
             RowVersion(key, ht=50, columns={ids["f"]: 7.0})]
    cpu.apply(batch); tpu.apply(batch)
    cpu.flush(); tpu.flush()
    r = same(cpu, tpu, read_ht=MAX_HT,
             aggregates=[AggSpec("count", None), AggSpec("sum", "f")])
    assert r.rows == [(0, None)]
    same(cpu, tpu, read_ht=MAX_HT)
