"""Stock-driver-shaped interop: the vendored thin drivers
(yugabyte_db_tpu.drivers) run full driver sessions against the real
socket servers — the flows the reference proves with the Java CQL
driver (java/yb-cql), libpq (src/yb/yql/pgwrapper/pg_libpq-test.cc),
and Jedis (java/yb-jedis-tests).

The drivers implement each protocol's client side independently of the
server wire modules (own framing + value codecs), so these tests check
the server's bytes the way a foreign driver would: the CQL control
connection performs the DataStax-style schema discovery against
system.local / system.peers / system_schema.*; the PG session runs the
PQexecParams extended flow; the Redis session pipelines and subscribes.
"""

import threading

import pytest

from yugabyte_db_tpu.drivers import (CqlConnection, CqlError,
                                     PgConnection, PgError,
                                     RedisConnection, RedisError)
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.processor import LocalCluster
from yugabyte_db_tpu.yql.cql.server import CQLServer
from yugabyte_db_tpu.yql.pgsql.wire import PgServer
from yugabyte_db_tpu.yql.redis import RedisServer


# -- CQL ---------------------------------------------------------------------

@pytest.fixture
def cql(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = CQLServer(ClientCluster(c.client()))
    host, port = server.listen("127.0.0.1", 0)
    conn = CqlConnection(host, port)
    yield conn
    conn.close()
    server.shutdown()
    c.shutdown()


def test_cql_handshake_reports_supported(cql):
    assert "CQL_VERSION" in cql.supported


def test_cql_control_connection_discovery(cql):
    cql.execute("CREATE KEYSPACE app")
    cql.execute("CREATE TABLE app.users (id bigint PRIMARY KEY, "
                "name text, score double)")
    topo = cql.discover()
    assert topo["local"].get("cql_version") or topo["local"], topo
    assert "app" in topo["schema"]
    assert "users" in topo["schema"]["app"]["tables"]
    assert set(topo["schema"]["app"]["tables"]["users"]) == {
        "id", "name", "score"}


def test_cql_dml_roundtrip_typed(cql):
    cql.execute("CREATE KEYSPACE ks")
    cql.execute("USE ks")
    cql.execute("CREATE TABLE t (k bigint PRIMARY KEY, v text, "
                "d double, b boolean)")
    cql.execute("INSERT INTO t (k, v, d, b) VALUES (1, 'one', 1.5, true)")
    cql.execute("INSERT INTO t (k, v, d, b) VALUES (2, 'two', -2.5, "
                "false)")
    res = cql.execute("SELECT k, v, d, b FROM t WHERE k = 1")
    assert res.columns == ["k", "v", "d", "b"]
    assert res.rows == [(1, "one", 1.5, True)]


def test_cql_prepared_statements(cql):
    cql.execute("CREATE KEYSPACE pks")
    cql.execute("USE pks")
    cql.execute("CREATE TABLE t (k bigint PRIMARY KEY, v text)")
    ins = cql.prepare("INSERT INTO t (k, v) VALUES (?, ?)")
    for i in range(10):
        cql.execute_prepared(ins, [i, f"row{i}"])
    sel = cql.prepare("SELECT v FROM t WHERE k = ?")
    res = cql.execute_prepared(sel, [7])
    assert res.rows == [("row7",)]


def test_cql_prepared_binds_use_column_wire_types(cql):
    """Bind serialization must follow the PREPARED metadata: an `int`
    column takes 4 bytes on the wire and `float` a 4-byte IEEE single —
    not the 8-byte guess made from the Python value's type."""
    cql.execute("CREATE KEYSPACE wks")
    cql.execute("USE wks")
    cql.execute("CREATE TABLE t (k int PRIMARY KEY, s smallint, "
                "y tinyint, f float, d double)")
    ins = cql.prepare("INSERT INTO t (k, s, y, f, d) "
                      "VALUES (?, ?, ?, ?, ?)")
    from yugabyte_db_tpu.drivers.minicql import (T_DOUBLE, T_FLOAT,
                                                 T_INT, T_SMALLINT,
                                                 T_TINYINT)
    assert [s[0] for s in ins.bind_specs] == [
        T_INT, T_SMALLINT, T_TINYINT, T_FLOAT, T_DOUBLE]
    cql.execute_prepared(ins, [7, -300, 5, 1.5, -2.25])
    # Int binds into a float column are coerced by the typed encoder.
    cql.execute_prepared(ins, [-40000, 12, -3, 2, 3])
    sel = cql.prepare("SELECT k, s, y, f, d FROM t WHERE k = ?")
    assert cql.execute_prepared(sel, [7]).rows == [(7, -300, 5, 1.5,
                                                   -2.25)]
    assert cql.execute_prepared(sel, [-40000]).rows == [
        (-40000, 12, -3, 2.0, 3.0)]


def test_cql_paging_loop(cql):
    cql.execute("CREATE KEYSPACE pg2")
    cql.execute("USE pg2")
    cql.execute("CREATE TABLE t (k bigint PRIMARY KEY, v bigint)")
    for i in range(57):
        cql.execute(f"INSERT INTO t (k, v) VALUES ({i}, {i * 10})")
    first = cql.execute("SELECT k, v FROM t", page_size=10)
    assert len(first.rows) == 10 and first.has_more_pages
    res = cql.fetch_all("SELECT k, v FROM t", page_size=10)
    assert len(res.rows) == 57
    assert {k for k, _v in res.rows} == set(range(57))


def test_cql_pipelined_prepared_with_errors(cql):
    """Stream-multiplexed pipelining: errors come back in-place and the
    connection stays usable (no desync from stale frames)."""
    cql.execute("CREATE KEYSPACE plk")
    cql.execute("USE plk")
    cql.execute("CREATE TABLE t (k bigint PRIMARY KEY, v bigint)")
    ins = cql.prepare("INSERT INTO t (k, v) VALUES (?, ?)")
    vals = [[i, i * 2] for i in range(40)]
    vals[7] = [7, "not-an-int"]    # per-request failure mid-window
    vals[23] = [23, "bad"]
    out = cql.execute_prepared_many(ins, vals, window=16)
    assert sum(isinstance(r, CqlError) for r in out) == 2
    assert isinstance(out[7], CqlError) and isinstance(out[23], CqlError)
    # connection still healthy: later pipelined + sync calls work
    sel = cql.prepare("SELECT v FROM t WHERE k = ?")
    res = cql.execute_prepared_many(sel, [[i] for i in (1, 7, 39)])
    assert [r.rows for r in res] == [[(2,)], [], [(78,)]]
    assert cql.execute("SELECT count(*) FROM t").rows == [(38,)]


def test_cql_error_frame(cql):
    with pytest.raises(CqlError) as ei:
        cql.execute("SELECT * FROM nosuch.table")
    assert ei.value.code != 0 or ei.value.message


# -- PostgreSQL --------------------------------------------------------------

@pytest.fixture
def pg():
    server = PgServer(LocalCluster(num_tablets=2))
    host, port = server.listen("127.0.0.1", 0)
    conn = PgConnection(host, port, user="app")
    yield conn
    conn.close()
    server.shutdown()


def test_pg_simple_query_flow(pg):
    pg.execute("CREATE TABLE t (k INT PRIMARY KEY, v TEXT, d FLOAT8)")
    pg.execute("INSERT INTO t (k, v, d) VALUES (1, 'one', 1.5)")
    pg.execute("INSERT INTO t (k, v, d) VALUES (2, 'two', 2.5)")
    res = pg.execute("SELECT k, v, d FROM t ORDER BY k")
    assert res.columns == ["k", "v", "d"]
    assert res.rows == [(1, "one", 1.5), (2, "two", 2.5)]
    assert res.command_tag.startswith("SELECT")
    assert pg.txn_status == b"I"


def test_pg_execparams_extended_flow(pg):
    pg.execute("CREATE TABLE t (k INT PRIMARY KEY, v TEXT)")
    pg.execute_params("INSERT INTO t (k, v) VALUES ($1, $2)",
                      [1, "hello"])
    pg.execute_params("INSERT INTO t (k, v) VALUES ($1, $2)",
                      [2, "world"])
    res = pg.execute_params("SELECT v FROM t WHERE k = $1", [2])
    assert res.rows == [("world",)]


def test_pg_named_prepared(pg):
    pg.execute("CREATE TABLE t (k INT PRIMARY KEY, v TEXT)")
    pg.prepare("ins", "INSERT INTO t (k, v) VALUES ($1, $2)")
    for i in range(5):
        pg.execute_prepared("ins", [i, f"r{i}"])
    res = pg.execute("SELECT count(*) FROM t")
    assert res.rows == [(5,)]


def test_pg_window_over_wire(pg):
    pg.execute("CREATE TABLE s (id BIGINT PRIMARY KEY, g TEXT, "
               "x BIGINT)")
    for i, (g, x) in enumerate([("a", 10), ("a", 30), ("b", 20)], 1):
        pg.execute(f"INSERT INTO s (id, g, x) VALUES ({i}, '{g}', {x})")
    res = pg.execute("SELECT id, sum(x) OVER (PARTITION BY g ORDER BY "
                     "id) AS run FROM s ORDER BY id")
    assert res.rows == [(1, 10), (2, 40), (3, 20)]


def test_pg_error_and_recovery(pg):
    with pytest.raises(PgError) as ei:
        pg.execute("SELECT * FROM missing_table")
    assert ei.value.message
    res = pg.execute("SELECT 1")
    assert res.rows == [(1,)]


def test_pg_transaction_status(tmp_path):
    # Transactions need the distributed txn subsystem: serve the PG
    # frontend off a MiniCluster-backed ClientCluster.
    c = MiniCluster(str(tmp_path), num_masters=1,
                    num_tservers=3).start()
    c.wait_tservers_registered()
    server = PgServer(ClientCluster(c.client()))
    host, port = server.listen("127.0.0.1", 0)
    pg = PgConnection(host, port, user="app")
    try:
        pg.execute("CREATE TABLE t (k INT PRIMARY KEY)")
        pg.execute("BEGIN")
        assert pg.txn_status == b"T"
        pg.execute("INSERT INTO t (k) VALUES (1)")
        pg.execute("COMMIT")
        assert pg.txn_status == b"I"
        assert pg.execute("SELECT count(*) FROM t").rows == [(1,)]
    finally:
        pg.close()
        server.shutdown()
        c.shutdown()


# -- Redis -------------------------------------------------------------------

@pytest.fixture
def redis_rig(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = RedisServer(c.client("redis-proxy"))
    host, port = server.listen("127.0.0.1", 0)
    yield host, port
    server.shutdown()
    c.shutdown()


def test_redis_commands_and_types(redis_rig):
    r = RedisConnection(*redis_rig)
    assert r.command("PING") == "PONG"
    assert r.command("SET", "k", "v1") == "OK"
    assert r.command("GET", "k") == b"v1"
    assert r.command("GET", "missing") is None
    assert r.command("HSET", "h", "f1", "a", "f2", "b") in (2, "OK")
    got = r.command("HGETALL", "h")
    assert dict(zip(got[::2], got[1::2])) == {b"f1": b"a", b"f2": b"b"}
    with pytest.raises(RedisError):
        r.command("INCR", "k")  # not an integer
    r.close()


def test_redis_pipeline(redis_rig):
    r = RedisConnection(*redis_rig)
    replies = r.pipeline([("SET", f"p{i}", i) for i in range(20)]
                         + [("GET", f"p{i}") for i in range(20)])
    assert replies[:20] == ["OK"] * 20
    assert [int(b) for b in replies[20:]] == list(range(20))
    r.close()


def test_redis_pubsub(redis_rig):
    sub = RedisConnection(*redis_rig)
    acks = sub.subscribe("chan")
    assert acks and acks[0][0] == b"subscribe"
    got = []

    def listen():
        got.append(sub.get_message(timeout=10))

    t = threading.Thread(target=listen)
    t.start()
    pub = RedisConnection(*redis_rig)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        n = pub.command("PUBLISH", "chan", "hello")
        if n >= 1:
            break
        time.sleep(0.05)
    t.join(timeout=10)
    assert got and got[0][0] == b"message" and got[0][2] == b"hello"
    pub.close()
    sub.close()
