"""Write-path pipeline tests: cross-request group commit, ack-at-commit
with pipelined apply, and the append->apply backpressure window.

Reference analog: the leader-side Batcher/group-commit behaviour in
src/yb/consensus/consensus_queue-test.cc — concurrent appends share one
replication round + one WAL sync, and acknowledgment tracks the COMMIT
watermark, not the apply watermark.
"""

import threading
import time

import pytest

from yugabyte_db_tpu.consensus import LocalTransport, RaftOptions
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec
from yugabyte_db_tpu.tablet import TabletMetadata, TabletPeer
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import (BATCH_SIZE_BUCKETS,
                                           _write_path_entity, faults_fired)

FAST = RaftOptions(election_timeout_s=0.15, heartbeat_interval_s=0.03,
                   lease_s=0.4, rpc_timeout_s=0.5)


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
    ], table_id="t")


def enc(schema, k):
    return schema.encode_primary_key({"k": k},
                                     compute_hash_code(schema, {"k": k}))


def wait_for(pred, timeout=5.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class Group:
    """A 3-replica raft group over a LocalTransport (test_raft idiom)."""

    def __init__(self, tmp_path, n=3):
        self.schema = make_schema()
        self.transport = LocalTransport()
        self.tmp_path = tmp_path
        self.nodes = [f"node-{i}" for i in range(n)]
        self.peers = {}
        for uuid in self.nodes:
            meta = TabletMetadata("tablet-1", "t", self.schema, 0, 65536)
            peer = TabletPeer(uuid, meta, str(tmp_path / uuid),
                              self.transport.bind(uuid), self.nodes,
                              fsync=False, raft_opts=FAST)
            self.transport.register(
                uuid, lambda m, p, _pr=peer: _pr.raft.handle(m, p))
            self.peers[uuid] = peer
            peer.start()

    def leader(self):
        return wait_for(
            lambda: next((p for p in self.peers.values()
                          if p.raft.is_leader() and p.raft.has_lease()),
                         None),
            msg="leader election")

    def shutdown(self):
        for p in self.peers.values():
            p.shutdown()

    def row(self, k, v):
        cid = {c.name: c.col_id for c in self.schema.columns}
        return RowVersion(enc(self.schema, k), ht=0, liveness=True,
                          columns={cid["v"]: v})

    def read_all(self, peer):
        res = peer.scan(ScanSpec(read_ht=peer.tablet.clock.now().value),
                        allow_stale=True)
        return sorted(res.rows)


@pytest.fixture
def group(tmp_path):
    g = Group(tmp_path)
    yield g
    g.shutdown()


@pytest.fixture
def apply_stall():
    """Arm/disarm the --fault.raft_apply_stall apply-stage stall."""
    yield lambda on: FLAGS.set("fault.raft_apply_stall",
                               1.0 if on else 0.0, force=True)
    FLAGS.set("fault.raft_apply_stall", 0.0, force=True)


@pytest.fixture
def inflight_flag():
    old = FLAGS.get("raft_max_inflight_ops")
    yield lambda v: FLAGS.set("raft_max_inflight_ops", int(v))
    FLAGS.set("raft_max_inflight_ops", old)


@pytest.fixture
def window_flag():
    old = FLAGS.get("raft_group_commit_window_us")
    yield lambda v: FLAGS.set("raft_group_commit_window_us", int(v))
    FLAGS.set("raft_group_commit_window_us", old)


def test_ack_at_commit_precedes_apply(group, apply_stall):
    """A write acks once COMMITTED; the apply stage may lag behind it
    (pipelined apply) and drains without further traffic once the stall
    clears."""
    leader = group.leader()
    leader.write([group.row("warm", 0)])
    base = faults_fired("fault.raft_apply_stall")
    apply_stall(True)
    try:
        leader.write([group.row("a", 1)], timeout=5.0)  # returns at commit
        s = leader.raft.stats()
        assert s["commit_index"] > s["applied_index"]
        assert faults_fired("fault.raft_apply_stall") > base
    finally:
        apply_stall(False)
    wait_for(lambda: leader.raft.stats()["commit_index"]
             == leader.raft.stats()["applied_index"],
             msg="apply drain after stall clears")
    assert len(group.read_all(leader)) == 2


def test_backpressure_bounds_apply_window(group, apply_stall,
                                          inflight_flag):
    """With apply stalled, admission blocks once last_index -
    applied_index reaches --raft_max_inflight_ops, and recovers when
    the queue drains."""
    leader = group.leader()
    leader.write([group.row("warm", 0)])
    wait_for(lambda: leader.raft.stats()["commit_index"]
             == leader.raft.stats()["applied_index"], msg="warm apply")
    inflight_flag(4)
    apply_stall(True)
    try:
        for i in range(4):
            leader.write([group.row(f"fill{i}", i)], timeout=5.0)
        with pytest.raises(TimeoutError, match="backpressure"):
            leader.write([group.row("overflow", 9)], timeout=0.5)
    finally:
        apply_stall(False)
    wait_for(lambda: leader.raft.stats()["commit_index"]
             == leader.raft.stats()["applied_index"], msg="drain")
    leader.write([group.row("after", 10)], timeout=5.0)
    assert len(group.read_all(leader)) == 6  # overflow write never landed


def test_concurrent_writes_share_commit_rounds(group, window_flag):
    """Concurrent writers inside one group-commit window coalesce into
    shared WAL-sync + AppendEntries rounds: the batch-size histogram
    must record rounds with more than one entry."""
    window_flag(5000)
    leader = group.leader()
    h = _write_path_entity().histogram("yb_group_commit_batch_size",
                                       buckets=BATCH_SIZE_BUCKETS)
    before = list(h.counts)

    errors = []

    def writer(t):
        try:
            for i in range(10):
                leader.write([group.row(f"k{t}-{i}", i)], timeout=10.0)
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    delta = [a - b for a, b in zip(h.counts, before)]
    # Bucket 0 holds batch==1 rounds; anything beyond it coalesced.
    assert sum(delta[1:]) > 0, f"no multi-entry commit round: {delta}"
    assert len(group.read_all(leader)) == 80
    for p in group.peers.values():
        wait_for(lambda p=p: p.raft.stats()["applied_index"]
                 >= leader.raft.stats()["applied_index"],
                 msg="replica catchup")
        assert group.read_all(p) == group.read_all(leader)


def test_window_zero_restores_inline_signaling(group, window_flag):
    """--raft_group_commit_window_us=0 keeps the pre-pipeline behaviour:
    every append signals peers immediately and everything still
    replicates/applies."""
    window_flag(0)
    leader = group.leader()
    for i in range(20):
        leader.write([group.row(f"k{i}", i)])
    want = group.read_all(leader)
    assert len(want) == 20
    for p in group.peers.values():
        wait_for(lambda p=p: p.raft.stats()["applied_index"]
                 >= leader.raft.stats()["applied_index"],
                 msg="replica catchup")
        assert group.read_all(p) == want
