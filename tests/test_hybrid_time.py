"""Hybrid time / clock tests. Reference analog: src/yb/server/hybrid_clock-test.cc."""

import threading

import numpy as np

from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime, LogicalClock
from yugabyte_db_tpu.utils.planes import ht_to_planes, planes_to_u64, scalar_ht_planes


def test_packing():
    ht = HybridTime.from_micros(123456789, 7)
    assert ht.physical_micros == 123456789
    assert ht.logical == 7
    assert HybridTime.from_micros(123456789, 8) > ht > HybridTime.from_micros(123456788, 4095)


def test_clock_monotonic_same_micro():
    t = [1000]
    clock = HybridClock(now_micros=lambda: t[0])
    a = clock.now()
    b = clock.now()
    c = clock.now()
    assert a < b < c
    assert b.physical_micros == 1000 and b.logical >= 1


def test_clock_never_goes_backwards():
    t = [1000]
    clock = HybridClock(now_micros=lambda: t[0])
    a = clock.now()
    t[0] = 500  # wall clock regression
    b = clock.now()
    assert b > a


def test_clock_update_ratchets():
    clock = HybridClock(now_micros=lambda: 1000)
    remote = HybridTime.from_micros(99999, 3)
    clock.update(remote)
    assert clock.now() > remote


def test_clock_thread_safety():
    clock = HybridClock(now_micros=lambda: 42)
    seen = []
    lock = threading.Lock()

    def worker():
        vals = [clock.now().value for _ in range(200)]
        with lock:
            seen.extend(vals)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(set(seen)) == len(seen)  # all distinct


def test_logical_clock():
    c = LogicalClock()
    a, b = c.now(), c.now()
    assert b.value == a.value + 1
    c.update(HybridTime(100))
    assert c.now().value == 101


def test_ht_planes_roundtrip_and_order(rng):
    vals = rng.integers(0, (1 << 63) - 1, size=1000, dtype=np.int64)
    vals = np.sort(vals)
    hi, lo = ht_to_planes(vals)
    back = planes_to_u64(hi, lo).astype(np.int64)
    assert (back == vals).all()
    # Lexicographic (hi, lo) order under signed compare == numeric order.
    order = np.lexsort((lo, hi))
    assert (np.diff(order) > 0).all()

    h, l = scalar_ht_planes(int(vals[500]))
    assert (hi[500], lo[500]) == (h, l)
