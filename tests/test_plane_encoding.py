"""Plane-encoding byte-identity tests: compressed runs vs the CPU oracle.

The compressed plane encodings (--tpu_plane_encoding: dictionary for
varlen, RLE/delta16/const for ints, bit-packed bools — ops/encodings.py)
must be invisible to every reader: scans over encoded runs return the
exact rows/aggregates the CPU engine computes, on every path — the
code-promoted dictionary predicates, each per-column fallback branch
(dict overflow, low run-length, wide deltas), tombstones, TTL expiry,
same-batch write_id ties, and the eviction → demand-re-upload round
trip under a starved HBM budget.

Runs on the CPU JAX backend (conftest) — same kernels the TPU executes.
"""

import gc
import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.storage import (
    AggSpec, Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.residency import hbm_cache
from yugabyte_db_tpu.storage.row_version import MAX_HT
from yugabyte_db_tpu.storage.tpu_engine import TpuStorageEngine
from yugabyte_db_tpu.utils.flags import FLAGS

CITIES = ["austin", "boston", "chicago", "denver", "el paso",
          "fresno", "helena", "juneau"]


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("city", DataType.STRING),
        ColumnSchema("grp", DataType.INT32),     # long wide-delta runs -> rle
        ColumnSchema("seq", DataType.INT32),     # small spans -> delta16
        ColumnSchema("konst", DataType.INT32),   # one value -> const
        ColumnSchema("wild", DataType.INT32),    # full-range -> plain
    ], table_id="t")


def enc_key(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def ids(schema):
    return {c.name: c.col_id for c in schema.value_columns}


def both_engines(opts=None):
    schema = make_schema()
    return (schema,
            make_engine("cpu", schema, dict(opts or {})),
            make_engine("tpu", schema, dict(opts or {}, rows_per_block=64)))


def apply_both(cpu, tpu, rows):
    cpu.apply(rows)
    tpu.apply(rows)


def assert_same_scan(cpu, tpu, spec_kwargs):
    a = cpu.scan(ScanSpec(**spec_kwargs))
    b = tpu.scan(ScanSpec(**spec_kwargs))
    assert a.columns == b.columns
    assert a.rows == b.rows, f"spec={spec_kwargs}"
    assert (a.resume_key is None) == (b.resume_key is None)
    return a, b


def load_encoding_friendly(schema, cpu, tpu, n=400, seed=11):
    """A workload each int column of which targets one encoding branch
    and whose string column is low-cardinality (dictionary bait)."""
    rnd = random.Random(seed)
    cids = ids(schema)
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 3)
        key = enc_key(schema, rnd.choice("pq"), i)
        roll = rnd.random()
        # Sparse tombstones: each one zeroes its row's cmp planes, which
        # splits value runs — keep few enough per 64-row block that the
        # rle bait column stays under the run-count cap.
        if roll < 0.03:
            apply_both(cpu, tpu, [RowVersion(key, ht=ht, tombstone=True)])
            continue
        apply_both(cpu, tpu, [RowVersion(
            key, ht=ht, liveness=True,
            # grp: long runs with million-wide steps — delta16's per-block
            # span cap rules it out, so run-length encoding must win.
            columns={cids["city"]: rnd.choice(CITIES + [None]),
                     cids["grp"]: (i // 96) * 1_000_000,
                     cids["seq"]: 3 * i,
                     cids["konst"]: 7,
                     cids["wild"]: rnd.randrange(-2**31, 2**31 - 1)},
            expire_ht=ht + rnd.randrange(5, 300)
            if rnd.random() < 0.12 else MAX_HT)])
    return ht


@pytest.fixture
def encoding_flag():
    old = FLAGS.get("tpu_plane_encoding")
    yield lambda v: FLAGS.set("tpu_plane_encoding", v)
    FLAGS.set("tpu_plane_encoding", old)


@pytest.fixture
def budget_flag():
    gc.collect()
    hbm_cache().evict_unpinned()
    old = FLAGS.get("tpu_hbm_budget_bytes")
    yield lambda v: FLAGS.set("tpu_hbm_budget_bytes", int(v))
    FLAGS.set("tpu_hbm_budget_bytes", old)
    hbm_cache().evict_unpinned()


def force_encoded(tpu):
    """Build every run's encoded tree (what a device access does) and
    return the merged by-encoding byte map."""
    by = {}
    for t in tpu.runs:
        assert t.crun.encoded_arrays() is not None
        for k, v in t.crun.enc_stats["by_encoding"].items():
            by[k] = by.get(k, 0) + v
    return by


def test_each_encoding_branch_selected_and_identical():
    """Every selection branch fires on its bait column — and none of
    them changes a single scanned byte."""
    schema, cpu, tpu = both_engines()
    load_encoding_friendly(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    by = force_encoded(tpu)
    # One branch per bait column; bool planes bit-pack; the full-range
    # random column must have stayed plain (the no-win fallback).
    for kind in ("dict", "rle", "delta16", "const", "bits", "plain"):
        assert kind in by, f"expected a {kind} leaf, got {by}"
    stats = tpu.runs[0].crun.enc_stats
    assert stats["encoded_bytes"] < stats["logical_bytes"]
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT,
        aggregates=[AggSpec("count", None), AggSpec("sum", "grp"),
                    AggSpec("min", "wild"), AggSpec("max", "seq")]))


def test_dict_code_promotion_byte_identity():
    """Range/equality predicates on the dictionary column promote to
    code compares (no host re-verify on the aggregate path) and agree
    with the oracle on every operator — including literals absent from
    the dictionary and out of its range."""
    schema, cpu, tpu = both_engines()
    load_encoding_friendly(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    promoted = []
    orig = TpuStorageEngine._promote_code_preds

    def spy(self, trun, preds):
        out = orig(self, trun, preds)
        if out is not None:
            promoted.append(len(out))
        return out

    TpuStorageEngine._promote_code_preds = spy
    try:
        cases = [
            [Predicate("city", "=", "denver")],
            [Predicate("city", "=", "dallas")],      # absent literal
            [Predicate("city", "!=", "austin")],
            [Predicate("city", "<", "chicago")],
            [Predicate("city", "<=", "chicago")],
            [Predicate("city", ">", "fresno")],
            [Predicate("city", ">=", "fresnn")],     # absent, mid-range
            [Predicate("city", "<", "aaaa")],        # below the dict
            [Predicate("city", ">", "zzzz")],        # above the dict
        ]
        for preds in cases:
            assert_same_scan(cpu, tpu, dict(
                read_ht=MAX_HT, predicates=preds,
                aggregates=[AggSpec("count", None),
                            AggSpec("sum", "grp")]))
    finally:
        TpuStorageEngine._promote_code_preds = orig
    assert len(promoted) >= len(cases)


def test_dict_overflow_falls_back_plain(monkeypatch):
    """A varlen column whose cardinality exceeds the dictionary capacity
    stays in plain prefix planes (per-column fallback) while the rest of
    the run still encodes — and scans stay byte-identical."""
    monkeypatch.setattr(encodings, "DICT_MAX_VALUES", 4)
    schema, cpu, tpu = both_engines()
    load_encoding_friendly(schema, cpu, tpu)  # 8 cities > 4 slots
    cpu.flush(); tpu.flush()
    force_encoded(tpu)
    crun = tpu.runs[0].crun
    assert not crun.enc_dicts, "overflowed dict must not be encoded"
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    # The string predicate now takes the superset + host-verify path.
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, predicates=[Predicate("city", "=", "denver")],
        aggregates=[AggSpec("count", None)]))


def test_encoding_off_is_plain_and_identical(encoding_flag):
    """--tpu_plane_encoding=off: no encoded tree is ever built and the
    results match both the oracle and the encoded run's results."""
    schema, cpu, tpu = both_engines()
    load_encoding_friendly(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    a, _ = assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    encoding_flag("off")
    for t in tpu.runs:
        t.invalidate_device()
        assert t.crun.encoded_arrays() is None
    b, _ = assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert a.rows == b.rows


def test_tombstones_ttl_write_id_ties():
    """MVCC edge shapes over encoded planes: row tombstones shadowing
    same-batch writes (write_id ties at one hybrid time), TTL expiry
    straddling read points, and null-vs-absent dictionary codes."""
    schema, cpu, tpu = both_engines()
    cids = ids(schema)
    base = 1000
    for i in range(120):
        key = enc_key(schema, "p", i)
        # One batch, one ht: column write then a higher-write_id rewrite.
        apply_both(cpu, tpu, [
            RowVersion(key, ht=base, liveness=True, write_id=2 * i,
                       columns={cids["city"]: CITIES[i % 5],
                                cids["grp"]: i // 30}),
            RowVersion(key, ht=base, write_id=2 * i + 1,
                       columns={cids["city"]: CITIES[(i + 1) % 5]}),
        ])
    for i in range(0, 120, 3):  # delete every third key in a later batch
        apply_both(cpu, tpu, [RowVersion(enc_key(schema, "p", i),
                                         ht=base + 10, tombstone=True)])
    for i in range(120, 180):   # TTL'd rows expiring at base+50
        apply_both(cpu, tpu, [RowVersion(
            enc_key(schema, "p", i), ht=base + 20, liveness=True,
            columns={cids["city"]: None, cids["grp"]: 99},
            expire_ht=base + 50)])
    cpu.flush(); tpu.flush()
    force_encoded(tpu)
    for rp in (base, base + 10, base + 30, base + 60, MAX_HT):
        assert_same_scan(cpu, tpu, dict(read_ht=rp))
        assert_same_scan(cpu, tpu, dict(
            read_ht=rp, predicates=[Predicate("city", ">=", "boston")],
            aggregates=[AggSpec("count", None)]))


def test_eviction_demand_reupload_round_trip(budget_flag):
    """Evict under a 1/4 budget and demand re-upload: the re-upload is
    the compressed tree (smaller than the budget that evicted the
    seeded planes would imply) and scans stay identical before/after."""
    schema, cpu, tpu = both_engines()
    load_encoding_friendly(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    a, _ = assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    trun = tpu.runs[0]
    resident = trun.dev.nbytes
    budget_flag(max(resident // 4, 1))
    hbm_cache().evict_unpinned()
    dev = trun.dev  # demand re-upload through the starved cache
    assert dev.encoded, "re-upload must be the compressed tree"
    assert dev.nbytes < resident
    b, _ = assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert a.rows == b.rows
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, predicates=[Predicate("city", "=", "chicago")],
        aggregates=[AggSpec("count", None), AggSpec("sum", "seq")]))


def test_compaction_emits_encoded_runs():
    """Compacting two encoded runs produces a run that re-encodes (the
    merge path feeds the same builder) and matches the oracle across
    the history cutoff."""
    schema, cpu, tpu = both_engines()
    ht = load_encoding_friendly(schema, cpu, tpu, n=250, seed=21)
    cpu.flush(); tpu.flush()
    load_encoding_friendly(schema, cpu, tpu, n=250, seed=22)
    cpu.flush(); tpu.flush()
    cpu.compact(history_cutoff_ht=ht)
    tpu.compact(history_cutoff_ht=ht)
    assert cpu.stats()["num_runs"] == tpu.stats()["num_runs"] == 1
    by = force_encoded(tpu)
    assert "dict" in by
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(read_ht=ht))


@pytest.mark.slow
def test_randomized_predicate_sweep_encoded():
    """Randomized predicate sweep over encoded runs at many read
    points — the long-tail shapes the targeted cases above don't pin."""
    schema, cpu, tpu = both_engines(
        {"memtable_flush_versions": 97, "compaction_trigger": 4})
    rnd = random.Random(42)
    cids = ids(schema)
    ht = 0
    read_points = []
    for step in range(600):
        ht += rnd.randrange(1, 3)
        key = enc_key(schema, rnd.choice("abc"), rnd.randrange(80))
        roll = rnd.random()
        if roll < 0.1:
            rv = RowVersion(key, ht=ht, tombstone=True)
        else:
            rv = RowVersion(
                key, ht=ht, liveness=True,
                columns={cids["city"]: rnd.choice(CITIES + [None]),
                         cids["grp"]: rnd.randrange(4),
                         cids["seq"]: step,
                         cids["konst"]: 7,
                         cids["wild"]: rnd.randrange(-10**9, 10**9)},
                expire_ht=ht + rnd.randrange(3, 80)
                if rnd.random() < 0.15 else MAX_HT)
        apply_both(cpu, tpu, [rv])
        if step % 60 == 0:
            read_points.append(ht)
    ops = ["=", "!=", "<", "<=", ">", ">="]
    for rp in read_points + [ht, MAX_HT]:
        assert_same_scan(cpu, tpu, dict(read_ht=rp))
        assert_same_scan(cpu, tpu, dict(
            read_ht=rp,
            predicates=[Predicate("city", rnd.choice(ops),
                                  rnd.choice(CITIES))],
            aggregates=[AggSpec("count", None), AggSpec("sum", "grp")]))
