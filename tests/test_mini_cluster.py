"""Cluster integration tests: DDL, writes/reads through the client,
tserver kill + failover, re-replication, master failover, restarts.

Reference test analog: src/yb/client/ql-dml-test.cc (MiniCluster DML),
raft_consensus-itest.cc / ts_recovery-itest.cc (kill/restart),
master_failover-itest.cc.
"""

import time

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec

# Excluded from tier-1 (-m 'not slow'): multi-minute rig, full runs keep it.
pytestmark = pytest.mark.slow

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
    ColumnSchema("v", DataType.INT64),
    ColumnSchema("s", DataType.STRING),
]


def wait_for(pred, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    yield c
    c.shutdown()


def load_rows(client, table, n, start=0):
    session = client.session() if hasattr(client, "session") else None
    from yugabyte_db_tpu.client import YBSession
    s = YBSession(client)
    for i in range(start, start + n):
        s.insert(table, {"k": f"key{i % 17}", "r": i, "v": i * 10,
                         "s": f"val-{i}"})
    return s.flush()


def test_ddl_write_read_roundtrip(cluster):
    client = cluster.client()
    table = client.create_table("kv", COLUMNS, num_tablets=4,
                                replication_factor=3)
    assert load_rows(client, table, 100) == 100
    from yugabyte_db_tpu.client import YBSession
    s = YBSession(client)
    res = s.scan(table, ScanSpec())
    assert len(res.rows) == 100
    assert res.columns == ["k", "r", "v", "s"]
    # point get
    row = s.get(table, {"k": "key3", "r": 3})
    assert row == ("key3", 3, 30, "val-3")
    assert s.get(table, {"k": "nope", "r": 999}) is None
    # predicate + projection + limit
    res = s.scan(table, ScanSpec(predicates=[Predicate("v", ">=", 500)],
                                 projection=["r", "v"]))
    assert all(v >= 500 for _, v in res.rows)
    assert len(res.rows) == 50
    res = s.scan(table, ScanSpec(limit=7))
    assert len(res.rows) == 7
    # update + delete
    s.update(table, {"k": "key3", "r": 3}, {"v": -1})
    s.delete(table, {"k": "key4", "r": 4})
    s.flush()
    assert s.get(table, {"k": "key3", "r": 3})[2] == -1
    assert s.get(table, {"k": "key4", "r": 4}) is None
    assert len(s.scan(table, ScanSpec()).rows) == 99
    # tables listing
    assert [t["name"] for t in client.list_tables()] == ["kv"]


def test_multi_tablet_aggregates(cluster):
    client = cluster.client()
    table = client.create_table("agg", COLUMNS, num_tablets=4)
    load_rows(client, table, 200)
    from yugabyte_db_tpu.client import YBSession
    s = YBSession(client)
    res = s.scan(table, ScanSpec(aggregates=[
        AggSpec("count", None), AggSpec("sum", "v"), AggSpec("min", "v"),
        AggSpec("max", "v"), AggSpec("avg", "v")]))
    count, total, vmin, vmax, avg = res.rows[0]
    assert count == 200
    assert total == sum(i * 10 for i in range(200))
    assert (vmin, vmax) == (0, 1990)
    assert avg == total / 200
    # group by
    res = s.scan(table, ScanSpec(aggregates=[AggSpec("count", None)],
                                 group_by=["k"]))
    assert sum(r[1] for r in res.rows) == 200
    assert len(res.rows) == 17


def test_tserver_kill_failover_and_rereplication(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=4).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("ha", COLUMNS, num_tablets=2,
                                    replication_factor=3)
        load_rows(client, table, 30)
        # Find a tserver holding a replica and kill it.
        locs = client.meta_cache.locations("ha", refresh=True)
        victim = locs.tablets[0].replicas[0]
        c.stop_tserver(victim)
        # Writes and reads keep working through failover.
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)

        def can_write():
            try:
                load_rows(client, table, 10, start=1000)
                return True
            except Exception:
                return False
        wait_for(can_write, timeout=15.0, msg="writes after ts kill")
        assert len(s.scan(table, ScanSpec()).rows) == 40
        # Master re-replicates onto the spare tserver.
        def rereplicated():
            locs2 = client.meta_cache.locations("ha", refresh=True)
            return all(victim not in t.replicas and len(t.replicas) == 3
                       for t in locs2.tablets)
        wait_for(rereplicated, timeout=30.0, msg="re-replication")
    finally:
        c.shutdown()


def test_master_failover(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=3, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("mf", COLUMNS, num_tablets=2)
        load_rows(client, table, 20)
        leader = c.leader_master()
        # Kill the master leader (unregister + shutdown).
        c.transport.unregister(leader.uuid)
        c.masters.pop(leader.uuid).shutdown()
        # A new master leader takes over with the full catalog; the client
        # can still resolve tables and write.
        def catalog_served():
            try:
                client.meta_cache.locations("mf", refresh=True)
                return True
            except Exception:
                return False
        wait_for(catalog_served, timeout=15.0, msg="new master serves catalog")
        load_rows(client, table, 20, start=100)
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        assert len(s.scan(table, ScanSpec()).rows) == 40
        # New DDL needs the new leader's soft TS registry, rebuilt from
        # heartbeats (the reference's master failover behaves the same).
        new_leader = c.leader_master()
        wait_for(lambda: len(new_leader.ts_manager.live_tservers()) >= 3,
                 timeout=15.0, msg="tservers re-register with new master")
        client.create_table("mf2", COLUMNS, num_tablets=1)
        assert {t["name"] for t in client.list_tables()} == {"mf", "mf2"}
    finally:
        c.shutdown()


def test_full_cluster_restart_preserves_data(tmp_path):
    c = MiniCluster(str(tmp_path) + "/a", num_masters=1, num_tservers=3)
    c.start()
    c.wait_tservers_registered()
    client = c.client()
    table = client.create_table("persist", COLUMNS, num_tablets=2)
    load_rows(client, table, 50)
    c.shutdown()

    c2 = MiniCluster(str(tmp_path) + "/a", num_masters=1, num_tservers=3)
    c2.start()
    try:
        c2.wait_tservers_registered()
        client2 = c2.client()
        table2 = client2.open_table("persist")
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client2)

        def all_rows():
            try:
                return len(s.scan(table2, ScanSpec()).rows) == 50
            except Exception:
                return False
        wait_for(all_rows, timeout=15.0, msg="data after full restart")
    finally:
        c2.shutdown()


def test_socket_transport_cluster(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3,
                    transport="socket").start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("sock", COLUMNS, num_tablets=2)
        load_rows(client, table, 25)
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        res = s.scan(table, ScanSpec(aggregates=[AggSpec("count", None),
                                                 AggSpec("sum", "v")]))
        assert res.rows[0][0] == 25
    finally:
        c.shutdown()


def test_live_missing_replica_repaired_without_failed_creates(tmp_path):
    """A replica missing from a live tserver (e.g. the create dispatch was
    lost together with the master's in-memory _failed_creates on restart)
    is repaired through the config-cycle path: the master removes it from
    the group, re-creates it, and adds it back."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("fix", COLUMNS, num_tablets=1,
                                    replication_factor=3)
        load_rows(client, table, 30)
        locs = client.meta_cache.locations("fix", refresh=True)
        tinfo = locs.tablets[0]
        master = next(iter(c.masters.values()))
        leader = master.ts_manager.leader_of(tinfo.tablet_id)
        victim = next(r for r in tinfo.replicas if r != leader)
        # Simulate "create never happened / data lost" on a live tserver,
        # with no in-memory record of the failure.
        c.tservers[victim].tablet_manager.delete_tablet(tinfo.tablet_id)
        master._failed_creates.clear()
        master.missing_replica_grace_s = 1.0

        def repaired():
            ts = c.tservers[victim]
            try:
                peer = ts.tablet_manager.get(tinfo.tablet_id)
            except Exception:
                return False
            st = peer.raft.stats()
            return st["commit_index"] > 0 and \
                set(st.get("peers", tinfo.replicas)) == set(tinfo.replicas)
        wait_for(repaired, timeout=30.0, msg="config-cycle repair")
        # Data still fully readable.
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        assert len(s.scan(table, ScanSpec()).rows) == 30
    finally:
        c.shutdown()


@pytest.mark.mesh
def test_mesh_multi_tablet_aggregate(tmp_path):
    """Multi-tablet aggregates execute as ONE device program on the
    tserver's mesh (ts.multi_agg_scan -> parallel.sharded_aggregate with
    psum/pmax combine), not as per-tablet scans merged on the client."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=1).start()
    try:
        c.wait_tservers_registered(1)
        client = c.client()
        table = client.create_table("mesh", COLUMNS, num_tablets=4,
                                    replication_factor=1, engine="tpu")
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        n = 200
        for i in range(n):
            s.insert(table, {"k": f"key{i}", "r": i, "v": i * 10,
                             "s": f"val-{i}"})
        assert s.flush() == n
        ts = next(iter(c.tservers.values()))
        for peer in ts.tablet_manager.peers():
            peer.flush()
        total = sum(i * 10 for i in range(n))

        def mesh_served():
            # Transient lease/leadership states legitimately fall back to
            # per-tablet scans; results stay correct either way. Retry
            # until the mesh path engages.
            res = s.scan(table, ScanSpec(aggregates=[
                AggSpec("count", None), AggSpec("sum", "v"),
                AggSpec("min", "v"), AggSpec("max", "v"),
                AggSpec("avg", "v")]))
            assert res.rows == [(n, total, 0, 1990, total / n)]
            return ts.mesh_scan.served >= 1
        wait_for(mesh_served, timeout=20.0,
                 msg="aggregate riding the mesh")
        # Device-exact predicate pushdown through the mesh path.
        res2 = s.scan(table, ScanSpec(
            predicates=[Predicate("v", ">=", 1000)],
            aggregates=[AggSpec("count", None)]))
        assert res2.rows == [(100,)]
        assert ts.mesh_scan.served >= 2
        # Ineligible spec (string min needs the host path) falls back and
        # still returns correct results.
        res3 = s.scan(table, ScanSpec(aggregates=[AggSpec("max", "s")]))
        assert res3.rows == [("val-99",)]
        assert ts.mesh_scan.fallbacks >= 1
    finally:
        c.shutdown()


@pytest.mark.mesh
def test_mesh_multi_tablet_row_scan(tmp_path):
    """Row scans over many tablets of one tserver ride the mesh as ONE
    device program per page (ts.multi_row_scan ->
    parallel.sharded_row_page), with LIMIT paging chained by the opaque
    cross-tablet resume token; a flush replacing a tablet's run
    invalidates the cached stack (in-place update or rebuild+close)
    without leaking residency pins."""
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=1).start()
    try:
        c.wait_tservers_registered(1)
        client = c.client()
        table = client.create_table("meshrow", COLUMNS, num_tablets=4,
                                    replication_factor=1, engine="tpu")
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        n = 240
        for i in range(n):
            s.insert(table, {"k": f"key{i}", "r": i, "v": i * 10,
                             "s": f"val-{i}"})
        assert s.flush() == n
        ts = next(iter(c.tservers.values()))
        for peer in ts.tablet_manager.peers():
            peer.flush()
        want = sorted((f"key{i}", i, i * 10, f"val-{i}")
                      for i in range(n))

        def mesh_served():
            res = s.scan(table, ScanSpec())
            assert sorted(res.rows) == want
            return ts.mesh_scan.served_rows >= 1
        wait_for(mesh_served, timeout=20.0, msg="rows riding the mesh")
        # LIMIT + device-exact predicate through the mesh path.
        res2 = s.scan(table, ScanSpec(
            predicates=[Predicate("v", ">=", 1200)], limit=50))
        assert len(res2.rows) == 50
        assert all(r[2] >= 1200 for r in res2.rows)
        # A flush replacing one tablet's run supersedes the cached
        # stack; the next scan re-serves the NEW data on the mesh.
        for i in range(n, n + 40):
            s.insert(table, {"k": f"key{i}", "r": i, "v": i * 10,
                             "s": f"val-{i}"})
        s.flush()
        for peer in ts.tablet_manager.peers():
            peer.flush()
            peer.compact()
        want2 = sorted((f"key{i}", i, i * 10, f"val-{i}")
                       for i in range(n + 40))

        def mesh_served_again():
            before = ts.mesh_scan.served_rows
            res = s.scan(table, ScanSpec())
            assert sorted(res.rows) == want2
            return ts.mesh_scan.served_rows > before
        wait_for(mesh_served_again, timeout=20.0,
                 msg="post-flush rows riding the mesh")
        # Stack cache bounded; superseded stacks released their pins.
        from yugabyte_db_tpu.storage.residency import hbm_cache
        assert len(ts.mesh_scan._stacks) <= ts.mesh_scan._max_cached
        stats = hbm_cache().stats()
        ext = stats["by_encoding"].get("external", {"entries": 0})
        assert ext["entries"] <= ts.mesh_scan._max_cached + 4
    finally:
        c.shutdown()
