"""Tablet layer tests: WAL, bootstrap replay, flush frontier, MVCC manager.

Reference test analog: src/yb/consensus/log-test.cc,
src/yb/tablet/tablet_bootstrap-test.cc, mvcc-test.cc.
"""

import os
import threading

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec
from yugabyte_db_tpu.storage.row_version import MAX_HT
from yugabyte_db_tpu.tablet import Log, LogEntry, MvccManager, OpId, Tablet, TabletMetadata
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("v", DataType.STRING),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


# -- WAL -------------------------------------------------------------------

def test_wal_roundtrip(tmp_path):
    log = Log(str(tmp_path / "wal"), fsync=False)
    for i in range(1, 21):
        log.append(LogEntry(OpId(1, i), ht=100 + i, op_type="write",
                            body=[b"key", i, {"x": [1, 2.5, None]}]))
    log.sync()
    log.close()
    log2 = Log(str(tmp_path / "wal"), fsync=False)
    entries = list(log2.read_all())
    assert [e.op_id.index for e in entries] == list(range(1, 21))
    assert entries[3].body == [b"key", 4, {"x": [1, 2.5, None]}]
    assert log2.last_appended == OpId(1, 20)


def test_wal_rejects_non_monotonic(tmp_path):
    log = Log(str(tmp_path / "wal"), fsync=False)
    log.append(LogEntry(OpId(1, 5), 1, "write", []))
    with pytest.raises(ValueError):
        log.append(LogEntry(OpId(1, 5), 2, "write", []))


def test_wal_torn_tail_recovery(tmp_path):
    log = Log(str(tmp_path / "wal"), fsync=False)
    for i in range(1, 6):
        log.append(LogEntry(OpId(1, i), i, "write", [i]))
    log.sync()
    log.close()
    # Corrupt: truncate mid-record (simulated crash during write).
    path = log.segment_paths()[0]
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    entries = list(Log(str(tmp_path / "wal"), fsync=False).read_all())
    assert [e.body for e in entries] == [[1], [2], [3], [4]]  # last dropped


def test_wal_segment_roll_and_gc(tmp_path):
    log = Log(str(tmp_path / "wal"), segment_bytes=256, fsync=False)
    for i in range(1, 51):
        log.append(LogEntry(OpId(1, i), i, "write", ["x" * 30]))
    log.sync()
    assert len(log.segment_paths()) > 2
    deleted = log.gc(min_retained_index=30)
    assert deleted > 0
    entries = list(log.read_all(30))
    assert [e.op_id.index for e in entries][:1] == [30] or \
        entries[0].op_id.index < 30  # segment granularity keeps extra entries
    assert [e.op_id.index for e in entries][-1] == 50
    # everything >= 30 must survive
    idxs = {e.op_id.index for e in log.read_all()}
    assert set(range(30, 51)) <= idxs


# -- MvccManager -----------------------------------------------------------

def test_mvcc_safe_time_blocks_on_pending():
    clock = HybridClock(now_micros=lambda: 1000)
    m = MvccManager(clock)
    ht1 = clock.now()
    m.add_pending(ht1)
    assert m.safe_time().value == ht1.value - 1
    m.replicated(ht1)
    # Reads at the replicated ht are safe; observing must not issue an HT.
    assert m.safe_time() >= ht1
    assert m.safe_time() >= ht1  # stable across repeated observation
    assert m.last_replicated_ht == ht1


def test_mvcc_wait_for_safe_time():
    clock = HybridClock(now_micros=lambda: 1000)
    m = MvccManager(clock)
    ht = clock.now()
    m.add_pending(ht)
    done = []

    def waiter():
        done.append(m.wait_for_safe_time(ht, timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    m.replicated(ht)
    t.join(timeout=5)
    assert done == [True]


# -- Tablet end-to-end -----------------------------------------------------

@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_tablet_write_read_restart(tmp_path, engine):
    schema = make_schema()
    ids = {c.name: c.col_id for c in schema.value_columns}
    meta = TabletMetadata("t1", "tbl", schema, 0, 65536, engine=engine)
    tab = Tablet.create(meta, str(tmp_path), fsync=False)
    for i in range(30):
        tab.write([RowVersion(enc(schema, "a", i), ht=0, liveness=True,
                              columns={ids["v"]: f"val{i}"})])
    res = tab.scan(ScanSpec(read_ht=tab.read_time().value))
    assert len(res.rows) == 30
    tab.close()

    # Restart WITHOUT flush: everything must come back from the WAL.
    tab2 = Tablet.open("t1", str(tmp_path), fsync=False)
    assert tab2._replayed_on_bootstrap == 30
    res2 = tab2.scan(ScanSpec(read_ht=MAX_HT))
    assert res2.rows == res.rows
    tab2.close()


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_tablet_flush_frontier_and_wal_gc(tmp_path, engine):
    schema = make_schema()
    ids = {c.name: c.col_id for c in schema.value_columns}
    meta = TabletMetadata("t2", "tbl", schema, 0, 65536, engine=engine)
    tab = Tablet.create(meta, str(tmp_path), fsync=False)
    tab.log.segment_bytes = 512  # force rolls
    for i in range(60):
        tab.write([RowVersion(enc(schema, "a", i), ht=0, liveness=True,
                              columns={ids["v"]: f"v{i}"})])
    tab.flush()
    assert tab.meta.flushed_op_index == 60
    for i in range(60, 80):
        tab.write([RowVersion(enc(schema, "a", i), ht=0, liveness=True,
                              columns={ids["v"]: f"v{i}"})])
    tab.close()

    tab2 = Tablet.open("t2", str(tmp_path), fsync=False)
    # Only the 20 post-flush writes replay; flushed data loads from runs.
    assert tab2._replayed_on_bootstrap == 20
    res = tab2.scan(ScanSpec(read_ht=MAX_HT, projection=["r"]))
    assert [r[0] for r in res.rows] == list(range(80))
    tab2.close()


def test_tablet_mvcc_snapshot_after_restart(tmp_path):
    schema = make_schema()
    ids = {c.name: c.col_id for c in schema.value_columns}
    meta = TabletMetadata("t3", "tbl", schema, 0, 65536, engine="cpu")
    tab = Tablet.create(meta, str(tmp_path), fsync=False)
    key = enc(schema, "a", 1)
    ht1 = tab.write([RowVersion(key, ht=0, liveness=True, columns={ids["v"]: "x"})])
    ht2 = tab.write([RowVersion(key, ht=0, columns={ids["v"]: "y"})])
    tab.write([RowVersion(key, ht=0, tombstone=True)])
    tab.close()
    tab2 = Tablet.open("t3", str(tmp_path), fsync=False)
    assert tab2.scan(ScanSpec(read_ht=ht1.value)).rows == [("a", 1, "x")]
    assert tab2.scan(ScanSpec(read_ht=ht2.value)).rows == [("a", 1, "y")]
    assert tab2.scan(ScanSpec(read_ht=MAX_HT)).rows == []
    # Clock must have ratcheted past replayed HTs: new writes get larger HTs.
    ht4 = tab2.write([RowVersion(key, ht=0, liveness=True, columns={ids["v"]: "z"})])
    assert ht4 > ht2
    tab2.close()


def test_codec_roundtrip():
    from yugabyte_db_tpu.utils import codec
    cases = [
        None, True, False, 0, 1, -1, 2 ** 62, -(2 ** 62), 2 ** 80, -(2 ** 80),
        1.5, -0.0, "héllo", b"\x00\xff", [1, [2, [3]]],
        {"a": 1, "b": [None, {"c": b"x"}]}, [],
    ]
    for v in cases:
        assert codec.decode(codec.encode(v)) == v


def test_intra_batch_write_id_ordering(tmp_path):
    """Two writes to the SAME key in ONE batch share a hybrid time; the
    write_id sub-ordering (DocHybridTime's write_id component,
    src/yb/common/doc_hybrid_time.h) makes the LATER one win — on both
    engines, before and after flush."""
    import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401
    from yugabyte_db_tpu.models.partition import compute_hash_code

    for engine in ("cpu", "tpu"):
        schema = make_schema()
        cid = {c.name: c.col_id for c in schema.columns}
        meta = TabletMetadata(f"t-{engine}", "t", schema, 0, 65536,
                              engine=engine)
        t = Tablet.create(meta, str(tmp_path / engine), fsync=False)
        key = schema.encode_primary_key(
            {"k": "dup", "r": 0},
            compute_hash_code(schema, {"k": "dup"}))
        t.write([
            RowVersion(key, ht=0, liveness=True, columns={cid["v"]: "a"}),
            RowVersion(key, ht=0, liveness=True, columns={cid["v"]: "b"}),
            RowVersion(key, ht=0, columns={cid["v"]: "c"}),  # UPDATE-style
        ])
        for label in ("memtable", "flushed"):
            res = t.scan(ScanSpec(read_ht=t.read_time().value,
                                  projection=["k", "v"]))
            assert res.rows == [("dup", "c")], (engine, label, res.rows)
            t.flush()
        # same-batch DELETE shadows same-ht writes regardless of position
        # (the device kernel's <= tombstone rule; scan.py:182)
        t.write([
            RowVersion(key, ht=0, liveness=True, columns={cid["v"]: "z"}),
            RowVersion(key, ht=0, tombstone=True),
        ])
        res = t.scan(ScanSpec(read_ht=t.read_time().value))
        assert res.rows == [], (engine, res.rows)
        t.close()
