"""Device row-materialization (ops.row_gather) vs the CPU oracle.

Exercises the paths the engine-diff tests don't reach naturally: batched
page scans (scan_batch), multi-round continuations (host-verified
predicates overflowing the packed buffer), sparse pages crossing many
windows, and mixed batches (pages + aggregates + multi-source fallback).
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (AggSpec, Predicate, ScanSpec,
                                     make_engine)
from yugabyte_db_tpu.storage.row_version import RowVersion


def _schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
        ColumnSchema("s", DataType.STRING),
    ], table_id="gather")


def _load(num, seed=11, versions_per_key=1, rows_per_block=64):
    schema = _schema()
    rng = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.columns}
    cpu = make_engine("cpu", schema, {"rows_per_block": rows_per_block})
    tpu = make_engine("tpu", schema, {"rows_per_block": rows_per_block})
    ht = 10
    for i in range(num):
        key = schema.encode_primary_key(
            {"k": f"u{i:05d}", "r": i % 3},
            compute_hash_code(schema, {"k": f"u{i:05d}"}))
        for _v in range(versions_per_key):
            ht += 1
            rv = RowVersion(key, ht=ht, liveness=True, columns={
                cid["a"]: rng.randrange(-1000, 1000),
                cid["c"]: rng.uniform(-10, 10),
                cid["d"]: rng.randrange(0, 100),
                cid["s"]: rng.choice(["alpha", "beta", "gamma", None]),
            })
            cpu.apply([rv])
            tpu.apply([rv])
    cpu.flush()
    tpu.flush()
    return schema, cpu, tpu, ht


def _key_lower(schema, i):
    return schema.encode_primary_key(
        {"k": f"u{i:05d}", "r": 0},
        compute_hash_code(schema, {"k": f"u{i:05d}"}))


def _assert_same(a, b):
    assert a.columns == b.columns
    assert a.rows == b.rows
    assert a.resume_key == b.resume_key


def test_scan_batch_pages_identical():
    schema, cpu, tpu, ht = _load(2000)
    rng = random.Random(5)
    specs = []
    for _ in range(40):
        lo = _key_lower(schema, rng.randrange(2000))
        specs.append(ScanSpec(lower=lo, read_ht=ht + 1,
                              predicates=[Predicate("d", ">=", 30)],
                              projection=["k", "r", "a", "d"], limit=20))
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(ra, rb):
        _assert_same(a, b)


def test_paged_full_paging_equivalence():
    """Follow resume keys page by page; union must equal a full scan."""
    schema, cpu, tpu, ht = _load(1500)
    spec_full = ScanSpec(read_ht=ht + 1,
                         predicates=[Predicate("d", "<", 50)],
                         projection=["k", "a"])
    want = cpu.scan(spec_full).rows
    got = []
    lower = b""
    pages = 0
    while True:
        spec = ScanSpec(lower=lower, read_ht=ht + 1,
                        predicates=[Predicate("d", "<", 50)],
                        projection=["k", "a"], limit=37)
        res = tpu.scan(spec)
        got.extend(res.rows)
        pages += 1
        if res.resume_key is None:
            break
        lower = res.resume_key
    assert got == want
    assert pages >= 2


def test_host_verified_pred_continuation():
    """IN predicates are host-verified; with a large table and few matches
    the packed buffer overflows with unverified rows, forcing multi-round
    continuation that must still produce exact results."""
    schema, cpu, tpu, ht = _load(3000)
    targets = tuple(range(0, 3))  # d in 0..2: ~3% of rows
    for limit in (10, 50):
        sa = ScanSpec(read_ht=ht + 1,
                      predicates=[Predicate("d", "IN", targets)],
                      projection=["k", "d"], limit=limit)
        _assert_same(cpu.scan(sa), tpu.scan(sa))


def test_sparse_page_crosses_windows():
    """A page whose matches live far apart (cap growth path)."""
    schema, cpu, tpu, ht = _load(4000, rows_per_block=32)
    spec = ScanSpec(read_ht=ht + 1,
                    predicates=[Predicate("d", "=", 7)],  # ~1%
                    projection=["k", "r", "d"], limit=15)
    _assert_same(cpu.scan(spec), tpu.scan(spec))


def test_string_predicate_superset_verify():
    schema, cpu, tpu, ht = _load(1200)
    for op, val in (("=", "beta"), (">", "alpha"), ("!=", "gamma")):
        spec = ScanSpec(read_ht=ht + 1,
                        predicates=[Predicate("s", op, val)],
                        projection=["k", "s"], limit=25)
        _assert_same(cpu.scan(spec), tpu.scan(spec))


def test_mixed_batch():
    """Pages + aggregates + unlimited scans in one scan_batch call."""
    schema, cpu, tpu, ht = _load(1000)
    specs = [
        ScanSpec(read_ht=ht + 1, projection=["k", "a"], limit=10),
        ScanSpec(read_ht=ht + 1,
                 aggregates=[AggSpec("count", None), AggSpec("sum", "a")]),
        ScanSpec(read_ht=ht + 1, predicates=[Predicate("d", ">=", 90)],
                 projection=["k", "d"]),
        ScanSpec(lower=_key_lower(schema, 500), read_ht=ht + 1,
                 projection=["k", "r", "a", "c", "d", "s"], limit=55),
        ScanSpec(read_ht=ht + 1, aggregates=[AggSpec("min", "c")],
                 group_by=["r"]),
    ]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(ra, rb):
        _assert_same(a, b)


def test_multiversion_rows_not_flat():
    """3 versions per key: the general (segmented) kernel must agree."""
    schema, cpu, tpu, ht = _load(400, versions_per_key=3)
    assert tpu.runs[0].crun.max_group_versions == 3
    spec = ScanSpec(read_ht=ht + 1, projection=["k", "a", "d"], limit=50)
    _assert_same(cpu.scan(spec), tpu.scan(spec))
    # read in the past: older versions become visible
    spec_old = ScanSpec(read_ht=ht - 400,
                        projection=["k", "a", "d"], limit=50)
    _assert_same(cpu.scan(spec_old), tpu.scan(spec_old))


def test_rows_scanned_agrees_unlimited():
    """For unlimited scans over tombstone-free data the scanned statistic
    must match the CPU oracle exactly — this pins the scan_from gating
    that prevents double-counting across continuation rounds (a LIMIT
    page may legitimately over-report: the device resolves whole
    windows; see ScanResult.rows_scanned)."""
    schema, cpu, tpu, ht = _load(2500)
    for preds in ([], [Predicate("d", ">=", 97)], [Predicate("d", "<", 5)]):
        sa = ScanSpec(read_ht=ht + 1, predicates=list(preds),
                      projection=["k", "d"])
        ra, rb = cpu.scan(sa), tpu.scan(sa)
        assert ra.rows == rb.rows
        assert ra.rows_scanned == rb.rows_scanned, preds


def test_batch_with_memtable_fallback():
    """Un-flushed writes force the host merge path inside a batch."""
    schema, cpu, tpu, ht = _load(600)
    cid = {c.name: c.col_id for c in schema.columns}
    key = schema.encode_primary_key(
        {"k": "u00300", "r": 0}, compute_hash_code(schema, {"k": "u00300"}))
    rv = RowVersion(key, ht=ht + 5, liveness=True,
                    columns={cid["a"]: 424242})
    cpu.apply([rv])
    tpu.apply([rv])
    specs = [
        ScanSpec(read_ht=ht + 10, projection=["k", "a"], limit=400),
        ScanSpec(read_ht=ht + 10,
                 predicates=[Predicate("a", "=", 424242)],
                 projection=["k", "a"]),
    ]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(ra, rb):
        _assert_same(a, b)
