"""CQL user-defined types: CREATE TYPE, UDT columns (frozen field maps),
literal validation, round-trip, DROP TYPE guards — both cluster seams.

Reference analog: src/yb/yql/cql/ql/ptree/pt_create_type.cc + UDTypeInfo
catalog records; java/yb-cql TestUserDefinedTypes.
"""

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.utils.status import InvalidArgument, NotFound
from yugabyte_db_tpu.yql.cql import QLProcessor
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.processor import LocalCluster


@pytest.fixture
def local_ql():
    cluster = LocalCluster(num_tablets=2)
    ql = QLProcessor(cluster)
    yield ql
    cluster.close()


@pytest.fixture
def dist_ql(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    ql = QLProcessor(ClientCluster(c.client()))
    yield ql
    c.shutdown()


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_udt_round_trip(fixture, request):
    ql = request.getfixturevalue(fixture)
    ql.execute("CREATE TYPE address (street TEXT, city TEXT, zip INT)")
    ql.execute("CREATE TABLE people (id INT PRIMARY KEY, name TEXT, "
               "home FROZEN<address>)")
    ql.execute("INSERT INTO people (id, name, home) VALUES (1, 'ann', "
               "{'street': '1 Main', 'city': 'Springfield', 'zip': 11111})")
    ql.execute("INSERT INTO people (id, name, home) VALUES (2, 'bob', "
               "{'city': 'Shelbyville'})")  # missing fields -> NULL
    rows = ql.execute("SELECT id, home FROM people").dicts()
    by_id = {r["id"]: r["home"] for r in rows}
    assert by_id[1] == {"street": "1 Main", "city": "Springfield",
                       "zip": 11111}
    assert by_id[2] == {"street": None, "city": "Shelbyville", "zip": None}
    # UPDATE replaces the frozen value wholesale.
    ql.execute("UPDATE people SET home = {'city': 'Ogdenville'} "
               "WHERE id = 1")
    rows = ql.execute("SELECT home FROM people WHERE id = 1").rows
    assert rows[0][0]["city"] == "Ogdenville"


@pytest.mark.parametrize("fixture", ["local_ql", "dist_ql"])
def test_udt_validation_and_drop_guard(fixture, request):
    ql = request.getfixturevalue(fixture)
    ql.execute("CREATE TYPE pt (x INT, y INT)")
    with pytest.raises(Exception):
        ql.execute("CREATE TYPE pt (x INT)")  # duplicate
    ql.execute("CREATE TYPE IF NOT EXISTS pt (x INT)")  # tolerated
    with pytest.raises(InvalidArgument):
        ql.execute("CREATE TABLE t0 (id INT PRIMARY KEY, p nosuchtype)")
    ql.execute("CREATE TABLE t1 (id INT PRIMARY KEY, p FROZEN<pt>)")
    with pytest.raises(InvalidArgument):
        ql.execute("INSERT INTO t1 (id, p) VALUES (1, {'x': 1, 'z': 9})")
    with pytest.raises(Exception):
        ql.execute("DROP TYPE pt")  # in use by t1
    ql.execute("DROP TABLE t1")
    ql.execute("DROP TYPE pt")
    with pytest.raises(NotFound):
        ql.execute("DROP TYPE pt")
    ql.execute("DROP TYPE IF EXISTS pt")
