"""Engine-diff interop for the native request-batch serving path
(docs/serving-path.md): pipelined RESP GET/SET/MGET and prepared CQL
point SELECTs must produce BYTE-IDENTICAL replies whether a batch is
served by the native C++ executors or by the per-op Python path they
shortcut — including when the native module is not built at all.

Reference analog: the reference proves proxy fidelity with stock
drivers (java/yb-jedis-tests, java/yb-cql); here the two server-side
execution paths are diffed against each other at the socket byte level.
"""

import socket

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.cql import wire_protocol as W
from yugabyte_db_tpu.yql.cql import processor as procmod
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.server import CQLServer
from yugabyte_db_tpu.yql.redis import RedisServer
from yugabyte_db_tpu.yql.redis import resp as respmod
from yugabyte_db_tpu.yql.redis import server as redismod

try:
    from yugabyte_db_tpu.native import yb_rb as _yb_rb
except ImportError:  # pragma: no cover - native module not built
    _yb_rb = None

needs_native = pytest.mark.skipif(
    _yb_rb is None, reason="native yb_rb module not built")


# -- redis -------------------------------------------------------------------

def _resp_encode(cmds):
    out = []
    for args in cmds:
        out.append(f"*{len(args)}\r\n".encode())
        for a in args:
            b = a.encode() if isinstance(a, str) else a
            out.append(f"${len(b)}\r\n".encode() + b + b"\r\n")
    return b"".join(out)


def _read_replies(sock, n):
    """Raw bytes of exactly n RESP replies (nested arrays counted as
    one), so byte-level diffs cover framing, not just values."""
    buf = bytearray()

    def need(k):
        while len(buf) < k:
            chunk = sock.recv(65536)
            assert chunk, "connection closed"
            buf.extend(chunk)

    pos = 0

    def line():
        nonlocal pos
        while True:
            i = buf.find(b"\r\n", pos)
            if i >= 0:
                break
            need(len(buf) + 1)
        s = bytes(buf[pos:i])
        pos = i + 2
        return s

    def one():
        nonlocal pos
        ln = line()
        t = ln[:1]
        if t in (b"+", b"-", b":"):
            return
        if t == b"$":
            k = int(ln[1:])
            if k >= 0:
                need(pos + k + 2)
                pos += k + 2
            return
        assert t == b"*", ln
        cnt = int(ln[1:])
        for _ in range(max(cnt, 0)):
            one()

    for _ in range(n):
        one()
    assert pos == len(buf), "unexpected trailing bytes"
    return bytes(buf)


@pytest.fixture
def redis_rig(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = RedisServer(c.client("redis-proxy"))
    host, port = server.listen("127.0.0.1", 0)

    def run(cmds):
        s = socket.create_connection((host, port), timeout=10)
        try:
            s.sendall(_resp_encode(cmds))
            return _read_replies(s, len(cmds))
        finally:
            s.close()

    yield run
    server.shutdown()
    c.shutdown()


PIPELINE = ([("SET", f"k{i}", f"v{i}") for i in range(40)]
            + [("GET", f"k{i}") for i in range(40)]
            + [("GET", "missing"), ("GET", "k7"),
               ("MGET", "k1", "missing", "k2"),
               ("SET", "k1", "v1b"), ("GET", "k1"),
               ("MSET", "a", "1", "b", "2"), ("MGET", "a", "b", "c")])


@needs_native
def test_redis_pipeline_native_vs_python_byte_identical(redis_rig,
                                                        monkeypatch):
    native = redis_rig(PIPELINE)
    served = []
    orig = redismod.RedisServiceImpl._native_get_values

    def spy(self, rkeys):
        v = orig(self, rkeys)
        served.append(v is not None)
        return v

    monkeypatch.setattr(redismod.RedisServiceImpl, "_native_get_values",
                        spy)
    again = redis_rig(PIPELINE)
    assert served and all(served), "native batch path never served"
    assert again == native
    # identical pipeline with the native read path disabled entirely
    monkeypatch.setattr(redismod.RedisServiceImpl, "_native_get_values",
                        lambda self, rkeys: None)
    fallback = redis_rig(PIPELINE)
    assert fallback == native


def test_redis_pipeline_without_native_module(redis_rig, monkeypatch):
    """The whole pipeline (parse included) must behave identically when
    the native module is absent — the not-built deployment shape."""
    expected = redis_rig(PIPELINE)
    monkeypatch.setattr(respmod, "_yb_rb", None)
    monkeypatch.setattr(redismod, "_yb_rb", None)
    assert redis_rig(PIPELINE) == expected


# -- CQL ---------------------------------------------------------------------

class _CqlWire:
    """Minimal CQL v4 raw-frame client that can pipeline many EXECUTE
    frames in one socket write and hand back each reply frame verbatim."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        w = W.Writer()
        w.short(1)
        w.string("CQL_VERSION").string("3.4.4")
        self._send(0, W.OP_STARTUP, w.getvalue())
        _s, opcode, _b = self.recv_frame()
        assert opcode == W.OP_READY

    def close(self):
        self.sock.close()

    def _send(self, stream, opcode, body):
        self.sock.sendall(
            W.HEADER.pack(W.VERSION_REQ, 0, stream, opcode, len(body))
            + body)

    def _recvn(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            assert chunk, "connection closed"
            buf += chunk
        return buf

    def recv_frame(self):
        hdr = self._recvn(W.HEADER.size)
        _v, _f, stream, opcode, length = W.HEADER.unpack(hdr)
        return stream, opcode, self._recvn(length)

    def query(self, cql):
        self._send(1, W.OP_QUERY,
                   W.Writer().long_string(cql).short(1).byte(0).getvalue())
        _s, opcode, body = self.recv_frame()
        assert opcode == W.OP_RESULT, body
        return body

    def prepare(self, cql):
        self._send(1, W.OP_PREPARE,
                   W.Writer().long_string(cql).getvalue())
        _s, opcode, body = self.recv_frame()
        assert opcode == W.OP_RESULT, body
        r = W.Reader(body)
        assert r.int32() == W.RESULT_PREPARED
        return r.short_bytes()

    def execute_many(self, frames):
        """frames: [(stream, stmt_id, [raw_value_bytes])]. All sent in
        ONE write (the pipelined shape the batch path coalesces);
        returns {stream: (opcode, body)} for byte-level comparison."""
        out = []
        for stream, stmt_id, values in frames:
            w = W.Writer().short_bytes(stmt_id)
            w.short(1).byte(0x01 if values else 0)
            if values:
                w.short(len(values))
                for v in values:
                    w.bytes_(v)
            out.append(W.HEADER.pack(W.VERSION_REQ, 0, stream,
                                     W.OP_EXECUTE, len(w.getvalue()))
                       + w.getvalue())
        self.sock.sendall(b"".join(out))
        replies = {}
        for _ in range(len(frames)):
            stream, opcode, body = self.recv_frame()
            assert stream not in replies
            replies[stream] = (opcode, body)
        assert set(replies) == {f[0] for f in frames}
        return replies


@pytest.fixture
def cql_rig(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = CQLServer(ClientCluster(c.client()))
    host, port = server.listen("127.0.0.1", 0)
    cli = _CqlWire(host, port)
    cli.query("CREATE KEYSPACE sp")
    cli.query("USE sp")
    cli.query("CREATE TABLE t (k bigint PRIMARY KEY, v text, d double)")
    for i in range(30):
        cli.query(f"INSERT INTO t (k, v, d) VALUES ({i}, 'val{i}', "
                  f"{i * 0.5})")
    yield cli
    cli.close()
    server.shutdown()
    c.shutdown()


def _i64(v):
    return v.to_bytes(8, "big", signed=True)


def test_cql_prepared_point_select_batch_byte_identical(cql_rig,
                                                        monkeypatch):
    sel = cql_rig.prepare("SELECT k, v, d FROM t WHERE k = ?")
    frames = [(100 + i, sel, [_i64(i)]) for i in range(20)]
    frames += [(200, sel, [_i64(999)]),             # miss -> empty rows
               (201, b"\x00" * 16, [_i64(1)])]      # unknown stmt -> error
    served = []
    orig = procmod.QLProcessor.execute_wire_point_batch

    def spy(self, items):
        out = orig(self, items)
        served.extend(r is not None for r in out)
        return out

    monkeypatch.setattr(procmod.QLProcessor, "execute_wire_point_batch",
                        spy)
    batched = cql_rig.execute_many(frames)
    assert served and any(served), "batch path never served a frame"
    assert batched[201][0] == W.OP_ERROR
    # Same frames with the batch executor refusing everything: each
    # frame runs the canonical per-op handle_call path.
    monkeypatch.setattr(procmod.QLProcessor, "execute_wire_point_batch",
                        lambda self, items: [None] * len(items))
    fallback = cql_rig.execute_many(frames)
    assert fallback == batched


def test_cql_batch_mixed_with_nonpoint_select(cql_rig):
    """A pipelined window mixing point SELECTs with a full-table scan:
    the scan falls back per-op inside the SAME batch and every reply
    stays stream-paired."""
    sel = cql_rig.prepare("SELECT v FROM t WHERE k = ?")
    scan = cql_rig.prepare("SELECT k FROM t")
    frames = [(1, sel, [_i64(3)]), (2, scan, []), (3, sel, [_i64(4)])]
    replies = cql_rig.execute_many(frames)
    for stream in (1, 2, 3):
        opcode, body = replies[stream]
        assert opcode == W.OP_RESULT
        assert W.Reader(body).int32() == W.RESULT_ROWS
    # the scan really returned the whole table
    r = W.Reader(replies[2][1])
    assert r.int32() == W.RESULT_ROWS
    flags = r.int32()
    ncols = r.int32()
    if flags & 0x0002:
        r.bytes_()
    if flags & 0x0001:
        r.string(); r.string()
    for _ in range(ncols):
        r.string(); r.short()
    assert r.int32() == 30
