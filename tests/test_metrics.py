"""Metrics/flags/webserver: /metrics scrapeable on every daemon.

Reference analog: metrics-test.cc + the PrometheusWriter endpoint
(src/yb/util/metrics.h:584) and the per-daemon webservers.
"""

import json
import urllib.request

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import MetricRegistry

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("v", DataType.INT64),
]


def _get(addr, path):
    host, port = addr
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=10) as r:
        return r.read().decode()


def test_registry_prometheus_text():
    reg = MetricRegistry()
    ent = reg.entity(daemon="x")
    ent.counter("reqs_total").increment(3)
    ent.gauge("temp").set(42)
    h = ent.histogram("lat_us")
    for v in (100, 1000, 100000):
        h.observe(v)
    text = reg.prometheus_text()
    assert '# TYPE reqs_total counter' in text
    assert 'reqs_total{daemon="x"} 3' in text
    assert 'temp{daemon="x"} 42' in text
    assert 'lat_us_count{daemon="x"} 3' in text
    assert 'lat_us_sum{daemon="x"} 101100' in text
    assert 'le="+Inf"' in text
    assert h.percentile(0.5) >= 100


def test_flags_registry():
    FLAGS.define("test_only_flag", 7, "testing", ("runtime",))
    assert FLAGS.get("test_only_flag") == 7
    FLAGS.set("test_only_flag", 9)
    assert FLAGS.get("test_only_flag") == 9
    FLAGS.define("test_unsafe_flag", 1, "danger", ("unsafe",))
    import pytest
    with pytest.raises(PermissionError):
        FLAGS.set("test_unsafe_flag", 2)
    FLAGS.set("test_unsafe_flag", 2, force=True)
    assert FLAGS.get("test_unsafe_flag") == 2


def test_every_daemon_scrapeable(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        c.wait_tservers_registered()
        addrs = c.start_webservers()
        assert len(addrs) == 4
        client = c.client()
        table = client.create_table("m", COLUMNS, num_tablets=2)
        s = YBSession(client)
        for i in range(20):
            s.insert(table, {"k": f"k{i}", "v": i})
        s.flush()
        s.scan(table, ScanSpec(projection=["k"]))
        for uuid, addr in addrs.items():
            text = _get(addr, "/metrics")
            assert "rpc_requests_total" in text, uuid
            assert "rpc_latency_us_bucket" in text, uuid
            health = json.loads(_get(addr, "/healthz"))
            assert health["status"] == "ok"
            varz = json.loads(_get(addr, "/varz"))
            assert "compaction_trigger" in varz
        # tserver tablet gauges + master catalog gauges present
        ts_uuid = next(u for u in addrs if u in c.tservers)
        ts_text = _get(addrs[ts_uuid], "/metrics")
        assert "tablet_is_leader" in ts_text
        assert "tablet_run_versions" in ts_text
        tablets = json.loads(_get(addrs[ts_uuid], "/tablets"))
        assert any(t["table"] == "m" for t in tablets)
        m_uuid = next(u for u in addrs if u in c.masters)
        m_text = _get(addrs[m_uuid], "/metrics")
        assert "master_is_leader" in m_text
        assert "master_num_tablets" in m_text
        tables = json.loads(_get(addrs[m_uuid], "/tables"))
        assert any(t["name"] == "m" for t in tables)
    finally:
        c.shutdown()


def test_registry_hammer_counts_are_exact():
    """8 threads hammering one registry's counters/histograms/gauges:
    every increment lands (regression test for the unsynchronized
    read-modify-write counter bumps the iraces/ pass flagged)."""
    import threading

    reg = MetricRegistry()
    ent = reg.entity(role="hammer")
    c = ent.counter("hammer_total")
    h = ent.histogram("hammer_us", buckets=(10, 100, 1000))
    g = ent.gauge("hammer_last")
    n_threads, n_ops = 8, 2000
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        barrier.wait()
        for i in range(n_ops):
            c.increment()
            h.observe(i % 1000)
            g.set(tid)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get() == n_threads * n_ops
    assert h.count == n_threads * n_ops
    assert g.get() in range(n_threads)
