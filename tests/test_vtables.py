"""CQL system vtables: the stock-driver handshake sequence.

Reference analog: the master's YQLVirtualTable family
(yql_local_vtable.cc, yql_peers_vtable.cc, yql_keyspaces_vtable.cc,
yql_tables_vtable.cc, yql_columns_vtable.cc). A Cassandra driver's
connect sequence is: query system.local, system.peers, then the
system_schema tables to build its metadata — these tests replay that
exact sequence over the real wire protocol.
"""

import pytest

from tests.test_cql_wire import WireClient
from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.cql import wire_protocol as W
from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
from yugabyte_db_tpu.yql.cql.processor import LocalCluster, QLProcessor
from yugabyte_db_tpu.yql.cql.server import CQLServer


@pytest.fixture
def wire(tmp_path):
    cluster = LocalCluster(num_tablets=2)
    server = CQLServer(cluster)
    host, port = server.listen("127.0.0.1", 0)
    cli = WireClient(host, port)
    cli.startup()
    yield cli
    cli.close()
    server.shutdown()


def _text_cell(b):
    return None if b is None else b.decode()


def test_driver_handshake_sequence(wire):
    cli = wire
    # Schema the driver will discover.
    cli.query("CREATE TABLE users (id INT, r BIGINT, name TEXT, "
              "score DOUBLE, PRIMARY KEY ((id), r))")

    # 1. system.local — one row, the handshake's first read.
    cols, rows, _p = cli.query("SELECT * FROM system.local")
    names = [c[0] for c in cols]
    assert len(rows) == 1
    local = dict(zip(names, rows[0]))
    assert _text_cell(local["key"]) == "local"
    assert _text_cell(local["cql_version"]) == "3.4.4"
    assert _text_cell(local["partitioner"]).endswith("Murmur3Partitioner")
    for required in ("cluster_name", "data_center", "rack", "host_id",
                     "release_version", "rpc_address", "tokens",
                     "native_protocol_version", "schema_version"):
        assert required in names, required

    # 2. system.peers — valid result (empty for a single node) with the
    #    column set the driver reads.
    cols, rows, _p = cli.query("SELECT * FROM system.peers")
    names = [c[0] for c in cols]
    for required in ("peer", "rpc_address", "data_center", "rack",
                     "host_id", "tokens"):
        assert required in names, required

    # 3. schema metadata.
    cols, rows, _p = cli.query("SELECT keyspace_name FROM "
                               "system_schema.keyspaces")
    keyspaces = {_text_cell(r[0]) for r in rows}
    assert {"default", "system", "system_schema"} <= keyspaces

    cols, rows, _p = cli.query(
        "SELECT keyspace_name, table_name FROM system_schema.tables "
        "WHERE keyspace_name = 'default'")
    tables = {(_text_cell(r[0]), _text_cell(r[1])) for r in rows}
    assert ("default", "users") in tables

    cols, rows, _p = cli.query(
        "SELECT column_name, kind, position, type FROM "
        "system_schema.columns WHERE keyspace_name = 'default' AND "
        "table_name = 'users'")
    got = {_text_cell(r[0]): (_text_cell(r[1]), _text_cell(r[3]))
           for r in rows}
    assert got["id"] == ("partition_key", "int")
    assert got["r"] == ("clustering", "bigint")
    assert got["name"] == ("regular", "text")
    assert got["score"] == ("regular", "double")


def test_vtable_count_and_limit(wire):
    cli = wire
    cols, rows, _p = cli.query("SELECT count(*) FROM system.peers")
    assert [c[0] for c in cols] == ["count"]
    cli.query("CREATE TABLE t1 (k INT, PRIMARY KEY (k))")
    cli.query("CREATE TABLE t2 (k INT, PRIMARY KEY (k))")
    _c, rows, _p = cli.query(
        "SELECT table_name FROM system_schema.tables LIMIT 1")
    assert len(rows) == 1


def test_peers_reflect_distributed_tservers(tmp_path):
    mc = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    try:
        mc.wait_tservers_registered()
        p = QLProcessor(ClientCluster(mc.client()))
        res = p.execute("SELECT peer, rpc_address FROM system.peers")
        # 3 tservers -> this node + 2 peers.
        assert len(res.rows) == 2
    finally:
        mc.shutdown()


def test_vtables_readable_without_table_permission():
    """Handshake must work for ANY authenticated role (no grants)."""
    from yugabyte_db_tpu.auth import hash_password
    from yugabyte_db_tpu.utils.flags import FLAGS

    FLAGS.set("use_cassandra_authentication", True)
    try:
        cluster = LocalCluster(num_tablets=2)
        cluster.auth_op({"op": "auth_create_role", "name": "app",
                         "can_login": True,
                         "salted_hash": hash_password("x")})
        p = QLProcessor(cluster, login_role="app")
        assert p.execute("SELECT key FROM system.local").rows
        from yugabyte_db_tpu.yql.cql.processor import Unauthorized

        with pytest.raises(Unauthorized):
            p.execute("CREATE TABLE t (k INT, PRIMARY KEY (k))")
    finally:
        FLAGS.set("use_cassandra_authentication", False)
