"""Device GROUP BY / expression aggregates vs the CPU oracle.

Pins ops.group_agg (bucket hashing, exact digit-vector product sums,
collision/negative fallbacks) to Aggregator semantics — the TPC-H Q1/Q6
machinery.
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (AggSpec, Predicate, ScanSpec,
                                     make_engine)
from yugabyte_db_tpu.storage.expr import BinOp, Col, Const
from yugabyte_db_tpu.storage.row_version import RowVersion


def _load(num=3000, seed=7, with_nulls=True, negatives=False,
          versions=1):
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("flag", DataType.STRING),       # 1-char, Q1-like
        ColumnSchema("status", DataType.STRING),
        ColumnSchema("qty", DataType.INT64),
        ColumnSchema("price", DataType.INT64),       # cents
        ColumnSchema("disc", DataType.INT8),         # percent 0..10
        ColumnSchema("tax", DataType.INT8),          # percent 0..8
        ColumnSchema("d", DataType.INT32),
    ], table_id="li")
    rng = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.columns}
    cpu = make_engine("cpu", schema, {"rows_per_block": 256})
    tpu = make_engine("tpu", schema, {"rows_per_block": 256})
    ht = 10
    for i in range(num):
        key = schema.encode_primary_key(
            {"k": f"r{i:06d}"}, compute_hash_code(schema, {"k": f"r{i:06d}"}))
        for _v in range(versions):
            ht += 1
            price = rng.randrange(100, 10_000_00)
            if negatives and rng.random() < 0.01:
                price = -price
            cols = {
                cid["flag"]: rng.choice(["A", "N", "R"]),
                cid["status"]: rng.choice(["F", "O"]),
                cid["qty"]: rng.randrange(1, 51),
                cid["price"]: price,
                cid["disc"]: rng.randrange(0, 11),
                cid["tax"]: rng.randrange(0, 9),
                cid["d"]: rng.randrange(0, 1000),
            }
            if with_nulls and rng.random() < 0.05:
                cols[cid["qty"]] = None
            rv = RowVersion(key, ht=ht, liveness=True, columns=cols)
            cpu.apply([rv])
            tpu.apply([rv])
    cpu.flush()
    tpu.flush()
    return cpu, tpu, ht


Q1_AGGS = [
    AggSpec("count", None, label="n"),
    AggSpec("sum", "qty", label="sum_qty"),
    AggSpec("sum", "price", label="sum_price"),
    AggSpec("sum", None, label="sum_disc_price",
            expr=BinOp("*", Col("price"),
                       BinOp("-", Const(100), Col("disc")))),
    AggSpec("sum", None, label="sum_charge",
            expr=BinOp("*", BinOp("*", Col("price"),
                                  BinOp("-", Const(100), Col("disc"))),
                       BinOp("+", Const(100), Col("tax")))),
]


def test_grouped_q1_shape_matches_oracle():
    cpu, tpu, ht = _load()
    spec = ScanSpec(read_ht=ht + 1, aggregates=list(Q1_AGGS),
                    group_by=["flag", "status"],
                    predicates=[Predicate("d", "<", 900)])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.columns == b.columns
    assert a.rows == b.rows
    assert len(b.rows) == 6  # 3 flags x 2 statuses


def test_expression_sum_ungrouped_q6_shape():
    cpu, tpu, ht = _load()
    spec = ScanSpec(read_ht=ht + 1, aggregates=[
        AggSpec("sum", None, label="revenue",
                expr=BinOp("*", Col("price"), Col("disc"))),
    ], predicates=[Predicate("qty", "<", 25), Predicate("d", ">=", 100)])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows


def test_grouped_with_nulls_in_group_column():
    cpu, tpu, ht = _load(num=500)
    # null out some statuses via overwrites
    schema = cpu.schema
    cid = {c.name: c.col_id for c in schema.columns}
    rows = []
    for i in range(0, 500, 7):
        key = schema.encode_primary_key(
            {"k": f"r{i:06d}"}, compute_hash_code(schema, {"k": f"r{i:06d}"}))
        rows.append(RowVersion(key, ht=ht + 1, columns={cid["status"]: None}))
    cpu.apply(rows)
    tpu.apply(rows)
    cpu.flush()
    tpu.flush()
    cpu.compact()
    tpu.compact()
    spec = ScanSpec(read_ht=ht + 2, group_by=["status"],
                    aggregates=[AggSpec("count", None),
                                AggSpec("sum", "qty")])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows


def test_negative_base_falls_back_exactly():
    cpu, tpu, ht = _load(num=800, negatives=True)
    spec = ScanSpec(read_ht=ht + 1, group_by=["flag"], aggregates=[
        AggSpec("sum", "price"),
        AggSpec("sum", None,
                expr=BinOp("*", Col("price"),
                           BinOp("-", Const(100), Col("disc")))),
    ])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows


def test_multiversion_grouped():
    cpu, tpu, ht = _load(num=300, versions=3)
    spec = ScanSpec(read_ht=ht + 1, group_by=["flag", "status"],
                    aggregates=[AggSpec("count", None),
                                AggSpec("sum", "price")])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows
    # historical read (older versions visible)
    spec2 = ScanSpec(read_ht=ht - 300, group_by=["flag"],
                     aggregates=[AggSpec("sum", "qty")])
    assert cpu.scan(spec2).rows == tpu.scan(spec2).rows


def test_int32_group_column_and_count_col():
    cpu, tpu, ht = _load(num=1000)
    spec = ScanSpec(read_ht=ht + 1, group_by=["disc"],
                    aggregates=[AggSpec("count", "qty"),
                                AggSpec("sum", "price")])
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows
    assert len(b.rows) == 11
