"""Batched (vmapped) device aggregates vs the CPU oracle.

The batched planner groups same-signature single-source aggregate specs
from one scan_batch call and dispatches each group as ONE vmapped
program (tpu_engine._plan_device_aggregate_batch) — the tserver shape
where many concurrent aggregate queries differ only in bounds, read
points, and predicate literals. These tests pin the core group path
(stacking, pad lanes, per-lane finish slicing) that mixed-batch tests
only hit in the solo leg.
"""

import pytest

from tests.test_gather import _key_lower, _load
from yugabyte_db_tpu.storage import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.storage import tpu_engine as TE


def _aggs():
    return [AggSpec("count", None), AggSpec("sum", "a"),
            AggSpec("min", "c"), AggSpec("max", "d")]


def _assert_rows_equal(a, b):
    assert len(a.rows) == len(b.rows)
    for ra, rb in zip(a.rows, b.rows):
        for va, vb in zip(ra, rb):
            if isinstance(vb, float):
                assert va is not None and \
                    abs(va - vb) <= 1e-3 + 1e-5 * abs(vb)
            else:
                assert va == vb


@pytest.fixture
def spy(monkeypatch):
    calls: list[list[int]] = []
    orig = TE.TpuStorageEngine._plan_device_aggregate_batch

    def wrapper(self, items):
        out = orig(self, items)
        calls.append([pi for pi, *_ in items])
        return out

    monkeypatch.setattr(TE.TpuStorageEngine,
                        "_plan_device_aggregate_batch", wrapper)
    return calls


def test_vmapped_group_same_signature(spy):
    """5 specs, same signature, different literals: one vmapped group."""
    schema, cpu, tpu, ht = _load(600)
    specs = [ScanSpec(read_ht=ht + 1,
                      predicates=[Predicate("d", ">=", lo)],
                      aggregates=_aggs())
             for lo in (0, 17, 44, 71, 93)]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)
    assert spy and len(spy[0]) == 5


def test_vmapped_group_varying_read_ht(spy):
    """Same signature, different read points: MVCC visibility must be
    per-lane (each lane's read planes ride the stacked transfer)."""
    schema, cpu, tpu, ht = _load(300, versions_per_key=2)
    specs = [ScanSpec(read_ht=h, aggregates=_aggs())
             for h in (ht + 1, ht - 100, ht - 250, ht + 1)]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)
    assert spy and len(spy[0]) == 4


def test_vmapped_group_varying_bounds(spy):
    """Same signature, different key ranges: per-lane row bounds."""
    schema, cpu, tpu, ht = _load(500)
    specs = [ScanSpec(lower=_key_lower(schema, lo), read_ht=ht + 1,
                      aggregates=[AggSpec("count", None)])
             for lo in (0, 100, 250, 400, 499)]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)


def test_mixed_signatures_split_groups(spy):
    """Different predicate signatures in one batch: distinct groups
    (and a string-literal group exercising the [2]-plane literals)."""
    schema, cpu, tpu, ht = _load(400)
    specs = (
        [ScanSpec(read_ht=ht + 1, predicates=[Predicate("d", ">=", lo)],
                  aggregates=_aggs()) for lo in (5, 50)]
        + [ScanSpec(read_ht=ht + 1,
                    predicates=[Predicate("s", "=", v)],
                    aggregates=[AggSpec("count", None)])
           for v in ("alpha", "beta", "gamma")]
        + [ScanSpec(read_ht=ht + 1, aggregates=_aggs())]
    )
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)


def test_pad_lanes_padded_sizes(spy):
    """n=3 pads to m=4: pad lanes scan nothing and results stay
    per-spec correct."""
    schema, cpu, tpu, ht = _load(200)
    specs = [ScanSpec(read_ht=ht + 1,
                      predicates=[Predicate("a", ">=", lo)],
                      aggregates=[AggSpec("count", None),
                                  AggSpec("sum", "a")])
             for lo in (-1000, 0, 500)]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)


def test_async_batch_interface(spy):
    """The async API (issue now, finish later) over a vmapped group."""
    schema, cpu, tpu, ht = _load(300)
    specs = [ScanSpec(read_ht=ht + 1,
                      predicates=[Predicate("d", "<", hi)],
                      aggregates=_aggs())
             for hi in (10, 40, 80, 100)]
    h1 = tpu.scan_batch_async(specs)
    h2 = tpu.scan_batch_async(list(reversed(specs)))
    ra = cpu.scan_batch(specs)
    r1 = h1.finish()
    r2 = h2.finish()
    for a, b in zip(r1, ra):
        _assert_rows_equal(a, b)
    for a, b in zip(r2, list(reversed(ra))):
        _assert_rows_equal(a, b)


def test_vmapped_grouped_aggregates(monkeypatch):
    """GROUP BY specs batch through _plan_grouped_batch: one vmapped
    dispatch per signature group, per-lane results oracle-diffed."""
    calls: list[int] = []
    orig = TE.TpuStorageEngine._plan_grouped_batch

    def spy(self, items):
        calls.append(len(items))
        return orig(self, items)

    monkeypatch.setattr(TE.TpuStorageEngine, "_plan_grouped_batch", spy)
    schema, cpu, tpu, ht = _load(600)
    specs = [ScanSpec(read_ht=ht + 1,
                      predicates=[Predicate("d", ">=", lo)],
                      group_by=["s"],
                      aggregates=[AggSpec("count", None),
                                  AggSpec("sum", "a")])
             for lo in (0, 20, 55, 80)]
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for a, b in zip(rb, ra):
        _assert_rows_equal(a, b)
    assert calls and calls[0] == 4


def test_vmapped_grouped_mixed_with_plain(monkeypatch):
    """Plain + grouped aggregates in one batch: both sinks fire and
    every result matches the oracle."""
    schema, cpu, tpu, ht = _load(400)
    specs = (
        [ScanSpec(read_ht=ht + 1, group_by=["s"],
                  aggregates=[AggSpec("count", None)])
         for _ in range(3)]
        + [ScanSpec(read_ht=ht + 1,
                    predicates=[Predicate("d", "<", hi)],
                    aggregates=_aggs()) for hi in (30, 70)]
        + [ScanSpec(read_ht=ht + 1, projection=["k", "a"], limit=5)]
    )
    ra = cpu.scan_batch(specs)
    rb = tpu.scan_batch(specs)
    for i, (a, b) in enumerate(zip(rb, ra)):
        if i < 6:
            _assert_rows_equal(a, b)
        else:
            assert a.rows == b.rows
