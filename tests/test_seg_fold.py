"""ops.seg_fold (fused segmented MVCC aggregate) vs the CPU oracle and
the windowed fold on randomized multi-version data: overwrites,
tombstones (including same-ht DELETE+write ties), TTL, NULLs,
predicates, range bounds, and many read points.
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (AggSpec, Predicate, RowVersion,
                                     ScanSpec, make_engine)
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
    ], table_id="sf")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def load_multiversion(schema, engines, n=900, nkeys=120, seed=3):
    """Heavy overwrite workload: ~7 versions per key on average, with
    tombstones, same-ht delete/write ties, TTLs and NULLs."""
    rnd = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.value_columns}
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 3)
        key = enc(schema, f"k{rnd.randrange(nkeys):04d}", 0)
        roll = rnd.random()
        batch = []
        if roll < 0.12:
            batch.append(RowVersion(key, ht=ht, tombstone=True))
            if rnd.random() < 0.3:  # same-ht DELETE + write tie
                batch.append(RowVersion(
                    key, ht=ht, liveness=True,
                    columns={cid["a"]: rnd.randrange(-10**9, 10**9)}))
        elif roll < 0.7:
            batch.append(RowVersion(
                key, ht=ht, liveness=True,
                columns={cid["a"]: rnd.randrange(-10**12, 10**12),
                         cid["c"]: rnd.uniform(-1e6, 1e6),
                         cid["d"]: rnd.choice(
                             [None, rnd.randrange(-10**6, 10**6)])},
                expire_ht=(ht + rnd.randrange(5, 500)
                           if rnd.random() < 0.1 else MAX_HT)))
        else:
            col = rnd.choice(["a", "c", "d"])
            val = {"a": rnd.randrange(-10**10, 10**10),
                   "c": rnd.uniform(-100, 100),
                   "d": rnd.randrange(-1000, 1000)}[col]
            batch.append(RowVersion(key, ht=ht, columns={cid[col]: val}))
        for e in engines:
            e.apply(batch)
    for e in engines:
        e.flush()
    return ht


AGGS = [AggSpec("count", None), AggSpec("count", "d"), AggSpec("sum", "a"),
        AggSpec("sum", "d"), AggSpec("min", "a"), AggSpec("max", "a"),
        AggSpec("min", "d"), AggSpec("max", "d"), AggSpec("min", "c"),
        AggSpec("max", "c"), AggSpec("avg", "a")]


def assert_same_agg(cpu, tpu, **kw):
    a = cpu.scan(ScanSpec(**kw))
    b = tpu.scan(ScanSpec(**kw))
    assert a.columns == b.columns
    for va, vb, name in zip(a.rows[0], b.rows[0], a.columns):
        if isinstance(va, float):
            assert vb == pytest.approx(va, rel=1e-5, abs=1e-5), name
        else:
            assert va == vb, name


def setup(n=900, seed=3, rows_per_block=64):
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": rows_per_block})
    ht = load_multiversion(schema, [cpu, tpu], n=n, seed=seed)
    return schema, cpu, tpu, ht


def test_seg_route_taken():
    from yugabyte_db_tpu.ops import seg_fold

    schema, cpu, tpu, ht = setup()
    assert tpu.runs[0].crun.max_group_versions > 1  # genuinely segmented
    spec = ScanSpec(read_ht=MAX_HT, aggregates=list(AGGS))
    assert tpu._plan_scan(spec)[0] == "agg_deferred"
    route = tpu._device_agg_prep(tpu.runs[0], spec, [])[1]
    assert route in ("lookback", "seg")  # multi-version resolve route


def test_seg_matches_oracle_many_read_points():
    schema, cpu, tpu, ht = setup()
    for rp in (1, ht // 4, ht // 2, 3 * ht // 4, ht, MAX_HT):
        assert_same_agg(cpu, tpu, read_ht=rp, aggregates=list(AGGS))


@pytest.fixture(scope="module")
def seg_setup9():
    return setup(seed=9)


# One compiled program per distinct (aggregates, predicates) signature
# makes each case ~70s of XLA time, so tier-1 keeps the two cases with
# unique coverage (multi-predicate + range bounds at a mid read point)
# and the full sweep rides in the slow lane.
def _pred_cases():
    def lo_hi(schema):
        return enc(schema, "k0020", 0), enc(schema, "k0090", 0)

    return [
        pytest.param(
            lambda schema, ht: dict(
                read_ht=ht, aggregates=list(AGGS),
                predicates=[Predicate("a", "<", 0),
                            Predicate("d", "!=", 3)]),
            id="two-predicates", marks=pytest.mark.slow),
        pytest.param(
            lambda schema, ht: dict(
                read_ht=ht // 2, aggregates=list(AGGS),
                lower=lo_hi(schema)[0], upper=lo_hi(schema)[1]),
            id="bounds-mid-read-point"),
        pytest.param(
            lambda schema, ht: dict(
                read_ht=MAX_HT, aggregates=list(AGGS),
                predicates=[Predicate("d", ">=", 0)]),
            id="full-aggs-int-predicate", marks=pytest.mark.slow),
        pytest.param(
            lambda schema, ht: dict(
                read_ht=MAX_HT, aggregates=[AggSpec("count", None)],
                predicates=[Predicate("c", ">=", 0.0)]),
            id="count-only-float-predicate", marks=pytest.mark.slow),
        pytest.param(
            lambda schema, ht: dict(
                read_ht=MAX_HT, aggregates=list(AGGS),
                predicates=[Predicate("d", ">", 10**7)]),
            id="selective-predicate", marks=pytest.mark.slow),
    ]


@pytest.mark.parametrize("case", _pred_cases())
def test_seg_predicates_and_bounds(seg_setup9, case):
    schema, cpu, tpu, ht = seg_setup9
    assert_same_agg(cpu, tpu, **case(schema, ht))


def test_seg_matches_windowed_fold_exactly():
    """Bit-for-bit equivalence of the two device programs on the same
    uploaded run (the windowed fold is the long-standing oracle)."""
    import jax.numpy as jnp

    from yugabyte_db_tpu.ops import agg_fold, seg_fold
    from yugabyte_db_tpu.ops import scan as dscan

    schema, _cpu, tpu, ht = setup(seed=21)
    trun = tpu.runs[0]
    crun = trun.crun
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    kinds = tpu._kinds
    dev_aggs, _low = agg_fold.lower_aggs(AGGS, name_to_id, kinds)
    cols = tpu._col_sigs()
    preds = (dscan.PredSig(name_to_id["d"], "i32", ">="),)
    K = agg_fold.safe_window_blocks(crun.R, agg_fold.FULL_WINDOW_BLOCKS)
    sig = dscan.ScanSig(B=trun.dev.B, R=crun.R, K=K, cols=cols,
                        preds=preds, aggs=dev_aggs, apply_preds=True,
                        flat=False)
    from yugabyte_db_tpu.utils import planes as P

    for rp in (ht // 3, ht, MAX_HT - 1):
        r_hi, r_lo = P.scalar_ht_planes(rp)
        args_common = (trun.dev.arrays, jnp.int32(0),
                       jnp.int32(crun.total_rows()))
        tail = (jnp.int32(r_hi), jnp.int32(r_lo), jnp.int32(r_hi),
                jnp.int32(r_lo), (jnp.int32(-500),))
        W = trun.dev.B // K
        iv_w, fv_w = agg_fold.compiled_full_aggregate(sig)(
            *args_common, jnp.int32(0), jnp.int32(W), *tail)
        iv_s, fv_s = seg_fold.compiled_seg_aggregate(sig)(
            *args_common, *tail)
        # Digit vectors are non-canonical (different limb carry
        # distributions encode one total): compare FINALIZED values.
        acc_w, scanned_w = agg_fold.unpack(dev_aggs, iv_w, fv_w)
        acc_s, scanned_s = agg_fold.unpack(dev_aggs, iv_s, fv_s)
        assert scanned_w == scanned_s, rp
        for ag, aw, as_ in zip(dev_aggs, acc_w, acc_s):
            vw = agg_fold.finalize(ag, aw, ag.fn)
            vs = agg_fold.finalize(ag, as_, ag.fn)
            if isinstance(vw, float):
                assert vs == pytest.approx(vw, rel=1e-5, abs=1e-3), rp
            else:
                assert vw == vs, (rp, ag)


# Tier-1 keeps the non-power-of-two block size (the shape most likely
# to break window math); the power-of-two sweeps ride in the slow lane.
@pytest.mark.parametrize("seed,rpb", [
    pytest.param(31, 32, id="rpb32", marks=pytest.mark.slow),
    pytest.param(32, 128, id="rpb128", marks=pytest.mark.slow),
    pytest.param(33, 257, id="rpb257"),
])
def test_seg_randomized_blocks_sizes(seed, rpb):
    schema, cpu, tpu, ht = setup(n=400, seed=seed, rows_per_block=rpb)
    assert_same_agg(cpu, tpu, read_ht=MAX_HT, aggregates=list(AGGS))
    assert_same_agg(cpu, tpu, read_ht=ht // 2, aggregates=list(AGGS))
