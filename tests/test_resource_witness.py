"""Runtime resource witness: pin attribution, lock-hold durations, and
the ``--witness-check`` cross-validation against the ires/ + iholds/
static facts.

Tier 1 runs the witness over a deterministic two-round fault sweep and
feeds the dump to ``python -m yugabyte_db_tpu.analysis --witness-check``
(must exit 0: no runtime leak, no hold pair the static pass doesn't
know).  Forged dumps — a leaked pin, a hold on an unsanctioned
(class, kind) pair — must exit 2.
"""

import json
import os
import tempfile
import threading

import pytest

from yugabyte_db_tpu.utils import locking, resources
from yugabyte_db_tpu.utils.locking import guarded_by


@pytest.fixture(autouse=True)
def _witness_reset():
    resources.witness().clear()
    yield
    resources.disable_resource_witness()
    resources.witness().clear()


def _witness_check(dump_path):
    from yugabyte_db_tpu.analysis.__main__ import main

    return main(["--witness-check", dump_path])


# -- pin attribution ----------------------------------------------------------

def test_pin_lifecycle_attributed_and_balanced():
    """Every residency pin is attributed to its acquire site + thread;
    a balanced acquire/release leaves nothing outstanding."""
    from yugabyte_db_tpu.storage.residency import HbmCache

    resources.enable_resource_witness()
    cache = HbmCache()

    class Owner:
        pass

    o = Owner()
    key = cache.register(o, label="plane")
    cache.pin(key, lambda: (object(), 256))
    out = resources.witness().outstanding()
    assert len(out) == 1
    rec = out[0]
    assert rec["key"] == f"plane#{key}"
    assert "test_resource_witness" in rec["site"]
    assert rec["thread"] == threading.current_thread().name
    cache.unpin(key)
    assert resources.witness().outstanding() == []
    w = resources.witness()
    assert w.pin_acquires == w.pin_releases == 1


def test_external_pins_are_not_leaks():
    """add_external entries are permanently pinned by design — excluded
    from the leak set, but counted."""
    from yugabyte_db_tpu.storage.residency import HbmCache

    resources.enable_resource_witness()
    cache = HbmCache()

    class Owner:
        pass

    o = Owner()
    cache.add_external(o, 512, label="mesh")
    assert resources.witness().outstanding() == []
    assert resources.witness().pin_acquires == 1


def test_entry_teardown_retires_all_pins():
    """invalidate() releases every pin on the key at once — balanced
    teardown, not a leak."""
    from yugabyte_db_tpu.storage.residency import HbmCache

    resources.enable_resource_witness()
    cache = HbmCache()

    class Owner:
        pass

    o = Owner()
    key = cache.register(o, label="run")
    cache.pin(key, lambda: (object(), 64))
    cache.acquire(key, lambda: (object(), 64), pin=True)
    assert len(resources.witness().outstanding()) == 2
    cache.invalidate(key)
    assert resources.witness().outstanding() == []


def test_real_leak_is_attributed(tmp_path):
    """A pin never released surfaces in the dump with its acquire site,
    and the dump contradicts the static clean bill (exit 2)."""
    from yugabyte_db_tpu.storage.residency import HbmCache

    resources.enable_resource_witness()
    cache = HbmCache()

    class Owner:
        pass

    o = Owner()
    key = cache.register(o, label="leaky")
    cache.pin(key, lambda: (object(), 64))   # never unpinned
    path = str(tmp_path / "leak.json")
    resources.dump_resource_witness(path)
    dump = json.load(open(path))
    assert dump["kind"] == "yb-resource-witness"
    (leak,) = dump["leaks"]
    assert leak["key"] == f"leaky#{key}"
    assert "test_resource_witness" in leak["site"]
    assert _witness_check(path) == 2
    del o  # keep the owner alive until after the dump


# -- lock-hold tracking -------------------------------------------------------

@guarded_by("_lock", "_n")
class _Demo:
    def __init__(self):
        self._lock = threading.RLock()
        self._n = 0

    def poke(self, blocking=None):
        with self._lock:
            self._n += 1
            if blocking:
                resources.note_blocking(blocking)


def test_hold_across_blocking_recorded_with_class():
    resources.enable_resource_witness()
    d = _Demo()  # constructed under the witness: guard lock wrapped
    d.poke(blocking="fsync")
    d.poke(blocking="fsync")
    d.poke()
    (h,) = resources.witness().holds()
    assert h["cls"] == "_Demo" and h["blocking"] == "fsync"
    assert h["count"] == 2
    assert "test_resource_witness" in h["site"]


def test_unsanctioned_hold_pair_contradicts(tmp_path, capsys):
    """No static hold site pairs (_Demo, fsync), so the runtime
    observation means the static pass missed a path: exit 2."""
    resources.enable_resource_witness()
    _Demo().poke(blocking="fsync")
    path = str(tmp_path / "hold.json")
    resources.dump_resource_witness(path)
    assert _witness_check(path) == 2
    out = capsys.readouterr().out
    assert "_Demo" in out and "no static hold site sanctions" in out


def test_sanctioned_hold_pair_is_consistent(tmp_path):
    """The WAL's segment roll-over fsyncs the old segment under
    ``Log._lock`` (a justified, suppressed hold) — the runtime pair
    (Log, fsync) is known to the static pass, so the check passes."""
    from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId

    resources.enable_resource_witness()
    with tempfile.TemporaryDirectory() as d:
        # Tiny segments: every append rolls, closing (flush+fsync) the
        # old segment inside append's critical section.
        log = Log(d, segment_bytes=1, fsync=True)
        for i in range(1, 4):
            log.append(LogEntry(OpId(1, i), i, "write", {"i": i}))
            log.sync()
        log.close()
    holds = {(h["cls"], h["blocking"])
             for h in resources.witness().holds()}
    assert ("Log", "fsync") in holds
    path = os.path.join(tempfile.gettempdir(), "wal_hold.json")
    resources.dump_resource_witness(path)
    try:
        assert _witness_check(path) == 0
    finally:
        os.unlink(path)


def test_group_commit_fsync_runs_unlocked():
    """The steady-state sync() path fsyncs OUTSIDE ``_lock`` (the
    group-commit shape) — no hold observation without a roll-over."""
    from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId

    resources.enable_resource_witness()
    with tempfile.TemporaryDirectory() as d:
        log = Log(d, fsync=True)  # default segments: no roll-over
        for i in range(1, 4):
            log.append(LogEntry(OpId(1, i), i, "write", {"i": i}))
            log.sync()
        holds = {(h["cls"], h["blocking"])
                 for h in resources.witness().holds()}
        assert ("Log", "fsync") not in holds
        log.close()


# -- metrics exposure ---------------------------------------------------------

def test_hold_histogram_and_counters_on_metrics_page():
    """yb_lock_hold_seconds{cls} and the witness counters render on a
    daemon /metrics scrape (they live on the process registry)."""
    import urllib.request

    from yugabyte_db_tpu.server.webserver import Webserver
    from yugabyte_db_tpu.utils.metrics import MetricRegistry

    resources.enable_resource_witness()
    d = _Demo()
    d.poke()                               # one hold interval observed
    resources.witness().pin_acquired(1, label="m")
    resources.witness().pin_released(1)
    ws = Webserver(MetricRegistry(), daemon_name="wit-test")
    host, port = ws.start()
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
    finally:
        ws.stop()
    assert "yb_lock_hold_seconds_bucket" in text
    assert 'cls="_Demo"' in text
    assert "yb_resource_pin_acquires" in text
    assert "yb_resource_pin_releases" in text


# -- dump-kind dispatch -------------------------------------------------------

def test_loader_rejects_other_dump_kinds(tmp_path):
    p = tmp_path / "lock.json"
    p.write_text(json.dumps({"kind": "yb-lock-witness",
                             "observations": []}))
    with pytest.raises(ValueError):
        resources.load_resource_witness_dump(str(p))


def test_witness_check_dispatches_all_three_kinds(tmp_path):
    """One CLI, three dump kinds: lock, compile, and resource dumps all
    route to their own static-fact comparison."""
    from yugabyte_db_tpu.utils import jitting

    lock_path = str(tmp_path / "lock.json")
    locking.enable_lock_witness()
    locking.dump_lock_witness(lock_path)
    locking.disable_lock_witness()

    compile_path = str(tmp_path / "compile.json")
    jitting.enable_compile_witness()
    jitting.dump_compile_witness(compile_path)
    jitting.disable_compile_witness()

    res_path = str(tmp_path / "res.json")
    resources.enable_resource_witness()
    resources.dump_resource_witness(res_path)

    for p in (lock_path, compile_path, res_path):
        assert _witness_check(p) == 0, p


def test_forged_leak_dump_exits_two(tmp_path, capsys):
    p = tmp_path / "forged_leak.json"
    p.write_text(json.dumps({
        "version": 1, "kind": "yb-resource-witness",
        "leaks": [{"key": "plane#9", "site": "engine.py:1",
                   "thread": "scan-0", "external": False}],
        "holds": [],
        "counters": {"pin_acquires": 1, "pin_releases": 0}}))
    assert _witness_check(str(p)) == 2
    out = capsys.readouterr().out
    assert "leaked pin `plane#9`" in out and "engine.py:1" in out


def test_forged_hold_dump_exits_two(tmp_path, capsys):
    p = tmp_path / "forged_hold.json"
    p.write_text(json.dumps({
        "version": 1, "kind": "yb-resource-witness",
        "leaks": [],
        "holds": [{"cls": "MetaCache", "blocking": "rpc", "count": 3,
                   "site": "meta_cache.py:50"}],
        "counters": {"pin_acquires": 0, "pin_releases": 0}}))
    assert _witness_check(str(p)) == 2
    out = capsys.readouterr().out
    assert "MetaCache" in out and "no static hold site sanctions" in out


# -- the tier-1 integration round ---------------------------------------------

def test_sweep_resource_witness_clean(tmp_path):
    """A deterministic two-round fault sweep under the resource witness:
    the dump shows no leaked pin and no unsanctioned hold, and
    ``--witness-check`` exits 0."""
    from yugabyte_db_tpu.integration.fault_sweep import FaultSweep

    path = str(tmp_path / "sweep_res.json")
    with tempfile.TemporaryDirectory() as root:
        summary = FaultSweep(root, seed=1234, ops_per_round=8,
                             schedule=("device_dispatch", "hbm_eviction"),
                             resource_witness_out=path).run()
    assert summary["rounds"] == 2
    dump = json.load(open(path))
    assert dump["kind"] == "yb-resource-witness"
    assert dump["leaks"] == []
    assert dump["counters"]["pin_acquires"] == \
        dump["counters"]["pin_releases"]
    assert _witness_check(path) == 0


@pytest.mark.slow
def test_randomized_sweep_resource_witness_clean(tmp_path):
    from yugabyte_db_tpu.integration.fault_sweep import run_sweep

    path = str(tmp_path / "rand_res.json")
    with tempfile.TemporaryDirectory() as root:
        run_sweep(root, seed=1977, rounds=8, ops_per_round=24,
                  resource_witness_out=path)
    assert _witness_check(path) == 0
