"""Operator tooling tests: yb-admin CLI, AdminClient, ysck checker.

Reference test analog: src/yb/tools/yb-admin-test.cc, ysck-test.cc +
ClusterVerifier usage across integration tests.
"""

import time

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.tools import AdminClient, Ysck

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
    ColumnSchema("v", DataType.INT64),
]


def wait_for(pred, timeout=10.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def load_rows(client, table, n):
    from yugabyte_db_tpu.client import YBSession
    s = YBSession(client)
    for i in range(n):
        s.insert(table, {"k": f"key{i % 7}", "r": i, "v": i * 3})
    return s.flush()


@pytest.fixture
def cluster(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    yield c
    c.shutdown()


def _admin(cluster) -> AdminClient:
    return AdminClient(cluster.transport.bind("admin"),
                       cluster.master_uuids)


def test_admin_listings_and_maintenance(cluster):
    client = cluster.client()
    table = client.create_table("adm", COLUMNS, num_tablets=2,
                                replication_factor=3)
    load_rows(client, table, 40)
    admin = _admin(cluster)

    names = [t["name"] for t in admin.list_tables()]
    assert "adm" in names
    servers = admin.list_tservers()
    assert len(servers) == 3 and all(d["alive"] for d in servers)
    locs = admin.table_locations("adm")
    assert len(locs) == 2
    for t in locs:
        assert len(t["replicas"]) == 3
    assert admin.flush_table("adm") == 2
    assert admin.compact_table("adm") == 2

    st = admin.tserver_status(servers[0]["uuid"])
    assert st["code"] == "ok" and st["tablets"]


def test_admin_leader_stepdown(cluster):
    client = cluster.client()
    client.create_table("sd", COLUMNS, num_tablets=1,
                        replication_factor=3)
    admin = _admin(cluster)
    t = admin.table_locations("sd")[0]
    tid = t["tablet_id"]

    def leader():
        info = admin.locate_tablet(tid)
        return info.get("leader")

    old = wait_for(leader, msg="initial leader")
    target = next(r["uuid"] for r in t["replicas"] if r["uuid"] != old)
    admin.leader_stepdown(tid, target)
    assert wait_for(lambda: leader() == target, timeout=15.0,
                    msg="leadership moved")


def test_ysck_clean_then_detects_divergence(cluster):
    client = cluster.client()
    table = client.create_table("chk", COLUMNS, num_tablets=2,
                                replication_factor=3)
    load_rows(client, table, 60)
    admin = _admin(cluster)
    ysck = Ysck(admin)

    report = ysck.check_cluster(["chk"])
    assert report.ok, report.summary()
    assert report.tservers_alive == 3
    assert len(report.tablet_checks) == 2
    assert sum(c.rows for c in report.tablet_checks) == 60

    # Diverge ONE follower replica out-of-band (bypassing Raft): an extra
    # visible row version only it can see.
    t = admin.table_locations("chk")[0]
    tid = t["tablet_id"]
    leader = admin.locate_tablet(tid)["leader"]
    victim = next(r["uuid"] for r in t["replicas"] if r["uuid"] != leader)
    peer = cluster.tservers[victim].tablet_manager.get(tid)
    ht = peer.tablet.clock.now().value
    kv = next({"k": f"key{i % 7}", "r": i} for i in range(60)
              if client.meta_cache.lookup_by_hash(
                  "chk", table.hash_code({"k": f"key{i % 7}"})
              ).tablet_id == tid)
    peer.tablet.engine.apply([RowVersion(
        table.encode_key(kv), ht=ht, liveness=False,
        columns={table.col_id["v"]: 999_999})])

    report = ysck.check_cluster(["chk"], timeout_s=3.0)
    assert not report.ok
    bad = [c for c in report.tablet_checks if not c.consistent]
    assert len(bad) == 1 and bad[0].tablet_id == tid
    assert "mismatch" in bad[0].detail


def test_fs_tool_offline_inspection(tmp_path, capsys):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=1).start()
    c.wait_tservers_registered()
    client = c.client()
    table = client.create_table("fsd", COLUMNS, num_tablets=1,
                                replication_factor=1)
    load_rows(client, table, 20)
    for ts in c.tservers.values():
        for p in ts.tablet_manager.peers():
            p.flush()
    c.shutdown()

    from yugabyte_db_tpu.tools import fs_tool
    infos = fs_tool.list_tablet_dirs(str(tmp_path))
    # 1 data tablet + 1 master sys-catalog
    data = [i for i in infos if i.get("runs", 0) > 0]
    assert data, infos
    t = data[0]
    assert t["wal_segments"] >= 1 and t["run_bytes"] > 0

    assert fs_tool.main(["list", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "tablet dir(s)" in out and t["tablet_id"] in out

    import glob
    run_file = glob.glob(f"{t['dir']}/runs/run-*.dat")[0]
    entries = list(fs_tool.iter_run_entries(run_file))
    assert sum(len(v) for _k, v in entries) == 20
    assert fs_tool.main(["dump_run", run_file, "-n", "3"]) == 0
    out = capsys.readouterr().out
    assert "PUT" in out and "key=" in out

    seg = glob.glob(f"{t['dir']}/wal/wal-*.seg")[0]
    recs = [r for r, e in fs_tool.iter_wal_records(seg) if e is None]
    assert any(r[3] == "write" for r in recs)
    assert fs_tool.main(["dump_wal", seg, "-n", "10"]) == 0
    out = capsys.readouterr().out
    assert "write" in out

    # corrupt the WAL tail: the dump reports it instead of crashing
    with open(seg, "r+b") as f:
        f.seek(-2, 2)
        f.write(b"\xff\xff")
    assert fs_tool.main(["dump_wal", seg, "-n", "100"]) == 1
    out = capsys.readouterr().out
    assert "CRC mismatch" in out or "torn record" in out

    # a truncated run file is reported, not a traceback
    with open(run_file, "r+b") as f:
        f.truncate(30)
    assert fs_tool.main(["dump_run", run_file]) == 1
    out = capsys.readouterr().out
    assert "corrupt run file" in out


def test_yb_admin_and_ysck_cli_over_sockets(tmp_path, capsys):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3,
                    transport="socket").start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("cli", COLUMNS, num_tablets=2,
                                    replication_factor=3)
        load_rows(client, table, 25)
        host, port = c.transport.address_book[c.master_uuids[0]]
        master = f"{host}:{port}"

        from yugabyte_db_tpu.tools import yb_admin, ysck
        assert yb_admin.main(["--master", master, "list_tables"]) == 0
        out = capsys.readouterr().out
        assert "cli" in out

        assert yb_admin.main(["--master", master,
                              "list_all_tablet_servers"]) == 0
        out = capsys.readouterr().out
        assert "ALIVE" in out and "ts-0" in out

        assert yb_admin.main(["--master", master, "list_tablets",
                              "cli"]) == 0
        out = capsys.readouterr().out
        assert out.count("ts-") >= 6  # 2 tablets x 3 replicas

        assert ysck.main(["--master", master, "--tables", "cli"]) == 0
        out = capsys.readouterr().out
        assert "ysck: OK" in out
    finally:
        c.shutdown()
