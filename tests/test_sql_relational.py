"""SQL relational operators above the storage seam: JOIN (inner/left,
multi-way), DISTINCT, HAVING, scalar / IN subqueries — and TPC-H Q3.

Reference capability: the full PostgreSQL executor running joins/sorts/
subplans above the FDW scan (src/postgres/src/backend/executor/
ybc_fdw.c:364); here the equivalent relational pipeline runs in
yql/pgsql/executor.py over predicate-pushdown scans. Every expected
result is computed independently in Python over the same data.
"""

import random

import pytest

from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.cql.processor import LocalCluster
from yugabyte_db_tpu.yql.pgsql import PgProcessor


@pytest.fixture
def pg():
    return PgProcessor(LocalCluster(num_tablets=3))


def setup_orders(pg):
    pg.execute("CREATE TABLE cust (ck INT PRIMARY KEY, name TEXT, "
               "seg TEXT)")
    pg.execute("CREATE TABLE ords (ok INT PRIMARY KEY, ck INT, "
               "total INT, day INT)")
    pg.execute("INSERT INTO cust (ck, name, seg) VALUES "
               "(1, 'alice', 'retail'), (2, 'bob', 'corp'), "
               "(3, 'carol', 'retail'), (4, 'dan', 'gov')")
    pg.execute("INSERT INTO ords (ok, ck, total, day) VALUES "
               "(10, 1, 100, 5), (11, 1, 250, 6), (12, 2, 70, 5), "
               "(13, 3, 300, 7), (14, 9, 40, 8)")  # ck=9: no customer


# -- joins -------------------------------------------------------------------

def test_inner_join_basic(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT c.name, o.total FROM cust c JOIN ords o ON c.ck = o.ck "
        "ORDER BY total")
    assert res.rows == [("bob", 70), ("alice", 100), ("alice", 250),
                        ("carol", 300)]


def test_inner_join_where_both_sides(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT c.name, o.total FROM cust c JOIN ords o ON c.ck = o.ck "
        "WHERE c.seg = 'retail' AND o.total > 150 ORDER BY o.total")
    assert res.rows == [("alice", 250), ("carol", 300)]


def test_left_join_nulls(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT c.name, o.ok FROM cust c LEFT JOIN ords o ON c.ck = o.ck "
        "ORDER BY name, ok")
    # dan has no orders -> NULL-extended row survives a LEFT JOIN
    assert ("dan", None) in res.rows
    assert len(res.rows) == 5


def test_left_join_where_on_right_filters_null_rows(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT c.name FROM cust c LEFT JOIN ords o ON c.ck = o.ck "
        "WHERE o.total > 0 ORDER BY name")
    # PG applies WHERE after the join: dan's NULL row is dropped.
    names = [r[0] for r in res.rows]
    assert "dan" not in names and len(res.rows) == 4


def test_join_unqualified_unambiguous(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT name, total FROM cust JOIN ords ON cust.ck = ords.ck "
        "WHERE total >= 250 ORDER BY total")
    assert res.rows == [("alice", 250), ("carol", 300)]


def test_join_ambiguous_bare_column_errors(pg):
    setup_orders(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT ck FROM cust JOIN ords ON cust.ck = ords.ck")


def test_join_aggregate_group_having(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT c.name, sum(o.total) AS t, count(*) AS n "
        "FROM cust c JOIN ords o ON c.ck = o.ck "
        "GROUP BY c.name HAVING sum(o.total) > 100 ORDER BY t DESC")
    assert res.columns == ["name", "t", "n"]
    assert res.rows == [("alice", 350, 2), ("carol", 300, 1)]


def test_three_way_join(pg):
    setup_orders(pg)
    pg.execute("CREATE TABLE items (ik INT PRIMARY KEY, ok INT, qty INT)")
    pg.execute("INSERT INTO items (ik, ok, qty) VALUES "
               "(100, 10, 2), (101, 10, 3), (102, 13, 1), (103, 12, 4)")
    res = pg.execute(
        "SELECT c.name, i.qty FROM cust c "
        "JOIN ords o ON c.ck = o.ck "
        "JOIN items i ON i.ok = o.ok "
        "ORDER BY c.name, i.qty")
    assert res.rows == [("alice", 2), ("alice", 3), ("bob", 4),
                        ("carol", 1)]


# -- DISTINCT ----------------------------------------------------------------

def test_distinct_rows(pg):
    setup_orders(pg)
    res = pg.execute("SELECT DISTINCT seg FROM cust ORDER BY seg")
    assert res.rows == [("corp",), ("gov",), ("retail",)]


def test_distinct_multi_column(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT DISTINCT ck, day FROM ords WHERE ck = 1 ORDER BY day")
    assert res.rows == [(1, 5), (1, 6)]


def test_distinct_order_by_hidden_errors(pg):
    setup_orders(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT DISTINCT seg FROM cust ORDER BY name")


# -- HAVING (single table, pushed-down partials) -----------------------------

def test_having_single_table(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ck, sum(total) AS t FROM ords GROUP BY ck "
        "HAVING sum(total) >= 300 ORDER BY ck")
    assert res.rows == [(1, 350), (3, 300)]


def test_having_agg_not_in_select(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ck FROM ords GROUP BY ck HAVING count(*) > 1")
    assert res.rows == [(1,)]


def test_having_avg_and_group_col(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ck, count(*) AS n FROM ords GROUP BY ck "
        "HAVING avg(total) > 100 AND ck < 5 ORDER BY ck")
    assert res.rows == [(1, 2), (3, 1)]


# -- subqueries --------------------------------------------------------------

def test_scalar_subquery(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ok FROM ords WHERE total = "
        "(SELECT max(total) FROM ords)")
    assert res.rows == [(13,)]


def test_in_subquery(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ok FROM ords WHERE ck IN "
        "(SELECT ck FROM cust WHERE seg = 'retail') ORDER BY ok")
    assert res.rows == [(10,), (11,), (13,)]


def test_scalar_subquery_null_matches_nothing(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT ok FROM ords WHERE total < "
        "(SELECT min(total) FROM ords WHERE ck = 42)")
    assert res.rows == []


def test_scalar_subquery_multi_row_errors(pg):
    setup_orders(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT ok FROM ords WHERE total = "
                   "(SELECT total FROM ords)")


# -- TPC-H Q3 ----------------------------------------------------------------

def test_tpch_q3(pg):
    """Q3: 3-way join + predicate on each table + grouped revenue +
    ORDER BY revenue DESC, date + LIMIT. Expected result computed
    independently over the generated rows."""
    rnd = random.Random(42)
    pg.execute("CREATE TABLE customer (c_custkey INT PRIMARY KEY, "
               "c_mktsegment TEXT)")
    pg.execute("CREATE TABLE orders (o_orderkey INT PRIMARY KEY, "
               "o_custkey INT, o_orderdate INT, o_shippriority INT)")
    pg.execute("CREATE TABLE lineitem (l_linekey INT PRIMARY KEY, "
               "l_orderkey INT, l_extendedprice INT, l_discount INT, "
               "l_shipdate INT)")
    segs = ["BUILDING", "AUTOMOBILE", "MACHINERY"]
    customers = [(ck, rnd.choice(segs)) for ck in range(1, 31)]
    orders = [(ok, rnd.randrange(1, 31), rnd.randrange(9000, 9200),
               rnd.randrange(3)) for ok in range(1, 81)]
    lineitems = [(lk, rnd.randrange(1, 81), rnd.randrange(1000, 90000),
                  rnd.randrange(0, 11), rnd.randrange(9000, 9200))
                 for lk in range(1, 241)]
    for ck, seg in customers:
        pg.execute(f"INSERT INTO customer (c_custkey, c_mktsegment) "
                   f"VALUES ({ck}, '{seg}')")
    for ok, ck, d, pr in orders:
        pg.execute(f"INSERT INTO orders (o_orderkey, o_custkey, "
                   f"o_orderdate, o_shippriority) "
                   f"VALUES ({ok}, {ck}, {d}, {pr})")
    for lk, ok, price, disc, sd in lineitems:
        pg.execute(f"INSERT INTO lineitem (l_linekey, l_orderkey, "
                   f"l_extendedprice, l_discount, l_shipdate) "
                   f"VALUES ({lk}, {ok}, {price}, {disc}, {sd})")

    CUT = 9100
    res = pg.execute(
        "SELECT l.l_orderkey, "
        "sum(l.l_extendedprice * (100 - l.l_discount)) AS revenue, "
        "o.o_orderdate, o.o_shippriority "
        "FROM customer c "
        "JOIN orders o ON c.c_custkey = o.o_custkey "
        "JOIN lineitem l ON l.l_orderkey = o.o_orderkey "
        f"WHERE c.c_mktsegment = 'BUILDING' AND o.o_orderdate < {CUT} "
        f"AND l.l_shipdate > {CUT} "
        "GROUP BY l.l_orderkey, o.o_orderdate, o.o_shippriority "
        "ORDER BY revenue DESC, o_orderdate LIMIT 10")
    assert res.columns == ["l_orderkey", "revenue", "o_orderdate",
                           "o_shippriority"]

    # Independent oracle (plain Python over the same tuples).
    seg_of = dict(customers)
    odict = {ok: (ck, d, pr) for ok, ck, d, pr in orders}
    agg: dict = {}
    for lk, ok, price, disc, sd in lineitems:
        o = odict.get(ok)
        if o is None or sd <= CUT:
            continue
        ck, d, pr = o
        if seg_of.get(ck) != "BUILDING" or d >= CUT:
            continue
        key = (ok, d, pr)
        agg[key] = agg.get(key, 0) + price * (100 - disc)
    expect = sorted(((ok, rev, d, pr) for (ok, d, pr), rev in agg.items()),
                    key=lambda r: (-r[1], r[2]))[:10]
    assert res.rows == expect


def test_qualified_single_table(pg):
    setup_orders(pg)
    res = pg.execute(
        "SELECT o.ok FROM ords o WHERE o.total > 200 ORDER BY o.ok")
    assert res.rows == [(11,), (13,)]
