"""Raft consensus tests: election, replication, failover, divergence,
restart recovery, membership change.

Reference test analog: src/yb/consensus/raft_consensus-test.cc and
raft_consensus-itest.cc (kill/restart via ExternalMiniCluster; here via
LocalTransport isolation — same black-box effect, one process).
"""

import time

import pytest

from yugabyte_db_tpu.consensus import (LocalTransport, NotLeader, RaftOptions)
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec
from yugabyte_db_tpu.tablet import TabletMetadata, TabletPeer

FAST = RaftOptions(election_timeout_s=0.15, heartbeat_interval_s=0.03,
                   lease_s=0.4, rpc_timeout_s=0.5)


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
    ], table_id="t")


def enc(schema, k):
    return schema.encode_primary_key({"k": k}, compute_hash_code(schema, {"k": k}))


def wait_for(pred, timeout=5.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class Group:
    """A 3-replica (by default) raft group over a LocalTransport."""

    def __init__(self, tmp_path, n=3, engine="cpu"):
        self.schema = make_schema()
        self.transport = LocalTransport()
        self.tmp_path = tmp_path
        self.nodes = [f"node-{i}" for i in range(n)]
        self.peers = {}
        for uuid in self.nodes:
            self.start_node(uuid)

    def start_node(self, uuid):
        meta = TabletMetadata("tablet-1", "t", self.schema, 0, 65536)
        root = str(self.tmp_path / uuid)
        peer = TabletPeer(uuid, meta, root, self.transport.bind(uuid),
                          self.nodes, fsync=False, raft_opts=FAST)
        self.transport.register(uuid, lambda m, p, _pr=peer: _pr.raft.handle(m, p))
        self.peers[uuid] = peer
        peer.start()
        return peer

    def stop_node(self, uuid):
        self.transport.unregister(uuid)
        self.peers.pop(uuid).shutdown()

    def leader(self):
        return wait_for(
            lambda: next((p for p in self.peers.values()
                          if p.raft.is_leader() and p.raft.has_lease()), None),
            msg="leader election")

    def shutdown(self):
        for p in list(self.peers.values()):
            p.shutdown()

    def row(self, k, v):
        cid = {c.name: c.col_id for c in self.schema.columns}
        return RowVersion(enc(self.schema, k), ht=0, liveness=True,
                          columns={cid["v"]: v})

    def read_all(self, peer):
        res = peer.scan(ScanSpec(read_ht=peer.tablet.clock.now().value),
                        allow_stale=True)
        return sorted(res.rows)


@pytest.fixture
def group(tmp_path):
    g = Group(tmp_path)
    yield g
    g.shutdown()


def test_elects_single_leader_and_replicates(group):
    leader = group.leader()
    for i in range(20):
        leader.write([group.row(f"k{i}", i)])
    want = group.read_all(leader)
    assert len(want) == 20
    for uuid, p in group.peers.items():
        wait_for(lambda p=p: p.raft.stats()["applied_index"]
                 >= leader.raft.stats()["applied_index"],
                 msg=f"{uuid} catchup")
        assert group.read_all(p) == want


def test_only_leader_accepts_writes(group):
    leader = group.leader()
    follower = next(p for p in group.peers.values() if p is not leader)
    with pytest.raises(NotLeader) as ei:
        follower.write([group.row("x", 1)])
    assert ei.value.leader_hint == leader.node_uuid


def test_leader_failover_and_rejoin(group):
    leader = group.leader()
    leader.write([group.row("a", 1)])
    group.transport.isolate(leader.node_uuid)
    new_leader = wait_for(
        lambda: next((p for p in group.peers.values()
                      if p is not leader and p.raft.is_leader()
                      and p.raft.has_lease()), None),
        msg="new leader after isolation")
    new_leader.write([group.row("b", 2)])
    # Old leader no longer holds a lease, so it refuses reads.
    wait_for(lambda: not leader.raft.has_lease(), msg="old lease expiry")
    with pytest.raises(NotLeader):
        leader.scan(ScanSpec())
    # Heal: old leader steps down to follower and catches up.
    group.transport.heal(leader.node_uuid)
    wait_for(lambda: not leader.raft.is_leader(), msg="old leader steps down")
    wait_for(lambda: group.read_all(leader) == group.read_all(new_leader),
             msg="old leader catches up")
    assert len(group.read_all(leader)) == 2


def test_divergent_suffix_truncated(group):
    """A partitioned leader's uncommitted writes are erased on rejoin."""
    leader = group.leader()
    leader.write([group.row("committed", 1)])
    others = [p for p in group.peers.values() if p is not leader]
    group.transport.isolate(leader.node_uuid)
    # This write can't commit (no majority): it lands in the old leader's
    # log only. Use a short timeout.
    with pytest.raises((TimeoutError, NotLeader)):
        leader.write([group.row("orphan", 9)], timeout=0.4)
    new_leader = wait_for(
        lambda: next((p for p in others if p.raft.is_leader()), None),
        msg="new leader")
    new_leader.write([group.row("winner", 2)])
    group.transport.heal(leader.node_uuid)
    wait_for(lambda: sorted(group.read_all(leader))
             == sorted(group.read_all(new_leader)),
             msg="rejoined log convergence")
    keys = group.read_all(leader)
    assert len(keys) == 2  # committed + winner, no orphan


def test_restart_recovers_data(group):
    leader = group.leader()
    for i in range(10):
        leader.write([group.row(f"k{i}", i)])
    want = group.read_all(leader)
    for uuid in list(group.peers):
        group.stop_node(uuid)
    for uuid in group.nodes:
        group.start_node(uuid)
    leader2 = group.leader()
    assert group.read_all(leader2) == want


def test_change_config_add_then_remove(group, tmp_path):
    leader = group.leader()
    for i in range(5):
        leader.write([group.row(f"k{i}", i)])
    # Add a fourth, empty peer; it must catch up from index 1.
    new_uuid = "node-3"
    meta = TabletMetadata("tablet-1", "t", group.schema, 0, 65536)
    new_peer = TabletPeer(new_uuid, meta, str(tmp_path / new_uuid),
                          group.transport.bind(new_uuid),
                          group.nodes + [new_uuid], fsync=False,
                          raft_opts=FAST)
    group.transport.register(new_uuid,
                             lambda m, p: new_peer.raft.handle(m, p))
    group.peers[new_uuid] = new_peer
    new_peer.start()
    leader.raft.change_config(group.nodes + [new_uuid])
    wait_for(lambda: group.read_all(new_peer) == group.read_all(leader),
             msg="new peer catchup")
    assert leader.raft.stats()["config"]["peers"] == group.nodes + [new_uuid]
    # Remove it again; it stops being part of majorities.
    leader.raft.change_config(group.nodes)
    wait_for(lambda: leader.raft.stats()["config"]["peers"] == group.nodes,
             msg="config shrink commit")
    leader.write([group.row("after-shrink", 7)])


def test_rf1_instant_leadership(tmp_path):
    g = Group(tmp_path, n=1)
    try:
        leader = g.leader()
        # Writes are accepted once the own-term no-op applies
        # (leader_ready) — the exactly-once dedup registry completeness
        # guarantee; briefly rejected writes surface as NotLeader, which
        # cluster clients retry.
        deadline = time.monotonic() + 5.0
        while True:
            try:
                leader.write([g.row("solo", 1)])
                break
            except NotLeader:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        assert len(g.read_all(leader)) == 1
    finally:
        g.shutdown()


def test_no_progress_without_majority(group):
    leader = group.leader()
    for p in group.peers.values():
        if p is not leader:
            group.transport.isolate(p.node_uuid)
    with pytest.raises((TimeoutError, NotLeader)):
        leader.write([group.row("stuck", 1)], timeout=0.4)
    group.transport.heal()
    # After healing, the group makes progress again (any leader).
    def can_write():
        for p in group.peers.values():
            try:
                p.write([group.row("ok", 2)], timeout=1.0)
                return True
            except (NotLeader, TimeoutError):
                continue
        return False
    wait_for(can_write, timeout=10.0, msg="post-heal write")


def test_message_borne_lease_expires_when_isolated(group):
    """The leader holds its lease only while a majority's explicit
    grants (shipped in AppendEntries, echoed in acks) are running;
    isolating it must drop has_lease within one lease window
    (reference: leader_lease.h message-borne leases)."""
    leader = group.leader()
    assert leader.raft.has_lease()
    group.transport.isolate(leader.node_uuid)
    # grants were measured from send time: within effective_lease_s the
    # isolated leader must stop serving lease reads
    wait_for(lambda: not leader.raft.has_lease(), timeout=3.0,
             msg="lease expiry after isolation")
    # and the remaining majority elects a replacement only AFTER their
    # promises to the old leader expired — there is never a moment with
    # two lease-holding leaders
    new = wait_for(
        lambda: next((p for p in group.peers.values()
                      if p.node_uuid != leader.node_uuid
                      and p.raft.is_leader() and p.raft.has_lease()),
                     None), timeout=5.0, msg="replacement leader")
    assert not leader.raft.has_lease()
    group.transport.heal()
    wait_for(lambda: not leader.raft.is_leader(), timeout=5.0,
             msg="old leader steps down")
    assert new.raft.has_lease()


def test_wall_clock_jump_does_not_affect_leases_or_order(group, monkeypatch):
    """Jump one node's WALL clock far ahead: leases (monotonic-duration
    arithmetic) must be unaffected, and hybrid-time causality must hold
    — writes after the jump get larger hybrid times everywhere
    (reference: SkewedClock tests, clock_synchronization-itest.cc)."""
    import yugabyte_db_tpu.utils.hybrid_time as HT

    leader = group.leader()
    ht1 = leader.write([group.row("before-jump", 1)])

    # jump the wall clock +1 hour for every NEW physical reading
    real_time = HT.time.time
    monkeypatch.setattr(HT.time, "time", lambda: real_time() + 3600.0)

    assert leader.raft.has_lease()  # monotonic lease unaffected
    ht2 = leader.write([group.row("after-jump", 2)])
    assert ht2.value > ht1.value
    # followers ratchet to the jumped clock through message hybrid times
    # (causality), so a failover cannot go back in time
    wait_for(lambda: all(
        p.tablet.clock.now().value > ht2.value
        for p in group.peers.values()), timeout=3.0,
        msg="clock propagation")

    # restore the wall clock: hybrid time must NEVER regress
    monkeypatch.setattr(HT.time, "time", real_time)
    ht3 = leader.write([group.row("after-restore", 3)])
    assert ht3.value > ht2.value
    assert leader.raft.has_lease()
