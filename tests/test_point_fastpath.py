"""Point-get fast paths vs the generic scan: both engines' scan_batch
must return byte-identical results to per-spec scan() on exact-key
ranges, across memtable/run mixes, tombstones, TTL, predicates, and
both point-range spellings (key+0xff and prefix_successor)."""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import prefix_successor
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import Predicate, RowVersion, ScanSpec, make_engine
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def make_world(engine_name, n=300, seed=21):
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
        ColumnSchema("s", DataType.STRING),
    ], table_id="pf")
    eng = make_engine(engine_name, schema, {"rows_per_block": 32})
    cid = {c.name: c.col_id for c in schema.value_columns}
    rng = random.Random(seed)
    ht = 10
    keys = []
    for i in range(n):
        ht += 1
        key = schema.encode_primary_key(
            {"k": f"q{i:04d}"}, compute_hash_code(schema, {"k": f"q{i:04d}"}))
        keys.append(key)
        eng.apply([RowVersion(key, ht=ht, liveness=True, columns={
            cid["v"]: i, cid["s"]: f"s{i}"})])
    eng.flush()
    # second run + live memtable with updates/tombstones/TTL
    for i in range(0, n, 3):
        ht += 1
        eng.apply([RowVersion(keys[i], ht=ht,
                              columns={cid["v"]: i * 10})])
    eng.flush()
    for i in range(0, n, 5):
        ht += 1
        if i % 15 == 0:
            eng.apply([RowVersion(keys[i], ht=ht, tombstone=True)])
        else:
            eng.apply([RowVersion(keys[i], ht=ht, liveness=True,
                                  columns={cid["v"]: -i},
                                  expire_ht=ht + 2)])
    return schema, eng, keys, ht


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
@pytest.mark.parametrize("shape", ["ff", "succ"])
def test_point_fastpath_matches_generic(engine, shape):
    schema, eng, keys, ht = make_world(engine)
    rng = random.Random(4)
    sel = [keys[rng.randrange(len(keys))] for _ in range(60)]
    sel.append(schema.encode_primary_key(
        {"k": "zz-absent"},
        compute_hash_code(schema, {"k": "zz-absent"})))  # missing key
    specs = []
    for key in sel:
        upper = key + b"\xff" if shape == "ff" else prefix_successor(key)
        for rht, limit, preds in ((ht + 1, 1, []),
                                  (ht - 3, None, []),
                                  (ht + 1, 1, [Predicate("v", ">=", 0)])):
            specs.append(ScanSpec(lower=key, upper=upper, read_ht=rht,
                                  limit=limit, predicates=list(preds),
                                  projection=["k", "v", "s"]))
    fast = eng.scan_batch(specs)
    for spec, f in zip(specs, fast):
        g = eng.scan(spec)
        assert f.rows == g.rows, spec.lower
        assert f.resume_key == g.resume_key
        assert f.rows_scanned == g.rows_scanned


def test_cpu_vs_tpu_point_parity():
    _, cpu, keys, ht = make_world("cpu")
    _, tpu, _, _ = make_world("tpu")
    specs = [ScanSpec(lower=k, upper=prefix_successor(k),
                      read_ht=ht + 1, limit=1) for k in keys[:80]]
    a = cpu.scan_batch(specs)
    b = tpu.scan_batch(specs)
    assert [r.rows for r in a] == [r.rows for r in b]
