"""Tracing tests: Trace/TRACE plumbing, RpczStore sampling, /rpcz
endpoint over the embedded webserver.

Reference test analog: src/yb/util/trace-test.cc + the rpcz handler of
src/yb/server/rpcz-path-handler.cc.
"""

import json
import threading
import urllib.request

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.utils.trace import (TRACE, RpczStore, Trace,
                                         trace_request)

COLUMNS = [
    ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("v", DataType.INT64),
]


def test_trace_collects_messages_below_dispatch():
    def nested():
        TRACE("deep %d", 42)

    with trace_request("svc.method") as t:
        TRACE("start")
        nested()
    assert t.duration_us >= 0
    msgs = [m for _dt, m in t.entries]
    assert msgs == ["start", "deep 42"]
    d = t.dump()
    assert d["method"] == "svc.method" and len(d["messages"]) == 2


def test_trace_without_active_request_is_noop():
    TRACE("nobody listening")  # must not raise


def test_trace_is_context_isolated():
    errs = []

    def worker(i):
        with trace_request(f"m{i}") as t:
            for j in range(10):
                TRACE(f"w{i}-{j}")
        if [m for _d, m in t.entries] != [f"w{i}-{j}" for j in range(10)]:
            errs.append(i)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs


def test_trace_message_cap():
    with trace_request("m") as t:
        for i in range(200):
            TRACE(f"msg{i}")
    assert len(t.entries) == 64
    assert t.dump()["dropped_messages"] == 136


def test_rpcz_store_recent_and_slow():
    store = RpczStore(recent_per_method=2, slow_threshold_us=1000)
    for i in range(5):
        t = Trace("a.b")
        t.finish()
        store.record(t)
    slow = Trace("a.b")
    slow.finish()
    slow.duration_us = 5000
    store.record(slow)
    d = store.dump()
    assert len(d["methods"]["a.b"]) == 2  # bounded per method
    assert len(d["slow"]) == 1 and d["slow"][0]["duration_us"] == 5000


def test_rpcz_endpoint_serves_request_traces(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=1).start()
    try:
        c.wait_tservers_registered()
        client = c.client()
        table = client.create_table("tr", COLUMNS, num_tablets=1,
                                    replication_factor=1)
        from yugabyte_db_tpu.client import YBSession
        s = YBSession(client)
        s.insert(table, {"k": "a", "v": 1})
        s.flush()
        from yugabyte_db_tpu.storage.scan_spec import ScanSpec
        s.scan(table, ScanSpec())

        addrs = c.start_webservers()
        ts_uuid = next(iter(c.tservers))
        host, port = addrs[ts_uuid]
        with urllib.request.urlopen(
                f"http://{host}:{port}/rpcz", timeout=5) as r:
            d = json.load(r)
        # The session's write pipeline admits via ts.write_admit
        # (two-phase); ts.write remains the one-shot form.
        assert "ts.write_admit" in d["methods"]
        assert "ts.scan" in d["methods"]
        write_sample = d["methods"]["ts.write_admit"][-1]
        assert write_sample["duration_us"] >= 0
        assert any("stamped" in m for m in write_sample["messages"])
        scan_sample = d["methods"]["ts.scan"][-1]
        assert any("row(s)" in m for m in scan_sample["messages"])
    finally:
        c.shutdown()


def test_trace_events_and_stacks():
    from yugabyte_db_tpu.utils.trace import (TRACE_EVENTS, dump_stacks,
                                             trace_event)

    with trace_event("unit-span", tablet="t1"):
        pass
    events = TRACE_EVENTS.dump()["traceEvents"]
    mine = [e for e in events if e["name"] == "unit-span"]
    assert mine and mine[-1]["ph"] == "X" and mine[-1]["dur"] >= 0
    assert mine[-1]["args"] == {"tablet": "t1"}
    stacks = dump_stacks()
    assert "MainThread" in stacks and "test_trace_events_and_stacks" in stacks


def test_tracing_json_over_http():
    import json
    import urllib.request

    from yugabyte_db_tpu.utils.metrics import MetricRegistry
    from yugabyte_db_tpu.server.webserver import Webserver

    ws = Webserver(MetricRegistry(), "trace-test")
    host, port = ws.start()
    try:
        data = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/tracing.json", timeout=5).read())
        assert "traceEvents" in data
        stacks = urllib.request.urlopen(
            f"http://{host}:{port}/stacks", timeout=5).read().decode()
        assert "thread" in stacks
    finally:
        ws.stop()
