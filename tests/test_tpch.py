"""TPC-H Q1/Q6 end-to-end: engine-level pushdown and the SQL frontend.

Reference analog: the YSQL scan path (ybc_fdw.c -> PgsqlReadOperation)
running TPC-H's scan-heavy queries — BASELINE config 3.
"""

import pytest

from yugabyte_db_tpu.storage import make_engine
from yugabyte_db_tpu.yql.pgsql import tpch
from yugabyte_db_tpu.yql.pgsql.operations import PgsqlReadOp
from yugabyte_db_tpu.yql.cql.processor import LocalCluster, QLProcessor

N = 6000


@pytest.fixture(scope="module")
def engines():
    schema = tpch.lineitem_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema)
    ht1 = tpch.load_engine(cpu, schema, N)
    ht2 = tpch.load_engine(tpu, schema, N)
    assert ht1 == ht2
    return cpu, tpu, ht1


def test_q1_engine_matches_oracle(engines):
    cpu, tpu, ht = engines
    spec = tpch.q1_spec(ht + 1)
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.columns == b.columns
    assert a.rows == b.rows
    rows = tpch.q1_result(b)
    assert {(r["l_returnflag"], r["l_linestatus"]) for r in rows} == {
        ("A", "F"), ("R", "F"), ("N", "F"), ("N", "O")}
    for r in rows:
        assert r["sum_disc_price"] < r["sum_base_price"]
        assert r["sum_charge"] > r["sum_disc_price"]
        assert r["count_order"] > 0


def test_q6_engine_matches_oracle(engines):
    cpu, tpu, ht = engines
    spec = tpch.q6_spec(ht + 1)
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows
    assert tpch.q6_result(b) > 0


def test_q1_partitioned_combine(engines):
    """Multi-tablet shape: partials from range-split scans combine to the
    single-scan answer."""
    cpu, tpu, ht = engines
    spec = tpch.q1_spec(ht + 1)
    whole = tpu.scan(spec)
    # emulate 2 tablets by splitting the key range at a run midpoint
    crun = tpu.runs[0].crun
    mid_key = crun.key_at(crun.total_rows() // 2)
    import dataclasses
    left = dataclasses.replace(spec, upper=mid_key)
    right = dataclasses.replace(spec, lower=mid_key)
    from yugabyte_db_tpu.yql.pgsql.operations import combine_grouped
    combined = combine_grouped(spec, [tpu.scan(left), tpu.scan(right)])
    assert combined.rows == whole.rows


def test_q1_q6_through_sql_frontend():
    cluster = LocalCluster(num_tablets=4)
    try:
        ql = QLProcessor(cluster)
        cols = ", ".join(
            f"{c.name} {c.dtype.name}" for c in tpch.LINEITEM_COLUMNS)
        ql.execute(
            "CREATE TABLE lineitem (" + cols +
            ", PRIMARY KEY ((l_orderkey), l_linenumber))")
        handle = cluster.table("default.lineitem")
        rows = list(tpch.generate_lineitem(1500))
        for r in rows:
            names = ", ".join(r)
            vals = ", ".join(
                f"'{v}'" if isinstance(v, str) else str(v)
                for v in r.values())
            ql.execute(f"INSERT INTO lineitem ({names}) VALUES ({vals})")
        res = ql.execute(tpch.q1_sql())
        assert res.columns[:2] == ["l_returnflag", "l_linestatus"]
        assert [r[:2] for r in res.rows] == sorted(r[:2] for r in res.rows)
        # oracle recomputation in python
        cutoff = 10471
        want = {}
        for r in rows:
            if r["l_shipdate"] > cutoff:
                continue
            k = (r["l_returnflag"], r["l_linestatus"])
            acc = want.setdefault(k, [0, 0, 0, 0, 0])
            acc[0] += r["l_quantity"]
            acc[1] += r["l_extendedprice"]
            acc[2] += r["l_extendedprice"] * (100 - r["l_discount"])
            acc[3] += (r["l_extendedprice"] * (100 - r["l_discount"])
                       * (100 + r["l_tax"]))
            acc[4] += 1
        for row in res.rows:
            k = (row[0], row[1])
            acc = want[k]
            assert row[2] == acc[0]          # sum_qty
            assert row[3] == acc[1]          # sum_base_price
            assert row[4] == acc[2]          # sum_disc_price
            assert row[5] == acc[3]          # sum_charge
            assert row[8] == acc[4]          # count_order
            assert row[6] == acc[0] / acc[4]  # avg_qty
        res6 = ql.execute(tpch.q6_sql())
        want6 = sum(
            r["l_extendedprice"] * r["l_discount"] for r in rows
            if 9131 <= r["l_shipdate"] < 9131 + 365
            and 5 <= r["l_discount"] <= 7 and r["l_quantity"] < 24)
        assert res6.rows[0][0] == want6
    finally:
        cluster.close()
