"""Hashed-prefix bloom pruning for point gets (storage.bloom).

Reference analog: DocDbAwareFilterPolicy (src/yb/docdb/doc_key.h:
551-575) — without it every point get pays one seek per overlapping
sorted run; with it the per-run filter keeps point-get cost independent
of run count.
"""

import random

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import (GROUP_END, hashed_prefix,
                                             prefix_successor)
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.storage.bloom import BloomFilter
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("v", DataType.INT64),
    ], table_id="bp")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def test_hashed_prefix_extraction():
    schema = make_schema()
    a0 = enc(schema, "alpha", 0)
    a9 = enc(schema, "alpha", 9)
    b0 = enc(schema, "beta", 0)
    hp_a0, hp_a9, hp_b0 = map(hashed_prefix, (a0, a9, b0))
    # Same hash components -> same prefix regardless of range columns.
    assert hp_a0 == hp_a9 != hp_b0
    assert a0.startswith(hp_a0) and b0.startswith(hp_b0)
    assert hp_a0[-1] == GROUP_END
    # Range-partitioned (no hash section) keys have no prefix.
    assert hashed_prefix(b"\x02abc") == b""


def test_bloom_no_false_negatives():
    bl = BloomFilter(1000)
    items = [f"item{i}".encode() for i in range(1000)]
    for it in items:
        bl.add(it)
    assert all(bl.may_contain(it) for it in items)
    # FP rate sanity: ~1% expected, allow generous slack.
    fps = sum(bl.may_contain(f"other{i}".encode()) for i in range(2000))
    assert fps < 2000 * 0.05, fps


def _load_many_runs(engine, schema, n_runs=12, keys_per_run=200):
    """Each run gets its own disjoint key set; hash codes interleave so
    min/max key ranges of all runs overlap (min/max pruning is useless,
    only the bloom can skip runs)."""
    ht = 0
    cid = {c.name: c.col_id for c in schema.columns}
    all_keys = []
    for run in range(n_runs):
        rows = []
        for i in range(keys_per_run):
            name = f"u{run:02d}x{i:04d}"
            key = enc(schema, name, i % 5)
            ht += 1
            rows.append(RowVersion(key, ht=ht, liveness=True,
                                   columns={cid["v"]: run * 10000 + i}))
            all_keys.append((name, i % 5, key, run * 10000 + i))
        engine.apply(rows)
        engine.flush()
    return all_keys, ht


def test_point_get_prunes_runs():
    schema = make_schema()
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    all_keys, ht = _load_many_runs(tpu, schema)
    assert len(tpu.runs) == 12
    rnd = random.Random(9)
    checked = scanned_total = 0
    for name, r, key, want_v in rnd.sample(all_keys, 60):
        spec = ScanSpec(lower=key, upper=key + b"\x00", read_ht=ht + 1)
        overlapping = tpu._overlapping_runs(spec)
        scanned_total += len(overlapping)
        checked += 1
        res = tpu.scan(spec)
        assert len(res.rows) == 1 and res.rows[0][2] == want_v, name
    # Without the bloom every get would touch all 12 runs (min/max
    # ranges fully overlap); with it, ~1 (+ rare false positives).
    assert scanned_total / checked < 2.0, scanned_total / checked


def test_missing_key_scans_zero_runs_mostly():
    schema = make_schema()
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    _keys, ht = _load_many_runs(tpu, schema, n_runs=8)
    rnd = random.Random(4)
    total = 0
    for i in range(50):
        key = enc(schema, f"missing{i:05d}", 0)
        spec = ScanSpec(lower=key, upper=key + b"\x00", read_ht=ht + 1)
        total += len(tpu._overlapping_runs(spec))
        assert tpu.scan(spec).rows == []
    assert total < 50 * 1.0, total   # ~all pruned; fp slack


def test_single_key_range_scan_pruned_and_correct():
    """All versions/rows under ONE primary key: same hashed prefix, so
    the bloom applies to the whole range scan, not just point gets."""
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    _keys, ht = _load_many_runs(tpu, schema, n_runs=6, keys_per_run=50)
    _keys2, ht2 = _load_many_runs(cpu, schema, n_runs=6, keys_per_run=50)
    lo = enc(schema, "u03x0007", 0)[:0]  # build prefix via encoding
    from yugabyte_db_tpu.models.encoding import (encode_doc_key_prefix)

    hc = compute_hash_code(schema, {"k": "u03x0007"})
    prefix = encode_doc_key_prefix(hc, [("u03x0007", DataType.STRING)], [])
    spec = ScanSpec(lower=prefix, upper=prefix_successor(prefix),
                    read_ht=max(ht, ht2) + 1)
    assert len(tpu._overlapping_runs(spec)) <= 2
    a = cpu.scan(spec)
    b = tpu.scan(spec)
    assert a.rows == b.rows and len(b.rows) == 1


def test_bloom_survives_compaction_and_restore():
    schema = make_schema()
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    all_keys, ht = _load_many_runs(tpu, schema, n_runs=4)
    tpu.compact(history_cutoff_ht=0)
    name, r, key, want_v = all_keys[100]
    spec = ScanSpec(lower=key, upper=key + b"\x00", read_ht=ht + 1)
    res = tpu.scan(spec)
    assert res.rows[0][2] == want_v
    assert len(tpu._overlapping_runs(spec)) == 1
