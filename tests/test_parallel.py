"""Sharded multi-tablet aggregate vs the CPU oracle.

The mesh-parallel combine (psum / lexicographic pmax over the ("t", "b")
mesh) must produce exactly what a single CPU engine holding the union of
all tablets' rows produces — the multi-tablet analog of the engine-diff
tests, and the test for BASELINE config 5 (the reference merges per-tablet
aggregate partials client-side: src/yb/yql/cql/ql/exec/eval_aggr.cc).

Runs on 8 virtual CPU devices (conftest) as a 4-tablet x 2-block-shard mesh.
"""

import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.parallel import ShardedTablets, sharded_aggregate
from yugabyte_db_tpu.storage import (
    AggSpec, Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage.memtable import MemTable
from yugabyte_db_tpu.storage.row_version import MAX_HT

pytestmark = pytest.mark.mesh


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def build_world(seed, num_tablets=4, num_keys=400, rows_per_block=16):
    """Random MVCC history distributed round-robin over tablets; returns
    (runs, oracle_engine, all_keys_sorted, max_ht)."""
    rng = random.Random(seed)
    schema = make_schema()
    oracle = make_engine("cpu", schema)
    mems = [MemTable() for _ in range(num_tablets)]
    cid = {c.name: c.col_id for c in schema.columns}
    ht = 100
    keys = []
    for i in range(num_keys):
        key = enc(schema, f"user{i:05d}", rng.randrange(10))
        keys.append(key)
        t = i % num_tablets
        for _ in range(rng.randrange(1, 4)):
            ht += rng.randrange(1, 5)
            roll = rng.random()
            if roll < 0.08:
                rv = RowVersion(key, ht=ht, tombstone=True)
            elif roll < 0.2:
                rv = RowVersion(key, ht=ht, columns={
                    cid["a"]: rng.randrange(-10**12, 10**12)})
            else:
                rv = RowVersion(key, ht=ht, liveness=True, columns={
                    cid["a"]: rng.randrange(-10**12, 10**12),
                    cid["c"]: rng.uniform(-1e6, 1e6),
                    cid["d"]: rng.randrange(-10**6, 10**6),
                })
            mems[t].apply([rv])
            oracle.apply([rv])
    runs = [ColumnarRun.build(make_schema(), m.drain_sorted(), rows_per_block)
            for m in mems]
    return runs, oracle, sorted(keys), ht


@pytest.fixture(scope="module")
def world():
    return build_world(seed=7)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("t", "b"))


@pytest.fixture(scope="module")
def sharded(world, mesh):
    runs, _, _, _ = world
    return ShardedTablets(make_schema(), runs, mesh, window_blocks=2)


AGGS = [
    AggSpec("count", None), AggSpec("sum", "a"), AggSpec("min", "a"),
    AggSpec("max", "a"), AggSpec("sum", "d"), AggSpec("min", "d"),
    AggSpec("max", "c"), AggSpec("min", "c"), AggSpec("avg", "d"),
    AggSpec("count", "a"),
]


def check(st, oracle, spec):
    got = sharded_aggregate(st, spec)
    want = oracle.scan(spec)
    assert got.columns == want.columns
    for g, w in zip(got.rows[0], want.rows[0]):
        if w is None or g is None:
            assert g == w
        elif isinstance(w, float):
            assert g == pytest.approx(w, rel=1e-5, abs=1e-3)
        else:
            assert g == w


def test_full_range_aggregates(world, sharded):
    _, oracle, _, max_ht = world
    spec = ScanSpec(read_ht=max_ht + 1, aggregates=AGGS)
    check(sharded, oracle, spec)


def test_bounded_range(world, sharded):
    _, oracle, keys, max_ht = world
    lo, hi = keys[len(keys) // 5], keys[4 * len(keys) // 5]
    spec = ScanSpec(lower=lo, upper=hi, read_ht=max_ht + 1, aggregates=AGGS)
    check(sharded, oracle, spec)


def test_historical_read_points(world, sharded):
    _, oracle, keys, max_ht = world
    for read_ht in (150, 400, 800, max_ht // 2):
        spec = ScanSpec(read_ht=read_ht, aggregates=AGGS)
        check(sharded, oracle, spec)


def test_predicates(world, sharded):
    _, oracle, _, max_ht = world
    cases = [
        [Predicate("a", ">=", 0)],
        [Predicate("d", "<", 0), Predicate("a", "!=", 3)],
        [Predicate("c", ">", -5e5), Predicate("c", "<=", 5e5)],
        [Predicate("a", ">", -10**11), Predicate("d", ">=", -500000)],
    ]
    for preds in cases:
        spec = ScanSpec(read_ht=max_ht + 1, predicates=preds, aggregates=AGGS)
        check(sharded, oracle, spec)


def test_empty_range(world, sharded):
    _, oracle, keys, max_ht = world
    spec = ScanSpec(lower=keys[-1] + b"\xff", read_ht=max_ht + 1,
                    aggregates=[AggSpec("count", None), AggSpec("sum", "a"),
                                AggSpec("min", "d")])
    check(sharded, oracle, spec)


def test_exact_int64_sum_at_scale():
    """Big magnitudes: digit-vector psum must be bit-exact where f64 would
    lose precision."""
    runs, oracle, _, max_ht = build_world(seed=99, num_keys=300)
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("t", "b"))
    st = ShardedTablets(make_schema(), runs, mesh, window_blocks=2)
    spec = ScanSpec(read_ht=max_ht + 1, aggregates=[AggSpec("sum", "a")])
    got = sharded_aggregate(st, spec)
    want = oracle.scan(spec)
    assert got.rows[0][0] == want.rows[0][0]  # exact int equality


# -- sharded row/paging path -------------------------------------------------

def build_flat_world(seed, num_tablets=8, num_keys=800, rows_per_block=16):
    """Single-version rows (the flat-run shape the row path serves),
    spread over tablets; per-tablet CPU oracles for page parity."""
    rng = random.Random(seed)
    schema = make_schema()
    mems = [MemTable() for _ in range(num_tablets)]
    oracles = [make_engine("cpu", schema) for _ in range(num_tablets)]
    cid = {c.name: c.col_id for c in schema.columns}
    ht = 100
    for i in range(num_keys):
        key = enc(schema, f"user{i:05d}", rng.randrange(10))
        t = i % num_tablets
        ht += 1
        if rng.random() < 0.05:
            rv = RowVersion(key, ht=ht, tombstone=True)
        else:
            cols = {cid["a"]: rng.randrange(-10**12, 10**12),
                    cid["d"]: rng.randrange(-10**6, 10**6)}
            if rng.random() < 0.8:
                cols[cid["c"]] = rng.uniform(-1e6, 1e6)
            rv = RowVersion(key, ht=ht, liveness=True, columns=cols)
        mems[t].apply([rv])
        oracles[t].apply([rv])
    runs = []
    for m, o in zip(mems, oracles):
        o.flush()
        runs.append(ColumnarRun.build(make_schema(), m.drain_sorted(),
                                      rows_per_block))
    return schema, runs, oracles, ht


def test_sharded_row_pages_ycsbe_style():
    """8-way sharded YCSB-E shape on the CPU mesh: LIMIT pages with a
    predicate, chained by resume token per tablet order, match the
    per-tablet oracles' union exactly."""
    from yugabyte_db_tpu.parallel import sharded_row_page

    schema, runs, oracles, max_ht = build_flat_world(seed=3)
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("t", "b"))
    st = ShardedTablets(schema, runs, mesh, window_blocks=2)

    spec_kw = dict(read_ht=max_ht + 1,
                   predicates=[Predicate("d", ">=", 0)],
                   projection=["k", "r", "a", "d"])
    # Expected: per-tablet oracle scans concatenated in tablet order.
    want = []
    for o in oracles:
        want.extend(o.scan(ScanSpec(**spec_kw)).rows)

    got = []
    token = None
    pages = 0
    while True:
        res = sharded_row_page(st, ScanSpec(limit=100, **spec_kw),
                               resume=token)
        got.extend(res.rows)
        pages += 1
        if res.resume_key is None:
            break
        token = res.resume_key
        assert pages < 50
    # Pages walk tablets in order; within a tablet rows are key-ordered;
    # chaining by the (tablet, key) token visits every matching row
    # exactly once.
    assert got == want
    assert pages > 1


def test_sharded_row_pages_bounds_and_historical():
    from yugabyte_db_tpu.parallel import sharded_row_page

    schema, runs, oracles, max_ht = build_flat_world(seed=11,
                                                     num_tablets=4,
                                                     num_keys=300)
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("t", "b"))
    st = ShardedTablets(schema, runs, mesh, window_blocks=2)
    lo = enc(schema, "user00050", 0)
    hi = enc(schema, "user00250", 0)
    for rht in (max_ht + 1, max_ht // 2 + 60):
        kw = dict(lower=lo, upper=hi, read_ht=rht,
                  projection=["k", "a"])
        want = []
        for o in oracles:
            want.extend(o.scan(ScanSpec(**kw)).rows)
        got = sharded_row_page(st, ScanSpec(limit=4096, **kw))
        assert sorted(got.rows) == sorted(want), rht


def build_mvcc_tablets(seed, num_tablets=4, num_keys=240,
                       rows_per_block=16):
    """Multi-version histories with tombstones, TTL expiry and same-ht
    write_id ties, with PER-TABLET oracles (row scans compare in tablet
    order, unlike the union-oracle aggregate tests)."""
    rng = random.Random(seed)
    schema = make_schema()
    mems = [MemTable() for _ in range(num_tablets)]
    oracles = [make_engine("cpu", schema) for _ in range(num_tablets)]
    cid = {c.name: c.col_id for c in schema.columns}
    ht = 100
    for i in range(num_keys):
        key = enc(schema, f"user{i:05d}", rng.randrange(10))
        t = i % num_tablets
        for _ in range(rng.randrange(1, 4)):
            ht += rng.randrange(1, 5)
            roll = rng.random()
            if roll < 0.08:
                rv = RowVersion(key, ht=ht, tombstone=True)
            elif roll < 0.16:
                # TTL: some already expired at the read point, some not.
                rv = RowVersion(key, ht=ht, liveness=True,
                                expire_ht=ht + rng.randrange(1, 400),
                                columns={cid["a"]: rng.randrange(10**9)})
            elif roll < 0.24:
                # Same-ht write_id tie: the later write_id wins.
                rv = RowVersion(key, ht=ht, liveness=True, columns={
                    cid["a"]: rng.randrange(10**9)})
                mems[t].apply([rv])
                oracles[t].apply([rv])
                rv = RowVersion(key, ht=ht, write_id=1, columns={
                    cid["a"]: rng.randrange(10**9)})
            elif roll < 0.4:
                rv = RowVersion(key, ht=ht, columns={
                    cid["d"]: rng.randrange(-10**6, 10**6)})
            else:
                rv = RowVersion(key, ht=ht, liveness=True, columns={
                    cid["a"]: rng.randrange(-10**12, 10**12),
                    cid["c"]: rng.uniform(-1e6, 1e6),
                    cid["d"]: rng.randrange(-10**6, 10**6),
                })
            mems[t].apply([rv])
            oracles[t].apply([rv])
    runs = [ColumnarRun.build(make_schema(), m.drain_sorted(),
                              rows_per_block) for m in mems]
    assert any(r.max_group_versions > 1 for r in runs)
    return schema, runs, oracles, ht


def _page_all(st, spec_kw, limit):
    from yugabyte_db_tpu.parallel import sharded_row_page

    got, token, pages = [], None, 0
    while True:
        res = sharded_row_page(st, ScanSpec(limit=limit, **spec_kw),
                               resume=token)
        got.extend(res.rows)
        pages += 1
        assert pages < 80
        if res.resume_key is None:
            return got, pages
        token = res.resume_key


def test_sharded_row_pages_mvcc(mesh):
    """Row paging over MULTI-VERSION runs: on-device MVCC resolution
    (visibility, tombstone shadowing, TTL, write_id ties) must match the
    per-tablet CPU oracles at current and historical read points."""
    schema, runs, oracles, max_ht = build_mvcc_tablets(seed=17)
    st = ShardedTablets(schema, runs, mesh, window_blocks=2)
    assert any(r.max_group_versions > 1 for r in st.runs)
    for rht in (max_ht + 1, max_ht // 2 + 60):
        spec_kw = dict(read_ht=rht, projection=["k", "r", "a", "d"])
        want = []
        for o in oracles:
            want.extend(o.scan(ScanSpec(**spec_kw)).rows)
        got, pages = _page_all(st, spec_kw, limit=64)
        assert got == want, rht
        assert pages > 1


def test_sharded_row_pages_encoded_vs_plain(mesh):
    """Encoded stacks (compressed device planes) serve byte-identical
    pages to the uncompressed stack — including resume-token chains."""
    schema, runs, oracles, max_ht = build_mvcc_tablets(seed=29)
    st_enc = ShardedTablets(schema, runs, mesh, window_blocks=2,
                            encode=True)
    st_plain = ShardedTablets(schema, runs, mesh, window_blocks=2,
                              encode=False)
    assert st_enc.encoded and not st_plain.encoded
    spec_kw = dict(read_ht=max_ht + 1, projection=["k", "r", "a", "c"])
    got_e, _ = _page_all(st_enc, spec_kw, limit=96)
    got_p, _ = _page_all(st_plain, spec_kw, limit=96)
    assert got_e == got_p
    want = []
    for o in oracles:
        want.extend(o.scan(ScanSpec(**spec_kw)).rows)
    assert got_e == want


def test_update_tablet_in_place(mesh):
    """Single-tablet refresh: update_tablet rewrites one slot of the
    stacked arrays on device (no rebuild), after which aggregates and
    row pages serve the NEW run's data; per-device residency accounting
    is unchanged (same shapes)."""
    from yugabyte_db_tpu.parallel import sharded_row_page
    from yugabyte_db_tpu.storage.residency import hbm_cache

    schema, runs, oracles, max_ht = build_flat_world(seed=41,
                                                     num_tablets=4,
                                                     num_keys=200)
    st = ShardedTablets(schema, runs, mesh, window_blocks=2,
                        encode=False)
    before = {d: v["resident_bytes"]
              for d, v in hbm_cache().stats()["by_device"].items()}
    # New data for tablet 2: rewrite every row's d to a sentinel value.
    t = 2
    mem = MemTable()
    o2 = make_engine("cpu", schema)
    cid = {c.name: c.col_id for c in schema.columns}
    ht = max_ht
    old = oracles[t].scan(ScanSpec(read_ht=max_ht + 1,
                                   projection=["k", "r"]))
    rng = random.Random(1)
    for k, r in old.rows:
        ht += 1
        rv = RowVersion(enc(schema, k, r), ht=ht, liveness=True, columns={
            cid["a"]: rng.randrange(10**9), cid["d"]: 777})
        mem.apply([rv])
        o2.apply([rv])
    new_run = ColumnarRun.build(make_schema(), mem.drain_sorted(), 16)
    assert st.update_tablet(t, new_run)
    after = {d: v["resident_bytes"]
             for d, v in hbm_cache().stats()["by_device"].items()}
    assert after == before  # same shapes -> same per-device charge
    spec_kw = dict(read_ht=ht + 1, projection=["k", "r", "a", "d"])
    want = []
    for i, o in enumerate(oracles):
        want.extend((o2 if i == t else o).scan(ScanSpec(**spec_kw)).rows)
    got, _ = _page_all(st, spec_kw, limit=4096)
    assert got == want
    res = sharded_row_page(st, ScanSpec(
        read_ht=ht + 1, predicates=[Predicate("d", "=", 777)],
        projection=["k", "d"], limit=4096))
    assert len(res.rows) == len(old.rows)
    # Encoded stacks can't splice a plain run in place: callers rebuild.
    st_enc = ShardedTablets(schema, runs, mesh, window_blocks=2,
                            encode=True)
    if st_enc.encoded:
        assert not st_enc.update_tablet(t, new_run)


def test_stack_close_mid_serve(mesh):
    """close() releases the stack's residency pin immediately but keeps
    the arrays alive for in-flight pages — the flush/compaction
    supersede-while-serving case must neither leak pins nor break the
    page being served."""
    from yugabyte_db_tpu.storage.residency import hbm_cache
    from yugabyte_db_tpu.utils.memtracker import root_tracker

    import gc

    tracker = root_tracker().child("device").child("sharded")
    gc.collect()
    hbm_cache().stats()  # reap stacks dead from earlier tests first
    base = tracker.consumption
    schema, runs, oracles, max_ht = build_flat_world(seed=43,
                                                     num_tablets=4,
                                                     num_keys=200)
    st = ShardedTablets(schema, runs, mesh, window_blocks=2)
    assert tracker.consumption > base
    spec_kw = dict(read_ht=max_ht + 1, projection=["k", "a"])
    from yugabyte_db_tpu.parallel import sharded_row_page

    first = sharded_row_page(st, ScanSpec(limit=32, **spec_kw))
    assert first.resume_key is not None
    st.close()
    # Pin + MemTracker charge gone the moment the stack is superseded...
    assert tracker.consumption == base
    # ...and double-close stays a no-op.
    st.close()
    assert tracker.consumption == base
    # The in-flight page chain still serves, byte-identical.
    got = list(first.rows)
    token = first.resume_key
    while token is not None:
        res = sharded_row_page(st, ScanSpec(limit=32, **spec_kw),
                               resume=token)
        got.extend(res.rows)
        token = res.resume_key
    want = []
    for o in oracles:
        want.extend(o.scan(ScanSpec(**spec_kw)).rows)
    assert got == want
