"""ops.lookback_fold (bounded shifted-mask MVCC aggregate) vs the CPU
oracle and the segmented-scan fold on randomized multi-version data:
overwrites, tombstones (incl. same-ht DELETE+write ties), TTL, NULLs,
predicates, range bounds, many read points.
"""

import pytest

from yugabyte_db_tpu.storage import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.storage.row_version import MAX_HT

from tests.test_seg_fold import AGGS, assert_same_agg, enc, setup


@pytest.mark.slow
def test_lookback_route_taken(monkeypatch):
    """The ENGINE's aggregate planner must actually dispatch through
    lookback_fold for a bounded-version run (not fall to seg_fold)."""
    from yugabyte_db_tpu.ops import lookback_fold

    schema, cpu, tpu, ht = setup()
    mgv = tpu.runs[0].crun.max_group_versions
    assert 1 < mgv <= lookback_fold.MAX_LOOKBACK
    seen = []
    orig = lookback_fold.compiled_lookback_aggregate

    def spy(sig):
        seen.append(sig)
        return orig(sig)

    monkeypatch.setattr(lookback_fold, "compiled_lookback_aggregate", spy)
    assert_same_agg(cpu, tpu, read_ht=MAX_HT, aggregates=list(AGGS))
    assert seen, "engine did not route through lookback_fold"
    assert seen[0].lookback >= mgv  # rounded-up power of two


def test_lookback_matches_oracle_many_read_points():
    schema, cpu, tpu, ht = setup(seed=41)
    for rp in (1, ht // 4, ht // 2, 3 * ht // 4, ht, MAX_HT):
        assert_same_agg(cpu, tpu, read_ht=rp, aggregates=list(AGGS))


@pytest.mark.slow
def test_lookback_predicates_and_bounds():
    schema, cpu, tpu, ht = setup(seed=43)
    lo = enc(schema, "k0020", 0)
    hi = enc(schema, "k0090", 0)
    cases = [
        dict(read_ht=MAX_HT, aggregates=list(AGGS),
             predicates=[Predicate("d", ">=", 0)]),
        dict(read_ht=ht, aggregates=list(AGGS),
             predicates=[Predicate("a", "<", 0),
                         Predicate("d", "!=", 3)]),
        dict(read_ht=ht // 2, aggregates=list(AGGS), lower=lo, upper=hi),
        dict(read_ht=MAX_HT, aggregates=[AggSpec("count", None)],
             predicates=[Predicate("c", ">=", 0.0)]),
    ]
    for kw in cases:
        assert_same_agg(cpu, tpu, **kw)


def test_lookback_matches_seg_fold_exactly():
    """Finalized-value equivalence of the shifted-mask resolve and the
    segmented-scan resolve on the same uploaded run."""
    import jax.numpy as jnp

    from yugabyte_db_tpu.ops import agg_fold, lookback_fold, seg_fold
    from yugabyte_db_tpu.ops import scan as dscan
    from yugabyte_db_tpu.utils import planes as P

    schema, _cpu, tpu, ht = setup(seed=57)
    trun = tpu.runs[0]
    crun = trun.crun
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    dev_aggs, _low = agg_fold.lower_aggs(AGGS, name_to_id, tpu._kinds)
    cols = tpu._col_sigs()
    preds = (dscan.PredSig(name_to_id["d"], "i32", ">="),)
    K = agg_fold.safe_window_blocks(crun.R, agg_fold.FULL_WINDOW_BLOCKS)
    base = dict(B=trun.dev.B, R=crun.R, K=K, cols=cols, preds=preds,
                aggs=dev_aggs, apply_preds=True, flat=False)
    sig_seg = dscan.ScanSig(**base)
    sig_lb = dscan.ScanSig(**base, lookback=crun.max_group_versions)
    assert lookback_fold.supports(sig_lb)

    for rp in (ht // 3, ht, MAX_HT - 1):
        r_hi, r_lo = P.scalar_ht_planes(rp)
        args = (trun.dev.arrays, jnp.int32(0), jnp.int32(crun.total_rows()),
                jnp.int32(r_hi), jnp.int32(r_lo), jnp.int32(r_hi),
                jnp.int32(r_lo), (jnp.int32(-500),))
        iv_s, fv_s = seg_fold.compiled_seg_aggregate(sig_seg)(*args)
        iv_l, fv_l = lookback_fold.compiled_lookback_aggregate(sig_lb)(*args)
        acc_s, scanned_s = agg_fold.unpack(dev_aggs, iv_s, fv_s)
        acc_l, scanned_l = agg_fold.unpack(dev_aggs, iv_l, fv_l)
        assert scanned_s == scanned_l, rp
        for ag, a_s, a_l in zip(dev_aggs, acc_s, acc_l):
            vs = agg_fold.finalize(ag, a_s, ag.fn)
            vl = agg_fold.finalize(ag, a_l, ag.fn)
            if isinstance(vs, float):
                assert vl == pytest.approx(vs, rel=1e-5, abs=1e-3), rp
            else:
                assert vs == vl, (rp, ag)


@pytest.mark.slow
def test_lookback_randomized_blocks_sizes():
    for seed, rpb in ((61, 32), (62, 128), (63, 257)):
        schema, cpu, tpu, ht = setup(n=400, seed=seed,
                                     rows_per_block=rpb)
        assert_same_agg(cpu, tpu, read_ht=MAX_HT, aggregates=list(AGGS))
        assert_same_agg(cpu, tpu, read_ht=ht // 2,
                        aggregates=list(AGGS))
