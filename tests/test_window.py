"""Window functions: fn(...) OVER (PARTITION BY ... ORDER BY ...).

Reference capability: stock PG 11.2's WindowAgg node above the FDW scans
(src/postgres/src/backend/executor/nodeWindowAgg.c); test style follows
src/yb/yql/pgwrapper/pg_libpq-test.cc. Covers ranking functions
(row_number/rank/dense_rank), lag/lead, aggregate windows with PG's
default RANGE UNBOUNDED PRECEDING .. CURRENT ROW frame (peer rows share
the running value), partitioned and unpartitioned, over base tables,
CTEs, views, and joins.
"""

import pytest

from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.pgsql import PgProcessor
from yugabyte_db_tpu.yql.cql.processor import LocalCluster


@pytest.fixture(params=["cpu", "tpu"])
def pg(request, tmp_path):
    cluster = LocalCluster(str(tmp_path), num_tablets=2,
                           engine=request.param,
                           engine_options={"rows_per_block": 16})
    proc = PgProcessor(cluster)
    yield proc
    cluster.close()


def seed(pg):
    pg.execute("CREATE TABLE sales (id bigint PRIMARY KEY, rgn text, "
               "amt bigint)")
    for i, (rgn, amt) in enumerate([("e", 100), ("e", 300), ("e", 300),
                                    ("w", 50), ("w", 200)], start=1):
        pg.execute(f"INSERT INTO sales (id, rgn, amt) VALUES "
                   f"({i}, '{rgn}', {amt})")


def test_row_number_global(pg):
    seed(pg)
    r = pg.execute("SELECT id, row_number() OVER (ORDER BY amt DESC, id) "
                   "AS rn FROM sales ORDER BY rn")
    assert r.rows == [(2, 1), (3, 2), (5, 3), (1, 4), (4, 5)]


def test_row_number_partitioned(pg):
    seed(pg)
    r = pg.execute("SELECT id, row_number() OVER (PARTITION BY rgn "
                   "ORDER BY amt) AS rn FROM sales ORDER BY id")
    assert r.rows == [(1, 1), (2, 2), (3, 3), (4, 1), (5, 2)]


def test_rank_and_dense_rank_ties(pg):
    seed(pg)
    r = pg.execute("SELECT id, rank() OVER (ORDER BY amt DESC) AS rk, "
                   "dense_rank() OVER (ORDER BY amt DESC) AS dr "
                   "FROM sales ORDER BY id")
    # amts: 100,300,300,50,200 -> desc order 300,300,200,100,50
    assert r.rows == [(1, 4, 3), (2, 1, 1), (3, 1, 1), (4, 5, 4),
                      (5, 3, 2)]


def test_lag_lead(pg):
    seed(pg)
    r = pg.execute("SELECT id, lag(amt) OVER (PARTITION BY rgn "
                   "ORDER BY id) AS prev, lead(amt) OVER (PARTITION BY "
                   "rgn ORDER BY id) AS nxt FROM sales ORDER BY id")
    assert r.rows == [(1, None, 300), (2, 100, 300), (3, 300, None),
                      (4, None, 200), (5, 50, None)]


def test_lag_offset_and_default(pg):
    seed(pg)
    r = pg.execute("SELECT id, lag(amt, 2, 0) OVER (ORDER BY id) AS p2 "
                   "FROM sales ORDER BY id")
    assert r.rows == [(1, 0), (2, 0), (3, 100), (4, 300), (5, 300)]


def test_lag_bound_param_offset_and_default(pg):
    seed(pg)
    r = pg.execute("SELECT id, lag(amt, $1, $2) OVER (ORDER BY id) AS p "
                   "FROM sales ORDER BY id", [2, -1])
    assert r.rows == [(1, -1), (2, -1), (3, 100), (4, 300), (5, 300)]


def test_running_sum_default_frame(pg):
    seed(pg)
    # PG default frame with ORDER BY: peers (equal order keys) share the
    # running value — ids 2 and 3 are both amt=300 but distinct order
    # keys here (ORDER BY id), so a plain prefix sum.
    r = pg.execute("SELECT id, sum(amt) OVER (PARTITION BY rgn "
                   "ORDER BY id) AS run FROM sales ORDER BY id")
    assert r.rows == [(1, 100), (2, 400), (3, 700), (4, 50), (5, 250)]


def test_running_sum_peer_rows_share(pg):
    seed(pg)
    # ORDER BY amt: ids 2,3 are peers (amt=300) -> both see the full
    # 700 running total, exactly PG's RANGE-frame semantics.
    r = pg.execute("SELECT id, sum(amt) OVER (PARTITION BY rgn "
                   "ORDER BY amt) AS run FROM sales ORDER BY id")
    assert r.rows == [(1, 100), (2, 700), (3, 700), (4, 50), (5, 250)]


def test_whole_partition_aggregates(pg):
    seed(pg)
    r = pg.execute("SELECT id, sum(amt) OVER (PARTITION BY rgn) AS tot, "
                   "count(*) OVER (PARTITION BY rgn) AS n, "
                   "avg(amt) OVER (PARTITION BY rgn) AS mean "
                   "FROM sales ORDER BY id")
    assert r.rows == [(1, 700, 3, 700 / 3), (2, 700, 3, 700 / 3),
                      (3, 700, 3, 700 / 3), (4, 250, 2, 125.0),
                      (5, 250, 2, 125.0)]


def test_min_max_over(pg):
    seed(pg)
    r = pg.execute("SELECT id, min(amt) OVER (PARTITION BY rgn) AS lo, "
                   "max(amt) OVER (PARTITION BY rgn) AS hi "
                   "FROM sales WHERE rgn = 'e' ORDER BY id")
    assert r.rows == [(1, 100, 300), (2, 100, 300), (3, 100, 300)]


def test_window_over_cte(pg):
    seed(pg)
    r = pg.execute("WITH big AS (SELECT id, rgn, amt FROM sales "
                   "WHERE amt >= 100) "
                   "SELECT id, rank() OVER (ORDER BY amt DESC) AS rk "
                   "FROM big ORDER BY id")
    assert r.rows == [(1, 4), (2, 1), (3, 1), (5, 3)]


def test_window_over_view(pg):
    seed(pg)
    pg.execute("CREATE VIEW east AS SELECT id, amt FROM sales "
               "WHERE rgn = 'e'")
    r = pg.execute("SELECT id, row_number() OVER (ORDER BY amt DESC, id)"
                   " AS rn FROM east ORDER BY rn")
    assert r.rows == [(2, 1), (3, 2), (1, 3)]


def test_window_over_join(pg):
    seed(pg)
    pg.execute("CREATE TABLE rgns (rgn text PRIMARY KEY, nm text)")
    pg.execute("INSERT INTO rgns (rgn, nm) VALUES ('e', 'east')")
    pg.execute("INSERT INTO rgns (rgn, nm) VALUES ('w', 'west')")
    r = pg.execute("SELECT s.id, row_number() OVER (PARTITION BY r.nm "
                   "ORDER BY s.amt DESC) AS rn FROM sales s "
                   "JOIN rgns r ON s.rgn = r.rgn ORDER BY s.id")
    assert r.rows == [(1, 3), (2, 1), (3, 2), (4, 2), (5, 1)]


def test_window_star_projection(pg):
    seed(pg)
    r = pg.execute("SELECT *, row_number() OVER (ORDER BY id) AS rn "
                   "FROM sales WHERE rgn = 'w' ORDER BY id")
    assert [row[-1] for row in r.rows] == [1, 2]
    assert len(r.columns) == 4


def test_window_with_limit_offset(pg):
    seed(pg)
    r = pg.execute("SELECT id, row_number() OVER (ORDER BY amt DESC, id)"
                   " AS rn FROM sales ORDER BY rn LIMIT 2 OFFSET 1")
    assert r.rows == [(3, 2), (5, 3)]


def test_fromless_window(pg):
    r = pg.execute("SELECT row_number() OVER () AS rn")
    assert r.rows == [(1,)]
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT row_number() OVER (ORDER BY x)")


def test_window_rejects_group_by(pg):
    seed(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT rgn, row_number() OVER (ORDER BY rgn) "
                   "FROM sales GROUP BY rgn")


def test_window_requires_over(pg):
    seed(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT row_number() FROM sales")


def test_window_unknown_column(pg):
    seed(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT row_number() OVER (ORDER BY nope) FROM sales")
