"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-tablet sharding
(Mesh/shard_map/psum over the tablet axis) is exercised without TPU hardware,
per the standard JAX testing recipe. This must happen before jax initializes
a backend, hence the env mutation at module import time (conftest imports
before any test module).

Reference test-strategy analog: the in-process MiniCluster
(src/yb/integration-tests/mini_cluster.h) — "multi-node" behavior validated
inside one process.
"""

import os
import sys

# Force CPU: the ambient environment pins JAX_PLATFORMS=axon (the real TPU
# tunnel registered by sitecustomize) and its get_backend hook initializes
# the axon backend even under JAX_PLATFORMS=cpu — which would (a) run every
# test against the remote chip and (b) hang the whole suite whenever the
# tunnel is unavailable. The one canonical copy of this order-sensitive
# recipe lives in __graft_entry__._pin_cpu_platform (the driver gate uses
# the same one); its module top-level imports only stdlib+numpy, so it is
# safe to import before jax initializes.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _pin_cpu_platform

_pin_cpu_platform(8)

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    """Capability probe for the mesh read path.

    The shard_map API moved between jax generations (meshcompat.py holds
    the seam); on an interpreter with NEITHER spelling the mesh rigs
    cannot run at all.  Turn those into reasoned skips instead of 11
    identical AttributeError failures, so tier-1 reports honest dots.
    """
    from yugabyte_db_tpu.parallel import meshcompat

    reason = meshcompat.mesh_unavailable()
    if reason is None:
        return
    skip = pytest.mark.skip(reason="mesh path unavailable: " + reason)
    for item in items:
        if "mesh" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _reset_fault_injection():
    """Leak containment for the fault-injection plane: a test that arms
    a fault flag or a sync point and then fails (or forgets cleanup)
    must not poison the next test — armed one-shot faults would fire in
    whatever unrelated code path calls maybe_fault() next."""
    yield
    from yugabyte_db_tpu.utils.fault_injection import clear_faults
    from yugabyte_db_tpu.utils.sync_point import SYNC_POINT

    clear_faults()
    SYNC_POINT.disable_and_clear()
