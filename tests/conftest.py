"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-tablet sharding
(Mesh/shard_map/psum over the tablet axis) is exercised without TPU hardware,
per the standard JAX testing recipe. This must happen before jax initializes
a backend, hence the env mutation at module import time (conftest imports
before any test module).

Reference test-strategy analog: the in-process MiniCluster
(src/yb/integration-tests/mini_cluster.h) — "multi-node" behavior validated
inside one process.
"""

import os

# Force CPU: the ambient environment pins JAX_PLATFORMS=axon (the real TPU
# tunnel registered by sitecustomize) and its get_backend hook initializes
# the axon backend even under JAX_PLATFORMS=cpu — which would (a) run every
# test against the remote chip and (b) hang the whole suite whenever the
# tunnel is unavailable. Unregister the factory and pin the config instead.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import jax._src.xla_bridge as _xb

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
