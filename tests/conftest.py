"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-tablet sharding
(Mesh/shard_map/psum over the tablet axis) is exercised without TPU hardware,
per the standard JAX testing recipe. This must happen before jax initializes
a backend, hence the env mutation at module import time (conftest imports
before any test module).

Reference test-strategy analog: the in-process MiniCluster
(src/yb/integration-tests/mini_cluster.h) — "multi-node" behavior validated
inside one process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
