"""Engine-diff tests: TPU engine vs CPU oracle — identical results.

The TPU data plane (columnar runs + device scan kernels) must reproduce the
CPU engine's results on every scan: same rows, same order, same aggregates
(floating-point sums to tolerance). This is the framework's equivalent of
the reference's randomized DocDB-vs-InMemDocDbState oracle tests
(src/yb/docdb/randomized_docdb-test.cc).

Runs on the CPU JAX backend (conftest) — same kernels the TPU executes.
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (
    AggSpec, Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.row_version import MAX_HT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def ids(schema):
    return {c.name: c.col_id for c in schema.value_columns}


def both_engines(opts=None):
    schema = make_schema()
    return (schema,
            make_engine("cpu", schema, dict(opts or {})),
            make_engine("tpu", schema, dict(opts or {}, rows_per_block=64)))


def apply_both(cpu, tpu, rows):
    cpu.apply(rows)
    tpu.apply(rows)


def assert_same_scan(cpu, tpu, spec_kwargs, approx_cols=()):
    a = cpu.scan(ScanSpec(**spec_kwargs))
    b = tpu.scan(ScanSpec(**spec_kwargs))
    assert a.columns == b.columns
    if not approx_cols:
        assert a.rows == b.rows, f"spec={spec_kwargs}"
    else:
        assert len(a.rows) == len(b.rows)
        for ra, rb in zip(a.rows, b.rows):
            for i, (va, vb) in enumerate(zip(ra, rb)):
                if a.columns[i] in approx_cols and va is not None:
                    assert vb == pytest.approx(va, rel=1e-4, abs=1e-4)
                else:
                    assert va == vb
    assert (a.resume_key is None) == (b.resume_key is None)
    return a, b


def load_sample(schema, cpu, tpu, n=300, seed=5):
    rnd = random.Random(seed)
    cids = ids(schema)
    ht = 0
    for i in range(n):
        ht += rnd.randrange(1, 4)
        part = rnd.choice(["p", "q", "rr"])
        key = enc(schema, part, i % 97)
        roll = rnd.random()
        if roll < 0.1:
            apply_both(cpu, tpu, [RowVersion(key, ht=ht, tombstone=True)])
        elif roll < 0.6:
            apply_both(cpu, tpu, [RowVersion(
                key, ht=ht, liveness=True,
                columns={cids["a"]: rnd.randrange(-1000, 1000),
                         cids["b"]: rnd.choice(["xy", "xyz", "zz", None,
                                                "commonprefix-aa",
                                                "commonprefix-ab"]),
                         cids["c"]: rnd.uniform(-5, 5),
                         cids["d"]: rnd.randrange(-50, 50)},
                expire_ht=ht + rnd.randrange(5, 200) if rnd.random() < 0.15 else MAX_HT)])
        else:
            col = rnd.choice(["a", "b", "c", "d"])
            val = {"a": rnd.randrange(-1000, 1000), "b": rnd.choice(["w", None]),
                   "c": rnd.uniform(-5, 5), "d": rnd.randrange(-50, 50)}[col]
            apply_both(cpu, tpu, [RowVersion(key, ht=ht, columns={cids[col]: val})])
    return ht


def test_diff_full_scan_and_range_bounds():
    # Full scans (the fully-unbounded range) and range edges share one
    # engine pair: the former test_diff_single_run_full_scan used the
    # identical workload, so its read-point sweep rides here.
    schema, cpu, tpu = both_engines()
    max_ht = load_sample(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(read_ht=max_ht // 2))
    assert_same_scan(cpu, tpu, dict(read_ht=1))
    lo = enc(schema, "p", 10)
    hi = enc(schema, "p", 60)
    assert_same_scan(cpu, tpu, dict(lower=lo, upper=hi, read_ht=MAX_HT))
    # Degenerate and unbounded edges.
    assert_same_scan(cpu, tpu, dict(lower=hi, upper=hi and lo, read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(lower=b"", upper=lo, read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(lower=hi, upper=b"", read_ht=MAX_HT))


def test_diff_multi_run_and_memtable_overlay():
    schema, cpu, tpu = both_engines()
    ht = load_sample(schema, cpu, tpu, n=150, seed=7)
    cpu.flush(); tpu.flush()
    ht = load_sample(schema, cpu, tpu, n=150, seed=8)
    cpu.flush(); tpu.flush()
    # Third batch stays in the memtable: three overlapping sources.
    load_sample(schema, cpu, tpu, n=80, seed=9)
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(read_ht=ht))
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT,
        predicates=[Predicate("a", ">=", 0), Predicate("d", "<", 25)]))


def test_diff_predicates_single_run():
    schema, cpu, tpu = both_engines()
    load_sample(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    cases = [
        [Predicate("a", ">", 0)],
        [Predicate("a", "<=", -5), Predicate("d", "!=", 0)],
        [Predicate("c", ">=", 0.0)],
        [Predicate("b", "=", "xy")],     # varlen: device superset + host verify
        [Predicate("b", "!=", "xy")],
        [Predicate("b", "<", "xz")],
        [Predicate("r", ">=", 50)],      # key-column predicate: host path
        [Predicate("a", "IN", (1, 2, 3, 500))],
    ]
    for preds in cases:
        assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT, predicates=preds))


def test_diff_paging():
    schema, cpu, tpu = both_engines()
    load_sample(schema, cpu, tpu)
    cpu.flush(); tpu.flush()
    spec_a = ScanSpec(read_ht=MAX_HT, limit=7)
    spec_b = ScanSpec(read_ht=MAX_HT, limit=7)
    pages = 0
    while True:
        ra, rb = cpu.scan(spec_a), tpu.scan(spec_b)
        assert ra.rows == rb.rows
        assert (ra.resume_key is None) == (rb.resume_key is None)
        pages += 1
        if ra.resume_key is None:
            break
        spec_a = ScanSpec(lower=ra.resume_key, read_ht=MAX_HT, limit=7)
        spec_b = ScanSpec(lower=rb.resume_key, read_ht=MAX_HT, limit=7)
    assert pages > 2


def test_diff_aggregates_device_path():
    schema, cpu, tpu = both_engines()
    load_sample(schema, cpu, tpu, n=400)
    cpu.flush(); tpu.flush()
    aggs = [AggSpec("count", None), AggSpec("count", "b"), AggSpec("sum", "a"),
            AggSpec("sum", "d"), AggSpec("min", "a"), AggSpec("max", "a"),
            AggSpec("min", "d"), AggSpec("max", "d"), AggSpec("avg", "a")]
    a, b = assert_same_scan(
        cpu, tpu, dict(read_ht=MAX_HT, aggregates=aggs),
        approx_cols={"avg(a)"})
    # Exact integer sums.
    assert a.rows[0][2] == b.rows[0][2]
    # Float aggregates to tolerance.
    assert_same_scan(cpu, tpu,
                     dict(read_ht=MAX_HT, aggregates=[AggSpec("sum", "c"),
                                                      AggSpec("min", "c"),
                                                      AggSpec("max", "c")]),
                     approx_cols={"sum(c)"})


def test_diff_aggregates_with_predicates():
    schema, cpu, tpu = both_engines()
    load_sample(schema, cpu, tpu, n=400)
    cpu.flush(); tpu.flush()
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, aggregates=[AggSpec("count", None), AggSpec("sum", "a")],
        predicates=[Predicate("a", ">", 0)]))
    # String predicate forces the row-path fallback; results still identical.
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, aggregates=[AggSpec("count", None)],
        predicates=[Predicate("b", "=", "xy")]))


def test_diff_aggregate_group_by_fallback():
    schema, cpu, tpu = both_engines()
    load_sample(schema, cpu, tpu, n=200)
    cpu.flush(); tpu.flush()
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, group_by=["b"],
        aggregates=[AggSpec("count", None), AggSpec("sum", "a")]))


def test_diff_compaction_equivalence():
    schema, cpu, tpu = both_engines()
    ht = load_sample(schema, cpu, tpu, n=250, seed=31)
    cpu.flush(); tpu.flush()
    load_sample(schema, cpu, tpu, n=250, seed=32)
    cpu.flush(); tpu.flush()
    # Pre-compaction this is exactly the two-overlapping-runs shape the
    # former test_diff_aggregates_multi_run_fallback rebuilt from
    # scratch: the aggregate multi-run fallback asserts ride here.
    assert_same_scan(cpu, tpu, dict(
        read_ht=MAX_HT, aggregates=[AggSpec("count", None), AggSpec("sum", "a")]))
    cpu.compact(history_cutoff_ht=ht)
    tpu.compact(history_cutoff_ht=ht)
    assert cpu.stats()["num_runs"] == tpu.stats()["num_runs"] == 1
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert_same_scan(cpu, tpu, dict(read_ht=ht))


def test_diff_randomized_many_read_points():
    schema, cpu, tpu = both_engines(
        {"memtable_flush_versions": 61, "compaction_trigger": 3})
    rnd = random.Random(77)
    cids = ids(schema)
    ht = 0
    read_points = []
    for step in range(500):
        ht += rnd.randrange(1, 4)
        key = enc(schema, rnd.choice("ab"), rnd.randrange(40))
        roll = rnd.random()
        if roll < 0.12:
            rv = RowVersion(key, ht=ht, tombstone=True)
        elif roll < 0.55:
            rv = RowVersion(key, ht=ht, liveness=True,
                            columns={cids["a"]: rnd.randrange(100),
                                     cids["c"]: rnd.uniform(0, 1)},
                            expire_ht=ht + rnd.randrange(3, 60) if rnd.random() < 0.2 else MAX_HT)
        else:
            col = rnd.choice(["a", "b"])
            val = rnd.choice([5, 9, None]) if col == "a" else \
                rnd.choice(["s", "commonprefix-aa", "commonprefix-ab", None])
            rv = RowVersion(key, ht=ht, columns={cids[col]: val})
        apply_both(cpu, tpu, [rv])
        if step % 50 == 0:
            read_points.append(ht)
    for rp in read_points + [ht, MAX_HT]:
        assert_same_scan(cpu, tpu, dict(read_ht=rp))
