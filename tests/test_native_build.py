"""Native flush (Memtable.drain_run + ColumnarRun.build_from_memtable)
vs the generic Python build: every plane, payload, and metadatum must be
identical — the flush-path twin of the engine-diff oracle tests.
Reference analog: rocksdb flush building SSTables straight off the
memtable iterator (src/yb/rocksdb/db/flush_job.cc)."""

import datetime
import decimal
import random
import uuid as uuid_mod

import numpy as np
import pytest

from yugabyte_db_tpu.models.datatypes import DataType, Inet
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage.memtable import make_memtable
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b8", DataType.INT8),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("f", DataType.FLOAT),
        ColumnSchema("bl", DataType.BOOL),
        ColumnSchema("s", DataType.STRING),
        ColumnSchema("by", DataType.BINARY),
        ColumnSchema("js", DataType.JSONB),
    ], table_id="nb")


def make_rows(schema, n=800, seed=9):
    rng = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.value_columns}
    rows = []
    ht = 50
    for i in range(n):
        ht += rng.randrange(1, 3)
        kk = f"k{rng.randrange(n // 2):05d}"  # repeats: multi-version
        key = schema.encode_primary_key(
            {"k": kk, "r": i % 9},
            compute_hash_code(schema, {"k": kk}))
        if rng.random() < 0.06:
            rows.append(RowVersion(key, ht=ht, tombstone=True))
            continue
        cols = {}
        if rng.random() < 0.9:
            cols[cid["a"]] = rng.randrange(-2**62, 2**62)
        if rng.random() < 0.7:
            cols[cid["b8"]] = rng.randrange(-128, 128)
        if rng.random() < 0.7:
            cols[cid["c"]] = rng.uniform(-1e12, 1e12)
        if rng.random() < 0.7:
            cols[cid["f"]] = rng.uniform(-1e3, 1e3)
        if rng.random() < 0.6:
            cols[cid["bl"]] = rng.random() < 0.5
        if rng.random() < 0.7:
            cols[cid["s"]] = ("é" * rng.randrange(0, 3)
                              + f"str{rng.randrange(10**6)}")
        if rng.random() < 0.5:
            cols[cid["by"]] = rng.randbytes(rng.randrange(0, 14))
        if rng.random() < 0.3:
            cols[cid["js"]] = {"a": [i, "x"], "b": i % 2 == 0}
        if rng.random() < 0.1 and cols:
            cols[next(iter(cols))] = None  # explicit NULL
        ttl = rng.randrange(1, 10**6) if rng.random() < 0.2 else None
        rows.append(RowVersion(
            key, ht=ht, liveness=rng.random() < 0.8, columns=cols,
            expire_ht=(ht + ttl) if ttl else (1 << 63) - 1))
    return rows


def assert_runs_equal(a: ColumnarRun, b: ColumnarRun):
    assert a.B == b.B and a.R == b.R
    assert a.num_versions == b.num_versions
    assert a.min_key == b.min_key and a.max_key == b.max_key
    assert a.max_ht == b.max_ht
    assert a.max_key_len == b.max_key_len
    assert a.max_group_versions == b.max_group_versions
    assert a.varlen_max_len == b.varlen_max_len
    for nm in ("key_planes", "ht_hi", "ht_lo", "exp_hi", "exp_lo",
               "tomb", "live", "valid", "group_start"):
        np.testing.assert_array_equal(getattr(a, nm), getattr(b, nm), nm)
    assert set(a.cols) == set(b.cols)
    for cid in a.cols:
        ca, cb = a.cols[cid], b.cols[cid]
        np.testing.assert_array_equal(ca.set_, cb.set_, f"set {cid}")
        np.testing.assert_array_equal(ca.isnull, cb.isnull, f"nul {cid}")
        np.testing.assert_array_equal(ca.cmp_planes, cb.cmp_planes,
                                      f"cmp {cid}")
        if ca.arith is not None:
            np.testing.assert_array_equal(ca.arith, cb.arith,
                                          f"arith {cid}")
        if ca.varlen is not None:
            assert ca.varlen == cb.varlen, f"varlen {cid}"
    for bi in range(a.B):
        ma, mb = a.blocks[bi], b.blocks[bi]
        assert (ma.min_key, ma.max_key, ma.num_valid) == \
            (mb.min_key, mb.max_key, mb.num_valid)
        n = ma.num_valid
        assert a.row_keys[bi][:n].tolist() == b.row_keys[bi][:n].tolist()
        for r in range(n):
            va, vb = a.row_versions[bi][r], b.row_versions[bi][r]
            assert (va.key, va.ht, va.tombstone, va.liveness, va.columns,
                    va.expire_ht, va.ttl_us, va.write_id) == \
                (vb.key, vb.ht, vb.tombstone, vb.liveness, vb.columns,
                 vb.expire_ht, vb.ttl_us, vb.write_id)


@pytest.mark.parametrize("rpb", [16, 64, 2048])
def test_native_build_parity(rpb):
    schema = make_schema()
    rows = make_rows(schema)
    mt1 = make_memtable()
    mt1.apply(rows)
    native = ColumnarRun.build_from_memtable(schema, mt1, rpb)
    if native is None:
        pytest.skip("native memtable unavailable")
    mt2 = make_memtable()
    mt2.apply(rows)
    generic = ColumnarRun.build(schema, mt2.drain_sorted(), rpb)
    assert_runs_equal(generic, native)


def test_native_build_rich_types_fall_back():
    """Rich-typed values (EXT codec tags land in int columns? no — rich
    scalars in varlen columns succeed; unsupported shapes return None)."""
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("d", DataType.DECIMAL),
        ColumnSchema("u", DataType.UUID),
        ColumnSchema("ip", DataType.INET),
        ColumnSchema("dt", DataType.DATE),
    ], table_id="nbx")
    cid = {c.name: c.col_id for c in schema.value_columns}
    rows = []
    for i in range(40):
        key = schema.encode_primary_key(
            {"k": f"x{i:03d}"}, compute_hash_code(schema, {"k": f"x{i:03d}"}))
        rows.append(RowVersion(key, ht=10 + i, liveness=True, columns={
            cid["d"]: decimal.Decimal(i) / 4,
            cid["u"]: uuid_mod.UUID(int=i * 7919),
            cid["ip"]: Inet(f"10.0.0.{i}"),
            cid["dt"]: datetime.date(2024, 1, 1 + i % 28),
        }))
    mt1 = make_memtable()
    mt1.apply(rows)
    native = ColumnarRun.build_from_memtable(schema, mt1, 32)
    mt2 = make_memtable()
    mt2.apply(rows)
    generic = ColumnarRun.build(schema, mt2.drain_sorted(), 32)
    if native is not None:
        assert_runs_equal(generic, native)


def test_native_servebatch_builds_and_parses():
    """The request-batch module (native/servebatch.cc -> yb_rb) rides the
    same build-on-first-import as yb_codec/yb_wp; its strict RESP parser
    must agree with the pure-Python one on commands AND bytes consumed,
    and return None (nothing consumed) for the inline form so error
    behavior stays with the canonical Python path."""
    from yugabyte_db_tpu import native as native_pkg
    from yugabyte_db_tpu.yql.redis import resp
    yb_rb = native_pkg.yb_rb
    if yb_rb is None:
        if native_pkg.yb_wp is not None:
            pytest.fail("toolchain built yb_wp but not yb_rb")
        pytest.skip("native toolchain unavailable")
    cmds = ([["SET", f"k{i:04d}", "v" * (i % 9)] for i in range(40)]
            + [["GET", f"k{i:04d}"] for i in range(40)]
            + [["MGET", "k0001", "\x00bin\r\n$", ""]])
    buf = bytearray()
    for args in cmds:
        buf += b"*%d\r\n" % len(args)
        for a in args:
            ab = a.encode("utf-8", "surrogateescape")
            buf += b"$%d\r\n" % len(ab) + ab + b"\r\n"
    buf += b"*0\r\n"                             # empty array: skipped
    buf += b"*2\r\n$3\r\nGET\r\n$7\r\nk000"      # incomplete tail: left
    got = yb_rb.parse_resp(buf)
    assert got is not None
    native_cmds, consumed = got
    pybuf = bytearray(buf)
    assert native_cmds == resp.parse_commands(pybuf)
    assert consumed == len(buf) - len(pybuf)
    assert yb_rb.parse_resp(bytearray(b"PING\r\n")) is None


def test_flush_uses_native_and_engine_diff_holds():
    schema = make_schema()
    rows = make_rows(schema, n=500, seed=4)
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    for e in (cpu, tpu):
        e.apply(rows)
        e.flush()
    max_ht = max(r.ht for r in rows)
    for spec in (ScanSpec(read_ht=max_ht + 1),
                 ScanSpec(read_ht=max_ht // 2, limit=50)):
        a = cpu.scan(spec)
        b = tpu.scan(spec)
        assert a.rows == b.rows
