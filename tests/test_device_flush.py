"""Device-side memtable flush: the single-dispatch replay (ops.flush)
must produce runs byte-identical to the host columnar build, and every
ineligible or faulted flush must fall back to the host path untouched.

Reference analog: the rocksdb flush-job tests asserting the built
SSTable matches the memtable contents (src/yb/rocksdb/db/flush_job_test.cc)
— here "matches" is literal plane equality, because the authoritative
host planes are read back from the very arrays the device will scan.

Runs on the CPU JAX backend (conftest) — same kernels the TPU executes.
"""

import random

import numpy as np
import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import RowVersion, ScanSpec, make_engine
from yugabyte_db_tpu.storage.residency import hbm_cache
from yugabyte_db_tpu.storage.row_version import MAX_HT
from yugabyte_db_tpu.utils.fault_injection import arm_fault_once, clear_faults
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import flush_path_count
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("d", DataType.INT32),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def ids(schema):
    return {c.name: c.col_id for c in schema.value_columns}


@pytest.fixture
def device_flush_flag():
    old = FLAGS.get("tpu_device_flush")
    yield lambda v: FLAGS.set("tpu_device_flush", bool(v))
    FLAGS.set("tpu_device_flush", old)
    clear_faults()


@pytest.fixture
def budget_flag():
    old = FLAGS.get("tpu_hbm_budget_bytes")
    yield lambda v: FLAGS.set("tpu_hbm_budget_bytes", int(v))
    FLAGS.set("tpu_hbm_budget_bytes", old)
    hbm_cache().evict_unpinned()


def sample_rows(schema, n=200, seed=11):
    """Apply-order rows with every plane family exercised: multi-version
    keys, tombstones, nulls, TTL expiry, doubles, varlen strings, and
    same-(key, ht) write_id ties."""
    rnd = random.Random(seed)
    cids = ids(schema)
    rows, ht = [], 0
    for i in range(n):
        ht += rnd.randrange(1, 3)
        key = enc(schema, rnd.choice(["p", "q", "rr"]), i % 41)
        roll = rnd.random()
        if roll < 0.1:
            rows.append(RowVersion(key, ht=ht, tombstone=True,
                                   write_id=i % 7))
        elif roll < 0.55:
            rows.append(RowVersion(
                key, ht=ht, liveness=True, write_id=i % 7,
                columns={cids["a"]: rnd.randrange(-1000, 1000),
                         cids["b"]: rnd.choice(["xy", "xyz-longer", None,
                                                "commonprefix-aa",
                                                "commonprefix-ab"]),
                         cids["c"]: rnd.uniform(-5, 5),
                         cids["d"]: rnd.randrange(-50, 50)},
                expire_ht=ht + 40 if rnd.random() < 0.2 else MAX_HT))
        else:
            col = rnd.choice(["a", "b", "c", "d"])
            val = {"a": rnd.randrange(-1000, 1000),
                   "b": rnd.choice(["w", None]),
                   "c": rnd.uniform(-5, 5),
                   "d": rnd.randrange(-50, 50)}[col]
            rows.append(RowVersion(key, ht=ht, write_id=i % 7,
                                   columns={cids[col]: val}))
    return rows, ht


def assert_runs_identical(a, b):
    """Byte-level equality of two ColumnarRuns: every plane, every host
    payload, every block bound, every metadata field."""
    assert a.B == b.B and a.R == b.R
    for name in ("valid", "group_start", "tomb", "live",
                 "ht_hi", "ht_lo", "exp_hi", "exp_lo", "key_planes"):
        assert np.array_equal(getattr(a, name), getattr(b, name)), name
    assert set(a.cols) == set(b.cols)
    for cid, ca in a.cols.items():
        cb = b.cols[cid]
        assert np.array_equal(ca.set_, cb.set_), cid
        assert np.array_equal(ca.isnull, cb.isnull), cid
        assert np.array_equal(ca.cmp_planes, cb.cmp_planes), cid
        assert (ca.arith is None) == (cb.arith is None)
        if ca.arith is not None:
            assert np.array_equal(ca.arith, cb.arith), cid
        if ca.varlen is not None:
            assert ca.varlen == cb.varlen, cid
    assert np.array_equal(a.row_keys, b.row_keys)
    assert a.blocks == b.blocks
    for f in ("num_versions", "min_key", "max_key", "max_ht",
              "max_group_versions", "max_key_len", "varlen_max_len"):
        assert getattr(a, f) == getattr(b, f), f


def assert_same_scan(cpu, tpu, spec_kwargs):
    a = cpu.scan(ScanSpec(**spec_kwargs))
    b = tpu.scan(ScanSpec(**spec_kwargs))
    assert a.columns == b.columns
    assert len(a.rows) == len(b.rows)
    for ra, rb in zip(a.rows, b.rows):
        for i, (va, vb) in enumerate(zip(ra, rb)):
            if isinstance(va, float):
                assert vb == pytest.approx(va, rel=1e-4, abs=1e-4)
            else:
                assert va == vb, f"col={a.columns[i]} spec={spec_kwargs}"


def test_device_flush_planes_identical_to_host_build(device_flush_flag):
    """The replayed-on-device run must equal the host columnar build
    bit-for-bit — same sort, same block packing, same padding encoding."""
    schema = make_schema()
    rows, _ = sample_rows(schema)

    device_flush_flag(True)
    on = make_engine("tpu", schema, dict(rows_per_block=64))
    on.apply(rows)
    dev0 = flush_path_count("device")
    on.flush()
    assert flush_path_count("device") == dev0 + 1

    device_flush_flag(False)
    off = make_engine("tpu", schema, dict(rows_per_block=64))
    off.apply(rows)
    host0 = flush_path_count("host")
    off.flush()
    assert flush_path_count("host") == host0 + 1

    assert_runs_identical(on.runs[-1].crun, off.runs[-1].crun)


def test_device_flush_scan_identity_vs_cpu_oracle(device_flush_flag):
    device_flush_flag(True)
    schema = make_schema()
    rows, max_ht = sample_rows(schema, n=300, seed=7)
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    cpu.apply(rows); tpu.apply(rows)
    cpu.flush(); tpu.flush()
    for rht in (MAX_HT, max_ht // 2, max_ht - 20, 1):
        assert_same_scan(cpu, tpu, dict(read_ht=rht))
    lo, hi = enc(schema, "p", 5), enc(schema, "p", 30)
    assert_same_scan(cpu, tpu, dict(lower=lo, upper=hi, read_ht=MAX_HT))


def test_write_id_tie_ordering(device_flush_flag):
    """Two writes to the same key in one batch share a hybrid time and
    order by write_id — the flush sort key must break the tie so the
    later write wins, exactly as the CPU oracle resolves it."""
    device_flush_flag(True)
    schema = make_schema()
    cids = ids(schema)
    key = enc(schema, "p", 1)
    rows = [
        RowVersion(key, ht=10, liveness=True, write_id=0,
                   columns={cids["a"]: 1}),
        RowVersion(key, ht=10, write_id=1, columns={cids["a"]: 2}),
        RowVersion(key, ht=10, write_id=2, columns={cids["a"]: 3}),
    ]
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    cpu.apply(rows); tpu.apply(rows)
    cpu.flush(); tpu.flush()
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    got = tpu.scan(ScanSpec(read_ht=MAX_HT, projection=["k", "r", "a"]))
    assert [r[-1] for r in got.rows] == [3]


def test_budget_gate_falls_back_to_host(device_flush_flag, budget_flag):
    """A flush whose padded planes exceed --tpu_hbm_budget_bytes must
    take the host path (the seed would immediately thrash the cache)."""
    device_flush_flag(True)
    budget_flag(1000)
    schema = make_schema()
    rows, _ = sample_rows(schema, n=100, seed=3)
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    cpu.apply(rows); tpu.apply(rows)
    host0, dev0 = flush_path_count("host"), flush_path_count("device")
    cpu.flush(); tpu.flush()
    assert flush_path_count("host") == host0 + 1
    assert flush_path_count("device") == dev0
    budget_flag(0)
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))


def test_oversized_keys_fall_back_to_host(device_flush_flag):
    """Keys past the 32-byte prefix planes make the host-side memcmp
    sort inexact — the engine must refuse the device path."""
    device_flush_flag(True)
    schema = make_schema()
    cids = ids(schema)
    rows = [RowVersion(enc(schema, "x" * 40 + str(i), i), ht=5 + i,
                       liveness=True, columns={cids["a"]: i})
            for i in range(8)]
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    tpu.apply(rows)
    host0, dev0 = flush_path_count("host"), flush_path_count("device")
    tpu.flush()
    assert flush_path_count("host") == host0 + 1
    assert flush_path_count("device") == dev0
    got = tpu.scan(ScanSpec(read_ht=MAX_HT, projection=["a"]))
    assert sorted(r[0] for r in got.rows) == list(range(8))


def test_dispatch_fault_falls_back_then_recovers(device_flush_flag):
    """A device fault mid-flush lands on the breaker and the flush
    retries on the host path — no data loss, and the NEXT flush (fault
    cleared, breaker still closed) is back on the device path."""
    device_flush_flag(True)
    schema = make_schema()
    cids = ids(schema)
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    rows1, _ = sample_rows(schema, n=60, seed=1)
    cpu.apply(rows1); tpu.apply(rows1)
    host0, dev0 = flush_path_count("host"), flush_path_count("device")
    arm_fault_once("fault.tpu_dispatch")
    cpu.flush(); tpu.flush()
    assert flush_path_count("host") == host0 + 1
    assert flush_path_count("device") == dev0

    rows2 = [RowVersion(enc(schema, "z", i), ht=10_000 + i, liveness=True,
                        columns={cids["a"]: i}) for i in range(20)]
    cpu.apply(rows2); tpu.apply(rows2)
    cpu.flush(); tpu.flush()
    assert flush_path_count("device") == dev0 + 1
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))


def test_seeded_run_survives_eviction_roundtrip(device_flush_flag,
                                                budget_flag):
    """The seeded device payload must be evictable like any demand
    upload, and the re-upload (from the round-tripped host planes) must
    scan identically — host planes stay authoritative."""
    device_flush_flag(True)
    schema = make_schema()
    rows, _ = sample_rows(schema, n=150, seed=9)
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, dict(rows_per_block=64))
    cpu.apply(rows); tpu.apply(rows)
    dev0 = flush_path_count("device")
    cpu.flush(); tpu.flush()
    assert flush_path_count("device") == dev0 + 1

    # Seeded payload is already resident: the first scan must not
    # demand-upload the freshly flushed run.
    up0 = hbm_cache().stats()["demand_upload_bytes"]
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert hbm_cache().stats()["demand_upload_bytes"] == up0

    assert hbm_cache().evict_unpinned() > 0
    assert_same_scan(cpu, tpu, dict(read_ht=MAX_HT))
    assert hbm_cache().stats()["demand_upload_bytes"] > up0
