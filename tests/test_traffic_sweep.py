"""Traffic-sweep harness: seeded mixed-protocol traffic with splits,
a rolling follower restart, and leader rebalancing mid-stream.

Tier 1 runs ONE deterministic short seeded round-set (fixed seed and
op counts, so the replay is byte-for-byte) asserting the full
contract: >= 2 splits and >= 1 leader move fired mid-stream, zero
acked writes lost, post-split results byte-identical to the no-split
CPU-oracle replay, residency/MemTracker clean, per-protocol SLOs
green. The longer randomized-seed sweeps run under ``-m slow``.
"""

import tempfile

import pytest

from yugabyte_db_tpu.integration.traffic_sweep import (PROTOCOLS,
                                                       TrafficSweep,
                                                       run_sweep)


def test_deterministic_short_sweep():
    with tempfile.TemporaryDirectory() as root:
        out = TrafficSweep(root, seed=1234, rounds=3, ops_per_round=36,
                           keyspace=64).run()
    assert out["splits_fired"] >= 2
    assert out["leader_moves"] >= 1
    # Lineage names both seed parents with two children each.
    for rec in out["split_lineage"]:
        assert len(rec["children"]) == 2
    # Every protocol actually ran and reported latency percentiles.
    for proto in PROTOCOLS:
        stats = out["protocols"][proto]
        assert stats["ops"] > 0, proto
        assert stats["p99_ms"] >= stats["p50_ms"] >= 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [7, 424242])
def test_randomized_sweep(seed):
    with tempfile.TemporaryDirectory() as root:
        out = run_sweep(root, seed=seed)
    assert out["splits_fired"] >= 2
    assert out["leader_moves"] >= 1
