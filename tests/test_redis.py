"""RESP/Redis frontend tests: raw socket client against a MiniCluster.

Reference test analog: java/yb-jedis-tests driving the YEDIS proxy.
"""

import socket
import time

import pytest

from yugabyte_db_tpu.integration import MiniCluster
from yugabyte_db_tpu.yql.redis import RedisServer


class RespClient:
    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.buf = b""

    def close(self):
        self.sock.close()

    def cmd(self, *args):
        out = [f"*{len(args)}\r\n".encode()]
        for a in args:
            b = str(a).encode() if not isinstance(a, bytes) else a
            out.append(f"${len(b)}\r\n".encode() + b + b"\r\n")
        self.sock.sendall(b"".join(out))
        return self._read_reply()

    def _readline(self):
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            assert chunk, "closed"
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _readn(self, n):
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            assert chunk, "closed"
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._readline()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RedisError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return (None if n < 0 else
                    self._readn(n).decode("utf-8", "surrogateescape"))
        if t == b"*":
            n = int(rest)
            return None if n < 0 else [self._read_reply()
                                       for _ in range(n)]
        raise AssertionError(line)


class RedisError(Exception):
    pass


@pytest.fixture
def redis_cli(tmp_path):
    c = MiniCluster(str(tmp_path), num_masters=1, num_tservers=3).start()
    c.wait_tservers_registered()
    server = RedisServer(c.client("redis-proxy"))
    host, port = server.listen("127.0.0.1", 0)
    cli = RespClient(host, port)
    yield cli
    cli.close()
    server.shutdown()
    c.shutdown()


def test_strings(redis_cli):
    r = redis_cli
    assert r.cmd("PING") == "PONG"
    assert r.cmd("SET", "k1", "hello") == "OK"
    assert r.cmd("GET", "k1") == "hello"
    assert r.cmd("GET", "missing") is None
    assert r.cmd("APPEND", "k1", " world") == 11
    assert r.cmd("STRLEN", "k1") == 11
    assert r.cmd("GETSET", "k1", "v2") == "hello world"
    assert r.cmd("SETNX", "k1", "nope") == 0
    assert r.cmd("SETNX", "k2", "yes") == 1
    assert r.cmd("MSET", "a", "1", "b", "2") == "OK"
    assert r.cmd("MGET", "a", "b", "nope") == ["1", "2", None]
    assert r.cmd("INCR", "ctr") == 1
    assert r.cmd("INCRBY", "ctr", 41) == 42
    assert r.cmd("DECR", "ctr") == 41
    assert r.cmd("EXISTS", "k1", "missing") == 1
    assert r.cmd("DEL", "k1") == 1
    assert r.cmd("GET", "k1") is None
    with pytest.raises(RedisError):
        r.cmd("SET", "x", "1", "BOGUS")


def test_ttl_native_expiry(redis_cli):
    r = redis_cli
    assert r.cmd("SET", "tmp", "v", "PX", "1500") == "OK"
    assert r.cmd("GET", "tmp") == "v"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if r.cmd("GET", "tmp") is None:
            break
        time.sleep(0.1)
    assert r.cmd("GET", "tmp") is None
    assert r.cmd("SETEX", "tmp2", "600", "keep") == "OK"
    assert r.cmd("GET", "tmp2") == "keep"


def test_hashes(redis_cli):
    r = redis_cli
    assert r.cmd("HSET", "h", "f1", "v1", "f2", "v2") == 2
    assert r.cmd("HGET", "h", "f1") == "v1"
    assert r.cmd("HMGET", "h", "f1", "f2", "f3") == ["v1", "v2", None]
    assert r.cmd("HEXISTS", "h", "f1") == 1
    assert r.cmd("HLEN", "h") == 2
    got = r.cmd("HGETALL", "h")
    assert dict(zip(got[::2], got[1::2])) == {"f1": "v1", "f2": "v2"}
    assert sorted(r.cmd("HKEYS", "h")) == ["f1", "f2"]
    assert r.cmd("HDEL", "h", "f1") == 1
    assert r.cmd("HGET", "h", "f1") is None
    # strings and hashes don't collide on the same key namespace row
    assert r.cmd("SET", "h2", "strval") == "OK"
    assert r.cmd("HSET", "h2", "f", "x") == 1
    assert r.cmd("GET", "h2") == "strval"
    assert r.cmd("HGET", "h2", "f") == "x"


def test_sets_and_keys(redis_cli):
    r = redis_cli
    assert r.cmd("SADD", "s", "a", "b", "c") == 3
    assert r.cmd("SADD", "s", "a") == 0
    assert r.cmd("SCARD", "s") == 3
    assert r.cmd("SISMEMBER", "s", "b") == 1
    assert r.cmd("SREM", "s", "b") == 1
    assert r.cmd("SMEMBERS", "s") == ["a", "c"]
    r.cmd("SET", "user:1", "x")
    r.cmd("SET", "user:2", "y")
    r.cmd("SET", "other", "z")
    assert sorted(r.cmd("KEYS", "user:*")) == ["user:1", "user:2"]
    with pytest.raises(RedisError):
        r.cmd("NOSUCHCMD")


def test_binary_values_and_atomic_errors(redis_cli):
    r = redis_cli
    # arbitrary bytes round-trip (values are not required to be UTF-8)
    blob = bytes([0, 255, 137, 254, 10, 13, 0])
    assert r.cmd("SET", "bin", blob) == "OK"
    got = r.cmd("GET", "bin")
    assert got.encode("utf-8", "surrogateescape") == blob
    # an odd-arity HSET/MSET is rejected whole: no partial fields leak
    with pytest.raises(RedisError):
        r.cmd("HSET", "ah", "f1", "v1", "f2")
    assert r.cmd("HGET", "ah", "f1") is None
    with pytest.raises(RedisError):
        r.cmd("MSET", "am", "1", "am2")
    assert r.cmd("GET", "am") is None
