"""Standing stall detector (reference: kernel_stack_watchdog.h): flags
in-flight sections past their threshold and late completions the
sampler missed; the stress rigs consult the records as a standing
check."""

import time

from yugabyte_db_tpu.utils.watchdog import StallWatchdog


def test_flags_inflight_and_late_sections():
    wd = StallWatchdog(interval_s=0.05)
    with wd.watch("fast", threshold_s=1.0):
        pass
    assert wd.stalls() == []
    # In-flight past threshold: sampler flags while still running.
    with wd.watch("slow.sampled", threshold_s=0.1):
        time.sleep(0.4)
    recs = wd.stalls("slow.sampled")
    assert recs and recs[0]["seconds"] >= 0.1
    assert any(not r["completed"] for r in recs)
    # Late completion between samples: flagged post-hoc, once.
    wd2 = StallWatchdog(interval_s=30.0)
    with wd2.watch("slow.late", threshold_s=0.01):
        time.sleep(0.05)
    recs = wd2.stalls("slow.late")
    assert len(recs) == 1 and recs[0]["completed"]
    assert wd2.stall_count == 1
    wd2.reset()
    assert wd2.stalls() == []


def test_wal_sync_is_watched(tmp_path):
    """The WAL's group-commit sync registers with the process watchdog
    (smoke: a normal sync produces no stall records)."""
    from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId
    from yugabyte_db_tpu.utils.watchdog import watchdog

    watchdog().reset()
    log = Log(str(tmp_path), fsync=True)
    log.append(LogEntry(OpId(1, 1), 5, "write", {"x": 1}))
    log.sync()
    assert watchdog().stalls("wal.sync") == []
