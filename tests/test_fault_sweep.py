"""Fault-sweep harness: seeded fault rounds against a mini-cluster.

Tier 1 runs a deterministic schedule — one round per catalog fault with
a fixed ``fault.seed`` — checking all four invariants (acked-write
durability, device/host engine diff, residency pins, MemTracker
baseline) after every round. The full randomized sweep (rng-chosen
faults over several seeds) runs under ``-m slow``.
"""

import tempfile

import pytest

from yugabyte_db_tpu.integration.fault_sweep import (ARMED_FLAG,
                                                     FAULT_CATALOG,
                                                     HANDLER_FLAG,
                                                     FaultSweep, run_sweep)


def test_deterministic_schedule_covers_catalog():
    with tempfile.TemporaryDirectory() as root:
        summary = FaultSweep(root, seed=1234, ops_per_round=8,
                             schedule=FAULT_CATALOG).run()
    assert summary["rounds"] == len(FAULT_CATALOG)
    # Every armed fault point verifiably fired (the harness also
    # asserts this against yb_faults_fired internally).
    assert summary["faults_fired"] == {
        name: 1 for name in (*ARMED_FLAG, *HANDLER_FLAG)}


@pytest.mark.slow
@pytest.mark.parametrize("seed", [7, 1977, 424242])
def test_randomized_sweep(seed):
    with tempfile.TemporaryDirectory() as root:
        summary = run_sweep(root, seed=seed, rounds=8, ops_per_round=24)
    assert summary["rounds"] == 8
