"""Native wire page server vs the Python serializer on the CPU oracle.

scan_batch_wire must produce byte-identical pages from two independent
implementations: the TPU engine's native C emitter reading plane buffers
(native/writeplane.cc WireEmit) and the CPU oracle's scan + Python
serialization (models.wirefmt). Mirrors the reference's contract that
rows serialize once into rows_data (src/yb/common/ql_rowblock.h:66) and
the frontends forward bytes.
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (
    AggSpec, Predicate, RowVersion, ScanSpec, make_engine,
)
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("t8", DataType.INT8),
        ColumnSchema("t16", DataType.INT16),
        ColumnSchema("i", DataType.INT32),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("ts", DataType.TIMESTAMP),
        ColumnSchema("f", DataType.FLOAT),
        ColumnSchema("c", DataType.DOUBLE),
        ColumnSchema("bl", DataType.BOOL),
        ColumnSchema("s", DataType.STRING),
        ColumnSchema("by", DataType.BINARY),
    ], table_id="wire")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def load_engines(n=400, seed=17):
    schema = make_schema()
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema, {"rows_per_block": 64})
    rng = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.value_columns}
    ht = 10
    rows = []
    for i in range(n):
        ht += rng.randrange(1, 3)
        key = enc(schema, f"w{i:05d}", i % 5)
        if rng.random() < 0.04:
            rows.append(RowVersion(key, ht=ht, tombstone=True))
            continue
        cols = {}
        if rng.random() < 0.9:
            cols[cid["t8"]] = rng.randrange(-128, 128)
        if rng.random() < 0.9:
            cols[cid["t16"]] = rng.randrange(-2**15, 2**15)
        if rng.random() < 0.9:
            cols[cid["i"]] = rng.randrange(-2**31, 2**31)
        if rng.random() < 0.9:
            cols[cid["a"]] = rng.randrange(-2**62, 2**62)
        if rng.random() < 0.8:
            cols[cid["ts"]] = rng.randrange(0, 2**50)
        if rng.random() < 0.8:
            cols[cid["f"]] = rng.uniform(-1e5, 1e5)
        if rng.random() < 0.8:
            cols[cid["c"]] = rng.uniform(-1e9, 1e9)
        if rng.random() < 0.8:
            cols[cid["bl"]] = rng.random() < 0.5
        if rng.random() < 0.8:
            cols[cid["s"]] = f"val-{rng.randrange(10**6)}-é"
        if rng.random() < 0.7:
            cols[cid["by"]] = rng.randbytes(rng.randrange(0, 12))
        rows.append(RowVersion(key, ht=ht, liveness=True, columns=cols))
    cpu.apply(rows)
    cpu.flush()
    tpu.apply(rows)
    tpu.flush()
    return schema, cpu, tpu, ht


SPECS = [
    lambda S, ht: ScanSpec(read_ht=ht + 1, limit=50),
    lambda S, ht: ScanSpec(read_ht=ht + 1, limit=7,
                           projection=["k", "r", "a", "i"]),
    lambda S, ht: ScanSpec(read_ht=ht + 1, limit=100,
                           predicates=[Predicate("i", ">=", 0)]),
    lambda S, ht: ScanSpec(read_ht=ht + 1, limit=100,
                           predicates=[Predicate("a", "<", 0),
                                       Predicate("c", ">=", -5e8)],
                           projection=["k", "a", "c", "f", "s", "by",
                                       "bl", "t8", "t16", "ts"]),
    lambda S, ht: ScanSpec(read_ht=ht // 2, limit=64),  # historical read
]


def wire_pages_equal(a, b):
    assert a.columns == b.columns
    assert a.nrows == b.nrows
    assert a.resume == b.resume
    assert a.data == b.data


@pytest.mark.parametrize("fmt", ["cql", "pg"])
def test_wire_parity_single_run(fmt):
    schema, cpu, tpu, ht = load_engines()
    specs = [mk(schema, ht) for mk in SPECS]
    # Paging chains from varying lower bounds.
    for i in range(0, 400, 37):
        specs.append(ScanSpec(lower=enc(schema, f"w{i:05d}", 0),
                              read_ht=ht + 1, limit=20,
                              projection=["k", "r", "a", "s"]))
    got = tpu.scan_batch_wire(specs, fmt)
    want = cpu.scan_batch_wire(specs, fmt)
    for g, w in zip(got, want):
        wire_pages_equal(g, w)


def test_wire_native_path_used():
    """The flat-run LIMIT-page shape must ride the native emitter (no
    Python row construction); guard the fast path against regressions
    that silently fall back."""
    pytest.importorskip("yugabyte_db_tpu.native.yb_wp")
    from yugabyte_db_tpu.storage import host_page
    if host_page._native is None:
        pytest.skip("native page server unavailable")
    schema, cpu, tpu, ht = load_engines()
    spec = ScanSpec(read_ht=ht + 1, limit=10,
                    predicates=[Predicate("i", ">=", 0)],
                    projection=["k", "r", "a", "i"])
    served = host_page.serve_pages_wire(
        tpu, [(tpu.runs[0], spec,
               host_page.encode_pred_items(tpu, spec.predicates))],
        host_page.WIRE_CQL)
    assert served[0] is not None
    want = cpu.scan_batch_wire([spec], "cql")[0]
    wire_pages_equal(served[0], want)


@pytest.mark.parametrize("fmt", ["cql", "pg"])
def test_wire_parity_multisource_fallback(fmt):
    """Live memtable + overlapping runs: the wire API must fall back to
    the merged scan path and still produce identical bytes."""
    schema, cpu, tpu, ht = load_engines(n=200)
    cid = {c.name: c.col_id for c in schema.value_columns}
    rng = random.Random(5)
    more = []
    for i in range(0, 200, 3):
        ht += 1
        more.append(RowVersion(enc(schema, f"w{i:05d}", i % 5), ht=ht,
                               columns={cid["a"]: rng.randrange(-100, 100)}))
    cpu.apply(more)
    tpu.apply(more)  # memtable stays live: multi-source
    specs = [ScanSpec(read_ht=ht + 1, limit=30,
                      projection=["k", "r", "a", "s"]),
             ScanSpec(read_ht=ht + 1, limit=25,
                      predicates=[Predicate("i", ">=", 0)])]
    for g, w in zip(tpu.scan_batch_wire(specs, fmt),
                    cpu.scan_batch_wire(specs, fmt)):
        wire_pages_equal(g, w)


@pytest.mark.parametrize("fmt", ["cql", "pg"])
def test_point_get_parity(fmt):
    """Exact-key GETs (the processor's [key, key+0xff) shape) against
    the oracle: flat run (native path), then with a live memtable and
    overlapping runs (the dedicated bloom-pruned point path)."""
    schema, cpu, tpu, ht = load_engines(n=300)
    cid = {c.name: c.col_id for c in schema.value_columns}

    def point_specs(rht):
        specs = []
        for i in list(range(0, 300, 11)) + [999]:  # incl. missing key
            key = enc(schema, f"w{i:05d}", i % 5)
            specs.append(ScanSpec(lower=key, upper=key + b"\xff",
                                  read_ht=rht, limit=1))
            specs.append(ScanSpec(lower=key, upper=key + b"\xff",
                                  read_ht=rht,
                                  projection=["k", "a", "s"],
                                  predicates=[Predicate("i", ">=", 0)]))
        return specs

    for g, w in zip(tpu.scan_batch_wire(point_specs(ht + 1), fmt),
                    cpu.scan_batch_wire(point_specs(ht + 1), fmt)):
        wire_pages_equal(g, w)

    # Updates + tombstones into the memtable, plus a second run.
    rng = random.Random(7)
    more = []
    for i in range(0, 300, 4):
        ht += 1
        key = enc(schema, f"w{i:05d}", i % 5)
        if i % 20 == 0:
            more.append(RowVersion(key, ht=ht, tombstone=True))
        else:
            more.append(RowVersion(key, ht=ht, columns={
                cid["a"]: rng.randrange(-100, 100)}))
    half = len(more) // 2
    for e in (cpu, tpu):
        e.apply(more[:half])
        e.flush()           # second overlapping run
        e.apply(more[half:])  # live memtable
    assert not tpu.memtable.is_empty and len(tpu.runs) == 2
    for g, w in zip(tpu.scan_batch_wire(point_specs(ht + 1), fmt),
                    cpu.scan_batch_wire(point_specs(ht + 1), fmt)):
        wire_pages_equal(g, w)
    # Historical read below the updates still parities.
    for g, w in zip(tpu.scan_batch_wire(point_specs(ht // 2), fmt),
                    cpu.scan_batch_wire(point_specs(ht // 2), fmt)):
        wire_pages_equal(g, w)


def test_wire_aggregate_fallback():
    schema, cpu, tpu, ht = load_engines(n=150)
    spec = ScanSpec(read_ht=ht + 1,
                    aggregates=[AggSpec("count", None),
                                AggSpec("sum", "a")])
    g = tpu.scan_batch_wire([spec], "cql")[0]
    w = cpu.scan_batch_wire([spec], "cql")[0]
    wire_pages_equal(g, w)


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_cql_frontend_wire_frames_identical(tmp_path, engine):
    """End-to-end CQL: a SELECT served through the wire path
    (wire_results=True, the socket server's mode) must produce the exact
    RESULT frame of the row path — header, cells, paging state."""
    from yugabyte_db_tpu.yql.cql import QLProcessor
    from yugabyte_db_tpu.yql.cql.processor import LocalCluster
    from yugabyte_db_tpu.yql.cql import wire_protocol as W

    cluster = LocalCluster(str(tmp_path), num_tablets=3, engine=engine,
                           engine_options={"rows_per_block": 16})
    try:
        ql = QLProcessor(cluster)
        ql.execute("CREATE TABLE kv (k text, r int, v bigint, s text, "
                   "d double, bb boolean, PRIMARY KEY ((k), r))")
        for i in range(60):
            ql.execute(
                f"INSERT INTO kv (k, r, v, s, d, bb) VALUES "
                f"('key{i % 7}', {i}, {i * 10**10}, 'val{i}', "
                f"{i * 1.5}, {'true' if i % 2 else 'false'})")
        for t in cluster.table("default.kv").tablets:
            t.engine.flush()
        from yugabyte_db_tpu.models.wirefmt import serialize_rows

        schema = cluster.table("default.kv").schema
        for sql in (
                "SELECT * FROM kv",
                "SELECT k, v, s FROM kv WHERE v >= 100000000000",
                "SELECT * FROM kv WHERE k = 'key3'",
                "SELECT * FROM kv LIMIT 9",
        ):
            rrow = ql.execute(sql)
            rwire = ql.execute(sql, wire_results=True)
            dts = [schema.column(n).dtype for n in rrow.columns]
            cols = list(zip(rrow.columns, dts))
            f_row = W.rows_result(1, "default", "kv", cols, rrow.rows,
                                  paging_state=rrow.paging_state)
            assert rwire.wire_data is not None, sql
            f_wire = W.rows_result_wire(
                1, "default", "kv", cols, rwire.wire_rows,
                rwire.wire_data, paging_state=rwire.paging_state)
            assert f_row == f_wire, sql
        # Paged chains pin their own read time inside the paging token,
        # so tokens differ bytewise between two executions; compare the
        # serialized CELLS and total coverage instead.
        all_rows, paging = [], None
        while True:
            rrow = ql.execute("SELECT * FROM kv", page_size=10,
                              paging_state=paging)
            all_rows.extend(rrow.rows)
            paging = rrow.paging_state
            if paging is None:
                break
        all_bytes, nrows, paging = [], 0, None
        while True:
            rwire = ql.execute("SELECT * FROM kv", page_size=10,
                               paging_state=paging, wire_results=True)
            assert rwire.wire_data is not None
            all_bytes.append(rwire.wire_data)
            nrows += rwire.wire_rows
            paging = rwire.paging_state
            if paging is None:
                break
        dts = [schema.column(n).dtype for n in rrow.columns]
        assert nrows == len(all_rows) == 60
        assert b"".join(all_bytes) == serialize_rows("cql", dts, all_rows)
    finally:
        cluster.close()


def test_wire_resume_chain_covers_table():
    """Following resume tokens through wire pages visits every visible
    row exactly once (CQL paging contract)."""
    schema, cpu, tpu, ht = load_engines(n=300)
    full = cpu.scan(ScanSpec(read_ht=ht + 1, projection=["k", "r"]))
    seen = 0
    lower = b""
    while True:
        pg = tpu.scan_batch_wire(
            [ScanSpec(lower=lower, read_ht=ht + 1, limit=37,
                      projection=["k", "r"])], "cql")[0]
        seen += pg.nrows
        if pg.resume is None:
            break
        lower = pg.resume
    assert seen == len(full.rows)
