"""Consistency-under-churn rig: a chained write workload against a real
MULTI-PROCESS cluster while tservers are SIGKILLed and restarted in a
loop, then full-chain invariant verification plus cross-replica
checksums (ysck).

Reference analog: the linked_list-test.cc discipline —
TestWorkload-style sustained load under ExternalMiniCluster process
kills, verified with ClusterVerifier (checksum scans) afterwards
(src/yb/integration-tests/linked_list-test.cc, cluster_verifier.cc).

Invariants checked after >= 20 kill cycles:
- every ACK'd write is present with its chained value (no lost acks);
- no row exists outside acked + unknown-outcome writes (no invented or
  duplicated rows — keys are unique per op, so a duplicated replay
  would surface as an unexpected key or wrong chain value);
- replica checksums agree across the RF=3 groups (ysck).
"""

import os
import random
import signal
import time

import pytest

from yugabyte_db_tpu.tools.yb_ctl import ClusterCtl, _pid_alive

# Excluded from tier-1 (-m 'not slow'): multi-minute rig, full runs keep it.
pytestmark = pytest.mark.slow

KILL_CYCLES = 20


def _kill_tserver(ctl: ClusterCtl, uuid: str) -> None:
    state = ctl.load()
    for d in state["daemons"]:
        if d["uuid"] == uuid and d.get("pid") and _pid_alive(d["pid"]):
            os.kill(d["pid"], signal.SIGKILL)
            d["pid"] = None
    ctl.save(state)


def test_chained_writes_survive_kill_restart_cycles(tmp_path):
    from yugabyte_db_tpu.client.client import YBClient
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.models.datatypes import DataType
    from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
    from yugabyte_db_tpu.storage.scan_spec import ScanSpec
    from yugabyte_db_tpu.tools.admin_client import AdminClient
    from yugabyte_db_tpu.tools.ysck import Ysck

    ctl = ClusterCtl(os.path.join(str(tmp_path), "c"))
    ctl.create(num_masters=1, num_tservers=3)
    try:
        ctl.wait_tservers_registered()
        client = YBClient.connect(ctl.master_addresses())
        client.create_table("chain", [
            ColumnSchema("k", DataType.INT64, ColumnKind.HASH),
            ColumnSchema("prev", DataType.INT64),
        ], num_tablets=4)
        table = client.open_table("chain")

        rnd = random.Random(17)
        acked: set[int] = set()
        unknown: set[int] = set()
        next_key = 0
        tserver_uuids = ["ts-0", "ts-1", "ts-2"]

        def write_batch(n=40):
            nonlocal next_key
            s = YBSession(client)
            batch = list(range(next_key, next_key + n))
            next_key += n
            for i in batch:
                s.insert(table, {"k": i, "prev": i - 1})
            try:
                s.flush(timeout_s=8.0)
                acked.update(batch)
            except Exception:  # noqa: BLE001 — outcome unknown
                unknown.update(batch)

        for cycle in range(KILL_CYCLES):
            write_batch()
            victim = rnd.choice(tserver_uuids)
            _kill_tserver(ctl, victim)
            # Keep writing into the degraded cluster (leaders re-elect;
            # RF=3 tolerates one dead replica).
            for _ in range(3):
                write_batch()
            ctl.start()  # respawns the killed daemon
            write_batch()

        # Let the cluster settle and the client recover addresses.
        deadline = time.monotonic() + 60.0
        rows = None
        while time.monotonic() < deadline:
            try:
                client.refresh_tserver_addresses()
                res = YBSession(client).scan(
                    table, ScanSpec(projection=["k", "prev"]),
                    timeout_s=30.0)
                rows = {r[0]: r[1] for r in res.rows}
                if acked <= set(rows):
                    break
            except Exception:  # noqa: BLE001 — retried until deadline
                pass
            time.sleep(1.0)
        assert rows is not None, "cluster never became readable"

        assert len(acked) >= KILL_CYCLES * 100, "workload too small"
        missing = acked - set(rows)
        assert not missing, f"LOST {len(missing)} acked writes: " \
                            f"{sorted(missing)[:10]}"
        invented = set(rows) - acked - unknown
        assert not invented, f"rows outside acked+unknown: " \
                             f"{sorted(invented)[:10]}"
        bad_chain = [k for k, prev in rows.items() if prev != k - 1]
        assert not bad_chain, f"chain values corrupted: {bad_chain[:10]}"

        # Cross-replica consistency (the ClusterVerifier step).
        report = Ysck(AdminClient(client.transport,
                          client.master_uuids)).check_cluster(
            ["chain"])
        assert report.ok, report.summary()
    finally:
        try:
            ctl.stop()
        except Exception:  # noqa: BLE001
            pass
