"""SQL executor features: CTEs, OFFSET, correlated subqueries, scalar
functions, col-vs-col predicates.

Reference capability: everything stock PG 11.2's executor provides above
the FDW scans (src/postgres/src/backend/executor — nodeCtescan.c,
nodeSubplan.c, utils/adt scalar functions); test style follows
src/yb/yql/pgwrapper/pg_libpq-test.cc.
"""

import pytest

from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.pgsql import PgProcessor
from yugabyte_db_tpu.yql.cql.processor import LocalCluster


@pytest.fixture(params=["cpu", "tpu"])
def pg(request, tmp_path):
    cluster = LocalCluster(str(tmp_path), num_tablets=2,
                           engine=request.param,
                           engine_options={"rows_per_block": 16})
    proc = PgProcessor(cluster)
    yield proc
    cluster.close()


def seed(pg):
    pg.execute("CREATE TABLE items (id bigint PRIMARY KEY, cat text, "
               "price bigint, qty int, name text)")
    data = [
        (1, "a", 100, 3, "apple"),
        (2, "a", 250, 1, "avocado"),
        (3, "b", 80, 7, "banana"),
        (4, "b", 300, 2, "berry"),
        (5, "b", 150, 5, "bread"),
        (6, "c", 40, 9, "candy"),
    ]
    for row in data:
        pg.execute("INSERT INTO items (id, cat, price, qty, name) VALUES "
                   f"({row[0]}, '{row[1]}', {row[2]}, {row[3]}, "
                   f"'{row[4]}')")
    return data


# -- OFFSET ------------------------------------------------------------------

def test_offset_with_order_and_limit(pg):
    seed(pg)
    r = pg.execute("SELECT id FROM items ORDER BY price DESC "
                   "LIMIT 2 OFFSET 1")
    assert r.rows == [(2,), (5,)]
    # OFFSET alone and OFFSET-before-LIMIT order both parse.
    r = pg.execute("SELECT id FROM items ORDER BY id OFFSET 4")
    assert r.rows == [(5,), (6,)]
    r = pg.execute("SELECT id FROM items ORDER BY id OFFSET 2 LIMIT 2")
    assert r.rows == [(3,), (4,)]
    r = pg.execute("SELECT id FROM items ORDER BY id OFFSET 99")
    assert r.rows == []


def test_offset_without_order(pg):
    seed(pg)
    all_ids = {r[0] for r in pg.execute("SELECT id FROM items").rows}
    got = pg.execute("SELECT id FROM items OFFSET 2").rows
    assert len(got) == 4 and {r[0] for r in got} <= all_ids


# -- CTEs --------------------------------------------------------------------

def test_cte_basic(pg):
    seed(pg)
    r = pg.execute(
        "WITH cheap AS (SELECT id, cat, price FROM items "
        "WHERE price < 200) "
        "SELECT id FROM cheap ORDER BY id")
    assert r.rows == [(1,), (3,), (5,), (6,)]


def test_cte_aggregate_over_cte(pg):
    seed(pg)
    r = pg.execute(
        "WITH b AS (SELECT * FROM items WHERE cat = 'b') "
        "SELECT count(*), sum(price), min(qty) FROM b")
    assert r.rows == [(3, 530, 2)]
    r = pg.execute(
        "WITH t AS (SELECT cat, price FROM items) "
        "SELECT cat, sum(price) FROM t GROUP BY cat ORDER BY cat")
    assert r.rows == [("a", 350), ("b", 530), ("c", 40)]


def test_cte_chained_and_filtered(pg):
    seed(pg)
    r = pg.execute(
        "WITH b AS (SELECT id, price FROM items WHERE cat = 'b'), "
        "pricey AS (SELECT id, price FROM b WHERE price >= 150) "
        "SELECT id FROM pricey ORDER BY price DESC LIMIT 1")
    assert r.rows == [(4,)]


def test_cte_expressions_and_alias(pg):
    seed(pg)
    r = pg.execute(
        "WITH t AS (SELECT id, price * qty AS total FROM items) "
        "SELECT id, total FROM t c WHERE c.total >= 500 ORDER BY id")
    assert r.rows == [(3, 560), (4, 600), (5, 750)]


def test_cte_name_shadows_table(pg):
    seed(pg)
    r = pg.execute(
        "WITH items AS (SELECT id FROM items WHERE cat = 'c') "
        "SELECT count(*) FROM items")
    assert r.rows == [(1,)]


# -- correlated subqueries ---------------------------------------------------

def test_correlated_scalar_subquery(pg):
    seed(pg)
    # Rows at their category's max price.
    r = pg.execute(
        "SELECT id FROM items i WHERE price = "
        "(SELECT max(price) FROM items i2 WHERE i2.cat = i.cat) "
        "ORDER BY id")
    assert r.rows == [(2,), (4,), (6,)]


def test_correlated_inequality(pg):
    seed(pg)
    # Rows above their category's average price.
    r = pg.execute(
        "SELECT id FROM items i WHERE price > "
        "(SELECT avg(price) FROM items i2 WHERE i2.cat = i.cat) "
        "ORDER BY id")
    assert r.rows == [(2,), (4,)]


def test_correlated_in_subquery(pg):
    seed(pg)
    pg.execute("CREATE TABLE tags (id bigint PRIMARY KEY, item bigint, "
               "tag text)")
    for i, (item, tag) in enumerate([(1, "x"), (3, "x"), (4, "y")]):
        pg.execute(f"INSERT INTO tags (id, item, tag) VALUES "
                   f"({i}, {item}, 'x')" if tag == "x" else
                   f"INSERT INTO tags (id, item, tag) VALUES "
                   f"({i}, {item}, 'y')")
    r = pg.execute(
        "SELECT id FROM items i WHERE id IN "
        "(SELECT item FROM tags t WHERE t.tag = 'x') ORDER BY id")
    assert r.rows == [(1,), (3,)]


def test_uncorrelated_subquery_still_works(pg):
    seed(pg)
    r = pg.execute(
        "SELECT id FROM items WHERE price = "
        "(SELECT max(price) FROM items)")
    assert r.rows == [(4,)]


def test_col_vs_col_predicate(pg):
    seed(pg)
    r = pg.execute("SELECT id FROM items WHERE qty > price ORDER BY id")
    assert r.rows == []
    r = pg.execute("SELECT id FROM items i WHERE i.price > i.qty "
                   "ORDER BY id")
    assert len(r.rows) == 6


# -- scalar functions --------------------------------------------------------

def test_scalar_functions_projection(pg):
    seed(pg)
    r = pg.execute(
        "SELECT upper(name), lower(cat), length(name), abs(0 - price) "
        "FROM items WHERE id = 1")
    assert r.rows == [("APPLE", "a", 5, 100)]
    r = pg.execute("SELECT coalesce(name, 'none'), nullif(cat, 'a') "
                   "FROM items WHERE id = 1")
    assert r.rows == [("apple", None)]
    r = pg.execute("SELECT greatest(price, qty), least(price, qty) "
                   "FROM items WHERE id = 3")
    assert r.rows == [(80, 7)]
    r = pg.execute("SELECT concat(cat, '-', name), substring(name, 2, 3)"
                   " FROM items WHERE id = 6")
    assert r.rows == [("c-candy", "and")]
    r = pg.execute("SELECT mod(price, 7), round(price * 3), floor(qty), "
                   "ceil(qty) FROM items WHERE id = 5")
    assert r.rows == [(150 % 7, 450, 5, 5)]


def test_scalar_functions_nest_in_exprs(pg):
    seed(pg)
    r = pg.execute("SELECT length(name) + qty, abs(qty - length(name)) "
                   "FROM items WHERE id = 3")
    assert r.rows == [(13, 1)]
    r = pg.execute("SELECT id FROM items WHERE id = 1")
    assert r.rows == [(1,)]


def test_scalar_functions_over_cte_and_view(pg):
    seed(pg)
    r = pg.execute(
        "WITH t AS (SELECT name, qty FROM items WHERE cat = 'b') "
        "SELECT upper(name) FROM t ORDER BY name LIMIT 2")
    assert r.rows == [("BANANA",), ("BERRY",)]
    pg.execute("CREATE VIEW v AS SELECT name, price FROM items "
               "WHERE cat = 'a'")
    r = pg.execute("SELECT concat(name, '!') FROM v ORDER BY name")
    assert r.rows == [("apple!",), ("avocado!",)]


def test_functions_null_semantics(pg):
    pg.execute("CREATE TABLE nv (id bigint PRIMARY KEY, s text, n int)")
    pg.execute("INSERT INTO nv (id) VALUES (1)")
    r = pg.execute("SELECT upper(s), length(s), abs(n), "
                   "coalesce(s, 'dflt'), concat(s, 'x'), "
                   "greatest(n, id), nullif(id, 99) FROM nv")
    assert r.rows == [(None, None, None, "dflt", "x", 1, 1)]


def test_with_recursive_rejected(pg):
    seed(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("WITH RECURSIVE r AS (SELECT id FROM items) "
                   "SELECT * FROM r")


# -- UNION / UNION ALL -------------------------------------------------------

def test_union_dedup_and_all(pg):
    seed(pg)
    r = pg.execute("SELECT cat FROM items WHERE price < 100 "
                   "UNION SELECT cat FROM items WHERE qty > 4 "
                   "ORDER BY cat")
    assert r.rows == [("b",), ("c",)]
    r = pg.execute("SELECT cat FROM items WHERE price < 100 "
                   "UNION ALL SELECT cat FROM items WHERE qty > 4 "
                   "ORDER BY cat")
    assert r.rows == [("b",), ("b",), ("b",), ("c",), ("c",)]


def test_union_order_limit_offset_bind_to_whole(pg):
    seed(pg)
    r = pg.execute("SELECT id FROM items WHERE cat = 'a' "
                   "UNION SELECT id FROM items WHERE cat = 'b' "
                   "ORDER BY id DESC LIMIT 3 OFFSET 1")
    assert r.rows == [(4,), (3,), (2,)]


def test_union_three_way_mixed(pg):
    seed(pg)
    # left-assoc: (a UNION ALL a) UNION b -> dedups everything so far
    r = pg.execute("SELECT cat FROM items WHERE id = 1 "
                   "UNION ALL SELECT cat FROM items WHERE id = 2 "
                   "UNION SELECT cat FROM items WHERE id = 3 "
                   "ORDER BY cat")
    assert r.rows == [("a",), ("b",)]


def test_union_arity_mismatch(pg):
    seed(pg)
    with pytest.raises(InvalidArgument):
        pg.execute("SELECT id FROM items UNION SELECT id, cat FROM items")


def test_union_in_cte_and_view(pg):
    seed(pg)
    r = pg.execute("WITH u AS (SELECT id FROM items WHERE id <= 2 "
                   "UNION SELECT id FROM items WHERE id >= 5) "
                   "SELECT count(*) FROM u")
    assert r.rows == [(4,)]
    pg.execute("CREATE VIEW uv AS SELECT id FROM items WHERE cat = 'a' "
               "UNION SELECT id FROM items WHERE cat = 'c'")
    r = pg.execute("SELECT id FROM uv ORDER BY id")
    assert r.rows == [(1,), (2,), (6,)]


def test_union_with_aggregates_per_branch(pg):
    seed(pg)
    r = pg.execute("SELECT count(*) FROM items WHERE cat = 'a' "
                   "UNION ALL SELECT count(*) FROM items WHERE cat = 'b'")
    assert sorted(r.rows) == [(2,), (3,)]


# -- EXISTS / NOT EXISTS -----------------------------------------------------

def seed_orders(pg):
    pg.execute("CREATE TABLE orders (oid bigint PRIMARY KEY, item bigint, "
               "n int)")
    for oid, item, n in [(1, 1, 2), (2, 1, 1), (3, 3, 5)]:
        pg.execute(f"INSERT INTO orders (oid, item, n) VALUES "
                   f"({oid}, {item}, {n})")


def test_exists_correlated(pg):
    seed(pg)
    seed_orders(pg)
    r = pg.execute("SELECT id FROM items i WHERE EXISTS "
                   "(SELECT 1 FROM orders o WHERE o.item = i.id) "
                   "ORDER BY id")
    assert r.rows == [(1,), (3,)]
    r = pg.execute("SELECT id FROM items i WHERE NOT EXISTS "
                   "(SELECT 1 FROM orders o WHERE o.item = i.id) "
                   "ORDER BY id")
    assert r.rows == [(2,), (4,), (5,), (6,)]


def test_exists_uncorrelated(pg):
    seed(pg)
    seed_orders(pg)
    r = pg.execute("SELECT count(*) FROM items WHERE EXISTS "
                   "(SELECT 1 FROM orders WHERE n > 4)")
    assert r.rows == [(6,)]
    r = pg.execute("SELECT count(*) FROM items WHERE EXISTS "
                   "(SELECT 1 FROM orders WHERE n > 99)")
    assert r.rows == [(0,)]
    r = pg.execute("SELECT id FROM items WHERE NOT EXISTS "
                   "(SELECT 1 FROM orders WHERE n > 99) AND cat = 'c'")
    assert r.rows == [(6,)]


def test_exists_combined_with_predicates(pg):
    seed(pg)
    seed_orders(pg)
    r = pg.execute("SELECT id FROM items i WHERE price >= 100 AND "
                   "EXISTS (SELECT 1 FROM orders o WHERE o.item = i.id)"
                   " ORDER BY id")
    assert r.rows == [(1,)]


def test_exists_in_update_delete(pg):
    seed(pg)
    seed_orders(pg)
    pg.execute("UPDATE items SET qty = 0 WHERE id = 1 AND EXISTS "
               "(SELECT 1 FROM orders WHERE n > 4)")
    assert pg.execute("SELECT qty FROM items WHERE id = 1").rows == [(0,)]
    pg.execute("DELETE FROM items WHERE id = 6 AND EXISTS "
               "(SELECT 1 FROM orders WHERE n > 99)")
    assert pg.execute("SELECT count(*) FROM items").rows == [(6,)]
    pg.execute("DELETE FROM items WHERE id = 6 AND NOT EXISTS "
               "(SELECT 1 FROM orders WHERE n > 99)")
    assert pg.execute("SELECT count(*) FROM items").rows == [(5,)]


def test_exists_over_cte(pg):
    seed(pg)
    seed_orders(pg)
    r = pg.execute("WITH c AS (SELECT id, cat FROM items) "
                   "SELECT count(*) FROM c WHERE EXISTS "
                   "(SELECT 1 FROM orders WHERE n = 5)")
    assert r.rows == [(6,)]


# -- INTERSECT / EXCEPT ------------------------------------------------------

def test_except_and_intersect(pg):
    seed(pg)
    r = pg.execute("SELECT cat FROM items EXCEPT SELECT cat FROM items "
                   "WHERE cat = 'b' ORDER BY cat")
    assert r.rows == [("a",), ("c",)]
    r = pg.execute("SELECT cat FROM items WHERE price < 200 INTERSECT "
                   "SELECT cat FROM items WHERE qty >= 5 ORDER BY cat")
    assert r.rows == [("b",), ("c",)]


def test_intersect_binds_tighter_than_union(pg):
    seed(pg)
    # a UNION b INTERSECT c == a UNION (b INTERSECT c)
    r = pg.execute("SELECT cat FROM items WHERE cat = 'a' "
                   "UNION SELECT cat FROM items "
                   "INTERSECT SELECT cat FROM items WHERE qty > 8 "
                   "ORDER BY cat")
    assert r.rows == [("a",), ("c",)]


def test_except_all_per_occurrence(pg):
    seed(pg)
    # cats: a,a,b,b,b,c ; EXCEPT ALL one 'b' leaves b,b
    r = pg.execute("SELECT cat FROM items EXCEPT ALL "
                   "SELECT cat FROM items WHERE id = 3 ORDER BY cat")
    assert r.rows == [("a",), ("a",), ("b",), ("b",), ("c",)]


def test_intersect_all_multiset(pg):
    seed(pg)
    # lhs b,b,b ; rhs b,b -> min counts = 2
    r = pg.execute("SELECT cat FROM items WHERE cat = 'b' INTERSECT ALL "
                   "SELECT cat FROM items WHERE id >= 4 AND cat = 'b'")
    assert r.rows == [("b",), ("b",)]


def test_union_jsonb_rows(pg):
    pg.execute("CREATE TABLE j (id bigint PRIMARY KEY, data jsonb)")
    pg.execute("INSERT INTO j (id, data) VALUES (1, '{\"a\": 1}')")
    pg.execute("INSERT INTO j (id, data) VALUES (2, '{\"a\": 1}')")
    r = pg.execute("SELECT data FROM j UNION SELECT data FROM j")
    assert r.rows == [({"a": 1},)]
    r = pg.execute("SELECT data FROM j INTERSECT SELECT data FROM j "
                   "WHERE id = 2")
    assert r.rows == [({"a": 1},)]


def test_correlated_exists_clear_error_in_delete(pg):
    seed(pg)
    with pytest.raises(InvalidArgument) as ei:
        pg.execute("DELETE FROM items WHERE EXISTS "
                   "(SELECT 1 FROM items i2 WHERE i2.id = items.id)")
    assert "EXISTS" in str(ei.value)


def test_exists_subquery_typo_not_masked_as_correlated(pg):
    """A typo'd column inside an EXISTS subquery is the subquery's own
    error — it must NOT be rewrapped as 'correlated EXISTS
    unsupported' (only unresolvable outer-column references mean
    correlation)."""
    seed(pg)
    seed_orders(pg)
    with pytest.raises(InvalidArgument) as ei:
        pg.execute("SELECT count(*) FROM items WHERE EXISTS "
                   "(SELECT 1 FROM orders WHERE nosuch_col > 1)")
    assert "unknown column nosuch_col" in str(ei.value)
    assert "correlated" not in str(ei.value)
    # The genuinely-correlated case still gets the clear wrapper.
    with pytest.raises(InvalidArgument) as ei:
        pg.execute("SELECT count(*) FROM items WHERE EXISTS "
                   "(SELECT 1 FROM orders o WHERE o.item = items.id)")
    assert "correlated" in str(ei.value)


def test_false_exists_aggregate_is_empty_aggregate(pg):
    """A false folded EXISTS means 'aggregate over no rows': one row of
    count 0 / NULL sums without GROUP BY, zero rows with it."""
    seed(pg)
    seed_orders(pg)
    r = pg.execute("SELECT sum(price), count(*) FROM items WHERE "
                   "EXISTS (SELECT 1 FROM orders WHERE n > 99)")
    assert r.rows == [(None, 0)]
    r = pg.execute("SELECT cat, count(*) FROM items WHERE NOT EXISTS "
                   "(SELECT 1 FROM orders WHERE n > 0) GROUP BY cat")
    assert r.rows == []
