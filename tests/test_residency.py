"""HBM residency manager: budgeted device caching must never change
scan results.

Covers the PR's acceptance bar: with a budget far smaller than the
dataset's plane bytes the TPU engine stays byte-identical to the CPU
oracle (demand re-upload on miss, mid-scan eviction pressure included),
one full scan cannot flush the protected point-get pool (scan
resistance), residency accounting respects the budget and detaches on
close, and the incremental overlay advances by memtable deltas instead
of rebuilding.
"""

import gc

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import (
    AggSpec, Predicate, RowVersion, ScanSpec, make_engine,
)
from yugabyte_db_tpu.storage.residency import HbmCache, hbm_cache
from yugabyte_db_tpu.storage.row_version import MAX_HT
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.memtracker import root_tracker
from yugabyte_db_tpu.utils.sync_point import SYNC_POINT
import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401  (registers 'tpu')


def make_schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
        ColumnSchema("c", DataType.DOUBLE),
    ], table_id="t")


def enc(schema, k, r):
    return schema.encode_primary_key(
        {"k": k, "r": r}, compute_hash_code(schema, {"k": k}))


def ids(schema):
    return {c.name: c.col_id for c in schema.value_columns}


@pytest.fixture
def budget_flag():
    """Restore the budget flag (and drain stray residents) around a test."""
    gc.collect()  # dead engines from earlier tests release via weakrefs
    hbm_cache().evict_unpinned()
    old = FLAGS.get("tpu_hbm_budget_bytes")
    yield lambda v: FLAGS.set("tpu_hbm_budget_bytes", int(v))
    FLAGS.set("tpu_hbm_budget_bytes", old)
    hbm_cache().evict_unpinned()


def load_engines(n_flushes=3, rows_per_flush=120, tail_writes=40):
    """CPU + TPU engines with several runs plus live memtable writes."""
    schema = make_schema()
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, {"rows_per_block": 32})
    cids = ids(schema)
    ht = 0
    for f in range(n_flushes):
        rows = []
        for i in range(rows_per_flush):
            ht += 1
            rows.append(RowVersion(
                enc(schema, ["p", "q", "rr"][i % 3], (f * 7 + i) % 211),
                ht=ht, liveness=True,
                columns={cids["a"]: i - 50, cids["b"]: f"v{f}-{i % 9}",
                         cids["c"]: i * 0.25 - 3.0}))
        cpu.apply(rows)
        tpu.apply(rows)
        cpu.flush()
        tpu.flush()
    rows = []
    for i in range(tail_writes):
        ht += 1
        rows.append(RowVersion(
            enc(schema, "q", i % 211), ht=ht, liveness=True,
            columns={cids["a"]: 1000 + i}))
    cpu.apply(rows)
    tpu.apply(rows)
    return schema, cpu, tpu, ht


def plane_budget(tpu, fraction=0.25):
    total = sum(t._nbytes_hint() for t in tpu.runs)
    assert total > 0
    return max(int(total * fraction), 1)


def assert_same(cpu, tpu, **spec_kwargs):
    a = cpu.scan(ScanSpec(**spec_kwargs))
    b = tpu.scan(ScanSpec(**spec_kwargs))
    assert a.columns == b.columns
    assert a.rows == b.rows, f"spec={spec_kwargs}"


SCAN_BATTERY = [
    dict(read_ht=MAX_HT),
    dict(read_ht=MAX_HT,
         aggregates=[AggSpec("count", None), AggSpec("sum", "a"),
                     AggSpec("min", "a"), AggSpec("max", "a")]),
    dict(read_ht=MAX_HT, predicates=[Predicate("a", ">", 0)]),
]


def _bounded(schema):
    return dict(read_ht=MAX_HT, lower=enc(schema, "p", 10),
                upper=enc(schema, "p", 150))


def test_engine_diff_under_tiny_budget(budget_flag):
    """Dataset ≫ budget: every scan answer must still be byte-identical
    to the CPU oracle — misses demand re-upload from the authoritative
    host run — and once pins release, residency settles to the budget."""
    schema, cpu, tpu, max_ht = load_engines()
    budget = plane_budget(tpu, 0.25)
    budget_flag(budget)
    try:
        for spec in SCAN_BATTERY:
            assert_same(cpu, tpu, **spec)
        assert_same(cpu, tpu, **_bounded(schema))
        assert_same(cpu, tpu, read_ht=max_ht // 2)
        # Point-get shape: single-key range with an aggregate.
        assert_same(cpu, tpu, read_ht=MAX_HT,
                    lower=enc(schema, "q", 5),
                    upper=enc(schema, "q", 6),
                    aggregates=[AggSpec("count", None)])
        gc.collect()
        hbm_cache().evict_unpinned()  # drop THIS test's unpinned leftovers
        pinned = hbm_cache().pinned_bytes()
        assert hbm_cache().resident_bytes() <= budget + pinned
        assert hbm_cache().stats()["misses"] > 0
    finally:
        cpu.close()
        tpu.close()


def test_engine_diff_mid_scan_eviction(budget_flag):
    """Eviction pressure injected mid-plan (everything unpinned is
    dropped right after the memtable snapshot) must not change results:
    gathers re-acquire and re-upload on demand."""
    schema, cpu, tpu, _ = load_engines(n_flushes=2)
    budget_flag(plane_budget(tpu, 0.25))
    SYNC_POINT.set_callback(
        "tpu_engine:plan:mem_snapshotted",
        lambda _arg: hbm_cache().evict_unpinned())
    SYNC_POINT.enable()
    try:
        for spec in SCAN_BATTERY:
            assert_same(cpu, tpu, **spec)
        assert_same(cpu, tpu, **_bounded(schema))
    finally:
        SYNC_POINT.disable_and_clear()
        cpu.close()
        tpu.close()


def test_scan_resistance_protects_high_pool():
    """One full scan's worth of low-pri admissions must not evict the
    protected point-get entries: the low pool drains first."""
    cache = HbmCache()
    tracker = root_tracker().child("device").child("test_scanres")

    class Owner:
        pass

    owners = []

    def entry(nbytes, priority):
        o = Owner()
        owners.append(o)
        key = cache.register(o, tracker, "unit")
        cache.acquire(key, lambda: (("payload", nbytes), nbytes),
                      nbytes_hint=nbytes, priority=priority)
        return key

    try:
        FLAGS.set("tpu_hbm_budget_bytes", 1000)
        hot = [entry(200, "high") for _ in range(3)]  # 600B protected
        # A "full scan" streaming 20 low-pri entries through the cache.
        for _ in range(20):
            entry(300, "low")
        for key in hot:
            def must_not_rebuild():
                raise AssertionError("protected entry was evicted")
            assert cache.acquire(key, must_not_rebuild,
                                 priority="high") is not None
        assert cache.resident_bytes() <= 1000
    finally:
        FLAGS.set("tpu_hbm_budget_bytes", 0)
        for o in owners:
            del o
        owners.clear()
        gc.collect()
        tracker.detach()


def test_accounting_budget_and_detach(budget_flag):
    """resident_bytes tracks the MemTracker subtree exactly, never
    exceeds the budget for unpinned traffic, and engine close() releases
    and detaches its device subtree."""
    cache = HbmCache()
    tracker = root_tracker().child("device").child("test_acct")

    class Owner:
        pass

    keep = []
    observed = []
    SYNC_POINT.set_callback(
        "hbm_cache:admit", lambda _arg: observed.append(
            cache.resident_bytes()))
    SYNC_POINT.enable()
    try:
        FLAGS.set("tpu_hbm_budget_bytes", 512)
        for i in range(8):
            o = Owner()
            keep.append(o)
            key = cache.register(o, tracker, f"e{i}")
            cache.acquire(key, lambda: (object(), 200), nbytes_hint=200)
        assert observed and max(observed) <= 512
        assert cache.resident_bytes() == tracker.consumption
        assert cache.stats()["evictions"] >= 6
    finally:
        SYNC_POINT.disable_and_clear()
        FLAGS.set("tpu_hbm_budget_bytes", 0)
        keep.clear()
        gc.collect()
        tracker.detach()

    # Engine lifecycle: close() must empty and detach the device subtree.
    _schema, cpu, tpu, _ = load_engines(n_flushes=1, rows_per_flush=40,
                                        tail_writes=0)
    tpu.scan(ScanSpec(read_ht=MAX_HT))
    name = tpu.device_tracker.name
    parent = tpu.device_tracker.parent
    cpu.close()
    tpu.close()
    assert tpu.device_tracker.consumption == 0
    assert name not in parent._children


def test_alter_keeps_runs_managed(budget_flag):
    """ALTER invalidates each live run's stale device planes but must
    keep its residency registration: the post-alter demand re-upload
    goes through the cache (accounted, budgeted, evictable), never the
    unmanaged unregistered-owner fallback — which would duplicate
    planes per access and silently escape the budget."""
    schema, cpu, tpu, _ = load_engines(n_flushes=2, tail_writes=0)
    budget = plane_budget(tpu, 0.5)
    budget_flag(budget)
    cache = hbm_cache()
    try:
        new_schema = schema.with_added_column("d", DataType.INT64)
        cpu.alter_schema(new_schema)
        tpu.alter_schema(new_schema)
        # Registrations survive the invalidation...
        for t in tpu.runs:
            assert t._res_key in cache._entries
        # ...and the evolved planes are gone until the next access.
        assert all(cache._entries[t._res_key].payload is None
                   for t in tpu.runs)
        before = cache.stats()["demand_upload_bytes"]
        for spec in SCAN_BATTERY:
            assert_same(cpu, tpu, **spec)
        stats = cache.stats()
        # The re-upload was a managed miss, charged to the cache.
        assert stats["demand_upload_bytes"] > before
        # A fresh pinned access lands IN the cache (not an unmanaged
        # copy); pinned so tight-budget eviction can't race the check.
        tpu.runs[0].pin()
        try:
            assert (cache._entries[tpu.runs[0]._res_key].payload
                    is not None)
        finally:
            tpu.runs[0].unpin()
        gc.collect()
        cache.evict_unpinned()
        assert cache.resident_bytes() <= budget + cache.pinned_bytes()
    finally:
        cpu.close()
        tpu.close()


def test_overlay_incremental_delta(budget_flag):
    """A second post-write scan advances the cached overlay by the
    memtable delta: same masked plane object when only existing keys
    changed, fresh scatter when new primary rows need clearing — and
    results match the CPU oracle at every step."""
    # The overlay needs a dominant primary: one big run, a small delta
    # run, and a small live memtable (the postwrite_scan shape).
    schema = make_schema()
    cpu = make_engine("cpu", schema, {})
    tpu = make_engine("tpu", schema, {"rows_per_block": 32})
    cids = ids(schema)
    rows = [RowVersion(enc(schema, ["p", "q", "rr"][i % 3], i % 211),
                       ht=1 + i, liveness=True,
                       columns={cids["a"]: i - 50, cids["b"]: f"v{i % 9}",
                                cids["c"]: i * 0.25 - 3.0})
            for i in range(240)]
    cpu.apply(rows)
    tpu.apply(rows)
    cpu.flush()
    tpu.flush()
    rows = [RowVersion(enc(schema, "q", i), ht=500 + i, liveness=True,
                       columns={cids["a"]: 2_000 + i})
            for i in range(24)]
    cpu.apply(rows)
    tpu.apply(rows)
    cpu.flush()
    tpu.flush()
    rows = [RowVersion(enc(schema, "q", i), ht=600 + i, liveness=True,
                       columns={cids["a"]: 3_000 + i})
            for i in range(10)]
    cpu.apply(rows)
    tpu.apply(rows)
    # The overlay drives multi-source AGGREGATE scans (row scans merge
    # on host); this spec is the steady-state shape being accelerated.
    agg = dict(read_ht=MAX_HT,
               aggregates=[AggSpec("count", None), AggSpec("sum", "a"),
                           AggSpec("min", "a"), AggSpec("max", "a")])
    assert_same(cpu, tpu, **agg)  # builds the overlay
    state1 = tpu._overlay_cache[3]
    assert state1 is not None

    def write(key_i, val, part="q"):
        r = [RowVersion(enc(schema, part, key_i % 211), ht=10_000 + val,
                        liveness=True, columns={cids["a"]: val})]
        cpu.apply(r)
        tpu.apply(r)

    # Delta wave 1: only keys the overlay already tracks.
    for i in range(5):
        write(i, 7_000 + i)
    assert_same(cpu, tpu, **agg)
    state2 = tpu._overlay_cache[3]
    assert state2 is not state1
    assert state2.mem_count > state1.mem_count
    assert state2.masked is state1.masked  # no re-scatter needed
    assert len(state2.rows) == len(state1.rows)

    # Delta wave 2: brand-new keys present in the primary run.
    for i in range(30, 34):
        write(i, 8_000 + i, part="p")
    assert_same(cpu, tpu, **agg)
    state3 = tpu._overlay_cache[3]
    assert len(state3.rows) > len(state2.rows)
    assert state3.keys == sorted(state3.keys)
    assert_same(cpu, tpu, **_bounded(schema))

    # Steady state: an unchanged memtable is a pure cache hit.
    assert_same(cpu, tpu, **agg)
    assert tpu._overlay_cache[3] is state3
    cpu.close()
    tpu.close()


def test_metrics_and_memz_exposure(budget_flag):
    """The cache series render on the process registry and /memz carries
    the budget/resident/pinned breakdown."""
    from yugabyte_db_tpu.server.webserver import _memz
    from yugabyte_db_tpu.utils.metrics import process_registry

    _schema, cpu, tpu, _ = load_engines(n_flushes=1, rows_per_flush=40,
                                        tail_writes=0)
    tpu.scan(ScanSpec(read_ht=MAX_HT))
    text = process_registry().prometheus_text()
    for series in ("yb_hbm_cache_hits", "yb_hbm_cache_misses",
                   "yb_hbm_cache_evictions", "yb_hbm_demand_upload_bytes",
                   "yb_hbm_resident_bytes", "yb_hbm_pinned_bytes",
                   "yb_hbm_budget_bytes"):
        assert series in text
    memz = _memz()
    assert "hbm_cache" in memz
    for k in ("budget_bytes", "resident_bytes", "pinned_bytes", "pools"):
        assert k in memz["hbm_cache"]
    cpu.close()
    tpu.close()
