"""Schema and partitioning tests.

Reference analog: src/yb/common/schema-test.cc, partition-test.cc.
"""

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import (
    MAX_PARTITION_KEY,
    PartitionSchema,
    compute_hash_code,
    hash_column_compound_value,
)
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema


def make_schema():
    return Schema([
        ColumnSchema("v", DataType.STRING),
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("r", DataType.INT64, ColumnKind.RANGE),
        ColumnSchema("n", DataType.INT64),
    ], table_id="t1")


def test_schema_normalizes_column_order():
    s = make_schema()
    assert [c.name for c in s.columns] == ["k", "r", "v", "n"]
    assert s.num_hash == 1 and s.num_range == 1
    assert [c.name for c in s.value_columns] == ["v", "n"]
    assert s.column("r").kind == ColumnKind.RANGE


def test_schema_column_ids_stable_and_unique():
    s = make_schema()
    ids = [c.col_id for c in s.columns]
    assert len(set(ids)) == len(ids)
    s2 = Schema.from_dict(s.to_dict())
    assert [c.col_id for c in s2.columns] == ids
    assert [c.name for c in s2.columns] == [c.name for c in s.columns]


def test_schema_duplicate_names_rejected():
    with pytest.raises(ValueError):
        Schema([ColumnSchema("a", DataType.INT64),
                ColumnSchema("a", DataType.STRING)])


def test_hash_stability_and_spread():
    s = make_schema()
    codes = [compute_hash_code(s, {"k": f"user{i}"}) for i in range(2000)]
    assert codes == [compute_hash_code(s, {"k": f"user{i}"}) for i in range(2000)]
    assert all(0 <= c <= MAX_PARTITION_KEY for c in codes)
    # Reasonable spread over 8 buckets.
    buckets = [0] * 8
    for c in codes:
        buckets[c * 8 // (MAX_PARTITION_KEY + 1)] += 1
    assert min(buckets) > 2000 / 8 * 0.5


def test_partitions_cover_space_exactly():
    for n in (1, 3, 8, 16, 100):
        parts = PartitionSchema(n).create_partitions()
        assert parts[0].start == 0
        assert parts[-1].end == MAX_PARTITION_KEY + 1
        for a, b in zip(parts, parts[1:]):
            assert a.end == b.start


def test_partition_routing_consistent():
    ps = PartitionSchema(7)
    parts = ps.create_partitions()
    for h in [0, 1, 9362, 9363, 30000, MAX_PARTITION_KEY]:
        idx = ps.partition_index_for_hash(h)
        assert parts[idx].contains(h), (h, idx, parts[idx])


def test_range_partitioned_single_tablet():
    ps = PartitionSchema(5, hash_partitioned=False)
    assert ps.num_tablets == 1
    assert len(ps.create_partitions()) == 1
