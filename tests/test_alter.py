"""ALTER TABLE schema evolution + CQL BATCH.

Reference analogs: stable-ColumnId schema evolution
(src/yb/common/schema.h ColumnId, catalog_manager.cc AlterTable, the
AlterSchema tablet operation) and batch statement execution
(executor.cc PTListNode batches).
"""

import tempfile

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.cql.processor import LocalCluster, QLProcessor


def _schema():
    return Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
    ], table_id="t")


# -- schema helpers ----------------------------------------------------------

def test_schema_evolution_ids_stable_and_never_reused():
    s0 = _schema()
    ids0 = {c.name: c.col_id for c in s0.columns}
    s1 = s0.with_added_column("c", DataType.INT32)
    assert s1.version == 1
    assert {c.name: c.col_id for c in s1.columns} == {
        **ids0, "c": s0.next_col_id}
    # drop the HIGHEST-id column, then add: the id must NOT be reused
    s2 = s1.with_dropped_column("c")
    s3 = s2.with_added_column("d", DataType.INT32)
    assert s3.column("d").col_id > s1.column("c").col_id
    # round-trips preserve the allocator
    s4 = Schema.from_dict(s3.to_dict())
    assert s4.next_col_id == s3.next_col_id and s4.version == s3.version
    with pytest.raises(ValueError):
        s0.with_dropped_column("k")      # key column
    with pytest.raises(ValueError):
        s1.with_added_column("a", DataType.INT8)  # duplicate
    s5 = s0.with_renamed_column("a", "aa")
    assert s5.column("aa").col_id == ids0["a"]


# -- engines -----------------------------------------------------------------

@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_engine_alter_schema(engine):
    if engine == "tpu":
        import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401
    from yugabyte_db_tpu.models.partition import compute_hash_code
    from yugabyte_db_tpu.storage import ScanSpec, make_engine
    from yugabyte_db_tpu.storage.row_version import RowVersion

    schema = _schema()
    cid = {c.name: c.col_id for c in schema.columns}
    eng = make_engine(engine, schema, {"rows_per_block": 8})

    def key(i):
        return schema.encode_primary_key(
            {"k": f"u{i:03d}"}, compute_hash_code(schema, {"k": f"u{i:03d}"}))

    eng.apply([RowVersion(key(i), ht=10 + i, liveness=True,
                          columns={cid["a"]: i, cid["b"]: f"s{i}"})
               for i in range(40)])
    eng.flush()

    new_schema = schema.with_added_column("c", DataType.INT64)
    eng.alter_schema(new_schema)
    ncid = new_schema.column("c").col_id
    # old rows: c IS NULL; write new rows with c set
    eng.apply([RowVersion(key(i), ht=100 + i, liveness=True,
                          columns={cid["a"]: -i, ncid: i * 7})
               for i in range(40, 50)])
    eng.flush()
    res = eng.scan(ScanSpec(read_ht=10_000, projection=["k", "a", "c"]))
    got = {r[0]: (r[1], r[2]) for r in res.rows}
    assert got["u005"] == (5, None)
    assert got["u045"] == (-45, 45 * 7)
    # predicate on the added column
    res = eng.scan(ScanSpec(read_ht=10_000,
                            predicates=[__import__(
                                "yugabyte_db_tpu.storage",
                                fromlist=["Predicate"]).Predicate(
                                    "c", ">=", 301)],
                            projection=["k", "c"]))
    assert sorted(r[0] for r in res.rows) == ["u043", "u044", "u045",
                                              "u046", "u047", "u048",
                                              "u049"]
    # dropped column disappears from scans; its id is retired
    s2 = new_schema.with_dropped_column("b")
    eng.alter_schema(s2)
    res = eng.scan(ScanSpec(read_ht=10_000))
    assert "b" not in res.columns


# -- CQL frontend ------------------------------------------------------------

def test_cql_alter_and_batch():
    cluster = LocalCluster(num_tablets=2)
    try:
        ql = QLProcessor(cluster)
        ql.execute("CREATE TABLE t (k TEXT, v INT, PRIMARY KEY ((k)))")
        ql.execute("INSERT INTO t (k, v) VALUES ('x', 1)")
        ql.execute("ALTER TABLE t ADD w BIGINT")
        res = ql.execute("SELECT k, v, w FROM t")
        assert res.rows == [("x", 1, None)]
        ql.execute("INSERT INTO t (k, v, w) VALUES ('y', 2, 99)")
        res = ql.execute("SELECT k, w FROM t WHERE w = 99")
        assert res.rows == [("y", 99)]
        ql.execute("ALTER TABLE t RENAME v TO vv")
        res = ql.execute("SELECT vv FROM t WHERE k = 'x'")
        assert res.rows == [(1,)]
        ql.execute("ALTER TABLE t DROP vv")
        with pytest.raises(InvalidArgument):
            ql.execute("SELECT vv FROM t")
        # BATCH: multiple DML in one statement
        ql.execute("BEGIN BATCH "
                   "INSERT INTO t (k, w) VALUES ('b1', 1); "
                   "INSERT INTO t (k, w) VALUES ('b2', 2); "
                   "UPDATE t SET w = 100 WHERE k = 'b1'; "
                   "DELETE FROM t WHERE k = 'y'; "
                   "APPLY BATCH")
        res = ql.execute("SELECT k, w FROM t")
        got = dict(res.rows)
        assert got["b1"] == 100 and got["b2"] == 2 and "y" not in got
        with pytest.raises(InvalidArgument):
            ql.execute("BEGIN BATCH SELECT k FROM t; APPLY BATCH")
    finally:
        cluster.close()


# -- SQL frontend ------------------------------------------------------------

def test_pgsql_alter():
    from yugabyte_db_tpu.yql.pgsql import PgProcessor

    cluster = LocalCluster(num_tablets=2)
    try:
        pg = PgProcessor(cluster)
        pg.execute("CREATE TABLE t (k TEXT PRIMARY KEY, v BIGINT)")
        pg.execute("INSERT INTO t (k, v) VALUES ('a', 1)")
        pg.execute("ALTER TABLE t ADD COLUMN w TEXT")
        pg.execute("INSERT INTO t (k, v, w) VALUES ('b', 2, 'yes')")
        res = pg.execute("SELECT k, v, w FROM t ORDER BY k")
        assert res.rows == [("a", 1, None), ("b", 2, "yes")]
        pg.execute("ALTER TABLE t RENAME COLUMN v TO n")
        res = pg.execute("SELECT sum(n) FROM t")
        assert res.rows == [(3,)]
        pg.execute("ALTER TABLE t DROP COLUMN w")
        res = pg.execute("SELECT * FROM t ORDER BY k")
        assert res.columns == ["k", "n"]
    finally:
        cluster.close()


# -- distributed -------------------------------------------------------------

def test_alter_through_master_and_restart():
    from yugabyte_db_tpu.client.session import YBSession
    from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
    from yugabyte_db_tpu.storage.scan_spec import ScanSpec
    from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
    from yugabyte_db_tpu.yql.cql.processor import QLProcessor as QP

    with tempfile.TemporaryDirectory() as root:
        mc = MiniCluster(root, num_tservers=3).start()
        try:
            mc.wait_tservers_registered()
            client = mc.client()
            ql = QP(ClientCluster(client))
            ql.execute("CREATE TABLE kv (k TEXT, v BIGINT, "
                       "PRIMARY KEY ((k)))")
            s = YBSession(client)
            table = client.open_table("default.kv")
            for i in range(20):
                s.insert(table, {"k": f"r{i:02d}", "v": i})
            s.flush()
            ql.execute("ALTER TABLE kv ADD extra TEXT")

            # every replica adopts the replicated change (followers apply
            # asynchronously behind the leader's commit)
            def versions():
                return [peer.tablet.meta.schema.version
                        for ts in mc.tservers.values()
                        for peer in ts.tablet_manager.peers()
                        if peer.tablet.meta.table_name == "default.kv"]

            import time as _time
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and \
                    not all(v == 1 for v in versions()):
                _time.sleep(0.05)
            assert all(v == 1 for v in versions()), versions()
            ql.execute("INSERT INTO kv (k, v, extra) "
                       "VALUES ('zz', 99, 'new')")
            res = ql.execute("SELECT k, extra FROM kv WHERE k = 'zz'")
            assert res.rows == [("zz", "new")]
            res = ql.execute("SELECT k, extra FROM kv WHERE k = 'r05'")
            assert res.rows == [("r05", None)]
            # the new schema survives a tserver restart (meta + WAL replay)
            victim = next(iter(mc.tservers))
            mc.stop_tserver(victim)
            mc.restart_tserver(victim)
            ts = mc.tservers[victim]
            for peer in ts.tablet_manager.peers():
                if peer.tablet.meta.table_name == "default.kv":
                    assert peer.tablet.meta.schema.version == 1
        finally:
            mc.shutdown()
