"""Device compaction (ops.compact) vs the host merge: identical output.

Pins the device lexsort + vectorized history GC to
CpuStorageEngine._gc_versions / merge_entry_streams semantics —
BASELINE config 4's correctness contract (byte-identical results).
"""

import random

import pytest

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import ScanSpec, make_engine
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion


def _mk_engines(rows_per_block=64):
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("a", DataType.INT64),
        ColumnSchema("b", DataType.STRING),
        ColumnSchema("c", DataType.DOUBLE),
    ], table_id="dc")
    opts = {"rows_per_block": rows_per_block}
    return (schema, make_engine("cpu", schema, opts),
            make_engine("tpu", schema, opts))


def _random_load(schema, engines, num_keys=300, writes=1200, seed=5,
                 flushes=4):
    rng = random.Random(seed)
    cid = {c.name: c.col_id for c in schema.columns}
    ht = 10
    for w in range(writes):
        i = rng.randrange(num_keys)
        key = schema.encode_primary_key(
            {"k": f"u{i:04d}"}, compute_hash_code(schema, {"k": f"u{i:04d}"}))
        ht += rng.randrange(1, 3)
        roll = rng.random()
        if roll < 0.08:
            rv = RowVersion(key, ht=ht, tombstone=True)
        elif roll < 0.16:
            rv = RowVersion(key, ht=ht, liveness=True,
                            columns={cid["a"]: rng.randrange(100)},
                            expire_ht=ht + rng.randrange(1, 50))
        else:
            cols = {}
            if rng.random() < 0.8:
                cols[cid["a"]] = rng.randrange(10**9)
            if rng.random() < 0.5:
                cols[cid["b"]] = rng.choice(["x", "yy", None])
            if rng.random() < 0.4:
                cols[cid["c"]] = rng.uniform(-5, 5)
            rv = RowVersion(key, ht=ht, liveness=rng.random() < 0.5,
                            columns=cols)
        for e in engines:
            e.apply([rv])
        if w and w % (writes // flushes) == 0:
            for e in engines:
                e.flush()
    for e in engines:
        e.flush()
    return ht


def _entries_signature(engine):
    out = []
    for key, versions in engine.dump_entries():
        out.append((key, [(v.ht, v.tombstone, v.liveness,
                           tuple(sorted(v.columns.items(),
                                        key=lambda kv: kv[0])),
                           v.expire_ht)
                          for v in versions]))
    return out


@pytest.mark.parametrize("cutoff_frac", [0.0, 0.5, 1.0])
def test_device_compact_identical(cutoff_frac):
    schema, cpu, tpu = _mk_engines()
    ht = _random_load(schema, (cpu, tpu))
    cutoff = int(ht * cutoff_frac)
    assert all(t.crun.max_key_len <= 32 for t in tpu.runs)
    cpu.compact(cutoff)
    tpu.compact(cutoff)
    assert _entries_signature(cpu) == _entries_signature(tpu)
    # post-compaction reads agree at several read points
    for read_ht in (cutoff or 1, ht // 2 + cutoff // 2, ht + 1):
        if read_ht < cutoff:
            continue
        a = cpu.scan(ScanSpec(read_ht=read_ht))
        b = tpu.scan(ScanSpec(read_ht=read_ht))
        assert a.rows == b.rows, read_ht


def test_device_compact_repeated_and_ttl():
    schema, cpu, tpu = _mk_engines(rows_per_block=32)
    ht = _random_load(schema, (cpu, tpu), num_keys=80, writes=600, seed=9)
    for cutoff in (ht // 4, ht // 2, ht):
        cpu.compact(cutoff)
        tpu.compact(cutoff)
        assert _entries_signature(cpu) == _entries_signature(tpu), cutoff
    a = cpu.scan(ScanSpec(read_ht=ht + 1))
    b = tpu.scan(ScanSpec(read_ht=ht + 1))
    assert a.rows == b.rows


def test_long_keys_fall_back_to_host():
    schema = Schema([
        ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
        ColumnSchema("v", DataType.INT64),
    ], table_id="lk")
    cpu = make_engine("cpu", schema)
    tpu = make_engine("tpu", schema)
    cid = {c.name: c.col_id for c in schema.columns}
    ht = 0
    for i in range(40):
        name = f"very-long-key-{'x' * 40}-{i:03d}"
        key = schema.encode_primary_key(
            {"k": name}, compute_hash_code(schema, {"k": name}))
        ht += 1
        rv = RowVersion(key, ht=ht, liveness=True, columns={cid["v"]: i})
        cpu.apply([rv])
        tpu.apply([rv])
        if i % 13 == 12:
            cpu.flush()
            tpu.flush()
    cpu.flush()
    tpu.flush()
    assert any(t.crun.max_key_len > 32 for t in tpu.runs)
    cpu.compact(ht)
    tpu.compact(ht)
    assert _entries_signature(cpu) == _entries_signature(tpu)


def test_resident_device_mask_route(monkeypatch):
    """Force the device-resident retention mask (the large-union route,
    normally gated behind HOST_GC_MASK_MAX) and pin it to the oracle —
    a regression in its index mapping/padding must not hide behind the
    host-twin default."""
    import yugabyte_db_tpu.storage.tpu_engine as TE

    monkeypatch.setattr(TE, "HOST_GC_MASK_MAX", 0)
    schema, cpu, tpu = _mk_engines()
    ht = _random_load(schema, (cpu, tpu), seed=23)
    cpu.compact(ht // 2)
    tpu.compact(ht // 2)
    assert _entries_signature(cpu) == _entries_signature(tpu)
    a = cpu.scan(ScanSpec(read_ht=ht + 1))
    b = tpu.scan(ScanSpec(read_ht=ht + 1))
    assert a.rows == b.rows
