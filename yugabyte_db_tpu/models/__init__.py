"""The data model: types, primitive values, document keys, schema, partitioning.

Reference analog: src/yb/common (schema.h, partition.h, ql_value.h) and the
key-encoding half of src/yb/docdb (doc_key.h, primitive_value.h,
value_type.h). This package is pure host-side Python/numpy: it defines the
*logical* encoding whose ordering the TPU kernels reproduce on fixed-width
int32 key planes.
"""

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.models.encoding import (
    encode_key_component,
    decode_key_component,
    encode_doc_key,
    decode_doc_key,
)
from yugabyte_db_tpu.models.partition import PartitionSchema, Partition
