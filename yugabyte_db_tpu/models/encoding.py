"""Byte-comparable key encoding: the DocKey of the framework.

Reference analog: src/yb/docdb/doc_key.h:68 (DocKey), primitive_value.cc
(PrimitiveValue::AppendToKey), value_type.h:31-140 (ValueType tags),
src/yb/util/memcmpable_varint.cc. The invariant this module guarantees —
and the whole TPU data plane rests on — is:

    memcmp(encode(a), encode(b))  ==  logical_compare(a, b)

so that device kernels can compare fixed-width big-endian word prefixes of
encoded keys with plain int32 signed comparisons (after bias-flip, see
utils.planes) and reproduce logical key order.

Layout of an encoded DocKey (hash-partitioned table):

    [kHash][2-byte partition hash BE] [hashed components]* [kGroupEnd]
    [range components]* [kGroupEnd]

and for range-partitioned tables the hash prelude is omitted. Each component
is [type tag][payload]; tag values are chosen so kGroupEnd sorts before every
component tag (a shorter key group is a strict prefix and must sort first),
and NULL sorts before all values of a column.

Unlike the reference, the MVCC hybrid time is *not* appended to the key
(reference SubDocKey suffixes a descending-encoded DocHybridTime): columnar
blocks store (key, commit_ht) in separate planes and sort by (key asc,
ht desc) explicitly, which is what the device kernels want.
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.models.datatypes import DataType

# Type tags. Ordering constraints:
#   GROUP_END < NULL < FALSE < TRUE < INT < DOUBLE-family < STRING < BINARY
# GROUP_END lowest so shorter composite keys sort first; NULL lowest within a
# column so nulls sort first (CQL semantics).
GROUP_END = 0x01
TAG_NULL = 0x04
TAG_FALSE = 0x10
TAG_TRUE = 0x11
TAG_INT = 0x20      # all integer types normalize to int64 in keys
TAG_DOUBLE = 0x28   # float/double normalize to float64 in keys
TAG_STRING = 0x30
TAG_BINARY = 0x32
TAG_HASH = 0x08     # 2-byte partition-hash prelude (reference kUInt16Hash)

_STRING_TERM = b"\x00\x00"


def _encode_int64(v: int) -> bytes:
    if not -(1 << 63) <= v < (1 << 63):
        raise ValueError(f"integer key value out of int64 range: {v}")
    # Sign-flip to map signed order onto unsigned byte order.
    return struct.pack(">Q", v + (1 << 63))


def _decode_int64(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


def _encode_double(v: float) -> bytes:
    v = float(v)
    if v == 0.0:
        v = 0.0  # canonicalize -0.0: logically equal keys must encode equal
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)      # negative: flip all bits
    else:
        bits |= 1 << 63                      # positive: flip sign bit
    return struct.pack(">Q", bits)


def _decode_double(b: bytes) -> float:
    bits = struct.unpack(">Q", b)[0]
    if bits & (1 << 63):
        bits &= ~(1 << 63) & ((1 << 64) - 1)
    else:
        bits = ~bits & ((1 << 64) - 1)
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


def _encode_str_bytes(raw: bytes) -> bytes:
    # Escape embedded NULs (0x00 -> 0x00 0x01) and terminate with 0x00 0x00,
    # keeping byte order == lexicographic order on the raw bytes
    # (reference: primitive_value.cc ZeroEncodeAndAppendStrToKey).
    return raw.replace(b"\x00", b"\x00\x01") + _STRING_TERM


def _decode_str_bytes(buf: bytes, pos: int) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        nxt = buf.index(b"\x00", pos)
        out += buf[pos:nxt]
        marker = buf[nxt + 1]
        if marker == 0x00:
            return bytes(out), nxt + 2
        if marker != 0x01:
            raise ValueError("corrupt string encoding")
        out.append(0)
        pos = nxt + 2


def encode_key_component(value, dtype: DataType) -> bytes:
    """Encode one key column value as [tag][payload]."""
    if value is None:
        return bytes([TAG_NULL])
    if dtype == DataType.BOOL:
        return bytes([TAG_TRUE if value else TAG_FALSE])
    if dtype.is_integer:
        return bytes([TAG_INT]) + _encode_int64(int(value))
    if dtype in (DataType.FLOAT, DataType.DOUBLE):
        return bytes([TAG_DOUBLE]) + _encode_double(float(value))
    if dtype == DataType.STRING:
        return bytes([TAG_STRING]) + _encode_str_bytes(
            value.encode("utf-8", "surrogateescape"))
    if dtype == DataType.BINARY:
        return bytes([TAG_BINARY]) + _encode_str_bytes(bytes(value))
    raise ValueError(f"type {dtype} not valid in a key")


def decode_key_component(buf: bytes, pos: int) -> tuple[object, int]:
    """Decode one component at pos -> (python value, new pos)."""
    tag = buf[pos]
    pos += 1
    if tag == TAG_NULL:
        return None, pos
    if tag == TAG_FALSE:
        return False, pos
    if tag == TAG_TRUE:
        return True, pos
    if tag == TAG_INT:
        return _decode_int64(buf[pos:pos + 8]), pos + 8
    if tag == TAG_DOUBLE:
        return _decode_double(buf[pos:pos + 8]), pos + 8
    if tag == TAG_STRING:
        raw, pos = _decode_str_bytes(buf, pos)
        return raw.decode("utf-8", "surrogateescape"), pos
    if tag == TAG_BINARY:
        return _decode_str_bytes(buf, pos)
    raise ValueError(f"unknown key tag 0x{tag:02x} at {pos - 1}")


def encode_doc_key(hash_code: int | None,
                   hashed_components: list[tuple[object, DataType]],
                   range_components: list[tuple[object, DataType]]) -> bytes:
    """Encode a full DocKey. hash_code is the uint16 partition hash, or None
    for range-partitioned tables (reference doc_key.cc DocKey::AppendTo)."""
    return encode_doc_key_prefix(
        hash_code, hashed_components, range_components) + bytes([GROUP_END])


def encode_doc_key_prefix(hash_code: int | None,
                          hashed_components: list[tuple[object, DataType]],
                          range_components: list[tuple[object, DataType]]) -> bytes:
    """Encode a key *prefix* (for range scans bounded on leading range
    columns): like encode_doc_key but without the trailing GROUP_END, so all
    keys extending the given range components share this byte prefix."""
    out = bytearray()
    if hash_code is None:
        if hashed_components:
            raise ValueError("hashed components require a hash_code")
    else:
        out.append(TAG_HASH)
        out += struct.pack(">H", hash_code & 0xFFFF)
        for value, dtype in hashed_components:
            out += encode_key_component(value, dtype)
        out.append(GROUP_END)
    for value, dtype in range_components:
        out += encode_key_component(value, dtype)
    return bytes(out)


def decode_doc_key(buf: bytes) -> tuple[int | None, list, list]:
    """Decode -> (hash_code, hashed values, range values)."""
    pos = 0
    hash_code = None
    hashed: list = []
    if buf and buf[0] == TAG_HASH:
        hash_code = struct.unpack(">H", buf[1:3])[0]
        pos = 3
        while buf[pos] != GROUP_END:
            value, pos = decode_key_component(buf, pos)
            hashed.append(value)
        pos += 1
    ranges: list = []
    while pos < len(buf) and buf[pos] != GROUP_END:
        value, pos = decode_key_component(buf, pos)
        ranges.append(value)
    return hash_code, hashed, ranges


def hashed_prefix(buf: bytes) -> bytes:
    """The hashed-components section of an encoded key, INCLUDING its
    terminating GROUP_END — the unit the run bloom filters key on
    (reference: DocDbAwareFilterPolicy's hash-prefix extraction,
    src/yb/docdb/doc_key.h:551-575). b'' for range-partitioned keys
    (no hash section -> filter does not apply)."""
    if not buf or buf[0] != TAG_HASH:
        return b""
    pos = 3
    while pos < len(buf) and buf[pos] != GROUP_END:
        _v, pos = decode_key_component(buf, pos)
    return bytes(buf[:pos + 1])


def prefix_successor(prefix: bytes) -> bytes:
    """Smallest byte string greater than every string with this prefix.

    Empty result means "no upper bound" (prefix was all 0xFF). Used to turn a
    key prefix into an exclusive scan upper bound (reference analog:
    rocksdb iterate_upper_bound construction)."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return b""
