"""Byte-comparable key encoding: the DocKey of the framework.

Reference analog: src/yb/docdb/doc_key.h:68 (DocKey), primitive_value.cc
(PrimitiveValue::AppendToKey), value_type.h:31-140 (ValueType tags),
src/yb/util/memcmpable_varint.cc. The invariant this module guarantees —
and the whole TPU data plane rests on — is:

    memcmp(encode(a), encode(b))  ==  logical_compare(a, b)

so that device kernels can compare fixed-width big-endian word prefixes of
encoded keys with plain int32 signed comparisons (after bias-flip, see
utils.planes) and reproduce logical key order.

Layout of an encoded DocKey (hash-partitioned table):

    [kHash][2-byte partition hash BE] [hashed components]* [kGroupEnd]
    [range components]* [kGroupEnd]

and for range-partitioned tables the hash prelude is omitted. Each component
is [type tag][payload]; tag values are chosen so kGroupEnd sorts before every
component tag (a shorter key group is a strict prefix and must sort first),
and NULL sorts before all values of a column.

Unlike the reference, the MVCC hybrid time is *not* appended to the key
(reference SubDocKey suffixes a descending-encoded DocHybridTime): columnar
blocks store (key, commit_ht) in separate planes and sort by (key asc,
ht desc) explicitly, which is what the device kernels want.
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.models.datatypes import DataType

# Type tags. Ordering constraints:
#   GROUP_END < NULL < FALSE < TRUE < INT < DOUBLE-family < STRING < BINARY
# GROUP_END lowest so shorter composite keys sort first; NULL lowest within a
# column so nulls sort first (CQL semantics).
GROUP_END = 0x01
TAG_NULL = 0x04
TAG_FALSE = 0x10
TAG_TRUE = 0x11
TAG_INT = 0x20      # all integer types normalize to int64 in keys
TAG_DATE = 0x22     # days since epoch, offset-binary uint32
TAG_TIME = 0x23     # nanoseconds since midnight, uint64
TAG_DECIMAL = 0x24  # comparable decimal (util/decimal.h semantics)
TAG_VARINT = 0x26   # comparable arbitrary-precision integer
TAG_DOUBLE = 0x28   # float/double normalize to float64 in keys
TAG_STRING = 0x30
TAG_BINARY = 0x32
TAG_UUID = 0x34     # 16 raw bytes (lexicographic)
TAG_TIMEUUID = 0x35  # [8B v1 timestamp][16 raw bytes]
TAG_INET = 0x36     # [version byte][packed address]
TAG_TUPLE = 0x38    # components (value-inferred tags) + GROUP_END
TAG_FROZEN = 0x3A   # [container kind][components] + GROUP_END
TAG_HASH = 0x08     # 2-byte partition-hash prelude (reference kUInt16Hash)

_STRING_TERM = b"\x00\x00"


# -- comparable varint / decimal (reference: util/decimal.h ordering,
#    util/memcmpable_varint.cc technique restated) ---------------------------

def _encode_cmp_varint(v: int) -> bytes:
    """Arbitrary-precision int -> self-delimiting bytes whose memcmp
    order is numeric order: [0xC0+n][n-byte magnitude] for v >= 0,
    [0x3F-n][complemented magnitude] for v < 0 (longer negative
    magnitudes get smaller prefixes; magnitudes <= 62 bytes, i.e.
    ~496 bits — plenty beyond the reference's practical range)."""
    if v >= 0:
        mag = v.to_bytes((v.bit_length() + 7) // 8, "big") if v else b""
        if len(mag) > 62:
            raise ValueError("varint key value too large")
        return bytes([0xC0 + len(mag)]) + mag
    m = -v
    mag = m.to_bytes((m.bit_length() + 7) // 8, "big")
    if len(mag) > 62:
        raise ValueError("varint key value too large")
    return bytes([0x3F - len(mag)]) + bytes(0xFF - b for b in mag)


def _decode_cmp_varint(buf: bytes, pos: int) -> tuple[int, int]:
    first = buf[pos]
    pos += 1
    if first >= 0xC0:
        n = first - 0xC0
        mag = buf[pos:pos + n]
        return (int.from_bytes(mag, "big") if n else 0), pos + n
    n = 0x3F - first
    mag = bytes(0xFF - b for b in buf[pos:pos + n])
    return -int.from_bytes(mag, "big"), pos + n


def _encode_decimal(value) -> bytes:
    """decimal.Decimal -> comparable payload: class byte (0x10 neg /
    0x20 zero / 0x30 pos), then comparable (adjusted exponent, digit
    string) — negatives complemented so order reverses. Matches the
    reference's ordering contract (src/yb/util/decimal.h): trailing
    zeros are insignificant, exponent dominates, digits tiebreak."""
    import decimal

    d = decimal.Decimal(value)
    if d.is_nan() or d.is_infinite():
        raise ValueError("NaN/Infinity decimals are not storable")
    if d == 0:
        return b"\x20"
    sign, digits, exp = d.normalize().as_tuple()
    adj = exp + len(digits) - 1
    body = _encode_cmp_varint(adj) + bytes(dd + 1 for dd in digits) \
        + b"\x00"
    if sign:
        return b"\x10" + bytes(0xFF - b for b in body)
    return b"\x30" + body


def _decode_decimal(buf: bytes, pos: int):
    import decimal

    cls = buf[pos]
    pos += 1
    if cls == 0x20:
        return decimal.Decimal(0), pos
    neg = cls == 0x10
    if neg:
        # Complement lazily: find the complemented terminator (0xFF).
        first = 0xFF - buf[pos]
        n = (first - 0xC0) if first >= 0xC0 else (0x3F - first)
        vpos = pos + 1 + n
        adj, _ = _decode_cmp_varint(
            bytes(0xFF - b for b in buf[pos:vpos]), 0)
        digits = []
        while buf[vpos] != 0xFF:
            digits.append((0xFF - buf[vpos]) - 1)
            vpos += 1
        pos = vpos + 1
    else:
        adj, vpos = _decode_cmp_varint(buf, pos)
        digits = []
        while buf[vpos] != 0x00:
            digits.append(buf[vpos] - 1)
            vpos += 1
        pos = vpos + 1
    ds = "".join(str(dd) for dd in digits)
    text = f"{'-' if neg else ''}{ds[0]}.{ds[1:] or '0'}E{adj}"
    return decimal.Decimal(text).normalize(), pos


def _infer_component_dtype(value) -> DataType:
    """Runtime dtype of a tuple/frozen element (elements self-describe
    via their tags, so nested containers need no schema plumbing)."""
    import datetime
    import decimal
    import uuid as _uuid

    from yugabyte_db_tpu.models.datatypes import Inet, TimeUuid

    if isinstance(value, bool):
        return DataType.BOOL
    if isinstance(value, int):
        return DataType.INT64
    if isinstance(value, float):
        return DataType.DOUBLE
    if isinstance(value, str):
        return DataType.STRING
    if isinstance(value, (bytes, bytearray)):
        return DataType.BINARY
    if isinstance(value, decimal.Decimal):
        return DataType.DECIMAL
    if isinstance(value, TimeUuid):
        return DataType.TIMEUUID
    if isinstance(value, _uuid.UUID):
        return DataType.UUID
    if isinstance(value, Inet):
        return DataType.INET
    if isinstance(value, datetime.datetime):
        raise ValueError("datetime not valid in a key component")
    if isinstance(value, datetime.date):
        return DataType.DATE
    if isinstance(value, datetime.time):
        return DataType.TIME
    if isinstance(value, tuple):
        return DataType.TUPLE
    if isinstance(value, (list, set, frozenset, dict)):
        return DataType.FROZEN
    raise ValueError(f"cannot infer key dtype of {type(value)}")


def _encode_int64(v: int) -> bytes:
    if not -(1 << 63) <= v < (1 << 63):
        raise ValueError(f"integer key value out of int64 range: {v}")
    # Sign-flip to map signed order onto unsigned byte order.
    return struct.pack(">Q", v + (1 << 63))


def _decode_int64(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


def _encode_double(v: float) -> bytes:
    v = float(v)
    if v == 0.0:
        v = 0.0  # canonicalize -0.0: logically equal keys must encode equal
    bits = struct.unpack(">Q", struct.pack(">d", v))[0]
    if bits & (1 << 63):
        bits = ~bits & ((1 << 64) - 1)      # negative: flip all bits
    else:
        bits |= 1 << 63                      # positive: flip sign bit
    return struct.pack(">Q", bits)


def _decode_double(b: bytes) -> float:
    bits = struct.unpack(">Q", b)[0]
    if bits & (1 << 63):
        bits &= ~(1 << 63) & ((1 << 64) - 1)
    else:
        bits = ~bits & ((1 << 64) - 1)
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


def _encode_str_bytes(raw: bytes) -> bytes:
    # Escape embedded NULs (0x00 -> 0x00 0x01) and terminate with 0x00 0x00,
    # keeping byte order == lexicographic order on the raw bytes
    # (reference: primitive_value.cc ZeroEncodeAndAppendStrToKey).
    return raw.replace(b"\x00", b"\x00\x01") + _STRING_TERM


def _decode_str_bytes(buf: bytes, pos: int) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        nxt = buf.index(b"\x00", pos)
        out += buf[pos:nxt]
        marker = buf[nxt + 1]
        if marker == 0x00:
            return bytes(out), nxt + 2
        if marker != 0x01:
            raise ValueError("corrupt string encoding")
        out.append(0)
        pos = nxt + 2


def encode_key_component(value, dtype: DataType) -> bytes:
    """Encode one key column value as [tag][payload]."""
    if value is None:
        return bytes([TAG_NULL])
    if dtype == DataType.BOOL:
        return bytes([TAG_TRUE if value else TAG_FALSE])
    if dtype == DataType.DATE:
        import datetime

        days = (value - datetime.date(1970, 1, 1)).days
        return bytes([TAG_DATE]) + struct.pack(">I", days + (1 << 31))
    if dtype == DataType.TIME:
        ns = ((value.hour * 60 + value.minute) * 60
              + value.second) * 10**9 + value.microsecond * 1000
        return bytes([TAG_TIME]) + struct.pack(">Q", ns)
    if dtype.is_integer:
        return bytes([TAG_INT]) + _encode_int64(int(value))
    if dtype == DataType.VARINT:
        return bytes([TAG_VARINT]) + _encode_cmp_varint(int(value))
    if dtype == DataType.DECIMAL:
        return bytes([TAG_DECIMAL]) + _encode_decimal(value)
    if dtype in (DataType.FLOAT, DataType.DOUBLE):
        return bytes([TAG_DOUBLE]) + _encode_double(float(value))
    if dtype == DataType.STRING:
        return bytes([TAG_STRING]) + _encode_str_bytes(
            value.encode("utf-8", "surrogateescape"))
    if dtype == DataType.BINARY:
        return bytes([TAG_BINARY]) + _encode_str_bytes(bytes(value))
    if dtype == DataType.UUID:
        return bytes([TAG_UUID]) + value.bytes  # UUID or TimeUuid
    if dtype == DataType.TIMEUUID:
        from yugabyte_db_tpu.models.datatypes import TimeUuid

        tu = value if isinstance(value, TimeUuid) else TimeUuid(value)
        return bytes([TAG_TIMEUUID]) + struct.pack(">Q", tu.u.time) \
            + tu.bytes
    if dtype == DataType.INET:
        from yugabyte_db_tpu.models.datatypes import Inet

        inet = value if isinstance(value, Inet) else Inet(value)
        return bytes([TAG_INET, inet.version]) + inet.packed
    if dtype == DataType.TUPLE:
        out = bytearray([TAG_TUPLE])
        for el in value:
            out += encode_key_component(
                el, _infer_component_dtype(el) if el is not None
                else DataType.NULL)
        out.append(GROUP_END)
        return bytes(out)
    if dtype == DataType.FROZEN:
        return bytes([TAG_FROZEN]) + _encode_frozen(value)
    raise ValueError(f"type {dtype} not valid in a key")


def _encode_frozen(value) -> bytes:
    """Canonical comparable bytes of a frozen container: kind byte
    (list 0x05 / set 0x06 / map 0x07), then self-describing element
    components, GROUP_END-terminated (sets sorted; maps sorted by key,
    flattened k,v — CQL frozen-collection comparison semantics)."""
    def comp(el):
        return encode_key_component(
            el, _infer_component_dtype(el) if el is not None
            else DataType.NULL)

    out = bytearray()
    if isinstance(value, (list, tuple)):
        out.append(0x05)
        items = list(value)
    elif isinstance(value, (set, frozenset)):
        out.append(0x06)
        items = sorted(value, key=comp)
    elif isinstance(value, dict):
        out.append(0x07)
        items = []
        for k in sorted(value, key=comp):
            items += [k, value[k]]
    else:
        raise ValueError(f"not a frozen container: {type(value)}")
    for el in items:
        out += comp(el)
    out.append(GROUP_END)
    return bytes(out)


def decode_key_component(buf: bytes, pos: int) -> tuple[object, int]:
    """Decode one component at pos -> (python value, new pos)."""
    tag = buf[pos]
    pos += 1
    if tag == TAG_NULL:
        return None, pos
    if tag == TAG_FALSE:
        return False, pos
    if tag == TAG_TRUE:
        return True, pos
    if tag == TAG_INT:
        return _decode_int64(buf[pos:pos + 8]), pos + 8
    if tag == TAG_DATE:
        import datetime

        days = struct.unpack(">I", buf[pos:pos + 4])[0] - (1 << 31)
        return (datetime.date(1970, 1, 1)
                + datetime.timedelta(days=days)), pos + 4
    if tag == TAG_TIME:
        import datetime

        ns = struct.unpack(">Q", buf[pos:pos + 8])[0]
        us, _ = divmod(ns, 1000)
        s, us = divmod(us, 10**6)
        m, s = divmod(s, 60)
        h, m = divmod(m, 60)
        return datetime.time(h, m, s, us), pos + 8
    if tag == TAG_VARINT:
        return _decode_cmp_varint(buf, pos)
    if tag == TAG_DECIMAL:
        return _decode_decimal(buf, pos)
    if tag == TAG_DOUBLE:
        return _decode_double(buf[pos:pos + 8]), pos + 8
    if tag == TAG_STRING:
        raw, pos = _decode_str_bytes(buf, pos)
        return raw.decode("utf-8", "surrogateescape"), pos
    if tag == TAG_BINARY:
        return _decode_str_bytes(buf, pos)
    if tag == TAG_UUID:
        import uuid as _uuid

        return _uuid.UUID(bytes=bytes(buf[pos:pos + 16])), pos + 16
    if tag == TAG_TIMEUUID:
        from yugabyte_db_tpu.models.datatypes import TimeUuid
        import uuid as _uuid

        raw = bytes(buf[pos + 8:pos + 24])
        return TimeUuid(_uuid.UUID(bytes=raw)), pos + 24
    if tag == TAG_INET:
        from yugabyte_db_tpu.models.datatypes import Inet

        version = buf[pos]
        n = 4 if version == 4 else 16
        return Inet(bytes(buf[pos + 1:pos + 1 + n])), pos + 1 + n
    if tag == TAG_TUPLE:
        out = []
        while buf[pos] != GROUP_END:
            v, pos = decode_key_component(buf, pos)
            out.append(v)
        return tuple(out), pos + 1
    if tag == TAG_FROZEN:
        kind = buf[pos]
        pos += 1
        items = []
        while buf[pos] != GROUP_END:
            v, pos = decode_key_component(buf, pos)
            items.append(v)
        pos += 1
        if kind == 0x05:
            return items, pos
        if kind == 0x06:
            return items, pos  # sets normalize to sorted lists
        pairs = dict(zip(items[::2], items[1::2]))
        return pairs, pos
    raise ValueError(f"unknown key tag 0x{tag:02x} at {pos - 1}")


def encode_doc_key(hash_code: int | None,
                   hashed_components: list[tuple[object, DataType]],
                   range_components: list[tuple[object, DataType]]) -> bytes:
    """Encode a full DocKey. hash_code is the uint16 partition hash, or None
    for range-partitioned tables (reference doc_key.cc DocKey::AppendTo)."""
    return encode_doc_key_prefix(
        hash_code, hashed_components, range_components) + bytes([GROUP_END])


def encode_doc_key_prefix(hash_code: int | None,
                          hashed_components: list[tuple[object, DataType]],
                          range_components: list[tuple[object, DataType]]) -> bytes:
    """Encode a key *prefix* (for range scans bounded on leading range
    columns): like encode_doc_key but without the trailing GROUP_END, so all
    keys extending the given range components share this byte prefix."""
    out = bytearray()
    if hash_code is None:
        if hashed_components:
            raise ValueError("hashed components require a hash_code")
    else:
        out.append(TAG_HASH)
        out += struct.pack(">H", hash_code & 0xFFFF)
        for value, dtype in hashed_components:
            out += encode_key_component(value, dtype)
        out.append(GROUP_END)
    for value, dtype in range_components:
        out += encode_key_component(value, dtype)
    return bytes(out)


def decode_doc_key(buf: bytes) -> tuple[int | None, list, list]:
    """Decode -> (hash_code, hashed values, range values)."""
    pos = 0
    hash_code = None
    hashed: list = []
    if buf and buf[0] == TAG_HASH:
        hash_code = struct.unpack(">H", buf[1:3])[0]
        pos = 3
        while buf[pos] != GROUP_END:
            value, pos = decode_key_component(buf, pos)
            hashed.append(value)
        pos += 1
    ranges: list = []
    while pos < len(buf) and buf[pos] != GROUP_END:
        value, pos = decode_key_component(buf, pos)
        ranges.append(value)
    return hash_code, hashed, ranges


def full_doc_key_of(buf: bytes, num_hash: int,
                    num_range: int) -> bytes | None:
    """The canonical FULL doc key when ``buf`` binds every key column,
    else None. Accepts both spellings: the full encoded key (trailing
    GROUP_END) and the all-components-bound prefix
    (encode_doc_key_prefix output, no terminator) — the prefix gets its
    terminator appended. Used to classify exact-key reads."""
    pos = 0
    hashed = 0
    if num_hash:
        if not buf or buf[0] != TAG_HASH:
            return None
        pos = 3
        try:
            while pos < len(buf) and buf[pos] != GROUP_END:
                _v, pos = decode_key_component(buf, pos)
                hashed += 1
        except Exception:  # noqa: BLE001 — not a decodable key
            return None
        if pos >= len(buf) or hashed != num_hash:
            return None
        pos += 1  # hashed-section GROUP_END
    ranges = 0
    try:
        while pos < len(buf) and buf[pos] != GROUP_END:
            _v, pos = decode_key_component(buf, pos)
            ranges += 1
    except Exception:  # noqa: BLE001
        return None
    if ranges != num_range:
        return None
    if pos == len(buf):
        return buf + bytes([GROUP_END])  # prefix form
    if pos == len(buf) - 1 and buf[pos] == GROUP_END:
        return buf  # already the full key
    return None


def hashed_prefix(buf: bytes) -> bytes:
    """The hashed-components section of an encoded key, INCLUDING its
    terminating GROUP_END — the unit the run bloom filters key on
    (reference: DocDbAwareFilterPolicy's hash-prefix extraction,
    src/yb/docdb/doc_key.h:551-575). b'' for range-partitioned keys
    (no hash section -> filter does not apply)."""
    if not buf or buf[0] != TAG_HASH:
        return b""
    pos = 3
    while pos < len(buf) and buf[pos] != GROUP_END:
        _v, pos = decode_key_component(buf, pos)
    return bytes(buf[:pos + 1])


_EXT_TYPES = None


def encode_component_value(v) -> bytes | None:
    """Rich QL scalar -> its byte-comparable component bytes, or None
    when v is not one (the tagged codec's T_EXT payload; utils/codec.py
    and native/tagcodec.h both call this)."""
    global _EXT_TYPES
    if _EXT_TYPES is None:
        import datetime
        import decimal
        import uuid as _uuid

        from yugabyte_db_tpu.models.datatypes import Inet, TimeUuid

        _EXT_TYPES = (decimal.Decimal, _uuid.UUID, TimeUuid, Inet,
                      datetime.date, datetime.time)
    if not isinstance(v, _EXT_TYPES):
        return None
    return encode_key_component(v, _infer_component_dtype(v))


def decode_component_value(raw: bytes):
    """T_EXT payload -> the rich scalar value."""
    v, _pos = decode_key_component(raw, 0)
    return v


def prefix_successor(prefix: bytes) -> bytes:
    """Smallest byte string greater than every string with this prefix.

    Empty result means "no upper bound" (prefix was all 0xFF). Used to turn a
    key prefix into an exclusive scan upper bound (reference analog:
    rocksdb iterate_upper_bound construction)."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return b""
