"""Logical data types and their device representations.

Reference analog: src/yb/common/ql_type.h / DataType in common.proto. Each
logical type maps to (a) a byte-comparable key encoding (models.encoding),
and (b) a device column representation: a numpy/jax dtype for fixed-width
types, or a varlen byte-pool + 64-bit order-preserving prefix planes for
strings/binary (TPU kernels compare/select on the prefix; the host resolves
rare prefix ties and materializes full bytes).
"""

from __future__ import annotations

import enum

import numpy as np


class DataType(enum.IntEnum):
    NULL = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    FLOAT = 5
    DOUBLE = 6
    BOOL = 7
    STRING = 8
    BINARY = 9
    TIMESTAMP = 10  # micros since epoch, int64 semantics
    COUNTER = 11    # int64 with increment semantics (YCQL counter)
    # Opaque host-resident types: the value lives host-side like a varlen
    # payload (device planes carry a serialized prefix, used only for
    # grouping/equality heuristics; predicates on these are host-only).
    # Collections store normalized python containers (SET as a sorted
    # list, MAP with sorted keys) so replicas serialize identically.
    LIST = 12
    SET = 13
    MAP = 14
    JSONB = 15      # parsed JSON value (reference: common/jsonb.cc)
    # Extended QL scalar surface (reference: common.proto:65-99 DECIMAL/
    # VARINT/INET/UUID/TIMEUUID/DATE/TIME, util/decimal.h ordering,
    # util/uuid.cc comparable encoding). Values are rich host objects
    # (decimal.Decimal, int, yb UUID/Inet wrappers, datetime.date/time,
    # tuples, frozen containers); keys get dedicated byte-comparable
    # encodings (models.encoding), value columns ride the varlen host-
    # payload path (host-exact predicates).
    DECIMAL = 16    # arbitrary-precision decimal (decimal.Decimal)
    VARINT = 17     # arbitrary-precision integer (int)
    UUID = 18       # uuid.UUID, lexicographic byte order
    TIMEUUID = 19   # TimeUuid (v1), ordered by embedded timestamp
    INET = 20       # Inet wrapper (v4 sorts before v6)
    DATE = 21       # datetime.date
    TIME = 22       # datetime.time (ns precision per CQL)
    TUPLE = 23      # python tuple of scalar values
    FROZEN = 24     # frozen collection (normalized list/set/map)

    @property
    def is_fixed_width(self) -> bool:
        return self not in (DataType.STRING, DataType.BINARY,
                            DataType.LIST, DataType.SET, DataType.MAP,
                            DataType.JSONB, DataType.DECIMAL,
                            DataType.VARINT, DataType.UUID,
                            DataType.TIMEUUID, DataType.INET,
                            DataType.DATE, DataType.TIME,
                            DataType.TUPLE, DataType.FROZEN)

    @property
    def is_integer(self) -> bool:
        return self in (
            DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
            DataType.TIMESTAMP, DataType.COUNTER,
        )

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self in (DataType.FLOAT, DataType.DOUBLE)

    @property
    def np_dtype(self) -> np.dtype:
        """Host (numpy) storage dtype of a value column of this type."""
        return {
            DataType.INT8: np.dtype(np.int8),
            DataType.INT16: np.dtype(np.int16),
            DataType.INT32: np.dtype(np.int32),
            DataType.INT64: np.dtype(np.int64),
            DataType.TIMESTAMP: np.dtype(np.int64),
            DataType.COUNTER: np.dtype(np.int64),
            DataType.FLOAT: np.dtype(np.float32),
            DataType.DOUBLE: np.dtype(np.float64),
            DataType.BOOL: np.dtype(np.bool_),
        }[self]

    @property
    def device_planes(self) -> int:
        """Number of int32/float32 planes this type occupies device-side.

        int64-family and double columns ship as two 32-bit planes (TPU has no
        cheap 64-bit); varlen types ship as two planes of order-preserving
        8-byte prefix.
        """
        if not self.is_fixed_width:
            return 2
        if self.np_dtype.itemsize == 8:
            return 2
        return 1

    @staticmethod
    def parse(name: str) -> "DataType":
        aliases = {
            "DECIMAL": DataType.DECIMAL,
            "NUMERIC": DataType.DECIMAL,
            "VARINT": DataType.VARINT,
            "UUID": DataType.UUID,
            "TIMEUUID": DataType.TIMEUUID,
            "INET": DataType.INET,
            "DATE": DataType.DATE,
            "TIME": DataType.TIME,
            "TUPLE": DataType.TUPLE,
            "FROZEN": DataType.FROZEN,
            "INT8": DataType.INT8,
            "INT16": DataType.INT16,
            "INT64": DataType.INT64,
            "TINYINT": DataType.INT8,
            "SMALLINT": DataType.INT16,
            "INT": DataType.INT32,
            "INT32": DataType.INT32,
            "INTEGER": DataType.INT32,
            "BIGINT": DataType.INT64,
            "FLOAT": DataType.FLOAT,
            "REAL": DataType.FLOAT,
            "DOUBLE": DataType.DOUBLE,
            "BOOLEAN": DataType.BOOL,
            "BOOL": DataType.BOOL,
            "TEXT": DataType.STRING,
            "VARCHAR": DataType.STRING,
            "STRING": DataType.STRING,
            "BLOB": DataType.BINARY,
            "BINARY": DataType.BINARY,
            "TIMESTAMP": DataType.TIMESTAMP,
            "COUNTER": DataType.COUNTER,
            "LIST": DataType.LIST,
            "SET": DataType.SET,
            "MAP": DataType.MAP,
            "JSONB": DataType.JSONB,
        }
        key = name.strip().upper()
        if key not in aliases:
            raise ValueError(f"unknown data type: {name}")
        return aliases[key]


class TimeUuid:
    """A v1 (time-based) UUID ordered by its embedded timestamp, then
    raw bytes — CQL timeuuid comparison semantics (reference:
    src/yb/util/uuid.cc ToComparable's time-component reordering)."""

    __slots__ = ("u",)

    def __init__(self, u):
        import uuid as _uuid

        self.u = u if isinstance(u, _uuid.UUID) else _uuid.UUID(str(u))

    @property
    def bytes(self) -> bytes:
        return self.u.bytes

    def sort_key(self):
        return (self.u.time, self.u.bytes)

    def __eq__(self, other):
        o = other.u if isinstance(other, TimeUuid) else other
        return self.u == o

    def __hash__(self):
        return hash(self.u)

    def __lt__(self, other):
        return self.sort_key() < TimeUuid(
            other.u if isinstance(other, TimeUuid) else other).sort_key()

    def __le__(self, other):
        return self == other or self < other

    def __gt__(self, other):
        return not self <= other

    def __ge__(self, other):
        return not self < other

    def __str__(self):
        return str(self.u)

    def __repr__(self):
        return f"TimeUuid('{self.u}')"


class Inet:
    """An IPv4/IPv6 address; v4 sorts before v6, then by packed bytes
    (one column may mix families — plain ipaddress objects refuse to
    compare across versions)."""

    __slots__ = ("version", "packed")

    def __init__(self, addr):
        import ipaddress

        if isinstance(addr, Inet):
            self.version, self.packed = addr.version, addr.packed
            return
        a = ipaddress.ip_address(addr)
        self.version = a.version
        self.packed = a.packed

    def __eq__(self, other):
        o = Inet(other) if not isinstance(other, Inet) else other
        return (self.version, self.packed) == (o.version, o.packed)

    def __hash__(self):
        return hash((self.version, self.packed))

    def __lt__(self, other):
        o = Inet(other) if not isinstance(other, Inet) else other
        return (self.version, self.packed) < (o.version, o.packed)

    def __le__(self, other):
        return self == other or self < other

    def __gt__(self, other):
        return not self <= other

    def __ge__(self, other):
        return not self < other

    def __str__(self):
        import ipaddress

        return str(ipaddress.ip_address(self.packed))

    def __repr__(self):
        return f"Inet('{self}')"


def python_value_matches(dtype: DataType, value) -> bool:
    """Loose runtime type check for a python value against a logical type."""
    import datetime
    import decimal
    import uuid as _uuid

    if value is None:
        return True
    if dtype == DataType.DECIMAL:
        return isinstance(value, (decimal.Decimal, int))
    if dtype == DataType.VARINT:
        return isinstance(value, int) and not isinstance(value, bool)
    if dtype == DataType.UUID:
        return isinstance(value, (_uuid.UUID, TimeUuid))
    if dtype == DataType.TIMEUUID:
        return isinstance(value, (TimeUuid, _uuid.UUID))
    if dtype == DataType.INET:
        return isinstance(value, Inet)
    if dtype == DataType.DATE:
        return isinstance(value, datetime.date) and \
            not isinstance(value, datetime.datetime)
    if dtype == DataType.TIME:
        return isinstance(value, datetime.time)
    if dtype == DataType.TUPLE:
        return isinstance(value, tuple)
    if dtype == DataType.FROZEN:
        return isinstance(value, (list, dict, tuple, set, frozenset))
    if dtype.is_integer:
        return isinstance(value, int) and not isinstance(value, bool)
    if dtype in (DataType.FLOAT, DataType.DOUBLE):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if dtype == DataType.BOOL:
        return isinstance(value, bool)
    if dtype == DataType.STRING:
        return isinstance(value, str)
    if dtype == DataType.BINARY:
        return isinstance(value, (bytes, bytearray))
    if dtype == DataType.LIST:
        return isinstance(value, list)
    if dtype == DataType.SET:
        return isinstance(value, (list, set, frozenset))
    if dtype == DataType.MAP:
        return isinstance(value, dict)
    if dtype == DataType.JSONB:
        return isinstance(value, (dict, list, str, int, float, bool))
    return False
