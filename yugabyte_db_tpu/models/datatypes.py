"""Logical data types and their device representations.

Reference analog: src/yb/common/ql_type.h / DataType in common.proto. Each
logical type maps to (a) a byte-comparable key encoding (models.encoding),
and (b) a device column representation: a numpy/jax dtype for fixed-width
types, or a varlen byte-pool + 64-bit order-preserving prefix planes for
strings/binary (TPU kernels compare/select on the prefix; the host resolves
rare prefix ties and materializes full bytes).
"""

from __future__ import annotations

import enum

import numpy as np


class DataType(enum.IntEnum):
    NULL = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    FLOAT = 5
    DOUBLE = 6
    BOOL = 7
    STRING = 8
    BINARY = 9
    TIMESTAMP = 10  # micros since epoch, int64 semantics
    COUNTER = 11    # int64 with increment semantics (YCQL counter)
    # Opaque host-resident types: the value lives host-side like a varlen
    # payload (device planes carry a serialized prefix, used only for
    # grouping/equality heuristics; predicates on these are host-only).
    # Collections store normalized python containers (SET as a sorted
    # list, MAP with sorted keys) so replicas serialize identically.
    LIST = 12
    SET = 13
    MAP = 14
    JSONB = 15      # parsed JSON value (reference: common/jsonb.cc)

    @property
    def is_fixed_width(self) -> bool:
        return self not in (DataType.STRING, DataType.BINARY,
                            DataType.LIST, DataType.SET, DataType.MAP,
                            DataType.JSONB)

    @property
    def is_integer(self) -> bool:
        return self in (
            DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
            DataType.TIMESTAMP, DataType.COUNTER,
        )

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self in (DataType.FLOAT, DataType.DOUBLE)

    @property
    def np_dtype(self) -> np.dtype:
        """Host (numpy) storage dtype of a value column of this type."""
        return {
            DataType.INT8: np.dtype(np.int8),
            DataType.INT16: np.dtype(np.int16),
            DataType.INT32: np.dtype(np.int32),
            DataType.INT64: np.dtype(np.int64),
            DataType.TIMESTAMP: np.dtype(np.int64),
            DataType.COUNTER: np.dtype(np.int64),
            DataType.FLOAT: np.dtype(np.float32),
            DataType.DOUBLE: np.dtype(np.float64),
            DataType.BOOL: np.dtype(np.bool_),
        }[self]

    @property
    def device_planes(self) -> int:
        """Number of int32/float32 planes this type occupies device-side.

        int64-family and double columns ship as two 32-bit planes (TPU has no
        cheap 64-bit); varlen types ship as two planes of order-preserving
        8-byte prefix.
        """
        if not self.is_fixed_width:
            return 2
        if self.np_dtype.itemsize == 8:
            return 2
        return 1

    @staticmethod
    def parse(name: str) -> "DataType":
        aliases = {
            "INT8": DataType.INT8,
            "INT16": DataType.INT16,
            "INT64": DataType.INT64,
            "TINYINT": DataType.INT8,
            "SMALLINT": DataType.INT16,
            "INT": DataType.INT32,
            "INT32": DataType.INT32,
            "INTEGER": DataType.INT32,
            "BIGINT": DataType.INT64,
            "FLOAT": DataType.FLOAT,
            "REAL": DataType.FLOAT,
            "DOUBLE": DataType.DOUBLE,
            "BOOLEAN": DataType.BOOL,
            "BOOL": DataType.BOOL,
            "TEXT": DataType.STRING,
            "VARCHAR": DataType.STRING,
            "STRING": DataType.STRING,
            "BLOB": DataType.BINARY,
            "BINARY": DataType.BINARY,
            "TIMESTAMP": DataType.TIMESTAMP,
            "COUNTER": DataType.COUNTER,
            "LIST": DataType.LIST,
            "SET": DataType.SET,
            "MAP": DataType.MAP,
            "JSONB": DataType.JSONB,
        }
        key = name.strip().upper()
        if key not in aliases:
            raise ValueError(f"unknown data type: {name}")
        return aliases[key]


def python_value_matches(dtype: DataType, value) -> bool:
    """Loose runtime type check for a python value against a logical type."""
    if value is None:
        return True
    if dtype.is_integer:
        return isinstance(value, int) and not isinstance(value, bool)
    if dtype in (DataType.FLOAT, DataType.DOUBLE):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if dtype == DataType.BOOL:
        return isinstance(value, bool)
    if dtype == DataType.STRING:
        return isinstance(value, str)
    if dtype == DataType.BINARY:
        return isinstance(value, (bytes, bytearray))
    if dtype == DataType.LIST:
        return isinstance(value, list)
    if dtype == DataType.SET:
        return isinstance(value, (list, set, frozenset))
    if dtype == DataType.MAP:
        return isinstance(value, dict)
    if dtype == DataType.JSONB:
        return isinstance(value, (dict, list, str, int, float, bool))
    return False
