"""Protocol value serialization shared by the YQL frontends and the
native wire page server.

One definition of "a value's wire bytes" per protocol, used by three
consumers that must agree byte-for-byte:

- the CQL result writer (yql.cql.wire_protocol.encode_value),
- the PG text writer (yql.pgsql.wire._text / data_row),
- the native page server's pre-encoded payload blobs and its fallback
  serializer (storage.host_page), whose C emitter mirrors these rules
  for plane-resident types (see native/writeplane.cc WireEmit).

Reference analog: the reference serializes each result row once into
``rows_data`` (src/yb/common/ql_rowblock.h:66 Serialize) and the
frontends forward bytes; these functions define that row format here.
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.models.datatypes import DataType

# CQL binary widths per integer-semantics logical type (protocol §6).
CQL_INT_WIDTH = {
    DataType.INT8: 1,
    DataType.INT16: 2,
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.TIMESTAMP: 8,
    DataType.COUNTER: 8,
}


def _varint_bytes(v: int) -> bytes:
    """Two's-complement minimal big-endian (the CQL varint payload)."""
    n = max(1, (v.bit_length() + 8) // 8)
    return v.to_bytes(n, "big", signed=True)


def cql_cell(dt: DataType, v) -> bytes | None:
    """Python value -> CQL binary cell payload (None -> NULL cell).
    Formats per the native protocol §6 (reference serializers:
    src/yb/common/ql_value.cc Serialize)."""
    if v is None:
        return None
    w = CQL_INT_WIDTH.get(dt)
    if w is not None:
        # Two's-complement wrap (CQL integer arithmetic overflows by
        # wrapping; aggregate sums can exceed the column width).
        return (int(v) & ((1 << (8 * w)) - 1)).to_bytes(w, "big")
    if dt == DataType.BOOL:
        return b"\x01" if v else b"\x00"
    if dt == DataType.DOUBLE:
        return struct.pack(">d", float(v))
    if dt == DataType.FLOAT:
        return struct.pack(">f", float(v))
    if dt == DataType.STRING:
        return str(v).encode("utf-8")
    if dt == DataType.VARINT:
        return _varint_bytes(int(v))
    if dt == DataType.DECIMAL:
        import decimal

        d = decimal.Decimal(v)
        sign, digits, exp = d.as_tuple()
        unscaled = int("".join(map(str, digits)) or "0")
        if sign:
            unscaled = -unscaled
        return struct.pack(">i", -exp) + _varint_bytes(unscaled)
    if dt in (DataType.UUID, DataType.TIMEUUID):
        return v.bytes  # uuid.UUID and TimeUuid both expose raw bytes
    if dt == DataType.INET:
        from yugabyte_db_tpu.models.datatypes import Inet

        return (v if isinstance(v, Inet) else Inet(v)).packed
    if dt == DataType.DATE:
        import datetime

        days = (v - datetime.date(1970, 1, 1)).days
        return struct.pack(">I", days + (1 << 31))
    if dt == DataType.TIME:
        ns = ((v.hour * 60 + v.minute) * 60 + v.second) * 10**9 \
            + v.microsecond * 1000
        return struct.pack(">q", ns)
    if dt == DataType.TUPLE:
        return b"".join(_cql_element(el) for el in v)
    if dt == DataType.FROZEN:
        return _cql_frozen(v)
    return bytes(v)  # BLOB and opaque payloads


def _cql_element(el) -> bytes:
    """[int32 len][payload] for a tuple/collection element, its type
    inferred from the runtime value (elements self-describe)."""
    if el is None:
        return b"\xff\xff\xff\xff"
    from yugabyte_db_tpu.models.encoding import _infer_component_dtype

    b = cql_cell(_infer_component_dtype(el), el)
    return struct.pack(">i", len(b)) + b


def _cql_frozen(v) -> bytes:
    """Frozen collection payload: [int32 count] then length-prefixed
    elements (map: k,v pairs, key-sorted; set: element-sorted)."""
    if isinstance(v, dict):
        keys = sorted(v, key=_cql_element)
        parts = [struct.pack(">i", len(keys))]
        for k in keys:
            parts.append(_cql_element(k))
            parts.append(_cql_element(v[k]))
        return b"".join(parts)
    items = (sorted(v, key=_cql_element)
             if isinstance(v, (set, frozenset)) else list(v))
    return struct.pack(">i", len(items)) + b"".join(
        _cql_element(el) for el in items)


def pg_text(v) -> bytes:
    """Python value -> PG text-format payload (caller handles NULL)."""
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, (bytes, bytearray)):
        return b"\\x" + bytes(v).hex().encode()
    if isinstance(v, (dict, list)):  # jsonb / collections: json text
        import json

        return json.dumps(v, separators=(",", ":")).encode()
    return str(v).encode("utf-8", "replace")


def serialize_rows(fmt: str, dtypes, rows) -> bytes:
    """Rows -> concatenated wire bytes; the Python twin of the native
    emitter (fallback for shapes the page server can't serve).

    fmt "cql": per cell int32 BE length + payload (NULL = -1).
    fmt "pg": one complete DataRow message per row.
    """
    parts: list[bytes] = []
    if fmt == "cql":
        for row in rows:
            for dt, v in zip(dtypes, row):
                b = cql_cell(dt, v)
                if b is None:
                    parts.append(b"\xff\xff\xff\xff")
                else:
                    parts.append(struct.pack(">i", len(b)) + b)
        return b"".join(parts)
    for row in rows:
        cells: list[bytes] = [struct.pack(">H", len(row))]
        for v in row:
            if v is None:
                cells.append(b"\xff\xff\xff\xff")
            else:
                b = pg_text(v)
                cells.append(struct.pack(">i", len(b)) + b)
        body = b"".join(cells)
        parts.append(b"D" + struct.pack(">i", len(body) + 4) + body)
    return b"".join(parts)
