"""Table schema: columns, key structure, ids.

Reference analog: src/yb/common/schema.h (Schema, ColumnSchema, ColumnId).
A schema is hash columns + range columns (together the primary key) +
regular value columns; key encoding order is hash cols then range cols
(models.encoding.encode_doc_key).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import encode_doc_key


class ColumnKind(enum.IntEnum):
    HASH = 0
    RANGE = 1
    REGULAR = 2
    STATIC = 3  # YCQL static columns (per-partition); stored as regular for now


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    dtype: DataType
    kind: ColumnKind = ColumnKind.REGULAR
    nullable: bool = True
    # Column ids are stable across ALTER TABLE (reference schema.h ColumnId);
    # assigned by Schema/catalog.
    col_id: int = -1
    # User-defined type name when this column is a (frozen) UDT; the
    # storage dtype is MAP, the declared type rides here for literal
    # validation + driver metadata (reference: QLType::udtype_field_names,
    # src/yb/yql/cql/ql/ptree/pt_create_type.cc).
    udt: str | None = None

    @property
    def is_key(self) -> bool:
        return self.kind in (ColumnKind.HASH, ColumnKind.RANGE)


class Schema:
    """Immutable table schema.

    Column order: hash columns, then range columns, then regular columns —
    the same normalized layout the reference keeps (schema.h: key columns
    first).
    """

    def __init__(self, columns: list[ColumnSchema], table_id: str = "",
                 version: int = 0, next_col_id: int | None = None):
        hash_cols = [c for c in columns if c.kind == ColumnKind.HASH]
        range_cols = [c for c in columns if c.kind == ColumnKind.RANGE]
        value_cols = [c for c in columns if not c.is_key]
        ordered = hash_cols + range_cols + value_cols
        # Assign stable column ids if unset (first schema version).
        self.columns: list[ColumnSchema] = []
        next_id = 10  # start above 0 to catch id/index confusion in tests
        used = {c.col_id for c in ordered if c.col_id >= 0}
        for c in ordered:
            if c.col_id < 0:
                while next_id in used:
                    next_id += 1
                c = ColumnSchema(c.name, c.dtype, c.kind, c.nullable,
                                 next_id, c.udt)
                used.add(next_id)
                next_id += 1
            self.columns.append(c)
        self.table_id = table_id
        self.version = version
        # Monotonic id allocator for ALTER TABLE ADD: never reuses a
        # DROPPED column's id (old row versions still carry it — a reused
        # id would resurrect their values under the new column).
        self.next_col_id = next_col_id if next_col_id is not None else \
            (max(used) + 1 if used else 10)
        self._by_name = {c.name: i for i, c in enumerate(self.columns)}
        if len(self._by_name) != len(self.columns):
            raise ValueError("duplicate column names")
        self.num_hash = len(hash_cols)
        self.num_range = len(range_cols)

    # -- structure ---------------------------------------------------------
    @property
    def hash_columns(self) -> list[ColumnSchema]:
        return self.columns[: self.num_hash]

    @property
    def range_columns(self) -> list[ColumnSchema]:
        return self.columns[self.num_hash: self.num_hash + self.num_range]

    @property
    def key_columns(self) -> list[ColumnSchema]:
        return self.columns[: self.num_hash + self.num_range]

    @property
    def value_columns(self) -> list[ColumnSchema]:
        return self.columns[self.num_hash + self.num_range:]

    def column_index(self, name: str) -> int:
        if name not in self._by_name:
            raise KeyError(f"no column {name!r}")
        return self._by_name[name]

    def column(self, name: str) -> ColumnSchema:
        return self.columns[self.column_index(name)]

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    # -- key encoding ------------------------------------------------------
    def encode_primary_key(self, key_values: dict, hash_code: int) -> bytes:
        """Encode the DocKey for a row given its key column values."""
        hashed = [(key_values[c.name], c.dtype) for c in self.hash_columns]
        ranges = [(key_values[c.name], c.dtype) for c in self.range_columns]
        return encode_doc_key(hash_code if self.num_hash else None, hashed, ranges)

    # -- evolution (ALTER TABLE; reference: schema evolution keyed by
    # stable ColumnIds + a schema version, catalog_manager AlterTable) ---
    def with_added_column(self, name: str, dtype: DataType) -> "Schema":
        if self.has_column(name):
            raise ValueError(f"column {name} already exists")
        new = ColumnSchema(name, dtype, ColumnKind.REGULAR, True,
                           self.next_col_id)
        return Schema(self.columns + [new], self.table_id,
                      self.version + 1, self.next_col_id + 1)

    def with_dropped_column(self, name: str) -> "Schema":
        col = self.column(name)
        if col.is_key:
            raise ValueError(f"cannot drop key column {name}")
        cols = [c for c in self.columns if c.name != name]
        return Schema(cols, self.table_id, self.version + 1,
                      self.next_col_id)

    def with_renamed_column(self, old: str, new: str) -> "Schema":
        if self.has_column(new):
            raise ValueError(f"column {new} already exists")
        col = self.column(old)  # raises if absent
        cols = [ColumnSchema(new, c.dtype, c.kind, c.nullable, c.col_id)
                if c.name == old else c for c in self.columns]
        return Schema(cols, self.table_id, self.version + 1,
                      self.next_col_id)

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{c.name}:{c.dtype.name}:{c.kind.name}" for c in self.columns)
        return f"Schema[{cols}]"

    def to_dict(self) -> dict:
        return {
            "table_id": self.table_id,
            "version": self.version,
            "next_col_id": self.next_col_id,
            "columns": [
                {"name": c.name, "dtype": int(c.dtype), "kind": int(c.kind),
                 "nullable": c.nullable, "col_id": c.col_id,
                 **({"udt": c.udt} if c.udt else {})}
                for c in self.columns
            ],
        }

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        cols = [
            ColumnSchema(c["name"], DataType(c["dtype"]), ColumnKind(c["kind"]),
                         c["nullable"], c["col_id"], c.get("udt"))
            for c in d["columns"]
        ]
        return Schema(cols, d.get("table_id", ""), d.get("version", 0),
                      d.get("next_col_id"))
