"""Hash partitioning of tables into tablets.

Reference analog: src/yb/common/partition.h — multi-column hash of the hash
key columns onto a uint16 space (kMaxPartitionKey = 65535, partition.h:156;
EncodeMultiColumnHashValue partition.h:204; HashColumnCompoundValue
partition.h:274), split evenly into N tablets at table-creation time
(CatalogManager::CreateTabletsFromTable, src/yb/master/catalog_manager.cc:2274).
The initial split is even; master-driven tablet splitting
(master/split_manager.py) can later divide a hot tablet at the median
resident key hash, so ranges need not stay uniform over time.

The hash function differs from the reference's Jenkins hash by design (we are
not wire-compatible with YB's on-disk layout); it only needs to be stable and
well-spread. We hash the *encoded* hash-column bytes with CRC32 folded to 16
bits.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

from yugabyte_db_tpu.models.encoding import encode_key_component
from yugabyte_db_tpu.models.schema import Schema

MAX_PARTITION_KEY = 0xFFFF  # 65535, uint16 hash space


def hash_column_compound_value(encoded_components: bytes) -> int:
    """Stable uint16 hash of the concatenated encoded hash-column values."""
    crc = zlib.crc32(encoded_components) & 0xFFFFFFFF
    return ((crc >> 16) ^ (crc & 0xFFFF)) & 0xFFFF


def compute_hash_code(schema: Schema, key_values: dict) -> int | None:
    """Partition hash code for a row (None for range-partitioned tables)."""
    if schema.num_hash == 0:
        return None
    buf = bytearray()
    for c in schema.hash_columns:
        buf += encode_key_component(key_values[c.name], c.dtype)
    return hash_column_compound_value(bytes(buf))


@dataclass(frozen=True)
class Partition:
    """One tablet's slice of the hash space: [start, end) over uint16+1.

    end == MAX_PARTITION_KEY + 1 means "to the top". Range-partitioned
    tables use a single full-range partition in v1.
    """

    start: int
    end: int

    def contains(self, hash_code: int) -> bool:
        return self.start <= hash_code < self.end

    @property
    def key_start(self) -> bytes:
        return struct.pack(">H", self.start)

    def __repr__(self) -> str:
        return f"Partition[{self.start:#06x},{self.end:#06x})"


class PartitionSchema:
    """Splits the uint16 hash space evenly into num_tablets partitions.

    Reference analog: PartitionSchema::CreatePartitions (partition.cc) — the
    same even split of [0, 65536).
    """

    def __init__(self, num_tablets: int, hash_partitioned: bool = True):
        if num_tablets < 1:
            raise ValueError("need at least one tablet")
        self.hash_partitioned = hash_partitioned
        if not hash_partitioned:
            num_tablets = 1
        self.num_tablets = num_tablets
        if not hash_partitioned:
            self._partitions = [Partition(0, MAX_PARTITION_KEY + 1)]
        else:
            space = MAX_PARTITION_KEY + 1
            bounds = [round(i * space / num_tablets) for i in range(num_tablets + 1)]
            self._partitions = [Partition(bounds[i], bounds[i + 1])
                                for i in range(num_tablets)]

    def create_partitions(self) -> list[Partition]:
        return list(self._partitions)

    def partition_index_for_hash(self, hash_code: int) -> int:
        space = MAX_PARTITION_KEY + 1
        # Even split: invert the rounding used by the constructor.
        idx = min(self.num_tablets - 1, hash_code * self.num_tablets // space)
        # Guard against rounding edges.
        parts = self._partitions
        while idx > 0 and hash_code < parts[idx].start:
            idx -= 1
        while idx < self.num_tablets - 1 and hash_code >= parts[idx].end:
            idx += 1
        return idx

    def to_dict(self) -> dict:
        return {"num_tablets": self.num_tablets,
                "hash_partitioned": self.hash_partitioned}

    @staticmethod
    def from_dict(d: dict) -> "PartitionSchema":
        return PartitionSchema(d["num_tablets"], d.get("hash_partitioned", True))
