"""Roles, permissions, and password auth — the authorization state
machine shared by every frontend.

Reference analogs: the master's CreateRole / GrantRevokeRole /
GrantRevokePermission RPCs (src/yb/master/master.proto:1383-1388), the
role/permission records of the auth vtables
(src/yb/master/yql_auth_roles_vtable.cc, yql_auth_role_permissions_vtable.cc),
and CQL enforcement in the analyzer/executor. The store is a
deterministic state machine over small dict ops, so the master
replicates role DDL through the same Raft'd catalog pipeline as table
DDL, and an in-process cluster applies the ops directly.

Resources are hierarchical, Cassandra-style:
  data               all keyspaces
  data/<ks>          one keyspace
  data/<ks>/<table>  one table
  roles              all roles
  roles/<role>       one role
A permission granted on an ancestor applies to every descendant.
Passwords are stored as salted SHA-256 ("<salt>$<hexdigest>"); the hash
is computed BEFORE the op enters replication so replicas apply
byte-identical state.
"""

from __future__ import annotations

import hashlib
import os
import threading

from yugabyte_db_tpu.utils.status import InvalidArgument, NotFound

PERMISSIONS = ("ALTER", "AUTHORIZE", "CREATE", "DESCRIBE", "DROP",
               "MODIFY", "SELECT")


def hash_password(password: str, salt: str | None = None) -> str:
    salt = salt if salt is not None else os.urandom(8).hex()
    digest = hashlib.sha256((salt + password).encode()).hexdigest()
    return f"{salt}${digest}"


def verify_password(password: str, salted_hash: str) -> bool:
    if not salted_hash or "$" not in salted_hash:
        return False
    salt, _d = salted_hash.split("$", 1)
    return hash_password(password, salt) == salted_hash


class Role:
    __slots__ = ("name", "can_login", "superuser", "salted_hash",
                 "member_of")

    def __init__(self, name, can_login=False, superuser=False,
                 salted_hash="", member_of=None):
        self.name = name
        self.can_login = can_login
        self.superuser = superuser
        self.salted_hash = salted_hash
        self.member_of = set(member_of or ())

    def to_dict(self) -> dict:
        return {"name": self.name, "can_login": self.can_login,
                "superuser": self.superuser,
                "salted_hash": self.salted_hash,
                "member_of": sorted(self.member_of)}


class RoleStore:
    """Deterministic role/permission state machine."""

    def __init__(self):
        self._lock = threading.RLock()
        self.roles: dict[str, Role] = {}
        # (role, resource) -> set of permission names
        self.perms: dict[tuple[str, str], set[str]] = {}

    # -- the op interface (replicated verbatim) -----------------------------
    def apply(self, op: dict) -> None:
        kind = op["op"]
        with self._lock:
            if kind == "auth_create_role":
                name = op["name"]
                if name in self.roles:
                    from yugabyte_db_tpu.utils.status import AlreadyPresent

                    raise AlreadyPresent(f"role {name} already exists")
                self.roles[name] = Role(
                    name, op.get("can_login", False),
                    op.get("superuser", False),
                    op.get("salted_hash", ""))
            elif kind == "auth_alter_role":
                r = self._role(op["name"])
                if "can_login" in op:
                    r.can_login = op["can_login"]
                if "superuser" in op:
                    r.superuser = op["superuser"]
                if "salted_hash" in op:
                    r.salted_hash = op["salted_hash"]
            elif kind == "auth_drop_role":
                if self.roles.pop(op["name"], None) is None:
                    raise NotFound(f"role {op['name']} does not exist")
                for r in self.roles.values():
                    r.member_of.discard(op["name"])
                for key in [k for k in self.perms if k[0] == op["name"]]:
                    del self.perms[key]
            elif kind == "auth_grant_role":
                member = self._role(op["member"])
                self._role(op["role"])
                if self._reachable(op["member"], op["role"], reverse=True):
                    raise InvalidArgument(
                        f"{op['role']} is already a member of "
                        f"{op['member']} (circular grant)")
                member.member_of.add(op["role"])
            elif kind == "auth_revoke_role":
                self._role(op["member"]).member_of.discard(op["role"])
            elif kind == "auth_grant_perm":
                self._role(op["role"])
                perms = self.perms.setdefault(
                    (op["role"], op["resource"]), set())
                perms.update(self._perm_list(op["perm"]))
            elif kind == "auth_revoke_perm":
                key = (op["role"], op["resource"])
                have = self.perms.get(key)
                if have:
                    have.difference_update(self._perm_list(op["perm"]))
                    if not have:
                        del self.perms[key]
            else:
                raise ValueError(f"unknown auth op {kind!r}")

    @staticmethod
    def _perm_list(perm: str) -> tuple:
        if perm == "ALL":
            return PERMISSIONS
        if perm not in PERMISSIONS:
            raise InvalidArgument(f"unknown permission {perm}")
        return (perm,)

    def _role(self, name: str) -> Role:
        r = self.roles.get(name)
        if r is None:
            raise NotFound(f"role {name} does not exist")
        return r

    def _reachable(self, src: str, dst: str, reverse: bool = False) -> bool:
        """Is dst reachable from src over member_of edges? (cycle guard:
        with reverse=True asks whether src is already granted to dst)."""
        a, b = (dst, src) if reverse else (src, dst)
        seen, stack = set(), [a]
        while stack:
            cur = stack.pop()
            if cur == b:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            r = self.roles.get(cur)
            if r is not None:
                stack.extend(r.member_of)
        return False

    # -- queries ------------------------------------------------------------
    def effective_roles(self, name: str) -> set[str]:
        with self._lock:
            out: set[str] = set()
            stack = [name]
            while stack:
                cur = stack.pop()
                if cur in out or cur not in self.roles:
                    continue
                out.add(cur)
                stack.extend(self.roles[cur].member_of)
            return out

    @staticmethod
    def resource_chain(resource: str) -> list[str]:
        """A resource and its ancestors, root first."""
        parts = resource.split("/")
        return ["/".join(parts[:i + 1]) for i in range(len(parts))]

    def authorize(self, role_name: str, perm: str, resource: str) -> bool:
        with self._lock:
            r = self.roles.get(role_name)
            if r is None:
                return False
            eff = self.effective_roles(role_name)
            if any(self.roles[n].superuser for n in eff
                   if n in self.roles):
                return True
            chain = self.resource_chain(resource)
            for n in eff:
                for res in chain:
                    if perm in self.perms.get((n, res), ()):
                        return True
            return False

    def check_login(self, name: str, password: str) -> bool:
        with self._lock:
            r = self.roles.get(name)
            return (r is not None and r.can_login
                    and verify_password(password, r.salted_hash))

    def list_roles(self) -> list[Role]:
        with self._lock:
            return sorted(self.roles.values(), key=lambda r: r.name)

    def list_perms(self) -> list[tuple[str, str, str]]:
        """(role, resource, permission) triples, sorted."""
        with self._lock:
            return sorted((role, res, p)
                          for (role, res), ps in self.perms.items()
                          for p in ps)

    # -- serialization (client mirror fetch) --------------------------------
    def to_dict(self) -> dict:
        with self._lock:
            return {
                "roles": [r.to_dict() for r in self.roles.values()],
                "perms": [[role, res, sorted(ps)]
                          for (role, res), ps in self.perms.items()],
            }

    @classmethod
    def from_dict(cls, d: dict) -> "RoleStore":
        st = cls()
        for rd in d.get("roles", ()):
            st.roles[rd["name"]] = Role(
                rd["name"], rd["can_login"], rd["superuser"],
                rd["salted_hash"], rd["member_of"])
        for role, res, ps in d.get("perms", ()):
            st.perms[(role, res)] = set(ps)
        return st
