"""Native (C++) runtime components, with build-on-first-import.

The reference's runtime serialization/framing is C++ (protobuf +
src/yb/rpc); here the hot paths live in native/*.cc, compiled into
extension modules next to this package:

- ``yb_codec`` (native/codec.cc) — the tagged binary codec framing every
  RPC payload and WAL record.
- ``yb_wp``   (native/writeplane.cc) — the write plane: row-block batch
  encoding (doc keys + partition hash + per-tablet split), leader-side
  hybrid-time stamping, and the C++ memtable.
- ``yb_rb``   (native/servebatch.cc) — the request-batch serving path:
  RESP batch parsing and redis doc-key encoding for whole pipelined
  windows (docs/serving-path.md).

If an extension is missing, we try ONE quiet `make -C native` (the
toolchain is a build requirement, not a runtime one — pure-Python
fallbacks exist for every native component), gated by YB_NO_NATIVE=1.
"""

from __future__ import annotations

import importlib
import os
import subprocess
import sys

_MODS = ("yb_codec", "yb_wp", "yb_rb")


def _import_each():
    """Best-effort per-module import: one extension failing to build or
    import must not disable the others (each has its own pure-Python
    fallback)."""
    mods = {}
    for name in _MODS:
        try:
            mods[name] = importlib.import_module(f"{__name__}.{name}")
        except ImportError:
            mods[name] = None
    return mods


def _load():
    if os.environ.get("YB_NO_NATIVE") == "1":
        return {name: None for name in _MODS}
    mods = _import_each()
    if all(m is not None for m in mods.values()):
        return mods
    src = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native")
    if not os.path.isdir(src):
        return mods
    # Negative cache: one failed build attempt per source version, not one
    # per process (a doomed `make` at import time would tax every CLI run).
    stamp = os.path.join(src, ".build_failed")
    sources = [os.path.join(src, n)
               for n in ("codec.cc", "writeplane.cc", "servebatch.cc",
                         "tagcodec.h", "keycodec.h")]
    try:
        if os.path.exists(stamp) and all(
                os.path.getmtime(stamp) >= os.path.getmtime(s)
                for s in sources if os.path.exists(s)):
            return mods
    except OSError:
        return mods
    try:
        # -k: build every target it can — a partial toolchain failure
        # still yields the extensions that do compile.
        proc = subprocess.run(["make", "-C", src, "-k",
                               f"PY={sys.executable}"],
                              capture_output=True, timeout=120)
        mods = _import_each()
        if proc.returncode != 0:
            raise RuntimeError("partial native build")
        return mods
    except Exception:  # noqa: BLE001 — fall back to pure Python
        try:
            with open(stamp, "w") as f:
                f.write("native build failed; delete to retry\n")
        except OSError:
            pass
        return mods


_loaded = _load()
yb_codec = _loaded.get("yb_codec")
yb_wp = _loaded.get("yb_wp")
yb_rb = _loaded.get("yb_rb")
