"""Native (C++) runtime components, with build-on-first-import.

The reference's runtime serialization/framing is C++ (protobuf +
src/yb/rpc); here the codec hot path lives in native/codec.cc, compiled
into the extension module ``yb_codec`` next to this package. If the
extension is missing, we try ONE quiet `make -C native` (the toolchain
is a build requirement, not a runtime one — pure-Python fallbacks exist
for every native component), gated by YB_NO_NATIVE=1.
"""

from __future__ import annotations

import importlib
import os
import subprocess
import sys

_MOD = "yugabyte_db_tpu.native.yb_codec"


def _load():
    if os.environ.get("YB_NO_NATIVE") == "1":
        return None
    try:
        return importlib.import_module(_MOD)
    except ImportError:
        pass
    src = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "native")
    if not os.path.isdir(src):
        return None
    # Negative cache: one failed build attempt per source version, not one
    # per process (a doomed `make` at import time would tax every CLI run).
    stamp = os.path.join(src, ".build_failed")
    codec_src = os.path.join(src, "codec.cc")
    try:
        if os.path.exists(stamp) and \
                os.path.getmtime(stamp) >= os.path.getmtime(codec_src):
            return None
    except OSError:
        return None
    try:
        subprocess.run(["make", "-C", src, f"PY={sys.executable}"],
                       capture_output=True, timeout=120, check=True)
        return importlib.import_module(_MOD)
    except Exception:  # noqa: BLE001 — fall back to pure Python
        try:
            with open(stamp, "w") as f:
                f.write("native build failed; delete to retry\n")
        except OSError:
            pass
        return None


yb_codec = _load()
