"""Message transport between consensus peers / cluster nodes.

Reference analog: the rpc layer's Messenger/Proxy pair (src/yb/rpc/) as seen
by consensus — ``Peer`` sends UpdateConsensus/RequestConsensusVote through a
``ConsensusServiceProxy``. Here the seam is one method:
``send(dst, method, payload) -> response`` with node-level handlers.

``LocalTransport`` is the in-process fabric used by MiniCluster-style tests
(reference: mini_cluster.h runs real servers on loopback; we go one step
lighter and skip sockets). It supports fault injection — partitions, drops,
latency — the ExternalMiniCluster role of forcing failure paths. The socket
transport lives in yugabyte_db_tpu.rpc and plugs in behind the same seam.
"""

from __future__ import annotations

import random
import threading
import time

# The seam itself lives in the rpc layer; re-exported here because
# consensus is where most callers historically imported it from.
from yugabyte_db_tpu.rpc.interface import Transport, TransportError
from yugabyte_db_tpu.utils.retry import Deadline, RetryPolicy

__all__ = ["Transport", "TransportError", "LocalTransport",
           "BoundTransport", "send_with_retry"]

# Default policy for one-off sends through the seam: a short budget with
# jittered backoff (server-to-server fire-and-forget helpers; latency-
# sensitive loops construct their own).
_SEND_POLICY = RetryPolicy(timeout_s=5.0, initial_backoff_s=0.05,
                           max_backoff_s=0.5)


def send_with_retry(transport: Transport, dst: str, method: str,
                    payload: dict, *, policy: RetryPolicy | None = None,
                    deadline: Deadline | None = None,
                    timeout_s: float | None = None,
                    attempt_cap: float = 2.0) -> dict:
    """``transport.send`` under a RetryPolicy: transient transport
    failures and retriable wire codes back off and retry until the one
    deadline budget runs out; terminal responses return immediately.
    Raises TransportError when the policy gives up."""
    policy = policy or _SEND_POLICY
    last: object = None
    for attempt in policy.attempts(deadline=deadline, timeout_s=timeout_s):
        try:
            resp = transport.send(dst, method, payload,
                                  timeout=attempt.timeout(attempt_cap))
        except (TransportError, TimeoutError, ConnectionError) as e:
            last = e
            attempt.note(e)
            continue
        if not policy.retriable(resp):
            return resp
        last = resp
        attempt.note(resp)
    raise TransportError(
        f"{dst} unreachable before deadline ({method}): {last}")


class LocalTransport(Transport):
    """In-process transport with fault injection for tests."""

    def __init__(self, seed: int = 0):
        self._handlers: dict[str, object] = {}
        self._lock = threading.Lock()
        self._partitioned: set[frozenset] = set()
        self._isolated: set[str] = set()
        self.drop_rate = 0.0
        self.delay_s = 0.0
        self._rng = random.Random(seed)

    # -- wiring ------------------------------------------------------------
    def register(self, uuid: str, handler) -> None:
        with self._lock:
            self._handlers[uuid] = handler

    def unregister(self, uuid: str) -> None:
        with self._lock:
            self._handlers.pop(uuid, None)

    # -- fault injection ---------------------------------------------------
    def partition(self, a: str, b: str) -> None:
        """Block traffic between a and b (both directions)."""
        with self._lock:
            self._partitioned.add(frozenset((a, b)))

    def heal(self, a: str | None = None, b: str | None = None) -> None:
        with self._lock:
            if a is None:
                self._partitioned.clear()
                self._isolated.clear()
            elif b is None:
                self._isolated.discard(a)
                self._partitioned = {p for p in self._partitioned if a not in p}
            else:
                self._partitioned.discard(frozenset((a, b)))

    def isolate(self, uuid: str) -> None:
        """Cut a node off from everyone (network-level "kill")."""
        with self._lock:
            self._isolated.add(uuid)

    # -- delivery ----------------------------------------------------------
    def send(self, dst: str, method: str, payload: dict,
             timeout: float = 5.0, src: str | None = None) -> dict:
        from yugabyte_db_tpu.utils.resources import note_blocking

        note_blocking("rpc")
        with self._lock:
            handler = self._handlers.get(dst)
            blocked = (dst in self._isolated
                       or (src is not None
                           and (src in self._isolated
                                or frozenset((src, dst)) in self._partitioned)))
            drop = self.drop_rate and self._rng.random() < self.drop_rate
            delay = self.delay_s
        if delay:
            time.sleep(delay)
        if handler is None or blocked or drop:
            raise TransportError(f"{dst} unreachable ({method})")
        return handler(method, payload)

    def bind(self, src: str) -> "BoundTransport":
        """A view that stamps the sender uuid (so partitions apply)."""
        return BoundTransport(self, src)


class BoundTransport(Transport):
    def __init__(self, inner: LocalTransport, src: str):
        self._inner = inner
        self.src = src

    def send(self, dst: str, method: str, payload: dict, timeout: float = 5.0) -> dict:
        return self._inner.send(dst, method, payload, timeout, src=self.src)

    def register(self, uuid: str, handler) -> None:
        self._inner.register(uuid, handler)

    def unregister(self, uuid: str) -> None:
        self._inner.unregister(uuid)
