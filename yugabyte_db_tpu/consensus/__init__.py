"""Per-tablet Raft consensus.

Reference analog: src/yb/consensus/ — RaftConsensus (raft_consensus.cc),
the peer replication queue (consensus_queue.cc, consensus_peers.cc), leader
election (leader_election.cc), leader leases (leader_lease.h), and the
consensus metadata file (consensus_meta.cc). The WAL (tablet.wal.Log) is the
Raft log — "this replicated consistent log also plays the role of the WAL"
(consensus/README).
"""

from yugabyte_db_tpu.consensus.metadata import ConsensusMetadata, RaftConfig
from yugabyte_db_tpu.consensus.raft import (NotLeader, RaftConsensus,
                                            RaftOptions, Role)
from yugabyte_db_tpu.consensus.transport import (LocalTransport, Transport,
                                                 TransportError)

__all__ = [
    "ConsensusMetadata", "RaftConfig", "RaftConsensus", "RaftOptions",
    "Role", "NotLeader", "LocalTransport", "Transport", "TransportError",
]
