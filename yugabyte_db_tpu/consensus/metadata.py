"""Durable consensus metadata: current term, vote, and the Raft config.

Reference analog: src/yb/consensus/consensus_meta.{h,cc} — the cmeta file a
peer must persist *before* responding to a vote request, and
src/yb/consensus/metadata.proto (RaftConfigPB / RaftPeerPB).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class RaftConfig:
    """The replica set: voter uuids, versioned by the log index that
    committed it (reference: RaftConfigPB.opid_index)."""

    peers: list[str] = field(default_factory=list)
    opid_index: int = 0

    def majority_size(self) -> int:
        return len(self.peers) // 2 + 1

    def has_peer(self, uuid: str) -> bool:
        return uuid in self.peers

    def to_dict(self) -> dict:
        return {"peers": list(self.peers), "opid_index": self.opid_index}

    @staticmethod
    def from_dict(d: dict) -> "RaftConfig":
        return RaftConfig(list(d["peers"]), d.get("opid_index", 0))


class ConsensusMetadata:
    """Durable (term, voted_for, config); fsynced before any vote/term bump
    takes effect, the Raft persistence requirement."""

    def __init__(self, path: str, peer_uuid: str,
                 config: RaftConfig | None = None):
        self.path = path
        self.peer_uuid = peer_uuid
        self.current_term = 0
        self.voted_for: str | None = None
        self.committed_config = config or RaftConfig()
        # A pending (replicated-but-uncommitted) config, active immediately
        # per Raft config-change rules.
        self.pending_config: RaftConfig | None = None
        if os.path.exists(path):
            self._load()
        else:
            self.flush()

    @property
    def active_config(self) -> RaftConfig:
        return self.pending_config or self.committed_config

    def set_term(self, term: int, voted_for: str | None = None) -> None:
        assert term >= self.current_term, (term, self.current_term)
        if term > self.current_term:
            self.current_term = term
            self.voted_for = voted_for
        elif voted_for is not None:
            self.voted_for = voted_for
        self.flush()

    def flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "peer_uuid": self.peer_uuid,
                "current_term": self.current_term,
                "voted_for": self.voted_for,
                "committed_config": self.committed_config.to_dict(),
                "pending_config":
                    self.pending_config.to_dict() if self.pending_config else None,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _load(self) -> None:
        with open(self.path) as f:
            d = json.load(f)
        self.peer_uuid = d["peer_uuid"]
        self.current_term = d["current_term"]
        self.voted_for = d["voted_for"]
        self.committed_config = RaftConfig.from_dict(d["committed_config"])
        pc = d.get("pending_config")
        self.pending_config = RaftConfig.from_dict(pc) if pc else None
