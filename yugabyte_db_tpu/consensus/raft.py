"""RaftConsensus: per-tablet leader election + log replication.

Reference analog: src/yb/consensus/raft_consensus.cc (the role state
machine + vote handling), consensus_queue.cc (PeerMessageQueue — tracking
per-peer next/match indexes and advancing the majority-replicated
watermark), consensus_peers.cc (per-peer replication), leader_election.cc,
and leader_lease.h (leader leases so reads never need a quorum round-trip).

Structure: one lock per instance; three kinds of background threads —
a timer (election timeouts + heartbeat pacing), one replication thread per
remote peer (the reference's Peer + its thread-pool tokens), and an apply
thread that invokes ``apply_cb(entry)`` strictly in log order once entries
commit (the reference's OperationDriver::ApplyTask stage). The WAL is the
Raft log: every entry is fsynced before it counts toward majority.

Simplifications vs the reference, called out honestly:
- Leader leases are MESSAGE-BORNE (leader_lease.h): every AppendEntries
  carries a lease duration; the follower promises (vote withholding until
  a monotonic deadline) and echoes the grant in its ack; the leader holds
  the lease while a majority's grants — measured from each request's SEND
  time — are still running. All lease arithmetic is monotonic-clock
  durations, so wall-clock jumps cannot extend or break a lease.
- The in-memory entry cache (LogCache analog) is bounded by the engine's
  flushed frontier: every flush evicts entries below it (evict_cache,
  keeping two anchor entries for peer consistency probes), and a peer
  lagging past the eviction floor is re-seeded via remote bootstrap
  instead of log catchup — the same handoff consensus_queue.cc makes.
  Unlike the reference's LogCache there is no disk read-back path for
  peer catchup (log_cache.cc falls back to LogReader); the cache floor
  therefore never exceeds the flushed frontier.
"""

from __future__ import annotations

import enum
import logging
import random
import threading
import time
from dataclasses import dataclass

from yugabyte_db_tpu.consensus.metadata import ConsensusMetadata, RaftConfig
from yugabyte_db_tpu.consensus.transport import Transport, TransportError
from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.hybrid_time import HybridTime
from yugabyte_db_tpu.utils.locking import guarded_by
from yugabyte_db_tpu.utils.metrics import (count_fault_fired, count_swallowed,
                                           observe_group_commit_batch)
from yugabyte_db_tpu.utils.retry import Deadline


def _as_deadline(timeout) -> Deadline:
    """Normalize a float-seconds timeout or a Deadline to a Deadline —
    the PR-7 propagation convention: callers that already carry a
    deadline pass it through so every wait debits ONE budget instead of
    restarting a fresh 10 s at each layer."""
    if isinstance(timeout, Deadline):
        return timeout
    return Deadline.after(float(timeout))


class Role(enum.Enum):
    FOLLOWER = "FOLLOWER"
    CANDIDATE = "CANDIDATE"
    LEADER = "LEADER"


class NotLeader(Exception):
    """Raised on writes/reads addressed to a non-leader replica; carries the
    best-known leader hint (reference: TabletServerErrorPB::NOT_THE_LEADER)."""

    def __init__(self, uuid: str, leader_hint: str | None):
        super().__init__(f"{uuid} is not the leader (leader={leader_hint})")
        self.leader_hint = leader_hint


@dataclass
class RaftOptions:
    election_timeout_s: float = 0.5     # base; actual is jittered 1-2x
    heartbeat_interval_s: float = 0.1
    lease_s: float = 0.5                # leader lease window
    max_batch_entries: int = 64         # per UpdateConsensus request
    rpc_timeout_s: float = 2.0

    @property
    def effective_lease_s(self) -> float:
        """The lease window a leader may trust. Clamped to the MINIMUM
        election delay: followers withhold votes only for
        election_timeout_s after the last heartbeat, so a lease longer
        than that could outlive a successor's election and serve stale
        reads. The 0.8 factor keeps a margin from the exact boundary."""
        return min(self.lease_s, 0.8 * self.election_timeout_s)


class _PeerState:
    """Leader-side view of one remote peer (consensus_queue.cc tracking)."""

    def __init__(self, uuid: str, next_index: int):
        self.uuid = uuid
        self.next_index = next_index
        self.match_index = 0
        self.last_ack_monotonic = 0.0
        # monotonic deadline of the lease this peer GRANTED (ack of a
        # message carrying lease_s): the peer promised not to vote for
        # anyone else before it (leader_lease.h message-borne leases)
        self.lease_until = 0.0
        self.needs_remote_bootstrap = False
        self.signal = threading.Event()
        self.thread: threading.Thread | None = None


@guarded_by("_lock", "_gc_handled_index", "_gc_last_dispatch")
class RaftConsensus:
    def __init__(self, tablet_id: str, cmeta: ConsensusMetadata, log: Log,
                 transport: Transport, clock, apply_cb,
                 opts: RaftOptions | None = None,
                 initial_applied_index: int = 0,
                 preloaded_entries: list[LogEntry] | None = None):
        self.tablet_id = tablet_id
        self.cmeta = cmeta
        self.uuid = cmeta.peer_uuid
        self.log = log
        self.transport = transport
        self.clock = clock
        self.apply_cb = apply_cb
        self.opts = opts or RaftOptions()

        self._lock = threading.RLock()
        self._apply_cond = threading.Condition(self._lock)
        self._stall_watch = None  # open watchdog scope on an apply hole
        self._commit_cond = threading.Condition(self._lock)
        self._role = Role.FOLLOWER
        self._leader_uuid: str | None = None
        self._rng = random.Random(hash((self.uuid, tablet_id)) & 0xFFFF)
        self._election_timeout = self._next_timeout()
        self._last_heartbeat_recv = time.monotonic()
        # monotonic deadline of the vote-withholding promise made to the
        # current leader (message-borne lease grants)
        self._vote_withhold_until = 0.0
        self._last_broadcast = 0.0
        self._leader_since = 0.0  # when this node last won an election
        self._own_term_noop = (0, 0)  # (term, index) of our election no_op
        self._running = False

        # Log state: full in-memory entry cache (LogCache analog).
        self._entries: dict[int, LogEntry] = {}
        self._sync_lock = threading.Lock()  # serializes fsyncs (group commit)
        self._last_index = 0
        self._commit_index = 0
        self._applied_index = initial_applied_index
        entries = (preloaded_entries if preloaded_entries is not None
                   else self.log.read_all(0))
        for e in entries:
            self._entries[e.op_id.index] = e
            self._last_index = max(self._last_index, e.op_id.index)
            self._commit_index = max(self._commit_index, e.committed)
            if e.op_type == "change_config":
                cfg = RaftConfig.from_dict(e.body)
                cfg.opid_index = e.op_id.index
                if e.op_id.index <= self._commit_index:
                    if cfg.opid_index > self.cmeta.committed_config.opid_index:
                        self.cmeta.committed_config = cfg
                        self.cmeta.pending_config = None
                else:
                    self.cmeta.pending_config = cfg
        self._commit_index = min(self._commit_index, self._last_index)
        self._applied_index = min(self._applied_index, self._last_index)
        self._durable_index = self._last_index  # on-disk log is durable

        self._peers: dict[str, _PeerState] = {}
        self._applying = False  # single-applier guard (inline + thread)
        # Cross-request group commit (the reference's Log::AsyncAppend
        # batching across independent requests): leader appends park in
        # the log buffer and set _gc_event; the pipeline thread wakes,
        # waits out --raft_group_commit_window_us, then issues ONE peer
        # signal (one AppendEntries round per peer) and ONE WAL sync for
        # everything admitted in the window. _gc_handled_index is the
        # high-water mark of entries already handed to a window.
        self._gc_event = threading.Event()
        self._gc_handled_index = self._last_index
        self._gc_last_dispatch = 0.0  # monotonic time of the last round
        self._threads: list[threading.Thread] = []
        # Invoked (tablet_id, peer_uuid) when a peer needs entries evicted
        # from the cache — wired by the tserver to kick remote bootstrap.
        self.on_needs_bootstrap = None
        # Invoked (entries) when a log suffix is truncated (definite
        # aborts) — wired by the TabletPeer to resolve MVCC pendings.
        self.on_entries_truncated = None

    # ------------------------------------------------------------------ api
    def start(self) -> None:
        with self._lock:
            self._running = True
        t = threading.Thread(target=self._run_timer,
                             name=f"raft-timer-{self.uuid}", daemon=True)
        a = threading.Thread(target=self._run_apply,
                             name=f"raft-apply-{self.uuid}", daemon=True)
        g = threading.Thread(target=self._run_group_commit,
                             name=f"raft-gc-{self.uuid}", daemon=True)
        self._threads += [t, a, g]
        t.start()
        a.start()
        g.start()

    def shutdown(self) -> None:
        with self._lock:
            self._running = False
            self._role = Role.FOLLOWER
            peers = list(self._peers.values())
            self._peers.clear()
            self._apply_cond.notify_all()
            self._commit_cond.notify_all()
        self._gc_event.set()
        for p in peers:
            p.signal.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self.log.sync()

    # -- role/introspection -------------------------------------------------
    @property
    def role(self) -> Role:
        return self._role

    def is_leader(self) -> bool:
        return self._role == Role.LEADER

    def leader_ready(self) -> bool:
        """True once this leader has APPLIED an entry of its own term (the
        election no_op). Before that, the local commit/applied watermarks
        may lag the true cluster commit — destructive control-plane
        decisions (orphan-replica GC) must wait for this gate (reference:
        CatalogManager's leader-ready / sys-catalog-loaded check)."""
        with self._lock:
            if self._role != Role.LEADER:
                return False
            term, idx = self._own_term_noop
            return (term == self.cmeta.current_term and idx > 0 and
                    self._applied_index >= idx)

    def has_lease(self) -> bool:
        """Majority-ack leader lease: safe to serve reads locally."""
        with self._lock:
            if self._role != Role.LEADER:
                return False
            now = time.monotonic()
            cfg = self.cmeta.active_config
            # A fresh leader first waits out any predecessor's lease window
            # (the reference's "old leader lease expiry" wait) — except the
            # trivial single-member group, which has no predecessor reads.
            if len(cfg.peers) > 1 and \
                    now < self._leader_since + self.opts.effective_lease_s:
                return False
            acked = 0
            for uuid in cfg.peers:
                if uuid == self.uuid:
                    acked += 1  # self counts only while still a member
                    continue
                p = self._peers.get(uuid)
                if p is not None and p.lease_until > now:
                    acked += 1  # explicit grant still running
            return acked >= cfg.majority_size()

    def leader_uuid(self) -> str | None:
        return self._leader_uuid

    def stats(self) -> dict:
        with self._lock:
            return {
                "uuid": self.uuid,
                "role": self._role.value,
                "term": self.cmeta.current_term,
                "leader": self._leader_uuid,
                "last_index": self._last_index,
                "commit_index": self._commit_index,
                "applied_index": self._applied_index,
                "config": self.cmeta.active_config.to_dict(),
            }

    # -- write path ----------------------------------------------------------
    def replicate(self, op_type: str, body, ht: int | None = None,
                  timeout: float | Deadline = 10.0) -> LogEntry:
        """Leader-only: append, replicate to a majority, apply; returns the
        committed entry (with its assigned op id + hybrid time)."""
        deadline = _as_deadline(timeout)
        entry = self.append_leader(op_type, body, ht, deadline=deadline)
        self.wait_applied(entry.op_id, deadline)
        return entry

    def append_leader(self, op_type: str, body, ht: int | None = None,
                      decoded_rows=None, on_append=None,
                      deadline: Deadline | None = None) -> LogEntry:
        """Leader append + durability, without waiting for commit. Callers
        that need the outcome follow with wait_committed()/wait_applied().
        ``decoded_rows`` rides on the in-memory entry so the leader's own
        apply skips re-decoding the body (followers decode from wire).

        Multi-peer groups DEFER the leader's own fsync off the admission
        path: the entry only counts toward the majority once synced, but
        two follower disks already form a majority (standard Raft — a
        leader may lose its unsynced tail), and the group-commit pipeline
        plus each replication thread sync the log off the admission path
        (amortized group commit), so a majority that needs the leader's
        disk (one follower down) is never more than one replication round
        away. Single-peer groups sync inline — there is nobody else to
        carry durability."""
        with self._lock:
            self._wait_inflight_room_locked(deadline)
            entry = self._leader_append_locked(op_type, body, ht,
                                               decoded_rows)
            if on_append is not None:
                # Runs under the raft lock: applies/truncations of this
                # entry are ordered strictly after it, so per-entry
                # bookkeeping (the peer's MVCC-resolution registry) can
                # never miss its own entry.
                on_append(entry)
            defer = len(self.cmeta.active_config.peers) > 1
        if not defer:
            self._ensure_durable(entry.op_id.index)
        return entry

    def _wait_inflight_room_locked(self, deadline: Deadline | None) -> None:
        """Backpressure: block admission while the append->apply window
        is full (--raft_max_inflight_ops). Bounds the commit-ack apply
        queue — a stalled apply stage pushes back on writers instead of
        buffering unboundedly."""
        try:
            limit = int(FLAGS.get("raft_max_inflight_ops"))
        except KeyError:
            limit = 0
        if limit <= 0 or self._last_index - self._applied_index < limit:
            return
        dl = deadline if deadline is not None else Deadline.after(5.0)
        while self._last_index - self._applied_index >= limit:
            if self._role != Role.LEADER:
                raise NotLeader(self.uuid, self._leader_uuid)
            if not self._running or dl.expired():
                raise TimeoutError(
                    f"write backpressure: {self._last_index - self._applied_index} "
                    f"ops in flight (limit {limit})")
            self._commit_cond.wait(timeout=dl.timeout(0.05))

    def _leader_append_locked(self, op_type: str, body, ht: int | None,
                              decoded_rows=None) -> LogEntry:
        if self._role != Role.LEADER:
            raise NotLeader(self.uuid, self._leader_uuid)
        if ht is None:
            ht = self.clock.now().value
        entry = LogEntry(OpId(self.cmeta.current_term, self._last_index + 1),
                         ht, op_type, body, self._commit_index)
        if decoded_rows is not None:
            entry.decoded_rows = decoded_rows
        # No fsync under the lock: durability is established by
        # _ensure_durable OUTSIDE it, and the entry only counts toward the
        # majority (self's match = _durable_index) once synced. Concurrent
        # appends share one fsync — the WAL's group-commit design.
        self._append_local_locked(entry, sync=False)
        window_s = self._gc_window_s()
        if window_s > 0:
            now = time.monotonic()
            if now - self._gc_last_dispatch >= window_s:
                # Pipeline idle: dispatch this append's round inline —
                # the same latency as the no-window path (no thread
                # handoff for a lone writer).
                batch = self._last_index - self._gc_handled_index
                self._gc_handled_index = self._last_index
                self._gc_last_dispatch = now
                self._signal_peers_locked()
                observe_group_commit_batch(batch)
            else:
                # A round just went out: park the append; the pipeline
                # thread coalesces everything admitted within the window
                # into one WAL sync + one AppendEntries round per peer.
                self._gc_event.set()
        else:
            self._signal_peers_locked()
        return entry

    @staticmethod
    def _gc_window_s() -> float:
        try:
            return FLAGS.get("raft_group_commit_window_us") / 1e6
        except KeyError:
            return 0.0

    # -- group-commit pipeline ----------------------------------------------
    def _run_group_commit(self) -> None:
        try:
            self._group_commit_loop()
        except Exception:  # a dead pipeline must never be silent
            logging.getLogger(__name__).exception(
                "raft %s: group-commit thread died", self.uuid)

    def _group_commit_loop(self) -> None:
        while True:
            self._gc_event.wait(timeout=0.5)
            with self._lock:
                if not self._running:
                    return
            if not self._gc_event.is_set():
                continue
            # Conveyor pacing: appends only land here while a round is
            # already hot (idle appends dispatch inline in
            # _leader_append_locked), so hold back until the window since
            # the last dispatch elapses — everything admitted meanwhile
            # shares this round.
            window_s = self._gc_window_s()
            with self._lock:
                since = time.monotonic() - self._gc_last_dispatch
            if 0 < since < window_s:
                time.sleep(window_s - since)
            self._gc_event.clear()
            with self._lock:
                if self._role != Role.LEADER:
                    continue
                last = self._last_index
                batch = last - self._gc_handled_index
                if batch <= 0:
                    continue
                self._gc_handled_index = last
                self._gc_last_dispatch = time.monotonic()
                # One AppendEntries round per peer for the whole window.
                self._signal_peers_locked()
            observe_group_commit_batch(batch)
            try:
                # One WAL sync for the window, concurrent with the peer
                # sends (the replication threads re-check durability
                # after their round, so a failure here only defers
                # self's vote toward the majority).
                self._ensure_durable(last)
            except Exception as e:  # noqa: BLE001 — retried by peers/timer
                count_swallowed("raft.group_commit_sync", e)
                with self._lock:
                    self._gc_handled_index = min(self._gc_handled_index,
                                                 self._durable_index)

    def _ensure_durable(self, index: int) -> None:
        """Fsync the log up to at least ``index`` (batched across callers),
        then let the commit watermark advance with self counted."""
        with self._sync_lock:
            with self._lock:
                if self._durable_index >= index:
                    return
                target = self._last_index
            # Justified hold: _sync_lock IS the fsync serializer — it exists
            # only to batch concurrent durability requests into one sync
            # (contenders WANT to wait; their entries ride this fsync). The
            # state lock `_lock` is NOT held here, so appends/peer sends
            # proceed concurrently — this is the group-commit shape itself.
            # yb-lint: disable=iholds/lock-across-blocking
            self.log.sync()
            with self._lock:
                self._durable_index = max(self._durable_index, target)
                if self._role == Role.LEADER:
                    # Justified hold: _advance_commit_locked only touches
                    # in-memory watermarks here; the fsync the summary sees
                    # is a rare divergence-repair sub-path, not steady state.
                    # yb-lint: disable=iholds/lock-across-blocking
                    self._advance_commit_locked()

    def change_config(self, new_peers: list[str],
                      timeout: float | Deadline = 10.0) -> LogEntry:
        """Replicate a new replica set (one-at-a-time membership change).
        Validation and append are atomic under the lock so two racing
        changes cannot both enter flight."""
        with self._lock:
            if self._role != Role.LEADER:
                raise NotLeader(self.uuid, self._leader_uuid)
            if self.cmeta.pending_config is not None:
                raise RuntimeError("config change already pending")
            cur = set(self.cmeta.committed_config.peers)
            if len(cur.symmetric_difference(new_peers)) > 1:
                raise ValueError("only one-server-at-a-time config changes")
            entry = self._leader_append_locked(
                "change_config", {"peers": list(new_peers), "opid_index": 0},
                None)
        self._ensure_durable(entry.op_id.index)
        self.wait_applied(entry.op_id, timeout)
        return entry

    def transfer_leadership(self, target: str) -> None:
        """Ask ``target`` to start an immediate election (leader stepdown;
        reference: RunLeaderElection RPC, consensus.proto:592)."""
        resp = self.transport.send(target, "raft.run_election",
                                   {"tablet_id": self.tablet_id},
                                   timeout=self.opts.rpc_timeout_s)
        if resp.get("code") != "ok":
            # Best effort — the target may simply lose the election — but
            # an outright refusal should not vanish.
            count_swallowed("raft.transfer_leadership", resp.get("code"))

    # -- rpc dispatch --------------------------------------------------------
    def handle(self, method: str, payload: dict) -> dict:
        if method == "raft.request_vote":
            return self.handle_request_vote(payload)
        if method == "raft.update_consensus":
            return self.handle_update_consensus(payload)
        if method == "raft.run_election":
            self._start_election(ignore_live_leader=True)
            return {"ok": True}
        raise ValueError(f"unknown consensus method {method}")

    # ----------------------------------------------------------------- votes
    def handle_request_vote(self, req: dict) -> dict:
        with self._lock:
            term = self.cmeta.current_term
            if req["term"] < term:
                return {"term": term, "granted": False}
            # Vote withholding while a live leader exists (lease guard):
            # prevents a rejoining partitioned node from disrupting the
            # group (reference: leader leases / pre-elections).
            if not req.get("ignore_live_leader"):
                now = time.monotonic()
                # the explicit message-borne promise first, then the
                # live-leader recency guard
                if now < self._vote_withhold_until:
                    return {"term": term, "granted": False}
                since = now - self._last_heartbeat_recv
                if self._leader_uuid is not None and \
                        since < self.opts.election_timeout_s:
                    return {"term": term, "granted": False}
            if req["term"] > term:
                self._step_down_locked(req["term"])
            granted = False
            up_to_date = ((req["last_log_term"], req["last_log_index"])
                          >= self._last_log_key())
            if up_to_date and self.cmeta.voted_for in (None, req["candidate"]):
                # Justified hold: Raft safety — the vote must be durable
                # (cmeta fsync) BEFORE any other vote/term decision can
                # read voted_for, or a crash-revote double-grants the term.
                # yb-lint: disable=iholds/lock-across-blocking
                self.cmeta.set_term(self.cmeta.current_term,
                                    voted_for=req["candidate"])
                self._last_heartbeat_recv = time.monotonic()
                self._election_timeout = self._next_timeout()
                granted = True
            return {"term": self.cmeta.current_term, "granted": granted}

    def _last_log_key(self) -> tuple[int, int]:
        e = self._entries.get(self._last_index)
        return (e.op_id.term if e else 0, self._last_index)

    # ----------------------------------------------------------- replication
    def handle_update_consensus(self, req: dict) -> dict:
        """Follower side of AppendEntries (reference: UpdateConsensus)."""
        with self._lock:
            term = self.cmeta.current_term
            if req["term"] < term:
                return {"term": term, "success": False,
                        "last_index": self._last_index}
            if req["term"] > term:
                self._step_down_locked(req["term"])
            elif self._role != Role.FOLLOWER:
                self._become_follower_locked()
            self._leader_uuid = req["leader"]
            self._last_heartbeat_recv = time.monotonic()
            self._election_timeout = self._next_timeout()
            granted = float(req.get("lease_s", 0.0))
            if granted > 0:
                self._vote_withhold_until = max(
                    self._vote_withhold_until,
                    time.monotonic() + granted)

            prev_index, prev_term = req["prev_index"], req["prev_term"]
            if prev_index > 0:
                pe = self._entries.get(prev_index)
                if prev_index > self._last_index or \
                        (pe is not None and pe.op_id.term != prev_term):
                    # Divergence: tell the leader to back off.
                    return {"term": self.cmeta.current_term, "success": False,
                            "last_index": min(self._last_index,
                                              prev_index - 1)}
            appended = False
            for rec in req["entries"]:
                e = LogEntry.from_record(rec)
                existing = self._entries.get(e.op_id.index)
                if existing is not None:
                    if existing.op_id.term == e.op_id.term:
                        continue  # already have it
                    self._truncate_suffix_locked(e.op_id.index - 1)
                self._append_local_locked(e, sync=False)
                appended = True
            if appended or self._durable_index < self._last_index:
                # ALSO when nothing new appended: a retried request whose
                # first attempt buffered entries but failed its sync must
                # not ack (and grant a lease) over unsynced entries —
                # every success response implies everything is durable.
                # Justified hold: the follower ack (and the lease grant it
                # carries) must imply durability, and the next request's
                # prev-entry check must see this one's entries — releasing
                # `_lock` mid-request would let a reordered retry ack over
                # unsynced state. Leader-side latency hides behind the
                # leader's own pipelined sends, not this path.
                # yb-lint: disable=iholds/lock-across-blocking
                self.log.sync()  # one fsync per request (group commit)
                self._durable_index = self._last_index
            new_commit = min(req["commit_index"], self._last_index)
            if new_commit > self._commit_index:
                self._commit_index = new_commit
                self._on_commit_advanced_locked()
            return {"term": self.cmeta.current_term, "success": True,
                    "last_index": self._last_index,
                    "lease_s_granted": granted}

    def _append_local_locked(self, e: LogEntry, sync: bool = True) -> None:
        self.log.append(e)
        if sync:
            self.log.sync()
        self._entries[e.op_id.index] = e
        self._last_index = e.op_id.index
        self.clock.update(HybridTime(e.ht))
        if e.op_type == "change_config":
            cfg = RaftConfig.from_dict(e.body)
            cfg.opid_index = e.op_id.index
            self.cmeta.pending_config = cfg
            self.cmeta.flush()
            if self._role == Role.LEADER:
                self._sync_peer_threads_locked()

    def _truncate_suffix_locked(self, last_kept: int) -> None:
        """Erase a conflicting log suffix (follower divergence)."""
        self.log.truncate_after(last_kept)
        self._durable_index = min(self._durable_index, last_kept)
        dropped = []
        for idx in range(last_kept + 1, self._last_index + 1):
            e = self._entries.pop(idx, None)
            if e is None:
                continue
            dropped.append(e)
            if e.op_type == "change_config" and \
                    self.cmeta.pending_config is not None and \
                    self.cmeta.pending_config.opid_index == idx:
                self.cmeta.pending_config = None
                self.cmeta.flush()
        self._last_index = last_kept
        if dropped and self.on_entries_truncated is not None:
            # Definite aborts: these entries will never apply here.
            self.on_entries_truncated(dropped)

    # -- leader-side peer loop ----------------------------------------------
    def _peer_loop(self, peer: _PeerState) -> None:
        try:
            self._peer_loop_impl(peer)
        except Exception:  # a dead replication thread must never be silent
            import logging
            logging.getLogger(__name__).exception(
                "raft peer loop %s->%s died", self.uuid, peer.uuid)

    def _peer_loop_impl(self, peer: _PeerState) -> None:
        while True:
            peer.signal.wait(timeout=self.opts.heartbeat_interval_s)
            peer.signal.clear()
            with self._lock:
                if not self._running or self._role != Role.LEADER or \
                        peer.uuid not in self._peers:
                    return
                term = self.cmeta.current_term
                min_cached = min(self._entries, default=self._last_index + 1)
                if peer.next_index < min_cached:
                    # The peer needs entries already evicted from the
                    # cache: it must be re-seeded by remote bootstrap
                    # (§5.3); keep heartbeating from the cache floor so it
                    # stays quiet, and nudge the bootstrap notifier
                    # (rate-limited) so the re-seed actually happens.
                    peer.needs_remote_bootstrap = True
                    peer.next_index = min_cached
                    now = time.monotonic()
                    if self.on_needs_bootstrap is not None and \
                            now - getattr(peer, "last_rb_request", 0) > 5.0:
                        peer.last_rb_request = now
                        cb, target = self.on_needs_bootstrap, peer.uuid
                        threading.Thread(
                            target=cb, args=(self.tablet_id, target),
                            daemon=True).start()
                prev_index = peer.next_index - 1
                pe = self._entries.get(prev_index)
                prev_term = pe.op_id.term if pe else 0
                batch = []
                idx = peer.next_index
                while idx <= self._last_index and \
                        len(batch) < self.opts.max_batch_entries:
                    batch.append(self._entries[idx].to_record())
                    idx += 1
                req = {
                    "tablet_id": self.tablet_id, "term": term,
                    "leader": self.uuid, "prev_index": prev_index,
                    "prev_term": prev_term, "entries": batch,
                    "commit_index": self._commit_index,
                    # message-borne lease: the follower promises not to
                    # vote for this duration (measured from OUR send
                    # time; its ack makes the grant effective)
                    "lease_s": self.opts.effective_lease_s,
                }
            send_time = time.monotonic()
            try:
                resp = self.transport.send(peer.uuid, "raft.update_consensus",
                                           req, timeout=self.opts.rpc_timeout_s)
            except Exception as e:
                # ANY send/remote failure (not just TransportError — e.g. a
                # remote handler error surfacing as RpcCallError) must leave
                # this replication thread alive; retry on the next tick.
                count_swallowed("raft.update_consensus", e)
                continue
            if batch and self._durable_index < batch[-1][1]:
                # Deferred leader durability (append_leader): sync once
                # per replication round, off the admission path. Shared
                # across both peer threads via the group-commit sync
                # lock. A sync failure must not kill the replication
                # thread — self simply keeps not counting toward the
                # majority (the two followers carry it).
                try:
                    self._ensure_durable(batch[-1][1])
                except Exception as e:  # noqa: BLE001
                    count_swallowed("raft.leader_sync", e)
            need_apply = False
            with self._lock:
                if not self._running or self._role != Role.LEADER or \
                        self.cmeta.current_term != term:
                    return
                if resp["term"] > term:
                    self._step_down_locked(resp["term"])
                    return
                if resp["success"]:
                    peer.last_ack_monotonic = send_time
                    peer.lease_until = max(
                        peer.lease_until,
                        send_time + float(resp.get("lease_s_granted", 0.0)))
                    if batch:
                        peer.match_index = max(peer.match_index,
                                               batch[-1][1])
                        peer.next_index = peer.match_index + 1
                        peer.needs_remote_bootstrap = False
                    self._advance_commit_locked()
                    need_apply = self._applied_index < self._commit_index
                    if peer.next_index <= self._last_index:
                        peer.signal.set()  # keep streaming the backlog
                else:
                    peer.next_index = max(1, min(resp["last_index"] + 1,
                                                 peer.next_index - 1))
                    peer.signal.set()
            if need_apply:
                # Apply inline: the ack that advanced the commit point
                # finishes the write without an apply-thread hop. Bounded
                # so this replication thread keeps heartbeating its
                # follower; any remainder falls to the apply thread.
                self._drain_applies(max_entries=4 * self.opts.max_batch_entries)

    def _advance_commit_locked(self) -> None:
        """Advance the majority-replicated watermark (current-term entries
        only — the standard Raft commit rule)."""
        cfg = self.cmeta.active_config
        matches = []
        for uuid in cfg.peers:
            if uuid == self.uuid:
                matches.append(self._durable_index)  # only once fsynced
                continue
            p = self._peers.get(uuid)
            matches.append(p.match_index if p else 0)
        if not matches:
            return
        matches.sort(reverse=True)
        candidate = matches[cfg.majority_size() - 1]
        if candidate > self._commit_index:
            e = self._entries.get(candidate)
            if e is not None and e.op_id.term == self.cmeta.current_term:
                self._commit_index = candidate
                self._on_commit_advanced_locked()

    def _on_commit_advanced_locked(self) -> None:
        # Commit a pending config change.
        pc = self.cmeta.pending_config
        if pc is not None and pc.opid_index <= self._commit_index:
            self.cmeta.committed_config = pc
            self.cmeta.pending_config = None
            self.cmeta.flush()
            if self._role == Role.LEADER:
                self._sync_peer_threads_locked()
                if not self.cmeta.committed_config.has_peer(self.uuid):
                    self._become_follower_locked()  # we were removed
        self._apply_cond.notify_all()
        self._commit_cond.notify_all()

    def _signal_peers_locked(self) -> None:
        for p in self._peers.values():
            p.signal.set()

    # -- log cache eviction + bootstrap handoff ------------------------------
    def evict_cache(self, up_to: int) -> int:
        """Bound the in-memory entry cache: drop entries strictly below
        min(up_to, applied) — the floor entry itself is retained as the
        prev-term anchor for peer probing. Lagging peers whose next entry
        was evicted are re-seeded via remote bootstrap instead of log
        catchup (reference: LogCache eviction + the remote-bootstrap
        trigger in consensus_queue.cc)."""
        with self._lock:
            limit = min(up_to, self._applied_index)
            # Keep TWO anchors (limit-1 and limit): a peer whose next
            # entry is the floor still needs prev_term of floor-1 for its
            # consistency probe — evicting it would bounce that peer into
            # a needless full bootstrap.
            victims = [i for i in self._entries if i < limit - 1]
            for i in victims:
                del self._entries[i]
            return len(victims)

    def log_tail_snapshot(self) -> dict:
        """Everything a lagging peer needs beyond a storage snapshot:
        the cached log tail (with the commit watermark stamped on the
        records), current term, and the committed config — the payload
        of a remote-bootstrap session (remote_bootstrap_session.cc)."""
        with self._lock:
            records = []
            for i in sorted(self._entries):
                rec = self._entries[i].to_record()
                rec[5] = min(self._commit_index, i)  # stamp committed
                records.append(rec)
            return {
                "log": records,
                "term": self.cmeta.current_term,
                "config": self.cmeta.committed_config.to_dict(),
                "commit_index": self._commit_index,
            }

    # -- apply ---------------------------------------------------------------
    def _run_apply(self) -> None:
        try:
            self._apply_loop()
        except Exception:  # a silently-dead applier halts the state machine
            logging.getLogger(__name__).exception(
                "raft %s: apply thread died", self.uuid)

    def _apply_loop(self) -> None:
        while True:
            with self._lock:
                while self._running and \
                        (self._applying or
                         self._applied_index >= self._commit_index):
                    self._apply_cond.wait(timeout=0.5)
                if not self._running:
                    if self._stall_watch is not None:
                        self._stall_watch.__exit__(None, None, None)
                        self._stall_watch = None
                    return
            self._drain_applies()
            with self._lock:
                # A hole (possible transiently after an interrupted
                # truncation) must stall the apply, not busy-spin.
                if not self._applying and \
                        self._applied_index < self._commit_index:
                    # A hole that persists is an apply stall (standing
                    # watchdog check, kernel_stack_watchdog.h analog).
                    if self._stall_watch is None:
                        from yugabyte_db_tpu.utils.watchdog import watchdog

                        self._stall_watch = watchdog().watch(
                            "raft.apply_hole", threshold_s=5.0)
                        self._stall_watch.__enter__()
                    self._apply_cond.wait(timeout=0.2)
                elif self._stall_watch is not None:
                    self._stall_watch.__exit__(None, None, None)
                    self._stall_watch = None

    def _drain_applies(self, max_entries: int | None = None) -> None:
        """Apply committed entries in strict log order, from WHATEVER
        thread reached the commit point first (single applier at a
        time). Leader-side, the replication thread that advanced the
        commit watermark applies inline — the writer waiting in
        wait_applied wakes exactly once, with the result ready, instead
        of paying an extra thread hop through the apply loop (the same
        motive as the reference running ApplyTask on the prepare
        thread's token when it can, operation_driver.cc). The apply
        thread remains for entries nobody is waiting on (followers).

        ``max_entries`` bounds an inline drain: a replication thread
        must not disappear into a huge committed backlog (its follower
        would miss heartbeats long enough to start an election) — it
        applies a bounded slice and hands the rest to the apply thread."""
        try:
            if FLAGS.get("fault.raft_apply_stall") > 0:
                # Deterministic widening of the commit-ack/apply window
                # (the commit_ack_crash sweep round): committed entries
                # stay queued; acks still go out at commit.
                count_fault_fired("fault.raft_apply_stall")
                return
        except KeyError:
            pass
        with self._lock:
            if self._applying:
                return
            self._applying = True
        applied = 0
        try:
            while True:
                with self._lock:
                    # Strictly contiguous: stop at any hole.
                    batch = []
                    i = self._applied_index + 1
                    while i <= self._commit_index and i in self._entries:
                        if max_entries is not None and \
                                applied + len(batch) >= max_entries:
                            break
                        batch.append(self._entries[i])
                        i += 1
                    if not batch:
                        return
                for e in batch:
                    if e.op_type not in ("no_op", "change_config"):
                        self.apply_cb(e)
                    with self._lock:
                        self._applied_index = e.op_id.index
                        self._commit_cond.notify_all()
                applied += len(batch)
                if max_entries is not None and applied >= max_entries:
                    return
        finally:
            with self._lock:
                self._applying = False
                self._apply_cond.notify_all()

    def wait_applied(self, op_id: OpId, timeout: float | Deadline) -> None:
        """Block until the entry is applied. Raises NotLeader if it was
        truncated (definitely aborted) and TimeoutError if the outcome is
        still UNKNOWN — a timed-out entry may yet commit."""
        self._wait_watermark(op_id, _as_deadline(timeout), applied=True)

    def wait_committed(self, op_id: OpId, timeout: float | Deadline) -> None:
        """Block until the entry is majority-durable (commit-time ack —
        the pipelined-apply write path acks here). The entry may not yet
        be APPLIED locally: the apply stage drains asynchronously behind
        the MVCC read fence (safe time cannot pass an unapplied write).
        Raises NotLeader if the entry was truncated and TimeoutError
        while the outcome is still unknown."""
        self._wait_watermark(op_id, _as_deadline(timeout), applied=False)

    def wait_apply_drained(self, timeout: float | Deadline = 10.0) -> bool:
        """Block until the apply stage catches up with the commit
        watermark observed at entry — the barrier maintenance operations
        (flush, snapshot) take so a commit-acked write can't be missing
        from the memtable they capture. False on timeout/shutdown."""
        dl = _as_deadline(timeout)
        with self._lock:
            target = self._commit_index
            while self._applied_index < target:
                remaining = dl.remaining()
                if remaining <= 0 or not self._running:
                    return False
                # Justified hold: callers are maintenance barriers that
                # take _maintenance_lock precisely to EXCLUDE flush/
                # snapshot while apply drains — holding it across the
                # wait is the barrier's purpose (`_commit_cond` releases
                # the state lock `_lock` itself for the duration).
                # yb-lint: disable=iholds/lock-across-blocking
                self._commit_cond.wait(timeout=remaining)
        return True

    def _wait_watermark(self, op_id: OpId, deadline: Deadline,
                        applied: bool) -> None:
        with self._lock:
            while True:
                e = self._entries.get(op_id.index)
                if e is None:
                    if op_id.index <= self._applied_index:
                        return  # applied, then evicted from the cache
                    raise NotLeader(self.uuid, self._leader_uuid)  # truncated
                if e.op_id.term != op_id.term:
                    raise NotLeader(self.uuid, self._leader_uuid)  # truncated
                watermark = (self._applied_index if applied
                             else self._commit_index)
                if watermark >= op_id.index:
                    return
                remaining = deadline.remaining()
                if remaining <= 0 or not self._running:
                    raise TimeoutError(f"commit timeout for {op_id}")
                self._commit_cond.wait(timeout=remaining)

    # -- elections -----------------------------------------------------------
    def _next_timeout(self) -> float:
        return self.opts.election_timeout_s * (1.0 + self._rng.random())

    def _run_timer(self) -> None:
        try:
            self._timer_loop()
        except Exception:  # a silently-dead timer wedges heartbeats/elections
            logging.getLogger(__name__).exception(
                "raft %s: timer thread died", self.uuid)

    def _timer_loop(self) -> None:
        # Deadline-based, not fixed-tick: sleep until the next event
        # (heartbeat due / election timeout) and recompute on wake. A
        # node hosts one Raft instance PER TABLET, so idle tick storms
        # scale with tablet count — the reference amortizes this with a
        # shared timer wheel (rpc/scheduler.cc); sleeping to the exact
        # deadline gets the same effect per-instance.
        min_sleep = min(0.02, self.opts.heartbeat_interval_s / 2)
        while True:
            start_election = False
            retry_sync = 0
            with self._lock:
                if not self._running:
                    return
                now = time.monotonic()
                if self._role == Role.LEADER:
                    due = self._last_broadcast + \
                        self.opts.heartbeat_interval_s
                    if now >= due:
                        self._last_broadcast = now
                        self._signal_peers_locked()
                        due = now + self.opts.heartbeat_interval_s
                        if self._durable_index < self._last_index and \
                                len(self.cmeta.active_config.peers) == 1:
                            # A failed group-commit sync left a buffered
                            # tail; only SINGLE-peer groups need the
                            # heartbeat retry (multi-peer leaders defer
                            # fsync to the replication threads by design
                            # — syncing here would block the timer).
                            retry_sync = self._last_index
                    sleep_s = due - now
                elif self.cmeta.active_config.has_peer(self.uuid):
                    deadline = self._last_heartbeat_recv + \
                        self._election_timeout
                    if now > deadline:
                        start_election = True
                        sleep_s = min_sleep
                    else:
                        sleep_s = deadline - now
                else:
                    sleep_s = self.opts.election_timeout_s
            if retry_sync:
                try:
                    self._ensure_durable(retry_sync)
                except Exception as e:  # noqa: BLE001 — retried next beat
                    count_swallowed("raft.follower_sync_retry", e)
            if start_election:
                self._start_election()
            time.sleep(max(min_sleep, min(sleep_s, 0.5)))

    def _start_election(self, ignore_live_leader: bool = False) -> None:
        with self._lock:
            if not self._running or self._role == Role.LEADER:
                return
            if not self.cmeta.active_config.has_peer(self.uuid):
                return
            self._role = Role.CANDIDATE
            self._leader_uuid = None
            term = self.cmeta.current_term + 1
            # Justified hold: Raft safety — the self-vote and term bump
            # must hit disk before any concurrent request_vote can read
            # voted_for, or this node could double-vote in the new term.
            # yb-lint: disable=iholds/lock-across-blocking
            self.cmeta.set_term(term, voted_for=self.uuid)
            self._last_heartbeat_recv = time.monotonic()
            self._election_timeout = self._next_timeout()
            last_term, last_index = self._last_log_key()
            peers = [u for u in self.cmeta.active_config.peers
                     if u != self.uuid]
            majority = self.cmeta.active_config.majority_size()
        votes = {self.uuid}
        votes_lock = threading.Lock()
        req = {"tablet_id": self.tablet_id, "term": term,
               "candidate": self.uuid, "last_log_term": last_term,
               "last_log_index": last_index,
               "ignore_live_leader": ignore_live_leader}

        def ask(peer_uuid: str) -> None:
            try:
                resp = self.transport.send(peer_uuid, "raft.request_vote",
                                           req, timeout=self.opts.rpc_timeout_s)
            except Exception:  # any delivery failure = a vote not received
                return
            with self._lock:
                if resp["term"] > self.cmeta.current_term:
                    self._step_down_locked(resp["term"])
                    return
                if not (self._role == Role.CANDIDATE and
                        self.cmeta.current_term == term and resp["granted"]):
                    return
            with votes_lock:
                votes.add(peer_uuid)
                won = len(votes) >= majority
            if won:
                self._become_leader(term)

        threads = [threading.Thread(target=ask, args=(u,), daemon=True)
                   for u in peers]
        for t in threads:
            t.start()
        if majority == 1:
            self._become_leader(term)

    def _become_leader(self, term: int) -> None:
        with self._lock:
            if not self._running or self._role != Role.CANDIDATE or \
                    self.cmeta.current_term != term:
                return
            self._role = Role.LEADER
            self._leader_uuid = self.uuid
            self._last_broadcast = time.monotonic()
            self._leader_since = self._last_broadcast
            self._gc_handled_index = self._last_index
            self._peers.clear()
            self._sync_peer_threads_locked()
            # Assert leadership with a no_op; committing it commits all
            # prior-term entries (reference appends a NO_OP on election).
            entry = self._leader_append_locked("no_op", None, None)
            self._own_term_noop = (term, entry.op_id.index)
        try:
            self._ensure_durable(entry.op_id.index)
        except Exception:  # noqa: BLE001 — e.g. an injected sync fault
            # The no_op stays buffered; leadership stands (leader_ready
            # remains false until it lands) and the timer loop retries
            # durability — an election thread must never die on a
            # transient storage error.
            import logging

            logging.getLogger(__name__).warning(
                "%s: leader no_op durability deferred", self.uuid)

    def _sync_peer_threads_locked(self) -> None:
        """Make replication threads match the active config."""
        want = {u for u in self.cmeta.active_config.peers if u != self.uuid}
        for uuid in list(self._peers):
            if uuid not in want:
                self._peers.pop(uuid).signal.set()
        for uuid in want:
            if uuid not in self._peers:
                p = _PeerState(uuid, self._last_index + 1)
                self._peers[uuid] = p
                p.thread = threading.Thread(
                    target=self._peer_loop, args=(p,),
                    name=f"raft-peer-{self.uuid}->{uuid}", daemon=True)
                p.thread.start()

    def _step_down_locked(self, new_term: int) -> None:
        self.cmeta.set_term(new_term)
        self._become_follower_locked()

    def _become_follower_locked(self) -> None:
        if self._role == Role.LEADER:
            self._peers.clear()
        self._role = Role.FOLLOWER
        self._leader_uuid = None
        self._last_heartbeat_recv = time.monotonic()
        self._election_timeout = self._next_timeout()
