"""Human and JSON reporters for analysis results."""

from __future__ import annotations

import json

from yugabyte_db_tpu.analysis.core import AnalysisResult


def render_text(result: AnalysisResult) -> str:
    lines = [v.render() for v in result.violations]
    by_rule: dict[str, int] = {}
    for v in result.violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    if by_rule:
        lines.append("")
        for r, n in sorted(by_rule.items()):
            lines.append(f"  {r}: {n}")
    verdict = "ok" if result.ok else f"{len(result.violations)} violation(s)"
    lines.append(
        f"yb-lint: {verdict} "
        f"({result.files_checked} files, {result.baselined} baselined, "
        f"{result.suppressed} suppressed)")
    return "\n".join(lines)


def render_json(result: AnalysisResult) -> str:
    return json.dumps({
        "ok": result.ok,
        "files_checked": result.files_checked,
        "baselined": result.baselined,
        "suppressed": result.suppressed,
        "violations": [
            {"rule": v.rule, "file": v.file, "line": v.line,
             "message": v.message, "fingerprint": v.fingerprint}
            for v in result.violations
        ],
    }, indent=2)
