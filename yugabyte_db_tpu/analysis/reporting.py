"""Human, JSON, and SARIF reporters for analysis results."""

from __future__ import annotations

import json

from yugabyte_db_tpu.analysis.core import AnalysisResult


def render_text(result: AnalysisResult) -> str:
    lines = [v.render() for v in result.violations]
    by_rule: dict[str, int] = {}
    for v in result.violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    if by_rule:
        lines.append("")
        for r, n in sorted(by_rule.items()):
            lines.append(f"  {r}: {n}")
    verdict = "ok" if result.ok else f"{len(result.violations)} violation(s)"
    lines.append(
        f"yb-lint: {verdict} "
        f"({result.files_checked} files, {result.baselined} baselined, "
        f"{result.suppressed} suppressed)")
    return "\n".join(lines)


def render_json(result: AnalysisResult) -> str:
    return json.dumps({
        "ok": result.ok,
        "files_checked": result.files_checked,
        "baselined": result.baselined,
        "suppressed": result.suppressed,
        "violations": [
            {"rule": v.rule, "file": v.file, "line": v.line,
             "message": v.message, "fingerprint": v.fingerprint}
            for v in result.violations
        ],
    }, indent=2)


def render_sarif(result: AnalysisResult) -> str:
    """SARIF 2.1.0, the interchange format CI annotators ingest (GitHub
    code scanning et al.). One run, one result per violation; the
    baseline fingerprint rides along as a partialFingerprint so SARIF
    consumers can track a finding across line-number churn the same way
    our own baseline does."""
    rule_ids = sorted({v.rule for v in result.violations})
    rule_index = {r: i for i, r in enumerate(rule_ids)}
    return json.dumps({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "yb-lint",
                    "informationUri":
                        "https://github.com/yugabyte/yugabyte-db",
                    "rules": [{"id": r} for r in rule_ids],
                },
            },
            "results": [
                {
                    "ruleId": v.rule,
                    "ruleIndex": rule_index[v.rule],
                    "level": "error",
                    "message": {"text": v.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": v.file,
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {"startLine": max(v.line, 1)},
                        },
                    }],
                    "partialFingerprints": {
                        "ybLintBaselineKey/v1": v.baseline_key(),
                    },
                }
                for v in result.violations
            ],
        }],
    }, indent=2)
